"""Synthetic reference-schema datasets for tests, smoke runs and benchmarks.

The reference repo ships only download scripts for its datasets (PF-Pascal,
IVD, InLoc — datasets/*/download.sh); nothing can be fetched in a hermetic
environment.  This module fabricates tiny datasets with the exact CSV schemas
(/root/reference/datasets/pf-pascal/image_pairs/*.csv) from procedurally
generated images, with *known ground-truth correspondence*: the target image
is a shifted crop of the source, so keypoint transfer and match recovery have
an analytic answer.
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np
from PIL import Image


def _textured_image(rng: np.random.Generator, h: int, w: int) -> np.ndarray:
    """Smooth random texture (low-res noise, bilinearly upsampled) — gives
    local structure that feature extractors can actually match."""
    low = rng.uniform(0, 255, (max(h // 8, 2), max(w // 8, 2), 3))
    img = np.asarray(
        Image.fromarray(low.astype(np.uint8)).resize((w, h), Image.BILINEAR)
    )
    noise = rng.uniform(-12, 12, (h, w, 3))
    return np.clip(img + noise, 0, 255).astype(np.uint8)


def make_shifted_pair(
    rng: np.random.Generator, h: int, w: int, shift: Tuple[int, int]
) -> Tuple[np.ndarray, np.ndarray]:
    """Source + target where target[y + dy, x + dx] = source[y, x] on the
    overlap (content moves by (+dy, +dx) source→target); both (h, w, 3)."""
    dy, dx = shift
    big = _textured_image(rng, h + abs(dy), w + abs(dx))
    y0, x0 = max(dy, 0), max(dx, 0)
    src = big[y0 : y0 + h, x0 : x0 + w]
    tgt = big[y0 - dy : y0 - dy + h, x0 - dx : x0 - dx + w]
    return src, tgt


def write_pair_dataset(
    root: str,
    n_pairs: int = 6,
    image_hw: Tuple[int, int] = (96, 128),
    shift: Tuple[int, int] = (16, 16),
    seed: int = 0,
    splits: Tuple[str, ...] = ("train", "val"),
) -> str:
    """Weak-supervision layout: ``root/images/*.jpg`` +
    ``root/image_pairs/{split}_pairs.csv`` with the reference's
    ``source_image,target_image,class,flip`` columns."""
    rng = np.random.default_rng(seed)
    h, w = image_hw
    img_dir = os.path.join(root, "images")
    csv_dir = os.path.join(root, "image_pairs")
    os.makedirs(img_dir, exist_ok=True)
    os.makedirs(csv_dir, exist_ok=True)
    for split in splits:
        rows = ["source_image,target_image,class,flip"]
        for i in range(n_pairs):
            src, tgt = make_shifted_pair(rng, h, w, shift)
            a = f"images/{split}_{i}_a.jpg"
            b = f"images/{split}_{i}_b.jpg"
            Image.fromarray(src).save(os.path.join(root, a), quality=95)
            Image.fromarray(tgt).save(os.path.join(root, b), quality=95)
            rows.append(f"{a},{b},{1 + i % 3},0")
        with open(os.path.join(csv_dir, f"{split}_pairs.csv"), "w") as f:
            f.write("\n".join(rows) + "\n")
    return root


def write_pf_pascal_like(
    root: str,
    n_pairs: int = 4,
    image_hw: Tuple[int, int] = (96, 128),
    shift: Tuple[int, int] = (16, 16),
    n_points: int = 6,
    seed: int = 0,
) -> str:
    """Keypoint-annotated layout mirroring PF-Pascal's real on-disk layout
    (``root/image_pairs/test_pairs.csv`` + ``root/images/``): columns
    ``source_image,target_image,class,XA,YA,XB,YB`` with ';'-joined 1-indexed
    pixel coordinates.  GT: content shifts by (+dy, +dx) source→target, so
    ``(xB, yB) = (xA + dx, yA + dy)``."""
    rng = np.random.default_rng(seed)
    h, w = image_hw
    dy, dx = shift
    img_dir = os.path.join(root, "images")
    csv_dir = os.path.join(root, "image_pairs")
    os.makedirs(img_dir, exist_ok=True)
    os.makedirs(csv_dir, exist_ok=True)
    rows = ["source_image,target_image,class,XA,YA,XB,YB"]
    margin = 4
    for i in range(n_pairs):
        src, tgt = make_shifted_pair(rng, h, w, shift)
        a, b = f"images/test_{i}_a.jpg", f"images/test_{i}_b.jpg"
        Image.fromarray(src).save(os.path.join(root, a), quality=95)
        Image.fromarray(tgt).save(os.path.join(root, b), quality=95)
        # A-points anywhere whose B twin stays inside the frame (1-indexed)
        xa = rng.integers(max(-dx, 0) + margin, w - max(dx, 0) - margin, n_points) + 1
        ya = rng.integers(max(-dy, 0) + margin, h - max(dy, 0) - margin, n_points) + 1
        xb, yb = xa + dx, ya + dy
        fmt = lambda v: ";".join(str(float(x)) for x in v)  # noqa: E731
        rows.append(f"{a},{b},{1 + i % 3},{fmt(xa)},{fmt(ya)},{fmt(xb)},{fmt(yb)}")
    csv_path = os.path.join(csv_dir, "test_pairs.csv")
    with open(csv_path, "w") as f:
        f.write("\n".join(rows) + "\n")
    return csv_path


def write_inloc_like(
    root: str,
    n_queries: int = 2,
    n_panos: int = 3,
    image_hw: Tuple[int, int] = (96, 128),
    seed: int = 0,
) -> str:
    """InLoc-shaped layout: ``root/query/iphone7/*.jpg``, ``root/pano/*.jpg``
    and a densePE-style shortlist .mat whose ``ImgList`` struct array indexes
    per-query pano shortlists the way the reference reads it
    (/root/reference/eval_inloc.py:97-101: ``db[q][0].item()`` = query name,
    ``db[q][1].ravel()[idx].item()`` = pano name).

    Pano 0 of each query IS the query image (re-encoded), so a correct
    matcher scores near-identity matches on it.  Pano names follow the real
    dataset's cutout pattern (``DUC1/DUC_cutout_<scan>_<pan>_<tilt>.jpg``) so
    the localization stage's name parsing composes with these fixtures.
    Returns the shortlist path.
    """
    from scipy.io import savemat

    rng = np.random.default_rng(seed)
    h, w = image_hw
    qdir = os.path.join(root, "query", "iphone7")
    pdir = os.path.join(root, "pano", "DUC1")
    os.makedirs(qdir, exist_ok=True)
    os.makedirs(pdir, exist_ok=True)

    entries = np.zeros(
        (1, n_queries),
        dtype=np.dtype([("queryname", object), ("topNname", object)]),
    )
    for q in range(n_queries):
        qimg = _textured_image(rng, h, w)
        qfn = f"query_{q}.jpg"
        Image.fromarray(qimg).save(os.path.join(qdir, qfn), quality=95)
        panos = []
        for p in range(n_panos):
            pfn = f"DUC1/DUC_cutout_{q:03d}_{p * 30}_0.jpg"
            img = qimg if p == 0 else _textured_image(rng, h, w)
            Image.fromarray(img).save(
                os.path.join(root, "pano", pfn), quality=95
            )
            panos.append(pfn)
        entries[0, q] = (np.array([qfn]), np.array(panos, dtype=object)[:, None])
    shortlist = os.path.join(root, "shortlist.mat")
    savemat(shortlist, {"ImgList": entries})
    return shortlist
