"""Synthetic reference-schema datasets for tests, smoke runs and benchmarks.

The reference repo ships only download scripts for its datasets (PF-Pascal,
IVD, InLoc — datasets/*/download.sh); nothing can be fetched in a hermetic
environment.  This module fabricates tiny datasets with the exact CSV schemas
(/root/reference/datasets/pf-pascal/image_pairs/*.csv) from procedurally
generated images, with *known ground-truth correspondence*: the target image
is a shifted crop of the source, so keypoint transfer and match recovery have
an analytic answer.
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np
from PIL import Image


def _textured_image(rng: np.random.Generator, h: int, w: int) -> np.ndarray:
    """Smooth random texture (low-res noise, bilinearly upsampled) — gives
    local structure that feature extractors can actually match."""
    low = rng.uniform(0, 255, (max(h // 8, 2), max(w // 8, 2), 3))
    img = np.asarray(
        Image.fromarray(low.astype(np.uint8)).resize((w, h), Image.BILINEAR)
    )
    noise = rng.uniform(-12, 12, (h, w, 3))
    return np.clip(img + noise, 0, 255).astype(np.uint8)


def make_shifted_pair(
    rng: np.random.Generator, h: int, w: int, shift: Tuple[int, int]
) -> Tuple[np.ndarray, np.ndarray]:
    """Source + target where target[y + dy, x + dx] = source[y, x] on the
    overlap (content moves by (+dy, +dx) source→target); both (h, w, 3)."""
    dy, dx = shift
    big = _textured_image(rng, h + abs(dy), w + abs(dx))
    y0, x0 = max(dy, 0), max(dx, 0)
    src = big[y0 : y0 + h, x0 : x0 + w]
    tgt = big[y0 - dy : y0 - dy + h, x0 - dx : x0 - dx + w]
    return src, tgt


def write_pair_dataset(
    root: str,
    n_pairs: int = 6,
    image_hw: Tuple[int, int] = (96, 128),
    shift: Tuple[int, int] = (16, 16),
    seed: int = 0,
    splits: Tuple[str, ...] = ("train", "val"),
) -> str:
    """Weak-supervision layout: ``root/images/*.jpg`` +
    ``root/image_pairs/{split}_pairs.csv`` with the reference's
    ``source_image,target_image,class,flip`` columns."""
    rng = np.random.default_rng(seed)
    h, w = image_hw
    img_dir = os.path.join(root, "images")
    csv_dir = os.path.join(root, "image_pairs")
    os.makedirs(img_dir, exist_ok=True)
    os.makedirs(csv_dir, exist_ok=True)
    for split in splits:
        rows = ["source_image,target_image,class,flip"]
        for i in range(n_pairs):
            src, tgt = make_shifted_pair(rng, h, w, shift)
            a = f"images/{split}_{i}_a.jpg"
            b = f"images/{split}_{i}_b.jpg"
            Image.fromarray(src).save(os.path.join(root, a), quality=95)
            Image.fromarray(tgt).save(os.path.join(root, b), quality=95)
            rows.append(f"{a},{b},{1 + i % 3},0")
        with open(os.path.join(csv_dir, f"{split}_pairs.csv"), "w") as f:
            f.write("\n".join(rows) + "\n")
    return root


def write_pf_pascal_like(
    root: str,
    n_pairs: int = 4,
    image_hw: Tuple[int, int] = (96, 128),
    shift: Tuple[int, int] = (16, 16),
    n_points: int = 6,
    seed: int = 0,
) -> str:
    """Keypoint-annotated layout mirroring PF-Pascal's real on-disk layout
    (``root/image_pairs/test_pairs.csv`` + ``root/images/``): columns
    ``source_image,target_image,class,XA,YA,XB,YB`` with ';'-joined 1-indexed
    pixel coordinates.  GT: content shifts by (+dy, +dx) source→target, so
    ``(xB, yB) = (xA + dx, yA + dy)``."""
    rng = np.random.default_rng(seed)
    h, w = image_hw
    dy, dx = shift
    img_dir = os.path.join(root, "images")
    csv_dir = os.path.join(root, "image_pairs")
    os.makedirs(img_dir, exist_ok=True)
    os.makedirs(csv_dir, exist_ok=True)
    rows = ["source_image,target_image,class,XA,YA,XB,YB"]

    def _axis_bounds(length: int, s: int, margin: int = 4):
        """1-indexed B-coordinate bounds keeping every keypoint (and its A
        twin) (a) inside both frames and (b) clear of the border mismatch
        ring: near the edge content shifted FROM, a stride-16 trunk's
        receptive field bleeds into the shifted-in band and correlation
        argmax there is garbage — two feature cells (2×|shift|) plus a
        bleed pad keeps the bilinear-interp corner cells in the
        exactly-matched interior, where a shift-by-whole-cells pair matches
        bitwise even through JPEG."""
        lo, hi = 1 + margin, length - margin
        pad = 2 * abs(s) + 8
        if s > 0:
            lo = max(lo, 1 + margin + s, pad)
        elif s < 0:
            hi = min(hi, length - margin + s, length - pad)
        return float(lo), float(hi)

    # deterministic corner-spanning keypoints: the first four pin the A-point
    # bounding box (= L_pck, the PCK threshold scale) to the full safe box, so
    # the score's margin over the align-corners grid quantization (a
    # one-cell shift warps to (fs·stride−stride)/(fs−1) ≈ 19 px per 16-px
    # cell at 96², a systematic ~3 px/axis residual) is fixed by
    # construction instead of riding on a random keypoint spread
    x_lo, x_hi = _axis_bounds(w, dx)
    y_lo, y_hi = _axis_bounds(h, dy)
    corner_frac = [(0.0, 0.0), (1.0, 1.0), (1.0, 0.0), (0.0, 1.0)]
    for i in range(n_pairs):
        src, tgt = make_shifted_pair(rng, h, w, shift)
        a, b = f"images/test_{i}_a.jpg", f"images/test_{i}_b.jpg"
        Image.fromarray(src).save(os.path.join(root, a), quality=95)
        Image.fromarray(tgt).save(os.path.join(root, b), quality=95)
        fracs = corner_frac[:n_points]
        if n_points > len(corner_frac):
            extra = rng.uniform(0.1, 0.9, (n_points - len(corner_frac), 2))
            fracs = fracs + [tuple(p) for p in extra]
        xb = np.asarray([x_lo + fx * (x_hi - x_lo) for fx, _ in fracs])
        yb = np.asarray([y_lo + fy * (y_hi - y_lo) for _, fy in fracs])
        xa, ya = xb - dx, yb - dy
        fmt = lambda v: ";".join(str(float(x)) for x in v)  # noqa: E731
        rows.append(f"{a},{b},{1 + i % 3},{fmt(xa)},{fmt(ya)},{fmt(xb)},{fmt(yb)}")
    csv_path = os.path.join(csv_dir, "test_pairs.csv")
    with open(csv_path, "w") as f:
        f.write("\n".join(rows) + "\n")
    return csv_path


def write_inloc_like(
    root: str,
    n_queries: int = 2,
    n_panos: int = 3,
    image_hw: Tuple[int, int] = (96, 128),
    seed: int = 0,
) -> str:
    """InLoc-shaped layout: ``root/query/iphone7/*.jpg``, ``root/pano/*.jpg``
    and a densePE-style shortlist .mat whose ``ImgList`` struct array indexes
    per-query pano shortlists the way the reference reads it
    (/root/reference/eval_inloc.py:97-101: ``db[q][0].item()`` = query name,
    ``db[q][1].ravel()[idx].item()`` = pano name).

    Pano 0 of each query IS the query image (re-encoded), so a correct
    matcher scores near-identity matches on it.  Pano names follow the real
    dataset's cutout pattern (``DUC1/DUC_cutout_<scan>_<pan>_<tilt>.jpg``) so
    the localization stage's name parsing composes with these fixtures.
    Returns the shortlist path.
    """
    from scipy.io import savemat

    rng = np.random.default_rng(seed)
    h, w = image_hw
    qdir = os.path.join(root, "query", "iphone7")
    pdir = os.path.join(root, "pano", "DUC1")
    os.makedirs(qdir, exist_ok=True)
    os.makedirs(pdir, exist_ok=True)

    entries = np.zeros(
        (1, n_queries),
        dtype=np.dtype([("queryname", object), ("topNname", object)]),
    )
    for q in range(n_queries):
        qimg = _textured_image(rng, h, w)
        qfn = f"query_{q}.jpg"
        Image.fromarray(qimg).save(os.path.join(qdir, qfn), quality=95)
        panos = []
        for p in range(n_panos):
            pfn = f"DUC1/DUC_cutout_{q:03d}_{p * 30}_0.jpg"
            img = qimg if p == 0 else _textured_image(rng, h, w)
            Image.fromarray(img).save(
                os.path.join(root, "pano", pfn), quality=95
            )
            panos.append(pfn)
        entries[0, q] = (np.array([qfn]), np.array(panos, dtype=object)[:, None])
    shortlist = os.path.join(root, "shortlist.mat")
    savemat(shortlist, {"ImgList": entries})
    return shortlist
