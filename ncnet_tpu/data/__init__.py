"""Host-side input pipeline: datasets, loader, synthetic fixtures."""

from ncnet_tpu.data.datasets import (
    ImagePairDataset,
    MAX_KEYPOINTS,
    PASCAL_CATEGORIES,
    PFPascalDataset,
    SampleDecodeError,
    load_image,
)
from ncnet_tpu.data.loader import DataLoader, default_collate

__all__ = [
    "DataLoader",
    "ImagePairDataset",
    "MAX_KEYPOINTS",
    "PASCAL_CATEGORIES",
    "PFPascalDataset",
    "SampleDecodeError",
    "default_collate",
    "load_image",
]
