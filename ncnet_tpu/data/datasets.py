"""Host-side datasets: weak-supervision image pairs + PF-Pascal keypoints.

Reference: ``ImagePairDataset`` (/root/reference/lib/im_pair_dataset.py:26-94)
and ``PFPascalDataset`` (/root/reference/lib/pf_dataset.py:26-113).  Same CSV
schemas, same preprocessing order (grayscale→3ch, random crop, flip, record
im_size, THEN resize), same −1 keypoint padding to 20 and 'pf'/'scnet' PCK
procedures — but emitting channels-last numpy arrays for the TPU pipeline and
using a seeded ``np.random.Generator`` instead of ambient global RNG state.

Images are decoded with PIL (the reference uses skimage.io); resizing is the
align-corners bilinear twin of the reference's identity-affine grid_sample
(lib/transformation.py:25-46) — see ncnet_tpu/ops/image.py.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

import numpy as np
import pandas as pd
from PIL import Image

from ncnet_tpu.ops.image import normalize_imagenet, resize_bilinear_align_corners_np
from ncnet_tpu.utils import faults

PASCAL_CATEGORIES = (
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
)

MAX_KEYPOINTS = 20  # reference pads keypoint arrays to 20 (pf_dataset.py:106-108)


class SampleDecodeError(RuntimeError):
    """A sample's image could not be decoded after all retries.

    Carries the offending ``path`` so the loader's quarantine policy can log
    and skip exactly that file (data/loader.py)."""

    def __init__(self, path: str, cause: Exception):
        super().__init__(f"failed to decode {path!r}: {cause}")
        self.path = path


def load_image_with_retry(path: str, retries: int) -> np.ndarray:
    """``load_image`` with bounded transient-error retry, raising
    :class:`SampleDecodeError` (which carries the path for quarantine) after
    the budget — the one decode-resilience primitive both the training
    dataset and the eval datasets share."""
    err: Optional[Exception] = None
    for _ in range(max(retries, 0) + 1):
        try:
            return load_image(path)
        except Exception as e:  # PIL raises OSError/ValueError variants
            err = e
    raise SampleDecodeError(path, err)


def load_image(path: str) -> np.ndarray:
    """Decode to (H, W, 3) uint8; grayscale replicated to 3 channels
    (im_pair_dataset.py:64-65)."""
    faults.decode_hook(path)  # no-op unless a test armed an injected fault
    with Image.open(path) as im:
        arr = np.asarray(im)
    if arr.ndim == 2:
        arr = np.repeat(arr[:, :, None], 3, axis=2)
    if arr.shape[2] > 3:  # drop alpha
        arr = arr[:, :, :3]
    return arr


def _preprocess(
    image: np.ndarray, out_h: int, out_w: int, normalize: bool
) -> Tuple[np.ndarray, np.ndarray]:
    """Record (h, w, c) size then resize; optional ImageNet normalization
    (the reference's NormalizeImageDict transform, lib/normalization.py)."""
    im_size = np.asarray(image.shape, dtype=np.float32)
    image = resize_bilinear_align_corners_np(image.astype(np.float32), out_h, out_w)
    if normalize:
        image = normalize_imagenet(image).astype(np.float32)
    return image, im_size


class ImagePairDataset:
    """Weak-supervision pairs from a ``source,target,class,flip`` CSV
    (im_pair_dataset.py:26-57).

    ``decode_retries``: transient decode errors (network filesystems, busy
    mounts) are retried that many times per image; a sample that still fails
    raises :class:`SampleDecodeError`, which the loader's quarantine policy
    can absorb (one corrupt file must not kill a long run)."""

    def __init__(
        self,
        dataset_csv_path: str,
        dataset_csv_file: str,
        dataset_image_path: str,
        dataset_size: int = 0,
        output_size: Tuple[int, int] = (240, 240),
        normalize: bool = True,
        random_crop: bool = False,
        seed: int = 1,
        decode_retries: int = 1,
    ):
        self.out_h, self.out_w = output_size
        self.random_crop = random_crop
        self.normalize = normalize
        self.decode_retries = decode_retries
        df = pd.read_csv(os.path.join(dataset_csv_path, dataset_csv_file))
        if dataset_size:
            df = df.iloc[: min(dataset_size, len(df))]
        self.img_a_names = df.iloc[:, 0].tolist()
        self.img_b_names = df.iloc[:, 1].tolist()
        self.set = df.iloc[:, 2].to_numpy()
        self.flip = df.iloc[:, 3].to_numpy().astype(np.int64)
        self.image_path = dataset_image_path
        self.seed = seed
        self.epoch = 0  # set via set_epoch (DataLoader does this per epoch)

    def set_epoch(self, epoch: int) -> None:
        """Vary augmentation draws across epochs while staying deterministic;
        the role the reference's per-worker reseeding played
        (lib/dataloader.py:39-43)."""
        self.epoch = epoch

    def __len__(self) -> int:
        return len(self.img_a_names)

    def _load_with_retry(self, path: str) -> np.ndarray:
        return load_image_with_retry(path, self.decode_retries)

    def _get_image(self, name: str, flip: int, rng) -> Tuple[np.ndarray, np.ndarray]:
        image = self._load_with_retry(os.path.join(self.image_path, name))
        if self.random_crop:
            # crop bounds exactly as the reference draws them
            # (im_pair_dataset.py:68-74)
            h, w, _ = image.shape
            top = int(rng.integers(h // 4))
            bottom = int(3 * h / 4 + rng.integers(h // 4))
            left = int(rng.integers(w // 4))
            right = int(3 * w / 4 + rng.integers(w // 4))
            image = image[top:bottom, left:right]
        if flip:
            image = image[:, ::-1]
        return _preprocess(image, self.out_h, self.out_w, self.normalize)

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        # per-(seed, epoch, sample) generator: deterministic under any thread
        # scheduling (a single shared Generator is not thread-safe)
        rng = np.random.default_rng([self.seed, self.epoch, idx])
        flip = self.flip[idx]
        image_a, size_a = self._get_image(self.img_a_names[idx], flip, rng)
        image_b, size_b = self._get_image(self.img_b_names[idx], flip, rng)
        return {
            "source_image": image_a,
            "target_image": image_b,
            "source_im_size": size_a,
            "target_im_size": size_b,
            "set": self.set[idx],
        }


def _parse_points(x_str: str, y_str: str) -> np.ndarray:
    """';'-separated keypoint strings → (2, 20) with −1 padding
    (pf_dataset.py:104-108)."""
    def parse(s):
        if not isinstance(s, str):
            return np.atleast_1d(np.asarray(s, dtype=np.float64))
        return np.asarray([float(v) for v in s.split(";") if v.strip()])

    x, y = parse(x_str), parse(y_str)
    pts = -np.ones((2, MAX_KEYPOINTS), dtype=np.float32)
    pts[0, : len(x)] = x
    pts[1, : len(x)] = y
    return pts


class PFPascalDataset:
    """PF-Pascal keypoint-annotated pairs (pf_dataset.py:26-113).

    CSV columns: source, target, class, XA;YA strings, XB;YB strings.
    ``pck_procedure``: 'pf' (L_pck = max bbox side of valid A points) or
    'scnet' (points rescaled to 224×224, L_pck = 224).
    """

    def __init__(
        self,
        csv_file: str,
        dataset_path: str,
        output_size: Tuple[int, int] = (240, 240),
        normalize: bool = True,
        category: Optional[int] = None,
        pck_procedure: str = "pf",
        decode_retries: int = 1,
    ):
        self.out_h, self.out_w = output_size
        self.normalize = normalize
        self.pck_procedure = pck_procedure
        self.decode_retries = decode_retries
        df = pd.read_csv(csv_file)
        self.category = df.iloc[:, 2].to_numpy().astype(np.float32)
        if category is not None:
            keep = np.nonzero(self.category == category)[0]
            self.category = self.category[keep]
            df = df.iloc[keep]
        self.img_a_names = df.iloc[:, 0].tolist()
        self.img_b_names = df.iloc[:, 1].tolist()
        self.point_a = df.iloc[:, 3:5]
        self.point_b = df.iloc[:, 5:7]
        self.dataset_path = dataset_path

    def __len__(self) -> int:
        return len(self.img_a_names)

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        # SampleDecodeError-wrapped (with bounded transient retry) so the
        # loader's quarantine policy can isolate a corrupt eval image
        # instead of the decode aborting the whole PCK run
        image_a = load_image_with_retry(
            os.path.join(self.dataset_path, self.img_a_names[idx]),
            self.decode_retries)
        image_b = load_image_with_retry(
            os.path.join(self.dataset_path, self.img_b_names[idx]),
            self.decode_retries)
        image_a, size_a = _preprocess(image_a, self.out_h, self.out_w, self.normalize)
        image_b, size_b = _preprocess(image_b, self.out_h, self.out_w, self.normalize)

        pts_a = _parse_points(self.point_a.iloc[idx, 0], self.point_a.iloc[idx, 1])
        pts_b = _parse_points(self.point_b.iloc[idx, 0], self.point_b.iloc[idx, 1])
        n_pts = int(np.sum(pts_a[0] != -1))

        if self.pck_procedure == "pf":
            valid = pts_a[:, :n_pts]
            l_pck = np.asarray(
                [np.max(valid.max(axis=1) - valid.min(axis=1))], dtype=np.float32
            )
        elif self.pck_procedure == "scnet":
            # SCNet evaluation: rescale everything to a virtual 224×224 image
            # (pf_dataset.py:64-75)
            pts_a[0, :n_pts] *= 224 / size_a[1]
            pts_a[1, :n_pts] *= 224 / size_a[0]
            pts_b[0, :n_pts] *= 224 / size_b[1]
            pts_b[1, :n_pts] *= 224 / size_b[0]
            size_a = np.asarray([224, 224, 3], dtype=np.float32)
            size_b = np.asarray([224, 224, 3], dtype=np.float32)
            l_pck = np.asarray([224.0], dtype=np.float32)
        else:
            raise ValueError(f"unknown pck_procedure {self.pck_procedure!r}")

        return {
            "source_image": image_a,
            "target_image": image_b,
            "source_im_size": size_a,
            "target_im_size": size_b,
            "source_points": pts_a,
            "target_points": pts_b,
            "L_pck": l_pck,
        }
