"""Batched, prefetching, host-sharded data loader.

The reference vendors PyTorch-0.3's multiprocess DataLoader solely to add
per-worker numpy seeding (/root/reference/lib/dataloader.py:39-43,165).  A TPU
input pipeline has different constraints: samples are numpy arrays destined
for a single device transfer per batch, multi-host training wants each host to
own a disjoint shard of every epoch, and determinism should come from explicit
seeds, not process-fork timing.

Design:
  * thread-pool sample decoding (PIL/numpy release the GIL for the heavy
    parts; worker *processes* buy nothing for this workload),
  * double-buffered background prefetch of collated batches so host decode
    overlaps device compute,
  * epoch-keyed shuffling via ``np.random.Generator(seed, epoch)`` — the
    determinism the reference's per-worker seeding was added for, without
    vendored machinery,
  * ``num_shards``/``shard_index`` slicing after the shuffle for multi-host
    (per-host input sharding; pairs with the mesh 'data' axis).
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, List, Optional

import numpy as np


def default_collate(samples: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    """Stack a list of dict samples into a dict of batched arrays."""
    out: Dict[str, np.ndarray] = {}
    for key in samples[0]:
        vals = [s[key] for s in samples]
        first = vals[0]
        if isinstance(first, np.ndarray):
            out[key] = np.stack(vals)
        elif isinstance(first, (int, float, np.integer, np.floating)):
            out[key] = np.asarray(vals)
        else:  # strings etc. pass through as lists (reference collate_custom)
            out[key] = vals
    return out


class DataLoader:
    """Iterable over collated batches of a map-style dataset.

    Args:
      dataset: object with ``__len__`` and ``__getitem__`` → dict of arrays.
      batch_size: global per-host batch size.
      shuffle: epoch-keyed deterministic shuffle.
      num_workers: decode threads (0 ⇒ synchronous decode, no prefetch).
      drop_last: drop the trailing partial batch.
      num_shards / shard_index: this host's share of the (shuffled) epoch.
      seed: base seed; the epoch index is mixed in per epoch.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        shuffle: bool = False,
        num_workers: int = 0,
        drop_last: bool = False,
        num_shards: int = 1,
        shard_index: int = 0,
        seed: int = 1,
        prefetch_batches: int = 2,
    ):
        if not 0 <= shard_index < num_shards:
            raise ValueError(f"shard_index {shard_index} not in [0, {num_shards})")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.num_workers = num_workers
        self.drop_last = drop_last
        self.num_shards = num_shards
        self.shard_index = shard_index
        self.seed = seed
        self.prefetch_batches = prefetch_batches
        self.epoch = 0  # bump (or pass to set_epoch) to reshuffle

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def _shard_len(self) -> int:
        n = len(self.dataset)
        return n // self.num_shards if self.num_shards > 1 else n

    def _epoch_indices(self) -> np.ndarray:
        idx = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.default_rng([self.seed, self.epoch])
            rng.shuffle(idx)
        if self.num_shards > 1:
            # even, disjoint shards; trailing remainder dropped so every host
            # sees the same number of batches (collective-friendly)
            per = len(idx) // self.num_shards
            idx = idx[self.shard_index * per : (self.shard_index + 1) * per]
        return idx

    def __len__(self) -> int:
        n = self._shard_len()
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _batches(self) -> Iterator[np.ndarray]:
        idx = self._epoch_indices()
        for start in range(0, len(idx), self.batch_size):
            chunk = idx[start : start + self.batch_size]
            if self.drop_last and len(chunk) < self.batch_size:
                return
            yield chunk

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(self.epoch)
        if self.num_workers <= 0:
            for chunk in self._batches():
                yield default_collate([self.dataset[int(i)] for i in chunk])
            return
        yield from self._prefetch_iter()

    def _prefetch_iter(self) -> Iterator[Dict[str, np.ndarray]]:
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_batches)
        sentinel = object()
        stop = threading.Event()
        err: List[BaseException] = []

        def put_interruptible(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
                    for chunk in self._batches():
                        if stop.is_set():
                            return
                        samples = list(
                            pool.map(self.dataset.__getitem__, [int(i) for i in chunk])
                        )
                        if not put_interruptible(default_collate(samples)):
                            return
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                put_interruptible(sentinel)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                yield item
        finally:
            # abandoned early (break / exception in consumer): unblock and
            # stop the producer instead of leaking it on the bounded queue
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=10)
        if err:
            raise err[0]
