"""Batched, prefetching, host-sharded data loader.

The reference vendors PyTorch-0.3's multiprocess DataLoader solely to add
per-worker numpy seeding (/root/reference/lib/dataloader.py:39-43,165).  A TPU
input pipeline has different constraints: samples are numpy arrays destined
for a single device transfer per batch, multi-host training wants each host to
own a disjoint shard of every epoch, and determinism should come from explicit
seeds, not process-fork timing.

Design:
  * thread-pool sample decoding (PIL/numpy release the GIL for the heavy
    parts; worker *processes* buy nothing for this workload),
  * double-buffered background prefetch of collated batches so host decode
    overlaps device compute,
  * epoch-keyed shuffling via ``np.random.Generator(seed, epoch)`` — the
    determinism the reference's per-worker seeding was added for, without
    vendored machinery,
  * ``num_shards``/``shard_index`` slicing after the shuffle for multi-host
    (per-host input sharding; pairs with the mesh 'data' axis).
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, List, Optional, Set

import numpy as np

from ncnet_tpu.data.datasets import SampleDecodeError


def default_collate(samples: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    """Stack a list of dict samples into a dict of batched arrays."""
    out: Dict[str, np.ndarray] = {}
    for key in samples[0]:
        vals = [s[key] for s in samples]
        first = vals[0]
        if isinstance(first, np.ndarray):
            out[key] = np.stack(vals)
        elif isinstance(first, (int, float, np.integer, np.floating)):
            out[key] = np.asarray(vals)
        else:  # strings etc. pass through as lists (reference collate_custom)
            out[key] = vals
    return out


class DataLoader:
    """Iterable over collated batches of a map-style dataset.

    Args:
      dataset: object with ``__len__`` and ``__getitem__`` → dict of arrays.
      batch_size: global per-host batch size.
      shuffle: epoch-keyed deterministic shuffle.
      num_workers: decode threads (0 ⇒ synchronous decode, no prefetch).
      drop_last: drop the trailing partial batch.
      num_shards / shard_index: this host's share of the (shuffled) epoch.
      seed: base seed; the epoch index is mixed in per epoch.
      on_decode_error: 'raise' (default) propagates a dataset
        :class:`SampleDecodeError`; 'quarantine' logs + records the bad
        path (``self.quarantined``) and substitutes the next healthy
        dataset sample, so one corrupt file costs the epoch at most that
        sample instead of the whole run.  Substitution is
        index-deterministic (idx+1, idx+2, ... mod len), so a given corrupt
        file always maps to the same replacement.

    Mid-epoch resume: ``set_epoch(epoch, start_batch=B)`` skips the first
    ``B`` batches of the epoch *before* decode (no wasted work) while
    keeping the epoch-keyed shuffle, so a resumed run sees exactly the
    batches the crashed run never consumed.  ``len()`` still reports the
    full epoch; consumers read ``start_batch`` back for global indexing.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        shuffle: bool = False,
        num_workers: int = 0,
        drop_last: bool = False,
        num_shards: int = 1,
        shard_index: int = 0,
        seed: int = 1,
        prefetch_batches: int = 2,
        on_decode_error: str = "raise",
    ):
        if not 0 <= shard_index < num_shards:
            raise ValueError(f"shard_index {shard_index} not in [0, {num_shards})")
        if on_decode_error not in ("raise", "quarantine"):
            raise ValueError(
                f"on_decode_error {on_decode_error!r}: use 'raise' or "
                "'quarantine'"
            )
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.num_workers = num_workers
        self.drop_last = drop_last
        self.num_shards = num_shards
        self.shard_index = shard_index
        self.seed = seed
        self.prefetch_batches = prefetch_batches
        self.on_decode_error = on_decode_error
        self.quarantined: Set[str] = set()   # bad image paths, for reporting
        self._bad_indices: Set[int] = set()  # dataset indices to skip over
        # guards quarantine-state WRITES and snapshot reads: _quarantine
        # runs on prefetch worker threads while eval consumers snapshot
        # bad_indices on the main thread (membership tests stay lock-free —
        # atomic under the GIL)
        self._quarantine_lock = threading.Lock()
        self.epoch = 0  # bump (or pass to set_epoch) to reshuffle
        self.start_batch = 0

    def set_epoch(self, epoch: int, start_batch: int = 0) -> None:
        self.epoch = epoch
        self.start_batch = start_batch

    @property
    def bad_indices(self) -> frozenset:
        """Dataset indices whose OWN samples failed decode (and were
        substituted under the quarantine policy).  Eval consumers key their
        invalid-scoring on this, not on ``quarantined`` paths: an image can
        be shared across samples and fail transiently for one of them —
        path-level matching would wrongly invalidate the healthy ones.
        Snapshot under the quarantine lock: prefetch workers mutate the set
        concurrently, and an unguarded frozenset() can raise mid-iteration."""
        with self._quarantine_lock:
            return frozenset(self._bad_indices)

    def _shard_len(self) -> int:
        n = len(self.dataset)
        return n // self.num_shards if self.num_shards > 1 else n

    def _epoch_indices(self) -> np.ndarray:
        idx = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.default_rng([self.seed, self.epoch])
            rng.shuffle(idx)
        if self.num_shards > 1:
            # even, disjoint shards; trailing remainder dropped so every host
            # sees the same number of batches (collective-friendly)
            per = len(idx) // self.num_shards
            idx = idx[self.shard_index * per : (self.shard_index + 1) * per]
        return idx

    def __len__(self) -> int:
        n = self._shard_len()
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _batches(self) -> Iterator[np.ndarray]:
        idx = self._epoch_indices()
        for bi, start in enumerate(range(0, len(idx), self.batch_size)):
            chunk = idx[start : start + self.batch_size]
            if self.drop_last and len(chunk) < self.batch_size:
                return
            if bi < self.start_batch:
                continue  # mid-epoch resume: already consumed before a crash
            yield chunk

    def _quarantine(self, err: SampleDecodeError, idx: int) -> None:
        with self._quarantine_lock:
            self._bad_indices.add(idx)
            fresh = err.path not in self.quarantined
            self.quarantined.add(err.path)
        if fresh:
            from ncnet_tpu.observability import events as obs_events
            from ncnet_tpu.observability import get_logger

            get_logger("data").warning(
                f"[fault-tolerance] quarantined undecodable sample "
                f"{err.path!r}: {err}", kind="decode")
            obs_events.emit("quarantine", unit=str(err.path), kind="decode",
                            scope="sample", error=str(err)[:300])

    # fresh (not previously known-bad) decode failures tolerated within ONE
    # substitution scan before declaring the failure systemic: large enough
    # to ride out a cluster of corrupt files, small enough that a wrong
    # --dataset_image_path fails in seconds, not after scanning every sample
    _MAX_FRESH_FAILURES = 8

    def _fetch(self, i: int) -> Dict[str, np.ndarray]:
        i = int(i)
        try:
            if i not in self._bad_indices:
                return self.dataset[i]
            err = None  # known-bad: go straight to substitution
        except SampleDecodeError as e:
            if self.on_decode_error != "quarantine":
                raise
            self._quarantine(e, i)
            err = e
        n = len(self.dataset)
        fresh_failures = 1 if err is not None else 0
        for k in range(1, n):
            j = (i + k) % n
            if j in self._bad_indices:
                continue
            try:
                return self.dataset[j]
            except SampleDecodeError as e:
                self._quarantine(e, j)
                err = e
                fresh_failures += 1
                if fresh_failures >= self._MAX_FRESH_FAILURES:
                    raise SampleDecodeError(
                        f"<{fresh_failures} consecutive samples>", e
                    ) from e  # systemic (bad image root?), not one bad file
        raise SampleDecodeError(
            f"<no decodable sample left: {len(self._bad_indices)}/{n} "
            "quarantined>", err
        )

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(self.epoch)
        if self.num_workers <= 0:
            for chunk in self._batches():
                yield default_collate([self._fetch(i) for i in chunk])
            return
        yield from self._prefetch_iter()

    def _prefetch_iter(self) -> Iterator[Dict[str, np.ndarray]]:
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_batches)
        sentinel = object()
        stop = threading.Event()
        err: List[BaseException] = []

        def put_interruptible(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
                    for chunk in self._batches():
                        if stop.is_set():
                            return
                        samples = list(
                            pool.map(self._fetch, [int(i) for i in chunk])
                        )
                        if not put_interruptible(default_collate(samples)):
                            return
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                put_interruptible(sentinel)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                yield item
        finally:
            # abandoned early (break / exception in consumer): unblock and
            # stop the producer instead of leaking it on the bounded queue
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=10)
        if err:
            raise err[0]
