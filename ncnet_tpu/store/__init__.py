"""Persistent database-side feature store (localization-as-a-service).

Public surface: :class:`FeatureStore` (verified reads, two-phase atomic
commits, fail-open degradation, LRU eviction, generation GC) and the key
helpers :func:`content_digest` / :func:`backbone_fingerprint` /
:func:`weights_digest` — see ``feature_store.py`` for the design and the
README "Feature store" section for the operator view.
"""

from ncnet_tpu.store.feature_store import (  # noqa: F401
    SCHEMA_VERSION,
    STORE_DEGRADED,
    STORE_OK,
    FeatureStore,
    backbone_fingerprint,
    coarse_fingerprint,
    content_digest,
    weights_digest,
)

__all__ = [
    "SCHEMA_VERSION",
    "STORE_DEGRADED",
    "STORE_OK",
    "FeatureStore",
    "backbone_fingerprint",
    "coarse_fingerprint",
    "content_digest",
    "weights_digest",
]
