"""Crash-safe, content-addressed persistent feature store.

InLoc-style localization serves queries against a FIXED database of panos,
yet before this store every query paid two full backbone extractions
because nothing persisted between calls (ROADMAP item 5c).  This module is
the database side of that workload made durable: backbone features are
computed once, committed to disk, and verified on every read — turning each
query into ONE extraction (its own) plus cached matching, the classic
millions-of-users-one-index production shape.

Persistent state is also the first place this stack could start returning
*silently wrong* answers — a torn write, a flipped bit, features computed
under superseded weights — so the store is built robustness-first around
one invariant: **a query NEVER fails because of the store and NEVER uses
unverified bytes.**  The mechanisms:

  * **Content-addressed keys** — an entry is keyed by the sha256 digest of
    the raw database image bytes (:func:`content_digest`), under a
    **backbone fingerprint** directory (:func:`backbone_fingerprint` =
    weights digest + ``image_size`` + ``k_size`` + dtype).  Features from
    different weights / preprocessing can never collide; a re-trained
    checkpoint simply addresses a different generation.
  * **Verified reads** — every entry carries a sha256 checksum over its raw
    array bytes in a JSON header line.  A mismatch (or an unparseable
    header, foreign fingerprint, newer schema) QUARANTINES the entry file
    into ``<root>/quarantine/`` (atomic rename — the evidence is preserved
    for the postmortem, the poisoned bytes can never be served) and reads
    as a miss: the caller transparently recomputes and rewrites.
  * **Two-phase atomic commits** — entries land via
    ``utils/io.atomic_write_bytes`` (pid-suffixed temp + ``os.replace``,
    fsync file + parent dir: the opt-in DURABLE commit), with the
    ``faults.store_commit_kill_hook`` seam between payload write and
    rename: SIGKILL mid-commit leaves a temp carcass and NO visible entry.
  * **Degradation ladder** — any I/O failure (disk full, permissions, a
    dying disk) fails OPEN: reads report a miss, writes become no-ops, the
    store transitions to DEGRADED (a ``store_health`` event + the health
    section consumers surface on ``/healthz``), and the first later
    successful operation transitions it back to OK — the DEGRADED →
    recovered timeline the chaos suite asserts from the event log.
  * **Superseded-generation GC** — :meth:`FeatureStore.gc_superseded`
    removes sibling fingerprint directories whose WEIGHTS digest differs
    from the current one (new weights = a dead generation); sibling dirs
    with the same weights but a different size/k/dtype belong to another
    live consumer (e.g. the serving engine's bucket ladder) and are kept.
  * **LRU eviction with a journal** — ``budget_bytes`` bounds the
    generation's footprint; the least-recently-used entry is evicted
    first, with access order persisted in an append-only, torn-tail-
    tolerant ``journal.jsonl`` (put/evict records fsynced under the
    durable contract, touch records best-effort) so LRU order survives
    restarts.  The journal is compacted on open when it dwarfs the entry
    count.

Telemetry: hit/miss/corrupt/evict/degraded counters ride the health dict
(rendered as ``ncnet_store_*`` families on the serving ``/metrics`` plane
and flushed as one ``store_stats`` event by :meth:`flush_stats`);
transitions and quarantines are events (``store_health``, ``store_corrupt``,
``store_evict``, ``store_gc``), replayable via ``run_report --store``.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.observability import get_logger
from ncnet_tpu.utils import faults
from ncnet_tpu.utils.io import atomic_write_bytes, fsync_dir

log = get_logger("store")

SCHEMA_VERSION = 1
_MAGIC = "ncnet-feature-store"
_ENTRY_SUFFIX = ".feat"
# a header line is a few hundred bytes; a "header" that exceeds this is a
# corrupt file, not a header (bounds the read on a garbage first line)
_MAX_HEADER_BYTES = 4096
# commit carcasses (*.feat.tmp.<pid>) older than this are swept on open: a
# LIVE writer's temp lives for seconds, so age is a safe ownership test
_TMP_SWEEP_AGE_S = 600.0

STORE_OK = "OK"
STORE_DEGRADED = "DEGRADED"


def content_digest(array: np.ndarray) -> str:
    """Content address of one array (dtype + shape + raw bytes, sha256).
    For the localization database this is computed over the RAW decoded
    uint8 image, so the same pano file always resolves to the same entry
    regardless of which query's shortlist named it."""
    a = np.ascontiguousarray(array)
    h = hashlib.sha256()
    h.update(str(a.dtype.str).encode())
    h.update(str(tuple(a.shape)).encode())
    h.update(a.tobytes())
    return h.hexdigest()[:32]


def weights_digest(params) -> str:
    """Digest of the backbone weights — the generation identity.  Hashes
    every leaf's dtype/shape/bytes in pytree order; NC-filter params are
    deliberately excluded (database-side features are a pure function of
    the TRUNK — retraining only the filter must not invalidate terabytes
    of cached features)."""
    import jax

    tree = params.get("backbone", params) if isinstance(params, dict) \
        else params
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(tree):
        a = np.ascontiguousarray(np.asarray(leaf))
        h.update(str(a.dtype.str).encode())
        h.update(str(tuple(a.shape)).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


def backbone_fingerprint(params, *, image_size, k_size: int,
                         dtype: str) -> str:
    """The extraction-program fingerprint an entry is valid under:
    ``<weights digest>-s<image_size>-k<k_size>-<dtype>``.  ``image_size``
    may be an int (the InLoc quantized-resize target) or a string token
    (the serving engine's shape-polymorphic path, where the bucket shape
    lives in the content digest instead).  Everything that changes the
    bytes :func:`content_digest` maps to must be in here — a fingerprint
    mismatch is a MISS, never a wrong answer."""
    return f"{weights_digest(params)}-s{image_size}-k{int(k_size)}-{dtype}"


def coarse_fingerprint(base_fingerprint: str, factor: int) -> str:
    """The retrieval tier's coarse-volume generation:
    ``<base fingerprint>-c<factor>`` — a DISTINCT store generation from
    the dense features it was pooled from (a coarse entry must never
    answer a dense read or vice versa), but sharing the leading weights
    segment, so :meth:`FeatureStore.gc_superseded`'s keep-same-weights-
    siblings rule protects dense and coarse generations of the same
    weights together.  ``base_fingerprint`` is a
    :func:`backbone_fingerprint` for backbone-pooled volumes, or a
    synthetic model-free token (e.g. ``raw-s16-k0-f32``) for the
    ``raw`` extractor — the builder and every reader derive it the same
    way, so a mismatch is a MISS, never a wrong shortlist."""
    return f"{base_fingerprint}-c{int(factor)}"


def _weights_segment(fingerprint: str) -> str:
    return fingerprint.split("-", 1)[0]


class FeatureStore:
    """One generation of the persistent feature store (see module
    docstring).  Thread-safe: the serving engine resolves entries from
    replica fetcher threads concurrently.

    ``resolve(digest, compute)`` is the API consumers should use — it IS
    the degradation ladder in one place: verified hit → cached bytes;
    miss / corruption / I/O failure → ``compute()`` + best-effort rewrite.
    ``compute`` failures propagate (they are the caller's device errors,
    owned by its retry/quarantine isolation, not the store's)."""

    def __init__(self, root: str, fingerprint: str, *,
                 budget_bytes: int = 0, durable: bool = True,
                 scope: str = "store"):
        self.root = root
        self.fingerprint = fingerprint
        self.budget_bytes = int(budget_bytes)
        self.durable = bool(durable)
        self.scope = scope
        self.state = STORE_OK
        self.state_reason: Optional[str] = None
        self._lock = threading.RLock()
        # digest -> file size in bytes, in LRU order (oldest first)
        self._lru: "OrderedDict[str, int]" = OrderedDict()
        self._bytes = 0
        self._journal_f = None
        self._journal_appends = 0
        self._closed = False
        # digests with a commit in flight: the budget enforcer must not
        # pick one as its eviction victim (deleting a just-recommitted
        # entry's fresh file)
        self._inflight_puts: set = set()
        # monotone failure counter: an operation may only claim recovery
        # (_note_ok) if NOTHING failed while it ran — without this, a
        # journal/evict failure inside get()/put() would be cleared by the
        # same call's trailing recovery check and never surface in health
        self._fail_seq = 0
        self.counters: Dict[str, int] = {
            "hits": 0, "misses": 0, "puts": 0, "corrupt": 0,
            "evictions": 0, "degraded_ops": 0, "gc_entries": 0,
        }
        try:
            os.makedirs(self._gen_dir(), exist_ok=True)
            self._open_journal()
            self._reconcile()
        except OSError as e:
            self._fail("open", e)
        obs_events.emit("store_open", scope=self.scope, root=self.root,
                        fingerprint=self.fingerprint,
                        entries=len(self._lru), bytes=self._bytes,
                        budget_bytes=self.budget_bytes, state=self.state)

    # -- paths --------------------------------------------------------------

    def _gen_dir(self) -> str:
        return os.path.join(self.root, self.fingerprint)

    def _entry_path(self, digest: str) -> str:
        return os.path.join(self._gen_dir(), digest + _ENTRY_SUFFIX)

    def _journal_path(self) -> str:
        return os.path.join(self._gen_dir(), "journal.jsonl")

    def _quarantine_dir(self) -> str:
        return os.path.join(self.root, "quarantine")

    # -- open-time reconciliation ------------------------------------------

    def _open_journal(self) -> None:
        self._journal_f = open(self._journal_path(), "a")

    def _replay_journal(self) -> "OrderedDict[str, bool]":
        """Journal-recorded access order: ``digest -> True`` for digests
        the journal last saw alive, oldest access first.  Torn tails and
        foreign lines are skipped — records are independent."""
        order: "OrderedDict[str, bool]" = OrderedDict()
        try:
            with open(self._journal_path(), "rb") as f:
                raw = f.read()
        except OSError:
            return order
        for line in raw.split(b"\n"):
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail / foreign line
            if not isinstance(rec, dict):
                continue
            op, digest = rec.get("op"), rec.get("digest")
            if not isinstance(digest, str):
                continue
            if op in ("put", "touch"):
                order.pop(digest, None)
                order[digest] = True
            elif op in ("evict", "quarantine"):
                order.pop(digest, None)
        return order

    def _reconcile(self) -> None:
        """Files on disk are the truth for existence; the journal supplies
        LRU order.  Entries the journal never saw (or whose records were
        lost) fall back to mtime order and are appended oldest-first.
        Stale commit carcasses (``*.feat.tmp.<pid>`` left by writers
        killed mid-commit) are swept once old enough that no live writer
        can still own them — crash loops must not accumulate invisible
        disk usage the budget never counts."""
        now = time.time()
        on_disk: Dict[str, Tuple[int, float]] = {}
        for name in os.listdir(self._gen_dir()):
            path = os.path.join(self._gen_dir(), name)
            if _ENTRY_SUFFIX + ".tmp." in name:
                try:
                    if now - os.stat(path).st_mtime > _TMP_SWEEP_AGE_S:
                        os.remove(path)
                except OSError:
                    pass
                continue
            if not name.endswith(_ENTRY_SUFFIX):
                continue
            try:
                st = os.stat(path)
            except OSError:
                continue
            on_disk[name[: -len(_ENTRY_SUFFIX)]] = (st.st_size, st.st_mtime)
        journal_order = self._replay_journal()
        self._lru.clear()
        self._bytes = 0
        unknown = sorted(
            (d for d in on_disk if d not in journal_order),
            key=lambda d: on_disk[d][1])
        for digest in [d for d in journal_order if d in on_disk] + unknown:
            size = on_disk[digest][0]
            self._lru[digest] = size
            self._bytes += size
        try:
            with open(self._journal_path(), "rb") as jf:
                self._journal_appends = sum(1 for _ in jf)
        except OSError:
            self._journal_appends = len(journal_order)
        if self._journal_needs_compaction():
            self._compact_journal_locked()

    def _journal_needs_compaction(self) -> bool:
        return (self._journal_appends > 64
                and self._journal_appends > 4 * max(1, len(self._lru)))

    def _compact_journal_locked(self) -> None:
        """Rewrite the journal as one put-record per live entry in LRU
        order (touch records accumulate one per hit; a long-lived warm
        process would otherwise grow the file without bound).  Multi-
        writer caveat, a documented tradeoff: a concurrent process
        sharing this store root keeps appending to the REPLACED inode, so
        its records until its next reopen are lost — acceptable because
        the journal is ADVISORY: entries are discovered from the
        directory and verified per read, so a lost record can only
        degrade eviction ORDER (mtime fallback on the next open), never
        correctness."""
        body = "".join(
            json.dumps({"op": "put", "digest": d, "bytes": s,
                        "t": round(time.time(), 3)}) + "\n"
            for d, s in self._lru.items())
        if self._journal_f is not None:
            self._journal_f.close()
            # None-out BEFORE the rewrite: if it fails, a closed-but-
            # non-None handle would make every later append raise into
            # _fail and pin the store DEGRADED forever — None instead
            # routes appends through the lazy reopen in _journal
            self._journal_f = None
        try:
            atomic_write_bytes(self._journal_path(), body.encode(),
                               durable=self.durable)
        finally:
            try:
                self._open_journal()
            except OSError:
                self._journal_f = None  # lazily reopened by _journal
        self._journal_appends = len(self._lru)

    # -- degradation state machine -----------------------------------------

    def _fail(self, op: str, exc: BaseException) -> None:
        with self._lock:
            self.counters["degraded_ops"] += 1
            self._fail_seq += 1
            reason = f"{op}:{type(exc).__name__}"
            if self.state != STORE_DEGRADED:
                self.state = STORE_DEGRADED
                self.state_reason = reason
                log.warning(
                    f"feature store DEGRADED ({reason}: {exc}); failing "
                    "open — queries continue via recompute", kind="io")
                obs_events.emit("store_health", scope=self.scope,
                                state=STORE_DEGRADED, reason=reason)

    def _note_ok(self, fail_seq_before: int) -> None:
        """Claim recovery — ONLY valid when no failure landed since
        ``fail_seq_before`` (a journal/evict failure inside this very
        operation must keep the store DEGRADED, not be erased by the
        operation's own success path)."""
        with self._lock:
            if self._fail_seq != fail_seq_before:
                return
            if self.state == STORE_DEGRADED:
                self.state = STORE_OK
                reason = self.state_reason
                self.state_reason = None
                log.info("feature store recovered (operation succeeded "
                         f"after {reason})", kind="io")
                obs_events.emit("store_health", scope=self.scope,
                                state=STORE_OK, reason="recovered")

    # -- journal ------------------------------------------------------------

    def _journal(self, op: str, digest: str, *, size: Optional[int] = None,
                 sync: bool = False) -> None:
        """Append one journal record (fail-open; ``sync`` fsyncs under the
        durable contract — put/evict records, not touches)."""
        try:
            faults.store_io_hook("journal", self._journal_path())
            rec: Dict[str, Any] = {"op": op, "digest": digest,
                                   "t": round(time.time(), 3)}
            if size is not None:
                rec["bytes"] = int(size)
            # appends serialize under the store lock: resurrection-probe
            # dispatches resolve entries off the worker thread, and two
            # interleaved buffered writes would tear BOTH records
            with self._lock:
                if self._journal_f is None:
                    # self-healing: a failed compaction (or close) left no
                    # handle — reopen in append mode so a recovered disk
                    # resumes journaling without a process restart
                    if self._closed:
                        return
                    self._open_journal()
                self._journal_f.write(json.dumps(rec) + "\n")
                self._journal_f.flush()
                if sync and self.durable:
                    os.fsync(self._journal_f.fileno())
                self._journal_appends += 1
                if self._journal_needs_compaction():
                    # a warm long-lived process compacts in place (one
                    # touch record per hit would otherwise grow the file
                    # until the next restart)
                    self._compact_journal_locked()
        except (OSError, ValueError) as e:
            self._fail("journal", e)

    # -- read ---------------------------------------------------------------

    def contains(self, digest: str) -> bool:
        with self._lock:
            return digest in self._lru

    def get(self, digest: str) -> Optional[np.ndarray]:
        """Verified read.  Returns the array, or None for ANY of: no entry,
        checksum/header mismatch (entry quarantined), I/O failure (store
        degraded).  Never raises."""
        path = self._entry_path(digest)
        with self._lock:
            seq0 = self._fail_seq
        try:
            faults.store_io_hook("read", path)
            try:
                with open(path, "rb") as f:
                    raw = f.read()
            except FileNotFoundError:
                with self._lock:
                    self.counters["misses"] += 1
                    self._drop_index(digest)
                return None
            arr = self._verify(digest, path, raw)
            if arr is None:
                with self._lock:
                    self.counters["misses"] += 1
                return None
            with self._lock:
                self.counters["hits"] += 1
                if digest in self._lru:
                    self._lru.move_to_end(digest)
            self._journal("touch", digest)
            self._note_ok(seq0)
            return arr
        except Exception as e:  # noqa: BLE001 — the ladder: a store read
            # failure is a MISS with the store degraded, never a query
            # failure
            self._fail("read", e)
            with self._lock:
                self.counters["misses"] += 1
            return None

    def _verify(self, digest: str, path: str,
                raw: bytes) -> Optional[np.ndarray]:
        """Parse + verify one entry's bytes; quarantines and returns None
        on any mismatch."""
        nl = raw.find(b"\n")
        if nl < 0 or nl > _MAX_HEADER_BYTES:
            self._quarantine_entry(digest, path, "no header line")
            return None
        try:
            head = json.loads(raw[:nl])
        except ValueError:
            self._quarantine_entry(digest, path, "unparseable header")
            return None
        if not isinstance(head, dict) or head.get("magic") != _MAGIC:
            self._quarantine_entry(digest, path, "foreign file")
            return None
        if head.get("schema", 0) > SCHEMA_VERSION:
            self._quarantine_entry(digest, path,
                                   f"newer schema {head.get('schema')}")
            return None
        if head.get("digest") != digest \
                or head.get("fingerprint") != self.fingerprint:
            self._quarantine_entry(digest, path, "key mismatch")
            return None
        payload = raw[nl + 1:]
        want = head.get("checksum", "")
        got = "sha256:" + hashlib.sha256(payload).hexdigest()
        if want != got:
            self._quarantine_entry(digest, path, "checksum mismatch")
            return None
        try:
            shape = tuple(int(s) for s in head["shape"])
            arr = np.frombuffer(payload, dtype=np.dtype(head["dtype"]))
            return arr.reshape(shape).copy()
        except (KeyError, TypeError, ValueError) as e:
            self._quarantine_entry(digest, path,
                                   f"bad array header ({e})")
            return None

    def _quarantine_entry(self, digest: str, path: str, why: str) -> None:
        """Move a failed-verification entry aside (atomic rename — the
        poisoned bytes can never be served again, the evidence survives
        for the postmortem) and drop it from the index."""
        with self._lock:
            self.counters["corrupt"] += 1
            self._drop_index(digest)
        dest = None
        try:
            os.makedirs(self._quarantine_dir(), exist_ok=True)
            dest = os.path.join(
                self._quarantine_dir(),
                f"{self.fingerprint}.{os.path.basename(path)}"
                f".{int(time.time() * 1e3)}")
            os.replace(path, dest)
        except OSError as e:
            # even quarantine failing must not fail the query: drop the
            # index entry (already done) and degrade
            self._fail("quarantine", e)
            dest = None
        self._journal("quarantine", digest, sync=True)
        log.warning(f"feature store entry {digest} failed verification "
                    f"({why}); quarantined — recomputing", kind="validation")
        obs_events.emit("store_corrupt", scope=self.scope, digest=digest,
                        reason=why, quarantined_to=dest)

    def _drop_index(self, digest: str) -> None:
        size = self._lru.pop(digest, None)
        if size is not None:
            self._bytes -= size

    # -- write --------------------------------------------------------------

    def put(self, digest: str, array: np.ndarray) -> bool:
        """Two-phase atomic (and, by default, durable) commit of one entry.
        Fail-open: returns False (store degraded) instead of raising."""
        a = np.ascontiguousarray(array)
        # ONE payload materialization (an InLoc-resolution entry is
        # ~117 MB; hashing and writing the same buffer avoids two extra
        # full copies per commit on the dispatch path)
        payload = a.tobytes()
        head = {
            "magic": _MAGIC, "schema": SCHEMA_VERSION,
            "digest": digest, "fingerprint": self.fingerprint,
            "shape": list(a.shape), "dtype": a.dtype.str,
            "checksum": "sha256:" + hashlib.sha256(payload).hexdigest(),
            "t": round(time.time(), 3),
        }
        header = json.dumps(head, sort_keys=True).encode() + b"\n"
        size = len(header) + len(payload)
        path = self._entry_path(digest)
        with self._lock:
            seq0 = self._fail_seq
            self._inflight_puts.add(digest)
        try:
            try:
                faults.store_io_hook("write", path)
                atomic_write_bytes(
                    path, (header, payload), durable=self.durable,
                    # SIGKILL between payload write and rename lands here:
                    # the chaos suite proves a rerun sees NO visible entry
                    commit_hook=faults.store_commit_kill_hook)
                # post-commit corruption seam (bit-flip injection): the
                # NEXT verified read must catch what this plants
                faults.store_bitflip_hook(path)
            except (OSError, ValueError) as e:
                self._fail("write", e)
                return False
            with self._lock:
                self._drop_index(digest)
                self._lru[digest] = size
                self._bytes += size
                self.counters["puts"] += 1
        finally:
            with self._lock:
                self._inflight_puts.discard(digest)
        self._journal("put", digest, size=size, sync=True)
        self._enforce_budget()
        self._note_ok(seq0)
        return True

    def _enforce_budget(self) -> None:
        """LRU eviction down to ``budget_bytes`` (0 = unbounded).  An
        eviction failure degrades the store and stops this round — better
        over-budget than an eviction loop against a sick disk."""
        if self.budget_bytes <= 0:
            return
        while True:
            with self._lock:
                if self._bytes <= self.budget_bytes or len(self._lru) <= 1:
                    return
                # CLAIM the victim under the lock (drop it from the index
                # before touching the file): a second concurrent enforcer
                # can then never pick the same digest — no double-counted
                # evictions, no duplicate journal records.  In-flight puts
                # are skipped: evicting a digest whose fresh commit is
                # landing would delete the new entry's file.  (Residual
                # TOCTOU — a put of the claimed digest STARTING between
                # claim and remove — is benign by the ladder: the next
                # read takes the FileNotFoundError miss path and
                # recomputes; verified reads can never serve wrong bytes.)
                victim = next(
                    (d for d in self._lru if d not in self._inflight_puts),
                    None)
                if victim is None:
                    return
                digest, size = victim, self._lru[victim]
                self._drop_index(digest)
            path = self._entry_path(digest)
            try:
                faults.store_io_hook("evict", path)
                os.remove(path)
            except FileNotFoundError:
                pass
            except OSError as e:
                self._fail("evict", e)
                return
            with self._lock:
                self.counters["evictions"] += 1
            self._journal("evict", digest, size=size, sync=True)
            obs_events.emit("store_evict", scope=self.scope, digest=digest,
                            bytes=size)

    # -- the ladder, in one place ------------------------------------------

    def resolve(self, digest: str,
                compute: Callable[[], np.ndarray]
                ) -> Tuple[np.ndarray, str]:
        """``(features, status)`` — status ``"hit"`` (verified cached
        bytes), ``"miss"`` (no entry: computed + committed), or
        ``"recompute"`` (an entry existed but failed verification or I/O:
        quarantined/degraded, computed + rewritten).  The store can only
        make this SLOWER, never wrong and never fatal; ``compute()``
        exceptions are the caller's (device-error isolation owns them)."""
        had = self.contains(digest)
        arr = self.get(digest)
        if arr is not None:
            return arr, "hit"
        arr = np.asarray(compute())
        self.put(digest, arr)
        return arr, ("recompute" if had else "miss")

    # -- generations --------------------------------------------------------

    def gc_superseded(self, keep_generations: int = 0) -> int:
        """Remove sibling fingerprint directories whose WEIGHTS digest
        differs from this generation's (features computed under superseded
        weights are dead: they can never be read again — fingerprint
        mismatch is already a miss — so they only waste the budget).
        Same-weights siblings (another image_size/k/dtype consumer, e.g.
        the serving engine beside the InLoc eval) are live and kept.

        ``keep_generations`` is the live-rollout grace (serving/
        rollout.py): the N most-recently-touched superseded WEIGHTS
        generations survive — a rollback target's cache stays warm through
        promotion instead of cold-recomputing every pano.  0 (the default)
        is the old immediate-removal behavior.

        Returns the number of entries removed."""
        keep = _weights_segment(self.fingerprint)
        removed = 0
        removed_dirs = []
        try:
            names = os.listdir(self.root)
        except OSError as e:
            self._fail("gc", e)
            return 0
        spared: set = set()
        if keep_generations > 0:
            # rank superseded WEIGHTS segments by the newest mtime among
            # their dirs (a generation the pod served until the swap is
            # the freshest) and spare the top N whole
            newest: Dict[str, float] = {}
            for name in names:
                path = os.path.join(self.root, name)
                if name in (self.fingerprint, "quarantine") \
                        or not os.path.isdir(path):
                    continue
                seg = _weights_segment(name)
                if seg == keep:
                    continue
                try:
                    t = os.stat(path).st_mtime
                except OSError:
                    continue
                newest[seg] = max(newest.get(seg, 0.0), t)
            spared = {seg for seg, _ in sorted(
                newest.items(), key=lambda kv: kv[1],
                reverse=True)[:keep_generations]}
        for name in names:
            path = os.path.join(self.root, name)
            if name in (self.fingerprint, "quarantine") \
                    or not os.path.isdir(path):
                continue
            if _weights_segment(name) == keep:
                continue  # same weights, different consumer: live
            if _weights_segment(name) in spared:
                continue  # rollback grace: recent generation kept warm
            try:
                faults.store_io_hook("evict", path)
                n = sum(1 for f in os.listdir(path)
                        if f.endswith(_ENTRY_SUFFIX))
                shutil.rmtree(path)
            except OSError as e:
                self._fail("gc", e)
                continue
            removed += n
            removed_dirs.append(name)
        if removed_dirs:
            with self._lock:
                self.counters["gc_entries"] += removed
            fsync_dir(self.root)
            log.info(f"feature store GC: removed {removed} entr(ies) of "
                     f"{len(removed_dirs)} superseded generation(s): "
                     f"{removed_dirs}", kind="io")
            obs_events.emit("store_gc", scope=self.scope,
                            fingerprints=removed_dirs, entries=removed)
        return removed

    # -- probes -------------------------------------------------------------

    @property
    def entries(self) -> int:
        with self._lock:
            return len(self._lru)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def hit_pct(self) -> Optional[float]:
        """Verified-hit percentage over all lookups so far (None before
        the first lookup) — the cache-effectiveness number the bench gates
        and ``serve_top`` renders."""
        with self._lock:
            n = self.counters["hits"] + self.counters["misses"]
            if not n:
                return None
            return round(100.0 * self.counters["hits"] / n, 2)

    def health(self) -> Dict[str, Any]:
        """The store's section of the unified health document (surfaced on
        ``/healthz`` by the serving plane): state + reason + footprint +
        the counter set the ``ncnet_store_*`` metric families render."""
        with self._lock:
            return {
                "state": self.state,
                "reason": self.state_reason,
                "root": self.root,
                "fingerprint": self.fingerprint,
                "entries": len(self._lru),
                "bytes": self._bytes,
                "budget_bytes": self.budget_bytes,
                "hit_pct": self.hit_pct(),
                "counters": dict(self.counters),
            }

    def flush_stats(self, **extra) -> Dict[str, Any]:
        """Emit one ``store_stats`` event carrying :meth:`health` (the
        durable copy ``run_report --store`` replays) and return it."""
        doc = self.health()
        fields = {"scope": self.scope, "store": doc, **extra}
        obs_events.emit("store_stats", **fields)
        return doc

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._journal_f is not None:
                try:
                    self._journal_f.close()
                except OSError:
                    pass
                self._journal_f = None
