"""Per-query fault isolation for the inference/eval path.

PR 1 made *training* crash-safe; this module is the inference twin.  The
serving-shaped loops (InLoc eval, PF-Pascal eval, the PnP localization
stage) process hundreds to thousands of independent work units, and before
this layer one bad unit — a corrupt pano, a mid-run ``RESOURCE_EXHAUSTED``,
a hung tunnel fetch — aborted the whole run.  Request-level fault tolerance
is the binding constraint on serving this model at all, exactly as
checkpoint atomicity was for training, so the same discipline applies: every
recovery path is executed by deterministic fault injection
(``utils/faults.py``), not merely written.

Three pieces, shared by all three loops:

  * :func:`run_isolated` — bounded retry with exponential backoff around one
    work unit, with :func:`classify_failure` deciding the failure kind and
    an ``on_failure`` callback granting FREE retries for recoveries that
    change the program (tier demotion re-traces onto a different backend
    tier, so the retry is not "the same thing again").  Exhausted retries
    quarantine the unit into the run manifest instead of aborting.
  * :class:`RunManifest` — a journaled per-experiment ``manifest.json``
    (completed / quarantined / in-flight), committed atomically via
    ``utils/io.atomic_write_json`` on every transition, so an operator (or a
    rerun) can always see which units finished, which were given up on and
    why, and which were mid-flight at a crash.
  * :class:`EvalJournal` — an append-only JSONL journal of per-batch result
    contributions for loops (PF-Pascal) whose accumulator otherwise lives
    only in memory.  Records carry the raw little-endian float bytes
    (base64), so a resumed run reproduces the uninterrupted result BITWISE;
    each append is flushed+fsynced, and a torn trailing line (kill
    mid-append) is detected and dropped on load.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import os
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.observability import get_logger
from ncnet_tpu.utils.io import atomic_write_json

log = get_logger("resilience")


def classify_failure(exc: BaseException) -> str:
    """Map an exception from a work unit to a failure kind.

    ``'timeout'``  — watchdog-expired dispatch/fetch (hung tunnel);
    ``'device'``   — runtime device error (OOM, XlaRuntimeError, injected);
    ``'decode'``   — undecodable input image;
    ``'io'``       — other filesystem/OS errors (missing .mat, savemat
    failures, permissions);
    ``'other'``    — everything else (a bug, most likely).

    The kind drives recovery: 'device' failures get a tier-demotion attempt
    before the plain retry budget; all kinds are retryable (a flaky NFS read
    and a transient tunnel reset both deserve the backoff) and end in
    quarantine, never in aborting the run.
    """
    from ncnet_tpu.evaluation.pipeline import FetchTimeoutError
    from ncnet_tpu.models.ncnet import RUNTIME_DEVICE_ERRORS

    if isinstance(exc, FetchTimeoutError):
        return "timeout"
    if isinstance(exc, RUNTIME_DEVICE_ERRORS):
        return "device"
    try:
        from ncnet_tpu.data.datasets import SampleDecodeError

        if isinstance(exc, SampleDecodeError):
            return "decode"
    except ImportError:  # pragma: no cover - datasets always importable here
        pass
    if isinstance(exc, OSError):
        # PIL raises OSError for truncated/corrupt images ("cannot identify
        # image file", "truncated"); an injected decode fault
        # (InjectedFault) is an OSError too.  Match the decode PHRASES, not
        # bare words like "image" — a FileNotFoundError whose PATH contains
        # 'images/' is an io failure, not a decode one.
        msg = str(exc).lower()
        if "decode" in msg or "truncated" in msg or "cannot identify" in msg:
            return "decode"
        return "io"
    return "other"


@dataclasses.dataclass(frozen=True)
class FaultPolicy:
    """How hard to fight for one work unit before giving up on it."""

    retries: int = 2          # retry attempts after the first failure
    backoff_s: float = 0.5    # sleep before retry k is backoff_s * 2**(k-1)
    quarantine: bool = True   # exhausted retries: quarantine (True) or raise
    # consecutive quarantines before the run aborts as SYSTEMIC (see
    # QuarantineBreaker); <= 0 disables the breaker
    max_consecutive_quarantines: int = 5


class SystemicEvalError(RuntimeError):
    """Too many CONSECUTIVE quarantines: the failure is systemic (dead
    device, unreachable dataset root, incompatible checkpoint), not
    per-query — aborting loudly beats quarantining an entire run one unit
    at a time and exiting 'successfully' with an empty result."""


class QuarantineBreaker:
    """Consecutive-quarantine circuit breaker — the eval twin of
    ``DataLoader._MAX_FRESH_FAILURES`` (PR 1's systemic-decode guard).  Any
    completed unit resets the streak; ``limit <= 0`` disables."""

    def __init__(self, limit: int):
        self.limit = limit
        self._streak = 0

    def note(self, quarantined: bool) -> None:
        if not quarantined:
            self._streak = 0
            return
        self._streak += 1
        if self.limit > 0 and self._streak >= self.limit:
            raise SystemicEvalError(
                f"{self._streak} consecutive work units quarantined — "
                "treating the failure as systemic, not per-query"
            )


class RunManifest:
    """Journaled run manifest: ``manifest.json`` per experiment directory.

    ``data`` layout::

        {"meta":        {... run settings fingerprint ...},
         "completed":   {unit_id: {optional info}},
         "quarantined": {unit_id: {"kind", "error", "attempts"}},
         "in_flight":   [unit_id, ...]}

    Every transition commits atomically (temp + rename), so after ANY crash
    the manifest is readable and at most one unit is listed in-flight per
    worker.  A unit re-run to completion leaves quarantine; re-running a
    completed unit is harmless (idempotent transitions).
    """

    def __init__(self, path: str, meta: Optional[dict] = None):
        self.path = path
        # normalize through one json round trip (as EvalJournal does) so
        # tuple-vs-list / int-vs-float representation cannot fail the match
        meta = (json.loads(json.dumps(meta, sort_keys=True))
                if meta is not None else None)
        self.data = {
            "meta": meta or {},
            "completed": {},
            "quarantined": {},
            "in_flight": [],
        }
        if os.path.exists(path):
            try:
                with open(path) as f:
                    loaded = json.load(f)
            except (OSError, ValueError):
                # atomic writes should make this impossible; a foreign or
                # hand-edited file starts the manifest fresh rather than
                # crashing the run it exists to protect
                log.warning(f"unreadable run manifest {path}; starting fresh",
                            kind="validation")
                loaded = None
            if loaded and meta is not None and loaded.get("meta") != meta:
                # the manifest belongs to a DIFFERENT configuration (same
                # guard as EvalJournal's header): adopting its completed /
                # quarantined maps would report another experiment's units
                # as this run's
                log.warning(f"run manifest {path} belongs to a different "
                            "run configuration; starting fresh",
                            kind="validation")
                loaded = None
            if loaded:
                for key in ("completed", "quarantined", "in_flight"):
                    if isinstance(loaded.get(key), type(self.data[key])):
                        self.data[key] = loaded[key]
                if meta is None:
                    self.data["meta"] = loaded.get("meta", {})

    def save(self) -> None:
        atomic_write_json(self.path, self.data)

    def begin(self, unit_id: str) -> None:
        """Mark a unit in-flight (an attempt is starting)."""
        unit_id = str(unit_id)
        if unit_id not in self.data["in_flight"]:
            self.data["in_flight"].append(unit_id)
        self.save()

    def complete(self, unit_id: str, **info) -> None:
        unit_id = str(unit_id)
        if unit_id in self.data["in_flight"]:
            self.data["in_flight"].remove(unit_id)
        self.data["quarantined"].pop(unit_id, None)
        self.data["completed"][unit_id] = info
        self.save()

    def quarantine(self, unit_id: str, kind: str, message: str,
                   attempts: int) -> None:
        unit_id = str(unit_id)
        if unit_id in self.data["in_flight"]:
            self.data["in_flight"].remove(unit_id)
        self.data["quarantined"][unit_id] = {
            "kind": kind,
            "error": message[:500],
            "attempts": attempts,
        }
        self.save()

    def is_completed(self, unit_id: str) -> bool:
        return str(unit_id) in self.data["completed"]

    @property
    def quarantined_ids(self) -> Tuple[str, ...]:
        return tuple(self.data["quarantined"])


def run_isolated(
    unit_id: str,
    work: Callable[[], object],
    *,
    policy: FaultPolicy,
    manifest: Optional[RunManifest] = None,
    on_failure: Optional[Callable[[BaseException, str], Optional[str]]] = None,
    label: str = "",
) -> Tuple[bool, object]:
    """Run one work unit under per-query fault isolation.

    ``work`` is called up to ``1 + policy.retries`` times (plus free retries,
    below).  On each failure the exception is classified
    (:func:`classify_failure`) and ``on_failure(exc, kind)`` runs first — it
    is the recovery seam (tier demotion + retrace, pipeline-controller
    ``note_failure``); when it returns truthy the next attempt is FREE (not
    counted against the budget), because the recovery changed the program
    being retried.  Free retries are self-bounding: tier demotion returns
    None once every tier is disabled.

    Returns ``(True, result)`` on success.  On an exhausted budget:
    quarantines into ``manifest`` and returns ``(False, None)`` when
    ``policy.quarantine``, else re-raises the last exception (the
    fail-fast policy for callers that prefer the old abort behavior).
    ``BaseException``s that are not ``Exception`` (KeyboardInterrupt,
    SystemExit, injected SIGKILL/SIGTERM) always propagate — preemption is
    handled at a different layer, not retried.
    """
    from concurrent.futures import BrokenExecutor

    name = label or str(unit_id)
    attempts = 0  # counted against the budget; recovered failures are free
    while True:
        if manifest is not None:
            manifest.begin(unit_id)
        try:
            result = work()
        except BrokenExecutor:
            # a dead worker pool fails EVERY remaining unit instantly;
            # retrying/quarantining would convert one systemic failure into
            # silent mass loss — abort loudly, like the pre-isolation code
            raise
        except Exception as e:
            kind = classify_failure(e)
            recovered = on_failure(e, kind) if on_failure is not None else None
            if recovered:
                # the program changed (e.g. tier demoted + re-traced): retry
                # immediately, and do NOT count the attempt — the budget is
                # for retrying the SAME program, and a post-recovery
                # transient still deserves its full plain-retry allowance
                log.warning(f"{name}: {kind} failure (recovered: "
                            f"{recovered}; retrying off-budget): "
                            f"{type(e).__name__}: {e}", kind=kind)
                obs_events.emit("retry", unit=str(unit_id), kind=kind,
                                recovered=str(recovered), on_budget=False)
                continue
            attempts += 1
            log.warning(f"{name}: {kind} failure "
                        f"(attempt {attempts}): {type(e).__name__}: {e}",
                        kind=kind)
            if attempts <= policy.retries:
                obs_events.emit("retry", unit=str(unit_id), kind=kind,
                                attempt=attempts, on_budget=True)
                time.sleep(policy.backoff_s * 2 ** (attempts - 1))
                continue
            if policy.quarantine:
                log.warning(f"{name}: quarantined after {attempts} "
                            f"attempt(s) — the run continues without it",
                            kind="quarantine")
                obs_events.emit("quarantine", unit=str(unit_id), kind=kind,
                                attempts=attempts, error=str(e)[:300])
                if manifest is not None:
                    manifest.quarantine(unit_id, kind, str(e), attempts)
                return False, None
            raise
        else:
            if manifest is not None:
                manifest.complete(unit_id)
            return True, result


def manifest_has_quarantined(path: str) -> bool:
    """Whether a run manifest at ``path`` records quarantined units — THE
    degraded-run check, shared by every consumer (CLI exit codes, the
    localization driver's pin-resume gate) so the schema read lives in one
    place.  Missing/unreadable manifests read as not-degraded."""
    try:
        with open(path) as f:
            return bool(json.load(f).get("quarantined"))
    except (OSError, ValueError):
        return False


def _encode_f32(arr: np.ndarray) -> str:
    return base64.b64encode(
        np.ascontiguousarray(arr, dtype="<f4").tobytes()
    ).decode("ascii")


def _decode_f32(s: str) -> np.ndarray:
    return np.frombuffer(base64.b64decode(s), dtype="<f4").astype(
        np.float32, copy=True
    )


class EvalJournal:
    """Append-only journal of per-batch eval contributions (JSONL).

    Line 1 is a header fingerprinting the run settings; each later line is
    one batch's contribution ``{"batch": i, "pck": <base64 f32 bytes>}``.
    Floats travel as raw little-endian bytes, so a resumed run concatenates
    EXACTLY the values the killed run computed — the bitwise-resume bar the
    training checkpoints already meet.  Appends flush+fsync (a journal that
    loses its tail on power cut would silently recompute, which is correct
    but wasteful; a torn TAIL, however, must be tolerated: a process can
    die mid-``write``).  A header mismatch — the journal belongs to a
    different configuration — discards the journal and starts fresh rather
    than poisoning the result.
    """

    def __init__(self, path: str, header: dict):
        self.path = path
        # normalize through one json round trip so tuple-vs-list and
        # int-vs-float representation differences cannot fail the match
        self.header = json.loads(json.dumps(header, sort_keys=True))
        self.entries: Dict[int, np.ndarray] = {}
        self._appends = 0
        good_bytes = self._load()
        if good_bytes is None:
            if os.path.exists(self.path) and os.path.getsize(self.path):
                # never destroy another run's journal at construction time:
                # a mismatched --journal_dir may be an operator mistake, and
                # the displaced run's accumulated results should survive it
                stale = self.path + ".stale"
                os.replace(self.path, stale)
                log.warning(f"set the non-resumable journal aside as "
                            f"{stale}", kind="validation")
            self._f = open(self.path, "w")
            self._write_raw(json.dumps({"header": self.header},
                                       sort_keys=True) + "\n")
        else:
            # truncate the torn tail BEFORE appending: the next record must
            # start on a fresh line, not be concatenated onto the partial
            # one (which would corrupt it and cost every later batch on the
            # next resume)
            with open(self.path, "rb+") as f:
                f.truncate(good_bytes)
            self._f = open(self.path, "a")

    def _load(self) -> Optional[int]:
        """Parse an existing journal.  Returns the byte offset of the end of
        the last GOOD line when the journal is resumable (header matches),
        else None.  A torn trailing line is dropped; torn or foreign content
        earlier in the file discards everything from that point (those
        batches simply recompute)."""
        if not os.path.exists(self.path):
            return None
        with open(self.path, "rb") as f:
            lines = f.read().split(b"\n")
        if len(lines) < 2 or not lines[0]:
            # no newline at all: even the header line is torn — fresh start
            return None
        try:
            head = json.loads(lines[0])
        except ValueError:
            head = None
        if not isinstance(head, dict) or head.get("header") != self.header:
            log.warning(f"eval journal {self.path} belongs to a different "
                        "run configuration; starting fresh",
                        kind="validation")
            return None
        good_bytes = len(lines[0]) + 1
        # every element except the LAST was newline-terminated; the last is
        # b"" for a cleanly-terminated file, else a newline-less tail.  A
        # newline-less record is dropped (truncated) EVEN IF it parses:
        # accepting it would either make good_bytes overshoot the file size
        # (truncate would zero-extend) or let the next append fuse onto it —
        # one recomputed batch is the cheap, correct outcome.  A torn but
        # TERMINATED line mid-file (a failed write repaired by the next
        # append's newline) is merely skipped: records are independent and
        # keyed by batch index, so later lines stay valid.
        for i, line in enumerate(lines[1:], start=2):
            if i == len(lines):
                break  # the unterminated tail (or the clean-file b"")
            good_bytes += len(line) + 1
            if not line:
                continue  # a sealing newline after a repaired torn write
            try:
                rec = json.loads(line)
                self.entries[int(rec["batch"])] = _decode_f32(rec["pck"])
            except (ValueError, KeyError, TypeError):
                log.warning(f"eval journal {self.path}: skipping "
                            f"undecodable line {i} (its batch will "
                            "recompute)", kind="validation")
        return good_bytes

    def _write_raw(self, text: str) -> None:
        # _dirty spans the write: a failure part-way (ENOSPC, EIO) may have
        # landed a torn prefix on disk, and the NEXT append must start on a
        # fresh line or it would fuse onto it (losing that record AND its
        # retry at the next resume)
        self._dirty = True
        self._f.write(text)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._dirty = text[-1:] != "\n"

    def append(self, batch_index: int, pck: np.ndarray) -> None:
        from ncnet_tpu.utils import faults

        if getattr(self, "_dirty", False):
            self._write_raw("\n")  # seal a torn previous write
        line = json.dumps(
            {"batch": int(batch_index), "pck": _encode_f32(pck)},
            sort_keys=True,
        )
        self._appends += 1
        # injected SIGKILL mid-append: a torn prefix is flushed first, so the
        # resumed run must prove partial-trailing-line tolerance
        faults.journal_kill_hook(
            self._appends,
            lambda: self._write_raw(line[: max(1, len(line) // 2)]),
        )
        self._write_raw(line + "\n")
        self.entries[int(batch_index)] = np.asarray(pck, dtype=np.float32)

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()
