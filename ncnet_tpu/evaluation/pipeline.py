"""Shared async dispatch/fetch pipeline machinery for the eval loops.

The InLoc loop grew an adaptive-depth dispatch/fetch pipeline in rounds 3-5
(dispatch pair i+1 before fetching pair i, so the tunnel's dispatch/transfer
latency hides behind device compute, with the queue depth adapting to the
tunnel's latency regime).  Round 6 moves the controller here so the
PF-Pascal loop (`evaluation/pf_pascal.py`) reuses it instead of a pinned
depth — the depth-control problem is identical, only the wall-time scale
differs (a PF-Pascal drain is one BATCH of pairs, an InLoc drain is one
pair), which the ``high_cap``/``low_cap`` knobs absorb.

Round 7 adds the fault-isolation hooks the resilient eval loops
(evaluation/resilience.py) need: :meth:`PipelineDepthController.note_failure`
(an aborted drain must not poison the controller's wall statistics) and
:func:`call_with_watchdog` (a hung tunnel fetch becomes a retryable
:class:`FetchTimeoutError` instead of an eternal stall).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional


class PipelineDepthController:
    """Adaptive dispatch/fetch pipeline depth for an eval loop.

    Depth 2 is the measured optimum when the tunnel's dispatch latency is
    low (r3 sweep on v5e: 0.62/0.285/0.47/0.51 s/pair at depths 1/2/3/4),
    but the same code measured 0.99 s/pair on a high-latency day, where
    deeper queues (3-4) won by hiding more round-trips.  This controller
    watches a 4-sample-memory EWMA of the drain-to-drain wall: above
    ``high`` s/drain it deepens one step up to 4; below ``low`` it returns
    to 2.  The thresholds default to ratios of the best (minimum) wall in
    a 16-sample window — a drain can never complete faster than one unit's
    device compute, so the windowed minimum IS a measured device-compute
    estimate, and ``2.0×best`` / ``1.3×best`` mark the latency-dominated
    and recovered regimes — CAPPED at ``high_cap``/``low_cap`` (defaults:
    the r3-measured per-pair rig values, 0.7/0.45 s; callers whose drain
    unit is a batch scale them up): the caps rescue a run that cold-starts
    in a high-latency regime (where every wall is inflated and a pure
    ratio of the minimum would never trigger), and the window bounds the
    damage of a single anomalously short wall to ~1.5 queries instead of
    the rest of the run.  Explicit ``high``/``low`` seconds override the
    derived thresholds.

    Wall statistics alone cannot distinguish latency-dominated from
    compute-bound slowness (in both, EWMA ≈ best), so every deepen is a
    SPECULATIVE PROBE: the pre-deepen EWMA is remembered, and if the next
    window's EWMA has not improved by ≥15% the step is reverted and
    further deepens are blocked until the EWMA leaves that regime (>1.3×
    the failed probe's wall, or a recovery below ``low``).  A genuinely
    compute-bound rig therefore pays one brief probe (two extra in-flight
    buffers for ~4 drains) instead of being pinned at depth 4 for the
    run, and a miscalibrated threshold self-corrects.

    A depth change resets the EWMA window AND the interval anchor (the
    min-wall window deliberately survives — it estimates device compute,
    which a depth change does not alter): the first post-change interval
    spans the queue refill (two dispatches, no drain between) and would
    otherwise read as ~2× the true wall, re-triggering a spurious deepen
    (ADVICE r4).  Inter-query gaps (preprocess + IO) are excluded via
    :meth:`note_gap`; depth and the device-compute estimate persist across
    queries, so each query seeds from the regime the previous one
    measured.

    ``fixed>0`` pins the depth verbatim and bypasses the 2–4 adaptive band
    entirely (a pinned 1 or 6 is honored); negative values are rejected.
    """

    _ALPHA = 0.4    # EWMA weight: ~4-sample effective memory (2/α − 1)
    _WINDOW = 16    # min-wall window: an outlier washes out in ~1.5 queries

    def __init__(self, fixed: int = 0, high: Optional[float] = None,
                 low: Optional[float] = None, high_cap: float = 0.7,
                 low_cap: float = 0.45):
        if fixed < 0:
            raise ValueError(
                f"pipeline_depth={fixed}: use 0 (adaptive) or a positive "
                "pinned depth"
            )
        self.depth = fixed if fixed > 0 else 2
        self._fixed = fixed > 0
        self._high, self._low = high, low
        self._high_cap, self._low_cap = high_cap, low_cap
        self._t_last: Optional[float] = None
        self._ewma: Optional[float] = None
        self._n = 0                       # samples since the last depth change
        self._walls: deque = deque(maxlen=self._WINDOW)
        self._probe: Optional[float] = None  # pre-deepen EWMA, judged next window
        self._block: Optional[float] = None  # EWMA regime where a deepen failed

    @property
    def best(self) -> Optional[float]:
        """Windowed-minimum wall ≈ device-compute estimate."""
        return min(self._walls) if self._walls else None

    def note_drain(self) -> None:
        now = time.perf_counter()
        if self._t_last is None:
            self._t_last = now
            return
        dt = now - self._t_last
        self._t_last = now
        self._walls.append(dt)
        self._ewma = dt if self._ewma is None else (
            self._ALPHA * dt + (1.0 - self._ALPHA) * self._ewma
        )
        self._n += 1
        if self._fixed or self._n < 4:
            return
        if self._block is not None and self._ewma > 1.3 * self._block:
            self._block = None  # clearly a new, worse regime: probe again
        if self._probe is not None:
            # judge the speculative deepen against the wall it tried to cut
            if self._ewma > 0.85 * self._probe:
                # no improvement: the slowness is compute, not latency
                self._change_depth(self.depth - 1, "probe_reverted")
                self._block = self._probe
                self._probe = None
                self._reset_ewma()
                return
            self._probe = None  # improvement confirmed; keep the depth
        best = min(self._walls)
        high = (self._high if self._high is not None
                else min(2.0 * best, self._high_cap))
        low = (self._low if self._low is not None
               else min(1.3 * best, self._low_cap))
        if self._ewma > high and self.depth < 4 and self._block is None:
            self._probe = self._ewma
            self._change_depth(self.depth + 1, "deepen_probe")
            self._reset_ewma()
        elif self._ewma < low:
            # regime recovered: lift any failed-probe block even at depth 2,
            # or a later genuine latency regime could never deepen
            self._block = None
            if self.depth > 2:
                self._change_depth(2, "recovered")
                self._probe = None
                self._reset_ewma()

    def _change_depth(self, new: int, reason: str) -> None:
        old, self.depth = self.depth, new
        from ncnet_tpu.observability import events as obs_events

        obs_events.emit("pipeline_depth", depth=new, prev=old, reason=reason)

    def _reset_ewma(self) -> None:
        # resets the decision window + anchor only, NOT the min-wall window:
        # device compute does not change when the depth does
        self._ewma = None
        self._n = 0
        self._t_last = None  # next interval spans the refill — don't record it

    def note_gap(self) -> None:
        self._t_last = None

    def note_failure(self) -> None:
        """An aborted drain (exception or watchdog timeout mid-fetch): the
        dispatch/drain cadence is broken, and the retried query's first
        interval would span the retry's backoff + queue refill — the same
        refill-spanning wall a depth change produces (ADVICE r4) — so clear
        the anchor AND the EWMA decision window.  A pending speculative
        probe is dropped unjudged (its judgment window was torn; the kept
        depth re-judges itself against fresh walls).  The min-wall window
        deliberately survives: device compute is unchanged by a failed
        query, and it is the device-compute estimate the thresholds derive
        from."""
        self._probe = None
        self._reset_ewma()


class FetchTimeoutError(RuntimeError):
    """A dispatch/fetch exceeded its watchdog budget — a hung tunnel or
    wedged device surfaced as a *retryable* per-query failure (classified
    'timeout' by evaluation/resilience.classify_failure) instead of stalling
    the eval loop forever."""


def call_with_watchdog(fn, args=(), timeout: float = 0.0, label: str = ""):
    """Run blocking ``fn(*args)`` under a wall-clock watchdog.

    ``timeout <= 0`` disables the watchdog (direct call — the default, since
    a healthy rig should not pay a thread handoff per fetch).  Otherwise the
    call runs in a daemon worker thread; if it has not returned within
    ``timeout`` seconds a :class:`FetchTimeoutError` is raised.  The stuck
    worker thread cannot be killed — it is abandoned (daemonized, so process
    exit is not blocked); the leak is bounded by the caller's retry budget,
    and an actually-hung tunnel leaves the process within a few retries via
    quarantine anyway.

    The injected-hang hook (``faults.hang_fetch_hook``) runs inside the
    worker, so a test-armed hang exercises the REAL timeout path rather than
    a simulated exception.
    """
    from ncnet_tpu.observability.tracing import span
    from ncnet_tpu.utils import faults

    if timeout <= 0:
        with span("watched_call", label=label or "fetch"):
            return fn(*args)
    result = {}
    done = threading.Event()

    def target():
        try:
            faults.hang_fetch_hook(label)
            result["value"] = fn(*args)
        except BaseException as e:  # re-raised in the caller below
            result["error"] = e
        finally:
            done.set()

    worker = threading.Thread(
        target=target, daemon=True,
        name=f"watchdog-{label or 'fetch'}",
    )
    # the span lives on the CALLER's thread (the worker has its own span
    # stack), so a timeout closes it with error=FetchTimeoutError and the
    # trace shows the watchdog budget as the span's wall
    with span("watched_call", label=label or "fetch",
              timeout_s=float(timeout)):
        worker.start()
        if not done.wait(timeout):
            from ncnet_tpu.observability import events as obs_events

            obs_events.emit("watchdog_timeout", label=label or "fetch",
                            timeout_s=float(timeout))
            raise FetchTimeoutError(
                f"{label or 'fetch'} exceeded its {timeout:.1f}s watchdog "
                "(hung tunnel or wedged device?)"
            )
        if "error" in result:
            raise result["error"]
        return result["value"]
