"""Metrics and evaluation loops."""

from ncnet_tpu.evaluation.inloc import run_inloc_eval
from ncnet_tpu.evaluation.pck import pck, pck_metric
from ncnet_tpu.evaluation.pf_pascal import make_eval_step, run_eval

__all__ = ["make_eval_step", "pck", "pck_metric", "run_eval", "run_inloc_eval"]
