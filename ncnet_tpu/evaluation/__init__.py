"""Metrics and evaluation loops."""

from ncnet_tpu.evaluation.inloc import (
    extract_match_table,
    make_pair_matcher,
    run_inloc_eval,
    sort_and_dedup,
)
from ncnet_tpu.evaluation.pck import pck, pck_metric
from ncnet_tpu.evaluation.pf_pascal import make_eval_step, run_eval

__all__ = [
    "extract_match_table",
    "make_eval_step",
    "make_pair_matcher",
    "pck",
    "pck_metric",
    "run_eval",
    "run_inloc_eval",
    "sort_and_dedup",
]
