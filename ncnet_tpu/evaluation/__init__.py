"""Metrics and evaluation loops."""

from ncnet_tpu.evaluation.inloc import (
    extract_match_table,
    make_pair_matcher,
    run_inloc_eval,
    sort_and_dedup,
    validate_matches_mat,
)
from ncnet_tpu.evaluation.pck import pck, pck_metric
from ncnet_tpu.evaluation.pf_pascal import make_eval_step, run_eval
from ncnet_tpu.evaluation.pipeline import (
    FetchTimeoutError,
    PipelineDepthController,
    call_with_watchdog,
)
from ncnet_tpu.evaluation.resilience import (
    EvalJournal,
    FaultPolicy,
    RunManifest,
    classify_failure,
    run_isolated,
)

__all__ = [
    "EvalJournal",
    "FaultPolicy",
    "FetchTimeoutError",
    "PipelineDepthController",
    "RunManifest",
    "call_with_watchdog",
    "classify_failure",
    "extract_match_table",
    "make_eval_step",
    "make_pair_matcher",
    "pck",
    "pck_metric",
    "run_eval",
    "run_inloc_eval",
    "run_isolated",
    "sort_and_dedup",
    "validate_matches_mat",
]
