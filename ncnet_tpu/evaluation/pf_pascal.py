"""PF-Pascal PCK evaluation (the reference's eval_pf_pascal.py as a library).

One jitted step per batch: forward → softmax match extraction → keypoint warp
→ PCK.  Unlike the reference ("Only batch_size=1 is supported",
eval_pf_pascal.py:52-53) any batch size works — all PF-Pascal eval images are
resized to the same square, so shapes are static.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ncnet_tpu.config import EvalPFPascalConfig, ModelConfig
from ncnet_tpu.data import DataLoader, PFPascalDataset
from ncnet_tpu.evaluation.pck import pck_metric
from ncnet_tpu.models import NCNet
from ncnet_tpu.ops import corr_to_matches
from ncnet_tpu.utils.profiling import annotate


def make_eval_step(net: NCNet, alpha: float):
    """Jitted (params, images..., points...) → per-sample PCK."""

    def step(params, batch):
        out = net.forward_fn(params, batch["source_image"], batch["target_image"])
        matches = corr_to_matches(out.corr, do_softmax=True)
        return pck_metric(batch, matches, alpha)

    jitted = jax.jit(step)

    def annotated(params, batch):
        with annotate("pf_pascal_eval_step"):
            return jitted(params, batch)

    return annotated


def run_eval(
    config: EvalPFPascalConfig,
    model_config: Optional[ModelConfig] = None,
    net: Optional[NCNet] = None,
    batch_size: int = 1,
    num_workers: int = 0,
    progress: bool = True,
) -> Dict[str, float]:
    """Evaluate PCK@alpha on the PF-Pascal test split.

    Returns ``{"pck": mean over valid pairs, "total": N, "valid": N_valid}``
    — the same three numbers the reference prints (eval_pf_pascal.py:84-89).
    """
    if net is None:
        mc = (model_config or ModelConfig()).replace(checkpoint=config.checkpoint)
        net = NCNet(mc)

    dataset = PFPascalDataset(
        csv_file=f"{config.eval_dataset_path.rstrip('/')}/image_pairs/test_pairs.csv",
        dataset_path=config.eval_dataset_path,
        output_size=(config.image_size, config.image_size),
        pck_procedure=config.pck_procedure,
    )
    loader = DataLoader(dataset, batch_size=batch_size, shuffle=False,
                        num_workers=num_workers)
    step = make_eval_step(net, config.pck_alpha)

    results = []
    n_batches = len(loader)
    # upload precision: when the trunk runs bf16 (backbone_bf16), its first
    # act is casting the images to bf16 — so uploading them AS bf16 is
    # numerically exact and halves the dominant byte cost on a tunneled
    # device (r5 measurement: the 299-pair eval moves ~1.2 GB of fp32
    # images through a ~15 MB/s tunnel; bf16 upload took the measured wall
    # 75 -> 52 s — the residual is decode + host casts + final drains)
    img_dt = jnp.bfloat16 if net.config.backbone_bf16 else None
    # pipelined dispatch (depth 3): jax's async dispatch lets batch i+1's
    # upload + forward overlap batch i's device compute and result download.
    # Results are fetched in dispatch order, so output order matches the
    # serial loop.
    in_flight: list = []

    def drain_one():
        handle, n0 = in_flight.pop(0)
        results.append(np.asarray(handle)[:n0])

    for i, batch in enumerate(loader):
        jb = {
            k: np.asarray(v)
            for k, v in batch.items()
            if k in ("source_image", "target_image", "source_points",
                     "target_points", "source_im_size", "target_im_size", "L_pck")
        }
        # pad a trailing partial batch up to batch_size (repeating the last
        # sample) so every step reuses the one compiled program, then crop
        n_real = jb["source_image"].shape[0]
        if n_real < batch_size:
            reps = [1] * batch_size
            reps[n_real - 1] = batch_size - n_real + 1
            jb = {k: np.repeat(v, reps[: n_real], axis=0) for k, v in jb.items()}
        jb = {
            k: jnp.asarray(
                v, dtype=img_dt if k.endswith("_image") and img_dt else None
            )
            for k, v in jb.items()
        }
        in_flight.append((step(net.params, jb), n_real))
        while len(in_flight) >= 3:
            drain_one()
        if progress:
            print(f"Batch: [{i}/{n_batches} ({100.0 * i / n_batches:.0f}%)]")
    while in_flight:
        drain_one()

    results = np.concatenate(results)
    # NaN = zero valid keypoints (the reference also had a -1 sentinel in its
    # preallocated stats array; pck() here never produces one)
    good = np.flatnonzero(~np.isnan(results))
    return {
        "pck": float(np.mean(results[good])) if good.size else float("nan"),
        "total": int(results.size),
        "valid": int(good.size),
        "per_pair": results,
    }
