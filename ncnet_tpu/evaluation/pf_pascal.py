"""PF-Pascal PCK evaluation (the reference's eval_pf_pascal.py as a library).

One jitted step per batch: forward → softmax match extraction → keypoint warp
→ PCK.  Unlike the reference ("Only batch_size=1 is supported",
eval_pf_pascal.py:52-53) any batch size works — all PF-Pascal eval images are
resized to the same square, so shapes are static.

Round-6 pipelining (VERDICT r5 #2: 718 ms/pair of wall against 11.7 ms of
device time): the loop now mirrors the InLoc eval's machinery —

  * images upload as RESIZED UINT8 (one quarter of the float32 bytes; the
    ImageNet normalization runs inside the jitted step), the dominant cost
    on a tunneled device where the 299-pair eval moves ~1.2 GB of fp32
    pixels through a ~15 MB/s link;
  * dispatch/fetch runs at an ADAPTIVE depth
    (:class:`~ncnet_tpu.evaluation.pipeline.PipelineDepthController`, the
    same controller the InLoc loop uses, with its wall caps scaled from
    per-pair to per-batch) instead of a pinned depth 3;
  * batch decode already overlaps device compute via the loader's
    thread-pool prefetch (``num_workers`` > 0, now the default);
  * the loop records a decode / dispatch / fetch wall split
    (``stats["timing"]``) so the bench can attribute the eval wall instead
    of guessing (BENCH ``pf_pascal_eval_s_*`` extras).

Numerics note: the uint8 upload rounds the resized image to the nearest
0-255 step before the device-side normalization (≤0.5/255 per pixel,
~20× below bf16 feature rounding).  ``device_normalize=False`` restores
the exact host-normalized float path.

Round-7 resilience (the inference twin of PR 1's training layer — see
README "Resilient inference"): per-BATCH fault isolation (bounded retry →
quarantine into a run manifest, via ``evaluation/resilience.run_isolated``),
an optional watchdog around each fetch (``config.fetch_timeout_s``), runtime
fused-tier demotion on device errors
(``models/ncnet.recover_from_device_failure``), and — when
``config.journal_dir`` is set — an append-only journal of per-batch PCK
contributions so a killed run resumes mid-eval and reproduces the
uninterrupted result bitwise.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ncnet_tpu.config import EvalPFPascalConfig, ModelConfig
from ncnet_tpu.data import DataLoader, PFPascalDataset
from ncnet_tpu.evaluation.pck import pck_metric
from ncnet_tpu.evaluation.pipeline import (
    PipelineDepthController,
    call_with_watchdog,
)
from ncnet_tpu.models import NCNet
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.observability import get_logger
from ncnet_tpu.observability.metrics import MetricsRegistry
from ncnet_tpu.observability.quality import (
    DIGEST_BINS,
    QUALITY_SIGNALS,
    SIGNAL_RANGE,
    active_tier,
    emit_quality,
    quality_table,
    spearman,
)
from ncnet_tpu.observability.tracing import span
from ncnet_tpu.ops import corr_to_matches
from ncnet_tpu.ops.image import normalize_imagenet, quantize_u8
from ncnet_tpu.utils.profiling import annotate

log = get_logger("eval.pf_pascal")

# per-batch result columns: per-pair PCK, then the quality signals — ONE
# fetched table per batch carries labels and label-free signals together
# (quality.py's zero-per-pair-postprocessing contract)
RESULT_COLUMNS = ("pck",) + QUALITY_SIGNALS


def make_eval_step(net: NCNet, alpha: float, device_normalize: bool = False):
    """Jitted (params, images..., points...) → per-sample ``(B, 6)`` table:
    PCK in column 0, the :data:`~ncnet_tpu.observability.quality.QUALITY_SIGNALS`
    in the rest (computed in-graph over the same filtered volume the match
    extraction reads — the fetch carries both at no extra round trip).

    ``device_normalize``: the batch's images arrive as raw resized uint8 and
    the ImageNet normalization runs on device (the uint8-upload fast path);
    otherwise images are already host-normalized floats.

    The jit is a :class:`~ncnet_tpu.models.ncnet.ResilientJit`: the returned
    function carries ``.retrace()`` so the eval loop's tier-degradation
    recovery can drop poisoned executables after a mid-run device failure."""
    from ncnet_tpu.models.ncnet import ResilientJit

    def step(params, batch):
        src, tgt = batch["source_image"], batch["target_image"]
        if device_normalize:
            src = normalize_imagenet(src.astype(jnp.float32))
            tgt = normalize_imagenet(tgt.astype(jnp.float32))
            if net.config.backbone_bf16:
                src = src.astype(jnp.bfloat16)
                tgt = tgt.astype(jnp.bfloat16)
        out = net.forward_fn(params, src, tgt)
        matches = corr_to_matches(out.corr, do_softmax=True)
        scores = pck_metric(batch, matches, alpha)
        return jnp.concatenate(
            [scores.astype(jnp.float32)[:, None], quality_table(out.corr)],
            axis=1,
        )

    jitted = ResilientJit(step, label="pf_pascal_step")

    def annotated(params, batch):
        with annotate("pf_pascal_eval_step"):
            return jitted(params, batch)

    annotated.retrace = jitted.retrace
    return annotated


def run_eval(
    config: EvalPFPascalConfig,
    model_config: Optional[ModelConfig] = None,
    net: Optional[NCNet] = None,
    batch_size: int = 1,
    num_workers: int = 4,
    progress: bool = True,
    device_normalize: bool = True,
    pipeline_depth: int = 0,
) -> Dict[str, float]:
    """Evaluate PCK@alpha on the PF-Pascal test split.  See
    :func:`_run_eval_impl` for the full contract; this wrapper owns the
    observability scope: when ``config.telemetry_dir`` is set it opens an
    event log there and binds it as the process-global sink for the run
    (restored on every exit path), so the loop's ``eval_batch`` events and
    the deep layers' retry/quarantine/tier events all land in one file."""
    own_sink = prev_sink = None
    if config.telemetry_dir:
        from ncnet_tpu.observability.events import EventLog

        own_sink = EventLog(
            os.path.join(config.telemetry_dir, "events.jsonl"),
            run_meta={"eval": "pf_pascal",
                      "checkpoint": config.checkpoint,
                      "image_size": config.image_size,
                      "batch_size": batch_size},
        )
        prev_sink = obs_events.set_global_sink(own_sink)
        own_sink.emit("run_start",
                      envelope=obs_events.run_envelope(own_sink.run_id),
                      eval="pf_pascal")
    try:
        return _run_eval_impl(
            config, model_config, net, batch_size, num_workers, progress,
            device_normalize, pipeline_depth,
        )
    finally:
        if own_sink is not None:
            obs_events.set_global_sink(prev_sink)
            own_sink.close()


def _run_eval_impl(
    # defaults live on run_eval (the public wrapper) ONLY — keeping a
    # second copy here would let the two drift apart silently
    config: EvalPFPascalConfig,
    model_config: Optional[ModelConfig],
    net: Optional[NCNet],
    batch_size: int,
    num_workers: int,
    progress: bool,
    device_normalize: bool,
    pipeline_depth: int,
) -> Dict[str, float]:
    """Evaluate PCK@alpha on the PF-Pascal test split.

    Returns ``{"pck": mean over valid pairs, "total": N, "valid": N_valid}``
    — the same three numbers the reference prints (eval_pf_pascal.py:84-89) —
    plus ``per_pair``, a ``timing`` wall split (decode / dispatch / fetch
    seconds, summed over the loop), and the resilience report
    (``quarantined_batches``: batch indices given up on after retries, their
    pairs scored NaN=invalid; ``decode_quarantined``: undecodable image paths
    the loader substituted).

    ``pipeline_depth``: 0 = adaptive (see module docstring), >0 pins the
    dispatch/fetch queue depth.

    Fault tolerance (``config`` knobs; see module docstring): when
    ``config.journal_dir`` is set, every completed batch's per-pair PCK is
    appended to ``<journal_dir>/pck_journal.jsonl`` and a run manifest is
    kept beside it; a rerun skips journaled batches (their decoded batches
    are still iterated — the loader's the cheap half — but nothing is
    dispatched) and reproduces the uninterrupted result bitwise.
    """
    from ncnet_tpu.evaluation.resilience import (
        EvalJournal,
        FaultPolicy,
        QuarantineBreaker,
        RunManifest,
        run_isolated,
    )
    from ncnet_tpu.models.ncnet import recover_from_device_failure

    if net is None:
        mc = (model_config or ModelConfig()).replace(checkpoint=config.checkpoint)
        if config.sparse_topk:
            # coarse-to-fine sparse matching (README "Coarse-to-fine
            # matching"): the knob rides the ModelConfig so the forward's
            # pipeline chooser sees it; ineligible shape classes fall back
            # dense inside ncnet_match_volume
            mc = mc.replace(sparse_topk=config.sparse_topk)
        net = NCNet(mc)

    dataset = PFPascalDataset(
        csv_file=f"{config.eval_dataset_path.rstrip('/')}/image_pairs/test_pairs.csv",
        dataset_path=config.eval_dataset_path,
        output_size=(config.image_size, config.image_size),
        pck_procedure=config.pck_procedure,
        decode_retries=config.decode_retries,
        # uint8-upload path: the dataset emits the resized image UNnormalized
        # (0-255 floats) so the loop can quantize to uint8 for the transfer
        normalize=not device_normalize,
    )
    loader = DataLoader(
        dataset, batch_size=batch_size, shuffle=False,
        num_workers=num_workers,
        # one corrupt image must not abort the run: the loader substitutes
        # the next healthy sample (index-deterministic, so reruns — and the
        # journal's bitwise-resume contract — are unaffected) and reports it
        on_decode_error="quarantine" if config.quarantine else "raise",
    )
    step = make_eval_step(net, config.pck_alpha,
                          device_normalize=device_normalize)
    policy = FaultPolicy(retries=config.query_retries,
                         backoff_s=config.retry_backoff_s,
                         quarantine=config.quarantine)
    breaker = QuarantineBreaker(policy.max_consecutive_quarantines)
    journal = manifest = None
    if config.journal_dir:
        os.makedirs(config.journal_dir, exist_ok=True)
        header = {
            "image_size": config.image_size,
            "pck_alpha": config.pck_alpha,
            "pck_procedure": config.pck_procedure,
            "checkpoint": config.checkpoint,
            "batch_size": batch_size,
            "device_normalize": bool(device_normalize),
            "n_pairs": len(dataset),
            # journaled records are now the full per-pair result table
            # (PCK + quality signals); a pre-quality journal must not be
            # misread as PCK-only rows, so the layout is part of the header
            # fingerprint and a mismatch starts fresh
            "columns": list(RESULT_COLUMNS),
        }
        journal = EvalJournal(
            os.path.join(config.journal_dir, "pck_journal.jsonl"), header)
        manifest = RunManifest(
            os.path.join(config.journal_dir, "manifest.json"), meta=header)

    registry = MetricsRegistry(scope="pf_pascal_eval")
    # memory observability at batch boundaries: rate-limited HBM snapshots
    # (before this, only `fit` ever emitted device_snapshot) and the
    # live-array leak sentinel (observability/memory.py)
    from ncnet_tpu.observability.device import DeviceMonitor
    from ncnet_tpu.observability.memory import LeakSentinel

    dev_monitor = DeviceMonitor(every_s=30.0)
    leak_sentinel = LeakSentinel(window=4, min_interval_s=1.0,
                                 scope="pf_pascal_eval")
    results = []
    quarantined_batches = []
    n_batches = len(loader)
    # upload precision (host-normalized path only): when the trunk runs bf16
    # (backbone_bf16), its first act is casting the images to bf16 — so
    # uploading them AS bf16 is numerically exact and halves the dominant
    # byte cost on a tunneled device.  The uint8 path quarters it instead.
    img_dt = jnp.bfloat16 if net.config.backbone_bf16 else None
    timing = {"decode_s": 0.0, "dispatch_s": 0.0, "fetch_s": 0.0}
    fresh_pairs = 0    # pairs actually dispatched THIS run
    replayed_batches = 0  # batches a journal resume skipped
    # the controller's wall caps were measured per InLoc PAIR; a PF-Pascal
    # drain is one batch, so scale them by the batch's relative weight
    # (≥1×: a tiny batch still cannot drain faster than one dispatch RTT)
    scale = max(1.0, batch_size / 2.0)
    depth_ctl = PipelineDepthController(
        pipeline_depth, high_cap=0.7 * scale, low_cap=0.45 * scale
    )
    in_flight: list = []

    n_cols = len(RESULT_COLUMNS)

    def nan_decode_quarantined(bi, arr) -> np.ndarray:
        """Score this batch's pairs NaN where THEIR OWN decode failed: the
        loader substituted the next healthy sample so the RUN survives, but
        a reported METRIC must not count the substitute twice.  Keyed on the
        loader's per-index bad set, not on quarantined paths (an image shared
        across pairs may fail transiently for a different pair).  Applied at
        RESOLVE time, before journaling — the override is then part of the
        journaled record, so a resume replays it even if the image's
        decodability changed between kill and rerun (the bitwise contract
        binds to what run 1 measured)."""
        bad = loader.bad_indices
        if not bad:
            return arr
        arr = arr.copy()
        for j in range(len(arr)):
            if bi * batch_size + j in bad:
                arr[j] = np.nan
        return arr

    def resolve_batch(bi, jb, n0, handle) -> np.ndarray:
        """Fetch one batch's per-sample PCK under per-batch fault isolation:
        watchdogged fetch, bounded retry (re-dispatching from the kept host
        batch when the handle is poisoned), tier demotion on device errors,
        quarantine (NaN scores) when the budget runs out."""
        state = {"handle": handle}

        def work():
            if state["handle"] is None:
                state["handle"] = step(net.params, jb)
            h = state["handle"]
            arr = np.asarray(
                call_with_watchdog(
                    lambda: np.asarray(h),
                    timeout=config.fetch_timeout_s,
                    label=f"pf_pascal batch {bi}",
                ),
                dtype=np.float32,
            )[:n0]
            arr = nan_decode_quarantined(bi, arr)
            if journal is not None:
                # journal BEFORE the manifest's completed transition (which
                # run_isolated applies on return): at any kill point the
                # journal — the source of truth for resume — is never behind
                # a manifest that claims completion
                journal.append(bi, arr)
            return arr

        def on_failure(exc, kind):
            state["handle"] = None  # poisoned (or never produced): re-dispatch
            depth_ctl.note_failure()
            if kind == "device":
                return recover_from_device_failure(exc, step)
            return None

        ok, arr = run_isolated(
            f"batch_{bi}", work, policy=policy, manifest=manifest,
            on_failure=on_failure, label=f"PF-Pascal batch {bi}",
        )
        # N consecutive quarantines = systemic: abort (SystemicEvalError)
        breaker.note(not ok)
        if not ok:
            quarantined_batches.append(bi)
            return np.full((n0, n_cols), np.nan, dtype=np.float32)
        return arr

    def drain_one(sample: bool = True):
        handle, n0, bi, jb = in_flight.pop(0)
        t0 = time.perf_counter()
        with span("fetch", batch=bi):
            arr = resolve_batch(bi, jb, n0, handle)
        results.append(arr)
        fetch_wall = time.perf_counter() - t0
        timing["fetch_s"] += fetch_wall
        registry.timer("fetch_wall").observe(fetch_wall)
        registry.counter("batches").inc()
        registry.gauge("pipeline_depth").set(depth_ctl.depth)
        dev_monitor.maybe_emit(step=bi)
        leak_sentinel.observe(step=bi)
        pck_col = arr[:, 0]
        if obs_events.get_global_sink() is not None:
            good = pck_col[~np.isnan(pck_col)]
            obs_events.emit(
                "eval_batch", batch=bi, n=int(pck_col.size),
                valid=int(good.size),
                pck=float(np.mean(good)) if good.size else None,
                fetch_wall_s=round(fetch_wall, 6),
                pipeline_depth=depth_ctl.depth,
            )
        # per-pair quality signals, tier-tagged, next to the per-pair PCK
        # (the event), and into the registry's fixed-bin digests (the
        # per-run percentile aggregation the drift gate consumes).  Tier
        # eligibility = this net's precision: an fp32 eval never consults
        # the Pallas chooser and must not inherit a stale bf16 decision
        # from elsewhere in the process.
        emit_quality(
            "pf_pascal_eval",
            {name: arr[:, i + 1] for i, name in enumerate(QUALITY_SIGNALS)},
            tier=active_tier(net.config.half_precision),
            pck=pck_col, registry=registry, batch=bi, n=int(pck_col.size),
        )
        if sample:
            depth_ctl.note_drain()
        else:
            # end-of-run tail: queued batches fetch back-to-back with no
            # dispatch between them — not a per-drain wall sample
            depth_ctl.note_gap()

    # explicit iterator: the decode wall (the loader's __next__, i.e. image
    # decode + resize on the prefetch pool's completion order) gets its own
    # span per batch instead of hiding in the for-statement
    loader_it = enumerate(loader)
    t_decode = time.perf_counter()
    while True:
        with span("decode"):
            nxt_item = next(loader_it, None)
        if nxt_item is None:
            break
        i, batch = nxt_item
        timing["decode_s"] += time.perf_counter() - t_decode
        if journal is not None and i in journal.entries:
            # resume: this batch's contribution is already journaled.  Flush
            # the pipeline first so the results list keeps batch order, then
            # reuse the stored (bitwise-exact) values without dispatching.
            while in_flight:
                drain_one(sample=False)
            replayed = journal.entries[i].reshape(-1, n_cols)
            results.append(replayed)
            # replayed pairs feed the quality digests (the per-run
            # aggregate must cover EVERY pair, so merged digests after a
            # SIGKILL-resume equal an uninterrupted run's) but re-emit no
            # quality event: the killed run's events for this batch are
            # already in the shared lineage log
            for k, name in enumerate(QUALITY_SIGNALS):
                lo, hi = SIGNAL_RANGE[name]
                vals = replayed[:, k + 1]
                registry.histogram(f"q_{name}", lo, hi, DIGEST_BINS).add(
                    vals[np.isfinite(vals)])
            replayed_batches += 1
            if manifest is not None:
                manifest.complete(f"batch_{i}", journaled=True)
            # a replayed unit is a completed unit: reset the breaker streak
            # (a resume must not see only the broken batches back-to-back
            # and falsely abort as systemic)
            breaker.note(False)
            depth_ctl.note_gap()
            if progress:
                log.info(f"Batch: [{i}/{n_batches}] (journaled, skipped)")
            t_decode = time.perf_counter()
            continue
        t0 = time.perf_counter()
        with span("dispatch", batch=i):
            jb = {
                k: np.asarray(v)
                for k, v in batch.items()
                if k in ("source_image", "target_image", "source_points",
                         "target_points", "source_im_size", "target_im_size",
                         "L_pck")
            }
            # pad a trailing partial batch up to batch_size (repeating the
            # last sample) so every step reuses the one compiled program,
            # then crop
            n_real = jb["source_image"].shape[0]
            if n_real < batch_size:
                reps = [1] * batch_size
                reps[n_real - 1] = batch_size - n_real + 1
                jb = {k: np.repeat(v, reps[: n_real], axis=0)
                      for k, v in jb.items()}

            def upload(k, v):
                if not k.endswith("_image"):
                    return jnp.asarray(v)
                if device_normalize:
                    # resized 0-255 floats → uint8 for the transfer (≤0.5/255
                    # rounding; the jitted step normalizes on device)
                    return jnp.asarray(quantize_u8(v))
                return jnp.asarray(v, dtype=img_dt)

            jb = {k: upload(k, v) for k, v in jb.items()}
            # pipelined dispatch: jax's async dispatch lets batch i+1's
            # upload + forward overlap batch i's device compute and result
            # download.  Results are fetched in dispatch order, so output
            # order matches the serial loop.  A dispatch-time failure (an
            # injected or real device error raised before the handle exists)
            # is deferred to the drain's isolation path: demote/re-trace now
            # if device-shaped, enqueue handle=None, and resolve_batch
            # re-dispatches under its retry budget.
            try:
                handle = step(net.params, jb)
            except Exception as e:
                from ncnet_tpu.evaluation.resilience import classify_failure

                kind = classify_failure(e)
                log.warning(f"PF-Pascal batch {i}: {kind} failure at "
                            f"dispatch: {type(e).__name__}: {e}", kind=kind)
                depth_ctl.note_failure()
                if kind == "device":
                    recover_from_device_failure(e, step)
                handle = None
            in_flight.append((handle, n_real, i, jb))
            fresh_pairs += n_real
        timing["dispatch_s"] += time.perf_counter() - t0
        while len(in_flight) >= depth_ctl.depth:
            drain_one()
        if progress:
            log.info(f"Batch: [{i}/{n_batches} "
                     f"({100.0 * i / n_batches:.0f}%)]")
        t_decode = time.perf_counter()
    while in_flight:
        drain_one(sample=False)
    if journal is not None:
        journal.close()

    results = np.concatenate(results)  # (N, 1 + len(QUALITY_SIGNALS))
    per_pair = results[:, 0]
    # NaN PCK = zero valid keypoints, a quarantined batch, or a pair with an
    # undecodable image (nan_decode_quarantined above; the reference also
    # had a -1 sentinel in its preallocated stats array — pck() here never
    # produces one)
    good = np.flatnonzero(~np.isnan(per_pair))
    quality = {name: results[:, i + 1]
               for i, name in enumerate(QUALITY_SIGNALS)}
    # signal-vs-PCK rank correlation: labels exist here, so the label-free
    # signals are validated against them (positive rho = the signal ranks
    # pairs the way PCK does — a usable unlabeled PCK proxy)
    quality_pck_spearman = {
        name: spearman(vals, per_pair) for name, vals in quality.items()
    }
    stats = {
        "pck": float(np.mean(per_pair[good])) if good.size else float("nan"),
        "total": int(per_pair.size),
        "valid": int(good.size),
        "per_pair": per_pair,
        "quality": quality,
        "quality_digests": {
            name: registry.histogram(
                f"q_{name}", *SIGNAL_RANGE[name], DIGEST_BINS).snapshot()
            for name in QUALITY_SIGNALS
        },
        "quality_pck_spearman": quality_pck_spearman,
        "quality_tier": active_tier(net.config.half_precision),
        "timing": timing,
        "quarantined_batches": quarantined_batches,
        "decode_quarantined": sorted(loader.quarantined),
    }
    registry.timer("decode_wall").observe(timing["decode_s"])
    registry.timer("dispatch_wall").observe(timing["dispatch_s"])
    registry.counter("quarantined_batches").inc(len(quarantined_batches))
    registry.counter("decode_quarantined").inc(
        len(stats["decode_quarantined"]))
    registry.gauge("pck").set(stats["pck"])
    registry.flush(event="eval_summary", total=stats["total"],
                   valid=stats["valid"], tier=stats["quality_tier"],
                   quality_pck_spearman={
                       k: (None if v != v else round(v, 4))
                       for k, v in quality_pck_spearman.items()})
    # cross-run perf history: PCK + the wall split land in the persistent
    # store so tools/perf_regress.py can gate the next eval against them
    # (fail-open; NaN PCK from an all-quarantined run is filtered there).
    # Walls are normalized PER PAIR and ingested only from FULL runs — the
    # totals depend on dataset size, and a journal resume decodes batches
    # it never dispatches, so gating raw (or resumed-run) walls would flag
    # every short/partial run as a regression.  A resumed run ingests PCK
    # only (the journal makes it bitwise-equal to the full result).
    from ncnet_tpu.observability import perfstore

    history = {"pf_pascal_pck": stats["pck"]}
    # quality-signal means join PCK in the gated accuracy trajectory —
    # direction is inferred from the signal name (margin/agreement/score/
    # coherence higher-is-better, entropy lower; perfstore.metric_direction)
    for name, vals in quality.items():
        finite = vals[np.isfinite(vals)]
        if finite.size:
            history[f"pf_pascal_quality_{name}"] = float(np.mean(finite))
    if fresh_pairs and not replayed_batches:
        for k in ("decode", "dispatch", "fetch"):
            history[f"pf_pascal_{k}_s_per_pair"] = (
                timing[f"{k}_s"] / fresh_pairs)
    perfstore.maybe_record(history, source="pf_pascal_eval")
    return stats
