"""InLoc dense-matching evaluation: the reference's second eval harness.

For each of 356 queries, match against its top-10 shortlisted database panos
at high resolution (max side 3200 px) with bf16 + k=2 maxpool4d
relocalization, extract matches in both directions, dedup, and write one
``matches/<experiment>/<q+1>.mat`` per query — the hand-off consumed by the
MATLAB L6 localization stage (compute_densePE_NCNet.m).

Reference behavior being matched, /root/reference/eval_inloc.py:
  * aspect-preserving resize with feature dims quantized to k·16  (:83-89)
  * fp16 (here: bf16) + relocalization_k_size forward             (:50-57)
  * both-direction corr_to_matches, scale='positive', softmax     (:151-158)
  * sort by descending score, then np.unique dedup over the
    (xA,yA,xB,yB) columns — keeping the max-score duplicate       (:159-173)
  * recentering of [0,1] grid coords onto cell centers            (:179-189)
  * fixed-capacity (1, n_panos, N, 5) zero-padded matches array,
    N = (S/16/k)·floor((S/16/k)·3/4), doubled for both dirs       (:116-118)
  * compressed savemat {'matches', 'query_fn', 'pano_fn'}         (:221)

TPU-native design: the forward + match extraction + recentering is ONE jitted
program per input-shape bucket (shapes recur heavily across the 3,560 pairs —
iPhone7 queries share one camera), cached in a small dict; sorting/dedup runs
host-side in numpy where ``np.unique``'s exact lexicographic semantics live.

Measured dead end (do not re-try without new evidence): batching a query's
same-shape panos into one dispatch via ``lax.map`` nets NO wall-clock win —
the mapped body loses XLA's cross-op fusion/layout quality (~3× slower device
time per pair than the standalone fused program, 11.6 s vs 3.5 s for a group
of 10 at InLoc resolution on v5e), which cancels the saved dispatch round
trips; host→device upload is not the bottleneck either (~1.4 GB/s warm).
"""

from __future__ import annotations

import math
import os
import time
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ncnet_tpu.config import EvalInLocConfig, ModelConfig
from ncnet_tpu.data.datasets import load_image
from ncnet_tpu.evaluation.pipeline import PipelineDepthController
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.observability import get_logger
from ncnet_tpu.observability.tracing import span

log = get_logger("eval.inloc")
from ncnet_tpu.models.ncnet import (
    extract_features,
    ncnet_forward,
    ncnet_forward_from_feature_pair,
    ncnet_forward_from_features,
)
from ncnet_tpu.ops.image import normalize_imagenet, resize_bilinear_align_corners_np
from ncnet_tpu.ops.matching import corr_to_matches

FEATURE_STRIDE = 16  # backbone stride: scale_factor 0.0625 (eval_inloc.py:77)


def quantized_resize_shape(
    h: int, w: int, image_size: int, k_size: int
) -> Tuple[int, int]:
    """Output (H', W') for the InLoc resize: longest side scaled to
    ``image_size`` preserving aspect ratio; for k>1 both dims floored to
    multiples of ``k·16`` so the pooled feature grid is integral
    (eval_inloc.py:83-89)."""
    scale = max(h, w) / image_size
    if k_size == 1:
        return int(h / scale), int(w / scale)
    sf = 1.0 / FEATURE_STRIDE
    q = FEATURE_STRIDE * k_size
    out_h = int(math.floor(h / scale * sf / k_size) * q)
    out_w = int(math.floor(w / scale * sf / k_size) * q)
    return out_h, out_w


def load_and_preprocess(path: str, image_size: int, k_size: int) -> np.ndarray:
    """Read → ImageNet-normalize → quantized aspect-preserving resize.

    Matches the reference order (normalize THEN resize, eval_inloc.py:129) —
    the two commute only approximately under bilinear resampling, so the order
    is kept.  Returns ``(1, H', W', 3)`` float32.
    """
    img = load_image(path).astype(np.float32)
    img = normalize_imagenet(img).astype(np.float32)
    out_h, out_w = quantized_resize_shape(
        img.shape[0], img.shape[1], image_size, k_size
    )
    return resize_bilinear_align_corners_np(img, out_h, out_w)[None]


def load_raw(path: str) -> np.ndarray:
    """Decode only: ``(1, H, W, 3)`` uint8 for the device-preprocessing
    matcher path — ~4-15× less host→device traffic than the preprocessed
    float32 tensors (the reference UPSCALES the 1600×1200 db cutouts to max
    side 3200, so their raw bytes are 15× smaller than the resized f32)."""
    return load_image(path)[None]


def device_preprocess(
    img: jnp.ndarray, image_size: int, k_size: int
) -> jnp.ndarray:
    """The jitted twin of :func:`load_and_preprocess` minus the decode:
    uint8 → ImageNet-normalize → quantized align-corners resize, same
    normalize-then-resize order as the reference (eval_inloc.py:129)."""
    from ncnet_tpu.ops.image import resize_bilinear_align_corners

    out_h, out_w = quantized_resize_shape(
        img.shape[1], img.shape[2], image_size, k_size
    )
    x = normalize_imagenet(img.astype(jnp.float32))
    return resize_bilinear_align_corners(x, out_h, out_w)


def match_capacity(image_size: int, k_size: int, both_directions: bool) -> int:
    """Fixed row capacity of the per-pair match table (eval_inloc.py:116-118).
    Assumes the reference's 3:4 portrait aspect for the nominal grid."""
    side = image_size / FEATURE_STRIDE / k_size
    n = int(side * math.floor(side * 3 / 4))
    return 2 * n if both_directions else n


def recenter(coord: jnp.ndarray, n: int) -> jnp.ndarray:
    """[0,1] grid-endpoint coordinate → cell-center coordinate on an
    ``n``-cell axis (eval_inloc.py:179-189): x·(n−1)/n + 0.5/n."""
    return coord * (n - 1) / n + 0.5 / n


class PreparedQuery(NamedTuple):
    """A query readied by ``matcher.preprocess``: the preprocessed image
    (kept for the sharded-forward fallback) plus its backbone features,
    computed ONCE and reused across the query's ~10 pano pairs — the
    reference recomputes the query trunk per pair (eval_inloc.py:124-132),
    ~30 ms/pair of redundant device work at 3200 px."""

    image: jnp.ndarray
    features: jnp.ndarray


class PreparedDb(NamedTuple):
    """A DATABASE image resolved through the persistent feature store by
    ``matcher.prepare_db`` (ncnet_tpu/store/): its backbone features (a
    verified store hit, or a recompute that was committed back) plus how
    they were obtained — ``"hit"`` / ``"miss"`` / ``"recompute"``.
    Dispatching a ``(PreparedQuery, PreparedDb)`` pair runs the
    feature-pair program: ZERO backbone extractions for the pair."""

    features: jnp.ndarray
    status: str


def extract_match_table(
    out,
    *,
    k_size: int,
    do_softmax: bool,
    both_directions: bool,
    flip_direction: bool = False,
) -> jnp.ndarray:
    """The post-forward half of the pair matcher's jitted program: filtered
    ``NCNetOutput`` → stacked ``(5, N)`` match table (xA, yA, xB, yB, score),
    cell-center recentered (eval_inloc.py:151-189 minus the host-side
    sort/dedup, which :func:`sort_and_dedup` applies after the single
    device→host pull).  Factored out of the matcher so the cross-framework
    parity test (tests/test_inloc_match_parity.py) binds to the PRODUCTION
    composition, not a restatement."""
    corr, delta4d = out.corr.astype(jnp.float32), out.delta4d
    fs1, fs2, fs3, fs4 = corr.shape[1:]
    k = max(k_size, 1)
    ms = []
    if both_directions or not flip_direction:
        ms.append(corr_to_matches(
            corr, delta4d=delta4d, k_size=k, do_softmax=do_softmax,
            scale="positive"))
    if both_directions or flip_direction:
        ms.append(corr_to_matches(
            corr, delta4d=delta4d, k_size=k, do_softmax=do_softmax,
            scale="positive", invert_matching_direction=True))
    xa = jnp.concatenate([m.xA for m in ms], axis=1)
    ya = jnp.concatenate([m.yA for m in ms], axis=1)
    xb = jnp.concatenate([m.xB for m in ms], axis=1)
    yb = jnp.concatenate([m.yB for m in ms], axis=1)
    score = jnp.concatenate([m.score for m in ms], axis=1)
    ya = recenter(ya, fs1 * k)
    xa = recenter(xa, fs2 * k)
    yb = recenter(yb, fs3 * k)
    xb = recenter(xb, fs4 * k)
    # one stacked (5, N) result: the device→host pull is a single
    # transfer instead of five round trips through the tunnel
    return jnp.stack(
        [v.astype(jnp.float32).ravel() for v in (xa, ya, xb, yb, score)]
    )


def make_pair_matcher(config: ModelConfig, params, *, do_softmax: bool,
                      both_directions: bool, flip_direction: bool,
                      mesh=None, preprocess_image_size: Optional[int] = None,
                      quality_cb=None, store=None):
    """Returns ``matcher(src, tgt) -> (xA, yA, xB, yB, score)`` numpy arrays.

    One jitted program per (src_shape, tgt_shape) bucket — jit's native
    per-shape compilation cache does the bucketing (shapes recur heavily
    across the 3,560 pairs): forward (bf16 + relocalization per ``config``),
    match extraction in the requested direction(s), and cell-center
    recentering all fused; results land on host for the numpy sort/dedup
    stage.

    ``preprocess_image_size``: when set, the matcher takes RAW uint8 images
    ``(1, H, W, 3)`` and runs :func:`device_preprocess` inside the jitted
    program (normalize + quantized resize to max side
    ``preprocess_image_size``).  Uploading raw uint8 instead of resized
    float32 cuts the dominant per-pair cost on this rig — host→device
    transfer — by ~4× (queries) to ~15× (upscaled db cutouts).  When None,
    the matcher takes already-preprocessed float32 tensors.

    ``mesh`` (with a >1 'spatial' axis) switches the forward to the
    hB-sharded path (parallel/spatial.py); pairs whose pooled hB does not
    divide over the shards fall back to the single-device forward.

    ``quality_cb``: when given, every fetched pair's label-free quality
    signals (``observability/quality.py``, computed IN the jitted pair
    program over the same filtered volume the matches come from and pulled
    as one extra row of the match table — no second device round trip)
    are passed to it as ``{signal: float}``.  ``run_inloc_eval`` wires this
    into tier-tagged ``quality`` events + the run's histogram digests; the
    default None costs nothing.

    ``store``: a :class:`~ncnet_tpu.store.FeatureStore` for DATABASE-side
    features.  ``matcher.prepare_db(raw_u8)`` resolves a pano's backbone
    features through it (content digest of the raw image → verified hit,
    or recompute + atomic commit) and returns a :class:`PreparedDb`;
    dispatching a ``(PreparedQuery, PreparedDb)`` pair rides the
    ``src_is_features=True`` jitted path extended with the target side
    (:func:`~ncnet_tpu.models.ncnet.ncnet_forward_from_feature_pair`), so
    a warm-store pair performs ZERO backbone extractions.  The store's
    degradation ladder guarantees ``prepare_db`` only ever gets SLOWER
    (recompute), never fails a query and never feeds unverified bytes.
    ``matcher.feature_extractions`` counts executed trunk dispatches —
    the spy the acceptance test reads ("a warm-store query performs
    exactly one backbone extraction").
    """
    k = max(config.relocalization_k_size, 1)

    def forward(p, src, tgt, sharded: bool):
        if sharded:
            from ncnet_tpu.parallel import spatial_forward

            return spatial_forward(config, p, src, tgt, mesh)
        return ncnet_forward(config, p, src, tgt)

    from ncnet_tpu.models.ncnet import ResilientJit

    # preprocessing is its OWN jitted stage (not part of the forward
    # program): both the sharded and unsharded forward then consume
    # bit-identical preprocessed tensors, so tie-breaking in the score sort
    # cannot depend on which forward program compiled the resize
    prep = ResilientJit(
        device_preprocess, hook=False,
        static_argnames=("image_size", "k_size"),
    )

    feats = ResilientJit(
        lambda p, x: extract_features(config, p, x), hook=False
    )

    def run_trunk(x: jnp.ndarray) -> jnp.ndarray:
        """THE backbone-extraction call site (query preprocess AND store
        misses both route here).  The counter counts EXECUTED dispatches of
        the compiled trunk program — not traces — so it is exactly the
        "extractions per query" number the feature store exists to
        minimize: 1 on a warm store, 1 + misses on a cold one."""
        matcher.feature_extractions += 1
        return feats(params, x)

    def prep_input(img) -> jnp.ndarray:
        """The ONE preprocessing call both input paths share — a divergence
        here would desync the PreparedQuery path from the in-dispatch path.
        (Scope note, ADVICE r3: sharing the preprocessed tensor makes the
        PREPROCESSING identical; the cached-trunk feature path itself is
        bit-stable only within one compiled program, so the eval loop uses
        the PreparedQuery path for every pair rather than mixing paths.)"""
        return prep(
            jnp.asarray(img), image_size=preprocess_image_size, k_size=k
        )

    def preprocess(img: np.ndarray) -> "PreparedQuery":
        """Raw uint8 ``(1, H, W, 3)`` → :class:`PreparedQuery` (preprocessed
        device tensor + backbone features).  Exposed as
        ``matcher.preprocess`` so the eval loop preprocesses AND trunks a
        query ONCE, reused across its ~10 pano pairs (the matcher accepts
        the returned object directly)."""
        assert preprocess_image_size is not None
        x = prep_input(img)
        return PreparedQuery(x, run_trunk(x))

    def prepare_db(img: np.ndarray) -> "PreparedDb":
        """Raw uint8 ``(1, H, W, 3)`` database image → :class:`PreparedDb`
        via the persistent store's degradation ladder: verified hit, or
        recompute through the SAME ``prep_input`` + trunk program the
        query path uses (so stored bytes are bit-identical to what a miss
        computes) + atomic commit back.  Requires ``store``."""
        assert store is not None, "prepare_db needs a FeatureStore"
        from ncnet_tpu.store import content_digest

        def compute() -> np.ndarray:
            return np.asarray(run_trunk(prep_input(img)), dtype=np.float32)

        arr, status = store.resolve(content_digest(np.asarray(img)), compute)
        return PreparedDb(jnp.asarray(arr), status)

    def run(p, src, tgt, sharded=False, src_is_features=False,
            tgt_is_features=False):
        if tgt_is_features:
            # the store-backed pair: both trunks precomputed, zero
            # extractions in this program
            out = ncnet_forward_from_feature_pair(config, p, src, tgt)
        elif src_is_features:
            out = ncnet_forward_from_features(config, p, src, tgt)
        else:
            out = forward(p, src, tgt, sharded)
        table = extract_match_table(
            out, k_size=k, do_softmax=do_softmax,
            both_directions=both_directions, flip_direction=flip_direction,
        )
        if quality_cb is None:
            return table
        # quality signals ride as one extra row of the (5, N) match table
        # (the append_quality_row wire protocol, defined in
        # observability/quality.py beside the signal list): the pair's
        # single device→host pull stays single
        from ncnet_tpu.observability.quality import append_quality_row

        return append_quality_row(table, out.corr)

    # the device-error injection hook lives on the pair program only (one
    # hook per dispatched PAIR keeps injected-call ordinals predictable);
    # prep/feats failures still reach the per-query isolation as plain
    # device errors and get the same demote-retrace recovery
    jitted = ResilientJit(
        run, label="inloc_pair",
        static_argnames=("sharded", "src_is_features", "tgt_is_features"),
    )

    warned_shapes = set()

    def can_shard(tgt_shape, raw: bool) -> bool:
        if mesh is None:
            return False
        from ncnet_tpu.parallel import SPATIAL_AXIS
        from ncnet_tpu.parallel.spatial import shardable_hb

        n = mesh.shape[SPATIAL_AXIS]
        if n <= 1:
            return False
        if raw:  # uint8 input: the quantized resize happens on device
            h = quantized_resize_shape(
                tgt_shape[1], tgt_shape[2], preprocess_image_size, k
            )[0]
        else:
            h = tgt_shape[1]
        hb = h // FEATURE_STRIDE  # fine-grid rows of the target
        ok = shardable_hb(hb, config.relocalization_k_size, n,
                          config.ncons_kernel_sizes)
        if not ok and tgt_shape not in warned_shapes:
            warned_shapes.add(tgt_shape)
            log.warning(f"target shape {tuple(tgt_shape)} (fine hB={hb}) "
                        f"does not shard over {n} devices; falling back to "
                        "the single-device forward for this shape bucket",
                        kind="validation")
        return ok

    def to_model_input(x):
        if isinstance(x, PreparedQuery):
            return x.image  # accepted in either argument position
        if preprocess_image_size is not None and x.dtype == np.uint8:
            return prep_input(x)
        return jnp.asarray(x)

    def dispatch(src, tgt):
        """Enqueue upload + preprocess + forward + match extraction for one
        pair and return the on-device (5, N) result WITHOUT blocking — jax's
        async dispatch lets the eval loop overlap this pair's device work
        (and its pano upload) with the previous pair's host-side fetch,
        sort/dedup, and the next pano's decode."""
        from ncnet_tpu.utils.profiling import annotate

        with annotate("inloc_pair_dispatch"):
            if isinstance(tgt, PreparedDb):
                # store-resolved database features: the feature-pair
                # program (never sharded — the caller gates the store off
                # under spatial sharding, whose forward takes images)
                if not isinstance(src, PreparedQuery):
                    raise ValueError(
                        "a PreparedDb target needs a PreparedQuery source "
                        "(both sides' features precomputed)")
                return jitted(params, src.features, tgt.features,
                              src_is_features=True, tgt_is_features=True)
            if isinstance(tgt, PreparedQuery):  # either position accepted
                tgt_shape, tgt_raw = tgt.image.shape, False
            else:
                tgt_shape, tgt_raw = tgt.shape, tgt.dtype == np.uint8
            sharded = can_shard(tgt_shape, raw=tgt_raw)
            tgt = to_model_input(tgt)
            if isinstance(src, PreparedQuery):
                if not sharded:
                    # fast path: the query's trunk ran once in preprocess
                    return jitted(params, src.features, tgt,
                                  src_is_features=True)
                src = src.image  # sharded forward replicates the trunk itself
            else:
                src = to_model_input(src)
            return jitted(params, src, tgt, sharded=sharded)

    def fetch(handle):
        """Block on a dispatch handle and unpack to five numpy vectors.
        A 6-row table carries the pair's quality-signal row (see ``run``):
        it is routed to ``quality_cb`` and stripped — callers always see
        the plain 5-vector match tuple."""
        from ncnet_tpu.observability.quality import split_quality_row

        table, quality = split_quality_row(
            np.asarray(handle, dtype=np.float32))
        if quality is not None and quality_cb is not None:
            quality_cb(quality)
        return tuple(table[i] for i in range(5))

    def matcher(src, tgt):
        """Inputs: preprocessed float tensors, or (when
        ``preprocess_image_size`` is set) raw uint8 images — a uint8 input is
        preprocessed on device, anything else is assumed preprocessed (e.g.
        by ``matcher.preprocess``).  Synchronous convenience wrapper around
        ``matcher.dispatch`` / ``matcher.fetch``."""
        return fetch(dispatch(src, tgt))

    def retrace():
        """Drop every cached executable (prep, trunk, pair program) so the
        next dispatch re-traces — the tier-degradation seam
        (models/ncnet.recover_from_device_failure) after a mid-run Pallas
        failure demoted the fused-stack tier."""
        for r in (prep, feats, jitted):
            r.retrace()

    matcher.preprocess = preprocess
    matcher.prepare_db = prepare_db
    matcher.dispatch = dispatch
    matcher.fetch = fetch
    matcher.retrace = retrace
    matcher.feature_extractions = 0  # executed trunk dispatches (the spy)
    matcher.store = store
    return matcher


def sort_and_dedup(xa, ya, xb, yb, score):
    """Sort matches by descending score, then drop duplicate (xA,yA,xB,yB)
    rows keeping the max-score instance — the reference's exact recipe
    (eval_inloc.py:159-173): ``np.unique`` over the coordinate columns of the
    score-sorted table returns first-occurrence indices, and first occurrence
    in a descending-score table IS the max-score duplicate.  Output order is
    np.unique's lexicographic order, as in the reference."""
    order = np.argsort(-score, kind="stable")
    xa, ya, xb, yb, score = (v[order] for v in (xa, ya, xb, yb, score))
    coords = np.stack([xa, ya, xb, yb], axis=0)
    _, unique_index = np.unique(coords, axis=1, return_index=True)
    return tuple(v[unique_index] for v in (xa, ya, xb, yb, score))


def manifest_name(host_index: int, host_count: int) -> str:
    """The run-manifest filename for one host stripe.  One manifest per
    stripe: concurrent hosts share the output dir, and a shared manifest's
    read-modify-write transitions would clobber each other.  The CLI's
    degraded-run exit check must read exactly THIS file — globbing
    manifest*.json would pick up other stripes' (or stale prior runs')
    manifests and fail a clean run forever."""
    if host_count == 1:
        return "manifest.json"
    return f"manifest.host{host_index}_of_{host_count}.json"


def resolve_host_stripe(config: EvalInLocConfig) -> Tuple[int, int]:
    """(host_index, host_count) with -1/0 auto-resolved from the jax
    process topology — the ONE resolution both the eval loop and the CLI's
    post-run manifest check use.  Raises on incoherent explicit stripes
    (index without count, index out of range): a misconfigured stripe
    silently drops/duplicates queries."""
    host_count = config.host_count or jax.process_count()
    host_index = (
        config.host_index if config.host_index >= 0 else jax.process_index()
    )
    if config.host_index >= 0 and not config.host_count:
        raise ValueError("host_index given without host_count")
    if not 0 <= host_index < host_count:
        raise ValueError(
            f"host_index {host_index} out of range for host_count {host_count}"
        )
    return host_index, host_count


def validate_matches_mat(path: str, n_panos: int, n_cap: int) -> bool:
    """Whether an existing per-query artifact is a loadable matches .mat
    with the expected keys and table shape.

    ``skip_existing`` treats existence as completion; that contract holds
    for OUR atomically-renamed artifacts, but a foreign file (a different
    n_panos run manually copied in, a file truncated by a full disk outside
    this writer) would otherwise be skipped and silently poison the
    downstream PnP stage.  Validation failure means "recompute", never
    "crash"."""
    try:
        from scipy.io import loadmat

        mat = loadmat(path)
    except Exception:
        return False
    m = mat.get("matches")
    if m is None or "query_fn" not in mat or "pano_fn" not in mat:
        return False
    if n_panos == 0:  # a zero-dim table roundtrips through .mat as empty
        return m.size == 0
    return m.shape == (1, n_panos, n_cap, 5)


def output_folder_name(config: EvalInLocConfig) -> str:
    """Experiment folder name encoding the eval settings
    (eval_inloc.py:60-71)."""
    name = os.path.basename(config.inloc_shortlist).split(".")[0]
    name += f"_SZ_NEW_{config.image_size}_K_{config.k_size}"
    if config.sparse_topk and config.k_size <= 1 and config.spatial_shards <= 1:
        # the coarse-to-fine tier changes the tables below full coverage:
        # its runs must not share (and silently overwrite) a dense run's
        # folder.  Appended only when the knob actually engages — with
        # k_size>1 or spatial sharding the pipeline chooser keeps every
        # pair dense and the outputs are the dense run's.
        name += f"_SPARSE{config.sparse_topk}"
    if config.retrieval_index:
        # the in-system shortlist changes WHICH panos each table row holds:
        # retrieval runs must not share (or skip-resume against) a
        # precomputed-order run's folder
        name += f"_RETR{config.retrieval_topk or config.n_panos}"
    if config.matching_both_directions:
        name += "_BOTHDIRS"
    elif config.flip_matching_direction:
        name += "_AtoB"
    else:
        name += "_BtoA"
    if config.softmax:
        name += "_SOFTMAX"
    if config.checkpoint:
        ckpt = os.path.basename(config.checkpoint.rstrip("/")).split(".")[0]
        name += "_CHECKPOINT_" + ckpt
    return name


def _as_str(x) -> str:
    """Unwrap loadmat's nested name cells (str | str-array | object scalar)."""
    while isinstance(x, np.ndarray):
        x = x.ravel()[0] if x.size else ""
    return str(x)


def load_shortlist(path: str):
    """Parse the densePE shortlist .mat: per-query filename + top-100 db pano
    list (eval_inloc.py:97-101).  Returns ``(query_fns, pano_fns)`` where
    ``pano_fns[q]`` is the array of pano names for query ``q``."""
    from scipy.io import loadmat

    dbmat = loadmat(path)
    db = dbmat["ImgList"][0, :]
    query_fns = [_as_str(db[q][0]) for q in range(len(db))]
    pano_fns = [np.asarray(db[q][1]).ravel() for q in range(len(db))]
    return query_fns, pano_fns


# the adaptive dispatch/fetch depth controller moved to
# evaluation/pipeline.py in round 6 (the PF-Pascal loop shares it); the
# private alias keeps this module's API and its tests stable
_PipelineDepthController = PipelineDepthController


def run_inloc_eval(
    config: EvalInLocConfig,
    model_config: Optional[ModelConfig] = None,
    params=None,
    progress: bool = True,
) -> str:
    """The full InLoc matching loop; returns the output matches directory.

    Reference flow (eval_inloc.py:124-221): per query, match against its
    top-``n_panos`` shortlisted images and write one compressed .mat with the
    fixed-capacity match table.

    Fault tolerance (round 7; ``config`` knobs, README "Resilient
    inference"): each query runs under per-query isolation — bounded retry
    with backoff, runtime fused-tier demotion on device errors, watchdogged
    fetches — and an exhausted budget quarantines the query into
    ``<out_dir>/manifest.json`` instead of aborting the run.  ``skip_existing``
    additionally validates the artifact before trusting it
    (:func:`validate_matches_mat`).
    """
    from ncnet_tpu.evaluation.pipeline import call_with_watchdog
    from ncnet_tpu.evaluation.resilience import (
        FaultPolicy,
        QuarantineBreaker,
        RunManifest,
        run_isolated,
    )
    from ncnet_tpu.models.ncnet import recover_from_device_failure
    from ncnet_tpu.utils.io import atomic_savemat

    if params is None:
        from ncnet_tpu.models.checkpoint import load_params

        base = ModelConfig(
            checkpoint=config.checkpoint,
            half_precision=True,  # the reference hard-codes it (eval_inloc.py:50)
            relocalization_k_size=config.k_size,
        )
        if config.checkpoint:
            model_config, params = load_params(config.checkpoint, base)
            model_config = model_config.replace(
                half_precision=True, relocalization_k_size=config.k_size
            )
        else:
            from ncnet_tpu.models.ncnet import init_ncnet

            model_config = base
            params = init_ncnet(model_config, jax.random.key(1))
    assert model_config is not None
    if model_config.relocalization_k_size != config.k_size:
        # the flag drives the model, as in the reference (eval_inloc.py:50-57)
        # — and the device resize quantization, match_capacity, and the
        # output folder name must all agree on one k
        model_config = model_config.replace(relocalization_k_size=config.k_size)
    if config.sparse_topk:
        # coarse-to-fine sparse matching (README "Coarse-to-fine matching"):
        # applies per shape bucket through the forward's pipeline chooser.
        # maxpool4d relocalization composes with the dense volume only, so
        # the default k_size=2 keeps every pair dense — warn loudly rather
        # than let the knob silently do nothing
        if config.k_size > 1:
            log.warning(
                f"sparse_topk={config.sparse_topk} with k_size="
                f"{config.k_size}: relocalization pooling keeps the dense "
                "path (pass --k_size 1 to run the coarse2fine tier)",
                kind="validation")
        if config.spatial_shards > 1:
            # the hB-sharded forward builds its own correlation volume and
            # never consults the pipeline chooser, while NON-shardable
            # shape buckets would fall back through it — one run must not
            # mix sparse and dense tables per pair, so the knob is dropped
            # wholesale here (the feature-store-under-sharding rule)
            log.warning(
                f"sparse_topk={config.sparse_topk} ignored under "
                f"spatial_shards={config.spatial_shards} (the hB-sharded "
                "forward is dense; a mixed sparse/dense run would be "
                "per-pair inconsistent)", kind="validation")
        else:
            model_config = model_config.replace(
                sparse_topk=config.sparse_topk)

    mesh = None
    if config.spatial_shards > 1:
        from ncnet_tpu.parallel import make_mesh

        # LOCAL devices only: under multi-host striping each process runs a
        # different query stream, so a mesh spanning processes would need
        # lockstep execution that striping deliberately gives up
        mesh = make_mesh(
            data=1, spatial=config.spatial_shards, devices=jax.local_devices()
        )

    query_fns, pano_fns = load_shortlist(config.inloc_shortlist)
    pano_fn_all = np.vstack([p[:, None] for p in pano_fns])

    out_dir = os.path.join(config.output_root, output_folder_name(config))
    os.makedirs(out_dir, exist_ok=True)

    n_queries = min(config.n_queries, len(query_fns))
    # multi-host: stripe queries across processes (per-query output files
    # are independent, so hosts never contend; -1/0 → auto-detect,
    # single-host runs get the identity stripe)
    host_index, host_count = resolve_host_stripe(config)

    # observability: an explicit telemetry dir opens (and globally binds) an
    # event log for the run — per-query events here, retry/quarantine/tier
    # events from the deep layers; otherwise events flow to any sink the
    # caller already bound, or nowhere, for free.  Bound BEFORE the feature
    # store below is constructed, so its store_open / GC / health events
    # land in THIS run's log (run_report --store replays them).
    own_sink = prev_sink = None
    n_done = 0
    if config.telemetry_dir:
        from ncnet_tpu.observability.events import EventLog

        # one file PER HOST under striping (the PR 3 manifests' rule):
        # EventLog's torn-tail sealing and fsynced appends assume a single
        # writer, so hosts must never share an append fd; run_report takes
        # multiple logs
        log_name = ("events.jsonl" if host_count == 1
                    else f"events.host{host_index}.jsonl")
        own_sink = EventLog(
            os.path.join(config.telemetry_dir, log_name),
            run_meta={"eval": "inloc",
                      "experiment": output_folder_name(config),
                      "host_index": host_index,
                      "host_count": host_count},
        )
        prev_sink = obs_events.set_global_sink(own_sink)
        own_sink.emit("run_start",
                      envelope=obs_events.run_envelope(own_sink.run_id),
                      eval="inloc", n_queries=n_queries)

    store = None  # assigned below; hoisted so the failure handler can close
    retrieval_store = None  # ditto — the in-system shortlist's coarse store
    try:
        # per-pair match-quality signals (README "Quality observability"):
        # computed in the pair program, fetched with the match table, streamed
        # as tier-tagged `quality` events and digested per run — the label-free
        # accuracy monitor this eval otherwise lacks entirely (InLoc has no
        # in-loop metric; a silent tier regression here only surfaces after the
        # downstream PnP stage, hours later)
        from ncnet_tpu.observability.metrics import MetricsRegistry
        from ncnet_tpu.observability.quality import emit_quality

        from ncnet_tpu.observability.quality import active_tier

        quality_registry = MetricsRegistry(scope="inloc_eval")
        # memory observability at query boundaries (observability/memory.py):
        # rate-limited device_snapshot events (HBM pressure beside the query
        # timeline — the InLoc volume is the repo's biggest allocation) and
        # the live-array leak sentinel (a handle retained across queries grows
        # without bound at ~90 MB per preprocessed pano)
        from ncnet_tpu.observability.device import DeviceMonitor
        from ncnet_tpu.observability.memory import LeakSentinel

        dev_monitor = DeviceMonitor(every_s=30.0)
        leak_sentinel = LeakSentinel(window=4, min_interval_s=1.0,
                                     scope="inloc_eval")

        def on_pair_quality(signals):
            emit_quality("inloc_eval", signals,
                         tier=active_tier(model_config.half_precision),
                         registry=quality_registry)

        # persistent database-side feature store (ncnet_tpu/store/; README
        # "Feature store"): pano features are resolved through verified cached
        # entries keyed by (image content digest, backbone fingerprint), so a
        # warm query pays ONE backbone extraction (its own) instead of 1 + 10.
        # Disabled under spatial sharding — the sharded forward takes images,
        # not features — and fail-open by construction: any store trouble only
        # means recompute, never a failed or wrong query.
        store = None
        if config.feature_store_dir:
            if mesh is not None:
                log.warning(
                    "feature_store_dir ignored under spatial_shards > 1 (the "
                    "hB-sharded forward consumes images, not cached features)",
                    kind="validation")
            else:
                from ncnet_tpu.store import FeatureStore, backbone_fingerprint

                fp = backbone_fingerprint(
                    params, image_size=config.image_size, k_size=config.k_size,
                    dtype="bf16" if model_config.half_precision else "f32")
                store = FeatureStore(
                    config.feature_store_dir, fp,
                    budget_bytes=config.feature_store_budget_mb * 2 ** 20,
                    scope="inloc_eval")
                # superseded-generation GC: entries computed under OTHER
                # weights can never be read again (fingerprint mismatch is a
                # miss), so they only waste the budget
                store.gc_superseded()

        # in-system retrieval shortlist (ncnet_tpu/retrieval/; README
        # "Sharded retrieval"): a coarse index + verified store re-rank each
        # query's precomputed .mat candidate row before fine matching.
        # Fail-open like the feature store: index/store/descriptor trouble
        # falls back to the precomputed .mat order with a warning + a
        # retrieval_fallback event — degraded retrieval may widen a query's
        # candidate order, never fail it and never silently truncate it.
        retrieval = None
        if config.retrieval_index:
            import re as _re

            from ncnet_tpu.retrieval.index import (
                load_index_manifests,
                local_shortlist,
            )
            from ncnet_tpu.retrieval.scoring import (
                coarse_volume_from_features,
                pooled_descriptor,
                raw_coarse_volume,
            )
            from ncnet_tpu.store import FeatureStore as _CoarseStore

            r_index = load_index_manifests(config.retrieval_index)
            # raw-extractor indexes encode their fine grid in the synthetic
            # fingerprint (raw-s<grid>-k0-f32-c<factor>); the query
            # descriptor must pool from the same grid to stay comparable
            _m = _re.search(r"^raw-s(\d+)-", r_index["fingerprint"])
            retrieval_store = _CoarseStore(
                os.path.dirname(os.path.abspath(r_index["sources"][0])),
                r_index["fingerprint"], scope="inloc_retrieval")
            retrieval = {"index": r_index,
                         "grid": int(_m.group(1)) if _m else 16,
                         "topk": int(config.retrieval_topk
                                     or config.n_panos)}
            log.info(
                f"retrieval shortlist on: {len(r_index['panos'])} indexed "
                f"panos, extractor={r_index['extractor']}, topk="
                f"{retrieval['topk']}, min_coverage="
                f"{config.retrieval_min_coverage}")

        matcher = make_pair_matcher(
            model_config, params,
            do_softmax=config.softmax,
            both_directions=config.matching_both_directions,
            flip_direction=config.flip_matching_direction,
            mesh=mesh,
            # raw uint8 in, normalize+resize on device: the upload is the
            # dominant per-pair cost and raw bytes are 4-15x smaller
            preprocess_image_size=config.image_size,
            quality_cb=on_pair_quality,
            store=store,
        )
        n_cap = match_capacity(
            config.image_size, config.k_size, config.matching_both_directions
        )

        # one decode-ahead worker: the next pano decodes while the device chews
        # on the current pair (and the first pano while the query preprocesses)
        # — the eval twin of the training loader's prefetch (the reference
        # decodes serially, eval_inloc.py:129)
        from concurrent.futures import ThreadPoolExecutor

        def pano_jobs(q):
            n_panos = min(config.n_panos, len(pano_fns[q]))
            return [
                os.path.join(config.pano_path, _as_str(pano_fns[q][idx]))
                for idx in range(n_panos)
            ]

        def retrieval_plan(q, raw_q, src):
            """Score query ``q``'s FULL precomputed candidate row by coarse
            similarity (``retrieval/index.py::local_shortlist`` through the
            verified store) and return ``(top-k pano names, coverage)`` —
            or ``(None, coverage)`` when the row cannot be covered to
            ``config.retrieval_min_coverage``, in which case the caller
            matches the original .mat order (a reported fallback, never a
            silent truncation)."""
            row = [_as_str(pano_fns[q][i]) for i in range(len(pano_fns[q]))]
            r_index = retrieval["index"]
            sub = dict(r_index)
            sub["panos"] = {n: r_index["panos"][n] for n in row
                            if n in r_index["panos"]}
            try:
                if r_index["extractor"] == "raw":
                    desc = pooled_descriptor(raw_coarse_volume(
                        raw_q, r_index["factor"], grid=retrieval["grid"]))
                else:
                    desc = pooled_descriptor(coarse_volume_from_features(
                        np.asarray(src.features, dtype=np.float32),
                        r_index["factor"]))
                res = local_shortlist(retrieval_store, sub, desc,
                                      topk=retrieval["topk"])
            except Exception as e:  # noqa: BLE001 — fail-open: retrieval
                # trouble must never fail a query, only un-reorder it
                log.warning(f"retrieval scoring failed for query {q + 1} "
                            f"({e}); matching the precomputed .mat order",
                            kind="retrieval")
                obs_events.emit("retrieval_fallback", query=q + 1,
                                reason="error", error=str(e)[:200])
                return None, 0.0
            # outcome-total coverage over the ROW: panos absent from the
            # index count against it exactly like unreadable entries
            coverage = res["consulted"] / max(1, len(row))
            if coverage < config.retrieval_min_coverage:
                log.warning(
                    f"retrieval coverage {coverage:.3f} < "
                    f"{config.retrieval_min_coverage} for query {q + 1} "
                    f"({res['consulted']}/{len(row)} row panos scored); "
                    "matching the precomputed .mat order", kind="retrieval")
                obs_events.emit("retrieval_fallback", query=q + 1,
                                reason="coverage",
                                coverage=round(coverage, 6),
                                consulted=res["consulted"], row=len(row))
                return None, coverage
            names = [p for p, _s in res["scores"]][:config.n_panos]
            obs_events.emit("retrieval_shortlist", query=q + 1,
                            coverage=round(coverage, 6),
                            consulted=res["consulted"], row=len(row),
                            topk=len(names),
                            unavailable=len(res["unavailable"]))
            return names, coverage

        def process_query(q, io_pool):
            out_path = os.path.join(out_dir, f"{q + 1}.mat")
            if progress:
                log.info(str(q))
            matches = np.zeros((1, config.n_panos, n_cap, 5))
            jobs = pano_jobs(q)
            shortlist_names = None
            retrieval_coverage = None
            if retrieval is None:
                # an empty shortlist row still writes its all-zeros table
                pending = io_pool.submit(load_raw, jobs[0]) if jobs else None
                # preprocess the query ONCE; it is reused across its ~10
                # pano pairs
                src = matcher.preprocess(
                    load_raw(os.path.join(config.query_path, query_fns[q]))
                )
            else:
                # retrieval may reorder the jobs, so the decode-ahead
                # submit has to wait for the plan; query load + preprocess
                # come first either way (the descriptor needs them)
                raw_q = load_raw(
                    os.path.join(config.query_path, query_fns[q]))
                src = matcher.preprocess(raw_q)
                with span("retrieval_plan", query=q + 1):
                    names, retrieval_coverage = retrieval_plan(
                        q, raw_q, src)
                if names is not None:
                    shortlist_names = names
                    jobs = [os.path.join(config.pano_path, n)
                            for n in names]
                pending = io_pool.submit(load_raw, jobs[0]) if jobs else None
            # pipelined dispatch: pair idx+1's upload + forward are dispatched
            # (async) before pair idx's result is pulled, so the tunnel's
            # dispatch/transfer latency hides behind the previous pair's device
            # compute and host-side sort/dedup.  The depth adapts to the
            # tunnel's latency regime (see _PipelineDepthController); each
            # in-flight slot holds one preprocessed pano (~90 MB at 3200 px).
            depth_ctl.note_gap()  # query preprocess/IO gap is not pair latency
            in_flight = []  # [(idx, handle)]

            def drain_one(sample: bool = True):
                idx0, handle = in_flight.pop(0)
                # the watchdog converts a hung tunnel fetch into a retryable
                # FetchTimeoutError that the per-query isolation absorbs
                with span("fetch", pair=idx0):
                    xa, ya, xb, yb, score = call_with_watchdog(
                        matcher.fetch, (handle,),
                        timeout=config.fetch_timeout_s,
                        label=f"InLoc query {q + 1} pair {idx0}",
                    )
                if sample:
                    depth_ctl.note_drain()
                else:
                    # end-of-query tail: queued pairs fetch back-to-back with no
                    # dispatch between them — not a per-pair wall; recording
                    # them would bias the controller toward spurious shrink
                    depth_ctl.note_gap()
                store_pair(idx0, xa, ya, xb, yb, score)

            def store_pair(idx, xa, ya, xb, yb, score):
                if config.matching_both_directions:
                    # single-direction outputs stay in grid order, as in the
                    # reference (sort/dedup only happens in both-dirs mode,
                    # eval_inloc.py:151-177)
                    xa, ya, xb, yb, score = sort_and_dedup(xa, ya, xb, yb, score)
                if len(xa) > n_cap:
                    # non-3:4-aspect pano overflowing the nominal table (the
                    # reference would crash here): keep the n_cap highest-scoring
                    # rows, preserving their current order
                    log.warning(f"{len(xa)} matches exceed capacity {n_cap}; "
                                "keeping highest-scoring rows",
                                kind="validation")
                    sel = np.sort(np.argsort(-score, kind="stable")[:n_cap])
                    xa, ya, xb, yb, score = (v[sel] for v in (xa, ya, xb, yb, score))
                npts = len(xa)
                matches[0, idx, :npts, 0] = xa[:npts]
                matches[0, idx, :npts, 1] = ya[:npts]
                matches[0, idx, :npts, 2] = xb[:npts]
                matches[0, idx, :npts, 3] = yb[:npts]
                matches[0, idx, :npts, 4] = score[:npts]
                if progress and idx % 10 == 0:
                    log.info(">>>" + str(idx))

            for idx in range(len(jobs)):
                # decode span = the WAIT on the decode-ahead worker, i.e. the
                # part of pano decode the pipeline failed to hide
                with span("decode", pair=idx):
                    tgt = pending.result()
                if idx + 1 < len(jobs):
                    pending = io_pool.submit(load_raw, jobs[idx + 1])
                if store is not None:
                    # database side through the store: verified hit, or
                    # recompute + commit — this pair then dispatches the
                    # zero-extraction feature-pair program either way
                    with span("store_resolve", pair=idx):
                        tgt = matcher.prepare_db(tgt)
                with span("dispatch", pair=idx):
                    in_flight.append((idx, matcher.dispatch(src, tgt)))
                # `while`, not `if`: when the controller SHRINKS the depth
                # mid-query the extra in-flight slots must actually drain, or
                # the old deeper queue (and its ~90 MB/slot pano buffers)
                # would persist to the end of the query.  Only the FIRST drain
                # of the iteration is a per-pair wall sample: subsequent ones
                # fetch already-completed results back-to-back, and their ~0 s
                # intervals would corrupt the controller's min-wall estimate.
                first = True
                while len(in_flight) >= depth_ctl.depth:
                    drain_one(sample=first)
                    first = False
            while in_flight:
                drain_one(sample=False)
            payload = {"matches": matches, "query_fn": query_fns[q],
                       "pano_fn": pano_fn_all}
            if shortlist_names is not None:
                # when retrieval reordered the row, `matches` rows follow
                # THIS list (not pano_fn order) — record it, plus the
                # coverage the reorder was made under, for consumers
                payload["shortlist"] = np.asarray(
                    [[n] for n in shortlist_names], dtype=object)
                payload["retrieval_coverage"] = float(retrieval_coverage)
            atomic_savemat(out_path, payload, do_compression=True)

        manifest = None
        if config.write_manifest:
            manifest = RunManifest(
                os.path.join(out_dir, manifest_name(host_index, host_count)),
                meta={
                    "experiment": output_folder_name(config),
                    "n_queries": n_queries,
                    "n_panos": config.n_panos,
                    "host_index": host_index,
                    "host_count": host_count,
                },
            )
        policy = FaultPolicy(retries=config.query_retries,
                             backoff_s=config.retry_backoff_s,
                             quarantine=config.quarantine)
        breaker = QuarantineBreaker(policy.max_consecutive_quarantines)
    except BaseException:
        # construction failed after the sink was globally bound: the
        # run's finally below never runs, so restore/close here —
        # a leaked global sink would swallow the NEXT run's events (and
        # a leaked store would hold its journal handle open)
        if store is not None:
            store.close()
        if retrieval_store is not None:
            retrieval_store.close()
        if own_sink is not None:
            obs_events.set_global_sink(prev_sink)
            own_sink.close()
        raise

    def _query_loop(io_pool):
        nonlocal n_done
        for q in range(host_index, n_queries, host_count):
            qid = f"query_{q + 1}"
            out_path = os.path.join(out_dir, f"{q + 1}.mat")
            if config.skip_existing and os.path.exists(out_path):
                # resume-by-artifact: the per-query .mat is written via
                # temp-file + os.replace at the end of its pano loop, so its
                # existence means the query is done.  The folder name encodes
                # checkpoint + settings, making a stale hit impossible short
                # of swapping checkpoint contents under an unchanged name —
                # but a FOREIGN or truncated file (copied in by hand, a
                # non-atomic writer) is caught by validation and recomputed
                # rather than poisoning the downstream PnP stage.
                # loadmat-validating hundreds of completed multi-MB tables
                # on every resume is wasteful when the manifest already
                # proves THIS writer completed the query (its transitions
                # commit atomically) — validation guards artifacts of
                # UNKNOWN provenance, i.e. ones the manifest cannot vouch
                # for.  The manifest only vouches for what it OBSERVED: a
                # write this run/resume completed, or a validation that
                # actually passed — skipping with validate_existing=False
                # records nothing, or a later validating run would trust it.
                vouched = manifest is not None and manifest.is_completed(qid)
                if vouched or not config.validate_existing \
                        or validate_matches_mat(out_path, config.n_panos, n_cap):
                    if progress:
                        log.info(f"{q} (exists, skipped)")
                    if manifest is not None and not vouched \
                            and config.validate_existing:
                        manifest.complete(qid, skipped=True)
                    # a skipped unit is a COMPLETED unit: it must reset the
                    # breaker streak, or a resume over a mostly-done run
                    # would see only the persistently-broken queries
                    # back-to-back and falsely abort as systemic
                    breaker.note(False)
                    continue
                log.warning(f"{out_path} exists but failed validation "
                            "(foreign or truncated artifact); recomputing",
                            kind="validation")

            def on_failure(exc, kind):
                # an aborted drain leaves the controller's interval anchor
                # pointing at a torn cadence — clear it before the retry
                depth_ctl.note_failure()
                if kind == "device":
                    # demote the fused tier + re-trace: the retry (granted
                    # off-budget when this returns a tier name) runs on the
                    # surviving tier
                    return recover_from_device_failure(exc, matcher)
                return None

            t_q = time.perf_counter()

            def _traced_query(q=q):
                # one span per ATTEMPT (retries each get their own), so the
                # trace shows retry cost where the eval_query event only
                # shows the total wall
                with span("inloc_query", query=q + 1):
                    return process_query(q, io_pool)

            ok, _ = run_isolated(
                qid,
                _traced_query,
                policy=policy,
                manifest=manifest,
                on_failure=on_failure,
                label=f"InLoc query {q + 1}",
            )
            # N consecutive quarantines = the rig, not the queries, is
            # broken: abort loudly (SystemicEvalError) instead of
            # quarantining the rest of an hours-long run one by one
            breaker.note(not ok)
            if ok:
                n_done += 1
            obs_events.emit(
                "eval_query", query=q + 1, ok=bool(ok),
                wall_s=round(time.perf_counter() - t_q, 6),
                pipeline_depth=depth_ctl.depth,
            )
            # memory plane at the query boundary: HBM snapshot (rate-
            # limited) + live-array census for the leak sentinel
            dev_monitor.maybe_emit(step=q + 1)
            leak_sentinel.observe(step=q + 1)

    try:
        depth_ctl = _PipelineDepthController(config.pipeline_depth)
        with ThreadPoolExecutor(max_workers=1) as io_pool:
            _query_loop(io_pool)
        if manifest is not None and manifest.quarantined_ids:
            log.warning("quarantined queries (see manifest.json): "
                        + ", ".join(manifest.quarantined_ids),
                        kind="quarantine")
        # flush the per-run quality digests beside the completion summary
        # (one `metrics` event; the drift tool and run_report read both)
        summary_extra = {}
        if store is not None:
            # the store's per-run counters + the extraction spy ride the
            # summary: a warm run shows hits == pairs, misses == 0, and
            # feature_extractions == completed queries (one trunk each)
            summary_extra["store"] = store.health()
            summary_extra["feature_extractions"] = \
                matcher.feature_extractions
        if retrieval_store is not None:
            summary_extra["retrieval_store"] = retrieval_store.health()
        quality_registry.flush(event="eval_summary", eval="inloc",
                               completed=n_done,
                               quarantined=(list(manifest.quarantined_ids)
                                            if manifest is not None else []),
                               **summary_extra)
    finally:
        if store is not None:
            # the durable stats record run_report --store replays, then
            # release the journal handle
            store.flush_stats(eval="inloc")
            store.close()
        if retrieval_store is not None:
            retrieval_store.flush_stats(eval="inloc_retrieval")
            retrieval_store.close()
        if own_sink is not None:
            obs_events.set_global_sink(prev_sink)
            own_sink.close()
    return out_dir
