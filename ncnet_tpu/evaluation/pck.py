"""PCK (percentage of correct keypoints) metric.

Reference: ``pck`` / ``pck_metric`` (/root/reference/lib/eval_util.py:12-50).
The reference loops per sample and slices the first N valid keypoints; here
the whole computation is a masked, batched jnp program (keypoints are padded
to 20 with −1, padding is a suffix — lib/pf_dataset.py:106-108), so it jits
and batches freely instead of being locked to batch_size 1.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

from ncnet_tpu.ops import (
    Matches,
    bilinear_interp_point_tnf,
    points_to_pixel_coords,
    points_to_unit_coords,
)


def pck(
    source_points: jnp.ndarray,
    warped_points: jnp.ndarray,
    l_pck: jnp.ndarray,
    alpha: float = 0.1,
) -> jnp.ndarray:
    """Per-sample fraction of keypoints within ``alpha * L_pck``.

    Args:
      source_points: ``(B, 2, N)`` pixel coords, −1-padded (suffix).
      warped_points: ``(B, 2, N)`` estimated correspondents of the targets.
      l_pck: ``(B,)`` or ``(B, 1)`` normalization length.

    Returns:
      ``(B,)`` PCK values (NaN when a sample has zero valid points — the
      reference produces NaN there too and filters downstream).
    """
    valid = (source_points[:, 0, :] != -1) & (source_points[:, 1, :] != -1)
    dist = jnp.sqrt(jnp.sum((source_points - warped_points) ** 2, axis=1))
    thresh = jnp.reshape(l_pck, (-1, 1)) * alpha
    correct = (dist <= thresh) & valid
    return jnp.sum(correct, axis=1) / jnp.sum(valid, axis=1)


def pck_metric(batch: Dict[str, jnp.ndarray], matches: Matches, alpha: float = 0.1):
    """Warp target keypoints through the match field and score PCK against the
    source keypoints (eval_util.py:27-50).

    ``batch`` needs: source/target_points ``(B, 2, N)``, source/target_im_size
    ``(B, 3)`` as (h, w, c), L_pck ``(B, 1)``.
    """
    target_norm = points_to_unit_coords(batch["target_points"], batch["target_im_size"])
    warped_norm = bilinear_interp_point_tnf(matches, target_norm)
    warped = points_to_pixel_coords(warped_norm, batch["source_im_size"])
    return pck(batch["source_points"], warped, batch["L_pck"], alpha)
