"""Coarse-index manifests: the durable map from pano names to store entries.

An index manifest is the small JSON document ``tools/build_coarse_index.py``
writes next to the feature store: the coarse generation's fingerprint +
factor + extractor, and ``{pano_name: content_digest}`` for every pano
whose coarse volume was committed.  Shard hosts load it to know WHAT they
serve (the rendezvous assignment then says WHICH subset), the coordinator
loads it to plan scatter coverage, and the InLoc in-system shortlist loads
it to score queries locally.  Manifests from a striped build merge
(:func:`load_index_manifests`) — but only when fingerprint/factor/extractor
agree exactly; a mixed-generation index is refused, never silently scored.
"""

from __future__ import annotations

import glob as _glob
import json
import os
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

INDEX_SCHEMA = 1

__all__ = [
    "INDEX_SCHEMA",
    "load_index_manifests",
    "local_shortlist",
    "write_index_manifest",
]


def write_index_manifest(path: str, *, fingerprint: str, factor: int,
                         extractor: str, panos: Dict[str, str],
                         meta: Optional[Dict[str, Any]] = None) -> None:
    """Atomically write one index manifest (tmp + rename, the store's
    two-phase discipline: a SIGKILLed build rerun sees the old manifest or
    the new one, never a torn prefix)."""
    doc = {
        "schema": INDEX_SCHEMA,
        "fingerprint": str(fingerprint),
        "factor": int(factor),
        "extractor": str(extractor),
        "panos": {str(k): str(v) for k, v in panos.items()},
    }
    if meta:
        doc["meta"] = dict(meta)
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, sort_keys=True, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_index_manifests(paths) -> Dict[str, Any]:
    """Load + merge index manifest(s).  ``paths`` is one path, a glob
    pattern, or an iterable of either.  Raises ``ValueError`` on schema,
    fingerprint, factor or extractor disagreement — a merged index must be
    one coherent generation."""
    if isinstance(paths, (str, os.PathLike)):
        paths = [os.fspath(paths)]
    files: List[str] = []
    for p in paths:
        p = os.fspath(p)
        hits = sorted(_glob.glob(p)) if _glob.has_magic(p) else [p]
        if not hits:
            raise ValueError(f"index manifest glob matched nothing: {p}")
        files.extend(hits)
    if not files:
        raise ValueError("no index manifest paths given")
    merged: Optional[Dict[str, Any]] = None
    for path in files:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or doc.get("schema") != INDEX_SCHEMA:
            raise ValueError(
                f"{path}: index schema "
                f"{doc.get('schema') if isinstance(doc, dict) else '?'} != "
                f"{INDEX_SCHEMA} — refusing a manifest this build does not "
                "understand")
        if merged is None:
            merged = {"schema": INDEX_SCHEMA,
                      "fingerprint": str(doc["fingerprint"]),
                      "factor": int(doc["factor"]),
                      "extractor": str(doc.get("extractor", "backbone")),
                      "panos": dict(doc.get("panos") or {}),
                      "sources": [path]}
            continue
        for key in ("fingerprint", "factor", "extractor"):
            a, b = merged[key], doc.get(
                key, "backbone" if key == "extractor" else None)
            if (int(a) if key == "factor" else str(a)) != \
                    (int(b) if key == "factor" else str(b)):
                raise ValueError(
                    f"{path}: {key} {b!r} != {a!r} — manifests from "
                    "different index generations do not merge")
        merged["panos"].update(doc.get("panos") or {})
        merged["sources"].append(path)
    return merged


def local_shortlist(store, index: Dict[str, Any], desc: np.ndarray,
                    topk: int, compute=None) -> Dict[str, Any]:
    """Single-process retrieval pass (the InLoc in-system shortlist and
    the bitflip-recovery test both run this): score ``desc`` against every
    indexed pano's coarse volume read through the store's verified-read /
    quarantine / recompute ladder.  ``compute`` maps a pano name to a
    freshly computed coarse volume (enables transparent recompute of a
    corrupted entry); without it an unreadable entry lowers ``coverage``
    instead — never a crash, never unverified bytes.

    Returns ``{"scores": ((pano, score), ...) top-k, "coverage": float,
    "consulted": n, "unavailable": [names]}`` — the same outcome-honest
    coverage contract the distributed tier reports."""
    from ncnet_tpu.retrieval.scoring import score_coarse_volume, top_k

    panos = index["panos"]
    scores: Dict[str, float] = {}
    unavailable: List[str] = []
    for name, digest in panos.items():
        if compute is not None:
            try:
                vol, _status = store.resolve(
                    digest, lambda name=name: compute(name))
            except Exception:  # noqa: BLE001 — a pano that cannot be
                # scored lowers coverage; it must not fail the query
                unavailable.append(name)
                continue
        else:
            vol = store.get(digest)
            if vol is None:
                unavailable.append(name)
                continue
        scores[name] = score_coarse_volume(desc, vol)
    total = max(1, len(panos))
    return {
        "scores": top_k(scores, topk),
        "coverage": round(len(scores) / total, 6),
        "consulted": len(scores),
        "total": len(panos),
        "unavailable": unavailable,
    }
