"""Rendezvous (highest-random-weight) pano→shard assignment.

The retrieval tier's coverage story starts here: every pano is owned by the
``replication`` highest-scoring shards under rendezvous hashing, so

  * the assignment is a pure function of ``(pano_id, shard_ids,
    replication)`` — the coordinator, every shard host, and the offline
    index builder all derive the SAME placement with zero shared state and
    zero coordination traffic;
  * a dead shard loses CAPACITY, not COVERAGE: each of its panos is still
    owned by ``replication - 1`` other shards, and the coordinator's
    scatter plan simply walks down the pano's replica ranking;
  * adding/removing a shard moves only the panos whose top-R ranking
    actually changes (~1/N of the database), never a full reshuffle — the
    property consistent placement exists for.

Scores are keyed on ``blake2b(pano_id | shard_id)`` so they are stable
across processes, platforms and Python hash randomization (``hash()`` is
per-process salted and would silently disagree between the coordinator and
its shards — the one bug class this module must make impossible).
"""

from __future__ import annotations

import hashlib
from typing import Dict, Iterable, List, Sequence, Tuple

__all__ = [
    "assignment_table",
    "rendezvous_score",
    "replica_shards",
]


def rendezvous_score(pano_id: str, shard_id: str) -> int:
    """The (pano, shard) rendezvous weight — a stable 64-bit integer."""
    h = hashlib.blake2b(f"{pano_id}|{shard_id}".encode("utf-8"),
                        digest_size=8)
    return int.from_bytes(h.digest(), "big")


def replica_shards(pano_id: str, shard_ids: Sequence[str],
                   replication: int) -> Tuple[str, ...]:
    """The pano's replica ranking: shard ids ordered by descending
    rendezvous weight (id-ordered on the astronomically unlikely tie),
    truncated to ``replication``.  Rank 0 is the pano's primary; the
    coordinator's failover/hedging walks ranks 1..R-1."""
    if replication < 1:
        raise ValueError(f"replication must be >= 1, got {replication}")
    ranked = sorted(set(str(s) for s in shard_ids),
                    key=lambda s: (-rendezvous_score(pano_id, s), s))
    return tuple(ranked[:replication])


def assignment_table(pano_ids: Iterable[str], shard_ids: Sequence[str],
                     replication: int) -> Dict[str, List[str]]:
    """``{shard_id: [pano_id, ...]}`` — every pano appears in exactly
    ``min(replication, len(shard_ids))`` shard lists.  This is what a shard
    host serves and what the index builder materializes; per-shard lists
    preserve the input pano order (deterministic manifests)."""
    table: Dict[str, List[str]] = {str(s): [] for s in shard_ids}
    for pano in pano_ids:
        for sid in replica_shards(str(pano), shard_ids, replication):
            table[sid].append(str(pano))
    return table
