"""Scatter-gather retrieval coordinator: partial-failure-tolerant sweeps.

One query in, a global top-k pano shortlist out — assembled by fanning the
query's pooled descriptor to every shard that owns an un-consulted pano,
gathering scored answers, and walking each pano's rendezvous replica
ranking (``assignment.py``) when a shard fails.  The coordinator is the
retrieval tier's twin of ``serving/router.py``: the same READY/DEAD shard
lifecycle, transport-failure streaks, ``/healthz`` probe loops with
wire-probe resurrection, EWMA latency accounting, and outcome-total
bookkeeping — re-derived here over PANOS instead of requests, because the
unit that must never be lost is a database entry's chance to be scored.

The honesty contract (what the chaos suite pins):

  * every answer carries ``coverage`` — the fraction of the requested
    database actually consulted.  Coverage below ``min_coverage`` makes
    the answer DEGRADED (or, at zero, a classified shed/deadline) — a
    shortlist is never silently truncated by a dead shard;
  * with replication R ≥ 2, one shard's death (SIGKILL, injected
    ``dead_shard_urls``, corrupt response) costs CAPACITY, not COVERAGE:
    its panos re-dispatch down their replica rankings and the sweep still
    reports coverage 1.0;
  * a straggling shard is HEDGED: after ``hedge_after_s`` with no answer,
    its un-consulted panos are re-dispatched to replicas while the
    original attempt keeps running — first answer per pano wins, and the
    straggler is never punished as dead.
"""

from __future__ import annotations

import concurrent.futures
import http.client
import json
import socket
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.observability.export import Family, render
from ncnet_tpu.observability.logging import get_logger
from ncnet_tpu.observability.metrics import Histogram
from ncnet_tpu.retrieval.assignment import replica_shards
from ncnet_tpu.retrieval.scoring import top_k
from ncnet_tpu.retrieval.shard import RETRIEVAL_DOC_SCHEMA
from ncnet_tpu.retrieval.wire import SETTLE_MARGIN_S, RetrieveClient
from ncnet_tpu.serving.health import (
    ADMITTING,
    DEGRADED,
    READY,
    STOPPED,
    HealthMachine,
)
from ncnet_tpu.serving.introspect import IntrospectionServer
from ncnet_tpu.serving.request import DeadlineExceeded, Overloaded
from ncnet_tpu.serving.wire import WireError

log = get_logger("retrieval")

# shard lifecycle states (the router's backend states, minus DRAINING-as-
# routing-target: a DRAINING shard is simply not planned to)
SHARD_READY = "READY"
SHARD_DRAINING = "DRAINING"
SHARD_DEAD = "DEAD"

_EWMA_ALPHA = 0.3
_TRANSPORT_ERRORS = (OSError, socket.timeout, http.client.HTTPException,
                     WireError)
_CLIENT_POOL_CAP = 8

__all__ = [
    "RetrievalConfig",
    "RetrievalCoordinator",
    "ShardBackend",
    "build_retrieval_document",
    "retrieval_metrics_families",
]


@dataclass(frozen=True)
class RetrievalConfig:
    """Coordinator knobs.  Defaults are the 4-shard CPU chaos pod's."""

    topk: int = 10
    replication: int = 2
    # coverage below this makes an answer DEGRADED (1.0 = full sweep
    # required; an InLoc caller may accept 0.9 and say so explicitly)
    min_coverage: float = 1.0
    # per-query budget when the caller sends none (None = unbounded)
    default_budget_s: Optional[float] = None
    # outstanding shard attempt older than this with un-consulted panos
    # gets hedged to replicas (0 disables hedging)
    hedge_after_s: float = 0.25
    # socket-level bound per shard attempt — the hung-peer backstop
    shard_timeout_s: float = 10.0
    probe_period_s: float = 1.0
    resurrect_after_s: float = 1.0
    probe_timeout_s: float = 5.0
    # consecutive transport failures before a shard is marked DEAD
    max_failures: int = 2
    # scatter worker threads shared by all in-flight queries
    max_workers: int = 16
    introspect_host: str = "127.0.0.1"
    introspect_port: Optional[int] = None


class ShardBackend:
    """One shard host as the coordinator sees it: client pool, failure
    streak, EWMA, lifecycle state.  The row shape mirrors the router's
    ``Backend.probe_row`` (``last_result_age_s`` / ``ewma_wall_ms``) so
    ``stall_watchdog --url`` reads a retrieval document unchanged."""

    def __init__(self, shard_id: str, url: str, *, timeout_s: float):
        self.id = str(shard_id)
        self.url = str(url)
        self.timeout_s = float(timeout_s)
        self.state = SHARD_READY
        self.consecutive_failures = 0
        self.inflight = 0
        self.requests = 0
        self.results = 0
        self.failures = 0
        self.deaths = 0
        self.hedges_absorbed = 0
        self.dead_since: Optional[float] = None
        self.last_result_t: Optional[float] = None
        self.ewma_wall_s: Optional[float] = None
        self._clients: List[RetrieveClient] = []

    # pool discipline copied from the router: pop/append under the owner's
    # lock, capped so a burst cannot hoard sockets
    def acquire(self) -> RetrieveClient:
        if self._clients:
            return self._clients.pop()
        return RetrieveClient(self.url, timeout_s=self.timeout_s)

    def release(self, client: RetrieveClient) -> None:
        if len(self._clients) < _CLIENT_POOL_CAP:
            self._clients.append(client)
        else:
            client.close()

    def close_clients(self) -> None:
        clients, self._clients = self._clients, []
        for c in clients:
            c.close()

    def note_success(self, wall_s: float) -> None:
        self.results += 1
        self.consecutive_failures = 0
        self.last_result_t = time.monotonic()
        self.ewma_wall_s = wall_s if self.ewma_wall_s is None else (
            _EWMA_ALPHA * wall_s + (1.0 - _EWMA_ALPHA) * self.ewma_wall_s)

    def note_failure(self) -> None:
        self.failures += 1
        self.consecutive_failures += 1

    def probe_row(self) -> Dict[str, Any]:
        now = time.monotonic()
        return {
            "id": self.id,
            "url": self.url,
            "state": self.state,
            "ewma_wall_ms": (round(self.ewma_wall_s * 1e3, 3)
                             if self.ewma_wall_s else None),
            "consecutive_failures": self.consecutive_failures,
            "inflight": self.inflight,
            "requests": self.requests,
            "results": self.results,
            "failures": self.failures,
            "deaths": self.deaths,
            "hedges_absorbed": self.hedges_absorbed,
            "dead_age_s": (round(now - self.dead_since, 3)
                           if self.dead_since is not None else None),
            "last_result_age_s": (round(now - self.last_result_t, 3)
                                  if self.last_result_t is not None
                                  else None),
        }


@dataclass
class _Attempt:
    """One in-flight shard dispatch inside a query's scatter plan."""

    shard_id: str
    panos: List[str]
    dispatched_t: float
    hedge: bool = False
    hedged: bool = False  # set once this attempt has spawned its hedge


class RetrievalCoordinator:
    """The scatter-gather front of a shard pod (see module docstring).

    ``shards`` maps shard id → base url of a running shard host (a
    ``ShardService`` behind its introspection server, usually a
    ``tools/serve_shard.py`` process); ``pano_ids`` is the full indexed
    database (usually ``index["panos"].keys()``)."""

    def __init__(self, shards: Dict[str, str], pano_ids: Sequence[str],
                 cfg: RetrievalConfig = RetrievalConfig()):
        if not shards:
            raise ValueError("a retrieval pod needs at least one shard")
        self.cfg = cfg
        self.shard_ids: Tuple[str, ...] = tuple(
            sorted(str(s) for s in shards))
        self.pano_ids: List[str] = [str(p) for p in pano_ids]
        self._pano_set = set(self.pano_ids)
        self._backends: Dict[str, ShardBackend] = {
            str(sid): ShardBackend(str(sid), url,
                                   timeout_s=cfg.shard_timeout_s)
            for sid, url in shards.items()}
        self._lock = threading.Lock()
        self._health = HealthMachine(event="retrieve_health")
        self._pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
        self._probe_threads: List[threading.Thread] = []
        self._stopping = threading.Event()
        self._introspect: Optional[_RetrievalIntrospectionServer] = None
        self._n = {"admitted": 0, "results": 0, "degraded": 0,
                   "deadline": 0, "shed": 0, "hedges": 0, "probes": 0}
        self._coverage_hist = Histogram(0.0, 1.0, bins=20)
        self._wall_hist = Histogram(0.0, 2000.0, bins=40)  # ms
        self._last_result_t: Optional[float] = None
        self._started_t = time.monotonic()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "RetrievalCoordinator":
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(2, int(self.cfg.max_workers)),
            thread_name_prefix="retrieve-scatter")
        if self.cfg.introspect_port is not None:
            self._introspect = _RetrievalIntrospectionServer(
                self, self.cfg.introspect_host, self.cfg.introspect_port)
            try:
                self._introspect.start()
            except OSError as e:
                self._introspect = None
                self._health.to(STOPPED, f"bind_failed:{e}")
                return self
        self._health.to(READY, "pod_up")
        obs_events.emit("retrieve_start", shards=len(self.shard_ids),
                        panos=len(self.pano_ids),
                        replication=self.cfg.replication,
                        topk=self.cfg.topk,
                        min_coverage=self.cfg.min_coverage)
        for sid in self.shard_ids:
            t = threading.Thread(target=self._probe_loop, args=(sid,),
                                 name=f"retrieve-probe-{sid}", daemon=True)
            t.start()
            self._probe_threads.append(t)
        return self

    def stop(self) -> None:
        self._stopping.set()
        with self._lock:
            if self._health.state != STOPPED:
                self._health.to(STOPPED, "clean")
            doc = build_retrieval_document(self)
        obs_events.emit("retrieve_health_doc", doc=doc)
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        for t in self._probe_threads:
            t.join(0.5)
        self._probe_threads = []
        with self._lock:
            for b in self._backends.values():
                b.close_clients()
        if self._introspect is not None:
            self._introspect.stop()
            self._introspect = None

    @property
    def state(self) -> str:
        return self._health.state

    @property
    def introspect_url(self) -> Optional[str]:
        return self._introspect.url if self._introspect else None

    def __enter__(self) -> "RetrievalCoordinator":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- shard lifecycle (the router's kill/revive machinery over shards) ---

    def _kill_locked(self, b: ShardBackend, reason: str) -> None:
        if b.state == SHARD_DEAD:
            return
        b.state = SHARD_DEAD
        b.deaths += 1
        b.dead_since = time.monotonic()
        b.close_clients()
        log.warning(f"retrieval shard {b.id} DEAD ({reason})", kind="pod")
        obs_events.emit("retrieve_backend", shard=b.id, state=SHARD_DEAD,
                        reason=reason, deaths=b.deaths)
        self._note_capacity_locked()

    def _revive_locked(self, b: ShardBackend, reason: str) -> None:
        if b.state == SHARD_READY:
            return
        b.state = SHARD_READY
        b.consecutive_failures = 0
        b.dead_since = None
        b.ewma_wall_s = None  # stale latency must not bias planning
        log.info(f"retrieval shard {b.id} READY ({reason})")
        obs_events.emit("retrieve_backend", shard=b.id, state=SHARD_READY,
                        reason=reason, deaths=b.deaths)
        self._note_capacity_locked()

    def _note_capacity_locked(self) -> None:
        ready = sum(1 for b in self._backends.values()
                    if b.state == SHARD_READY)
        total = len(self._backends)
        if ready < total and self._health.state == READY:
            self._health.to(DEGRADED, f"shards:{ready}/{total}")
        elif ready == total and self._health.state == DEGRADED:
            self._health.to(READY, "capacity_restored")

    # -- probing ------------------------------------------------------------

    def _probe_loop(self, sid: str) -> None:
        while not self._stopping.is_set():
            with self._lock:
                b = self._backends[sid]
                dead = b.state == SHARD_DEAD
            period = (self.cfg.resurrect_after_s if dead
                      else self.cfg.probe_period_s)
            if self._stopping.wait(max(0.05, period)):
                return
            try:
                self._probe_shard(sid)
            except Exception as e:  # noqa: BLE001 — a probe bug must
                # never kill the probe loop
                log.warning(f"shard probe {sid} error: "
                            f"{type(e).__name__}: {e}", kind="pod")

    def _fetch_healthz(self, url: str) -> Optional[Dict[str, Any]]:
        """The shard's ``/healthz`` document, accepting 200 OR 503 bodies
        (a DRAINING shard answers 503 with a valid document — that IS the
        signal).  None when the host is unreachable."""
        try:
            with urllib.request.urlopen(
                    f"{url}/healthz",
                    timeout=self.cfg.probe_timeout_s) as resp:
                raw = resp.read()
        except urllib.error.HTTPError as e:
            if e.code != 503:
                return None
            raw = e.read()
        except (OSError, socket.timeout):
            return None
        try:
            doc = json.loads(raw.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            return None
        return doc if isinstance(doc, dict) else None

    def _wire_probe(self, b: ShardBackend) -> bool:
        """Resurrection requires the DATA plane, not just a pretty
        document: one probe-marked request through the real wire."""
        client = RetrieveClient(b.url, timeout_s=self.cfg.probe_timeout_s)
        try:
            client.retrieve(np.zeros(1, np.float32), probe=True,
                            client="probe",
                            timeout_s=self.cfg.probe_timeout_s)
            return True
        except _TRANSPORT_ERRORS:
            return False
        except Exception:  # noqa: BLE001 — a CLASSIFIED outcome proves
            # the wire works; only transport failure keeps a shard dead
            return True
        finally:
            client.close()

    def _probe_shard(self, sid: str) -> None:
        with self._lock:
            b = self._backends[sid]
            state = b.state
        doc = self._fetch_healthz(b.url)
        admitting = (isinstance(doc, dict)
                     and doc.get("schema") == RETRIEVAL_DOC_SCHEMA
                     and doc.get("role") == "retrieval_shard"
                     and doc.get("state") in ADMITTING)
        with self._lock:
            self._n["probes"] += 1
        if state == SHARD_DEAD:
            if admitting and self._wire_probe(b):
                with self._lock:
                    self._revive_locked(b, "probe_ok")
            return
        if doc is None:
            with self._lock:
                b.consecutive_failures += 1
                if b.consecutive_failures >= self.cfg.max_failures:
                    self._kill_locked(b, "probe_unreachable")
            return
        if not admitting:
            # a valid document in a non-admitting state: coordinated
            # drain/stop — demote immediately, probe-only (no streak)
            with self._lock:
                if b.state == SHARD_READY:
                    b.state = SHARD_DRAINING
                    obs_events.emit("retrieve_backend", shard=b.id,
                                    state=SHARD_DRAINING,
                                    reason=str(doc.get("state")))
                    self._note_capacity_locked()
            return
        with self._lock:
            if b.state == SHARD_DRAINING:
                b.state = SHARD_READY
                obs_events.emit("retrieve_backend", shard=b.id,
                                state=SHARD_READY, reason="probe_ok")
                self._note_capacity_locked()
            # NOTE: an admitting document does NOT reset the data-plane
            # failure streak — only a real result does (note_success).  A
            # shard whose wire is dead but whose control plane still
            # answers must still cross the kill threshold.

    # -- the scatter-gather data plane --------------------------------------

    def _attempt(self, desc: np.ndarray, sid: str, panos: List[str],
                 topk: int, budget_s: Optional[float], request_id: str,
                 trace: Optional[str] = None
                 ) -> Tuple[str, str, Any, float]:
        """One shard dispatch, fully self-accounting (acquire/release,
        success/failure notes) so an ABANDONED straggler still settles its
        backend's books after the query has answered without it.  Returns
        ``(kind, shard_id, answer_or_exc, wall_s)`` with kind one of
        ``ok`` / ``classified`` / ``transport``."""
        with self._lock:
            b = self._backends[sid]
            client = b.acquire()
            b.inflight += 1
            b.requests += 1
        t0 = time.monotonic()
        try:
            timeout = self.cfg.shard_timeout_s
            if budget_s is not None:
                timeout = min(timeout, max(0.05,
                                           budget_s + SETTLE_MARGIN_S))
            answer = client.retrieve(
                desc, panos=panos, topk=topk, client="coordinator",
                budget_s=budget_s, request_id=request_id,
                timeout_s=timeout, trace=trace)
            wall = time.monotonic() - t0
            with self._lock:
                b.note_success(wall)
                self._last_result_t = time.monotonic()
            return ("ok", sid, answer, wall)
        except (Overloaded, DeadlineExceeded) as e:
            # a CLASSIFIED outcome: the shard is alive and honest — no
            # failure streak, but these panos retry on replicas
            wall = time.monotonic() - t0
            return ("classified", sid, e, wall)
        except _TRANSPORT_ERRORS as e:
            wall = time.monotonic() - t0
            with self._lock:
                b.note_failure()
                if b.consecutive_failures >= self.cfg.max_failures:
                    self._kill_locked(
                        b, f"transport:{type(e).__name__}")
            return ("transport", sid, e, wall)
        except Exception as e:  # noqa: BLE001 — outcome-total: anything
            # else is treated as a transport-grade shard failure
            wall = time.monotonic() - t0
            with self._lock:
                b.note_failure()
                if b.consecutive_failures >= self.cfg.max_failures:
                    self._kill_locked(b, f"error:{type(e).__name__}")
            return ("transport", sid, e, wall)
        finally:
            with self._lock:
                b.inflight -= 1
                b.release(client)

    def retrieve(self, desc: np.ndarray, *,
                 panos: Optional[Sequence[str]] = None,
                 topk: Optional[int] = None,
                 budget_s: Optional[float] = None,
                 client: str = "local", request_id: str = "",
                 probe: bool = False,
                 trace: Optional[str] = None) -> Dict[str, Any]:
        """One scatter-gather sweep → the coverage-honest answer document
        (see module docstring).  Raises classified ``Overloaded`` /
        ``DeadlineExceeded`` only at coverage ZERO — partial coverage is
        an answered, DEGRADED result, never an exception."""
        from ncnet_tpu.observability.tracing import normalize_trace

        trace = normalize_trace(trace)
        t0 = time.monotonic()
        with self._lock:
            if self._health.state not in ADMITTING:
                self._n["shed"] += 1
                raise Overloaded(
                    f"retrieval pod is {self._health.state}",
                    reason="draining")
            if not probe:
                self._n["admitted"] += 1
        if probe:
            return {"schema": RETRIEVAL_DOC_SCHEMA, "probe": True,
                    "scores": [], "coverage": 0.0, "consulted": 0,
                    "total": 0}
        k = int(topk) if topk else self.cfg.topk
        budget = (float(budget_s) if budget_s is not None
                  else self.cfg.default_budget_s)
        deadline_t = t0 + budget if budget is not None else None
        if panos is None:
            targets = list(self.pano_ids)
            unknown: List[str] = []
        else:
            targets = [str(p) for p in panos if str(p) in self._pano_set]
            unknown = [str(p) for p in panos
                       if str(p) not in self._pano_set]
        obs_events.emit("retrieve_admit", request=request_id,
                        client=client, panos=len(targets),
                        budget_s=budget,
                        **({"trace": trace} if trace else {}))
        desc = np.ascontiguousarray(np.asarray(desc, np.float32).ravel())
        return self._sweep(desc, targets, unknown, k, deadline_t, t0,
                           client, request_id, trace)

    def _plan_locked(self, uncovered: List[str],
                     tried: Dict[str, Set[str]]) -> Dict[str, List[str]]:
        """Group un-consulted panos by their best UNTRIED, READY replica
        shard (walking each pano's rendezvous ranking) — the scatter
        plan's single step.  Pure bookkeeping; caller holds the lock."""
        groups: Dict[str, List[str]] = {}
        for p in uncovered:
            for sid in replica_shards(p, self.shard_ids,
                                      self.cfg.replication):
                if sid in tried[p]:
                    continue
                if self._backends[sid].state != SHARD_READY:
                    continue
                groups.setdefault(sid, []).append(p)
                break
        return groups

    def _sweep(self, desc: np.ndarray, targets: List[str],
               unknown: List[str], k: int, deadline_t: Optional[float],
               t0: float, client: str, request_id: str,
               trace: Optional[str] = None) -> Dict[str, Any]:
        pool = self._pool
        if pool is None:
            raise Overloaded("coordinator not started", reason="draining")
        # conditional event stamp: untraced sweeps keep their event shape
        tr = {"trace": trace} if trace else {}
        tried: Dict[str, Set[str]] = {p: set() for p in targets}
        scores: Dict[str, float] = {}
        consulted: Set[str] = set()
        pending: Dict[concurrent.futures.Future, _Attempt] = {}
        hedges = attempts = 0

        def dispatch(groups: Dict[str, List[str]], *,
                     hedge: bool) -> None:
            nonlocal hedges, attempts
            for sid, group in groups.items():
                for p in group:
                    tried[p].add(sid)
                remaining = (max(0.01, deadline_t - time.monotonic())
                             if deadline_t is not None else None)
                fut = pool.submit(self._attempt, desc, sid, group, k,
                                  remaining, request_id, trace)
                pending[fut] = _Attempt(sid, group, time.monotonic(),
                                        hedge=hedge)
                attempts += 1
                if hedge:
                    hedges += 1
                    with self._lock:
                        self._n["hedges"] += 1
                        self._backends[sid].hedges_absorbed += 1
                    obs_events.emit("retrieve_hedge", request=request_id,
                                    shard=sid, panos=len(group), **tr)

        while True:
            now = time.monotonic()
            if deadline_t is not None and now >= deadline_t:
                break
            uncovered = [p for p in targets if p not in consulted]
            if not uncovered:
                break
            in_flight: Set[str] = set()
            for att in pending.values():
                in_flight.update(p for p in att.panos
                                 if p not in consulted)
            with self._lock:
                groups = self._plan_locked(
                    [p for p in uncovered if p not in in_flight], tried)
            dispatch(groups, hedge=False)
            # hedging: an outstanding attempt past hedge_after_s with
            # un-consulted panos gets those panos re-dispatched down
            # their replica rankings — first answer per pano wins
            if self.cfg.hedge_after_s > 0:
                for att in list(pending.values()):
                    if att.hedged or att.hedge:
                        continue
                    if now - att.dispatched_t < self.cfg.hedge_after_s:
                        continue
                    att.hedged = True
                    stale = [p for p in att.panos if p not in consulted]
                    if not stale:
                        continue
                    with self._lock:
                        hgroups = self._plan_locked(stale, tried)
                    dispatch(hgroups, hedge=True)
            if not pending:
                break  # nothing in flight and nothing plannable
            wait_t = 0.05
            if deadline_t is not None:
                wait_t = min(wait_t, max(0.001, deadline_t - now))
            done, _ = concurrent.futures.wait(
                list(pending), timeout=wait_t,
                return_when=concurrent.futures.FIRST_COMPLETED)
            for fut in done:
                att = pending.pop(fut)
                kind, sid, payload, wall = fut.result()
                if kind == "ok":
                    for p, s in payload.get("scores") or []:
                        p = str(p)
                        s = float(s)
                        if p not in scores or s > scores[p]:
                            scores[p] = s
                    consulted.update(
                        str(p) for p in payload.get("consulted") or [])
                else:
                    obs_events.emit(
                        "retrieve_shard_error", request=request_id,
                        shard=sid, kind=kind,
                        error=f"{type(payload).__name__}: {payload}"[:200],
                        panos=len(att.panos), **tr)
        # stragglers still in flight are ABANDONED (their _attempt settles
        # the backend's books when it lands); the query answers now
        total = len(targets)
        coverage = round(len(consulted) / total, 6) if total else 1.0
        wall_ms = round((time.monotonic() - t0) * 1e3, 3)
        uncoverable = sorted(p for p in targets if p not in consulted)
        if not consulted and total:
            expired = (deadline_t is not None
                       and time.monotonic() >= deadline_t)
            with self._lock:
                self._n["deadline" if expired else "shed"] += 1
            if expired:
                obs_events.emit("retrieve_deadline", request=request_id,
                                coverage=coverage, wall_ms=wall_ms, **tr)
                raise DeadlineExceeded(
                    "budget expired before any shard answered",
                    where="scatter")
            obs_events.emit("retrieve_shed", request=request_id,
                            reason="no_capacity", wall_ms=wall_ms, **tr)
            raise Overloaded("no shard could answer the sweep",
                             reason="no_capacity")
        degraded = coverage < self.cfg.min_coverage
        with self._lock:
            self._n["degraded" if degraded else "results"] += 1
            self._coverage_hist.add(coverage)
            self._wall_hist.add(wall_ms)
            self._last_result_t = time.monotonic()
        obs_events.emit("retrieve_result", request=request_id,
                        client=client, coverage=coverage,
                        degraded=degraded, hedges=hedges,
                        attempts=attempts, consulted=len(consulted),
                        total=total, wall_ms=wall_ms, **tr)
        return {
            "schema": RETRIEVAL_DOC_SCHEMA,
            "request": request_id,
            "scores": [[p, s] for p, s in top_k(scores, k)],
            "coverage": coverage,
            "consulted": len(consulted),
            "total": total,
            "degraded": degraded,
            "hedges": hedges,
            "attempts": attempts,
            "unavailable": uncoverable,
            "unknown": unknown,
            "wall_ms": wall_ms,
        }

    # -- health -------------------------------------------------------------

    def health(self) -> Dict[str, Any]:
        with self._lock:
            return build_retrieval_document(self)

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._n)


def build_retrieval_document(coord: RetrievalCoordinator
                             ) -> Dict[str, Any]:
    """The coordinator's health document (caller holds no invariants — the
    coordinator's :meth:`health` wraps this under its lock).  ``pod``
    carries one ``probe_row`` per shard in the router-document shape, so
    ``stall_watchdog``'s per-backend staleness backstop applies
    unchanged."""
    now = time.monotonic()
    backends = [coord._backends[sid].probe_row()
                for sid in coord.shard_ids]
    ready = sum(1 for b in backends if b["state"] == SHARD_READY)
    last = coord._last_result_t
    cov = coord._coverage_hist
    return {
        "schema": RETRIEVAL_DOC_SCHEMA,
        "role": "retrieval",
        "state": coord._health.state,
        "service": coord._health.probe(),
        "pod": {"ready": ready, "total": len(backends),
                "backends": backends},
        "retrieval": {
            "panos": len(coord.pano_ids),
            "replication": coord.cfg.replication,
            "topk": coord.cfg.topk,
            "min_coverage": coord.cfg.min_coverage,
            "coverage_p50": cov.percentile(0.5) if cov.count else None,
            "coverage_min": cov.min,
        },
        "counters": dict(coord._n),
        "activity": {
            "age_s": round(now - (last if last is not None
                                  else coord._started_t), 3),
            "requests": coord._n["results"] + coord._n["degraded"],
        },
    }


def retrieval_metrics_families(coord: RetrievalCoordinator
                               ) -> List[Family]:
    """The curated ``ncnet_retrieve_*`` exposition families — the
    coordinator-tier cut every scrape and ``serve_top`` reads."""
    doc = coord.health()
    with coord._lock:
        cov_hist = coord._coverage_hist
        wall_hist = coord._wall_hist
    fams: List[Family] = []
    fams.append(Family("ncnet_retrieve_up", "gauge",
                       "1 while the coordinator admits sweeps")
                .add(1 if doc["state"] in ADMITTING else 0))
    state = Family("ncnet_retrieve_state", "gauge",
                   "coordinator health state (1 on the active series)")
    state.add(1, state=doc["state"])
    fams.append(state)
    outcomes = Family("ncnet_retrieve_requests_total", "counter",
                      "sweep outcomes (admitted and terminals)")
    for outcome, n in sorted(doc["counters"].items()):
        outcomes.add(n, outcome=outcome)
    fams.append(outcomes)
    fams.append(Family("ncnet_retrieve_shards", "gauge",
                       "shard capacity: ready vs total")
                .add(doc["pod"]["ready"], status="ready")
                .add(doc["pod"]["total"], status="total"))
    up = Family("ncnet_retrieve_shard_up", "gauge",
                "1 while this shard takes scatter traffic")
    deaths = Family("ncnet_retrieve_shard_deaths_total", "counter",
                    "times this shard was declared DEAD")
    ewma = Family("ncnet_retrieve_shard_wall_ewma_ms", "gauge",
                  "per-shard attempt wall EWMA")
    for row in doc["pod"]["backends"]:
        up.add(1 if row["state"] == SHARD_READY else 0, shard=row["id"])
        deaths.add(row["deaths"], shard=row["id"])
        if row.get("ewma_wall_ms") is not None:
            ewma.add(row["ewma_wall_ms"], shard=row["id"])
    fams.extend([up, deaths, ewma])
    fams.append(Family("ncnet_retrieve_coverage", "histogram",
                       "per-answer coverage (fraction of the database "
                       "consulted)").add_histogram(cov_hist))
    fams.append(Family("ncnet_retrieve_wall_ms", "histogram",
                       "per-answer sweep wall time")
                .add_histogram(wall_hist))
    return fams


def _render_retrieval_statusz(coord: RetrievalCoordinator) -> str:
    doc = coord.health()
    c = doc["counters"]
    r = doc["retrieval"]
    svc = doc["service"]
    lines = [
        "ncnet_tpu retrieval coordinator — statusz",
        f"state: {doc['state']}  (for {svc['age_s']}s"
        + (f", reason: {svc['reason']}" if svc.get("reason") else "") + ")",
        f"pod: {doc['pod']['ready']}/{doc['pod']['total']} shards ready  "
        f"(R={r['replication']}, {r['panos']} panos, "
        f"topk={r['topk']}, min_coverage={r['min_coverage']})",
        f"sweeps: admitted={c['admitted']}  results={c['results']}  "
        f"degraded={c['degraded']}  deadline={c['deadline']}  "
        f"shed={c['shed']}  hedges={c['hedges']}",
        f"coverage: p50={r['coverage_p50']}  min={r['coverage_min']}",
        "", "shards:",
    ]
    for row in doc["pod"]["backends"]:
        lines.append(
            f"  {row['id']:<12} {row['state']:<9} "
            f"results={row['results']:<6} failures={row['failures']:<4} "
            f"deaths={row['deaths']:<3} "
            f"ewma={row['ewma_wall_ms'] or '-'} ms "
            f"last_result_age={row['last_result_age_s'] or '-'} s")
    return "\n".join(lines) + "\n"


class _RetrievalIntrospectionServer(IntrospectionServer):
    """Coordinator control plane: base lifecycle/handler, retrieval-shaped
    payloads.  ``retrieve_payload`` dispatches to the coordinator's data
    plane via the base class; ``/match`` is refused."""

    def metrics_text(self) -> str:
        self._scrapes += 1
        fams = retrieval_metrics_families(self._service)
        fams.append(Family("ncnet_retrieve_scrapes_total", "counter",
                           "scrapes answered by the coordinator")
                    .add(self._scrapes))
        return render(fams)

    def statusz_text(self) -> str:
        return _render_retrieval_statusz(self._service)

    def match_payload(self, body: bytes):
        return (404, "text/plain; charset=utf-8",
                b"this host serves /retrieve, not /match\n")
