"""Retrieval wire plane: ``POST /retrieve`` on the NCMW framing.

The match wire (``serving/wire.py``) carries image pairs to one backend;
the retrieval wire carries a query's POOLED coarse descriptor to many
shard hosts and their scored pano lists back.  Same versioned ``NCMW``
framing (magic + schema byte checked before anything is trusted), same
``budget_s`` remaining-deadline contract, same outcome-total HTTP mapping
onto the ``serving/request.py`` exception classes — so coordinator code
cannot tell, and need not care, whether a shard is in-process or across
the pod.

One addition the match wire does not need: the RESULT payload carries a
sha256 checksum in its header.  A shard's answer is a small JSON document
(scores + the consulted-pano accounting that feeds the coverage contract)
— silent corruption of one score would reorder a shortlist with no
downstream integrity check to catch it, so the client verifies the digest
and refuses a mismatch as :class:`~ncnet_tpu.serving.wire.WireError`
(= shard failure → the coordinator re-routes those panos to a replica).
The ``shard_bitflip_urls`` chaos hook flips a response byte client-side to
prove exactly that path.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import socket
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple
from urllib.parse import urlsplit

import numpy as np

from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.serving.request import (
    DeadlineExceeded,
    Overloaded,
    RequestQuarantined,
)
from ncnet_tpu.serving.wire import (
    _frame,
    _unframe,
    _OUTCOME_STATUS,
    CLOCK_SYNC_INTERVAL_S,
    WIRE_SETTLE_MARGIN_S,
    WireError,
    emit_clock_sync,
    sync_stamps,
)

RETRIEVE_CONTENT_TYPE = "application/x-ncnet-retrieve"

__all__ = [
    "RETRIEVE_CONTENT_TYPE",
    "RetrieveClient",
    "decode_retrieve_request",
    "decode_retrieve_response",
    "encode_retrieve_request",
    "encode_retrieve_response",
    "serve_retrieve",
]


# ---------------------------------------------------------------------------
# requests
# ---------------------------------------------------------------------------


def encode_retrieve_request(desc: np.ndarray, *,
                            panos: Optional[Sequence[str]] = None,
                            topk: Optional[int] = None,
                            client: str = "wire",
                            budget_s: Optional[float] = None,
                            request_id: str = "",
                            probe: bool = False,
                            trace: Optional[str] = None) -> bytes:
    """One retrieval query as wire bytes.  ``panos`` scopes the sweep to a
    subset of the receiver's assigned panos (the coordinator's scatter
    plan / failover re-dispatch); None = score everything assigned.
    ``probe=True`` marks the coordinator's resurrection probe — answered
    through the full data plane without scoring anything.  ``trace`` is
    the additive pod-trace header (old shards ignore the key losslessly);
    ``sent_t`` always rides so responses can carry the NTP-style clock
    stamps back (``serving/wire.py::sync_stamps``)."""
    d = np.ascontiguousarray(np.asarray(desc, dtype=np.float32).ravel())
    header = {
        "kind": "retrieve",
        "dim": int(d.shape[0]),
        "dtype": "float32",
        "panos": ([str(p) for p in panos] if panos is not None else None),
        "topk": (int(topk) if topk is not None else None),
        "client": str(client),
        "budget_s": (round(float(budget_s), 6)
                     if budget_s is not None else None),
        "request": str(request_id),
        "probe": bool(probe),
        "sent_t": round(obs_events.wall_now(), 6),
    }
    if trace:
        header["trace"] = str(trace)
    return _frame(header, d.tobytes())


def decode_retrieve_request(data: bytes
                            ) -> Tuple[np.ndarray, Dict[str, Any]]:
    """Wire bytes → ``(descriptor, meta)``; raises :class:`WireError` on a
    frame this build must refuse."""
    header, payload = _unframe(data)
    if header.get("kind") != "retrieve":
        raise WireError(f"not a retrieve frame: kind={header.get('kind')!r}")
    if header.get("dtype") != "float32":
        raise WireError(f"descriptor dtype {header.get('dtype')!r} != "
                        "float32")
    try:
        dim = int(header["dim"])
    except (KeyError, TypeError, ValueError) as e:
        raise WireError(f"bad descriptor dim: {e}") from e
    if dim < 1 or len(payload) != dim * 4:
        raise WireError(f"descriptor payload {len(payload)} bytes != "
                        f"declared {dim * 4}")
    desc = np.frombuffer(payload, np.float32, count=dim)
    panos = header.get("panos")
    meta = {
        "panos": ([str(p) for p in panos]
                  if isinstance(panos, list) else None),
        "topk": (int(header["topk"])
                 if isinstance(header.get("topk"), (int, float)) else None),
        "client": str(header.get("client", "wire")),
        "budget_s": (float(header["budget_s"])
                     if isinstance(header.get("budget_s"), (int, float))
                     else None),
        "request": str(header.get("request", "")),
        "probe": bool(header.get("probe", False)),
        "trace": (str(header["trace"])
                  if isinstance(header.get("trace"), str) else None),
        "sent_t": (float(header["sent_t"])
                   if isinstance(header.get("sent_t"), (int, float))
                   else None),
    }
    return desc, meta


# ---------------------------------------------------------------------------
# responses
# ---------------------------------------------------------------------------


def encode_retrieve_response(answer: Dict[str, Any],
                             extra: Optional[Dict[str, Any]] = None
                             ) -> Tuple[int, bytes]:
    """``(http_status, wire bytes)`` for a shard's (or coordinator's)
    answer document.  The document travels as canonical JSON payload with
    its sha256 in the header — the integrity seal the client verifies.
    ``extra`` merges additive header fields (the clock-sync stamps);
    the seal covers the payload only, so stamps stay out of the digest."""
    payload = json.dumps(answer, sort_keys=True).encode("utf-8")
    header = {
        "outcome": "result",
        "kind": "retrieve",
        "sha256": hashlib.sha256(payload).hexdigest(),
    }
    if extra:
        header.update(extra)
    return _OUTCOME_STATUS["result"], _frame(header, payload)


def encode_retrieve_error(exc: Exception,
                          extra: Optional[Dict[str, Any]] = None
                          ) -> Tuple[int, bytes]:
    """Classified terminal rejection — same outcome classes and status
    mapping as the match wire (``serving/wire.py::encode_error``); an
    unexpected exception encodes as a quarantine-shaped 500 so the wire
    stays outcome-total."""
    header: Dict[str, Any] = {"kind": "retrieve",
                              "message": str(exc)[:500]}
    if extra:
        header.update(extra)
    if isinstance(exc, Overloaded):
        header.update(outcome="overloaded", reason=exc.reason,
                      retry_after_s=exc.retry_after_s)
    elif isinstance(exc, DeadlineExceeded):
        header.update(outcome="deadline", where=exc.where)
    elif isinstance(exc, RequestQuarantined):
        header.update(outcome="quarantined", kind_=exc.kind,
                      attempts=exc.attempts)
    else:
        header.update(outcome="quarantined", kind_="internal", attempts=1)
    return _OUTCOME_STATUS[header["outcome"]], _frame(header)


def decode_retrieve_response(data: bytes) -> Dict[str, Any]:
    """Wire response → the answer document, or RAISES the classified
    terminal error exactly as the local call would.  A payload whose
    sha256 does not match its header is a :class:`WireError` — corrupt
    bytes from a shard are a SHARD failure (re-route to a replica), never
    a silently reordered shortlist."""
    header, payload = _unframe(data)
    return _retrieve_response_from(header, payload)


def _retrieve_response_from(header: Dict[str, Any],
                            payload: bytes) -> Dict[str, Any]:
    """The classify-or-return body of :func:`decode_retrieve_response`,
    split out so the client can read the clock-sync stamps off the header
    before the outcome check raises."""
    outcome = header.get("outcome")
    msg = str(header.get("message", ""))
    if outcome == "overloaded":
        ra = header.get("retry_after_s")
        raise Overloaded(msg or "shard overloaded",
                         reason=str(header.get("reason", "unknown")),
                         retry_after_s=float(ra) if isinstance(
                             ra, (int, float)) else None)
    if outcome == "deadline":
        raise DeadlineExceeded(msg or "deadline expired at the shard",
                               where=str(header.get("where", "shard")))
    if outcome == "quarantined":
        raise RequestQuarantined(
            msg or "shard quarantined the request",
            kind=str(header.get("kind_", "unknown")),
            attempts=int(header.get("attempts", 1) or 1))
    if outcome != "result":
        raise WireError(f"unknown retrieve outcome {outcome!r}")
    want = header.get("sha256")
    got = hashlib.sha256(payload).hexdigest()
    if not isinstance(want, str) or want != got:
        raise WireError(
            f"retrieve payload checksum mismatch ({got[:12]}… != declared "
            f"{str(want)[:12]}…) — refusing corrupt scores")
    try:
        answer = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"unparseable retrieve answer: {e}") from e
    if not isinstance(answer, dict):
        raise WireError("retrieve answer is not an object")
    return answer


# ---------------------------------------------------------------------------
# server side: the /retrieve handler body
# ---------------------------------------------------------------------------


def serve_retrieve(retrieve: Callable[..., Dict[str, Any]], body: bytes, *,
                   max_wait_s: float = 600.0) -> Tuple[int, str, bytes]:
    """Handle one wire request against ``retrieve`` (a
    ``ShardService.retrieve`` or ``RetrievalCoordinator.retrieve`` — the
    wire cannot tell tiers apart): decode, call with the propagated budget
    + client + pano scope, encode the answer.  Returns ``(status,
    content_type, payload)`` for the HTTP handler.  ``max_wait_s`` is
    advisory here (the call is synchronous); a budgeted request classifies
    its own :class:`DeadlineExceeded` at the scoring loop's checkpoints."""
    recv_t = obs_events.wall_now()
    try:
        desc, meta = decode_retrieve_request(body)
    except WireError as e:
        # deliberate 400 override, same as the match wire: the frame
        # itself was unserviceable, a caller error
        _, payload = encode_retrieve_error(RequestQuarantined(
            f"unserviceable retrieve request: {e}", kind="wire",
            attempts=1), extra=sync_stamps(recv_t))
        return 400, RETRIEVE_CONTENT_TYPE, payload
    del max_wait_s  # symmetry with serve_match; the call blocks inline
    # additive trace pass-through: only traced requests add the kwarg so a
    # retrieve callable without it keeps working for untraced callers
    tr = {"trace": meta["trace"]} if meta.get("trace") else {}
    try:
        answer = retrieve(
            desc, panos=meta["panos"], topk=meta["topk"],
            budget_s=meta["budget_s"], client=meta["client"],
            request_id=meta["request"], probe=meta["probe"], **tr)
    except (Overloaded, DeadlineExceeded, RequestQuarantined) as e:
        status, payload = encode_retrieve_error(
            e, extra=sync_stamps(recv_t))
        return status, RETRIEVE_CONTENT_TYPE, payload
    except Exception as e:  # noqa: BLE001 — the wire stays outcome-total
        status, payload = encode_retrieve_error(
            e, extra=sync_stamps(recv_t))
        return status, RETRIEVE_CONTENT_TYPE, payload
    status, payload = encode_retrieve_response(
        answer, extra=sync_stamps(recv_t))
    return status, RETRIEVE_CONTENT_TYPE, payload


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------


class RetrieveClient:
    """One persistent HTTP/1.1 connection to a shard's ``/retrieve``.

    NOT thread-safe — the coordinator pools one client per concurrent
    attempt per shard (``ShardBackend.acquire``).  Transport failures
    raise their native exceptions with the connection closed so the next
    call reconnects; classified outcomes raise the ``serving/request.py``
    exception classes via :func:`decode_retrieve_response`.
    """

    def __init__(self, base_url: str, timeout_s: float = 30.0):
        parts = urlsplit(base_url if "//" in base_url
                         else f"http://{base_url}")
        if not parts.hostname or not parts.port:
            raise ValueError(f"shard url needs host:port, got {base_url!r}")
        self.base_url = f"http://{parts.hostname}:{parts.port}"
        self._host = parts.hostname
        self._port = int(parts.port)
        self.timeout_s = float(timeout_s)
        self._conn: Optional[http.client.HTTPConnection] = None
        self._last_sync_t = 0.0  # monotonic; clock_sync emission throttle

    def _connection(self, timeout: float) -> http.client.HTTPConnection:
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self._host, self._port, timeout=timeout)
        elif self._conn.sock is not None:
            self._conn.sock.settimeout(timeout)
        else:
            self._conn.timeout = timeout
        return self._conn

    def retrieve(self, desc: np.ndarray, *,
                 panos: Optional[Sequence[str]] = None,
                 topk: Optional[int] = None,
                 client: str = "wire", budget_s: Optional[float] = None,
                 request_id: str = "", probe: bool = False,
                 timeout_s: Optional[float] = None,
                 trace: Optional[str] = None) -> Dict[str, Any]:
        """One wire round trip.  ``timeout_s`` bounds the WHOLE attempt at
        the socket level — the hung-socket backstop that keeps a wedged
        shard from absorbing the coordinator's dispatch slots."""
        from ncnet_tpu.utils import faults

        # the retrieval chaos seam: injected shard death / stalled-peer
        # hang / straggler slowness without a real process to kill (the
        # chaos suite also SIGKILLs real serve_shard processes)
        faults.shard_fault_hook(self.base_url, "send")
        body = encode_retrieve_request(
            desc, panos=panos, topk=topk, client=client, budget_s=budget_s,
            request_id=request_id, probe=probe, trace=trace)
        conn = self._connection(timeout_s if timeout_s is not None
                                else self.timeout_s)
        t_send = obs_events.wall_now()
        try:
            conn.request("POST", "/retrieve", body=body,
                         headers={"Content-Type": RETRIEVE_CONTENT_TYPE})
            resp = conn.getresponse()
            data = resp.read()
        except (OSError, http.client.HTTPException, socket.timeout):
            self.close()  # the connection state is unknowable: reconnect
            raise
        t_recv = obs_events.wall_now()
        # response-corruption chaos seam: a flipped byte here must fail the
        # checksum in decode_retrieve_response, never reorder a shortlist
        data = faults.shard_payload_hook(self.base_url, data)
        header, payload = _unframe(data)
        if time.monotonic() - self._last_sync_t >= CLOCK_SYNC_INTERVAL_S:
            self._last_sync_t = time.monotonic()
            emit_clock_sync(self.base_url, header, t_send, t_recv)
        return _retrieve_response_from(header, payload)

    def close(self) -> None:
        conn, self._conn = self._conn, None
        if conn is not None:
            try:
                conn.close()
            except Exception:  # noqa: BLE001 — closing a dead socket
                pass

    def __enter__(self) -> "RetrieveClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# re-exported for coordinator symmetry with the match tier
SETTLE_MARGIN_S = WIRE_SETTLE_MARGIN_S
