"""Coarse-volume retrieval scoring: the cheap proxy for full matching.

Per Dual-Resolution Correspondence Networks (PAPERS.md), a low-resolution
correlation is a faithful stand-in for the full 4D match — so retrieval
scores a query's POOLED coarse descriptor against each pano's cached
coarse volume instead of running the O((hw)^2) dense pipeline per
candidate.  The cached unit is 1/factor^4 the size of a dense feature
entry (~117 MB/pano at 3200 px), which is what makes a millions-of-panos
sweep a memory-resident numpy pass per shard.

Conventions (shared by the index builder, the shard scorer, and the InLoc
in-system shortlist — one module so they can never drift):

  * a **coarse volume** is ``(h, w, c) float32``, L2-normalized per
    location (the backbone is NHWC end-to-end; entries store the same
    layout);
  * a **query descriptor** is ``(c,) float32``, unit-norm — the pooled
    coarse query;
  * the **score** is the max cosine similarity over the pano's coarse
    locations: "somewhere in this pano looks like the query", the
    retrieval analog of the match-volume max the fine stage ranks by.

Two extractors feed the same formats: :func:`coarse_volume_from_features`
pools real backbone features by ``factor`` (the PR 15 coarse pass's
resolution), and :func:`raw_coarse_volume` builds a model-free local
color/gradient-statistics grid straight from the uint8 image — the CPU
path the chaos suite and the ``--raw`` index builder run with zero
compiles.  The store fingerprint records which extractor built an index
(``store/feature_store.py::coarse_fingerprint``), so mixing them is a
MISS, never a wrong shortlist.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

__all__ = [
    "coarse_volume_from_features",
    "pooled_descriptor",
    "raw_coarse_volume",
    "score_coarse_volume",
]

_EPS = 1e-8


def _l2_normalize(a: np.ndarray, axis: int) -> np.ndarray:
    n = np.sqrt(np.sum(np.square(a), axis=axis, keepdims=True))
    return a / np.maximum(n, _EPS)


def coarse_volume_from_features(feat: np.ndarray,
                                factor: int) -> np.ndarray:
    """Backbone features ``(h, w, c)`` (or batched ``(1, h, w, c)``) →
    coarse volume: average-pool by ``factor`` per spatial axis (trailing
    remainder rows/cols folded into the last cell, so no location is
    silently dropped), then L2-normalize per coarse location."""
    a = np.asarray(feat, dtype=np.float32)
    if a.ndim == 4:
        if a.shape[0] != 1:
            raise ValueError(f"expected a single feature map, got batch "
                             f"{a.shape[0]}")
        a = a[0]
    if a.ndim != 3:
        raise ValueError(f"features must be (h, w, c), got {a.shape}")
    f = max(1, int(factor))
    h, w, c = a.shape
    ch, cw = max(1, h // f), max(1, w // f)
    out = np.zeros((ch, cw, c), np.float32)
    for i in range(ch):
        i0, i1 = i * f, ((i + 1) * f if i < ch - 1 else h)
        for j in range(cw):
            j0, j1 = j * f, ((j + 1) * f if j < cw - 1 else w)
            out[i, j] = a[i0:i1, j0:j1].mean(axis=(0, 1))
    return _l2_normalize(out, axis=-1)


def raw_coarse_volume(image: np.ndarray, factor: int,
                      grid: int = 16) -> np.ndarray:
    """Model-free coarse volume straight from a uint8 ``(H, W, 3)`` image
    (batched ``(1, H, W, 3)`` accepted): a ``(grid/factor)²`` cell grid of
    local statistics — per-channel mean, per-channel std, and two gradient
    magnitudes — L2-normalized per cell.  Deterministic, numpy-only, no
    jax import: the extractor the chaos suite and ``build_coarse_index
    --raw`` run.  ``grid`` fixes the FINE grid the factor pools from, so
    volumes from differently-sized images stay comparable."""
    a = np.asarray(image)
    if a.ndim == 4:
        if a.shape[0] != 1:
            raise ValueError(f"expected one image, got batch {a.shape[0]}")
        a = a[0]
    if a.ndim != 3 or a.shape[-1] != 3:
        raise ValueError(f"image must be (H, W, 3), got {a.shape}")
    a = a.astype(np.float32) / 255.0
    f = max(1, int(factor))
    cells = max(1, int(grid) // f)
    H, W = a.shape[:2]
    ys = np.linspace(0, H, cells + 1).astype(int)
    xs = np.linspace(0, W, cells + 1).astype(int)
    gy = np.abs(np.diff(a.mean(axis=-1), axis=0))
    gx = np.abs(np.diff(a.mean(axis=-1), axis=1))
    out = np.zeros((cells, cells, 8), np.float32)
    for i in range(cells):
        for j in range(cells):
            tile = a[ys[i]:max(ys[i + 1], ys[i] + 1),
                     xs[j]:max(xs[j + 1], xs[j] + 1)]
            ty = gy[ys[i]:max(ys[i + 1] - 1, ys[i] + 1),
                    xs[j]:max(xs[j + 1], xs[j] + 1)]
            tx = gx[ys[i]:max(ys[i + 1], ys[i] + 1),
                    xs[j]:max(xs[j + 1] - 1, xs[j] + 1)]
            out[i, j, :3] = tile.mean(axis=(0, 1))
            out[i, j, 3:6] = tile.std(axis=(0, 1))
            out[i, j, 6] = ty.mean() if ty.size else 0.0
            out[i, j, 7] = tx.mean() if tx.size else 0.0
    return _l2_normalize(out, axis=-1)


def pooled_descriptor(volume: np.ndarray) -> np.ndarray:
    """Coarse volume ``(h, w, c)`` → unit-norm pooled query descriptor
    ``(c,)`` (mean over locations, then L2) — the few-hundred-float
    payload a query fans out to every shard."""
    v = np.asarray(volume, dtype=np.float32)
    if v.ndim != 3:
        raise ValueError(f"coarse volume must be (h, w, c), got {v.shape}")
    d = v.mean(axis=(0, 1))
    return np.asarray(_l2_normalize(d[None], axis=-1)[0], np.float32)


def score_coarse_volume(desc: np.ndarray, volume: np.ndarray) -> float:
    """Max cosine similarity of the query descriptor over the pano's
    coarse locations.  A channel-count mismatch is a caller bug (index
    built under a different extractor/config than the query descriptor)
    and raises — a silently-wrong ranking is the one failure retrieval
    may never produce."""
    d = np.asarray(desc, dtype=np.float32).ravel()
    v = np.asarray(volume, dtype=np.float32)
    if v.ndim != 3 or v.shape[-1] != d.shape[0]:
        raise ValueError(
            f"descriptor dim {d.shape[0]} does not match coarse volume "
            f"{v.shape} — index and query were built under different "
            "extractors")
    return float(np.max(v.reshape(-1, d.shape[0]) @ d))


def top_k(scores, k: int) -> Tuple[Tuple[str, float], ...]:
    """Deterministic top-``k`` of ``{pano: score}`` / ``[(pano, score)]``:
    descending score, pano id as the tie-break (two hosts ranking the same
    scores must return the same list, or the gather merge would be
    replica-order dependent)."""
    items = scores.items() if hasattr(scores, "items") else scores
    ranked = sorted(((str(p), float(s)) for p, s in items),
                    key=lambda ps: (-ps[1], ps[0]))
    return tuple(ranked[:max(0, int(k))])
