"""One retrieval shard host: rendezvous-assigned coarse volumes + scoring.

A ``ShardService`` is the per-host unit the coordinator fans out to: it
derives its assigned pano set from the SAME rendezvous assignment every
other tier computes (``assignment.py`` — no placement service, no config
drift), reads each pano's coarse volume through the PR 14 feature store's
verified-read / quarantine / recompute ladder, and answers one scoring
sweep per ``/retrieve`` request: requested ∩ assigned panos scored against
the query descriptor, deterministic top-k back.

Honesty contract (what the coordinator's coverage accounting builds on):
the answer lists exactly which panos were CONSULTED and which were
UNAVAILABLE (store miss, quarantined entry with no recompute path) — a
shard never pads, never silently skips.  A corrupt entry therefore costs
this shard one pano (quarantined on read) while the coordinator re-routes
that pano to a replica shard; with a ``compute`` callback the store
recomputes it transparently instead and the shortlist is identical to an
uncorrupted run (tests/test_retrieval.py proves both).

Fronted by :class:`ShardIntrospectionServer`: the standard ``/healthz`` /
``/metrics`` / ``/statusz`` control plane plus ``POST /retrieve`` on the
versioned NCMW wire (``retrieval/wire.py``); ``tools/serve_shard.py`` is
the process wrapper the chaos suite SIGKILLs.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.observability.export import Family, render
from ncnet_tpu.observability.logging import get_logger
from ncnet_tpu.retrieval.assignment import replica_shards
from ncnet_tpu.retrieval.scoring import score_coarse_volume, top_k
from ncnet_tpu.serving.health import (
    ADMITTING,
    DEGRADED,
    DRAINING,
    READY,
    STOPPED,
    HealthMachine,
)
from ncnet_tpu.serving.introspect import IntrospectionServer
from ncnet_tpu.serving.request import DeadlineExceeded, Overloaded
from ncnet_tpu.store.feature_store import STORE_DEGRADED

log = get_logger("retrieval")

# retrieval health-document schema (shard AND coordinator documents): the
# version gate a coordinator applies before trusting a shard's document,
# exactly like ROUTER_DOC_SCHEMA one tier down
RETRIEVAL_DOC_SCHEMA = 1

_EWMA_ALPHA = 0.3

__all__ = [
    "RETRIEVAL_DOC_SCHEMA",
    "ShardIntrospectionServer",
    "ShardService",
    "shard_metrics_families",
]


class ShardService:
    """One shard host's retrieval service (see module docstring).

    ``index`` is a loaded/merged manifest from
    :func:`ncnet_tpu.retrieval.index.load_index_manifests`; ``store`` a
    :class:`~ncnet_tpu.store.FeatureStore` opened under the index's coarse
    fingerprint.  ``compute`` (optional) maps a pano name to a freshly
    computed coarse volume — the transparent-recompute path for corrupted
    entries; without it an unreadable pano is honestly UNAVAILABLE."""

    def __init__(self, shard_id: str, shard_ids: Sequence[str],
                 index: Dict[str, Any], store, *,
                 replication: int = 2, default_topk: int = 10,
                 compute: Optional[Callable[[str], np.ndarray]] = None,
                 introspect_host: str = "127.0.0.1",
                 introspect_port: Optional[int] = None):
        self.shard_id = str(shard_id)
        self.shard_ids = tuple(str(s) for s in shard_ids)
        if self.shard_id not in self.shard_ids:
            raise ValueError(f"shard id {shard_id!r} not in the shard set "
                             f"{self.shard_ids}")
        self.index = index
        self.store = store
        self.replication = max(1, int(replication))
        self.default_topk = max(1, int(default_topk))
        self._compute = compute
        self._introspect_host = introspect_host
        self._introspect_port = introspect_port
        self._introspect: Optional[ShardIntrospectionServer] = None
        # the rendezvous-assigned subset this host serves (order preserved
        # from the index manifest: deterministic sweeps)
        self.assigned: List[str] = [
            name for name in index["panos"]
            if self.shard_id in replica_shards(name, self.shard_ids,
                                               self.replication)]
        self._assigned_set = set(self.assigned)
        self._cache: Dict[str, np.ndarray] = {}
        self._unavailable: set = set()
        self._lock = threading.Lock()
        self._health = HealthMachine(event="retrieve_shard_health")
        self._inflight = 0
        self._activity_t = time.monotonic()
        self._last_result_t: Optional[float] = None
        self._ewma_wall_s: Optional[float] = None
        self._n = {"requests": 0, "results": 0, "deadline": 0, "shed": 0,
                   "errors": 0, "probes": 0}

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ShardService":
        if self._introspect_port is not None:
            self._introspect = ShardIntrospectionServer(
                self, self._introspect_host, self._introspect_port)
            try:
                self._introspect.start()
            except OSError as e:
                self._introspect = None
                self._health.to(STOPPED, f"bind_failed:{e}")
                return self
        self._health.to(READY, "shard_loaded")
        obs_events.emit("retrieve_shard_start", shard=self.shard_id,
                        shards=len(self.shard_ids),
                        replication=self.replication,
                        assigned=len(self.assigned),
                        indexed=len(self.index["panos"]))
        return self

    def request_drain(self, reason: str = "drain") -> None:
        """Coordinated drain: ``/healthz`` answers 503 from here on, so
        the coordinator demotes this host BEFORE it stops answering."""
        with self._lock:
            if self._health.state in ADMITTING:
                self._health.to(DRAINING, reason)

    def stop(self) -> None:
        with self._lock:
            if self._health.state != STOPPED:
                self._health.to(STOPPED, "clean")
        if self._introspect is not None:
            self._introspect.stop()
            self._introspect = None

    @property
    def state(self) -> str:
        return self._health.state

    @property
    def introspect_url(self) -> Optional[str]:
        return self._introspect.url if self._introspect else None

    def __enter__(self) -> "ShardService":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- entries ------------------------------------------------------------

    def _entry(self, name: str) -> Optional[np.ndarray]:
        """One pano's coarse volume through the store ladder: cached in
        memory after the first verified read (coarse volumes are tiny —
        1/factor^4 of dense features — so a whole shard stays resident).
        Returns None when the pano is honestly unavailable."""
        with self._lock:
            hit = self._cache.get(name)
        if hit is not None:
            return hit
        digest = self.index["panos"][name]
        try:
            if self._compute is not None:
                vol, _status = self.store.resolve(
                    digest, lambda name=name: self._compute(name))
            else:
                vol = self.store.get(digest)
        except Exception as e:  # noqa: BLE001 — a store/compute failure
            # costs this shard one pano, never the whole sweep
            log.warning(f"shard {self.shard_id}: pano {name} unreadable "
                        f"({type(e).__name__}: {e})", kind="io")
            vol = None
        with self._lock:
            if vol is None:
                self._unavailable.add(name)
            else:
                self._unavailable.discard(name)
                self._cache[name] = vol
        return vol

    # -- the data plane -----------------------------------------------------

    def retrieve(self, desc: np.ndarray, *,
                 panos: Optional[Sequence[str]] = None,
                 topk: Optional[int] = None,
                 budget_s: Optional[float] = None,
                 client: str = "wire", request_id: str = "",
                 probe: bool = False,
                 trace: Optional[str] = None) -> Dict[str, Any]:
        """One scoring sweep: requested ∩ assigned panos scored, top-k +
        the consulted/unavailable accounting back.  Raises the classified
        ``serving/request.py`` outcomes (Overloaded when not admitting,
        DeadlineExceeded when the budget expires mid-sweep) — the wire
        maps them onto HTTP, a local caller sees them directly."""
        from ncnet_tpu.observability.tracing import normalize_trace

        trace = normalize_trace(trace)
        t0 = time.monotonic()
        with self._lock:
            if self._health.state not in ADMITTING:
                self._n["shed"] += 1
                raise Overloaded(
                    f"shard {self.shard_id} is {self._health.state}",
                    reason="draining")
            self._n["probes" if probe else "requests"] += 1
            self._inflight += 1
        try:
            if probe:
                return {"shard": self.shard_id, "probe": True,
                        "scores": [], "consulted": [], "unavailable": [],
                        "assigned": len(self.assigned)}
            deadline_t = (t0 + float(budget_s)
                          if budget_s is not None else None)
            if panos is None:
                targets = list(self.assigned)
                unknown: List[str] = []
            else:
                targets = [str(p) for p in panos
                           if str(p) in self._assigned_set]
                unknown = [str(p) for p in panos
                           if str(p) not in self._assigned_set]
            scores: Dict[str, float] = {}
            unavailable: List[str] = []
            for name in targets:
                if deadline_t is not None \
                        and time.monotonic() >= deadline_t:
                    with self._lock:
                        self._n["deadline"] += 1
                    raise DeadlineExceeded(
                        f"budget expired after {len(scores)}/"
                        f"{len(targets)} panos", where="shard_score")
                vol = self._entry(name)
                if vol is None:
                    unavailable.append(name)
                    continue
                scores[name] = score_coarse_volume(desc, vol)
            wall = time.monotonic() - t0
            with self._lock:
                self._n["results"] += 1
                self._last_result_t = time.monotonic()
                self._ewma_wall_s = wall if self._ewma_wall_s is None else (
                    _EWMA_ALPHA * wall
                    + (1.0 - _EWMA_ALPHA) * self._ewma_wall_s)
                degraded = (self.store.health().get("state")
                            == STORE_DEGRADED) or bool(self._unavailable)
                if degraded and self._health.state == READY:
                    self._health.to(DEGRADED,
                                    "store_degraded" if not
                                    self._unavailable else
                                    f"unavailable:{len(self._unavailable)}")
                elif not degraded and self._health.state == DEGRADED:
                    self._health.to(READY, "restored")
            obs_events.emit(
                "retrieve_shard_result", shard=self.shard_id,
                request=request_id, client=client,
                consulted=len(scores), unavailable=len(unavailable),
                requested=len(targets), wall_ms=round(wall * 1e3, 3),
                **({"trace": trace} if trace else {}))
            return {
                "shard": self.shard_id,
                "scores": [[p, s] for p, s in
                           top_k(scores, topk or self.default_topk)],
                "consulted": sorted(scores),
                "unavailable": unavailable,
                "unknown": unknown,
                "assigned": len(self.assigned),
                "wall_ms": round(wall * 1e3, 3),
            }
        except (Overloaded, DeadlineExceeded):
            raise
        except Exception:
            with self._lock:
                self._n["errors"] += 1
            raise
        finally:
            with self._lock:
                self._inflight -= 1

    # -- health -------------------------------------------------------------

    def health(self) -> Dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            if self._inflight == 0 and self._last_result_t is None:
                # a deliberately idle shard is alive (the router's idle-
                # beat rule): the activity stamp advances until work lands
                self._activity_t = now
            last = self._last_result_t
            age = now - (last if last is not None else self._activity_t)
            return {
                "schema": RETRIEVAL_DOC_SCHEMA,
                "role": "retrieval_shard",
                "state": self._health.state,
                "service": self._health.probe(),
                "shard": {
                    "id": self.shard_id,
                    "shards": len(self.shard_ids),
                    "replication": self.replication,
                    "assigned": len(self.assigned),
                    "loaded": len(self._cache),
                    "unavailable": sorted(self._unavailable),
                    "ewma_wall_ms": (round(self._ewma_wall_s * 1e3, 3)
                                     if self._ewma_wall_s else None),
                    "inflight": self._inflight,
                },
                "counters": dict(self._n),
                "activity": {"age_s": round(max(0.0, age), 3),
                             "requests": self._n["results"]},
                "store": self.store.health(),
            }


def shard_metrics_families(shard: ShardService) -> List[Family]:
    """The curated ``ncnet_retrieve_shard_*`` family set, one consistent
    health-document cut (the shard-tier twin of ``metrics_families``)."""
    doc = shard.health()
    fams: List[Family] = []
    fams.append(Family("ncnet_retrieve_shard_up", "gauge",
                       "1 while the shard admits "
                       "(STARTING/READY/DEGRADED)")
                .add(1 if doc["state"] in ADMITTING else 0,
                     shard=doc["shard"]["id"]))
    state = Family("ncnet_retrieve_shard_state", "gauge",
                   "shard health state (1 on the active state's series)")
    state.add(1, state=doc["state"], shard=doc["shard"]["id"])
    fams.append(state)
    outcomes = Family("ncnet_retrieve_shard_requests_total", "counter",
                      "terminal outcomes of shard scoring sweeps")
    for outcome, n in sorted(doc["counters"].items()):
        outcomes.add(n, outcome=outcome, shard=doc["shard"]["id"])
    fams.append(outcomes)
    sh = doc["shard"]
    fams.append(Family("ncnet_retrieve_shard_panos", "gauge",
                       "pano accounting on this shard")
                .add(sh["assigned"], status="assigned")
                .add(sh["loaded"], status="loaded")
                .add(len(sh["unavailable"]), status="unavailable"))
    if sh.get("ewma_wall_ms") is not None:
        fams.append(Family("ncnet_retrieve_shard_wall_ewma_ms", "gauge",
                           "scoring-sweep wall EWMA")
                    .add(sh["ewma_wall_ms"], shard=sh["id"]))
    return fams


def _render_shard_statusz(shard: ShardService) -> str:
    doc = shard.health()
    sh, c = doc["shard"], doc["counters"]
    svc = doc["service"]
    lines = [
        "ncnet_tpu retrieval shard — statusz",
        f"shard: {sh['id']}  ({sh['assigned']} assigned of a "
        f"{len(shard.index['panos'])}-pano index, R={sh['replication']} "
        f"over {sh['shards']} shards)",
        f"state: {doc['state']}  (for {svc['age_s']}s"
        + (f", reason: {svc['reason']}" if svc.get("reason") else "") + ")",
        f"requests: results={c['results']}  deadline={c['deadline']}  "
        f"shed={c['shed']}  errors={c['errors']}  probes={c['probes']}",
        f"entries: loaded={sh['loaded']}  "
        f"unavailable={len(sh['unavailable'])}"
        + (f" ({', '.join(sh['unavailable'][:5])}"
           + ("…" if len(sh["unavailable"]) > 5 else "") + ")"
           if sh["unavailable"] else ""),
        f"store: {doc['store'].get('state')}"
        + (f" ({doc['store'].get('reason')})"
           if doc["store"].get("reason") else ""),
    ]
    return "\n".join(lines) + "\n"


class ShardIntrospectionServer(IntrospectionServer):
    """The shard's control + data plane: base lifecycle and handler with
    shard-shaped payloads.  ``retrieve_payload`` is inherited from the
    base server (it dispatches to ``ShardService.retrieve``);
    ``POST /match`` is refused — a retrieval shard serves no match wire."""

    def metrics_text(self) -> str:
        self._scrapes += 1
        fams = shard_metrics_families(self._service)
        fams.append(Family("ncnet_retrieve_shard_scrapes_total", "counter",
                           "scrapes answered by this shard")
                    .add(self._scrapes))
        return render(fams)

    def statusz_text(self) -> str:
        return _render_shard_statusz(self._service)

    def match_payload(self, body: bytes):
        return (404, "text/plain; charset=utf-8",
                b"this host serves /retrieve, not /match\n")
