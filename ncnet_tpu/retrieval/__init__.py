"""Shard-replicated coarse-volume retrieval: the scatter-gather shortlist
tier in front of fine matching.

A query's pooled coarse descriptor fans out to shard hosts; each host
scores its rendezvous-assigned panos' cached coarse volumes (the PR 14
feature store's verified-read / quarantine / recompute ladder, one
``coarse_fingerprint`` generation per extractor+factor) and the
coordinator gathers a global top-k shortlist.  Replication R means a dead
shard loses capacity, not coverage; every answer carries a ``coverage``
fraction with outcome-total semantics — below ``min_coverage`` it is
DEGRADED or shed, never silently truncated.

Modules:

  * ``assignment`` — rendezvous (HRW) pano→shard placement, a pure
    function every tier derives identically;
  * ``scoring``    — coarse-volume formats + max-cosine scoring + the
    model-free ``raw`` extractor (CPU chaos path);
  * ``index``      — durable pano→digest manifests and the single-process
    ``local_shortlist`` (the InLoc in-system path);
  * ``wire``       — ``POST /retrieve`` on the NCMW framing with
    checksum-sealed answers;
  * ``shard``      — one shard host's service + introspection plane;
  * ``coordinator`` — the scatter-gather front: failover, hedging,
    probe/resurrection, coverage accounting.
"""

from ncnet_tpu.retrieval.assignment import (
    assignment_table,
    rendezvous_score,
    replica_shards,
)
from ncnet_tpu.retrieval.coordinator import (
    RetrievalConfig,
    RetrievalCoordinator,
    ShardBackend,
    build_retrieval_document,
    retrieval_metrics_families,
)
from ncnet_tpu.retrieval.index import (
    INDEX_SCHEMA,
    load_index_manifests,
    local_shortlist,
    write_index_manifest,
)
from ncnet_tpu.retrieval.scoring import (
    coarse_volume_from_features,
    pooled_descriptor,
    raw_coarse_volume,
    score_coarse_volume,
    top_k,
)
from ncnet_tpu.retrieval.shard import (
    RETRIEVAL_DOC_SCHEMA,
    ShardIntrospectionServer,
    ShardService,
    shard_metrics_families,
)
from ncnet_tpu.retrieval.wire import (
    RETRIEVE_CONTENT_TYPE,
    RetrieveClient,
    decode_retrieve_request,
    decode_retrieve_response,
    encode_retrieve_request,
    encode_retrieve_response,
    serve_retrieve,
)

__all__ = [
    "INDEX_SCHEMA",
    "RETRIEVAL_DOC_SCHEMA",
    "RETRIEVE_CONTENT_TYPE",
    "RetrievalConfig",
    "RetrievalCoordinator",
    "RetrieveClient",
    "ShardBackend",
    "ShardIntrospectionServer",
    "ShardService",
    "assignment_table",
    "build_retrieval_document",
    "coarse_volume_from_features",
    "decode_retrieve_request",
    "decode_retrieve_response",
    "encode_retrieve_request",
    "encode_retrieve_response",
    "load_index_manifests",
    "local_shortlist",
    "pooled_descriptor",
    "raw_coarse_volume",
    "rendezvous_score",
    "replica_shards",
    "retrieval_metrics_families",
    "score_coarse_volume",
    "serve_retrieve",
    "shard_metrics_families",
    "top_k",
    "write_index_manifest",
]
