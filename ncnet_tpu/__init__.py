"""ncnet_tpu — a TPU-native dense-correspondence framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of NCNet
("Neighbourhood Consensus Networks", Rocco et al., NeurIPS 2018; reference
implementation studied at /root/reference — see SURVEY.md).  Nothing here is a
port: the compute path is functional JAX (einsum correlation, single-op 4D
convolution, pjit/shard_map parallelism) rather than the reference's
PyTorch-0.3 module graph.

Layout:
    ops/       pure-function compute kernels (correlation, conv4d, matching)
    models/    Flax modules (backbones, NCNet assembly)
    parallel/  device-mesh, data-parallel and spatially-sharded execution
    data/      host-side input pipeline (CSV pair datasets, loader)
    training/  weak-supervision loss + train loop
    utils/     checkpointing (orbax + torch import), seeding, profiling, .mat IO
    cli/       entry points mirroring the reference CLIs
"""

__version__ = "0.1.0"

from ncnet_tpu import ops  # noqa: F401
