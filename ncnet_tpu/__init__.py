"""ncnet_tpu — a TPU-native dense-correspondence framework.

A from-scratch JAX/XLA re-design of the capabilities of NCNet
("Neighbourhood Consensus Networks", Rocco et al., NeurIPS 2018; reference
implementation studied at /root/reference — see SURVEY.md).  Nothing here is a
port: the compute path is functional JAX (einsum correlation, whole-volume 4D
convolution with MXU-aware formulations, jit + shard_map parallelism) rather
than the reference's PyTorch-0.3 module graph.

Layout:
    ops/        pure-function compute kernels (correlation, conv4d, matching,
                pooling, image resize/normalization)
    models/     functional backbones + NCNet assembly (params are plain
                pytrees), orbax/torch checkpoint I/O
    parallel/   device mesh, data-parallel helpers, spatially-sharded
                (hB-sharded, halo-exchange) volume forward
    data/       host-side input pipeline (CSV pair datasets, loader,
                synthetic fixtures)
    training/   weak-supervision loss + jitted train loop
    evaluation/ PF-Pascal PCK + InLoc dense-matching (.mat writer)
    localization/ the InLoc downstream stage (the reference's MATLAB L6):
                batched P3P LO-RANSAC PnP, synthetic-view pose verification,
                localization curves
    utils/      seeding, profiling, plot helpers
    cli/        entry points mirroring the reference CLIs
"""

__version__ = "0.2.0"

from ncnet_tpu import ops  # noqa: F401
