"""Version-compatibility shims for the jax API surface.

The repo targets the jax version baked into the container; where a
convenience alias moved between releases (``jax.tree.*`` grew over several
minors), the shim resolves the available spelling once at import time so
call sites stay on one name.
"""

from __future__ import annotations

import jax

if hasattr(jax.tree, "map_with_path"):  # jax >= 0.4.34-ish alias
    tree_map_with_path = jax.tree.map_with_path
else:
    tree_map_with_path = jax.tree_util.tree_map_with_path

__all__ = ["tree_map_with_path"]
