"""Determinism helpers.

The reference seeds torch + numpy globally (/root/reference/train.py:25-29) and
vendors a DataLoader purely to get per-worker numpy seeding
(/root/reference/lib/dataloader.py:39-43).  JAX is explicit-PRNG so model-side
determinism is structural; these helpers cover the host-side (numpy) pipeline
and give each data worker an independent, reproducible stream.
"""

from __future__ import annotations

import numpy as np


def global_seed(seed: int = 1) -> np.random.Generator:
    """Seed host-side numpy (legacy global RNG, used by augmentations) and
    return a fresh Generator for code that takes one explicitly."""
    np.random.seed(seed)
    return np.random.default_rng(seed)


def worker_rng(base_seed: int, worker_id: int) -> np.random.Generator:
    """Independent stream per data-loading worker (reference's reason for
    vendoring its DataLoader — lib/dataloader.py:39-43)."""
    return np.random.default_rng(np.random.SeedSequence([base_seed, worker_id]))
