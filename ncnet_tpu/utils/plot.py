"""Plot helpers: de-normalized image display + marginless figure saving.

Parity with the reference's ``lib/plot.py`` (plot_image :6-19, save_plot
:21-29), channels-last and matplotlib-Agg-safe for headless use.
"""

from __future__ import annotations

import numpy as np

from ncnet_tpu.ops.image import IMAGENET_MEAN, IMAGENET_STD


def denormalize_image(image: np.ndarray) -> np.ndarray:
    """Invert ImageNet normalization → [0,1] float image (H, W, 3)."""
    img = np.asarray(image)
    if img.ndim == 4:
        img = img[0]
    return np.clip(img * IMAGENET_STD + IMAGENET_MEAN, 0.0, 1.0)


def plot_image(image, return_im: bool = False, ax=None):
    """De-normalize and imshow (reference plot_image, lib/plot.py:6-19).

    ``image``: (H, W, 3) or (1, H, W, 3) ImageNet-normalized array.
    ``return_im=True`` returns the displayable array without plotting.
    """
    im = denormalize_image(image)
    if return_im:
        return im
    import matplotlib.pyplot as plt

    ax = ax or plt.gca()
    ax.imshow(im)
    ax.set_axis_off()
    return ax


def save_plot(filename: str, fig=None) -> None:
    """Save the current figure without margins (lib/plot.py:21-29)."""
    import matplotlib.pyplot as plt

    fig = fig or plt.gcf()
    fig.subplots_adjust(left=0, right=1, top=1, bottom=0)
    for ax in fig.axes:
        ax.set_axis_off()
    fig.savefig(filename, bbox_inches="tight", pad_inches=0)
