"""Deterministic fault injection for proving the fault-tolerance layer.

The training stack (ncnet_tpu/training/train.py) claims to survive four
real-world failure modes: undecodable images, non-finite losses, a process
killed mid-checkpoint-save, and SIGTERM preemption.  Claims about crash paths
rot unless they are executed, so the production code carries four tiny hook
call sites and this module arms them deterministically from tests:

  * ``decode_hook(path)``         — data/datasets.load_image: raises
    :class:`InjectedFault` (an OSError) for matching image paths, optionally
    only for the first k attempts per path (exercises decode retry).
  * ``corrupt_batch_hook(b, s)``  — training/train.process_epoch: NaN-fills
    the source images of selected global train steps, so the NaN flows
    through the real jitted loss/grads/update and the guard must keep it out
    of Adam state (injecting at the loss value would bypass the mechanism
    under test).
  * ``kill_mid_save_hook(n)``     — training/train.save_train_checkpoint:
    SIGKILLs the process between the ``params`` and ``opt`` writes of
    checkpoint version ``step_<n>`` — the ``.tmp`` directory exists with
    partial content and the commit rename never runs.
  * ``sigterm_hook(step)``        — the fit train loop: delivers SIGTERM to
    the process after a given global step (exercises the preemption handler
    end-to-end, including the final boundary checkpoint).

Arming: programmatic via :func:`install`/:func:`clear` (or the
:func:`injected` context manager) in-process, or the ``NCNET_TPU_FAULTS``
environment variable (a JSON object of :class:`FaultPlan` fields) for
subprocess tests — the kill-mid-save test SIGKILLs its worker, so the plan
must survive process creation.  Every hook is a no-op returning after one
``is None`` check when nothing is armed; the production hot path pays nothing.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import signal
import threading
from typing import Dict, Optional, Tuple

import numpy as np


class InjectedFault(OSError):
    """An injected I/O failure.  Subclasses OSError so production retry and
    quarantine paths treat it exactly like a real decode error."""


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """What to break, and when.  All fields default to 'never'."""

    # global train steps (1-based, = TrainState.step after the batch) whose
    # input batch is NaN-corrupted before the jitted step runs
    nan_loss_steps: Tuple[int, ...] = ()
    # image paths containing this substring raise InjectedFault on decode
    decode_fail_substring: str = ""
    # -1: every decode attempt fails; k >= 0: only the first k attempts per
    # path fail (a transient error that retry should absorb)
    decode_fail_times: int = -1
    # SIGKILL self mid-save of checkpoint version step_<N> (between the
    # params and opt writes: .tmp exists, commit rename never happens)
    kill_at_version: int = -1
    # SIGTERM self after this global train step (1-based)
    sigterm_at_step: int = -1


_plan: Optional[FaultPlan] = None
_env_read = False
_decode_attempts: Dict[str, int] = {}
_lock = threading.Lock()


def install(plan: FaultPlan) -> None:
    """Arm ``plan`` for this process (replaces any prior plan)."""
    global _plan
    with _lock:
        _plan = plan
        _decode_attempts.clear()


def clear() -> None:
    """Disarm all faults (tests must always pair install with clear)."""
    global _plan, _env_read
    with _lock:
        _plan = None
        _env_read = True  # an explicit clear also wins over the env var
        _decode_attempts.clear()


@contextlib.contextmanager
def injected(plan: FaultPlan):
    """``with injected(FaultPlan(...)):`` — armed inside, disarmed after."""
    install(plan)
    try:
        yield plan
    finally:
        clear()


def _active() -> Optional[FaultPlan]:
    global _plan, _env_read
    if _plan is None and not _env_read:
        with _lock:
            if _plan is None and not _env_read:
                _env_read = True
                env = os.environ.get("NCNET_TPU_FAULTS", "")
                if env:
                    fields = json.loads(env)
                    if "nan_loss_steps" in fields:
                        fields["nan_loss_steps"] = tuple(fields["nan_loss_steps"])
                    _plan = FaultPlan(**fields)
    return _plan


# ---------------------------------------------------------------------------
# hooks (called from production code; no-ops when nothing is armed)
# ---------------------------------------------------------------------------


def decode_hook(path: str) -> None:
    """Raise :class:`InjectedFault` when ``path`` is scheduled to fail."""
    p = _active()
    if p is None or not p.decode_fail_substring:
        return
    if p.decode_fail_substring not in path:
        return
    if p.decode_fail_times >= 0:
        with _lock:
            n = _decode_attempts.get(path, 0)
            _decode_attempts[path] = n + 1
        if n >= p.decode_fail_times:
            return  # transient fault already absorbed by earlier attempts
    raise InjectedFault(f"injected decode failure for {path!r}")


def corrupt_batch_hook(batch: dict, step: int) -> dict:
    """NaN-fill the source images of the host batch feeding global ``step``."""
    p = _active()
    if p is None or step not in p.nan_loss_steps:
        return batch
    out = dict(batch)
    src = np.asarray(out["source_image"], dtype=np.float32)
    out["source_image"] = np.full_like(src, np.nan)
    return out


def kill_mid_save_hook(version: int) -> None:
    """SIGKILL self mid-save of checkpoint version ``version`` (if armed)."""
    p = _active()
    if p is None or p.kill_at_version < 0 or version != p.kill_at_version:
        return
    os.kill(os.getpid(), signal.SIGKILL)


def sigterm_hook(step: int) -> None:
    """Deliver SIGTERM to self after global train step ``step`` (if armed)."""
    p = _active()
    if p is None or p.sigterm_at_step < 0 or step != p.sigterm_at_step:
        return
    os.kill(os.getpid(), signal.SIGTERM)
