"""Deterministic fault injection for proving the fault-tolerance layer.

The training stack (ncnet_tpu/training/train.py) claims to survive four
real-world failure modes: undecodable images, non-finite losses, a process
killed mid-checkpoint-save, and SIGTERM preemption.  Claims about crash paths
rot unless they are executed, so the production code carries tiny hook call
sites and this module arms them deterministically from tests:

  * ``decode_hook(path)``         — data/datasets.load_image: raises
    :class:`InjectedFault` (an OSError) for matching image paths, optionally
    only for the first k attempts per path (exercises decode retry).
  * ``corrupt_batch_hook(b, s)``  — training/train.process_epoch: NaN-fills
    the source images of selected global train steps, so the NaN flows
    through the real jitted loss/grads/update and the guard must keep it out
    of Adam state (injecting at the loss value would bypass the mechanism
    under test).
  * ``kill_mid_save_hook(n)``     — training/train.save_train_checkpoint:
    SIGKILLs the process between the ``params`` and ``opt`` writes of
    checkpoint version ``step_<n>`` — the ``.tmp`` directory exists with
    partial content and the commit rename never runs.
  * ``sigterm_hook(step)``        — the fit train loop: delivers SIGTERM to
    the process after a given global step (exercises the preemption handler
    end-to-end, including the final boundary checkpoint).

The inference/eval fault-tolerance layer (evaluation/resilience.py) adds the
serving-shaped failure modes — a query must be retried/quarantined rather
than abort an hours-long eval run:

  * ``savemat_hook(path)``        — utils/io.atomic_savemat: raises
    :class:`InjectedFault` for matching artifact paths (optionally only the
    first k attempts per path), exercising per-query retry around artifact
    writes.
  * ``savemat_kill_hook(path)``   — utils/io.atomic_savemat: SIGKILLs the
    process between the temp-file write and the commit rename — the
    resume-by-artifact crash window (a ``.tmp`` carcass, no final file).
  * ``device_error_hook(label)``  — models/ncnet.ResilientJit dispatch:
    raises :class:`InjectedDeviceError` on selected dispatch-call ordinals
    (a process-global counter), standing in for a mid-run
    ``XlaRuntimeError``/OOM so the runtime tier-demotion path executes.
  * ``hang_fetch_hook(label)``    — evaluation/pipeline.call_with_watchdog:
    sleeps on selected watchdog-call ordinals, standing in for a hung
    tunnel fetch that the watchdog must convert into a retryable timeout.
  * ``journal_kill_hook(n, w)``   — evaluation/resilience.EvalJournal:
    SIGKILLs mid-append of the Nth journal record, after flushing a TORN
    prefix of the line via ``w()`` — the resumed run must prove
    partial-trailing-line tolerance.

The observability layer (ncnet_tpu/observability/) makes the same crash
claims about its event log, so it gets the same proof obligation:

  * ``event_kill_hook(n, w)``     — observability/events.EventLog: SIGKILLs
    mid-append of the Nth event record (per process), flushing a torn
    prefix first — replay and re-open must tolerate the partial tail.

The resident match service (ncnet_tpu/serving/) rides the existing serving-
shaped hooks — ``device_error_hook`` fires on its batch dispatches (the
engine's ResilientJit carries label ``serve_batch``) and
``hang_fetch_hook`` on its watchdogged batch fetches — and adds:

  * ``serve_drain_kill_hook(n)``  — serving/service.MatchService: SIGKILLs
    the process after the Nth request reaches a terminal outcome DURING a
    drain — the kill-mid-drain crash window.  The replayed event log must
    still account for every admitted request (terminal or provably
    in-flight at death), which ``tools/run_report.py --serving`` checks.
  * ``queue_overflow_burst(...)`` — not a hook but the chaos traffic
    generator: fires N back-to-back submissions at a service and returns
    the admitted futures + classified sheds, the deterministic
    queue-overflow shape the chaos suite and ``tools/serve_probe.py``
    share.
  * ``replica_fault_hook(id, phase)`` — serving/replica.py dispatch/fetch:
    kills (``dead_replica_ids``: InjectedDeviceError until cleared — the
    chip-death shape whose batches must fail over to surviving replicas
    with zero lost requests) or slows (``slow_replica_ids``: a per-fetch
    sleep the health-scored router must de-prioritize) individual pool
    replicas.
  * ``backend_fault_hook(url, phase)`` — serving/wire.py MatchClient: the
    multi-host twin of the replica hook — kills (``dead_backend_urls``:
    ConnectionError until cleared, the backend-process-death shape the
    router must fail over across) or stalls (``hang_backend_urls``: a
    pre-send sleep whose late result must classify DeadlineExceeded, not
    land as a zombie success) individual wire backends.  Real process
    kills and real socket hangs are exercised by tests/test_router.py
    against spawned ``tools/serve_backend.py`` processes; this hook is the
    in-process deterministic seam.

The persistent feature store (ncnet_tpu/store/) claims a strict degradation
ladder — a query NEVER fails and NEVER uses bad data — so its crash /
corruption windows get deterministic seams too:

  * ``store_commit_kill_hook(path)`` — SIGKILLs the process between the
    payload write and the commit rename of the Nth entry commit (a ``.tmp``
    carcass, no visible entry — the rerun rebuilds it).
  * ``store_bitflip_hook(path)``     — called post-commit: flips one payload
    bit of matching committed entries, so the next verified read must fail
    the checksum, quarantine the entry, and recompute.
  * ``store_io_hook(op, path)``      — raises ``OSError(ENOSPC)`` on armed
    store operations (read/write/evict/journal): the store must fail open
    to recompute and mark itself DEGRADED, never fail the query.

Arming: programmatic via :func:`install`/:func:`clear` (or the
:func:`injected` context manager) in-process, or the ``NCNET_TPU_FAULTS``
environment variable (a JSON object of :class:`FaultPlan` fields) for
subprocess tests — the kill-mid-save test SIGKILLs its worker, so the plan
must survive process creation.  Every hook is a no-op returning after one
``is None`` check when nothing is armed; the production hot path pays nothing.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import signal
import threading
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np


class InjectedFault(OSError):
    """An injected I/O failure.  Subclasses OSError so production retry and
    quarantine paths treat it exactly like a real decode error."""


class InjectedDeviceError(RuntimeError):
    """An injected runtime device failure (the test stand-in for a mid-run
    ``XlaRuntimeError`` / ``RESOURCE_EXHAUSTED``).  Listed in
    ``models/ncnet.RUNTIME_DEVICE_ERRORS`` so the production tier-demotion
    path treats it exactly like the real thing."""


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """What to break, and when.  All fields default to 'never'."""

    # global train steps (1-based, = TrainState.step after the batch) whose
    # input batch is NaN-corrupted before the jitted step runs
    nan_loss_steps: Tuple[int, ...] = ()
    # image paths containing this substring raise InjectedFault on decode
    decode_fail_substring: str = ""
    # -1: every decode attempt fails; k >= 0: only the first k attempts per
    # path fail (a transient error that retry should absorb)
    decode_fail_times: int = -1
    # SIGKILL self mid-save of checkpoint version step_<N> (between the
    # params and opt writes: .tmp exists, commit rename never happens)
    kill_at_version: int = -1
    # SIGTERM self after this global train step (1-based)
    sigterm_at_step: int = -1
    # --- eval-path faults (evaluation/resilience.py layer) ---
    # artifact paths containing this substring raise InjectedFault inside
    # atomic_savemat (before any bytes are written)
    savemat_fail_substring: str = ""
    # -1: every matching savemat fails; k >= 0: only the first k attempts
    # per path fail (a transient error that per-query retry should absorb)
    savemat_fail_times: int = -1
    # SIGKILL self inside atomic_savemat for matching paths, between the
    # temp-file write and the commit rename (.tmp carcass, no final file)
    kill_in_savemat_substring: str = ""
    # dispatch-call ordinals (1-based, process-global counter over
    # ResilientJit dispatches) that raise InjectedDeviceError
    device_fail_calls: Tuple[int, ...] = ()
    # watchdog-call ordinals (1-based, process-global counter over
    # call_with_watchdog invocations) whose wrapped call sleeps
    # hang_fetch_seconds — simulating a hung tunnel fetch
    hang_fetch_calls: Tuple[int, ...] = ()
    hang_fetch_seconds: float = 30.0
    # SIGKILL self mid-append of the Nth EvalJournal record (1-based),
    # flushing a torn prefix of the line first
    kill_at_journal_append: int = -1
    # SIGKILL self mid-append of the Nth observability EventLog record
    # (1-based, per EventLog instance), flushing a torn prefix first
    kill_at_event_append: int = -1
    # --- serving faults (ncnet_tpu/serving/ layer) ---
    # SIGKILL self after the Nth terminal request outcome of a service
    # DRAIN (1-based) — the kill-mid-drain window: some admitted requests
    # die without an outcome and the event log must prove exactly which
    kill_at_drain_result: int = -1
    # --- replica-pool faults (ncnet_tpu/serving/replica.py layer) ---
    # these replica ids fail every armed-phase call with
    # InjectedDeviceError — the SIGKILL-style chip death: the replica stays
    # dead until the plan is cleared (a resurrection probe then succeeds)
    dead_replica_ids: Tuple[str, ...] = ()
    # which calls die: "fetch" (default — the mid-batch window: the
    # dispatch already succeeded, the in-flight batch must fail over),
    # "dispatch", or "both"
    dead_replica_phase: str = "fetch"
    # these replica ids sleep slow_replica_seconds inside every fetch — the
    # degraded-chip shape the health-scored router must de-prioritize
    slow_replica_ids: Tuple[str, ...] = ()
    slow_replica_seconds: float = 0.25
    # --- multi-host router faults (ncnet_tpu/serving/wire.py layer) ---
    # backend base-url substrings whose wire sends raise ConnectionError —
    # the cross-process chip-death shape WITHOUT a real process to kill
    # (the chaos suite also SIGKILLs real serve_backend processes; this
    # hook covers the in-process router tests): the backend stays dead
    # until the plan is cleared, then a /healthz probe resurrects it
    dead_backend_urls: Tuple[str, ...] = ()
    # backend base-url substrings whose wire sends sleep
    # hang_backend_seconds BEFORE the request leaves — the slow-network /
    # stalled-peer shape: a response landing after the edge budget must
    # classify DeadlineExceeded, never a zombie success
    hang_backend_urls: Tuple[str, ...] = ()
    hang_backend_seconds: float = 0.5
    # --- retrieval-tier faults (ncnet_tpu/retrieval/ layer) ---
    # shard base-url substrings whose retrieval wire sends raise
    # ConnectionError — the shard-death shape without a process to kill:
    # the coordinator must fail the pano group over to replica shards and
    # keep coverage, then resurrect the shard via probe once cleared
    dead_shard_urls: Tuple[str, ...] = ()
    # shard base-url substrings whose retrieval wire sends sleep
    # hang_shard_seconds then DIE — the stalled-then-lost peer: hedged
    # re-dispatch must already have covered its panos elsewhere
    hang_shard_urls: Tuple[str, ...] = ()
    hang_shard_seconds: float = 0.5
    # shard base-url substrings whose retrieval wire sends sleep
    # slow_shard_seconds then PROCEED — the pure-straggler shape the
    # coordinator's hedging exists for: the hedge must beat the straggler
    # without ever marking the slow shard dead
    slow_shard_urls: Tuple[str, ...] = ()
    slow_shard_seconds: float = 0.25
    # shard base-url substrings whose retrieval wire RESPONSES get one bit
    # flipped before decode — in-flight corruption: the response checksum
    # must refuse the payload (classified transport error, pano group
    # retried on replicas), never a silently-wrong shortlist
    shard_bitflip_urls: Tuple[str, ...] = ()
    # --- feature-store faults (ncnet_tpu/store/ layer) ---
    # entry paths containing any of these substrings get ONE payload bit
    # flipped immediately AFTER their commit rename — the media-corruption
    # shape the per-entry checksum exists for: the next verified read must
    # detect it, quarantine the entry, and transparently recompute
    store_bitflip_paths: Tuple[str, ...] = ()
    # store operations ("read", "write", "evict", "journal") that raise
    # OSError(ENOSPC) at their hook site — the disk-full / IO-error shape:
    # the store must fail OPEN (query answered via recompute) and mark
    # itself DEGRADED in health/telemetry, never fail the query
    store_io_error_ops: Tuple[str, ...] = ()
    # SIGKILL self during the Nth store entry commit (1-based, process-
    # global counter), between the payload write and the rename — the
    # two-phase-commit crash window: a rerun must see NO visible entry
    # (only a .tmp carcass) and rebuild it
    kill_at_store_commit: int = -1
    # --- live-rollout faults (ncnet_tpu/serving/rollout.py layer) ---
    # SIGKILL self during the Nth rollout weight swap (1-based, process-
    # global counter over rollout_swap calls), AFTER the new params are
    # staged on the drained replica but BEFORE its warmup/readmission —
    # the mid-swap crash window: the serving-version pointer has not
    # advanced, so a restart must come back on ONE consistent (old) version
    kill_at_weight_swap: int = -1
    # candidate checkpoint paths containing this substring get one param
    # leaf bit-flipped AFTER a successful load — the silently-corrupt-
    # candidate shape the commit-metadata payload sha256 exists for: the
    # rollout's staging verification must refuse the candidate before any
    # replica is touched
    corrupt_candidate_checkpoint: str = ""
    # additive shift applied to every quality signal of batches served by
    # replicas whose model_version contains canary_shift_version — the
    # injected canary regression: the PSI drift gate must breach and the
    # rollout must auto-rollback.  0.0 = disarmed.
    canary_quality_shift: float = 0.0
    canary_shift_version: str = ""


_plan: Optional[FaultPlan] = None
_env_read = False
_decode_attempts: Dict[str, int] = {}
_savemat_attempts: Dict[str, int] = {}
_device_calls = 0
_watchdog_calls = 0
_store_commits = 0
_weight_swaps = 0
_lock = threading.Lock()


def _reset_counters_locked() -> None:
    global _device_calls, _watchdog_calls, _store_commits, _weight_swaps
    _decode_attempts.clear()
    _savemat_attempts.clear()
    _device_calls = 0
    _watchdog_calls = 0
    _store_commits = 0
    _weight_swaps = 0


def install(plan: FaultPlan) -> None:
    """Arm ``plan`` for this process (replaces any prior plan)."""
    global _plan
    with _lock:
        _plan = plan
        _reset_counters_locked()


def clear() -> None:
    """Disarm all faults (tests must always pair install with clear)."""
    global _plan, _env_read
    with _lock:
        _plan = None
        _env_read = True  # an explicit clear also wins over the env var
        _reset_counters_locked()


@contextlib.contextmanager
def injected(plan: FaultPlan):
    """``with injected(FaultPlan(...)):`` — armed inside, disarmed after."""
    install(plan)
    try:
        yield plan
    finally:
        clear()


def _active() -> Optional[FaultPlan]:
    global _plan, _env_read
    if _plan is None and not _env_read:
        with _lock:
            if _plan is None and not _env_read:
                _env_read = True
                env = os.environ.get("NCNET_TPU_FAULTS", "")
                if env:
                    fields = {
                        k: tuple(v) if isinstance(v, list) else v
                        for k, v in json.loads(env).items()
                    }
                    _plan = FaultPlan(**fields)
    return _plan


# ---------------------------------------------------------------------------
# hooks (called from production code; no-ops when nothing is armed)
# ---------------------------------------------------------------------------


def decode_hook(path: str) -> None:
    """Raise :class:`InjectedFault` when ``path`` is scheduled to fail."""
    p = _active()
    if p is None or not p.decode_fail_substring:
        return
    if p.decode_fail_substring not in path:
        return
    if p.decode_fail_times >= 0:
        with _lock:
            n = _decode_attempts.get(path, 0)
            _decode_attempts[path] = n + 1
        if n >= p.decode_fail_times:
            return  # transient fault already absorbed by earlier attempts
    raise InjectedFault(f"injected decode failure for {path!r}")


def corrupt_batch_hook(batch: dict, step: int) -> dict:
    """NaN-fill the source images of the host batch feeding global ``step``."""
    p = _active()
    if p is None or step not in p.nan_loss_steps:
        return batch
    out = dict(batch)
    src = np.asarray(out["source_image"], dtype=np.float32)
    out["source_image"] = np.full_like(src, np.nan)
    return out


def kill_mid_save_hook(version: int) -> None:
    """SIGKILL self mid-save of checkpoint version ``version`` (if armed)."""
    p = _active()
    if p is None or p.kill_at_version < 0 or version != p.kill_at_version:
        return
    os.kill(os.getpid(), signal.SIGKILL)


def sigterm_hook(step: int) -> None:
    """Deliver SIGTERM to self after global train step ``step`` (if armed)."""
    p = _active()
    if p is None or p.sigterm_at_step < 0 or step != p.sigterm_at_step:
        return
    os.kill(os.getpid(), signal.SIGTERM)


# ---------------------------------------------------------------------------
# eval-path hooks
# ---------------------------------------------------------------------------


def savemat_hook(path: str) -> None:
    """Raise :class:`InjectedFault` when ``path``'s savemat is scheduled to
    fail (before any bytes reach disk, so no carcass is left)."""
    p = _active()
    if p is None or not p.savemat_fail_substring:
        return
    if p.savemat_fail_substring not in path:
        return
    if p.savemat_fail_times >= 0:
        with _lock:
            n = _savemat_attempts.get(path, 0)
            _savemat_attempts[path] = n + 1
        if n >= p.savemat_fail_times:
            return  # transient fault already absorbed by earlier attempts
    raise InjectedFault(f"injected savemat failure for {path!r}")


def savemat_kill_hook(path: str) -> None:
    """SIGKILL self between the temp write and the commit rename of a
    matching atomic_savemat (if armed)."""
    p = _active()
    if p is None or not p.kill_in_savemat_substring:
        return
    if p.kill_in_savemat_substring in path:
        os.kill(os.getpid(), signal.SIGKILL)


def device_error_hook(label: str = "") -> None:
    """Raise :class:`InjectedDeviceError` on armed dispatch-call ordinals."""
    p = _active()
    if p is None or not p.device_fail_calls:
        return
    global _device_calls
    with _lock:
        _device_calls += 1
        n = _device_calls
    if n in p.device_fail_calls:
        raise InjectedDeviceError(
            f"injected runtime device failure (dispatch call {n}"
            + (f", {label}" if label else "") + ")"
        )


def hang_fetch_hook(label: str = "") -> None:
    """Sleep ``hang_fetch_seconds`` on armed watchdog-call ordinals — the
    wrapped fetch then overruns its watchdog timeout, which must surface the
    hang as a retryable FetchTimeoutError."""
    p = _active()
    if p is None or not p.hang_fetch_calls:
        return
    global _watchdog_calls
    with _lock:
        _watchdog_calls += 1
        n = _watchdog_calls
    if n in p.hang_fetch_calls:
        time.sleep(p.hang_fetch_seconds)


def journal_kill_hook(n_append: int, write_partial: Callable[[], None]) -> None:
    """SIGKILL self mid-append of journal record ``n_append`` (if armed),
    flushing a torn prefix of the record via ``write_partial`` first so the
    resumed run must tolerate a partial trailing line."""
    p = _active()
    if p is None or p.kill_at_journal_append < 0 \
            or n_append != p.kill_at_journal_append:
        return
    write_partial()
    os.kill(os.getpid(), signal.SIGKILL)


def serve_drain_kill_hook(n_resolved: int) -> None:
    """SIGKILL self after the ``n_resolved``-th terminal request outcome of
    a serving drain (if armed) — the kill-mid-drain crash window.  The
    event log's fsynced appends mean every outcome emitted before the kill
    survives; run_report --serving must account the rest as lost-in-drain,
    not silently."""
    p = _active()
    if p is None or p.kill_at_drain_result < 0 \
            or n_resolved != p.kill_at_drain_result:
        return
    os.kill(os.getpid(), signal.SIGKILL)


def replica_fault_hook(replica_id: str, phase: str) -> None:
    """The replica-pool chaos seam (serving/replica.py dispatch/fetch).

    ``slow_replica_ids`` sleep on fetch — the slow-chip injection whose
    inflated batch walls the health-scored router must measurably
    de-prioritize.  ``dead_replica_ids`` raise :class:`InjectedDeviceError`
    on the armed phase(s) — a replica-local death: with survivors in the
    pool the service must re-route the batch off-budget and quarantine the
    REPLICA, never the request."""
    p = _active()
    if p is None:
        return
    if phase == "fetch" and replica_id in p.slow_replica_ids:
        time.sleep(p.slow_replica_seconds)
    if replica_id in p.dead_replica_ids and \
            p.dead_replica_phase in (phase, "both"):
        raise InjectedDeviceError(
            f"injected replica death ({replica_id}, {phase})"
        )


def backend_fault_hook(base_url: str, phase: str) -> None:
    """The multi-host chaos seam (serving/wire.py MatchClient.match).

    ``hang_backend_urls`` sleep before the request leaves — the stalled-
    peer shape whose late result the router's post-flight deadline check
    must classify.  ``dead_backend_urls`` raise ``ConnectionError`` — a
    backend-process death without a process: the router must re-route
    off-budget, quarantine the BACKEND after its failure streak, and
    resurrect it via a /healthz probe once the plan clears."""
    p = _active()
    if p is None:
        return
    if any(s and s in base_url for s in p.hang_backend_urls):
        time.sleep(p.hang_backend_seconds)
    if any(s and s in base_url for s in p.dead_backend_urls):
        raise ConnectionError(
            f"injected backend death ({base_url}, {phase})")


def shard_fault_hook(base_url: str, phase: str) -> None:
    """The retrieval-tier chaos seam (retrieval/wire.py
    RetrieveClient.retrieve).

    ``slow_shard_urls`` sleep then proceed — the pure straggler the
    coordinator must HEDGE around (the shard stays healthy and its late
    answer still counts).  ``hang_shard_urls`` sleep then die — the
    stalled-then-lost peer.  ``dead_shard_urls`` raise ``ConnectionError``
    — shard death without a process: the coordinator re-routes the pano
    group to replicas and a probe resurrects the shard once the plan
    clears."""
    p = _active()
    if p is None:
        return
    if any(s and s in base_url for s in p.slow_shard_urls):
        time.sleep(p.slow_shard_seconds)
    if any(s and s in base_url for s in p.hang_shard_urls):
        time.sleep(p.hang_shard_seconds)
        raise ConnectionError(
            f"injected shard hang-death ({base_url}, {phase})")
    if any(s and s in base_url for s in p.dead_shard_urls):
        raise ConnectionError(
            f"injected shard death ({base_url}, {phase})")


def shard_payload_hook(base_url: str, data: bytes) -> bytes:
    """Flip one bit of a retrieval wire RESPONSE for matching shard urls
    (the in-flight corruption shape): the client-side response checksum
    must refuse the payload and the coordinator must re-cover the pano
    group from replicas — a silently-wrong shortlist is the one failure
    this tier may never produce.  Returns ``data`` unchanged when the
    fault is not armed."""
    p = _active()
    if p is None or not p.shard_bitflip_urls:
        return data
    if not any(s and s in base_url for s in p.shard_bitflip_urls) \
            or not data:
        return data
    flipped = bytearray(data)
    flipped[-1] ^= 0x01
    return bytes(flipped)


def queue_overflow_burst(submit: Callable[[], object], n: int):
    """Drive ``n`` back-to-back submissions (the queue-overflow chaos
    traffic shape): returns ``(futures, sheds)`` where ``futures`` are the
    admitted :class:`~ncnet_tpu.serving.request.MatchFuture`s and ``sheds``
    the classified :class:`~ncnet_tpu.serving.request.Overloaded`
    rejections, in submission order.  Any other exception propagates — a
    burst that crashes the service is a finding, not a shed."""
    return paced_burst(submit, rate_qps=0.0, n=n)


def paced_burst(submit: Callable[[], object], rate_qps: float, n: int):
    """Open-loop paced traffic: one submission every ``1/rate_qps``
    seconds regardless of completions (``rate_qps <= 0`` = back to back).
    Returns ``(futures, sheds)`` like :func:`queue_overflow_burst`.

    The pacing is load-bearing for the bench's ``serve_shed_pct`` gate
    direction: at a PINNED overload factor the steady state admits
    ~capacity and sheds the rest, so the shed fraction reads as the
    overload fraction and gates lower-is-better soundly — a back-to-back
    burst instead sheds MORE the faster the service is (queue/offered),
    which would invert the gate.  One implementation here so bench.py and
    tools/serve_probe.py can never drift apart on that subtlety."""
    from ncnet_tpu.serving.request import Overloaded

    futures, sheds = [], []
    t0 = time.perf_counter()
    for i in range(int(n)):
        if rate_qps > 0:
            dt = t0 + i / rate_qps - time.perf_counter()
            if dt > 0:
                time.sleep(dt)
        try:
            futures.append(submit())
        except Overloaded as e:
            sheds.append(e)
    return futures, sheds


def event_kill_hook(n_append: int, write_partial: Callable[[], None]) -> None:
    """SIGKILL self mid-append of observability event record ``n_append``
    (if armed), flushing a torn prefix via ``write_partial`` first so the
    replayed log must tolerate a partial trailing line."""
    p = _active()
    if p is None or p.kill_at_event_append < 0 \
            or n_append != p.kill_at_event_append:
        return
    write_partial()
    os.kill(os.getpid(), signal.SIGKILL)


# ---------------------------------------------------------------------------
# feature-store hooks (ncnet_tpu/store/ layer)
# ---------------------------------------------------------------------------


def store_io_hook(op: str, path: str = "") -> None:
    """Raise ``OSError(ENOSPC)`` when store operation ``op`` ("read" /
    "write" / "evict" / "journal") is armed — the disk-full shape the
    store's fail-open degradation ladder must absorb: the query is still
    answered (via recompute), the store goes DEGRADED, nothing crashes."""
    p = _active()
    if p is None or not p.store_io_error_ops:
        return
    if op in p.store_io_error_ops:
        import errno

        raise OSError(errno.ENOSPC,
                      f"injected store {op} failure (no space left)", path)


def store_commit_kill_hook(path: str) -> None:
    """SIGKILL self between the payload write and the commit rename of the
    Nth store entry commit (1-based, if armed) — the two-phase-commit crash
    window: the rerun must see only a ``.tmp`` carcass, never a torn
    visible entry."""
    p = _active()
    if p is None or p.kill_at_store_commit < 0:
        return
    global _store_commits
    with _lock:
        _store_commits += 1
        n = _store_commits
    if n == p.kill_at_store_commit:
        os.kill(os.getpid(), signal.SIGKILL)


def store_bitflip_hook(path: str) -> None:
    """Flip one bit of a committed store entry's PAYLOAD (the file's last
    byte — the header line is at the front) for matching paths — the
    silent-media-corruption shape: a later verified read must fail the
    checksum, quarantine the entry, and recompute, never return the
    poisoned bytes."""
    p = _active()
    if p is None or not p.store_bitflip_paths:
        return
    if not any(s and s in path for s in p.store_bitflip_paths):
        return
    with open(path, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        byte = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([byte[0] ^ 0x01]))


# ---------------------------------------------------------------------------
# live-rollout hooks (ncnet_tpu/serving/rollout.py layer)
# ---------------------------------------------------------------------------


def weight_swap_kill_hook() -> None:
    """SIGKILL self during the Nth rollout weight swap (1-based, if armed)
    — fired after the candidate params are staged on the drained replica
    but before warmup/readmission.  The crash window the two-phase serving-
    version pointer exists for: the pointer only advances at COMPLETE, so
    the restarted process must come back serving ONE consistent (old)
    version."""
    p = _active()
    if p is None or p.kill_at_weight_swap < 0:
        return
    global _weight_swaps
    with _lock:
        _weight_swaps += 1
        n = _weight_swaps
    if n == p.kill_at_weight_swap:
        os.kill(os.getpid(), signal.SIGKILL)


def corrupt_candidate_hook(path: str, params):
    """Flip one bit of one param leaf of a just-loaded rollout candidate
    for matching checkpoint paths — the bit-rotted-checkpoint shape that
    deserialization alone does NOT catch: the commit-metadata payload
    sha256 verification must refuse the candidate before any replica is
    touched.  Returns ``params`` unchanged when not armed."""
    p = _active()
    if p is None or not p.corrupt_candidate_checkpoint:
        return params
    if p.corrupt_candidate_checkpoint not in path:
        return params

    flipped = [False]

    def flip(leaf):
        arr = np.array(leaf, copy=True)
        if not flipped[0] and arr.size:
            raw = arr.view(np.uint8).reshape(-1)
            raw[0] ^= 0x01
            flipped[0] = True
        return arr

    try:
        import jax

        return jax.tree.map(flip, params)
    except ImportError:  # fake-engine chaos paths carry no real pytree
        return params


def canary_quality_shift_hook(model_version: str, quality):
    """Additively shift every quality signal of a batch served by a
    matching ``model_version`` — the injected canary regression (a new
    checkpoint whose match quality silently degraded): the rollout's PSI
    drift gate must breach and auto-rollback.  ``quality`` is the per-pair
    signal-dict list from ``BatchMatchEngine.split`` (or None for narrow
    grids); returned unchanged when not armed or not matching."""
    p = _active()
    if p is None or not p.canary_quality_shift or not quality:
        return quality
    if not p.canary_shift_version \
            or p.canary_shift_version not in (model_version or ""):
        return quality
    return [
        {k: min(1.0, max(0.0, float(v) + p.canary_quality_shift))
         for k, v in row.items()} if row else row
        for row in quality
    ]
