"""Filesystem helpers for resume-by-artifact outputs.

The eval/localization stages treat an artifact's *existence* as proof its
work unit completed (the reference's ``exist(...)~=2`` guards, SURVEY §5.3).
That contract only holds if artifacts appear atomically — a process killed
mid-``savemat`` must not leave a truncated file that a rerun then skips.

``atomic_write_json`` is the manifest twin: the per-experiment run manifests
(evaluation/resilience.py) journal completed / quarantined / in-flight work
units through the same temp-file + ``os.replace`` commit, so a manifest read
never sees a half-written document.

Atomicity vs durability — the contract, and who opts into what:

  * ATOMICITY (every writer here): a reader never observes a partial file.
    Temp file + same-directory ``os.replace``; a crash leaves a ``.tmp``
    carcass at worst, never a torn visible artifact.
  * DURABILITY (``durable=True``): the committed bytes additionally survive
    a POWER LOSS / kernel crash — the temp file is fsynced before the
    rename and the parent directory is fsynced after it, so both the data
    and the directory entry are on stable storage when the call returns.

  Callers that opt into durability: the feature store's entry commits and
  its eviction journal (``ncnet_tpu/store/feature_store.py``) — a store
  whose LRU journal says an entry exists while the entry's bytes evaporated
  with the page cache would serve a miss it believes is corruption.  The
  eval manifests and per-query ``.mat`` artifacts deliberately do NOT: a
  lost-but-consistent manifest or artifact only costs redone work, which
  the per-artifact resume already tolerates, and an fsync per query would
  serialize the eval loop behind the disk.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Optional


def fsync_dir(path: str) -> None:
    """Best-effort fsync of directory ``path`` (makes a just-renamed entry
    durable).  Platforms/filesystems that refuse ``open(dir)`` or the fsync
    degrade silently — the rename is still atomic, only the power-loss
    guarantee narrows to what the OS gives by default."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_savemat(path: str, mdict: dict, **kwargs) -> None:
    """``scipy.io.savemat`` to ``path`` via a same-directory temp file +
    ``os.replace``, so the file exists only once fully written."""
    from scipy.io import savemat

    from ncnet_tpu.utils import faults

    faults.savemat_hook(path)  # no-op unless a test armed an injected fault
    tmp = path + ".tmp"
    try:
        savemat(tmp, mdict, **kwargs)
        # injected SIGKILL lands HERE — the resume-by-artifact crash window
        # (.tmp carcass written, commit rename never runs)
        faults.savemat_kill_hook(path)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path: str, obj, durable: bool = False) -> None:
    """``json.dump`` to ``path`` via a same-directory temp file +
    ``os.replace`` — atomic always; ``durable=True`` additionally fsyncs
    the temp file before and the parent directory after the rename (see
    the module docstring for who opts in and why)."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=1, sort_keys=True)
            if durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if durable:
            fsync_dir(os.path.dirname(os.path.abspath(path)))
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def atomic_write_bytes(path: str, data, *, durable: bool = False,
                       commit_hook: Optional[Callable[[str], None]] = None
                       ) -> None:
    """Write ``data`` (bytes, or a sequence of byte chunks written back to
    back — large payloads avoid one concatenation copy) to ``path`` via
    the two-phase commit: temp file (pid-suffixed — concurrent writers of
    one entry must not clobber each other's temp), optional fsync,
    ``os.replace``, optional parent-dir fsync.  ``commit_hook(path)`` runs
    between the (synced) payload write and the rename — the crash-window
    test seam (the feature store passes ``faults.store_commit_kill_hook``,
    mirroring ``atomic_savemat``'s inline kill hook): a process killed
    there leaves a temp carcass and NO visible entry."""
    tmp = f"{path}.tmp.{os.getpid()}"
    parts = (data,) if isinstance(data, (bytes, bytearray)) else data
    try:
        with open(tmp, "wb") as f:
            for chunk in parts:
                f.write(chunk)
            if durable:
                f.flush()
                os.fsync(f.fileno())
        if commit_hook is not None:
            commit_hook(path)
        os.replace(tmp, path)
        if durable:
            fsync_dir(os.path.dirname(os.path.abspath(path)))
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
