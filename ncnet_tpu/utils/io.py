"""Filesystem helpers for resume-by-artifact outputs.

The eval/localization stages treat an artifact's *existence* as proof its
work unit completed (the reference's ``exist(...)~=2`` guards, SURVEY §5.3).
That contract only holds if artifacts appear atomically — a process killed
mid-``savemat`` must not leave a truncated file that a rerun then skips.
"""

from __future__ import annotations

import os


def atomic_savemat(path: str, mdict: dict, **kwargs) -> None:
    """``scipy.io.savemat`` to ``path`` via a same-directory temp file +
    ``os.replace``, so the file exists only once fully written."""
    from scipy.io import savemat

    tmp = path + ".tmp"
    try:
        savemat(tmp, mdict, **kwargs)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
