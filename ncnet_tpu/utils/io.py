"""Filesystem helpers for resume-by-artifact outputs.

The eval/localization stages treat an artifact's *existence* as proof its
work unit completed (the reference's ``exist(...)~=2`` guards, SURVEY §5.3).
That contract only holds if artifacts appear atomically — a process killed
mid-``savemat`` must not leave a truncated file that a rerun then skips.

``atomic_write_json`` is the manifest twin: the per-experiment run manifests
(evaluation/resilience.py) journal completed / quarantined / in-flight work
units through the same temp-file + ``os.replace`` commit, so a manifest read
never sees a half-written document.
"""

from __future__ import annotations

import json
import os


def atomic_savemat(path: str, mdict: dict, **kwargs) -> None:
    """``scipy.io.savemat`` to ``path`` via a same-directory temp file +
    ``os.replace``, so the file exists only once fully written."""
    from scipy.io import savemat

    from ncnet_tpu.utils import faults

    faults.savemat_hook(path)  # no-op unless a test armed an injected fault
    tmp = path + ".tmp"
    try:
        savemat(tmp, mdict, **kwargs)
        # injected SIGKILL lands HERE — the resume-by-artifact crash window
        # (.tmp carcass written, commit rename never runs)
        faults.savemat_kill_hook(path)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path: str, obj) -> None:
    """``json.dump`` to ``path`` via a same-directory temp file +
    ``os.replace`` — atomicity (a reader never sees a partial document), not
    durability (no fsync: a lost-but-consistent manifest only costs redone
    work, which the per-artifact resume already tolerates)."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
