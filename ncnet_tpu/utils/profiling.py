"""Profiling hooks: trace annotations + on-demand profiler capture.

The reference has no tracing at all (SURVEY §5.1 — print() only); this is new
TPU-native surface.  Two layers:

  * :func:`annotate` — a ``jax.profiler.TraceAnnotation`` context manager
    used around the train/eval steps and the eval forward, so xprof/
    TensorBoard traces show framework-level phases, not just XLA ops.
  * :func:`maybe_trace` — capture a profiler trace for a whole block when a
    directory is given (or the ``NCNET_TPU_PROFILE_DIR`` env var is set);
    no-ops otherwise, so production paths carry zero overhead.

View captures with TensorBoard's profile plugin or xprof
(``tensorboard --logdir <dir>``).
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional

import jax

PROFILE_DIR_ENV = "NCNET_TPU_PROFILE_DIR"


def annotate(name: str):
    """Named region in the device trace (cheap; always on)."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def maybe_trace(
    log_dir: Optional[str] = None, enabled: bool = True
) -> Iterator[bool]:
    """Capture a jax profiler trace into ``log_dir`` (or $NCNET_TPU_PROFILE_DIR)
    for the duration of the block; yields whether tracing is active.
    ``enabled=False`` forces a no-op regardless of the env var (callers use it
    to bound the capture to one representative phase)."""
    log_dir = log_dir or os.environ.get(PROFILE_DIR_ENV) or None
    if not log_dir or not enabled:
        yield False
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield True
    finally:
        jax.profiler.stop_trace()
