"""Profiling hooks: trace annotations + on-demand profiler capture.

The reference has no tracing at all (SURVEY §5.1 — print() only); this is new
TPU-native surface.  Three layers:

  * :func:`annotate` — a ``jax.profiler.TraceAnnotation`` context manager
    used around the train/eval steps, the eval forward, checkpoint commits
    and device snapshots — the annotation names MATCH the observability
    event types (``train_step``, ``pf_pascal_eval_step``,
    ``checkpoint_commit``, ``device_snapshot``), so an xprof trace and a
    replayed event log describe the same phases by the same names.
  * :func:`maybe_trace` — capture a profiler trace for a whole block when a
    directory is given (or the ``NCNET_TPU_PROFILE_DIR`` env var is set);
    no-ops otherwise, so production paths carry zero overhead.
  * :class:`StepWindowTracer` — ``NCNET_TPU_PROFILE_STEPS=<a>:<b>`` bounds
    the capture to exactly global train steps ``[a, b)`` instead of a whole
    epoch: the training loop feeds it every step number and the trace
    starts/stops at the window edges.  When the window knob is set,
    ``fit`` hands the capture to the tracer and ``maybe_trace`` stands
    down (a block capture AND a window capture would fight over the one
    global profiler session).

View captures with TensorBoard's profile plugin or xprof
(``tensorboard --logdir <dir>``).
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional, Tuple

import jax

PROFILE_DIR_ENV = "NCNET_TPU_PROFILE_DIR"
PROFILE_STEPS_ENV = "NCNET_TPU_PROFILE_STEPS"


def annotate(name: str):
    """Named region in the device trace (cheap; always on)."""
    return jax.profiler.TraceAnnotation(name)


def profile_step_window() -> Optional[Tuple[int, int]]:
    """Parse ``NCNET_TPU_PROFILE_STEPS=<a>:<b>`` into ``(a, b)`` — capture
    exactly global train steps ``[a, b)``.  Unset/empty → None; a malformed
    value raises (a silently ignored profiling request wastes the run it
    was meant to measure)."""
    raw = os.environ.get(PROFILE_STEPS_ENV, "").strip()
    if not raw:
        return None
    try:
        a_s, b_s = raw.split(":")
        a, b = int(a_s), int(b_s)
    except ValueError:
        raise ValueError(
            f"{PROFILE_STEPS_ENV}={raw!r}: expected '<a>:<b>' "
            "(capture steps [a, b), 1-based)"
        ) from None
    if a < 1 or b <= a:
        raise ValueError(
            f"{PROFILE_STEPS_ENV}={raw!r}: need 1 <= a < b"
        )
    return a, b


class StepWindowTracer:
    """Start/stop the jax profiler around global train steps ``[a, b)``.

    Inactive (every call a cheap no-op) unless BOTH a log dir (argument or
    ``$NCNET_TPU_PROFILE_DIR``) and a window (argument or
    ``$NCNET_TPU_PROFILE_STEPS``) are present.  ``at_step(g)`` is called
    with each global step number just before that step dispatches;
    ``close()`` (always call it — the window may outlive the run) stops a
    capture left open by an early exit."""

    def __init__(self, log_dir: Optional[str] = None,
                 window: Optional[Tuple[int, int]] = None):
        self.log_dir = log_dir or os.environ.get(PROFILE_DIR_ENV) or None
        self.window = window if window is not None else profile_step_window()
        self._active = False
        self._done = False

    @property
    def enabled(self) -> bool:
        return bool(self.log_dir and self.window and not self._done)

    def at_step(self, global_step: int) -> None:
        """Called before global step ``global_step`` dispatches."""
        if not self.enabled:
            return
        a, b = self.window
        if not self._active and a <= global_step < b:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif self._active and global_step >= b:
            self.close()

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
        self._done = True


@contextlib.contextmanager
def maybe_trace(
    log_dir: Optional[str] = None, enabled: bool = True
) -> Iterator[bool]:
    """Capture a jax profiler trace into ``log_dir`` (or $NCNET_TPU_PROFILE_DIR)
    for the duration of the block; yields whether tracing is active.
    ``enabled=False`` forces a no-op regardless of the env var (callers use it
    to bound the capture to one representative phase — or to stand down when
    a :class:`StepWindowTracer` owns the capture instead)."""
    log_dir = log_dir or os.environ.get(PROFILE_DIR_ENV) or None
    if not log_dir or not enabled:
        yield False
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield True
    finally:
        jax.profiler.stop_trace()
