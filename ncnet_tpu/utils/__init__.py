"""Utilities: seeding, profiling, atomic artifact I/O, fault injection
(``ncnet_tpu.utils.faults`` — stdlib+numpy only; its hooks are no-ops
unless a test arms a plan)."""

from ncnet_tpu.utils.io import atomic_savemat, atomic_write_json
from ncnet_tpu.utils.profiling import annotate, maybe_trace
from ncnet_tpu.utils.seeding import global_seed, worker_rng

__all__ = [
    "annotate",
    "atomic_savemat",
    "atomic_write_json",
    "maybe_trace",
    "global_seed",
    "worker_rng",
]
