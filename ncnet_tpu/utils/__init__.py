"""Utilities: seeding, profiling."""

from ncnet_tpu.utils.profiling import annotate, maybe_trace
from ncnet_tpu.utils.seeding import global_seed, worker_rng

__all__ = ["annotate", "maybe_trace", "global_seed", "worker_rng"]
