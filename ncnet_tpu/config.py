"""Configuration dataclasses for the framework.

The reference drives everything with per-script argparse flags
(/root/reference/train.py:34-47, eval_pf_pascal.py:28-30, eval_inloc.py:30-40)
and smuggles architecture hyper-parameters inside checkpoints
(/root/reference/lib/model.py:215-220).  Here every entry point is driven by a
typed config; CLI flags keep the reference's names/defaults so command-line
compatibility holds, and checkpoints carry the full `ModelConfig` so loading a
checkpoint reproduces its architecture exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture of the NCNet model.

    Defaults mirror the reference ImMatchNet defaults
    (/root/reference/lib/model.py:193-205) with the PF-Pascal training values
    from /root/reference/train.py:42-43 left to the train config.
    """

    backbone: str = "resnet101"          # 'resnet101' | 'vgg' | 'densenet201' | 'tiny'
    backbone_last_layer: str = ""        # '' → layer3 (resnet) / pool4 (vgg)
    ncons_kernel_sizes: Sequence[int] = (3, 3, 3)
    ncons_channels: Sequence[int] = (10, 10, 1)
    symmetric_mode: bool = True
    normalize_features: bool = True
    relocalization_k_size: int = 0       # >1 enables maxpool4d relocalization
    # coarse-to-fine sparse correlation (ops/sparse_topk.py +
    # ops/sparse_corr.py; README "Coarse-to-fine matching"): 0 = dense (the
    # unchanged default); k > 0 filters a pooled coarse volume first, keeps
    # the top-k candidate target neighbourhoods per coarse source cell, and
    # evaluates + NC-filters fine correlation only on the gathered tiles —
    # fine-stage FLOPs/bytes scale with k·patch⁴ instead of (hw)².  Falls
    # back dense when the shape class is ineligible (relocalization on,
    # dims not divisible by the factor) or the "coarse2fine" tier was
    # demoted at runtime (ops.demote_fused_tier).
    sparse_topk: int = 0
    sparse_factor: int = 2               # coarse pooling factor (stride-16
                                         # features → stride-32 at 2)
    sparse_halo: int = -1                # fine-cell patch halo around each
                                         # candidate block; -1 = auto (one
                                         # coarse ring = factor cells)
    # streaming tracked mode (ops/temporal.py; README "Streaming matching"):
    # search-window radius, in coarse cells, used to dilate the previous
    # frame's match table into candidate rows when a stream session skips
    # the coarse pass.  The tracked fine pass evaluates (2r+1)² tiles per
    # source cell, so the radius scales its cost the way sparse_topk
    # scales the coarse-to-fine tier's — radius 0 (one tile: the prior's
    # cell, with the sparse_halo ring already granting ±halo fine cells
    # of motion) is the steady-frame configuration that undercuts the
    # k-candidate coarse-to-fine wall; radius 1 costs 9 tiles/cell and
    # only pays off when frame-to-frame motion routinely crosses coarse
    # cells (cut detection handles the rest by exact fallback).  Only
    # consumed by the tracked filter — dense and coarse-to-fine queries
    # ignore it.
    track_radius: int = 0
    # force a named ARITHMETIC filter tier ('cp' | 'fft'; ops/conv4d_cp.py,
    # ops/conv4d_fft.py) through the NC stack, bypassing choose_fused_stack's
    # FLOP gates.  '' (default) lets the chooser pick.  'cp' requires CP
    # factors on every NC layer (tools/cp_decompose.py); the fine-tune path
    # (TrainConfig.finetune_cp_rank) sets this so factor gradients flow.
    nc_tier: str = ""
    half_precision: bool = False         # bf16 volume + NC weights (TPU-native fp16 analog)
    backbone_bf16: bool = False          # run the (frozen) trunk in bfloat16 —
                                         # TPU-native fast path with no reference
                                         # analog (the reference keeps the trunk
                                         # fp32 even in half mode, model.py:265)
    backbone_weights: str = ""           # torchvision state_dict (.pth) for the
                                         # trunk; the reference always starts
                                         # from ImageNet weights (model.py:25,39)
    checkpoint: str = ""                 # path to orbax dir or torch .pth.tar

    def replace(self, **kw) -> "ModelConfig":
        return dataclasses.replace(self, **kw)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Weak-supervision training run (reference train.py:34-47 flags)."""

    model: ModelConfig = ModelConfig(
        ncons_kernel_sizes=(5, 5, 5), ncons_channels=(16, 16, 1)
    )
    image_size: int = 400
    dataset_image_path: str = "datasets/pf-pascal/"
    dataset_csv_path: str = "datasets/pf-pascal/image_pairs/"
    num_epochs: int = 5
    batch_size: int = 16
    lr: float = 5e-4
    result_model_fn: str = "checkpoint_adam"
    result_model_dir: str = "trained_models"
    fe_finetune_params: int = 0
    # CP fine-tune (ISSUE 17; Lebedev et al.): > 0 decomposes every NC
    # kernel of the (loaded) dense params to rank-R CP factors
    # (tools/cp_decompose.py) and trains the FACTORS through the 'cp' tier
    # with the trunk frozen — the paper's PCK-recovery recipe.  The model
    # config is forced to nc_tier='cp' for the run so the gradient path
    # matches serving.  0 = dense training, the unchanged default.
    finetune_cp_rank: int = 0
    seed: int = 1
    num_workers: int = 0
    eval_num_workers: int = 4
    log_interval: int = 1
    # TPU-native additions (no reference analog):
    data_parallel: bool = True           # shard the pair batch over the mesh 'data' axis
    donate_state: bool = True
    remat_nc_layers: bool = False        # rematerialize each NC layer in the
                                         # backward: fits bs16 (bf16) on one
                                         # 16G chip at ~30% step-time cost —
                                         # see training/loss.py measurements
    nc_custom_grad: bool = False         # conv4d custom VJP: ~18% slower but
                                         # ~45% less backward temp memory
                                         # than plain AD (models/ncnet.py).
                                         # Since r4 the default bs16 recipe
                                         # is accum_chunks (below), which
                                         # fits 16G in both precisions; this
                                         # knob passes through to the
                                         # chunked backward too
    fold_pos_neg: bool = False           # one 2B-batch NC-filter call for the
                                         # positive+negative volumes instead
                                         # of two B-sized calls — identical
                                         # math but measured NO faster (r4,
                                         # XLA backward) and the larger
                                         # program crashes the tunnel
                                         # compile-helper at bs8 fp32.  Only
                                         # applies with accum_chunks=0; now
                                         # a CLI flag (--fold_pos_neg) and
                                         # bench.py measures folded vs
                                         # unfolded on the r7 Pallas-VJP
                                         # path so the default can flip on
                                         # evidence (training/loss.py)
    nc_pallas_vjp: bool = True           # route the NC filter through the
                                         # fused Pallas forward + RESIDENT
                                         # Pallas backward where the shape
                                         # class compiles (round 7,
                                         # ops/nc_fused_lane_vjp.py);
                                         # ineligible configs (fp32, CPU,
                                         # remat/custom-grad escape hatches)
                                         # keep the XLA formulations.
                                         # --no_nc_pallas_vjp disables
    remat_filter: bool = True            # jax.checkpoint around the NC filter
                                         # (recompute volumes in the backward)
    accum_chunks: int = -1               # frozen trunk only: exact
                                         # volume-chunked gradient
                                         # accumulation — scan the filter
                                         # backward over chunks of the 2B
                                         # pos/neg volume batch; fits and
                                         # compiles ANY batch size, skips
                                         # the remat recompute, and is the
                                         # fastest measured path (bs8 fp32
                                         # 9.75→13.4 pairs/s, bf16 16.6;
                                         # tools/train_probe.py r4).
                                         # -1 = auto chunking, 0 = off
                                         # (whole-batch backward), >1 =
                                         # explicit chunk count
                                         # (training/loss.py)
    # static jit shapes need whole batches; dropping the val remainder (4 of
    # 308 PF-Pascal pairs at bs=16) makes best-checkpoint selection score a
    # fixed subset each epoch.  A documented deviation: the reference scores
    # all pairs (but shuffles val, so its per-epoch val sets differ anyway).
    val_drop_last: bool = True
    distributed: bool = False            # jax.distributed multi-host init +
                                         # per-host input sharding
    profile_dir: str = ""                # capture a jax profiler trace here
                                         # (also honours $NCNET_TPU_PROFILE_DIR;
                                         # $NCNET_TPU_PROFILE_STEPS=<a>:<b>
                                         # bounds the capture to exactly
                                         # global steps [a, b))
    # observability (ncnet_tpu/observability/; README "Observability"):
    telemetry: bool = True               # structured run telemetry: a
                                         # schema-versioned JSONL event log
                                         # (step/epoch/checkpoint/NaN-skip/
                                         # tier/quarantine events), a
                                         # heartbeat file bumped every step,
                                         # and periodic device snapshots.
                                         # Primary-process only; replay with
                                         # tools/run_report.py
    telemetry_dir: str = ""              # where the event log + heartbeat
                                         # live; "" = <checkpoint root>/
                                         # telemetry (so crash/resume cycles
                                         # of one lineage share one log)
    # fault tolerance (training/train.py "Fault tolerance" docstring;
    # no reference analog — the reference can only restart at epoch 1):
    checkpoint_steps: int = 0            # ALSO save every N train steps
                                         # (mid-epoch, with resume position);
                                         # 0 = epoch-end saves only
    keep_checkpoints: int = 3            # retention window of step_<N>
                                         # versions per root (the best_ copy
                                         # is separate and never pruned)
    nan_guard: bool = True               # jitted non-finite-loss detector:
                                         # skip the poisoned update (params
                                         # AND Adam state untouched); costs
                                         # one host sync per step
    max_bad_steps: int = 3               # abort (TrainDivergedError) after
                                         # K consecutive skipped steps
    io_retries: int = 3                  # bounded retry of orbax save/
                                         # restore; forced to 1 multi-process
                                         # (collective-save deadlock rules)
    io_retry_backoff: float = 0.5        # seconds, doubled per attempt
    decode_retries: int = 1              # per-image transient decode retries
    quarantine_decode_errors: bool = True  # skip+log undecodable samples
                                         # (loader substitutes the next
                                         # healthy one) instead of crashing


@dataclasses.dataclass(frozen=True)
class EvalPFPascalConfig:
    """PCK evaluation on PF-Pascal (reference eval_pf_pascal.py:28-30)."""

    checkpoint: str = ""
    image_size: int = 400
    eval_dataset_path: str = "datasets/pf-pascal/"
    pck_alpha: float = 0.1
    pck_procedure: str = "scnet"
    # coarse-to-fine sparse matching passthrough (ModelConfig.sparse_topk):
    # >0 evaluates with the sparse pipeline at this k (applies when the
    # eval constructs the net itself; a caller-supplied net keeps its own
    # ModelConfig).  0 = dense, the unchanged default.
    sparse_topk: int = 0
    # fault tolerance (evaluation/resilience.py; README "Resilient
    # inference" — no reference analog: the reference loses all accumulated
    # PCK on any crash):
    journal_dir: str = ""                # journal per-batch PCK contributions
                                         # + run manifest here; a rerun with
                                         # the same settings resumes mid-eval
                                         # to a bitwise-identical result.
                                         # "" = no journal (in-memory only)
    query_retries: int = 2               # per-batch retry attempts after the
                                         # first dispatch/fetch failure
    retry_backoff_s: float = 0.5         # seconds, doubled per attempt
    quarantine: bool = True              # exhausted retries: record the batch
                                         # in the manifest and keep going
                                         # (its pairs score NaN = invalid)
                                         # instead of aborting the run
    fetch_timeout_s: float = 0.0         # watchdog around each result fetch;
                                         # a hung tunnel becomes a retryable
                                         # timeout. 0 = no watchdog
    decode_retries: int = 1              # per-image transient decode retries
                                         # (the eval twin of
                                         # TrainConfig.decode_retries)
    # observability (README "Observability"): open a structured event log
    # here for the run (per-batch eval events + an eval_summary metrics
    # flush). "" = emit only to an already-bound global sink, if any
    telemetry_dir: str = ""


@dataclasses.dataclass(frozen=True)
class EvalInLocConfig:
    """Dense matching for InLoc localization (reference eval_inloc.py:30-40)."""

    checkpoint: str = ""
    inloc_shortlist: str = "datasets/inloc/densePE_top100_shortlist_cvpr18.mat"
    k_size: int = 2
    image_size: int = 3200
    n_queries: int = 356
    n_panos: int = 10
    softmax: bool = True
    matching_both_directions: bool = True
    flip_matching_direction: bool = False
    pano_path: str = "datasets/inloc/pano/"
    query_path: str = "datasets/inloc/query/iphone7/"
    output_root: str = "matches"
    # TPU-native addition: shard the 4D volume spatially over this many devices.
    spatial_shards: int = 1
    # coarse-to-fine sparse matching passthrough (ModelConfig.sparse_topk):
    # >0 evaluates with the sparse pipeline at this k.  Requires k_size=1 —
    # maxpool4d relocalization composes with the dense volume only, so a
    # sparse run at the default k_size=2 falls back dense with a warning.
    sparse_topk: int = 0
    # dispatch/fetch pipeline depth of the eval loop. 0 = adaptive: start at
    # the low-latency optimum of 2 (r3 sweep: 0.62/0.285/0.47/0.51 s/pair at
    # depths 1/2/3/4) and deepen to at most 4 when the per-pair wall EWMA
    # exceeds 2x the windowed-minimum wall (a measured device-compute
    # estimate), capped at the r3-measured 0.7 s (r3 observation: under
    # ~2-3x latency regimes depth 3-4 beat 2). >0 pins the depth verbatim,
    # BYPASSING the 2-4 adaptive band; negative values are rejected.
    pipeline_depth: int = 0
    # TPU-native addition: stripe queries across hosts (each host writes its
    # own per-query .mat files — the host-parallel eval analog of the
    # reference's MATLAB parfor).  -1 → auto from jax.process_index/count.
    host_index: int = -1
    host_count: int = 0
    # resume-by-artifact: skip queries whose output .mat already exists (the
    # folder name encodes checkpoint + settings, so hits cannot be stale)
    skip_existing: bool = True
    # fault tolerance (evaluation/resilience.py; README "Resilient
    # inference" — no reference analog: the reference aborts the whole
    # multi-hour run on the first bad query):
    validate_existing: bool = True       # before skipping, loadmat-validate
                                         # the artifact (expected keys +
                                         # table shape) so a foreign or
                                         # truncated file is recomputed, not
                                         # silently fed to the PnP stage
    query_retries: int = 2               # per-query retry attempts after the
                                         # first failure (decode, device,
                                         # savemat, timeout)
    retry_backoff_s: float = 0.5         # seconds, doubled per attempt
    quarantine: bool = True              # exhausted retries: record the query
                                         # in manifest.json and keep going
                                         # instead of aborting the run
    fetch_timeout_s: float = 0.0         # watchdog around each pair fetch;
                                         # a hung tunnel becomes a retryable
                                         # timeout. 0 = no watchdog
    write_manifest: bool = True          # journal completed / quarantined /
                                         # in-flight queries to
                                         # <out_dir>/manifest.json
    # observability (README "Observability"): open a structured event log
    # here for the run (per-query events + an eval_summary metrics flush).
    # "" = emit only to an already-bound global sink, if any
    telemetry_dir: str = ""
    # persistent database-side feature store (ncnet_tpu/store/; README
    # "Feature store"): pano backbone features are cached on disk keyed by
    # (image content digest, backbone fingerprint), verified on read,
    # committed atomically, and recomputed transparently on any miss /
    # corruption / IO failure — a warm store turns each query into ONE
    # backbone extraction + cached matching.  "" = off; bulk-build with
    # tools/build_feature_store.py.  Ignored under spatial_shards > 1.
    feature_store_dir: str = ""
    feature_store_budget_mb: int = 0     # LRU-evict above this (0 = unbounded)
    # in-system retrieval shortlist (ncnet_tpu/retrieval/; README "Sharded
    # retrieval"): point this at a coarse index manifest (or glob of
    # per-stripe manifests) built by tools/build_coarse_index.py and the
    # eval re-ranks each query's precomputed .mat candidate row by coarse-
    # volume similarity before fine matching — the top retrieval_topk
    # candidates are matched, in retrieval order.  The precomputed .mat
    # order stays the fallback: a query whose row coverage (fraction of
    # row panos the index + store could actually score) falls below
    # retrieval_min_coverage is matched in the original .mat order, with a
    # warning and a retrieval_fallback event — degraded input is reported,
    # never silently used.  "" = off (bitwise-identical legacy behavior).
    retrieval_index: str = ""
    retrieval_topk: int = 0              # 0 → n_panos
    retrieval_min_coverage: float = 1.0


@dataclasses.dataclass(frozen=True)
class LocalizationConfig:
    """InLoc downstream localization (the reference's MATLAB L6 stage,
    compute_densePE_NCNet.m: thresholds at :33-34, pnp_topN at :31)."""

    matches_dir: str = ""                # matches/<experiment> from eval_inloc
    shortlist: str = "datasets/inloc/densePE_top100_shortlist_cvpr18.mat"
    query_path: str = "datasets/inloc/query/iphone7/"
    cutout_path: str = "datasets/inloc/pano/"     # cutout images + XYZcut .mat
    cutout_mat_suffix: str = ".mat"      # appended to the cutout name
    scan_path: str = "datasets/inloc/scans/"      # *_scan_*.ptx.mat
    scan_suffix: str = ".ptx.mat"
    transformation_path: str = "datasets/inloc/"  # <floor>/transformations/
    refposes: str = "datasets/inloc/DUC_refposes_all.mat"
    output_dir: str = "outputs_localization"
    pnp_topN: int = 10                   # candidates per query
    match_score_thr: float = 0.75        # params.ncnet.thr
    pnp_inlier_thr_deg: float = 0.2      # params.ncnet.pnp_thr (degrees)
    ransac_iters: int = 10000
    max_tentatives: int = 0              # params.ncnet.N_subsample; 0 = all
    do_pose_verification: bool = True    # the densePV rerank stage
    query_focal_length: float = 0.0      # pixels; 0 → iPhone 7 EXIF default
    n_queries: int = 0                   # 0 = all queries in the shortlist
    seed: int = 0
    progress: bool = True
    num_workers: int = 0                 # >0: PnP (per query) and pose
                                         # verification (per scan) fan out
                                         # over spawn process pools — the
                                         # reference's two parfor loops
    # fault tolerance (evaluation/resilience.py): per-query isolation of the
    # PnP stage — a query whose matches/.mat/cutout data is broken is
    # retried, then quarantined into the stage manifest (it scores as
    # not-localized downstream), instead of aborting the stage
    query_retries: int = 2
    retry_backoff_s: float = 0.5
    quarantine: bool = True


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh layout.  axes: data-parallel pairs × spatial volume shards."""

    data: int = 1
    spatial: int = 1
