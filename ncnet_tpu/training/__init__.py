"""Training: weak-supervision loss, jitted steps, epoch loop, checkpoints."""

from ncnet_tpu.training.loss import match_score, weak_loss
from ncnet_tpu.training.train import (
    TrainState,
    create_train_state,
    fit,
    load_train_checkpoint,
    make_eval_step,
    make_optimizer,
    make_train_step,
    process_epoch,
    save_train_checkpoint,
    trainable_labels,
)

__all__ = [
    "TrainState",
    "create_train_state",
    "fit",
    "load_train_checkpoint",
    "make_eval_step",
    "make_optimizer",
    "make_train_step",
    "match_score",
    "process_epoch",
    "save_train_checkpoint",
    "trainable_labels",
    "weak_loss",
]
