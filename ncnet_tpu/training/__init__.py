"""Training: weak-supervision loss, jitted steps, epoch loop, checkpoints."""

from ncnet_tpu.training.loss import (
    auto_accum_chunks,
    match_score,
    match_score_per_pair,
    weak_loss,
    weak_loss_and_grads,
)
from ncnet_tpu.training.train import (
    PreemptionHandler,
    TrainDivergedError,
    TrainState,
    create_train_state,
    fit,
    load_train_checkpoint,
    make_eval_step,
    make_optimizer,
    make_train_step,
    process_epoch,
    save_train_checkpoint,
    trainable_labels,
)

__all__ = [
    "PreemptionHandler",
    "TrainDivergedError",
    "TrainState",
    "create_train_state",
    "fit",
    "load_train_checkpoint",
    "make_eval_step",
    "make_optimizer",
    "make_train_step",
    "auto_accum_chunks",
    "match_score",
    "match_score_per_pair",
    "process_epoch",
    "save_train_checkpoint",
    "trainable_labels",
    "weak_loss",
    "weak_loss_and_grads",
]
