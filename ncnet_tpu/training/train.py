"""Training loop: optax Adam over the trainable partition, jitted steps,
orbax epoch checkpoints with best-copy tracking.

Reference: /root/reference/train.py:161-205 (epoch loop, per-epoch checkpoint
carrying train/val loss history, ``best_`` copy on improvement) and
train.py:60-71 (Adam over requires_grad params only: the consensus stack plus
optionally the last backbone blocks).

Improvements over the reference, by design:
  * the train step is one jitted program (loss + grads + Adam update) with
    donated state — no Python in the hot loop;
  * resume is real: ``fit`` pointed at one of its own checkpoints restores
    params AND optimizer state AND the epoch counter (the reference saves the
    optimizer but never loads it and always restarts at epoch 1,
    train.py:71,190);
  * frozen parameters are handled by ``optax.multi_transform`` with
    ``set_to_zero``, so the update pytree structure is stable and shardable.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import shutil
import time
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ncnet_tpu.config import ModelConfig, TrainConfig
from ncnet_tpu.data import DataLoader, ImagePairDataset
from ncnet_tpu.models import backbone as bb
from ncnet_tpu.models import checkpoint as ckpt_io
from ncnet_tpu.models.ncnet import init_ncnet
from ncnet_tpu.training.loss import (
    auto_accum_chunks,
    weak_loss,
    weak_loss_and_grads,
)
from ncnet_tpu.utils.profiling import annotate, maybe_trace


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray  # scalar int32


def trainable_labels(config: ModelConfig, params, fe_finetune_params: int = 0):
    """'trainable'/'frozen' labels: consensus stack always trains; the last
    ``fe_finetune_params`` backbone blocks optionally join
    (train.py:60-63)."""
    return {
        "backbone": bb.finetune_labels(
            config.backbone, params["backbone"], fe_finetune_params
        ),
        "nc": jax.tree.map(lambda _: "trainable", params["nc"]),
    }


def make_optimizer(labels):
    """Returns an ``lr → GradientTransformation`` factory bound to the
    trainable/frozen label tree."""

    def tx(lr: float) -> optax.GradientTransformation:
        return optax.multi_transform(
            {"trainable": optax.adam(lr), "frozen": optax.set_to_zero()}, labels
        )

    return tx


def create_train_state(
    config: TrainConfig, key: Optional[jax.Array] = None
) -> Tuple[TrainState, optax.GradientTransformation, ModelConfig, Any]:
    """Init (or load from ``config.model.checkpoint``) params + fresh Adam.

    Returns ``(state, optimizer, model_config, labels)``."""
    model_config = config.model
    if model_config.checkpoint:
        model_config, params = ckpt_io.load_params(
            model_config.checkpoint, model_config
        )
    else:
        params = init_ncnet(model_config, key or jax.random.key(config.seed))
    labels = trainable_labels(model_config, params, config.fe_finetune_params)
    optimizer = make_optimizer(labels)(config.lr)
    state = TrainState(params, optimizer.init(params), jnp.asarray(0, jnp.int32))
    return state, optimizer, model_config, labels


def make_train_step(
    model_config: ModelConfig,
    optimizer,
    donate: bool = True,
    stop_backbone_grad: bool = False,
    remat_nc_layers: bool = False,
    nc_custom_grad: bool = False,
    fold_pos_neg: bool = False,
    remat_filter: bool = True,
    accum_chunks: int = 0,
):
    """Jitted (state, batch) → (state, loss).

    Pass ``stop_backbone_grad=True`` when no backbone blocks are being
    finetuned (``fe_finetune_params == 0``, the reference default): the trunk
    is detached, matching the reference's frozen-FE training and keeping the
    backward pass off the trunk activations entirely — required to fit the
    reference batch sizes at 400² on one chip.  It must stay False when
    finetuning, so False is the (safe) default; ``fit`` derives it from the
    config.

    ``accum_chunks != 0`` (frozen trunk only) switches to
    :func:`ncnet_tpu.training.loss.weak_loss_and_grads` — exact
    volume-chunked gradient accumulation, the fastest path and the one that
    fits/compiles any batch size (see its docstring for the measurements);
    ``-1`` = auto chunk choice."""

    if accum_chunks != 0 and not stop_backbone_grad:
        raise ValueError(
            "accum_chunks requires the frozen trunk (fe_finetune_params=0): "
            "chunked accumulation detaches the features"
        )

    def step(state: TrainState, batch):
        if accum_chunks != 0:
            # the memory knobs pass through (fold_pos_neg/remat_filter do
            # not apply: chunking already bounds the live volume set)
            loss, grads = weak_loss_and_grads(
                model_config, state.params, batch, accum_chunks=accum_chunks,
                remat_nc_layers=remat_nc_layers,
                nc_custom_grad=nc_custom_grad,
            )
        else:
            loss, grads = jax.value_and_grad(
                lambda p: weak_loss(
                    model_config, p, batch,
                    stop_backbone_grad=stop_backbone_grad,
                    remat_nc_layers=remat_nc_layers,
                    nc_custom_grad=nc_custom_grad,
                    fold_pos_neg=fold_pos_neg,
                    remat_filter=remat_filter,
                )
            )(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_eval_step(model_config: ModelConfig):
    return jax.jit(lambda params, batch: weak_loss(model_config, params, batch))


def process_epoch(
    mode: str,
    epoch: int,
    state: TrainState,
    step_fn,
    loader: DataLoader,
    log_interval: int = 1,
    put_batch=None,
) -> Tuple[TrainState, float]:
    """One pass over ``loader``; mirrors the reference's per-batch logging
    (train.py:161-181).  ``put_batch`` maps a host array onto devices
    (defaults to plain transfer; the data-parallel path shards the pair
    axis)."""
    put_batch = put_batch or jnp.asarray
    n = len(loader)
    if n == 0:
        raise ValueError(
            f"{mode} loader is empty (dataset smaller than batch_size with "
            "drop_last) — refusing to report a fake 0.0 epoch loss"
        )
    losses = []  # device scalars; only synced at log points / epoch end
    for batch_idx, batch in enumerate(loader):
        images = {
            "source_image": put_batch(batch["source_image"]),
            "target_image": put_batch(batch["target_image"]),
        }
        with annotate(f"{mode}_step"):
            if mode == "train":
                state, loss = step_fn(state, images)
            else:
                loss = step_fn(state.params, images)
        losses.append(loss)
        if batch_idx % log_interval == 0:
            print(
                f"{mode.capitalize()} Epoch: {epoch} [{batch_idx}/{n} "
                f"({100.0 * batch_idx / n:.0f}%)]\t\tLoss: {float(loss):.6f}"
            )
    epoch_loss = float(jnp.mean(jnp.stack(losses)))
    print(f"{mode.capitalize()} set: Average loss: {epoch_loss:.4f}")
    return state, epoch_loss


# ---------------------------------------------------------------------------
# checkpointing (full train state)
# ---------------------------------------------------------------------------


def save_train_checkpoint(
    path: str,
    config: TrainConfig,
    model_config: ModelConfig,
    state: TrainState,
    epoch: int,
    train_loss: np.ndarray,
    test_loss: np.ndarray,
    is_best: bool,
) -> None:
    """Epoch checkpoint; on improvement also copied to ``best_<name>``
    (torch_util.py:48-61).

    Layout is a superset of :func:`ncnet_tpu.models.checkpoint.save_params`:
    ``config.json`` carries the ModelConfig fields at top level (plus train
    metadata under ``_train``/``_epoch``/loss keys) and the weights live in a
    ``params/`` subtree — so ``load_params`` (and therefore eval/finetune
    ``--checkpoint``) reads a training checkpoint directly.  Optimizer state
    + step go in a separate ``opt/`` subtree for :func:`load_train_checkpoint`.

    Multi-process: EVERY process must call this — the orbax saves are
    collective (``sync_global_processes`` inside ``save``; gating them on
    process 0 deadlocks the job, caught by the two-process smoke test).
    Orbax itself writes array data from the primary host only; the
    non-collective extras (config.json, the ``best_`` copy) are primary-only
    here.
    """
    import orbax.checkpoint as ocp

    primary = jax.process_index() == 0
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    if primary:
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump(
                {
                    **dataclasses.asdict(model_config),
                    "_train": {
                        k: v
                        for k, v in dataclasses.asdict(config).items()
                        if k != "model"
                    },
                    "_epoch": epoch,
                    "_train_loss": list(map(float, train_loss)),
                    "_test_loss": list(map(float, test_loss)),
                },
                f,
                indent=2,
                default=list,
            )
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, "params"), state.params, force=True)
    ckptr.save(
        os.path.join(path, "opt"),
        {"opt_state": state.opt_state, "step": state.step},
        force=True,
    )
    ckptr.wait_until_finished()
    if is_best and primary:
        best = os.path.join(os.path.dirname(path), "best_" + os.path.basename(path))
        if os.path.isdir(best):
            shutil.rmtree(best)
        shutil.copytree(path, best)


def load_train_checkpoint(path: str, state_like: TrainState):
    """Restore a full train state (params + optimizer + step) for resume —
    the capability the reference saves for but never implements
    (train.py:71 creates a fresh Adam; ``checkpoint['optimizer']`` is never
    read)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    params = ckptr.restore(os.path.join(path, "params"), target=state_like.params)
    opt = ckptr.restore(
        os.path.join(path, "opt"),
        target={"opt_state": state_like.opt_state, "step": state_like.step},
    )
    with open(os.path.join(path, "config.json")) as f:
        meta = json.load(f)
    state = TrainState(params, opt["opt_state"], opt["step"])
    return (
        state,
        meta["_epoch"],
        np.asarray(meta["_train_loss"]),
        np.asarray(meta["_test_loss"]),
    )


def _resolve_accum_chunks(config: TrainConfig, n_dev: int) -> int:
    """Chunked accumulation needs the frozen trunk: the auto default (-1)
    quietly falls back to the whole-batch backward when finetuning, but an
    EXPLICIT chunk count with finetuning is a contradiction the user must
    resolve (the same combination raises in make_train_step)."""
    if config.fe_finetune_params > 0:
        if config.accum_chunks > 0:
            raise ValueError(
                f"accum_chunks={config.accum_chunks} requires the frozen "
                "trunk, but fe_finetune_params="
                f"{config.fe_finetune_params} finetunes backbone blocks; "
                "drop one of the two settings"
            )
        return 0
    if config.accum_chunks == -1:
        return auto_accum_chunks(config.batch_size, n_dev)
    if config.accum_chunks < 0:
        raise ValueError(
            f"accum_chunks={config.accum_chunks}: use -1 (auto), 0 (off) or "
            "a positive chunk count"
        )
    if config.accum_chunks and (2 * config.batch_size) % config.accum_chunks:
        raise ValueError(
            f"accum_chunks={config.accum_chunks} must divide "
            f"2*batch_size={2 * config.batch_size}"
        )
    if config.accum_chunks and n_dev > 1:
        chunk = (2 * config.batch_size) // config.accum_chunks
        if chunk % n_dev:
            # a chunk that doesn't divide over the data mesh forces GSPMD to
            # reshard/gather the volume every scan iteration — reject loudly
            # rather than silently running the slow program
            raise ValueError(
                f"accum_chunks={config.accum_chunks} gives chunk size "
                f"{chunk}, which does not divide over {n_dev} data-parallel "
                f"devices; pick a count where (2*batch_size/accum_chunks) % "
                f"n_devices == 0, or use -1 (auto)"
            )
    return config.accum_chunks


# ---------------------------------------------------------------------------
# fit: the whole reference train.py flow
# ---------------------------------------------------------------------------


def fit(config: TrainConfig, progress: bool = True) -> Dict[str, Any]:
    """Train per the reference recipe: epochs over train_pairs.csv, val loss
    on val_pairs.csv each epoch, checkpoint every epoch + best copy."""
    shard_kwargs = {}
    local_batch = config.batch_size
    if config.distributed:
        from ncnet_tpu.parallel import host_shard, initialize_distributed

        initialize_distributed()
        shard_kwargs = host_shard()
        n_procs = shard_kwargs["num_shards"]
        if n_procs > 1:
            if not config.data_parallel:
                # each host would silently train its own diverging model
                raise ValueError(
                    "distributed=True across multiple processes requires "
                    "data_parallel=True (there is no gradient sync otherwise)"
                )
            if config.batch_size % n_procs:
                raise ValueError(
                    f"batch_size {config.batch_size} must divide evenly over "
                    f"{n_procs} processes"
                )
            # batch_size stays the reference's GLOBAL batch; each host loads
            # its slice and the global array is assembled across processes
            local_batch = config.batch_size // n_procs
        if progress:
            print(f"Distributed: process {shard_kwargs['shard_index']} of "
                  f"{n_procs}")

    state, optimizer, model_config, labels = create_train_state(config)

    # resume: a checkpoint directory written by fit() carries opt/ — restore
    # the full train state and continue from the saved epoch
    start_epoch = 0
    prev_train = prev_test = None
    ckpt = config.model.checkpoint
    if ckpt and os.path.isdir(os.path.join(ckpt, "opt")):
        state, start_epoch, prev_train, prev_test = load_train_checkpoint(ckpt, state)
        if progress:
            print(f"Resumed full train state from {ckpt} at epoch {start_epoch}")

    n_trainable = sum(
        int(np.prod(np.asarray(x.shape)))
        for x, lbl in zip(jax.tree.leaves(state.params), jax.tree.leaves(labels))
        if lbl == "trainable"
    )
    if progress:
        print(f"Trainable parameters: {n_trainable:,}")

    # data parallelism: shard the pair axis over every device, replicate
    # params; jit + shardings make XLA psum the grads and route the
    # negative-roll permute over ICI (loss.py docstring)
    put_batch = None
    # largest device count that evenly divides the batch (all devices when
    # batch_size % len(devices) == 0, e.g. the reference's 16 on 8 chips)
    n_dev = max(
        d for d in range(1, min(len(jax.devices()), config.batch_size) + 1)
        if config.batch_size % d == 0
    )
    if config.data_parallel and n_dev > 1:
        if not config.val_drop_last:
            # a partial trailing val batch cannot be device_put with the
            # pair-axis sharding (batch size must divide the device count),
            # and padding it would perturb the in-batch negative roll
            raise ValueError(
                "val_drop_last=False is incompatible with data_parallel "
                "across multiple devices; disable one of the two"
            )
        from ncnet_tpu import parallel

        mesh = parallel.make_mesh(data=n_dev, devices=jax.devices()[:n_dev])
        # replicate the WHOLE state (step included): restored checkpoints are
        # committed to device 0 and would otherwise conflict with the mesh
        state = TrainState(*parallel.replicate(mesh, tuple(state)))
        sharding = parallel.batch_sharding(mesh)
        if jax.process_count() > 1:
            # each process holds only its host-local rows; assemble the
            # global batch array from per-process slices (device_put would
            # treat the local slice as the global value and drop data)
            put_batch = lambda x: jax.make_array_from_process_local_data(  # noqa: E731
                sharding, np.asarray(x)
            )
        else:
            put_batch = lambda x: jax.device_put(jnp.asarray(x), sharding)  # noqa: E731
        if progress:
            print(f"Data parallel over {n_dev} devices (mesh {mesh.shape})")

    accum = _resolve_accum_chunks(config, n_dev if config.data_parallel else 1)
    if progress and accum:
        print(f"Gradient accumulation: {accum} chunks of "
              f"{2 * config.batch_size // accum} volumes")
    train_step = make_train_step(
        model_config, optimizer, donate=config.donate_state,
        stop_backbone_grad=config.fe_finetune_params == 0,
        remat_nc_layers=config.remat_nc_layers,
        nc_custom_grad=config.nc_custom_grad,
        fold_pos_neg=config.fold_pos_neg,
        remat_filter=config.remat_filter,
        accum_chunks=accum,
    )
    eval_step = make_eval_step(model_config)

    size = (config.image_size, config.image_size)
    train_loader = DataLoader(
        ImagePairDataset(
            config.dataset_csv_path, "train_pairs.csv", config.dataset_image_path,
            output_size=size, seed=config.seed,
        ),
        batch_size=local_batch, shuffle=True,
        num_workers=config.num_workers, seed=config.seed, drop_last=True,
        **shard_kwargs,
    )
    # val: no shuffle — with drop_last (config.val_drop_last), a shuffle
    # would drop a DIFFERENT random subset each epoch, making the
    # best-checkpoint metric noisy (the reference shuffles but drops nothing)
    val_loader = DataLoader(
        ImagePairDataset(
            config.dataset_csv_path, "val_pairs.csv", config.dataset_image_path,
            output_size=size, seed=config.seed,
        ),
        batch_size=local_batch, shuffle=False,
        num_workers=config.eval_num_workers, seed=config.seed,
        drop_last=config.val_drop_last,
        **shard_kwargs,
    )

    # the checkpoint path must agree across processes (orbax saves are
    # collective): stamp from process 0's clock, broadcast to the others.
    # Broadcast as (days, seconds-of-day) int32s — with x64 disabled a float
    # timestamp would be quantized to ~128 s and an int64 silently truncated.
    stamp = time.time()
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        parts = multihost_utils.broadcast_one_to_all(
            np.asarray([int(stamp) // 86400, int(stamp) % 86400], np.int32)
        )
        stamp = float(int(parts[0]) * 86400 + int(parts[1]))
    ckpt_name = os.path.join(
        config.result_model_dir,
        # gmtime, not localtime: processes with differing TZ env would
        # format different paths from the same broadcast stamp and
        # re-diverge the collective save (ADVICE r3)
        time.strftime("%Y-%m-%d_%H:%M", time.gmtime(stamp))
        + "_" + config.result_model_fn,
    )
    if progress:
        print(f"Checkpoint name: {ckpt_name}")

    train_loss = np.zeros(config.num_epochs)
    test_loss = np.zeros(config.num_epochs)
    best = float("inf")
    if prev_train is not None and start_epoch > 0:
        n_keep = min(start_epoch, config.num_epochs)
        train_loss[:n_keep] = prev_train[:n_keep]
        test_loss[:n_keep] = prev_test[:n_keep]
        if n_keep:
            best = float(np.min(prev_test[:n_keep]))
    for epoch in range(start_epoch + 1, config.num_epochs + 1):
        train_loader.set_epoch(epoch)
        val_loader.set_epoch(epoch)
        # trace only the first post-resume epoch: a bounded, representative
        # capture (compile + steady-state steps) instead of a runaway file
        with maybe_trace(config.profile_dir, enabled=epoch == start_epoch + 1):
            state, train_loss[epoch - 1] = process_epoch(
                "train", epoch, state, train_step, train_loader,
                config.log_interval, put_batch,
            )
        _, test_loss[epoch - 1] = process_epoch(
            "test", epoch, state, eval_step, val_loader,
            config.log_interval, put_batch,
        )
        is_best = test_loss[epoch - 1] < best
        best = min(test_loss[epoch - 1], best)
        # multi-host: losses are computed on the global batch (replicated to
        # every process), so is_best agrees everywhere.  Every process calls
        # the (collective) save; orbax writes from the primary host only.
        save_train_checkpoint(
            ckpt_name, config, model_config, state, epoch, train_loss,
            test_loss, is_best,
        )
    return {
        "state": state,
        "model_config": model_config,
        "train_loss": train_loss,
        "test_loss": test_loss,
        "best_test_loss": best,
        "checkpoint": ckpt_name,
    }
