"""Training loop: optax Adam over the trainable partition, jitted steps,
orbax epoch checkpoints with best-copy tracking.

Reference: /root/reference/train.py:161-205 (epoch loop, per-epoch checkpoint
carrying train/val loss history, ``best_`` copy on improvement) and
train.py:60-71 (Adam over requires_grad params only: the consensus stack plus
optionally the last backbone blocks).

Improvements over the reference, by design:
  * the train step is one jitted program (loss + grads + Adam update) with
    donated state — no Python in the hot loop;
  * resume is real: ``fit`` pointed at one of its own checkpoints restores
    params AND optimizer state AND the epoch counter (the reference saves the
    optimizer but never loads it and always restarts at epoch 1,
    train.py:71,190);
  * frozen parameters are handled by ``optax.multi_transform`` with
    ``set_to_zero``, so the update pytree structure is stable and shardable.

Fault tolerance
===============

Long weakly-supervised runs on preemptible TPU time must survive crashes at
ANY point, not just epoch boundaries.  Four mechanisms (each proven
end-to-end by tests/test_faults.py via the ncnet_tpu/utils/faults.py
injection harness):

**Checkpoint directory layout** — ``fit`` writes a versioned root::

    <result_model_dir>/<stamp>_<name>/       # the "root"; result["checkpoint"]
        step_00000004/                       # complete version (committed)
            config.json   # ModelConfig + _train/_epoch/_position/loss keys
            params/       # orbax pytree (readable by models.load_params)
            opt/          # {opt_state, step} for full-state resume
        step_00000006.tmp/                   # crashed save: ignored, reclaimed
    <result_model_dir>/best_<stamp>_<name>/  # flat copy of the best version

Every version is written to ``step_<N>.tmp`` and committed by one atomic
rename; a crash mid-save leaves only a ``.tmp`` carcass that loaders skip
and the next save reclaims.  Retention keeps the newest
``TrainConfig.keep_checkpoints`` versions (the ``best_`` copy is a separate
flat directory and never pruned).  Orbax save/restore calls get bounded
retry + backoff (``io_retries``/``io_retry_backoff``) in single-process runs.

**Resume contract** — point ``model.checkpoint`` at the root (or a version,
or the ``best_`` copy): the newest *complete* version is restored — params,
optimizer state, step counter AND loader position.  ``_position`` in
config.json records ``{"epoch": E, "next_batch": B}`` = the first batch not
yet consumed; resume re-enters epoch E and skips its first B batches, which
is deterministic because the shuffle is epoch-keyed and per-sample
augmentation draws are (seed, epoch, idx)-keyed (data/loader.py).  Resuming
from a root written by ``fit`` continues *in place* (new versions land in
the same root); foreign checkpoints start a fresh timestamped root.
``checkpoint_steps > 0`` saves every N steps mid-epoch; the epoch-end save
(with val loss + best tracking) always happens.  A mid-epoch-resumed epoch
logs its train loss over the remaining batches only.

**In-loop guards** — with ``nan_guard`` (default on), the jitted step
detects a non-finite loss IN-GRAPH and keeps the whole update out of params
and Adam state (the step counter still advances, so step numbering stays
batch-deterministic); the host counts consecutive skips and raises
:class:`TrainDivergedError` after ``max_bad_steps``.  The guard costs one
host sync per step (the loss is fetched eagerly instead of at log points).
SIGTERM/SIGINT request a final checkpoint at the next step boundary and a
clean return (``result["preempted"]``); a second SIGINT aborts immediately.

**Multi-process collective-save rules** — invariants every edit must keep:
every process calls ``save_train_checkpoint`` (orbax saves are collective;
gating on process 0 deadlocks); version names derive from the host-side step
counter (identical everywhere — never from clocks); the non-collective
extras (config.json, commit rename, retention pruning, ``best_`` copy) are
primary-only, with a ``sync_global_processes`` barrier before the commit;
I/O retries are forced off (a lone host re-entering a collective save
deadlocks); NaN-guard and preemption-stop decisions are taken from
replicated values / at collective boundaries so all hosts agree.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import math
import os
import shutil
import signal
import threading
import time
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ncnet_tpu.config import ModelConfig, TrainConfig
from ncnet_tpu.data import DataLoader, ImagePairDataset
from ncnet_tpu.models import backbone as bb
from ncnet_tpu.models import checkpoint as ckpt_io
from ncnet_tpu.models.ncnet import init_ncnet
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.observability import get_logger
from ncnet_tpu.observability.device import DeviceMonitor, Heartbeat
from ncnet_tpu.observability.events import EventLog
from ncnet_tpu.observability.metrics import (
    MetricsRegistry,
    device_peak_tflops,
    train_step_flops,
)
from ncnet_tpu.observability.tracing import span
from ncnet_tpu.training.loss import (
    auto_accum_chunks,
    weak_loss,
    weak_loss_and_grads,
)
from ncnet_tpu.utils import faults
from ncnet_tpu.utils.profiling import (
    StepWindowTracer,
    annotate,
    maybe_trace,
    profile_step_window,
)

log = get_logger("training")


class TrainDivergedError(RuntimeError):
    """``max_bad_steps`` consecutive non-finite losses: the run is diverging
    (or its data is systematically poisoned), so continuing to skip updates
    would only burn accelerator time.  Params/opt state are NOT corrupted —
    every bad update was kept out by the NaN guard."""


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray  # scalar int32


def trainable_labels(config: ModelConfig, params, fe_finetune_params: int = 0):
    """'trainable'/'frozen' labels: consensus stack always trains; the last
    ``fe_finetune_params`` backbone blocks optionally join
    (train.py:60-63)."""
    return {
        "backbone": bb.finetune_labels(
            config.backbone, params["backbone"], fe_finetune_params
        ),
        "nc": jax.tree.map(lambda _: "trainable", params["nc"]),
    }


def make_optimizer(labels):
    """Returns an ``lr → GradientTransformation`` factory bound to the
    trainable/frozen label tree."""

    def tx(lr: float) -> optax.GradientTransformation:
        return optax.multi_transform(
            {"trainable": optax.adam(lr), "frozen": optax.set_to_zero()}, labels
        )

    return tx


def create_train_state(
    config: TrainConfig, key: Optional[jax.Array] = None
) -> Tuple[TrainState, optax.GradientTransformation, ModelConfig, Any]:
    """Init (or load from ``config.model.checkpoint``) params + fresh Adam.

    Returns ``(state, optimizer, model_config, labels)``."""
    model_config = config.model
    if model_config.checkpoint:
        model_config, params = ckpt_io.load_params(
            model_config.checkpoint, model_config
        )
    else:
        params = init_ncnet(model_config, key or jax.random.key(config.seed))
    if config.finetune_cp_rank > 0:
        # CP fine-tune (ISSUE 17, the Lebedev et al. recovery recipe):
        # decompose every NC kernel to rank-R factors and train THEM with
        # the trunk frozen.  nc_tier='cp' forces the forward/backward
        # through the CP chain regardless of the chooser's FLOP gate —
        # gate-dependent routing would silently zero the factor gradients
        # wherever the dense tiers win.  The dense kernels ride along
        # (zero grads → Adam no-op) so checkpoints stay dense-servable.
        if config.fe_finetune_params > 0:
            raise ValueError(
                "finetune_cp_rank fine-tunes CP factors with the trunk "
                "frozen (the paper's recipe); it is incompatible with "
                "fe_finetune_params > 0"
            )
        from ncnet_tpu.ops.cp_als import decompose_stack

        params = dict(params)
        params["nc"], cp_errs = decompose_stack(
            params["nc"], config.finetune_cp_rank)
        model_config = model_config.replace(nc_tier="cp")
        log.info(
            f"CP fine-tune: rank {config.finetune_cp_rank}, per-layer "
            f"reconstruction error {[round(e, 4) for e in cp_errs]}"
        )
        if not config.model.checkpoint:
            log.warning(
                "finetune_cp_rank without model.checkpoint decomposes a "
                "RANDOM init — sensible only for smoke tests"
            )
    labels = trainable_labels(model_config, params, config.fe_finetune_params)
    optimizer = make_optimizer(labels)(config.lr)
    state = TrainState(params, optimizer.init(params), jnp.asarray(0, jnp.int32))
    return state, optimizer, model_config, labels


def make_train_step(
    model_config: ModelConfig,
    optimizer,
    donate: bool = True,
    stop_backbone_grad: bool = False,
    remat_nc_layers: bool = False,
    nc_custom_grad: bool = False,
    fold_pos_neg: bool = False,
    remat_filter: bool = True,
    accum_chunks: int = 0,
    nan_guard: bool = False,
    nc_pallas_vjp: bool = True,
    with_grad_norm: bool = False,
):
    """Jitted (state, batch) → (state, loss).  Returned as a
    :class:`~ncnet_tpu.models.ncnet.ResilientJit` so ``fit``'s device-
    failure recovery can drop the compiled cache after a tier demotion
    (and so the fault-injection harness has a dispatch seam,
    label ``"train_step"``).

    ``nan_guard=True`` adds an in-graph non-finite detector over the loss
    AND the update tree (a backward overflow can produce non-finite grads
    under a finite loss): when either is non-finite the whole update
    (params AND Adam moments/count) is dropped and the previous state
    carried forward, so one poisoned batch cannot contaminate optimizer
    state for every remaining step.  The step
    counter still advances (it counts consumed batches, keeping step
    numbering — and therefore checkpoint version names and resume positions
    — deterministic regardless of how many steps were skipped).  The loss is
    returned as computed so the host can count/log the skip.

    Pass ``stop_backbone_grad=True`` when no backbone blocks are being
    finetuned (``fe_finetune_params == 0``, the reference default): the trunk
    is detached, matching the reference's frozen-FE training and keeping the
    backward pass off the trunk activations entirely — required to fit the
    reference batch sizes at 400² on one chip.  It must stay False when
    finetuning, so False is the (safe) default; ``fit`` derives it from the
    config.

    ``accum_chunks != 0`` (frozen trunk only) switches to
    :func:`ncnet_tpu.training.loss.weak_loss_and_grads` — exact
    volume-chunked gradient accumulation, the fastest path and the one that
    fits/compiles any batch size (see its docstring for the measurements);
    ``-1`` = auto chunk choice.

    ``nc_pallas_vjp`` (round 7 default): route the NC filter through the
    fused Pallas forward + resident Pallas backward where the shape class
    compiles (see :func:`ncnet_tpu.training.loss.weak_loss`); ineligible
    configurations keep the XLA formulations unchanged.

    ``with_grad_norm=True`` (telemetry, round 8): the step additionally
    returns the global L2 grad norm — ``(state, loss, grad_norm)`` instead
    of ``(state, loss)`` — computed in-graph (one extra reduction over the
    grad tree, negligible next to the filter) so the per-step metrics scope
    can record it without a second backward.  Default off: the two-tuple
    signature is the public one."""

    if accum_chunks != 0 and not stop_backbone_grad:
        raise ValueError(
            "accum_chunks requires the frozen trunk (fe_finetune_params=0): "
            "chunked accumulation detaches the features"
        )

    def step(state: TrainState, batch):
        if accum_chunks != 0:
            # the memory knobs pass through (fold_pos_neg/remat_filter do
            # not apply: chunking already bounds the live volume set)
            loss, grads = weak_loss_and_grads(
                model_config, state.params, batch, accum_chunks=accum_chunks,
                remat_nc_layers=remat_nc_layers,
                nc_custom_grad=nc_custom_grad,
                nc_pallas_vjp=nc_pallas_vjp,
            )
        else:
            loss, grads = jax.value_and_grad(
                lambda p: weak_loss(
                    model_config, p, batch,
                    stop_backbone_grad=stop_backbone_grad,
                    remat_nc_layers=remat_nc_layers,
                    nc_custom_grad=nc_custom_grad,
                    fold_pos_neg=fold_pos_neg,
                    remat_filter=remat_filter,
                    nc_pallas_vjp=nc_pallas_vjp,
                )
            )(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        if nan_guard:
            # loss finiteness alone is not enough: a backward overflow can
            # produce non-finite updates under a finite loss, which would
            # poison params while the guard looks the other way — AND in
            # the whole update tree (the optax.apply_if_finite discipline)
            ok = jnp.isfinite(loss)
            for u in jax.tree.leaves(updates):
                ok = ok & jnp.all(jnp.isfinite(u))
            params = jax.tree.map(
                lambda new, old: jnp.where(ok, new, old), params, state.params
            )
            opt_state = jax.tree.map(
                lambda new, old: jnp.where(ok, new, old),
                opt_state, state.opt_state,
            )
            # report NaN for any rejected step so host-side skip counting
            # and the epoch-mean exclusion see EVERY skip, including the
            # finite-loss/non-finite-grads case
            loss = jnp.where(ok, loss, jnp.nan)
        new_state = TrainState(params, opt_state, state.step + 1)
        if with_grad_norm:
            return new_state, loss, optax.global_norm(grads)
        return new_state, loss

    from ncnet_tpu.models.ncnet import ResilientJit

    def _batch_shape_key(state, batch):
        # key on the BATCH alone (params/opt shapes are constant within a
        # process): a handful of leaves instead of the full state pytree —
        # this runs on every step dispatch, so it must stay cheap
        from ncnet_tpu.observability.memory import shape_class

        return shape_class(batch)

    return ResilientJit(
        step, label="train_step",
        # compiled-program memory ledger (observability/memory.py): the
        # train step's footprint — temp bytes ARE the backward's working
        # set, the quantity the remat/custom-grad knobs exist to shrink
        ledger_program="train_step",
        ledger_key_fn=_batch_shape_key,
        donate_argnums=(0,) if donate else ())


def make_eval_step(model_config: ModelConfig):
    return jax.jit(lambda params, batch: weak_loss(model_config, params, batch))


def process_epoch(
    mode: str,
    epoch: int,
    state: TrainState,
    step_fn,
    loader: DataLoader,
    log_interval: int = 1,
    put_batch=None,
    step_base: int = 0,
    on_step: Optional[Callable[[int, TrainState, jnp.ndarray], bool]] = None,
    telemetry_ctx: Optional[Dict[str, Any]] = None,
) -> Tuple[TrainState, float]:
    """One pass over ``loader``; mirrors the reference's per-batch logging
    (train.py:161-181).  ``put_batch`` maps a host array onto devices
    (defaults to plain transfer; the data-parallel path shards the pair
    axis).

    Mid-epoch resume: the loader's ``start_batch`` (set via
    ``loader.set_epoch(epoch, start_batch=...)``) is the single source of
    the skip — this function reads it back for global batch indexing, so
    logging and checkpoint positions stay aligned with the full epoch.

    ``on_step(batch_idx, state, loss)`` runs after every train step (NaN
    accounting, periodic/preemption checkpoints live in ``fit``'s closure);
    returning True ends the epoch early.  ``step_base`` is the host-side
    global step count entering this epoch (used to address fault-injection
    hooks without a device sync).  Non-finite losses are excluded from the
    epoch mean (and counted), so one guarded-away batch does not wipe out
    the epoch statistic.

    Host→device transfer is DOUBLE-BUFFERED (round 7): batch N+1 is staged
    (``put_batch`` — an async ``device_put`` on TPU) right after step N is
    dispatched and BEFORE the per-step loss sync, so the upload rides
    behind the device's step compute instead of serializing in front of
    step N+1.  The staging order is the only change: logging, ``on_step``
    accounting, and checkpoint positions still run per batch in order, and
    an early stop (preemption) simply discards the staged batch — the
    position cursor marks it unconsumed, so resume re-delivers it from the
    epoch-keyed shuffle.

    Telemetry (round 8): every train step emits a ``step`` event to the
    bound observability sink — loss, step wall, host→device staging wall,
    throughput pairs/s, and (when the step was built with
    ``with_grad_norm``) the global grad norm.  ``telemetry_ctx`` carries
    the optional extras ``fit`` precomputes: ``flops_per_pair`` +
    ``peak_tflops`` (the 6×-filter-FLOP MFU basis — emitted as ``mfu_pct``
    when both are known), a ``tracer`` (:class:`StepWindowTracer`, fed each
    global step number), and a ``registry``
    (:class:`~ncnet_tpu.observability.metrics.MetricsRegistry` accumulating
    the same numbers for the epoch-end ``metrics`` flush).  With no sink
    bound and no ctx the loop's only extra work is two ``perf_counter``
    reads per step.
    """
    put_batch = put_batch or jnp.asarray
    ctx = telemetry_ctx or {}
    tracer: Optional[StepWindowTracer] = ctx.get("tracer")
    registry: Optional[MetricsRegistry] = ctx.get("registry")
    n = len(loader)
    if n == 0:
        raise ValueError(
            f"{mode} loader is empty (dataset smaller than batch_size with "
            "drop_last) — refusing to report a fake 0.0 epoch loss"
        )
    start_batch = getattr(loader, "start_batch", 0)
    if start_batch:
        log.info(f"{mode.capitalize()} Epoch: {epoch} resuming at batch "
                 f"{start_batch}/{n}")
    losses = []  # device scalars; only synced at log points / epoch end

    def stage(off, batch):
        if mode == "train":
            batch = faults.corrupt_batch_hook(batch, step_base + off + 1)
        t0 = time.perf_counter()
        with span("stage", mode=mode, step=step_base + off + 1):
            staged_batch = {
                "source_image": put_batch(batch["source_image"]),
                "target_image": put_batch(batch["target_image"]),
            }
        stage_walls[0] = time.perf_counter() - t0
        return staged_batch

    stage_walls = [0.0]  # wall of the most recent stage() call
    it = enumerate(loader)
    nxt = next(it, None)
    staged = stage(*nxt) if nxt is not None else None
    while nxt is not None:
        off, _ = nxt
        batch_idx = start_batch + off
        gstep = step_base + off + 1  # global step about to run (train mode)
        stage_wall, stage_walls[0] = stage_walls[0], 0.0
        images, staged = staged, None
        if tracer is not None and mode == "train":
            tracer.at_step(gstep)
        t_step = time.perf_counter()
        grad_norm = None
        # the per-step parent span: dispatch / stage(N+1) / loss-sync — and
        # any checkpoint commit inside on_step — nest under it, so the trace
        # (and run_report --spans) can split step wall into its phases
        with span(f"{mode}_step", step=gstep, batch=batch_idx):
            with annotate(f"{mode}_step"), \
                    span("dispatch", mode=mode, step=gstep):
                if mode == "train":
                    out = step_fn(state, images)
                    if len(out) == 3:
                        state, loss, grad_norm = out
                    else:
                        state, loss = out
                else:
                    loss = step_fn(state.params, images)
            # stage batch N+1 while step N runs on device (the loader's own
            # prefetch thread has usually decoded it already; this overlaps
            # the host→device leg too), then sync the loss for logging/guards
            nxt = next(it, None)
            if nxt is not None:
                staged = stage(*nxt)
            losses.append(loss)
            if batch_idx % log_interval == 0:
                log.info(
                    f"{mode.capitalize()} Epoch: {epoch} [{batch_idx}/{n} "
                    f"({100.0 * batch_idx / n:.0f}%)]\t\tLoss: "
                    f"{float(loss):.6f}"
                )
            if mode == "train" and obs_events.get_global_sink() is not None:
                # the loss sync above (or float() here) bounds the step wall;
                # without the nan_guard's eager fetch this wall includes
                # async dispatch only — still the honest host-side cadence
                with span("loss_sync", step=gstep):
                    loss_f = float(loss)
                wall = time.perf_counter() - t_step
                # .shape is the GLOBAL batch shape even for sharded/
                # multi-host arrays — never materialize the batch on host
                # just to count it
                pairs = int(images["source_image"].shape[0]) \
                    if hasattr(images["source_image"], "shape") else 0
                fields: Dict[str, Any] = {
                    "mode": mode, "epoch": epoch, "batch": batch_idx,
                    "step": gstep, "loss": loss_f,
                    "wall_s": round(wall, 6),
                    "stage_wall_s": round(stage_wall, 6),
                }
                if pairs and wall > 0:
                    fields["pairs_per_s"] = round(pairs / wall, 3)
                if grad_norm is not None:
                    fields["grad_norm"] = float(grad_norm)
                flops = ctx.get("flops_per_pair")
                peak = ctx.get("peak_tflops")
                if flops and peak and pairs and wall > 0:
                    fields["mfu_pct"] = round(
                        100.0 * (flops * pairs / wall / 1e12) / peak, 2)
                obs_events.emit("step", **fields)
                # per-step weak-loss health signal: the pos/neg score gap
                # (score(pos) − score(neg) = −loss, since the weak loss is
                # score(neg) − score(pos)).  A healthy run's gap GROWS; a
                # low-precision tier regression or poisoned data shrinks it
                # long before a labeled eval would notice.  Emitted as a
                # `quality` event tagged with the active fused tier and
                # digested in the registry, exactly like the eval signals.
                if math.isfinite(loss_f):
                    from ncnet_tpu.observability.quality import (
                        active_tier,
                        emit_quality,
                    )

                    emit_quality(
                        "train", {"score_gap": [-loss_f]},
                        # training's tier is the BACKWARD chooser's (the
                        # step runs the fused stack only where the Pallas
                        # VJP engages); eligibility rides in from fit's
                        # model config — an fp32 step is xla by definition
                        tier=active_tier(ctx.get("nc_bf16", False),
                                         stage="backward"),
                        registry=registry, step=gstep, epoch=epoch,
                    )
                if registry is not None:
                    registry.timer("step_wall").observe(wall)
                    registry.timer("stage_wall").observe(stage_wall)
                    registry.gauge("loss").set(loss_f)
                    if "pairs_per_s" in fields:
                        registry.gauge("pairs_per_s").set(
                            fields["pairs_per_s"])
                    if "mfu_pct" in fields:
                        registry.gauge("mfu_pct").set(fields["mfu_pct"])
                    if grad_norm is not None:
                        registry.gauge("grad_norm").set(float(grad_norm))
            stop_now = (on_step is not None
                        and on_step(batch_idx, state, loss))
        if stop_now:
            break
    if not losses:
        # a resume position at the very end of an epoch: nothing left to do
        log.info(f"{mode.capitalize()} set: no batches past resume position "
                 f"{start_batch}/{n}")
        return state, float("nan")
    arr = jnp.stack(losses)
    if mode == "train":
        # guarded-away (non-finite) steps must not wipe out the epoch
        # statistic.  TRAIN ONLY: a val batch with a non-finite loss means
        # the model itself misbehaves on part of the val set — its epoch
        # mean must stay NaN so it can never be crowned best_
        finite = jnp.isfinite(arr)
        n_bad = int(jnp.sum(~finite))
        if n_bad:
            log.info(f"{mode.capitalize()} set: excluded {n_bad} non-finite "
                     f"step loss(es) from the epoch mean")
        epoch_loss = float(jnp.nanmean(jnp.where(finite, arr, jnp.nan)))
    else:
        epoch_loss = float(jnp.mean(arr))
    log.info(f"{mode.capitalize()} set: Average loss: {epoch_loss:.4f}")
    return state, epoch_loss


# ---------------------------------------------------------------------------
# checkpointing (full train state)
# ---------------------------------------------------------------------------


def _sync_processes(tag: str) -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_processes(tag)


def save_train_checkpoint(
    path: str,
    config: TrainConfig,
    model_config: ModelConfig,
    state: TrainState,
    epoch: int,
    train_loss: np.ndarray,
    test_loss: np.ndarray,
    is_best: bool,
    *,
    step: Optional[int] = None,
    position: Optional[Dict[str, int]] = None,
    keep: int = 0,
    io_retries: int = 3,
    io_retry_backoff: float = 0.5,
) -> str:
    """Atomic, versioned checkpoint: write ``<path>/step_<N>.tmp``, commit by
    rename.  On improvement the committed version is also copied flat to
    ``best_<name>`` beside the root (torch_util.py:48-61).  Returns the
    committed version directory.

    ``path`` is the versioned ROOT (see the module docstring for the
    layout).  ``step`` names the version (defaults to ``state.step`` — pass
    the host-side counter to avoid a device sync); ``position`` is the
    resume cursor stored as ``_position``; ``keep > 0`` prunes all but the
    newest ``keep`` complete versions after the commit.  A crash at any
    point leaves every previously committed version intact: the in-progress
    ``.tmp`` is skipped by loaders and reclaimed by the next save.

    Each version's layout is a superset of
    :func:`ncnet_tpu.models.checkpoint.save_params`: ``config.json`` carries
    the ModelConfig fields at top level (plus train metadata under
    ``_train``/``_epoch``/``_position``/loss keys) and the weights live in a
    ``params/`` subtree — so ``load_params`` (and therefore eval/finetune
    ``--checkpoint``) reads a training checkpoint directly.  Optimizer state
    + step go in a separate ``opt/`` subtree for
    :func:`load_train_checkpoint`.

    Multi-process: EVERY process must call this — the orbax saves are
    collective (``sync_global_processes`` inside ``save``; gating them on
    process 0 deadlocks the job, caught by the two-process smoke test), and
    the version name must be computed from replicated state (the host step
    counter), never from clocks.  Orbax itself writes array data from the
    primary host only; the non-collective extras (config.json, the commit
    rename, retention pruning, the ``best_`` copy) are primary-only here,
    with a cross-process barrier before the commit so no process can observe
    a half-written version.  I/O retries are disabled multi-process
    (``with_io_retries``): one host re-entering a collective save alone
    would deadlock the job.
    """
    import orbax.checkpoint as ocp

    primary = jax.process_index() == 0
    root = os.path.abspath(path)
    os.makedirs(root, exist_ok=True)
    n = int(step) if step is not None else int(jax.device_get(state.step))
    final = os.path.join(root, ckpt_io.checkpoint_version_name(n))
    tmp = final + ".tmp"
    if primary:
        # reclaim carcasses of crashed saves (fit is the root's sole writer):
        # .tmp = uncommitted replacement, always dropped; .old = the
        # displaced original of a same-step re-save — restored when the
        # replacement's commit rename never happened, dropped otherwise
        for name in os.listdir(root):
            full = os.path.join(root, name)
            if name.endswith(".tmp"):
                shutil.rmtree(full, ignore_errors=True)
            elif name.endswith(".old"):
                committed = os.path.join(root, name[:-4])
                if os.path.isdir(committed):
                    shutil.rmtree(full, ignore_errors=True)
                else:
                    os.rename(full, committed)
        os.makedirs(tmp, exist_ok=True)
        with open(os.path.join(tmp, "config.json"), "w") as f:
            json.dump(
                {
                    **dataclasses.asdict(model_config),
                    "_train": {
                        k: v
                        for k, v in dataclasses.asdict(config).items()
                        if k != "model"
                    },
                    "_epoch": epoch,
                    "_step": n,
                    "_position": position,
                    # non-finite entries (a resumed epoch whose train phase
                    # was already consumed) serialize as null, keeping
                    # config.json strict JSON; load maps null back to NaN
                    "_train_loss": [
                        float(v) if math.isfinite(v) else None
                        for v in train_loss
                    ],
                    "_test_loss": [
                        float(v) if math.isfinite(v) else None
                        for v in test_loss
                    ],
                    # payload identity: lets resume/rollout refuse a
                    # bit-rotted or torn copy instead of training/serving
                    # on silently-wrong weights
                    ckpt_io.PAYLOAD_SHA_KEY:
                        ckpt_io.params_payload_sha256(state.params),
                },
                f,
                indent=2,
                default=list,
            )
    ckptr = ocp.StandardCheckpointer()

    def _save(subdir, tree):
        ckpt_io.with_io_retries(
            lambda: (ckptr.save(os.path.join(tmp, subdir), tree, force=True),
                     ckptr.wait_until_finished()),
            attempts=io_retries, backoff=io_retry_backoff,
            what=f"save of {tmp}/{subdir}",
        )

    _save("params", state.params)
    faults.kill_mid_save_hook(n)  # no-op unless a test armed it
    _save("opt", {"opt_state": state.opt_state, "step": state.step})
    # all processes must have finished their collective part before the
    # primary commits (a rename concurrent with a straggler's save window
    # could publish a version that is still being written)
    _sync_processes(f"ncnet_ckpt_commit_{n}")
    if primary:
        with annotate("checkpoint_commit"), span("checkpoint_commit", step=n):
            if os.path.isdir(final):
                # re-save at the same step (an epoch-end save landing on a
                # periodic-save step): replace the old version, still
                # leaving a complete directory at every instant
                stale = final + ".old"
                shutil.rmtree(stale, ignore_errors=True)
                os.rename(final, stale)
                os.rename(tmp, final)
                shutil.rmtree(stale, ignore_errors=True)
            else:
                os.rename(tmp, final)  # THE commit point
            if keep > 0:
                for _, old in ckpt_io.list_checkpoint_versions(root)[:-keep]:
                    shutil.rmtree(old, ignore_errors=True)
            if is_best:
                best = os.path.join(
                    os.path.dirname(root), "best_" + os.path.basename(root)
                )
                if os.path.isdir(best):
                    shutil.rmtree(best)
                shutil.copytree(final, best)
        obs_events.emit(
            "checkpoint_commit", step=n, path=final, epoch=epoch,
            position=position, best=bool(is_best),
        )
    return final


def load_train_checkpoint(
    path: str,
    state_like: TrainState,
    io_retries: int = 3,
    io_retry_backoff: float = 0.5,
):
    """Restore a full train state (params + optimizer + step) for resume —
    the capability the reference saves for but never implements
    (train.py:71 creates a fresh Adam; ``checkpoint['optimizer']`` is never
    read).

    ``path`` may be a versioned root (resolved to its newest COMPLETE
    version — in-progress ``.tmp`` saves are never considered), a single
    ``step_<N>`` version, or a legacy flat checkpoint.  Returns ``(state,
    epoch, train_loss, test_loss, position)`` where ``epoch`` counts fully
    completed epochs and ``position`` is the ``{"epoch": E, "next_batch":
    B}`` resume cursor (synthesized as epoch-start for checkpoints predating
    mid-epoch saves)."""
    import orbax.checkpoint as ocp

    path = ckpt_io.resolve_checkpoint_dir(path)
    ckptr = ocp.StandardCheckpointer()
    params = ckpt_io.with_io_retries(
        lambda: ckptr.restore(
            os.path.join(path, "params"), target=state_like.params
        ),
        attempts=io_retries, backoff=io_retry_backoff,
        what=f"restore of {path}/params",
    )
    opt = ckpt_io.with_io_retries(
        lambda: ckptr.restore(
            os.path.join(path, "opt"),
            target={"opt_state": state_like.opt_state, "step": state_like.step},
        ),
        attempts=io_retries, backoff=io_retry_backoff,
        what=f"restore of {path}/opt",
    )
    with open(os.path.join(path, "config.json")) as f:
        meta = json.load(f)
    # resume refuses a checkpoint whose params no longer hash to the sha
    # recorded at commit (legacy checkpoints without the key pass through)
    expect = meta.get(ckpt_io.PAYLOAD_SHA_KEY)
    if expect and ckpt_io.params_payload_sha256(params) != expect:
        raise ckpt_io.CheckpointPayloadError(
            f"training checkpoint {path!r} payload sha256 mismatch — "
            "refusing to resume from a corrupt/torn params payload")
    state = TrainState(params, opt["opt_state"], opt["step"])
    position = meta.get("_position") or {
        "epoch": meta["_epoch"] + 1, "next_batch": 0
    }
    return (
        state,
        meta["_epoch"],
        # null entries (non-finite at save time) come back as NaN
        np.asarray(meta["_train_loss"], dtype=np.float64),
        np.asarray(meta["_test_loss"], dtype=np.float64),
        position,
    )


class PreemptionHandler:
    """SIGTERM/SIGINT → "checkpoint at the next step boundary, then stop".

    Installed around the fit epoch loop.  The handler only flips a flag —
    the train loop notices it between steps, writes a final checkpoint (with
    the exact resume position) and returns cleanly with
    ``result["preempted"]``.  A second SIGINT raises KeyboardInterrupt
    immediately (the operator escape hatch).  Installation is skipped off
    the main thread (``signal.signal`` would raise) and previous handlers
    are always restored.

    Multi-process: each host observes only its own signal; real preemption
    (GCE/TPU maintenance) delivers SIGTERM to every host.  The stop decision
    is still agreed collectively — ``fit`` ORs the flags across hosts at
    checkpoint/epoch boundaries (``_global_any``) so one host can never
    enter a collective save alone.
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        self.requested = False
        self.signum: Optional[int] = None
        self._old: Dict[int, Any] = {}

    def _handle(self, signum, frame):
        if self.requested and signum == signal.SIGINT:
            raise KeyboardInterrupt
        self.requested = True
        self.signum = signum
        # os.write, not print: a buffered flush interrupted by the signal
        # can replay its buffer (duplicated log lines), and print() from a
        # handler can deadlock on the interrupted stream's lock
        os.write(2, (f"[fault-tolerance] received "
                     f"{signal.Signals(signum).name}; will checkpoint at "
                     "the next step boundary and stop\n").encode())

    def __enter__(self) -> "PreemptionHandler":
        if threading.current_thread() is threading.main_thread():
            for s in self.SIGNALS:
                self._old[s] = signal.signal(s, self._handle)
        return self

    def __exit__(self, *exc):
        for s, old in self._old.items():
            signal.signal(s, old)
        self._old = {}
        return False


def _global_any(flag: bool) -> bool:
    """OR a host-local flag across processes (identity single-process).
    Collective — in multi-process mode call it only at points every process
    reaches (checkpoint/epoch boundaries)."""
    if jax.process_count() <= 1:
        return flag
    from jax.experimental import multihost_utils

    got = multihost_utils.process_allgather(np.asarray([flag], np.int32))
    return bool(np.any(got))


def _resolve_accum_chunks(config: TrainConfig, n_dev: int) -> int:
    """Chunked accumulation needs the frozen trunk: the auto default (-1)
    quietly falls back to the whole-batch backward when finetuning, but an
    EXPLICIT chunk count with finetuning is a contradiction the user must
    resolve (the same combination raises in make_train_step)."""
    if config.fe_finetune_params > 0:
        if config.accum_chunks > 0:
            raise ValueError(
                f"accum_chunks={config.accum_chunks} requires the frozen "
                "trunk, but fe_finetune_params="
                f"{config.fe_finetune_params} finetunes backbone blocks; "
                "drop one of the two settings"
            )
        return 0
    if config.accum_chunks == -1:
        return auto_accum_chunks(config.batch_size, n_dev)
    if config.accum_chunks < 0:
        raise ValueError(
            f"accum_chunks={config.accum_chunks}: use -1 (auto), 0 (off) or "
            "a positive chunk count"
        )
    if config.accum_chunks and (2 * config.batch_size) % config.accum_chunks:
        raise ValueError(
            f"accum_chunks={config.accum_chunks} must divide "
            f"2*batch_size={2 * config.batch_size}"
        )
    if config.accum_chunks and n_dev > 1:
        chunk = (2 * config.batch_size) // config.accum_chunks
        if chunk % n_dev:
            # a chunk that doesn't divide over the data mesh forces GSPMD to
            # reshard/gather the volume every scan iteration — reject loudly
            # rather than silently running the slow program
            raise ValueError(
                f"accum_chunks={config.accum_chunks} gives chunk size "
                f"{chunk}, which does not divide over {n_dev} data-parallel "
                f"devices; pick a count where (2*batch_size/accum_chunks) % "
                f"n_devices == 0, or use -1 (auto)"
            )
    return config.accum_chunks


# ---------------------------------------------------------------------------
# fit: the whole reference train.py flow
# ---------------------------------------------------------------------------


def fit(config: TrainConfig, progress: bool = True) -> Dict[str, Any]:
    """Train per the reference recipe: epochs over train_pairs.csv, val loss
    on val_pairs.csv each epoch, checkpoint every epoch + best copy."""
    shard_kwargs = {}
    local_batch = config.batch_size
    if config.distributed:
        from ncnet_tpu.parallel import host_shard, initialize_distributed

        initialize_distributed()
        shard_kwargs = host_shard()
        n_procs = shard_kwargs["num_shards"]
        if n_procs > 1:
            if not config.data_parallel:
                # each host would silently train its own diverging model
                raise ValueError(
                    "distributed=True across multiple processes requires "
                    "data_parallel=True (there is no gradient sync otherwise)"
                )
            if config.batch_size % n_procs:
                raise ValueError(
                    f"batch_size {config.batch_size} must divide evenly over "
                    f"{n_procs} processes"
                )
            # batch_size stays the reference's GLOBAL batch; each host loads
            # its slice and the global array is assembled across processes
            local_batch = config.batch_size // n_procs
        if progress:
            log.info(f"Distributed: process {shard_kwargs['shard_index']} of "
                     f"{n_procs}")

    state, optimizer, model_config, labels = create_train_state(config)

    # resume: a checkpoint written by fit() carries opt/ — restore the full
    # train state (params + optimizer + step + loader position).  A root of
    # step_<N> versions resolves to its newest COMPLETE version; ``.tmp``
    # carcasses from a crash mid-save are never considered.
    start_epoch = 0
    prev_train = prev_test = None
    resume_epoch: Optional[int] = None
    resume_batch = 0
    resume_root = None
    ckpt = config.model.checkpoint
    resolved = (
        ckpt_io.resolve_checkpoint_dir(ckpt)
        if ckpt and os.path.isdir(ckpt) else ""
    )
    if resolved and os.path.isdir(os.path.join(resolved, "opt")):
        # pass the resolved version (not the raw path) so this is the ONE
        # point of version selection — load_train_checkpoint's own resolve
        # is then the identity
        state, start_epoch, prev_train, prev_test, position = (
            load_train_checkpoint(
                resolved, state, io_retries=config.io_retries,
                io_retry_backoff=config.io_retry_backoff,
            )
        )
        resume_epoch = int(position["epoch"])
        resume_batch = int(position["next_batch"])
        # resumed from our own versioned output: keep writing new versions
        # into the SAME root (crash/preempt/restart cycles share one lineage)
        resume_root = ckpt_io.owning_checkpoint_root(resolved)
        if progress:
            log.info(f"Resumed full train state from {resolved}: "
                     f"{start_epoch} completed epoch(s), position epoch "
                     f"{resume_epoch} batch {resume_batch}")

    n_trainable = sum(
        int(np.prod(np.asarray(x.shape)))
        for x, lbl in zip(jax.tree.leaves(state.params), jax.tree.leaves(labels))
        if lbl == "trainable"
    )
    if progress:
        log.info(f"Trainable parameters: {n_trainable:,}")

    # data parallelism: shard the pair axis over every device, replicate
    # params; jit + shardings make XLA psum the grads and route the
    # negative-roll permute over ICI (loss.py docstring)
    put_batch = None
    # largest device count that evenly divides the batch (all devices when
    # batch_size % len(devices) == 0, e.g. the reference's 16 on 8 chips)
    n_dev = max(
        d for d in range(1, min(len(jax.devices()), config.batch_size) + 1)
        if config.batch_size % d == 0
    )
    if config.data_parallel and n_dev > 1:
        if not config.val_drop_last:
            # a partial trailing val batch cannot be device_put with the
            # pair-axis sharding (batch size must divide the device count),
            # and padding it would perturb the in-batch negative roll
            raise ValueError(
                "val_drop_last=False is incompatible with data_parallel "
                "across multiple devices; disable one of the two"
            )
        from ncnet_tpu import parallel

        mesh = parallel.make_mesh(data=n_dev, devices=jax.devices()[:n_dev])
        # replicate the WHOLE state (step included): restored checkpoints are
        # committed to device 0 and would otherwise conflict with the mesh
        state = TrainState(*parallel.replicate(mesh, tuple(state)))
        sharding = parallel.batch_sharding(mesh)
        if jax.process_count() > 1:
            # each process holds only its host-local rows; assemble the
            # global batch array from per-process slices (device_put would
            # treat the local slice as the global value and drop data)
            put_batch = lambda x: jax.make_array_from_process_local_data(  # noqa: E731
                sharding, np.asarray(x)
            )
        else:
            put_batch = lambda x: jax.device_put(jnp.asarray(x), sharding)  # noqa: E731
        if progress:
            log.info(f"Data parallel over {n_dev} devices (mesh {mesh.shape})")

    accum = _resolve_accum_chunks(config, n_dev if config.data_parallel else 1)
    if progress and accum:
        log.info(f"Gradient accumulation: {accum} chunks of "
                 f"{2 * config.batch_size // accum} volumes")
    # telemetry EMISSION is primary-only (one event log per run, not per
    # process), but the grad-norm output is part of the jitted program,
    # which must be identical on every process of a multi-controller run —
    # so the step shape follows config.telemetry alone and non-primary
    # processes drop the extra output unread
    want_telemetry = config.telemetry and jax.process_index() == 0
    train_step = make_train_step(
        model_config, optimizer, donate=config.donate_state,
        stop_backbone_grad=config.fe_finetune_params == 0,
        remat_nc_layers=config.remat_nc_layers,
        nc_custom_grad=config.nc_custom_grad,
        fold_pos_neg=config.fold_pos_neg,
        remat_filter=config.remat_filter,
        accum_chunks=accum,
        nan_guard=config.nan_guard,
        nc_pallas_vjp=config.nc_pallas_vjp,
        with_grad_norm=config.telemetry,
    )

    def guarded_train_step(state, images):
        """The training twin of the eval loops' tier-degradation recovery:
        a runtime device failure inside the jitted step demotes the Pallas
        BACKWARD tier first (``resident_vjp`` — the tier only training
        runs), drops the compiled cache, and retries once per demotion so
        the run continues on the surviving tier.  Caveat: with donated
        state, a failure that fired mid-execution (not at the injection
        seam) may have consumed the input buffers — the retry then raises
        and the normal crash/resume machinery takes over; nothing is made
        worse than the pre-recovery behavior."""
        from ncnet_tpu.models.ncnet import (
            RUNTIME_DEVICE_ERRORS,
            recover_from_device_failure,
        )

        while True:
            try:
                return train_step(state, images)
            except RUNTIME_DEVICE_ERRORS as e:
                tier = recover_from_device_failure(
                    e, train_step, prefer_tier="resident_vjp")
                if tier is None:
                    raise

    eval_step = make_eval_step(model_config)

    decode_policy = (
        "quarantine" if config.quarantine_decode_errors else "raise"
    )
    size = (config.image_size, config.image_size)
    train_loader = DataLoader(
        ImagePairDataset(
            config.dataset_csv_path, "train_pairs.csv", config.dataset_image_path,
            output_size=size, seed=config.seed,
            decode_retries=config.decode_retries,
        ),
        batch_size=local_batch, shuffle=True,
        num_workers=config.num_workers, seed=config.seed, drop_last=True,
        on_decode_error=decode_policy,
        **shard_kwargs,
    )
    # val: no shuffle — with drop_last (config.val_drop_last), a shuffle
    # would drop a DIFFERENT random subset each epoch, making the
    # best-checkpoint metric noisy (the reference shuffles but drops nothing)
    val_loader = DataLoader(
        ImagePairDataset(
            config.dataset_csv_path, "val_pairs.csv", config.dataset_image_path,
            output_size=size, seed=config.seed,
            decode_retries=config.decode_retries,
        ),
        batch_size=local_batch, shuffle=False,
        num_workers=config.eval_num_workers, seed=config.seed,
        drop_last=config.val_drop_last,
        on_decode_error=decode_policy,
        **shard_kwargs,
    )

    if resume_root:
        ckpt_name = resume_root
    else:
        # the checkpoint path must agree across processes (orbax saves are
        # collective): stamp from process 0's clock, broadcast to the others.
        # Broadcast as (days, seconds-of-day) int32s — with x64 disabled a
        # float timestamp would be quantized to ~128 s and an int64 silently
        # truncated.
        stamp = time.time()
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            parts = multihost_utils.broadcast_one_to_all(
                np.asarray([int(stamp) // 86400, int(stamp) % 86400], np.int32)
            )
            stamp = float(int(parts[0]) * 86400 + int(parts[1]))
        ckpt_name = os.path.join(
            config.result_model_dir,
            # gmtime, not localtime: processes with differing TZ env would
            # format different paths from the same broadcast stamp and
            # re-diverge the collective save (ADVICE r3)
            time.strftime("%Y-%m-%d_%H:%M", time.gmtime(stamp))
            + "_" + config.result_model_fn,
        )
    if progress:
        log.info(f"Checkpoint name: {ckpt_name}")

    # --- telemetry: event log + heartbeat + device monitor (primary only).
    # The log lives under the checkpoint root by default, so crash/resume
    # cycles of one training lineage append to ONE file (each run under its
    # own run id) and tools/run_report.py reconstructs the whole history.
    telemetry: Optional[EventLog] = None
    prev_sink = None
    heartbeat: Optional[Heartbeat] = None
    dev_monitor: Optional[DeviceMonitor] = None
    train_registry: Optional[MetricsRegistry] = None
    step_tracer = StepWindowTracer(config.profile_dir)
    # the tracer rides along even without an event log: the profile-window
    # knob ($NCNET_TPU_PROFILE_STEPS) is orthogonal to telemetry
    telemetry_ctx: Dict[str, Any] = {"tracer": step_tracer}
    if want_telemetry:
        tdir = config.telemetry_dir or os.path.join(ckpt_name, "telemetry")
        try:
            telemetry = EventLog(
                os.path.join(tdir, "events.jsonl"),
                run_meta={"config": dataclasses.asdict(config)},
            )
        except OSError as e:
            # telemetry must never be the reason a run cannot start
            log.warning(f"could not open the event log under {tdir} ({e}); "
                        "continuing without telemetry", kind="io")
    if telemetry is not None:
        prev_sink = obs_events.set_global_sink(telemetry)
        heartbeat = Heartbeat(os.path.join(
            os.path.dirname(telemetry.path), "heartbeat.json"),
            run_id=telemetry.run_id)
        dev_monitor = DeviceMonitor()
        train_registry = MetricsRegistry(scope="train_step")
        telemetry_ctx.update(
            registry=train_registry,
            peak_tflops=device_peak_tflops(),
            # quality-event tier eligibility: the step can only have routed
            # through a fused Pallas tier when the NC stack ran bf16 with
            # the Pallas VJP permitted
            nc_bf16=bool(model_config.half_precision
                         and config.nc_pallas_vjp),
        )
        try:
            from ncnet_tpu.models.ncnet import extract_features

            feat = jax.eval_shape(
                lambda p, x: extract_features(model_config, p, x),
                state.params,
                jax.ShapeDtypeStruct(
                    (1, config.image_size, config.image_size, 3),
                    jnp.float32),
            )
            telemetry_ctx["flops_per_pair"] = train_step_flops(
                feat.shape[1], model_config.ncons_kernel_sizes,
                model_config.ncons_channels)
        except Exception:  # noqa: BLE001 — exotic trunks: no MFU, no crash
            pass
        # via the self-disabling global emit (the sink is bound above):
        # a failing append must never be the reason a run cannot start
        obs_events.emit(
            "run_start", envelope=obs_events.run_envelope(telemetry.run_id),
            checkpoint_root=ckpt_name, num_epochs=config.num_epochs,
            batch_size=config.batch_size, resumed=bool(resume_root),
        )
        if resume_root:
            obs_events.emit(
                "resume", checkpoint=resolved, completed_epochs=start_epoch,
                epoch=resume_epoch, batch=resume_batch,
                step=int(jax.device_get(state.step)),
            )

    train_loss = np.zeros(config.num_epochs)
    test_loss = np.zeros(config.num_epochs)
    best = float("inf")
    if prev_train is not None and start_epoch > 0:
        n_keep = min(start_epoch, config.num_epochs)
        train_loss[:n_keep] = prev_train[:n_keep]
        test_loss[:n_keep] = prev_test[:n_keep]
        finite_prev = prev_test[:n_keep][np.isfinite(prev_test[:n_keep])]
        if finite_prev.size:
            best = float(np.min(finite_prev))

    if len(train_loader) == 0:
        raise ValueError(
            "train loader is empty (dataset smaller than batch_size with "
            "drop_last) — refusing to report a fake 0.0 epoch loss"
        )

    first_epoch = resume_epoch if resume_epoch is not None else start_epoch + 1
    steps_done = int(jax.device_get(state.step))  # host mirror of state.step
    if resume_root and jax.process_index() == 0:
        # explicit rollback (resume from a non-newest version): versions
        # newer than the resume point are stale — left in place, a crash
        # before the new lineage surpasses them would make the next resume
        # silently pick the very checkpoint the operator rolled back from
        for n_v, p_v in ckpt_io.list_checkpoint_versions(resume_root):
            if n_v > steps_done:
                shutil.rmtree(p_v, ignore_errors=True)
                log.warning(f"[fault-tolerance] pruned stale version {p_v} "
                            f"(rolled back to step {steps_done})",
                            kind="validation")
    if resume_root:
        _sync_processes("ncnet_rollback_prune")
    nan_streak = nan_skipped = 0
    preempted = False
    save_kwargs = dict(
        keep=config.keep_checkpoints, io_retries=config.io_retries,
        io_retry_backoff=config.io_retry_backoff,
    )

    @contextlib.contextmanager
    def _telemetry_scope():
        """run_end + sink restore + log close on EVERY exit path — normal
        completion, preemption, TrainDivergedError, a crash.  The closure
        reads the loop counters at exit time, so the final event records
        where the run actually stopped."""
        try:
            yield
        finally:
            step_tracer.close()
            if telemetry is not None:
                if train_registry is not None:
                    train_registry.flush(final=True)
                    # cross-run perf history: the run's step-wall/throughput
                    # summary lands in the persistent store so
                    # tools/perf_regress.py can gate the NEXT run against it
                    # (fail-open: an unwritable store never blocks the exit)
                    from ncnet_tpu.observability import perfstore

                    snap = train_registry.snapshot()
                    summary: Dict[str, float] = {}
                    for name, key in (("step_wall", "train_step_wall_s"),
                                      ("stage_wall", "train_stage_wall_s")):
                        st = snap.get(name)
                        if isinstance(st, dict) and st.get("count"):
                            # median, not mean: the first step's compile
                            # dominates a short run's mean and would make
                            # runs of different lengths incomparable in the
                            # gated cross-run history
                            summary[key] = st.get("p50_s", st["mean_s"])
                    for name, key in (("pairs_per_s", "train_pairs_per_s"),
                                      ("mfu_pct", "train_mfu_pct")):
                        v = snap.get(name)
                        if isinstance(v, (int, float)):
                            summary[key] = float(v)
                    # accuracy trajectory: the run's mean pos/neg score gap
                    # (higher-is-better by name inference) gates the NEXT
                    # run's weak-supervision health like the walls.  MEAN,
                    # not the digest p50: the histogram's [-1,1]/32-bin
                    # median quantizes at ~0.06 — coarser than a typical
                    # early-training gap — while count/sum are exact
                    gap = snap.get("q_score_gap")
                    if isinstance(gap, dict) and gap.get("count") \
                            and isinstance(gap.get("mean"), (int, float)):
                        summary["train_quality_score_gap"] = gap["mean"]
                    perfstore.maybe_record(
                        summary, source="fit", run_id=telemetry.run_id)
                # global emit, not telemetry.emit: a disk-full append in a
                # finally block must not mask the real exit (or a clean
                # return) with an OSError
                obs_events.emit(
                    "run_end", step=steps_done, preempted=preempted,
                    nan_steps_skipped=nan_skipped,
                )
                obs_events.set_global_sink(prev_sink)
                try:
                    telemetry.close()
                except OSError:  # best-effort: the log is already fsynced
                    pass

    with _telemetry_scope(), PreemptionHandler() as preempt:
        for epoch in range(first_epoch, config.num_epochs + 1):
            start_b = resume_batch if epoch == first_epoch else 0
            n_train = len(train_loader)
            train_loader.set_epoch(epoch, start_batch=min(start_b, n_train))
            val_loader.set_epoch(epoch)
            stop_epoch = {"preempted": False}

            def on_step(batch_idx, cur_state, loss,
                        epoch=epoch, stop=stop_epoch):
                nonlocal steps_done, nan_streak, nan_skipped
                steps_done += 1
                if heartbeat is not None:
                    heartbeat.beat(step=steps_done)
                if dev_monitor is not None:
                    dev_monitor.maybe_emit(step=steps_done)
                if config.nan_guard:
                    # the guard's one host sync per step; the loss is
                    # replicated (computed on the global batch), so every
                    # process takes the same branch
                    if not math.isfinite(float(loss)):
                        nan_streak += 1
                        nan_skipped += 1
                        log.warning(f"[fault-tolerance] non-finite loss at "
                                    f"step {steps_done}: update skipped "
                                    f"(streak {nan_streak}/"
                                    f"{config.max_bad_steps})",
                                    kind="nan_guard")
                        obs_events.emit("nan_skip", step=steps_done,
                                        epoch=epoch, streak=nan_streak)
                        if train_registry is not None:
                            train_registry.counter("nan_skips").inc()
                        if nan_streak >= config.max_bad_steps:
                            obs_events.emit(
                                "diverged", step=steps_done, epoch=epoch,
                                streak=nan_streak,
                            )
                            raise TrainDivergedError(
                                f"{nan_streak} consecutive non-finite losses "
                                f"up to step {steps_done} (epoch {epoch}); "
                                "params/opt state are NOT corrupted (every "
                                "bad update was skipped) — lower the lr or "
                                "inspect the data"
                            )
                    else:
                        nan_streak = 0
                faults.sigterm_hook(steps_done)  # no-op unless a test armed it
                at_ckpt = (config.checkpoint_steps > 0
                           and steps_done % config.checkpoint_steps == 0)
                if jax.process_count() > 1:
                    # one host must never stop (and final-save) alone: the
                    # stop decision is agreed at collective boundaries.
                    # Those boundaries must stay frequent regardless of
                    # checkpoint_steps (a preemption grace window is ~30s;
                    # a 1000-step save cadence would forfeit it), so agree
                    # every few steps — one tiny host allgather, amortized
                    agree_every = (min(config.checkpoint_steps, 8)
                                   if config.checkpoint_steps else 8)
                    want_stop = (steps_done % agree_every == 0
                                 and _global_any(preempt.requested))
                else:
                    want_stop = preempt.requested
                if want_stop or at_ckpt:
                    save_train_checkpoint(
                        ckpt_name, config, model_config, cur_state,
                        epoch - 1, train_loss, test_loss, False,
                        step=steps_done,
                        position={"epoch": epoch, "next_batch": batch_idx + 1},
                        **save_kwargs,
                    )
                    if train_registry is not None:
                        train_registry.counter("checkpoint_commits").inc()
                if want_stop:
                    obs_events.emit("preemption", step=steps_done,
                                    epoch=epoch, batch=batch_idx)
                    stop["preempted"] = True
                    return True
                return False

            obs_events.emit("epoch_start", epoch=epoch,
                            start_batch=min(start_b, n_train),
                            n_batches=n_train)
            if train_loader.start_batch < n_train:
                # trace only the first post-resume epoch: a bounded,
                # representative capture (compile + steady-state steps)
                # instead of a runaway file — unless a step-window tracer
                # owns the one global profiler session
                with maybe_trace(config.profile_dir,
                                 enabled=(epoch == first_epoch
                                          and not step_tracer.enabled)):
                    state, train_loss[epoch - 1] = process_epoch(
                        "train", epoch, state, guarded_train_step,
                        train_loader,
                        config.log_interval, put_batch,
                        step_base=steps_done, on_step=on_step,
                        telemetry_ctx=telemetry_ctx,
                    )
            else:
                # resume position at the epoch's very end (killed between the
                # last periodic save and the epoch-end save): nothing to
                # recompute, but val + the epoch-end save still run
                log.info(f"Train Epoch: {epoch} already fully consumed at "
                         "the resume position; skipping to validation")
                train_loss[epoch - 1] = float("nan")
            if stop_epoch["preempted"]:
                preempted = True
                break
            _, test_loss[epoch - 1] = process_epoch(
                "test", epoch, state, eval_step, val_loader,
                config.log_interval, put_batch,
            )
            is_best = test_loss[epoch - 1] < best  # False for a NaN epoch
            # fmin, not min: a NaN val epoch must not poison best tracking
            # (min(nan, best) is nan, disabling best_ for the rest of the run)
            best = float(np.fmin(test_loss[epoch - 1], best))
            # multi-host: losses are computed on the global batch (replicated
            # to every process), so is_best agrees everywhere.  Every process
            # calls the (collective) save; orbax writes from the primary host
            # only.
            save_train_checkpoint(
                ckpt_name, config, model_config, state, epoch, train_loss,
                test_loss, is_best, step=steps_done,
                position={"epoch": epoch + 1, "next_batch": 0},
                **save_kwargs,
            )
            if train_registry is not None:
                train_registry.counter("checkpoint_commits").inc()
            obs_events.emit(
                "epoch_end", epoch=epoch, step=steps_done,
                train_loss=float(train_loss[epoch - 1]),
                test_loss=float(test_loss[epoch - 1]), best=bool(is_best),
            )
            if train_registry is not None:
                train_registry.flush(epoch=epoch)
            if _global_any(preempt.requested):
                preempted = True
                log.info("[fault-tolerance] stopping after the epoch "
                         "checkpoint (preemption requested)",
                         kind="preemption")
                obs_events.emit("preemption", step=steps_done, epoch=epoch,
                                boundary="epoch")
                break
    if preempted and progress:
        log.info(f"Preemption checkpoint committed under {ckpt_name}; "
                 "resume by pointing --checkpoint at it", kind="preemption")
    return {
        "state": state,
        "model_config": model_config,
        "train_loss": train_loss,
        "test_loss": test_loss,
        "best_test_loss": best,
        "checkpoint": ckpt_name,
        "preempted": preempted,
        "nan_steps_skipped": nan_skipped,
        "quarantined": sorted(
            train_loader.quarantined | val_loader.quarantined
        ),
    }
