"""Weakly-supervised matching loss.

Reference: ``weak_loss`` (/root/reference/train.py:110-156): score a pair as
the mean (over cells, both directions) of the max normalized match value;
loss = score(negative) − score(positive), where the negative pairs each
target with the *next* source in the batch (in-batch roll,
train.py:137).

TPU-native observation: the reference runs the full forward twice — but the
backbone is per-image, so the rolled-negative features ARE the positive's
source features rolled along the batch axis.  We extract features once and
build both correlation volumes from them: exactly the reference's math at
roughly half the FLOPs (the backbone dominates at 400²).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from ncnet_tpu.config import ModelConfig
from ncnet_tpu.models.ncnet import extract_features, ncnet_filter
from ncnet_tpu.ops import correlation_4d


def _normalize(x: jnp.ndarray, axis: int, normalization: str) -> jnp.ndarray:
    if normalization == "softmax":
        return jax.nn.softmax(x, axis=axis)
    if normalization == "l1":
        return x / (jnp.sum(x, axis=axis, keepdims=True) + 1e-4)
    if normalization is None or normalization == "none":
        return x
    raise ValueError(f"unknown normalization {normalization!r}")


def match_score(corr: jnp.ndarray, normalization: str = "softmax") -> jnp.ndarray:
    """Mean best-match score of a filtered volume, averaged over both
    matching directions (train.py:125-134).

    Args:
      corr: ``(B, hA, wA, hB, wB)``.
    Returns:
      scalar score (mean over batch, cells, directions).
    """
    b, ha, wa, hb, wb = corr.shape
    # B→A direction: distribution over A cells for each B cell
    nc_b = _normalize(corr.reshape(b, ha * wa, hb, wb), 1, normalization)
    # A→B direction: distribution over B cells for each A cell
    nc_a = _normalize(corr.reshape(b, ha, wa, hb * wb), 3, normalization)
    scores_b = jnp.max(nc_b, axis=1)          # (B, hB, wB)
    scores_a = jnp.max(nc_a, axis=3)          # (B, hA, wA)
    return jnp.mean(scores_a + scores_b) / 2.0


def weak_loss(
    config: ModelConfig,
    params,
    batch: Dict[str, jnp.ndarray],
    normalization: str = "softmax",
    stop_backbone_grad: bool = False,
    remat_nc_layers: bool = False,
    nc_custom_grad: bool = False,
) -> jnp.ndarray:
    """score(negative) − score(positive) on an image-pair batch.

    ``batch``: ``source_image``/``target_image`` of shape ``(B, H, W, 3)``.
    The negative pairing rolls the *source features* by −1 along the batch
    (identical to the reference rolling source images, train.py:137, since
    feature extraction is per-image).  Under a data-sharded batch axis this
    roll is a global permute — XLA lowers it to a collective, so negatives
    cross shard boundaries exactly like the reference's single-device
    global-batch roll.

    ``stop_backbone_grad``: detach the features (the reference's frozen-FE
    ``requires_grad=False`` semantics, model.py:75-78) — set when no backbone
    blocks are being finetuned so the backward pass neither recomputes nor
    stores the trunk.  The NC filter is rematerialized (``jax.checkpoint``)
    so the huge 16-channel volume activations are recomputed, not stored.

    ``remat_nc_layers``: additionally rematerialize each NC layer separately,
    shrinking the backward's concurrent folded-conv intermediates at the cost
    of recompute.  Measured on a 16G v5e at 400² (frozen trunk, donated
    state): OFF → bs8 fp32 at ~9.8 pairs/s, bs16 OOMs (20.8G fp32 / 15.8G
    bf16); ON → bs16 bf16 FITS at ~8.9 pairs/s, but bs8 fp32 drops to ~6.7
    pairs/s — so it is a flag (``TrainConfig.remat_nc_layers``), not a
    default.  The knob helps ONLY with the bf16 volume: bs16 fp32 WITH it
    needs 24.4G (XLA schedules more concurrent recompute buffers than the
    un-rematted 20.8G) — pair it with ``half_precision``.

    ``nc_custom_grad``: the other memory knob — conv4d's custom VJP, ~18%
    slower but ~45% less temp memory than plain AD (see
    :func:`ncnet_tpu.models.ncnet.neigh_consensus`).
    """
    fa = extract_features(config, params, batch["source_image"])
    fb = extract_features(config, params, batch["target_image"])
    if stop_backbone_grad:
        fa = jax.lax.stop_gradient(fa)
        fb = jax.lax.stop_gradient(fb)
    if config.half_precision:
        fa = fa.astype(jnp.bfloat16)
        fb = fb.astype(jnp.bfloat16)

    filt = jax.checkpoint(
        lambda p, corr: ncnet_filter(
            config, p, corr, remat_nc_layers=remat_nc_layers,
            nc_custom_grad=nc_custom_grad,
        ).corr
    )
    corr_pos = filt(params, correlation_4d(fa, fb))
    corr_neg = filt(params, correlation_4d(jnp.roll(fa, -1, axis=0), fb))

    score_pos = match_score(corr_pos, normalization)
    score_neg = match_score(corr_neg, normalization)
    return score_neg - score_pos
