"""Weakly-supervised matching loss.

Reference: ``weak_loss`` (/root/reference/train.py:110-156): score a pair as
the mean (over cells, both directions) of the max normalized match value;
loss = score(negative) − score(positive), where the negative pairs each
target with the *next* source in the batch (in-batch roll,
train.py:137).

TPU-native observation: the reference runs the full forward twice — but the
backbone is per-image, so the rolled-negative features ARE the positive's
source features rolled along the batch axis.  We extract features once and
build both correlation volumes from them: exactly the reference's math at
roughly half the FLOPs (the backbone dominates at 400²).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ncnet_tpu.config import ModelConfig
from ncnet_tpu.models.ncnet import extract_features, ncnet_filter
from ncnet_tpu.ops import correlation_4d


def _normalize(x: jnp.ndarray, axis: int, normalization: str) -> jnp.ndarray:
    if normalization == "softmax":
        return jax.nn.softmax(x, axis=axis)
    if normalization == "l1":
        return x / (jnp.sum(x, axis=axis, keepdims=True) + 1e-4)
    if normalization is None or normalization == "none":
        return x
    raise ValueError(f"unknown normalization {normalization!r}")


def match_score_per_pair(
    corr: jnp.ndarray, normalization: str = "softmax"
) -> jnp.ndarray:
    """Per-pair best-match score of a filtered volume, averaged over both
    matching directions (train.py:125-134).

    Args:
      corr: ``(B, hA, wA, hB, wB)``.
    Returns:
      ``(B,)`` scores (mean over cells and directions per pair).
    """
    b, ha, wa, hb, wb = corr.shape
    # B→A direction: distribution over A cells for each B cell
    nc_b = _normalize(corr.reshape(b, ha * wa, hb, wb), 1, normalization)
    # A→B direction: distribution over B cells for each A cell
    nc_a = _normalize(corr.reshape(b, ha, wa, hb * wb), 3, normalization)
    scores_b = jnp.mean(jnp.max(nc_b, axis=1), axis=(1, 2))  # (B,)
    scores_a = jnp.mean(jnp.max(nc_a, axis=3), axis=(1, 2))  # (B,)
    return (scores_a + scores_b) / 2.0


def match_score(corr: jnp.ndarray, normalization: str = "softmax") -> jnp.ndarray:
    """Batch-mean of :func:`match_score_per_pair` (the reference's scalar
    pair score, train.py:125-134)."""
    return jnp.mean(match_score_per_pair(corr, normalization))


def weak_loss(
    config: ModelConfig,
    params,
    batch: Dict[str, jnp.ndarray],
    normalization: str = "softmax",
    stop_backbone_grad: bool = False,
    remat_nc_layers: bool = False,
    nc_custom_grad: bool = False,
    fold_pos_neg: bool = False,
    remat_filter: bool = True,
    nc_pallas_vjp: bool = True,
) -> jnp.ndarray:
    """score(negative) − score(positive) on an image-pair batch.

    ``batch``: ``source_image``/``target_image`` of shape ``(B, H, W, 3)``.
    The negative pairing rolls the *source features* by −1 along the batch
    (identical to the reference rolling source images, train.py:137, since
    feature extraction is per-image).  Under a data-sharded batch axis this
    roll is a global permute — XLA lowers it to a collective, so negatives
    cross shard boundaries exactly like the reference's single-device
    global-batch roll.

    ``stop_backbone_grad``: detach the features (the reference's frozen-FE
    ``requires_grad=False`` semantics, model.py:75-78) — set when no backbone
    blocks are being finetuned so the backward pass neither recomputes nor
    stores the trunk.  The NC filter is rematerialized (``jax.checkpoint``)
    so the huge 16-channel volume activations are recomputed, not stored.

    ``remat_nc_layers``: additionally rematerialize each NC layer separately,
    shrinking the backward's concurrent folded-conv intermediates at the cost
    of recompute.  Measured on a 16G v5e at 400² (frozen trunk, donated
    state): OFF → bs8 fp32 at ~9.8 pairs/s, bs16 OOMs (20.8G fp32 / 15.8G
    bf16); ON → bs16 bf16 FITS at ~8.9 pairs/s, but bs8 fp32 drops to ~6.7
    pairs/s — so it is a flag (``TrainConfig.remat_nc_layers``), not a
    default.  The knob helps ONLY with the bf16 volume: bs16 fp32 WITH it
    needs 24.4G (XLA schedules more concurrent recompute buffers than the
    un-rematted 20.8G) — pair it with ``half_precision``.

    ``nc_custom_grad``: the other memory knob — conv4d's custom VJP, ~18%
    slower but ~45% less temp memory than plain AD (see
    :func:`ncnet_tpu.models.ncnet.neigh_consensus`).

    ``fold_pos_neg``: run the positive and negative volumes through ONE
    NC-filter call at batch 2B instead of two B-sized calls.  Identical
    math (the filter is per-volume; batching does not reassociate), but the
    doubled batch fills the MXU better and the backward transposes one
    program instead of two.  Composes with the square-volume symmetric
    batch fold in ``neigh_consensus`` (→ 4B).  Measured on v5e
    (tools/train_probe.py r4, 400²): NO faster (bs4 fp32 405.9 vs 390.0 ms
    base), and the doubled whole-batch backward program crashes the tunnel
    compile-helper at bs8 fp32 — default off; the fast path is
    :func:`weak_loss_and_grads` instead.

    ``remat_filter``: wrap the NC filter in ``jax.checkpoint`` so the
    backward recomputes the volume intermediates instead of storing them
    (the round-2 memory default).

    ``nc_pallas_vjp`` (round 7, the training default): route the NC stack
    through the fused Pallas forward + RESIDENT Pallas backward
    (ops/nc_fused_lane_vjp.py) where ``choose_fused_vjp`` confirms the
    whole pair engages — bf16 volumes + params, the resident shape class,
    green compile probes, no runtime demotion.  Everywhere else (fp32,
    CPU, InLoc-scale volumes, ``remat_nc_layers``/``nc_custom_grad``
    escape hatches) the stack keeps the plain XLA formulations exactly as
    before — pre-r7, training pinned ``nc_pallas=False`` because the
    fused kernels' VJP replayed the XLA stack, a net loss under
    ``value_and_grad``; the resident VJP removes that trade.
    """
    fa = extract_features(config, params, batch["source_image"])
    fb = extract_features(config, params, batch["target_image"])
    if stop_backbone_grad:
        fa = jax.lax.stop_gradient(fa)
        fb = jax.lax.stop_gradient(fb)
    if config.half_precision:
        fa = fa.astype(jnp.bfloat16)
        fb = fb.astype(jnp.bfloat16)

    def filt(p, corr):
        # nc_pallas_vjp gates BOTH directions together: the fused forward
        # engages only where the resident Pallas backward does too
        return ncnet_filter(
            config, p, corr, remat_nc_layers=remat_nc_layers,
            nc_custom_grad=nc_custom_grad, nc_pallas=nc_pallas_vjp,
            nc_pallas_vjp=nc_pallas_vjp,
        ).corr

    if remat_filter:
        filt = jax.checkpoint(filt)
    corr_pos = correlation_4d(fa, fb)
    corr_neg = correlation_4d(jnp.roll(fa, -1, axis=0), fb)

    if fold_pos_neg:
        b = corr_pos.shape[0]
        nc = filt(params, jnp.concatenate([corr_pos, corr_neg], axis=0))
        scores = match_score_per_pair(nc, normalization)  # (2B,)
        return jnp.mean(scores[b:]) - jnp.mean(scores[:b])

    score_pos = match_score(filt(params, corr_pos), normalization)
    score_neg = match_score(filt(params, corr_neg), normalization)
    return score_neg - score_pos


def auto_accum_chunks(batch_size: int, n_dev: int = 1) -> int:
    """Chunk count for :func:`weak_loss_and_grads`: target chunk size of
    FOUR volumes — the fastest measured on v5e at the PF-Pascal 25⁴ workload
    across bs8/bs16 × fp32/bf16 (tools/train_probe.py r4: chunk-4 beats
    chunk-8 and chunk-16 in every cell, e.g. bf16 bs8 481.8 vs 542.5 ms) —
    rounded up to a multiple of the data-parallel device count so the
    sharded pair axis still divides.  The DATA-PARALLEL caller must pass
    ``n_dev`` itself (``fit`` does); :func:`weak_loss_and_grads`' own ``-1``
    resolution assumes a single device."""
    n2 = 2 * batch_size
    target = max(4, n_dev)
    # nearest feasible chunk size to the target: a multiple of n_dev that
    # divides 2B — search below the target first (smaller chunks measured
    # no worse and use less memory), then above, else one whole chunk
    for c in list(range(target, n_dev - 1, -1)) + list(range(target + 1, n2)):
        if c > 0 and n2 % c == 0 and c % n_dev == 0:
            return n2 // c
    return 1


def weak_loss_and_grads(
    config: ModelConfig,
    params,
    batch: Dict[str, jnp.ndarray],
    normalization: str = "softmax",
    accum_chunks: int = -1,
    remat_nc_layers: bool = False,
    nc_custom_grad: bool = False,
    nc_pallas_vjp: bool = True,
) -> Tuple[jnp.ndarray, Dict]:
    """Exact :func:`weak_loss` value AND parameter gradients via
    volume-chunked gradient accumulation — the frozen-trunk fast path.

    With the trunk frozen (the reference's default training mode,
    /root/reference/train.py:60-63 with ``fe_finetune_params=0``), the loss
    is LINEAR in per-volume scores: ``mean(score(neg)) − mean(score(pos))``.
    So: extract features once for the whole batch (no gradient), build the
    2B-volume score list (B positives weighted −1/B, B rolled negatives
    weighted +1/B, the global-batch roll of train.py:137), and
    ``lax.scan`` the NC-filter forward+backward over ``accum_chunks``
    chunks of it, summing parameter grads.  Exact — chunking a weighted sum
    reassociates nothing across chunks — and the compiled program holds ONE
    chunk's filter backward, which:

      * sidesteps the tunnel-toolchain compile-crash at large whole-batch
        backward programs (bs8 fp32 / bs16 bf16 un-rematted forms crash
        ``tpu_compile_helper``; measured r4),
      * needs no ``jax.checkpoint`` recompute (the round-3 default burned
        ~25% of the step rematerializing the filter; tools/train_probe.py),
      * caps live memory at one chunk regardless of batch size — the
        reference's bs16 recipe fits a 16G chip without the
        ``remat_nc_layers`` throughput penalty.

    Backbone gradient leaves come back as zeros (the trunk is detached),
    matching the ``optax.multi_transform`` frozen partition in
    training/train.py.  Requires ``2 * B % accum_chunks == 0``.
    """
    fa = extract_features(config, params, batch["source_image"])
    fb = extract_features(config, params, batch["target_image"])
    fa = jax.lax.stop_gradient(fa)
    fb = jax.lax.stop_gradient(fb)
    if config.half_precision:
        fa = fa.astype(jnp.bfloat16)
        fb = fb.astype(jnp.bfloat16)

    b = fa.shape[0]
    n2 = 2 * b
    if accum_chunks == -1:
        accum_chunks = auto_accum_chunks(b)
    if n2 % accum_chunks:
        raise ValueError(
            f"accum_chunks={accum_chunks} must divide 2*batch={n2}"
        )
    fa2 = jnp.concatenate([fa, jnp.roll(fa, -1, axis=0)], axis=0)
    fb2 = jnp.concatenate([fb, fb], axis=0)
    w2 = jnp.concatenate(
        [jnp.full((b,), -1.0 / b), jnp.full((b,), 1.0 / b)]
    )

    def chunk_loss(nc_params, fac, fbc, wc):
        p = {**params, "nc": nc_params}
        nc = ncnet_filter(
            config, p, correlation_4d(fac, fbc),
            remat_nc_layers=remat_nc_layers, nc_custom_grad=nc_custom_grad,
            # the resident Pallas fwd+bwd pair where eligible (see
            # weak_loss); the chunked scan composes — each chunk's backward
            # runs the staged VJP chain at the chunk batch
            nc_pallas=nc_pallas_vjp, nc_pallas_vjp=nc_pallas_vjp,
        ).corr
        return jnp.sum(match_score_per_pair(nc, normalization) * wc)

    c = n2 // accum_chunks

    # the scan walks CHUNK INDICES and dynamic-slices the 2B-volume operands
    # inside the body, NOT a pre-chunked (chunks, c, ...) reshape of them.
    # The two are the same program in principle, but under a data-parallel
    # pair-axis sharding this container's CPU XLA MISCOMPILES the reshaped
    # form: reshaping the sharded-concatenated feature batch to
    # (chunks, c, ...) and consuming a scanned slice through the symmetric
    # batch-fold (concat([x, xT]) → conv → y[:b] + y[b:]) returns wrong
    # VALUES (≈2× off at chunk parity, worse elsewhere — reproduced outside
    # this module with the fold alone; the two-pass form is unaffected).
    # Slicing the operands in the body sidesteps the bad partition and is
    # bitwise-identical on a single device.
    def body(acc, i):
        fac = lax.dynamic_slice_in_dim(fa2, i * c, c, axis=0)
        fbc = lax.dynamic_slice_in_dim(fb2, i * c, c, axis=0)
        wc = lax.dynamic_slice_in_dim(w2, i * c, c, axis=0)
        val, g_nc = jax.value_and_grad(chunk_loss)(params["nc"], fac, fbc, wc)
        return (
            acc[0] + val,
            jax.tree.map(jnp.add, acc[1], g_nc),
        ), None

    zero = (jnp.zeros(()), jax.tree.map(jnp.zeros_like, params["nc"]))
    (loss, g_nc), _ = lax.scan(body, zero, jnp.arange(accum_chunks))
    # zero gradients for the (detached) trunk — the optax frozen partition
    # expects the full param tree structure
    grads = {
        **jax.tree.map(jnp.zeros_like, {k: v for k, v in params.items()
                                        if k != "nc"}),
        "nc": g_nc,
    }
    return loss, grads
