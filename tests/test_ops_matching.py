"""Tests for corr_to_matches / point transfer / coordinate transforms."""

import numpy as np
import jax.numpy as jnp

from ncnet_tpu import ops


def _np_corr_to_matches(corr, do_softmax=False, scale="centered", invert=False,
                        delta4d=None, k_size=1):
    """Independent numpy oracle following the reference's documented
    semantics (point_tnf.py:12-80)."""
    b, fs1, fs2, fs3, fs4 = corr.shape
    lo = -1.0 if scale == "centered" else 0.0
    gxa = np.linspace(lo, 1, fs2 * k_size)
    gya = np.linspace(lo, 1, fs1 * k_size)
    gxb = np.linspace(lo, 1, fs4 * k_size)
    gyb = np.linspace(lo, 1, fs3 * k_size)
    if invert:
        nc = corr.reshape(b, fs1 * fs2, fs3 * fs4)
        if do_softmax:
            e = np.exp(nc - nc.max(2, keepdims=True))
            nc = e / e.sum(2, keepdims=True)
        score = nc.max(2)
        idx = nc.argmax(2)
        i_b, j_b = idx // fs4, idx % fs4
        i_a = np.broadcast_to((np.arange(fs1 * fs2) // fs2)[None], idx.shape)
        j_a = np.broadcast_to((np.arange(fs1 * fs2) % fs2)[None], idx.shape)
    else:
        nc = corr.reshape(b, fs1 * fs2, fs3 * fs4)
        if do_softmax:
            e = np.exp(nc - nc.max(1, keepdims=True))
            nc = e / e.sum(1, keepdims=True)
        score = nc.max(1)
        idx = nc.argmax(1)
        i_a, j_a = idx // fs2, idx % fs2
        i_b = np.broadcast_to((np.arange(fs3 * fs4) // fs4)[None], idx.shape)
        j_b = np.broadcast_to((np.arange(fs3 * fs4) % fs4)[None], idx.shape)
    if delta4d is not None:
        dia, dja, dib, djb = delta4d
        bi = np.arange(b)[:, None]
        i_a, j_a, i_b, j_b = (
            i_a * k_size + dia[bi, i_a, j_a, i_b, j_b],
            j_a * k_size + dja[bi, i_a, j_a, i_b, j_b],
            i_b * k_size + dib[bi, i_a, j_a, i_b, j_b],
            j_b * k_size + djb[bi, i_a, j_a, i_b, j_b],
        )
    return gxa[j_a], gya[i_a], gxb[j_b], gyb[i_b], score


def test_corr_to_matches_directions_and_softmax(rng):
    corr = rng.standard_normal((2, 3, 4, 5, 2)).astype(np.float32)
    for invert in (False, True):
        for do_softmax in (False, True):
            for scale in ("centered", "positive"):
                m = ops.corr_to_matches(
                    jnp.asarray(corr), do_softmax=do_softmax, scale=scale,
                    invert_matching_direction=invert)
                xa, ya, xb, yb, score = _np_corr_to_matches(
                    corr, do_softmax=do_softmax, scale=scale, invert=invert)
                np.testing.assert_allclose(np.asarray(m.xA), xa, rtol=1e-5)
                np.testing.assert_allclose(np.asarray(m.yA), ya, rtol=1e-5)
                np.testing.assert_allclose(np.asarray(m.xB), xb, rtol=1e-5)
                np.testing.assert_allclose(np.asarray(m.yB), yb, rtol=1e-5)
                np.testing.assert_allclose(np.asarray(m.score), score,
                                           rtol=1e-5, atol=1e-6)


def test_corr_to_matches_relocalization(rng):
    """Full relocalization roundtrip: hi-res volume → maxpool4d → matches on
    the fine grid must equal the oracle on the pooled volume + offsets."""
    k = 2
    hi = rng.standard_normal((1, 6, 4, 6, 4)).astype(np.float32)
    pooled, delta = ops.maxpool4d_with_argmax(jnp.asarray(hi), k)
    m = ops.corr_to_matches(pooled, delta4d=delta, k_size=k, scale="positive")
    delta_np = tuple(np.asarray(d) for d in delta)
    xa, ya, xb, yb, score = _np_corr_to_matches(
        np.asarray(pooled), scale="positive", delta4d=delta_np, k_size=k)
    np.testing.assert_allclose(np.asarray(m.xA), xa, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(m.yA), ya, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(m.xB), xb, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(m.yB), yb, rtol=1e-5)


def test_normalize_axis_roundtrip(rng):
    x = rng.uniform(1, 200, size=(7,)).astype(np.float32)
    n = ops.normalize_axis(x, 200.0)
    back = ops.unnormalize_axis(n, 200.0)
    np.testing.assert_allclose(back, x, rtol=1e-5)
    # reference convention: pixel 1 → -1, pixel L → +1 (1-indexed)
    np.testing.assert_allclose(ops.normalize_axis(1.0, 100.0), -1.0)
    np.testing.assert_allclose(ops.normalize_axis(100.0, 100.0), 1.0)


def test_points_unit_pixel_roundtrip(rng):
    pts = rng.uniform(1, 90, size=(2, 2, 5)).astype(np.float32)
    im_size = np.array([[100.0, 120.0], [50.0, 60.0]], dtype=np.float32)
    unit = ops.points_to_unit_coords(jnp.asarray(pts), jnp.asarray(im_size))
    back = ops.points_to_pixel_coords(unit, jnp.asarray(im_size))
    np.testing.assert_allclose(np.asarray(back), pts, rtol=1e-4)


def _identity_matches(fs):
    """Matches where every B cell maps to the same A cell position."""
    g = np.linspace(-1, 1, fs).astype(np.float32)
    xb, yb = np.meshgrid(g, g)
    xb, yb = xb.reshape(1, -1), yb.reshape(1, -1)
    return ops.Matches(jnp.asarray(xb), jnp.asarray(yb),
                       jnp.asarray(xb), jnp.asarray(yb),
                       jnp.ones_like(jnp.asarray(xb)))


def test_bilinear_interp_identity_field():
    fs = 5
    m = _identity_matches(fs)
    pts = np.array([[[-0.3, 0.1, 0.77], [0.2, -0.6, 0.33]]], dtype=np.float32)
    warped = np.asarray(ops.bilinear_interp_point_tnf(m, jnp.asarray(pts)))
    np.testing.assert_allclose(warped, pts, atol=1e-5)


def test_nearest_neighbor_identity_field():
    fs = 5
    m = _identity_matches(fs)
    g = np.linspace(-1, 1, fs)
    pts = np.array([[[g[1] + 0.01, g[3]], [g[2], g[0] + 0.02]]], dtype=np.float32)
    warped = np.asarray(ops.nearest_neighbor_point_tnf(m, jnp.asarray(pts)))
    np.testing.assert_allclose(warped[0, 0], [g[1], g[3]], atol=1e-6)
    np.testing.assert_allclose(warped[0, 1], [g[2], g[0]], atol=1e-6)


def test_bilinear_interp_affine_field():
    """A linear match field must be reproduced exactly by bilinear interp."""
    fs = 6
    g = np.linspace(-1, 1, fs).astype(np.float32)
    xb, yb = np.meshgrid(g, g)
    xa = 0.5 * xb + 0.1
    ya = -0.25 * yb - 0.05
    m = ops.Matches(*(jnp.asarray(v.reshape(1, -1)) for v in (xa, ya, xb, yb)),
                    jnp.ones((1, fs * fs)))
    pts = np.array([[[-0.5, 0.3], [0.7, -0.2]]], dtype=np.float32)
    warped = np.asarray(ops.bilinear_interp_point_tnf(m, jnp.asarray(pts)))
    np.testing.assert_allclose(warped[:, 0], 0.5 * pts[:, 0] + 0.1, atol=1e-5)
    np.testing.assert_allclose(warped[:, 1], -0.25 * pts[:, 1] - 0.05, atol=1e-5)


def test_bilinear_interp_rectangular_grid():
    """grid_hw unlocks rectangular B grids (InLoc): a linear match field on a
    4×7 grid must still be reproduced exactly."""
    fh, fw = 4, 7
    gx = np.linspace(-1, 1, fw).astype(np.float32)
    gy = np.linspace(-1, 1, fh).astype(np.float32)
    xb, yb = np.meshgrid(gx, gy)  # (fh, fw) row-major
    xa = 0.5 * xb + 0.1
    ya = -0.25 * yb - 0.05
    m = ops.Matches(*(jnp.asarray(v.reshape(1, -1)) for v in (xa, ya, xb, yb)),
                    jnp.ones((1, fh * fw)))
    pts = np.array([[[-0.5, 0.3, 0.9], [0.7, -0.2, -0.9]]], dtype=np.float32)
    warped = np.asarray(
        ops.bilinear_interp_point_tnf(m, jnp.asarray(pts), grid_hw=(fh, fw))
    )
    np.testing.assert_allclose(warped[:, 0], 0.5 * pts[:, 0] + 0.1, atol=1e-5)
    np.testing.assert_allclose(warped[:, 1], -0.25 * pts[:, 1] - 0.05, atol=1e-5)
    # square-default inference must reject a non-square match count
    with np.testing.assert_raises(ValueError):
        ops.bilinear_interp_point_tnf(m, jnp.asarray(pts))
