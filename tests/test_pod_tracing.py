"""Pod-scope distributed tracing suite (ISSUE 20).

Three layers under test:

  * **Wire-propagated trace context** (``observability/tracing.py`` +
    the additive ``trace``/``sent_t`` header fields in
    ``serving/wire.py``): the router stamps or adopts a traceparent per
    request, backends attach their events to the remote parent, and
    every completed round trip yields an NTP-style ``clock_sync``
    offset sample.
  * **Multi-log federation** (``tools/trace_export.py --federate``):
    N per-process event logs merge into ONE Perfetto trace — per-host
    tracks, skew-corrected timestamps from the clock_sync graph,
    cross-host flow arrows keyed by trace id — tolerating torn tails,
    resume lineages / duplicated inputs, and sync-less logs (unaligned
    fallback: warning, correction 0, and NO arrows — never wrong ones).
  * **Pod identity report** (``run_report --pod``): the outcome-total
    identity recomputed across every log of the pod at once, dark
    trails named, failover re-routes attributed to their traces, and
    the edge-minus-backend overhead join.

THE acceptance chain (test_acceptance_pod_trace_federation): a real
3-process pod — router in-process, two backend subprocesses with
INJECTED ±50 ms clock skew (``NCNET_TPU_CLOCK_SKEW_S``) — one backend
SIGKILLed mid-batch; ``--federate`` then renders one valid Perfetto
trace where every cross-host request is a flow whose skew-corrected
backend slices nest inside the router slice, and ``run_report --pod``
proves zero lost requests from the merged logs alone with the failover
attributed to its trace.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from ncnet_tpu import ops
from ncnet_tpu.observability import EventLog
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.serving import (
    BACKEND_DEAD,
    MatchRouter,
    RouterConfig,
)
from ncnet_tpu.utils import faults

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import run_report  # noqa: E402
import trace_export  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)
    yield
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)


def u8(side=32, seed=0):
    return np.random.default_rng(seed).integers(
        0, 255, (side, side, 3), dtype=np.uint8)


def wait_until(pred, timeout=15.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _spawn_skewed_backend(tmp_path, name, skew_s, latency=0.05,
                          max_queue=32):
    """One real backend process whose WHOLE wall clock is shifted by
    ``skew_s`` (the ``NCNET_TPU_CLOCK_SKEW_S`` chaos seam in
    observability/events.py — read once at import, so every stamp the
    child publishes is consistently skewed), with its own event log."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               NCNET_TPU_PERF_STORE="off", NCNET_TPU_TIER_CACHE="off",
               NCNET_TPU_CLOCK_SKEW_S=repr(skew_s))
    log = str(tmp_path / f"{name}.jsonl")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_REPO, "tools", "serve_backend.py"),
         "--fake-engine", "--replicas", "1", "--latency", str(latency),
         "--max-queue", str(max_queue), "--max-batch", "1",
         "--events", log],
        stdout=subprocess.PIPE, text=True, env=env)
    doc = json.loads(proc.stdout.readline())
    return proc, doc["url"], log


def _run_id_of(log_path):
    head, _ = obs_events.replay_events(log_path)
    return str(head.get("header", {}).get("run_id"))


# ---------------------------------------------------------------------------
# THE acceptance chain
# ---------------------------------------------------------------------------


def test_acceptance_pod_trace_federation(tmp_path):
    """ISSUE 20 acceptance: 3-process pod with ±50 ms injected skew, one
    backend SIGKILLed mid-batch → one federated Perfetto trace (skew
    recovered from clock_sync, child slices nested, flows drawn) and the
    pod identity recomputed exactly from the merged logs alone."""
    router_log = str(tmp_path / "router.jsonl")
    skews = {"bplus": +0.05, "bminus": -0.05}
    procs = {}
    with obs_events.bound(EventLog(router_log)):
        for name, skew in skews.items():
            procs[name] = _spawn_skewed_backend(tmp_path, name, skew)
        router = MatchRouter(
            [url for _, url, _ in procs.values()],
            RouterConfig(probe_period_s=0.2, resurrect_after_s=120.0,
                         backend_max_failures=2, retries=1,
                         request_timeout_s=15.0, per_backend_depth=2,
                         max_queue=256,
                         max_in_flight_per_client=256)).start()
        img = u8()
        try:
            # phase 1: healthy traffic — every request gets a router-
            # stamped trace that rides the wire to some backend
            futs = [router.submit(img, img) for _ in range(12)]
            for f in futs:
                f.result(timeout=120)
            assert all(f.outcome == "result" for f in futs)

            # phase 2: SIGKILL one backend mid-batch under load — the
            # in-flight requests re-route OFF-budget, zero lost
            p_kill, url_kill, _ = procs["bplus"]
            victim = next(b for b in router.backends
                          if b.url in url_kill)
            futs = [router.submit(img, img) for _ in range(12)]
            time.sleep(0.06)  # let the victim take batches in flight
            p_kill.kill()
            for f in futs:
                f.result(timeout=120)
            assert all(f.outcome == "result" for f in futs)
            assert wait_until(lambda: victim.state == BACKEND_DEAD, 15)
        finally:
            router.stop()
            for p, _, _ in procs.values():
                if p.poll() is None:
                    p.terminate()
            for p, _, _ in procs.values():
                try:
                    p.wait(timeout=20)
                except Exception:  # noqa: BLE001 — wedged child
                    p.kill()

    logs = [router_log, procs["bplus"][2], procs["bminus"][2]]
    run_router = _run_id_of(router_log)
    run_plus = _run_id_of(procs["bplus"][2])
    run_minus = _run_id_of(procs["bminus"][2])

    # --- federation: one valid Perfetto trace, skew RECOVERED ----------
    warns = []
    doc = trace_export.build_federated_trace(logs, warn=warns.append)
    assert warns == [], warns  # every run reachable via clock_sync
    json.loads(json.dumps(doc))  # serializable end to end
    fed = doc["otherData"]["federation"]
    assert fed["unaligned"] == []
    assert all(r["aligned"] for r in fed["runs"].values())
    # the router is the reference clock; each backend's correction must
    # recover MINUS its injected skew (tolerance ~ the loopback RTT
    # bound of the NTP sample, far below the 100 ms skew separation)
    assert fed["runs"][run_router]["correction_s"] == 0.0
    assert abs(fed["runs"][run_plus]["correction_s"] + 0.05) < 0.02
    assert abs(fed["runs"][run_minus]["correction_s"] - 0.05) < 0.02
    assert fed["router_slices"] == 24
    assert fed["flows"] >= 12

    # every cross-host request is a flow whose skew-corrected backend
    # slice NESTS inside its router slice
    route_slice = {}  # trace -> (ts, ts+dur)
    for e in doc["traceEvents"]:
        if e.get("cat") == "route_request" and e["ph"] == "X" \
                and e["args"].get("trace"):
            route_slice[e["args"]["trace"]] = (e["ts"],
                                               e["ts"] + e["dur"])
    nested = 0
    eps_us = 10_000.0  # residual sync error bound (half-RTT scale)
    for e in doc["traceEvents"]:
        if e.get("cat") == "serve_request" and e["ph"] == "X":
            tr = e["args"]["trace"]
            assert tr in route_slice, f"orphan backend slice {tr}"
            r0, r1 = route_slice[tr]
            assert e["ts"] >= r0 - eps_us
            assert e["ts"] + e["dur"] <= r1 + eps_us
            nested += 1
    assert nested >= 12
    # flow endpoints exist on both sides of every drawn arrow
    assert sum(1 for e in doc["traceEvents"] if e["ph"] == "s") >= 12
    assert sum(1 for e in doc["traceEvents"] if e["ph"] == "f") >= 12

    # --- pod identity: recomputed from the merged logs ALONE -----------
    events = []
    for p in logs:
        _, recs = obs_events.replay_events(p)
        events.extend(recs)
    pod = run_report.build_pod_section(events)
    out = pod["outcomes"]
    assert out["admitted"] == 24
    assert out["results"] == 24
    assert out["terminals"] == out["admitted"]
    assert out["unresolved"] == 0
    assert pod["lost_requests"] == []
    assert pod["traced_admits"] == 24
    # every routed result is BACKED by a backend trail — nothing dark
    assert pod["dark_trails"] == []
    # the failover re-route is attributed to its trace, and that trace
    # recovered (settled as a result after re-routing)
    assert pod["failovers"], "SIGKILL produced no attributed re-route"
    for fo in pod["failovers"]:
        assert fo["trace"], fo
        assert fo["recovered"] is True, fo
    # the clock_sync graph covered both edges
    syncs = {str(e.get("peer_run")) for e in events
             if e.get("event") == "clock_sync"}
    assert {run_plus, run_minus} <= syncs
    # wire+routing overhead measured per request, trace-joined
    assert pod["overhead_samples"] >= 12
    assert pod["overhead_joined_by_trace"] >= 12

    # --- the CLI round trips -------------------------------------------
    out_path = str(tmp_path / "pod.trace.json")
    assert trace_export.main(logs + ["--federate", "-o", out_path]) == 0
    with open(out_path) as f:
        json.loads(f.read())
    assert run_report.main(logs + ["--pod"]) == 0


# ---------------------------------------------------------------------------
# federation edge cases (synthetic logs — controlled clocks)
# ---------------------------------------------------------------------------


def _write_log(path, run, events, host="hosta", torn_tail=False):
    """Hand-crafted event log: one header line + the given event records
    (each gains run/t defaults), optionally ending in a TORN line — the
    mid-append SIGKILL shape replay_events must absorb."""
    header = {"kind": "ncnet_tpu_events",
              "header": {"schema": 1, "run_id": run, "host": host,
                         "pid": 1, "time": 0.0}}
    lines = [json.dumps(header)]
    for e in events:
        rec = {"run": run, **e}
        lines.append(json.dumps(rec))
    text = "\n".join(lines) + "\n"
    if torn_tail:
        text += '{"t": 999.0, "run": "%s", "event": "serve_res' % run
    with open(path, "w") as f:
        f.write(text)
    return str(path)


def _router_events(trace, t=100.0, run="r1", request="q1", **extra):
    return [
        {"event": "route_admit", "t": t, "request": request,
         "client": "cam0", "trace": trace},
        {"event": "route_result", "t": t + 0.2, "request": request,
         "client": "cam0", "trace": trace, "wall_ms": 200.0,
         "backend_wall_ms": 50.0},
        *extra.get("more", []),
    ]


def test_federation_skewless_logs_fall_back_unaligned(tmp_path):
    """Zero clock_sync samples: the federation must DEGRADE honestly —
    warning emitted, corrections pinned to 0, and NO flow arrows between
    the unaligned runs (a confidently wrong arrow is worse than none)."""
    tr = "a" * 32
    log1 = _write_log(tmp_path / "router.jsonl", "r1",
                      _router_events(tr))
    log2 = _write_log(tmp_path / "backend.jsonl", "b1", [
        {"event": "request_timeline", "t": 105.1, "t0": 105.05,
         "total_ms": 50.0, "trace": tr, "request": "q1",
         "outcome": "result"},
    ], host="hostb")
    warns = []
    doc = trace_export.build_federated_trace([log1, log2],
                                             warn=warns.append)
    assert len(warns) == 1 and "b1" in warns[0]
    fed = doc["otherData"]["federation"]
    assert fed["unaligned"] == ["b1"]
    assert fed["runs"]["b1"] == {"correction_s": 0.0, "aligned": False}
    assert fed["runs"]["r1"]["aligned"] is True
    # the router slice still renders — only the CROSS-HOST arrow is
    # withheld
    assert fed["router_slices"] == 1
    assert fed["flows"] == 0
    assert not [e for e in doc["traceEvents"]
                if e["ph"] in ("s", "t", "f")]


def test_federation_absorbs_torn_tails_and_corrects_skew(tmp_path):
    """A backend log torn mid-append (SIGKILL shape) still federates: the
    torn line is dropped, the clock_sync edge aligns the run (+5 s skew
    recovered exactly), and the corrected backend slice lands inside the
    router slice."""
    tr = "b" * 32
    log1 = _write_log(tmp_path / "router.jsonl", "r1",
                      _router_events(tr) + [
                          {"event": "clock_sync", "t": 100.21,
                           "peer": "http://hostb:1", "peer_run": "b1",
                           "offset_s": 5.0, "rtt_s": 0.001},
                      ])
    # backend clock runs 5 s AHEAD: its stamps are t+5 for the same
    # instants
    log2 = _write_log(tmp_path / "backend.jsonl", "b1", [
        {"event": "request_timeline", "t": 105.15, "t0": 105.05,
         "total_ms": 50.0, "trace": tr, "request": "q1",
         "outcome": "result"},
    ], host="hostb", torn_tail=True)
    warns = []
    doc = trace_export.build_federated_trace([log1, log2],
                                             warn=warns.append)
    assert warns == []
    fed = doc["otherData"]["federation"]
    assert fed["runs"]["b1"] == {"correction_s": -5.0, "aligned": True}
    assert fed["flows"] == 1
    serve = [e for e in doc["traceEvents"]
             if e.get("cat") == "serve_request" and e["ph"] == "X"]
    route = [e for e in doc["traceEvents"]
             if e.get("cat") == "route_request" and e["ph"] == "X"]
    assert len(serve) == 1 and len(route) == 1
    # corrected: 105.05 - 5.0 = 100.05 ∈ [100.0, 100.2]
    assert route[0]["ts"] <= serve[0]["ts"]
    assert serve[0]["ts"] + serve[0]["dur"] \
        <= route[0]["ts"] + route[0]["dur"]


def test_federation_tolerates_resume_lineages_and_duplicate_inputs(
        tmp_path):
    """Resume lineages (two run ids in ONE file under one header) and the
    same log given TWICE must not double-count: slices are keyed
    (run, request), so every request renders exactly once."""
    tr1, tr2 = "c" * 32, "d" * 32
    log1 = str(tmp_path / "router.jsonl")
    header = {"kind": "ncnet_tpu_events",
              "header": {"schema": 1, "run_id": "r1", "host": "hosta",
                         "pid": 1, "time": 0.0}}
    recs = [header]
    for e in _router_events(tr1, t=100.0, run="r1", request="q1"):
        recs.append({"run": "r1", **e})
    # the resumed lineage appends under a FRESH run id, same file
    for e in _router_events(tr2, t=200.0, run="r1b", request="q1"):
        recs.append({"run": "r1b", **e})
    with open(log1, "w") as f:
        f.write("\n".join(json.dumps(r) for r in recs) + "\n")
    doc = trace_export.build_federated_trace([log1, log1],
                                             warn=lambda m: None)
    fed = doc["otherData"]["federation"]
    # same request id "q1" under two lineages = two distinct slices;
    # the duplicated input path adds NOTHING
    assert fed["router_slices"] == 2
    assert sorted(fed["runs"]) == ["r1", "r1b"]
    route = [e for e in doc["traceEvents"]
             if e.get("cat") == "route_request"]
    assert len(route) == 2


# ---------------------------------------------------------------------------
# pod identity edge cases
# ---------------------------------------------------------------------------


def test_pod_report_names_dark_trails(tmp_path):
    """A trace the router settled as result with NO backend trail in any
    merged log is named individually — the 'trail goes dark' verdict the
    acceptance criteria demand, never averaged away."""
    tr_ok, tr_dark = "e" * 32, "f" * 32
    events = []
    for e in _router_events(tr_ok, t=100.0, request="q1"):
        events.append({"run": "r1", **e})
    for e in _router_events(tr_dark, t=101.0, request="q2"):
        events.append({"run": "r1", **e})
    # only q1's trace has a backend-side trail
    events += [
        {"run": "b1", "event": "serve_admit", "t": 100.01,
         "request": "s1", "trace": tr_ok},
        {"run": "b1", "event": "serve_result", "t": 100.06,
         "request": "s1", "trace": tr_ok, "wall_ms": 50.0},
    ]
    pod = run_report.build_pod_section(events)
    assert pod["outcomes"]["unresolved"] == 0
    assert len(pod["dark_trails"]) == 1
    d = pod["dark_trails"][0]
    assert d["trace"] == tr_dark
    assert d["router_requests"] == ["q2"]
    assert d["backend_results"] == 0
    # the healthy trace joined for the overhead measurement
    assert pod["overhead_joined_by_trace"] == 1
