"""Aux subsystems: profiling hooks, plot helpers, demo script, host sharding."""

import os
import subprocess
import sys

import numpy as np

import jax
import jax.numpy as jnp

from ncnet_tpu.utils.plot import denormalize_image, plot_image, save_plot
from ncnet_tpu.utils.profiling import annotate, maybe_trace


def test_annotate_and_trace_capture(tmp_path):
    """A trace capture around a jitted call writes profiler artifacts."""
    f = jax.jit(lambda x: x * 2 + 1)
    with maybe_trace(str(tmp_path)) as active:
        assert active
        with annotate("test_region"):
            f(jnp.ones((8, 8))).block_until_ready()
    dumped = [os.path.join(r, fn) for r, _, fns in os.walk(tmp_path) for fn in fns]
    assert dumped, "profiler trace produced no files"


def test_maybe_trace_disabled_paths(tmp_path, monkeypatch):
    monkeypatch.delenv("NCNET_TPU_PROFILE_DIR", raising=False)
    with maybe_trace(None) as active:
        assert not active
    with maybe_trace(str(tmp_path), enabled=False) as active:
        assert not active
    assert not os.listdir(tmp_path)


def test_plot_roundtrip(tmp_path):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    from ncnet_tpu.ops.image import normalize_imagenet

    img = np.random.default_rng(0).uniform(0, 255, (24, 32, 3)).astype(np.float32)
    norm = normalize_imagenet(img)
    # denormalize inverts the ImageNet transform (up to /255 and clipping)
    np.testing.assert_allclose(denormalize_image(norm), img / 255.0,
                               rtol=1e-4, atol=1e-4)
    disp = plot_image(norm[None], return_im=True)
    assert disp.shape == (24, 32, 3) and disp.min() >= 0 and disp.max() <= 1
    fig, ax = plt.subplots()
    plot_image(norm, ax=ax)
    out = tmp_path / "fig.png"
    save_plot(str(out), fig)
    plt.close(fig)
    assert out.exists() and out.stat().st_size > 0


def test_host_shard_single_process():
    from ncnet_tpu.parallel import host_shard

    assert host_shard() == {"num_shards": 1, "shard_index": 0}


def test_demo_script_end_to_end(tmp_path):
    """The point-transfer demo (the reference notebook's replacement) runs
    headless on a synthetic pair and writes its figure."""
    out = tmp_path / "demo.png"
    env = dict(os.environ, JAX_PLATFORM_NAME="cpu")
    proc = subprocess.run(
        [sys.executable, "point_transfer_demo.py", "--synthetic",
         "--backbone", "tiny", "--image_size", "96", "--out", str(out)],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    assert out.exists() and out.stat().st_size > 0
