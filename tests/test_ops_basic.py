"""Unit tests for norm / correlation / pooling / mutual matching against
numpy brute-force oracles."""

import pytest
import numpy as np
import jax
import jax.numpy as jnp

from ncnet_tpu import ops


def test_feature_l2_norm(rng):
    x = rng.standard_normal((2, 3, 4, 8)).astype(np.float32)
    out = np.asarray(ops.feature_l2_norm(jnp.asarray(x)))
    expected = x / np.sqrt((x**2).sum(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_correlation_4d_matches_bruteforce(rng):
    fa = rng.standard_normal((2, 3, 4, 8)).astype(np.float32)
    fb = rng.standard_normal((2, 5, 6, 8)).astype(np.float32)
    out = np.asarray(ops.correlation_4d(jnp.asarray(fa), jnp.asarray(fb)))
    expected = np.einsum("bijc,bklc->bijkl", fa, fb)
    assert out.shape == (2, 3, 4, 5, 6)
    np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-5)


def test_correlation_3d_column_major_a_index(rng):
    b, h, w, c = 1, 3, 4, 5
    fa = rng.standard_normal((b, h, w, c)).astype(np.float32)
    fb = rng.standard_normal((b, h, w, c)).astype(np.float32)
    out = np.asarray(ops.correlation_3d(jnp.asarray(fa), jnp.asarray(fb), normalization=False))
    assert out.shape == (b, h * w, h, w)
    # reference indexing: idx_A = row_A + h * col_A (lib/model.py:104)
    for ia in range(h):
        for ja in range(w):
            for ib in range(h):
                for jb in range(w):
                    expected = fa[0, ia, ja] @ fb[0, ib, jb]
                    np.testing.assert_allclose(
                        out[0, ia + h * ja, ib, jb], expected, rtol=1e-5
                    )


def test_mutual_matching_bruteforce(rng):
    corr = rng.standard_normal((2, 3, 4, 5, 2)).astype(np.float32)
    out = np.asarray(ops.mutual_matching(jnp.asarray(corr)))
    eps = 1e-5
    max_a = corr.max(axis=(1, 2), keepdims=True)
    max_b = corr.max(axis=(3, 4), keepdims=True)
    expected = corr * ((corr / (max_b + eps)) * (corr / (max_a + eps)))
    np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-6)


def test_maxpool4d_with_argmax(rng):
    k = 2
    corr = rng.standard_normal((1, 4, 4, 6, 2)).astype(np.float32)
    pooled, (di, dj, dk, dl) = ops.maxpool4d_with_argmax(jnp.asarray(corr), k)
    pooled = np.asarray(pooled)
    assert pooled.shape == (1, 2, 2, 3, 1)
    for i in range(2):
        for j in range(2):
            for kk in range(3):
                for ll in range(1):
                    box = corr[0, i * k:(i + 1) * k, j * k:(j + 1) * k,
                               kk * k:(kk + 1) * k, ll * k:(ll + 1) * k]
                    assert pooled[0, i, j, kk, ll] == box.max()
                    # offsets point at the max element
                    off = (int(di[0, i, j, kk, ll]), int(dj[0, i, j, kk, ll]),
                           int(dk[0, i, j, kk, ll]), int(dl[0, i, j, kk, ll]))
                    assert box[off] == box.max()


def test_conv4d_matches_bruteforce(rng):
    b, ha, wa, hb, wb, cin, cout, k = 2, 3, 4, 3, 2, 2, 3, 3
    x = rng.standard_normal((b, ha, wa, hb, wb, cin)).astype(np.float32)
    w = rng.standard_normal((k, k, k, k, cin, cout)).astype(np.float32)
    bias = rng.standard_normal((cout,)).astype(np.float32)
    out = np.asarray(ops.conv4d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(bias)))
    assert out.shape == (b, ha, wa, hb, wb, cout)

    pad = k // 2
    xp = np.zeros((b, ha + 2 * pad, wa + 2 * pad, hb + 2 * pad, wb + 2 * pad, cin),
                  dtype=np.float32)
    xp[:, pad:-pad, pad:-pad, pad:-pad, pad:-pad] = x
    expected = np.zeros_like(out)
    for i in range(ha):
        for j in range(wa):
            for m in range(hb):
                for n in range(wb):
                    patch = xp[:, i:i + k, j:j + k, m:m + k, n:n + k, :]
                    expected[:, i, j, m, n, :] = (
                        np.tensordot(patch, w, axes=([1, 2, 3, 4, 5], [0, 1, 2, 3, 4]))
                    )
    expected += bias
    np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-4)


def test_conv4d_kernel5(rng):
    b, ha, wa, hb, wb, cin, cout, k = 1, 5, 5, 5, 5, 1, 2, 5
    x = rng.standard_normal((b, ha, wa, hb, wb, cin)).astype(np.float32)
    w = rng.standard_normal((k, k, k, k, cin, cout)).astype(np.float32)
    out = np.asarray(ops.conv4d(jnp.asarray(x), jnp.asarray(w)))
    pad = k // 2
    xp = np.pad(x, [(0, 0)] + [(pad, pad)] * 4 + [(0, 0)])
    expected = np.zeros((b, ha, wa, hb, wb, cout), dtype=np.float32)
    for i in range(ha):
        for j in range(wa):
            for m in range(hb):
                for n in range(wb):
                    patch = xp[:, i:i + k, j:j + k, m:m + k, n:n + k, :]
                    expected[:, i, j, m, n, :] = np.tensordot(
                        patch, w, axes=([1, 2, 3, 4, 5], [0, 1, 2, 3, 4]))
    np.testing.assert_allclose(out, expected, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("variant",
                         ["unroll", "tapfold", "coutfold", "afold",
                          "toeplitz_b"])
@pytest.mark.parametrize("pad_ha,pad_hb",
                         [(True, True), (False, True), (True, False), (False, False)])
def test_conv4d_variants_and_pad_modes_agree(rng, variant, pad_ha, pad_hb):
    """All three MXU formulations must agree with each other under every
    halo/pad mode (the spatially-sharded path feeds pre-padded volumes with
    pad_ha/pad_hb=False and expects a k//2-per-side shrink on that dim)."""
    b, ha, wa, hb, wb, cin, cout, k = 1, 6, 4, 7, 3, 2, 3, 3
    x = jnp.asarray(rng.standard_normal((b, ha, wa, hb, wb, cin)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((k, k, k, k, cin, cout)).astype(np.float32))
    bias = jnp.asarray(rng.standard_normal((cout,)).astype(np.float32))

    got = ops.conv4d(x, w, bias, pad_ha=pad_ha, pad_hb=pad_hb, variant=variant)
    # oracle: run the 'same' conv on a manually pre-padded volume and crop —
    # valid-mode output on padded input IS same-mode output on the original
    pad = k // 2
    exp_ha = ha if pad_ha else ha - 2 * pad
    exp_hb = hb if pad_hb else hb - 2 * pad
    assert got.shape == (b, exp_ha, wa, exp_hb, wb, cout)
    full = ops.conv4d(x, w, bias)  # same-padded reference (unroll/auto)
    sl_ha = slice(pad, -pad) if not pad_ha else slice(None)
    sl_hb = slice(pad, -pad) if not pad_hb else slice(None)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(full)[:, sl_ha, :, sl_hb], rtol=2e-4, atol=2e-4
    )


@pytest.mark.parametrize("variant", ["unroll", "tapfold", "coutfold"])
@pytest.mark.parametrize("pad_wa,pad_wb",
                         [(False, True), (True, False), (False, False)])
def test_conv4d_valid_w_matches_cropped_same(rng, variant, pad_wa, pad_wb):
    """The valid (unpadded) wA/wB paths must equal the same-padded output
    cropped by k//2 per side on that dim — the 2D-sharded path feeds
    pre-haloed volumes with pad_wa/pad_wb=False and relies on exactly this
    shrink arithmetic (ADVICE r5: these paths previously shipped with no
    callers and no coverage)."""
    b, ha, wa, hb, wb, cin, cout, k = 1, 4, 6, 3, 7, 2, 3, 3
    x = jnp.asarray(rng.standard_normal((b, ha, wa, hb, wb, cin)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((k, k, k, k, cin, cout)).astype(np.float32))
    bias = jnp.asarray(rng.standard_normal((cout,)).astype(np.float32))

    got = ops.conv4d(x, w, bias, pad_wa=pad_wa, pad_wb=pad_wb, variant=variant)
    pad = k // 2
    exp_wa = wa if pad_wa else wa - 2 * pad
    exp_wb = wb if pad_wb else wb - 2 * pad
    assert got.shape == (b, ha, exp_wa, hb, exp_wb, cout)
    full = ops.conv4d(x, w, bias)  # same-padded reference
    sl_wa = slice(None) if pad_wa else slice(pad, -pad)
    sl_wb = slice(None) if pad_wb else slice(pad, -pad)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(full)[:, :, sl_wa, :, sl_wb],
        rtol=2e-4, atol=2e-4,
    )


@pytest.mark.parametrize("variant", ["afold", "toeplitz_b"])
@pytest.mark.parametrize("pad_wa,pad_wb",
                         [(False, True), (True, False), (False, False)])
def test_conv4d_valid_w_unsupported_variants_raise(rng, variant, pad_wa, pad_wb):
    """afold/toeplitz_b support the same-padded w dims only (module
    docstring); both must refuse valid-w calls loudly instead of silently
    returning a same-padded wrong-shape result (ADVICE r5)."""
    b, ha, wa, hb, wb, cin, cout, k = 1, 4, 4, 3, 3, 2, 2, 3
    x = jnp.asarray(rng.standard_normal((b, ha, wa, hb, wb, cin)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((k, k, k, k, cin, cout)).astype(np.float32))
    with pytest.raises(ValueError, match="unpadded"):
        ops.conv4d(x, w, pad_wa=pad_wa, pad_wb=pad_wb, variant=variant)


def test_conv4d_auto_variant_matches_unroll(rng):
    """'auto' picks tapfold for 1-channel input and coutfold for 1-channel
    output; both must match the unroll formulation on NC-shaped layers."""
    b = 2
    x1 = jnp.asarray(rng.standard_normal((b, 5, 5, 5, 5, 1)).astype(np.float32))
    w1 = jnp.asarray(rng.standard_normal((5, 5, 5, 5, 1, 16)).astype(np.float32) * 0.1)
    x16 = jnp.asarray(rng.standard_normal((b, 5, 5, 5, 5, 16)).astype(np.float32))
    w3 = jnp.asarray(rng.standard_normal((3, 3, 3, 3, 16, 1)).astype(np.float32) * 0.1)
    for x, w in [(x1, w1), (x16, w3)]:
        auto = ops.conv4d(x, w)
        unroll = ops.conv4d(x, w, variant="unroll")
        np.testing.assert_allclose(np.asarray(auto), np.asarray(unroll),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("cin,cout", [(1, 4), (4, 4), (4, 1)])
def test_conv4d_same_gradient_parity(rng, cin, cout):
    """conv4d_same's custom VJP (dx as an explicit transposed conv4d, dw via
    the measured _DW_VARIANT formulation) must match jax.grad of the plain
    path on every NC channel pattern, on a rectangular volume."""
    b, ha, wa, hb, wb, k = 2, 5, 4, 6, 3, 3
    x = jnp.asarray(rng.standard_normal((b, ha, wa, hb, wb, cin)).astype(np.float32))
    w = jnp.asarray(
        rng.standard_normal((k, k, k, k, cin, cout)).astype(np.float32) * 0.2
    )
    bias = jnp.asarray(rng.standard_normal((cout,)).astype(np.float32))
    r = jnp.asarray(rng.standard_normal((b, ha, wa, hb, wb, cout)).astype(np.float32))

    def loss_custom(x, w, bias):
        return jnp.sum(ops.conv4d_same(x, w, bias) * r)

    def loss_plain(x, w, bias):
        return jnp.sum(ops.conv4d(x, w, bias, variant="unroll") * r)

    g_custom = jax.grad(loss_custom, argnums=(0, 1, 2))(x, w, bias)
    g_plain = jax.grad(loss_plain, argnums=(0, 1, 2))(x, w, bias)
    for gc, gp, name in zip(g_custom, g_plain, ("dx", "dw", "db")):
        np.testing.assert_allclose(
            np.asarray(gc), np.asarray(gp), rtol=1e-4, atol=1e-4, err_msg=name
        )


def test_conv4d_same_forward_identity(rng):
    """The custom-VJP wrapper must be exactly the auto-variant forward."""
    b = 1
    x = jnp.asarray(rng.standard_normal((b, 5, 5, 5, 5, 1)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((3, 3, 3, 3, 1, 4)).astype(np.float32))
    bias = jnp.asarray(rng.standard_normal((4,)).astype(np.float32))
    np.testing.assert_array_equal(
        np.asarray(ops.conv4d_same(x, w, bias)),
        np.asarray(ops.conv4d(x, w, bias)),
    )


def test_conv4d_transpose_weights_is_vjp(rng):
    """conv4d(g, transposed weights) == the x-cotangent of conv4d(x, w)."""
    b, s, cin, cout, k = 1, 5, 2, 3, 3
    x = jnp.asarray(rng.standard_normal((b, s, s, s, s, cin)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((k, k, k, k, cin, cout)).astype(np.float32))
    g = jnp.asarray(rng.standard_normal((b, s, s, s, s, cout)).astype(np.float32))
    _, vjp = jax.vjp(lambda xx: ops.conv4d(xx, w, variant="unroll"), x)
    (dx_ad,) = vjp(g)
    dx_explicit = ops.conv4d(g, ops.conv4d_transpose_weights(w), variant="unroll")
    np.testing.assert_allclose(
        np.asarray(dx_explicit), np.asarray(dx_ad), rtol=1e-4, atol=1e-4
    )


def test_conv4d_pallas_kernel_matches_oracle(rng):
    """The Pallas tap-folding kernel (interpret mode on CPU) must match the
    XLA formulations for the small-C_out shapes it serves, including the
    PF-Pascal last-layer shape class (k=5, 16ch) and the IVD k=3 kernel."""
    from ncnet_tpu.ops import conv4d_pallas as cp

    for (b, ha, wa, hb, wb, cin, cout, k) in [
        (1, 5, 5, 5, 5, 16, 1, 5),
        (2, 4, 6, 5, 3, 8, 1, 3),
        (1, 6, 4, 4, 6, 16, 2, 3),
    ]:
        x = jnp.asarray(
            rng.standard_normal((b, ha, wa, hb, wb, cin)).astype(np.float32))
        w = jnp.asarray(
            rng.standard_normal((k,) * 4 + (cin, cout)).astype(np.float32) * 0.1)
        want = ops.conv4d(x, w, variant="tapfold")
        got = cp._fwd_impl(x, w, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


def test_conv4d_pallas_backward_fallback(rng):
    """The custom_vjp backward (XLA fallback) must match grads of the plain
    formulation.  The bwd rule is exercised directly: on CPU the custom_vjp
    forward would hit Mosaic, and training never routes through the kernel."""
    import jax

    from ncnet_tpu.ops import conv4d_pallas as cp

    x = jnp.asarray(rng.standard_normal((1, 4, 4, 4, 4, 8)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((3,) * 4 + (8, 1)).astype(np.float32) * 0.1)
    g = jnp.asarray(rng.standard_normal((1, 4, 4, 4, 4, 1)).astype(np.float32))

    gx, gw = cp._bwd_rule((x, w), g)
    want_gx, want_gw = jax.vjp(
        lambda xx, ww: ops.conv4d(xx, ww, variant="unroll"), x, w
    )[1](g)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(want_gx),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(want_gw),
                               rtol=2e-4, atol=2e-4)


def test_conv4d_auto_demotes_folding_at_inloc_scale():
    """The channel-folding formulations materialize a kA·C whole-volume copy
    — tens of GB at the InLoc volume.  'auto' must demote to the 1×-volume
    unroll formulation there, and keep the folds at the PF-Pascal scale."""
    from ncnet_tpu.ops import choose_conv4d_variant, conv4d_fold_fits
    import jax.numpy as jnp

    inloc = dict(shape_a=(75, 100), hb=75, wb=100)
    pf = dict(shape_a=(25, 25), hb=25, wb=25)

    # 16->16 middle layer, bf16, sequential symmetric passes (batch 1)
    assert choose_conv4d_variant(
        16, 16, inloc["hb"], inloc["wb"], shape_a=inloc["shape_a"],
        kernel=(5,) * 4, dtype=jnp.bfloat16, batch=1,
    ) == "unroll"
    # PF-Pascal training at the folded batch keeps coutfold
    assert choose_conv4d_variant(
        16, 16, pf["hb"], pf["wb"], shape_a=pf["shape_a"],
        kernel=(5,) * 4, dtype=jnp.float32, batch=16,
    ) == "coutfold"
    # the shared gate agrees with both decisions
    assert not conv4d_fold_fits(1, 75, 100, 75, 100, 5, 16, jnp.bfloat16)
    assert conv4d_fold_fits(16, 25, 25, 25, 25, 5, 16, jnp.float32)
