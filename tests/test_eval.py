"""PCK metric + PF-Pascal evaluation loop tests."""

import numpy as np
import jax.numpy as jnp
import pytest

from ncnet_tpu.config import EvalPFPascalConfig, ModelConfig
from ncnet_tpu.data.synthetic import write_pf_pascal_like
from ncnet_tpu.evaluation import pck, run_eval
from ncnet_tpu import models


def test_pck_basic_and_padding():
    # 3 valid points (one wrong), 1 padded slot
    src = jnp.asarray([[[10.0, 20.0, 30.0, -1.0], [10.0, 20.0, 30.0, -1.0]]])
    warped = jnp.asarray([[[10.5, 20.0, 99.0, 0.0], [10.0, 20.5, 99.0, 0.0]]])
    l_pck = jnp.asarray([[10.0]])  # alpha*L = 1.0
    out = np.asarray(pck(src, warped, l_pck, alpha=0.1))
    np.testing.assert_allclose(out, [2.0 / 3.0])


def test_pck_all_padded_is_nan():
    src = -jnp.ones((1, 2, 4))
    out = np.asarray(pck(src, src, jnp.asarray([[5.0]])))
    assert np.isnan(out[0])


@pytest.fixture(scope="module")
def identity_tiny_net():
    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,), ncons_channels=(1,))
    net = models.NCNet(cfg, seed=0)
    w = np.zeros((3, 3, 3, 3, 1, 1), np.float32)
    w[1, 1, 1, 1, 0, 0] = 1.0
    net.params["nc"] = [{"w": jnp.asarray(w), "b": jnp.zeros((1,))}]
    return net


@pytest.mark.parametrize("batch_size", [1, 2])
def test_run_eval_recovers_known_shift(tmp_path, identity_tiny_net, batch_size):
    """Synthetic PF-Pascal-style set whose GT is an exact 1-feature-cell
    shift: the eval pipeline (dataset → model → matches → warp → PCK)
    must score (near-)perfect PCK."""
    # square images: the 400->400 eval resize is identity-like, so the
    # 1-feature-cell shift stays exact through the pipeline (a non-square
    # aspect change would turn it into a fractional-cell shift that a random
    # tiny trunk cannot match reliably)
    root = str(tmp_path)
    write_pf_pascal_like(root, n_pairs=4, image_hw=(96, 96), shift=(16, 16), seed=2)
    config = EvalPFPascalConfig(image_size=96, eval_dataset_path=root)
    stats = run_eval(config, net=identity_tiny_net, batch_size=batch_size,
                     progress=False)
    assert stats["total"] == 4 and stats["valid"] == 4
    assert stats["pck"] > 0.7, stats


def test_run_eval_batch_size_invariance(tmp_path, identity_tiny_net):
    root = str(tmp_path)
    write_pf_pascal_like(root, n_pairs=3, image_hw=(96, 96), shift=(16, 0), seed=3)
    config = EvalPFPascalConfig(image_size=96, eval_dataset_path=root)
    s1 = run_eval(config, net=identity_tiny_net, batch_size=1, progress=False)
    s3 = run_eval(config, net=identity_tiny_net, batch_size=3, progress=False)
    np.testing.assert_allclose(s1["per_pair"], s3["per_pair"], rtol=1e-5, atol=1e-5)


def test_run_eval_bf16_trunk_upload_path(tmp_path):
    """A backbone_bf16 net takes the bf16 image-upload fast path (halved
    tunnel bytes); the cast commutes with the trunk's own bf16 cast, so the
    identity-kernel shift recovery must still score like the fp32 path."""
    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                      ncons_channels=(1,), backbone_bf16=True)
    net = models.NCNet(cfg, seed=0)
    w = np.zeros((3, 3, 3, 3, 1, 1), np.float32)
    w[1, 1, 1, 1, 0, 0] = 1.0
    net.params["nc"] = [{"w": jnp.asarray(w), "b": jnp.zeros((1,))}]
    root = str(tmp_path)
    write_pf_pascal_like(root, n_pairs=4, image_hw=(96, 96), shift=(16, 16), seed=2)
    config = EvalPFPascalConfig(image_size=96, eval_dataset_path=root)
    stats = run_eval(config, net=net, batch_size=2, progress=False)
    assert stats["total"] == 4 and stats["valid"] == 4
    assert stats["pck"] > 0.7, stats


def test_run_eval_device_normalize_matches_host_path(tmp_path, identity_tiny_net):
    """The uint8-upload path (resized image quantized to uint8, ImageNet
    normalization inside the jitted step — 4× fewer tunnel bytes) scores the
    same per-pair PCK as the exact host-normalized float path on the
    synthetic fixture: at the square eval size the resize is identity on
    decoded uint8 pixels, so the quantization is lossless there and the
    only residual is normalize-order float rounding."""
    root = str(tmp_path)
    write_pf_pascal_like(root, n_pairs=3, image_hw=(96, 96), shift=(16, 0), seed=5)
    config = EvalPFPascalConfig(image_size=96, eval_dataset_path=root)
    dev = run_eval(config, net=identity_tiny_net, batch_size=3,
                   progress=False, device_normalize=True)
    host = run_eval(config, net=identity_tiny_net, batch_size=3,
                    progress=False, device_normalize=False)
    np.testing.assert_allclose(dev["per_pair"], host["per_pair"], atol=1e-6)
    for key in ("decode_s", "dispatch_s", "fetch_s"):
        assert dev["timing"][key] >= 0.0


def test_run_eval_pinned_pipeline_depth(tmp_path, identity_tiny_net):
    """A pinned dispatch/fetch depth bypasses the adaptive band and still
    produces the serial loop's results in order."""
    root = str(tmp_path)
    write_pf_pascal_like(root, n_pairs=4, image_hw=(96, 96), shift=(16, 0), seed=6)
    config = EvalPFPascalConfig(image_size=96, eval_dataset_path=root)
    deep = run_eval(config, net=identity_tiny_net, batch_size=1,
                    progress=False, pipeline_depth=4)
    flat = run_eval(config, net=identity_tiny_net, batch_size=1,
                    progress=False, pipeline_depth=1)
    np.testing.assert_allclose(deep["per_pair"], flat["per_pair"],
                               rtol=1e-5, atol=1e-5)


def test_cli_smoke(tmp_path, capsys):
    from ncnet_tpu.cli.eval_pf_pascal import main

    root = str(tmp_path)
    write_pf_pascal_like(root, n_pairs=2, image_hw=(64, 64), shift=(16, 16), seed=4)
    rc = main(["--eval_dataset_path", root, "--image_size", "64",
               "--backbone", "tiny", "--batch_size", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "PCK:" in out and "Total: 2" in out
