"""Coarse-to-fine sparse correlation (ISSUE 15): selection, gathered
refinement, dense/sparse parity, tier registration, and the drift gate.

Parity strategy (mirrors the ops/sparse_corr.py contract):

  * **k = full coverage** must reproduce the dense filtered volume EXACTLY
    (same gathered inner products, same mutual-matching maxes over full
    coverage, tile readout restricted to full-support core cells) — the
    degenerate upper bound that pins the whole pipeline's arithmetic to the
    dense reference.
  * **Provable partial coverage**: on a delta-structured fixture (one-hot
    features → exactly zero off-peak correlation) with a center-tap NC
    stack, every nonzero filtered cell is a covered peak, so when the
    candidate sets provably contain the dense argmax cells the sparse match
    table is row-for-row identical to the dense one.
  * **k = 1** bounds: static shapes and a readout support bounded by the
    candidate blocks.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ncnet_tpu.config import ModelConfig
from ncnet_tpu.models.ncnet import (
    ncnet_filter,
    ncnet_forward,
    ncnet_match_volume,
)
from ncnet_tpu.ops import (
    candidate_recall,
    choose_match_pipeline,
    coarse2fine_feasible,
    conv4d_init,
    correlation_4d,
    demote_fused_tier,
    demoted_fused_tiers,
    feature_l2_norm,
    pool_features,
    reset_fused_tier_demotions,
    scatter_sparse_scores,
    topk_candidates,
)
from ncnet_tpu.evaluation.inloc import extract_match_table

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)


@pytest.fixture(autouse=True)
def _restore_tier_state():
    """The pipeline chooser and demotion registry are process-global (by
    design — a demoted tier stays demoted); tests must not leak a
    'coarse2fine is active' stamp or a demotion into later test files."""
    from ncnet_tpu.ops import nc_fused_lane as nfl

    sel = dict(nfl._last_selected)
    emitted = dict(nfl._emitted_choices)
    demoted = set(nfl._runtime_demoted)
    yield
    nfl._last_selected.clear()
    nfl._last_selected.update(sel)
    nfl._emitted_choices.clear()
    nfl._emitted_choices.update(emitted)
    nfl._runtime_demoted.clear()
    nfl._runtime_demoted.update(demoted)


def _nc_params(kernels, channels, seed=1):
    key = jax.random.key(seed)
    nc = []
    c_in = 1
    for k, c_out in zip(kernels, channels):
        key, sub = jax.random.split(key)
        w, b = conv4d_init(sub, k, c_in, c_out)
        nc.append({"w": w, "b": b})
        c_in = c_out
    return {"nc": nc}


def _rand_features(rng, b, h, w, c):
    return feature_l2_norm(jnp.asarray(
        rng.normal(size=(b, h, w, c)).astype(np.float32)))


# ---------------------------------------------------------------------------
# selection primitives
# ---------------------------------------------------------------------------


def test_pool_features_shape_and_renorm(rng):
    f = jnp.asarray(rng.normal(size=(2, 8, 6, 5)).astype(np.float32))
    p = pool_features(f, 2)
    assert p.shape == (2, 4, 3, 5)
    norms = np.linalg.norm(np.asarray(p), axis=-1)
    assert np.allclose(norms, 1.0, atol=1e-3)
    # renormalize=False is the plain block mean
    p2 = np.asarray(pool_features(f, 2, renormalize=False))
    man = np.asarray(f).reshape(2, 4, 2, 3, 2, 5).mean(axis=(2, 4))
    assert np.allclose(p2, man, atol=1e-6)


def test_topk_coverage_padding(rng):
    corr = jnp.asarray(rng.normal(size=(1, 3, 3, 2, 2)).astype(np.float32))
    cand = topk_candidates(corr, 3)
    assert cand.shape == (1, 9, 3) and cand.dtype == jnp.int32
    flat = np.asarray(corr).reshape(1, 9, 4)
    # best-first ordering
    assert np.array_equal(np.asarray(cand)[0, :, 0], flat[0].argmax(axis=1))
    # k beyond the coarse grid: static shape, trailing slots repeat top-1
    wide = topk_candidates(corr, 7)
    assert wide.shape == (1, 9, 7)
    assert np.array_equal(np.asarray(wide)[:, :, 4:],
                          np.repeat(np.asarray(wide)[:, :, :1], 3, axis=2))


def test_origin_clamp_contains_core():
    from ncnet_tpu.ops.sparse_topk import block_origins

    # every coarse cell's patch must contain its full fine block, edges
    # included (the coverage-padding contract)
    factor, patch, length = 2, 6, 12
    origins = block_origins(length // factor, factor, patch, length)
    for c, o in enumerate(origins):
        assert 0 <= o <= length - patch
        assert o <= c * factor and c * factor + factor <= o + patch


# ---------------------------------------------------------------------------
# dense/sparse parity
# ---------------------------------------------------------------------------


def _tables(corr, both=True):
    class _Out:
        def __init__(self, c):
            self.corr = c
            self.delta4d = None

    return np.asarray(extract_match_table(
        _Out(corr), k_size=1, do_softmax=False, both_directions=both))


def test_k_full_reproduces_dense(rng):
    b, s, c = 2, 8, 16
    fa, fb = _rand_features(rng, b, s, s, c), _rand_features(rng, b, s, s, c)
    params = _nc_params((3, 3), (4, 1))
    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3, 3),
                      ncons_channels=(4, 1))
    dense = ncnet_filter(cfg, params, correlation_4d(fa, fb)).corr
    # 4x4 coarse grid -> k=16 is full coverage; halo 2 >= receptive radius
    sp = ncnet_match_volume(
        cfg.replace(sparse_topk=16, sparse_factor=2, sparse_halo=2),
        params, fa, fb)
    assert sp.corr.shape == dense.shape
    assert np.allclose(np.asarray(dense), np.asarray(sp.corr),
                       atol=1e-5, rtol=1e-4)
    # and the downstream wire tables agree row for row
    td, ts = _tables(dense), _tables(sp.corr)
    assert td.shape == ts.shape
    assert np.allclose(td, ts, atol=1e-5)


def test_k_full_rectangular_and_asymmetric(rng):
    # rectangular grids + symmetric_mode=False exercise the transposed tile
    # family's conjugated stack
    b = 1
    fa = _rand_features(rng, b, 8, 6, 12)
    fb = _rand_features(rng, b, 6, 8, 12)
    params = _nc_params((3,), (1,))
    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                      ncons_channels=(1,), symmetric_mode=False)
    dense = ncnet_filter(cfg, params, correlation_4d(fa, fb)).corr
    sp = ncnet_match_volume(
        cfg.replace(sparse_topk=12, sparse_factor=2, sparse_halo=2),
        params, fa, fb)
    assert np.allclose(np.asarray(dense), np.asarray(sp.corr),
                       atol=1e-5, rtol=1e-4)


def test_delta_fixture_row_parity_under_coverage(rng):
    """When top-k provably covers the true argmax cells, the sparse match
    table equals the dense one row for row — the headline accuracy claim at
    genuinely sparse k."""
    s, factor, k = 8, 2, 2
    n = s * s
    # one-hot identity features: corr is exactly the identity delta volume
    eye = np.eye(n, dtype=np.float32).reshape(s, s, n)
    fa = fb = jnp.asarray(eye[None])
    # center-tap-only stack: filtering is pointwise, so every nonzero
    # filtered cell is a covered peak and tile truncation is exact
    w = np.zeros((3, 3, 3, 3, 1, 1), np.float32)
    w[1, 1, 1, 1, 0, 0] = 0.7
    params = {"nc": [{"w": jnp.asarray(w), "b": jnp.zeros((1,))}]}
    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                      ncons_channels=(1,))
    raw = correlation_4d(fa, fb)
    dense = ncnet_filter(cfg, params, raw).corr

    # provable coverage: every fine cell's dense argmax falls inside its
    # coarse cell's candidate set (checked, not assumed)
    fac, fbc = pool_features(fa, factor), pool_features(fb, factor)
    coarse = ncnet_filter(cfg, params, correlation_4d(fac, fbc)).corr
    cand = topk_candidates(coarse, k)
    assert candidate_recall(np.asarray(cand), np.asarray(raw), factor) == 1.0
    cand_t = topk_candidates(jnp.transpose(coarse, (0, 3, 4, 1, 2)), k)
    assert candidate_recall(
        np.asarray(cand_t),
        np.asarray(jnp.transpose(raw, (0, 3, 4, 1, 2))), factor) == 1.0

    sp = ncnet_match_volume(
        cfg.replace(sparse_topk=k, sparse_factor=factor, sparse_halo=2),
        params, fa, fb)
    td, ts = _tables(dense), _tables(sp.corr)
    assert td.shape == ts.shape
    # row-for-row: identical match coordinates, scores to float tolerance
    assert np.array_equal(td[:4], ts[:4])
    assert np.allclose(td[4], ts[4], atol=1e-6)


def test_k1_degenerate_bounds(rng):
    b, s, factor = 1, 8, 2
    fa, fb = _rand_features(rng, b, s, s, 8), _rand_features(rng, b, s, s, 8)
    params = _nc_params((3,), (1,))
    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                      ncons_channels=(1,),
                      sparse_topk=1, sparse_factor=factor, sparse_halo=2)
    out = ncnet_match_volume(cfg, params, fa, fb)
    assert out.corr.shape == (b, s, s, s, s)
    # readout support is bounded by the candidate blocks: 2 tile families ×
    # N coarse cells × k × factor² × factor² cells
    n_cells = (s // factor) ** 2
    bound = 2 * n_cells * 1 * factor ** 4
    assert int(np.count_nonzero(np.asarray(out.corr))) <= bound
    # the wire shape matches the dense path's exactly
    dense = ncnet_filter(cfg, params, correlation_4d(fa, fb)).corr
    assert _tables(out.corr).shape == _tables(dense).shape


def test_recall_vs_k_curve(rng):
    b, s, factor = 1, 8, 2
    fa, fb = _rand_features(rng, b, s, s, 24), _rand_features(rng, b, s, s, 24)
    params = _nc_params((3,), (1,))
    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                      ncons_channels=(1,))
    raw = np.asarray(correlation_4d(fa, fb))
    coarse = ncnet_filter(cfg, params, correlation_4d(
        pool_features(fa, factor), pool_features(fb, factor))).corr
    ks = [1, 2, 4, 8, 16]
    recalls = [candidate_recall(np.asarray(topk_candidates(coarse, k)),
                                raw, factor) for k in ks]
    assert all(recalls[i] <= recalls[i + 1] + 1e-9
               for i in range(len(ks) - 1))
    assert recalls[-1] == 1.0  # k = full coarse grid covers everything


def test_scatter_sparse_scores_semantics():
    # duplicates resolve by max; untouched cells stay zero
    values = jnp.asarray(np.array([[[[[[[1.0]]]], [[[[3.0]]]]]]],
                                  dtype=np.float32))  # (1,1,2,1,1,1,1)
    ia = jnp.asarray(np.array([[2]], dtype=np.int32))
    ja = jnp.asarray(np.array([[1]], dtype=np.int32))
    ib = jnp.asarray(np.array([[[[0], [0]]]], dtype=np.int32))  # same cell
    jb = jnp.asarray(np.array([[[[3], [3]]]], dtype=np.int32))
    out = np.asarray(scatter_sparse_scores(values, ia, ja, ib, jb,
                                           (4, 4, 4, 4)))
    assert out.shape == (1, 4, 4, 4, 4)
    assert out[0, 2, 1, 0, 3] == 3.0
    assert np.count_nonzero(out) == 1


# ---------------------------------------------------------------------------
# Pallas gather tier (interpret mode — no Mosaic dependency)
# ---------------------------------------------------------------------------


def test_pallas_gather_matches_xla_tier(rng):
    from ncnet_tpu.ops.sparse_corr import (
        gather_source_patches,
        gather_tile_corr_pallas,
        source_patch_index,
        sparse_fine_corr,
    )
    from ncnet_tpu.ops.sparse_topk import candidate_origins, patch_side

    b, s, c, factor, halo = 2, 8, 16, 2, 2
    patch = patch_side(factor, halo)
    n_cells = (s // factor) ** 2
    fa = jnp.asarray(rng.normal(size=(b, s, s, c)).astype(np.float32))
    fb = jnp.asarray(rng.normal(size=(b, s, s, c)).astype(np.float32))
    cand = jnp.asarray(rng.integers(0, n_cells, (b, n_cells, 3))
                       .astype(np.int32))
    xla = sparse_fine_corr(fa, fb, cand, factor=factor, halo=halo)
    ia, ja = source_patch_index(s, s, factor, patch)
    oi, oj = candidate_origins(cand, s // factor, factor, patch, s, s)
    fa_p2 = gather_source_patches(fa, ia, ja).reshape(
        b, n_cells, patch * patch, c)
    v = gather_tile_corr_pallas(fa_p2, fb, oi // factor, oj, patch=patch,
                                factor=factor, interpret=True)
    assert np.array_equal(
        np.asarray(v).reshape(xla.values.shape), np.asarray(xla.values))


def test_sparse_gather_feasibility_gate():
    from ncnet_tpu.ops.sparse_corr import sparse_gather_feasible

    # band alignment: a halo that is not a multiple of the factor cannot
    # ride the banded BlockSpec gather
    assert not sparse_gather_feasible(64, 64, 64, patch=7, factor=2, halo=3)
    assert sparse_gather_feasible(64, 64, 64, patch=6, factor=2, halo=2)
    # a VMEM-busting channel depth fails closed
    assert not sparse_gather_feasible(
        512, 512, 8192, patch=6, factor=2, halo=2)


# ---------------------------------------------------------------------------
# tier registration: dispatch, demotion, persistence, recovery
# ---------------------------------------------------------------------------


def _eligible_kw(k=2):
    return dict(sparse_topk=k, factor=2, halo=2, reloc_k=0)


def test_choose_pipeline_eligibility():
    assert choose_match_pipeline(8, 8, 8, 8, **_eligible_kw()) \
        == "coarse2fine"
    # knob off, relocalization on, or indivisible dims → dense
    assert choose_match_pipeline(8, 8, 8, 8, **{**_eligible_kw(), "sparse_topk": 0}) is None
    assert choose_match_pipeline(8, 8, 8, 8, **{**_eligible_kw(), "reloc_k": 2}) is None
    assert choose_match_pipeline(9, 8, 8, 8, **_eligible_kw()) is None
    assert not coarse2fine_feasible(4, 4, 4, 4, sparse_topk=2, factor=2,
                                    halo=2)  # patch exceeds the grid


def test_demotion_walk_and_reset():
    from ncnet_tpu.ops import nc_fused_lane as nfl

    reset_fused_tier_demotions()
    try:
        # dense pipeline active → the ladder walk skips coarse2fine
        nfl._last_selected["pipeline"] = "dense"
        assert demote_fused_tier() == "resident"
        reset_fused_tier_demotions()
        # sparse pipeline active → coarse2fine is the first suspect, and
        # the chooser falls back dense afterwards
        assert choose_match_pipeline(8, 8, 8, 8, **_eligible_kw()) \
            == "coarse2fine"
        assert demote_fused_tier() == "coarse2fine"
        assert "coarse2fine" in demoted_fused_tiers()
        assert choose_match_pipeline(8, 8, 8, 8, **_eligible_kw()) is None
        # the next walk moves down the ladder
        assert demote_fused_tier() == "resident"
        # demote by name is idempotent
        assert demote_fused_tier("coarse2fine") is None
    finally:
        reset_fused_tier_demotions()
    assert choose_match_pipeline(8, 8, 8, 8, **_eligible_kw()) \
        == "coarse2fine"


def test_demotion_persists_via_tier_cache(tmp_path, monkeypatch):
    from ncnet_tpu.ops import tier_cache

    monkeypatch.setenv(tier_cache.CACHE_ENV,
                       str(tmp_path / "tier_cache.json"))
    tier_cache._reset_state()
    reset_fused_tier_demotions()
    try:
        choose_match_pipeline(8, 8, 8, 8, **_eligible_kw())
        assert demote_fused_tier() == "coarse2fine"
        # a fresh process (in-process analog: clear the runtime registry
        # and the cache mirror) still sees the negative entry
        from ncnet_tpu.ops import nc_fused_lane as nfl

        nfl._runtime_demoted.clear()
        tier_cache._reset_state()
        assert "coarse2fine" in tier_cache.persistent_demotions()
        assert choose_match_pipeline(8, 8, 8, 8, **_eligible_kw()) is None
    finally:
        reset_fused_tier_demotions()
        tier_cache._reset_state()


def test_recover_from_device_failure_demotes_pipeline():
    from ncnet_tpu.models.ncnet import recover_from_device_failure
    from ncnet_tpu.utils import faults

    reset_fused_tier_demotions()
    try:
        choose_match_pipeline(8, 8, 8, 8, **_eligible_kw())

        class Spy:
            retraced = 0

            def retrace(self):
                Spy.retraced += 1

        tier = recover_from_device_failure(
            faults.InjectedDeviceError("boom"), Spy())
        assert tier == "coarse2fine"
        assert Spy.retraced == 1
        assert choose_match_pipeline(8, 8, 8, 8, **_eligible_kw()) is None
    finally:
        reset_fused_tier_demotions()


def test_active_tier_reports_pipeline():
    from ncnet_tpu.observability.quality import active_tier

    choose_match_pipeline(8, 8, 8, 8, **_eligible_kw())
    assert active_tier(False) == "coarse2fine"
    assert active_tier(True) == "coarse2fine"
    choose_match_pipeline(8, 8, 8, 8,
                          **{**_eligible_kw(), "sparse_topk": 0})
    assert active_tier(False) == "xla"


# ---------------------------------------------------------------------------
# end-to-end wiring
# ---------------------------------------------------------------------------


def test_sparse_forward_end_to_end():
    from ncnet_tpu.ops import last_selected_tier

    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                      ncons_channels=(1,), sparse_topk=2)
    from ncnet_tpu.models.ncnet import init_ncnet

    params = init_ncnet(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    src = jnp.asarray(rng.uniform(-1, 1, (1, 96, 96, 3)).astype(np.float32))
    tgt = jnp.asarray(rng.uniform(-1, 1, (1, 96, 96, 3)).astype(np.float32))
    out = ncnet_forward(cfg, params, src, tgt)
    assert out.corr.shape == (1, 6, 6, 6, 6)
    assert out.delta4d is None
    assert last_selected_tier("pipeline") == "coarse2fine"
    # dense config at the same shape keeps the dense pipeline
    dense_out = ncnet_forward(cfg.replace(sparse_topk=0), params, src, tgt)
    assert last_selected_tier("pipeline") == "dense"
    assert dense_out.corr.shape == out.corr.shape


def test_point_matcher_sparse_wire_shape():
    """The serving-path wire format is untouched: a sparse matcher returns
    the same (B, N) Matches fields and a quality row tagged coarse2fine."""
    from ncnet_tpu.models import make_point_matcher
    from ncnet_tpu.models.ncnet import init_ncnet

    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                      ncons_channels=(1,), sparse_topk=2)
    params = init_ncnet(cfg, jax.random.key(0))
    matcher = make_point_matcher(cfg, params)
    rng = np.random.default_rng(1)
    src = rng.integers(0, 255, (1, 96, 96, 3), dtype=np.uint8)
    tgt = rng.integers(0, 255, (1, 96, 96, 3), dtype=np.uint8)
    m, quality = matcher.match_with_quality(src, tgt)
    assert all(v.shape == (1, 36) for v in m)
    assert quality is not None and 0.0 <= quality["score"] <= 1.0


def test_probe_tiny_smoke(capsys):
    import sparse_corr_probe

    assert sparse_corr_probe.main(["--tiny"]) == 0
    outp = capsys.readouterr().out
    assert "tiny smoke: OK" in outp


def test_sparse_synthetic_eval_drift_green(tmp_path):
    """The satellite acceptance: the sparse synthetic eval's quality
    distributions gate green against the committed coarse2fine reference
    series (quality_drift --check), with every event tier-tagged
    coarse2fine — the label-free proof the sparse tier loses no accuracy
    on the pinned fixture."""
    import json

    import quality_drift

    stats, events_path = quality_drift.synthetic_reference_run(
        str(tmp_path), sparse=True)
    assert stats["quality_tier"] == "coarse2fine"
    tiers = set()
    with open(events_path) as f:
        for line in f:
            e = json.loads(line)
            if e.get("event") == "quality":
                tiers.add(e.get("tier"))
    assert tiers == {"coarse2fine"}
    # the confident pairs of the coarse-aligned sparse fixture match at
    # dense-level PCK (1.0 per pair) — coverage holds, accuracy holds
    assert float(np.nanmean(stats["per_pair"][:8])) == pytest.approx(1.0)
    assert quality_drift.main(["--check", events_path]) == 0
