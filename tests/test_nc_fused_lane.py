"""Fused-lane Pallas NC stack: numerics (interpret mode), gating, VJP.

The kernel's on-chip timing lives in tools/nc_fused_lane_probe.py (measured
2.0 vs 3.95 ms/volume against the XLA stack, v5e r5); these tests lock the
numerics and the routing so the fast path cannot drift from the XLA
formulations it replaces.  Reference semantics: NeighConsensus
(/root/reference/lib/model.py:122-153).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ncnet_tpu.ops.conv4d import conv4d
from ncnet_tpu.ops.nc_fused_lane import (
    choose_fused_stack,
    fused_lane_feasible,
    fused_resident_feasible,
    nc_stack_fused,
    nc_stack_fused_lane,
    nc_stack_resident,
)


def xla_stack(params, x):
    for layer in params:
        x = jax.nn.relu(conv4d(x, layer["w"], layer["b"]))
    return x


def make_params(key, kernels, channels, dtype=jnp.float32):
    params, c_in = [], 1
    for i, (k, c_out) in enumerate(zip(kernels, channels)):
        k1, k2, key = jax.random.split(key, 3)
        params.append({
            "w": jax.random.normal(k1, (k,) * 4 + (c_in, c_out), dtype) * 0.1,
            "b": jax.random.normal(k2, (c_out,), dtype) * 0.1,
        })
        c_in = c_out
    return params


@pytest.mark.parametrize("shape,kernels,channels", [
    ((2, 7, 7, 7, 7), (3, 3), (4, 1)),          # IVD-like 2-layer
    ((1, 6, 5, 7, 6), (3, 3, 3), (4, 4, 1)),    # rectangular, 3-layer
    ((1, 9, 9, 9, 9), (5, 5, 5), (4, 4, 1)),    # PF-Pascal k=5 class
])
def test_interpret_parity(shape, kernels, channels):
    """Interpret-mode fused chain == XLA stack (same bf16 inputs, f32
    comparison): locks the A-build order, the (r,s) lane-offset epilogue,
    the halo masks, and the thin-channel zero padding."""
    key = jax.random.key(0)
    # bf16 end-to-end: the kernel computes in bf16 (f32 dot accumulation),
    # so the XLA reference must see the same operands or the comparison
    # measures bf16 rounding, not the kernel
    params = make_params(key, kernels, channels, dtype=jnp.bfloat16)
    x = (jax.random.normal(jax.random.key(7), shape + (1,)) * 0.5
         ).astype(jnp.bfloat16)

    ref = np.asarray(xla_stack(params, x), np.float32)
    got = np.asarray(
        nc_stack_fused_lane(params, x, interpret=True), np.float32
    )
    scale = max(1e-6, float(np.max(np.abs(ref))))
    np.testing.assert_allclose(got / scale, ref / scale, atol=3e-2)


@pytest.mark.parametrize("shape,kernels,channels", [
    ((2, 7, 7, 7, 7), (3, 3), (4, 1)),            # IVD-like 2-layer
    ((1, 6, 5, 7, 6), (3, 3, 3), (4, 4, 1)),      # rectangular, 3-layer
    ((1, 9, 9, 9, 9), (5, 5, 5), (4, 4, 1)),      # PF-Pascal k=5 class
    ((2, 6, 7, 5, 8), (3, 3), (4, 2)),            # 2-ch final (tap-swap)
    ((1, 7, 7, 7, 7), (3,), (1,)),                # single layer, no rings
    ((1, 5, 5, 5, 5), (5, 5, 5), (2, 2, 1)),      # hA == k: halo-heavy
])
def test_resident_interpret_parity(shape, kernels, channels):
    """Interpret-mode RESIDENT chain == XLA stack: locks the wavefront
    schedule (layer l emits row ii − l·d), the ring-slot zero protocol
    (bottom-halo priming, top-halo zero rows, j-halo rewrites), the exact
    thin-layer K/N widths, and the fused layout in/out."""
    key = jax.random.key(0)
    params = make_params(key, kernels, channels, dtype=jnp.bfloat16)
    x = (jax.random.normal(jax.random.key(7), shape + (1,)) * 0.5
         ).astype(jnp.bfloat16)

    ref = np.asarray(xla_stack(params, x), np.float32)
    got = np.asarray(nc_stack_resident(params, x, interpret=True), np.float32)
    assert got.shape == ref.shape
    scale = max(1e-6, float(np.max(np.abs(ref))))
    np.testing.assert_allclose(got / scale, ref / scale, atol=3e-2)


def test_resident_ring_state_resets_across_batch_items():
    """The ring scratch persists across grid steps AND batch items: the
    step-0 priming + halo-write protocol must fully mask the previous batch
    item's rows, so per-item outputs match the item run alone."""
    params = make_params(jax.random.key(1), (3, 3), (4, 1),
                         dtype=jnp.bfloat16)
    x = (jax.random.normal(jax.random.key(2), (3, 6, 6, 6, 6, 1))
         ).astype(jnp.bfloat16)
    full = np.asarray(
        nc_stack_resident(params, x, interpret=True), np.float32)
    for i in range(3):
        alone = np.asarray(
            nc_stack_resident(params, x[i:i + 1], interpret=True), np.float32)
        np.testing.assert_array_equal(full[i:i + 1], alone)


def test_resident_feasibility_gate():
    assert fused_resident_feasible(25, 25, 25, 25, (5, 5, 5), (16, 16, 1))
    assert fused_resident_feasible(13, 13, 13, 13, (3, 3), (16, 1))
    # tap-swap block-diagonal chain shape class
    assert fused_resident_feasible(13, 17, 13, 17, (3, 3), (32, 2))
    # InLoc fine grid: the fused kl dim alone is ~30k lanes
    assert not fused_resident_feasible(100, 75, 150, 200, (3, 3), (16, 1))
    assert not fused_resident_feasible(25, 25, 25, 25, (5, 3, 5), (16, 16, 1))
    assert not fused_resident_feasible(25, 25, 25, 25, (4, 4, 4), (16, 16, 1))
    # wide final volumes are not the NC-stack shape class
    assert not fused_resident_feasible(25, 25, 25, 25, (5, 5), (16, 16))


def test_choose_fused_stack_skips_pallas_on_cpu():
    """Both Pallas tiers need a real TPU backend: on CPU a shape that fails
    the arithmetic gates too must land on the XLA formulations.  (The
    arithmetic cp/fft tiers are backend-agnostic by design — the k=5 arch
    legitimately routes 'fft' even on CPU; test_conv4d_tiers.py owns that.)"""
    assert choose_fused_stack(13, 13, 13, 13, (3, 3), (16, 1)) is None


def test_resident_tap_swap_chain_matches_symmetric_reference():
    """The tap-swap block-diagonal chain (models/ncnet.py tap_swap_chain)
    through the RESIDENT kernel == the stack-level symmetric reference
    NC(x) + NC(xᵀ)ᵀ — the algebraic identity plus the per-stack ReLU
    separation that the 2-channel final layer preserves."""
    from ncnet_tpu.models.ncnet import tap_swap_chain

    params = make_params(jax.random.key(3), (3, 3), (4, 1),
                         dtype=jnp.bfloat16)
    x = (jax.random.normal(jax.random.key(4), (1, 5, 7, 6, 4, 1)) * 0.5
         ).astype(jnp.bfloat16)
    xt = jnp.transpose(x, (0, 3, 4, 1, 2, 5))
    ref = xla_stack(params, x) + jnp.transpose(
        xla_stack(params, xt), (0, 3, 4, 1, 2, 5))
    y2 = nc_stack_resident(tap_swap_chain(params), x, interpret=True)
    got = np.asarray(y2[..., :1] + y2[..., 1:], np.float32)
    ref = np.asarray(ref, np.float32)
    scale = max(1e-6, float(np.max(np.abs(ref))))
    np.testing.assert_allclose(got / scale, ref / scale, atol=3e-2)


def test_rejects_multichannel_input():
    """The lane packing keeps only input channel 0 (x[..., 0]): calls whose
    volume or first layer carries more than 1 input channel must be rejected
    loudly, not silently given wrong results (ADVICE r5)."""
    params = make_params(jax.random.key(0), (3,), (1,), dtype=jnp.bfloat16)
    x2 = jnp.zeros((1, 5, 5, 5, 5, 2), jnp.bfloat16)
    with pytest.raises(AssertionError, match="1-channel input"):
        nc_stack_fused_lane(params, x2, interpret=True)
    # a first layer with c_in > 1 is the same class of misuse
    wide = [{"w": jnp.zeros((3, 3, 3, 3, 2, 1), jnp.bfloat16),
             "b": jnp.zeros((1,), jnp.bfloat16)}]
    x1 = jnp.zeros((1, 5, 5, 5, 5, 1), jnp.bfloat16)
    with pytest.raises(AssertionError, match="1-channel input"):
        nc_stack_fused_lane(wide, x1, interpret=True)


def test_feasibility_gate():
    """Shape-class gate: PF-Pascal passes; InLoc-scale VMEM blowups, mixed
    kernel sizes, and even kernels are all rejected."""
    assert fused_lane_feasible(25, 25, 25, 25, (5, 5, 5), (16, 16, 1))
    assert fused_lane_feasible(13, 13, 13, 13, (3, 3), (16, 1))
    # InLoc fine grid: the fused kl dim alone is ~30k lanes
    assert not fused_lane_feasible(100, 75, 150, 200, (3, 3), (16, 1))
    assert not fused_lane_feasible(25, 25, 25, 25, (5, 3, 5), (16, 16, 1))
    assert not fused_lane_feasible(25, 25, 25, 25, (4, 4, 4), (16, 16, 1))
    # the chain returns the scalar volume: wider final layers are not the
    # NC-stack shape class
    assert not fused_lane_feasible(25, 25, 25, 25, (5, 5), (16, 16))


@pytest.mark.skipif(
    "TPU" in jax.devices()[0].device_kind,
    reason="on a TPU backend the default path legitimately routes to Mosaic",
)
def test_cpu_routing_falls_back_to_xla():
    """On the CPU backend the chooser must not route to Mosaic: the
    neigh_consensus output equals the XLA stack bit-for-bit."""
    from ncnet_tpu.models.ncnet import neigh_consensus

    key = jax.random.key(1)
    params = make_params(key, (3, 3), (4, 1), dtype=jnp.bfloat16)
    corr = (jax.random.normal(jax.random.key(2), (2, 7, 7, 7, 7)) * 0.5
            ).astype(jnp.bfloat16)
    out = neigh_consensus(params, corr, symmetric=True)
    # reference: the explicit XLA-only path
    ref = neigh_consensus(params, corr, symmetric=True, allow_pallas=False)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_mixed_precision_params_keep_xla_path():
    """bf16 volume + fp32 NC params must NOT take the fused path (which
    would SILENTLY downcast the weights to bf16): the gate keeps the XLA
    path, where the dtype mismatch fails loudly — the production API
    (ncnet_filter) always casts volume and params together."""
    from ncnet_tpu.models.ncnet import neigh_consensus

    params = make_params(jax.random.key(5), (3,), (1,), dtype=jnp.float32)
    corr = (jax.random.normal(jax.random.key(6), (1, 6, 6, 6, 6)) * 0.5
            ).astype(jnp.bfloat16)
    with pytest.raises(TypeError, match="same dtypes"):
        neigh_consensus(params, corr, symmetric=False)


def test_custom_vjp_matches_xla_grads(monkeypatch):
    """User-level jax.vjp THROUGH nc_stack_fused (the registered custom_vjp,
    not its private pieces) must produce the XLA stack's gradients — this
    exercises the defvjp wiring end-to-end.  The primal runs the RESIDENT
    kernel in interpret mode on CPU via monkeypatching the dispatcher the
    rule calls (the CPU chooser would otherwise route to XLA)."""
    import ncnet_tpu.ops.nc_fused_lane as mod

    key = jax.random.key(3)
    params = make_params(key, (3,), (1,), dtype=jnp.bfloat16)
    x = (jax.random.normal(jax.random.key(4), (1, 5, 5, 5, 5, 1)) * 0.5
         ).astype(jnp.bfloat16)

    monkeypatch.setattr(
        mod, "_fused_stack_impl",
        lambda p, xx: mod.nc_stack_resident(p, xx, interpret=True),
    )

    out_f, vjp_f = jax.vjp(mod.nc_stack_fused, params, x)
    out_ref, vjp_ref = jax.vjp(lambda pp, xx: xla_stack(pp, xx), params, x)
    np.testing.assert_allclose(
        np.asarray(out_f, np.float32), np.asarray(out_ref, np.float32),
        rtol=3e-2, atol=3e-2,
    )
    g = jnp.ones_like(out_ref)
    d_fused = vjp_f(g)
    d_ref = vjp_ref(g)
    for a, b in zip(jax.tree.leaves(d_fused), jax.tree.leaves(d_ref)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-3, atol=1e-3,
        )
