"""Fused-lane Pallas NC stack: numerics (interpret mode), gating, VJP.

The kernel's on-chip timing lives in tools/nc_fused_lane_probe.py (measured
2.0 vs 3.95 ms/volume against the XLA stack, v5e r5); these tests lock the
numerics and the routing so the fast path cannot drift from the XLA
formulations it replaces.  Reference semantics: NeighConsensus
(/root/reference/lib/model.py:122-153).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ncnet_tpu.ops.conv4d import conv4d
from ncnet_tpu.ops.nc_fused_lane import (
    fused_lane_feasible,
    nc_stack_fused,
    nc_stack_fused_lane,
)


def xla_stack(params, x):
    for layer in params:
        x = jax.nn.relu(conv4d(x, layer["w"], layer["b"]))
    return x


def make_params(key, kernels, channels, dtype=jnp.float32):
    params, c_in = [], 1
    for i, (k, c_out) in enumerate(zip(kernels, channels)):
        k1, k2, key = jax.random.split(key, 3)
        params.append({
            "w": jax.random.normal(k1, (k,) * 4 + (c_in, c_out), dtype) * 0.1,
            "b": jax.random.normal(k2, (c_out,), dtype) * 0.1,
        })
        c_in = c_out
    return params


@pytest.mark.parametrize("shape,kernels,channels", [
    ((2, 7, 7, 7, 7), (3, 3), (4, 1)),          # IVD-like 2-layer
    ((1, 6, 5, 7, 6), (3, 3, 3), (4, 4, 1)),    # rectangular, 3-layer
    ((1, 9, 9, 9, 9), (5, 5, 5), (4, 4, 1)),    # PF-Pascal k=5 class
])
def test_interpret_parity(shape, kernels, channels):
    """Interpret-mode fused chain == XLA stack (same bf16 inputs, f32
    comparison): locks the A-build order, the (r,s) lane-offset epilogue,
    the halo masks, and the thin-channel zero padding."""
    key = jax.random.key(0)
    # bf16 end-to-end: the kernel computes in bf16 (f32 dot accumulation),
    # so the XLA reference must see the same operands or the comparison
    # measures bf16 rounding, not the kernel
    params = make_params(key, kernels, channels, dtype=jnp.bfloat16)
    x = (jax.random.normal(jax.random.key(7), shape + (1,)) * 0.5
         ).astype(jnp.bfloat16)

    ref = np.asarray(xla_stack(params, x), np.float32)
    got = np.asarray(
        nc_stack_fused_lane(params, x, interpret=True), np.float32
    )
    scale = max(1e-6, float(np.max(np.abs(ref))))
    np.testing.assert_allclose(got / scale, ref / scale, atol=3e-2)


def test_rejects_multichannel_input():
    """The lane packing keeps only input channel 0 (x[..., 0]): calls whose
    volume or first layer carries more than 1 input channel must be rejected
    loudly, not silently given wrong results (ADVICE r5)."""
    params = make_params(jax.random.key(0), (3,), (1,), dtype=jnp.bfloat16)
    x2 = jnp.zeros((1, 5, 5, 5, 5, 2), jnp.bfloat16)
    with pytest.raises(AssertionError, match="1-channel input"):
        nc_stack_fused_lane(params, x2, interpret=True)
    # a first layer with c_in > 1 is the same class of misuse
    wide = [{"w": jnp.zeros((3, 3, 3, 3, 2, 1), jnp.bfloat16),
             "b": jnp.zeros((1,), jnp.bfloat16)}]
    x1 = jnp.zeros((1, 5, 5, 5, 5, 1), jnp.bfloat16)
    with pytest.raises(AssertionError, match="1-channel input"):
        nc_stack_fused_lane(wide, x1, interpret=True)


def test_feasibility_gate():
    """Shape-class gate: PF-Pascal passes; InLoc-scale VMEM blowups, mixed
    kernel sizes, and even kernels are all rejected."""
    assert fused_lane_feasible(25, 25, 25, 25, (5, 5, 5), (16, 16, 1))
    assert fused_lane_feasible(13, 13, 13, 13, (3, 3), (16, 1))
    # InLoc fine grid: the fused kl dim alone is ~30k lanes
    assert not fused_lane_feasible(100, 75, 150, 200, (3, 3), (16, 1))
    assert not fused_lane_feasible(25, 25, 25, 25, (5, 3, 5), (16, 16, 1))
    assert not fused_lane_feasible(25, 25, 25, 25, (4, 4, 4), (16, 16, 1))
    # the chain returns the scalar volume: wider final layers are not the
    # NC-stack shape class
    assert not fused_lane_feasible(25, 25, 25, 25, (5, 5), (16, 16))


@pytest.mark.skipif(
    "TPU" in jax.devices()[0].device_kind,
    reason="on a TPU backend the default path legitimately routes to Mosaic",
)
def test_cpu_routing_falls_back_to_xla():
    """On the CPU backend the chooser must not route to Mosaic: the
    neigh_consensus output equals the XLA stack bit-for-bit."""
    from ncnet_tpu.models.ncnet import neigh_consensus

    key = jax.random.key(1)
    params = make_params(key, (3, 3), (4, 1), dtype=jnp.bfloat16)
    corr = (jax.random.normal(jax.random.key(2), (2, 7, 7, 7, 7)) * 0.5
            ).astype(jnp.bfloat16)
    out = neigh_consensus(params, corr, symmetric=True)
    # reference: the explicit XLA-only path
    ref = neigh_consensus(params, corr, symmetric=True, allow_pallas=False)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_mixed_precision_params_keep_xla_path():
    """bf16 volume + fp32 NC params must NOT take the fused path (which
    would SILENTLY downcast the weights to bf16): the gate keeps the XLA
    path, where the dtype mismatch fails loudly — the production API
    (ncnet_filter) always casts volume and params together."""
    from ncnet_tpu.models.ncnet import neigh_consensus

    params = make_params(jax.random.key(5), (3,), (1,), dtype=jnp.float32)
    corr = (jax.random.normal(jax.random.key(6), (1, 6, 6, 6, 6)) * 0.5
            ).astype(jnp.bfloat16)
    with pytest.raises(TypeError, match="same dtypes"):
        neigh_consensus(params, corr, symmetric=False)


def test_custom_vjp_matches_xla_grads(monkeypatch):
    """User-level jax.vjp THROUGH nc_stack_fused (the registered custom_vjp,
    not its private pieces) must produce the XLA stack's gradients — this
    exercises the defvjp wiring end-to-end.  The primal runs in interpret
    mode on CPU via monkeypatching the forward the rule calls."""
    import ncnet_tpu.ops.nc_fused_lane as mod

    key = jax.random.key(3)
    params = make_params(key, (3,), (1,), dtype=jnp.bfloat16)
    x = (jax.random.normal(jax.random.key(4), (1, 5, 5, 5, 5, 1)) * 0.5
         ).astype(jnp.bfloat16)

    real = mod.nc_stack_fused_lane
    monkeypatch.setattr(
        mod, "nc_stack_fused_lane",
        lambda p, xx, interpret=True: real(p, xx, interpret=True),
    )

    out_f, vjp_f = jax.vjp(mod.nc_stack_fused, params, x)
    out_ref, vjp_ref = jax.vjp(lambda pp, xx: xla_stack(pp, xx), params, x)
    np.testing.assert_allclose(
        np.asarray(out_f, np.float32), np.asarray(out_ref, np.float32),
        rtol=3e-2, atol=3e-2,
    )
    g = jnp.ones_like(out_ref)
    d_fused = vjp_f(g)
    d_ref = vjp_ref(g)
    for a, b in zip(jax.tree.leaves(d_fused), jax.tree.leaves(d_ref)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-3, atol=1e-3,
        )
