"""Fused-lane Pallas NC stack: numerics (interpret mode), gating, VJP.

The kernel's on-chip timing lives in tools/nc_fused_lane_probe.py (measured
2.0 vs 3.95 ms/volume against the XLA stack, v5e r5); these tests lock the
numerics and the routing so the fast path cannot drift from the XLA
formulations it replaces.  Reference semantics: NeighConsensus
(/root/reference/lib/model.py:122-153).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ncnet_tpu.ops.conv4d import conv4d
from ncnet_tpu.ops.nc_fused_lane import (
    fused_lane_feasible,
    nc_stack_fused,
    nc_stack_fused_lane,
)


def xla_stack(params, x):
    for layer in params:
        x = jax.nn.relu(conv4d(x, layer["w"], layer["b"]))
    return x


def make_params(key, kernels, channels, dtype=jnp.float32):
    params, c_in = [], 1
    for i, (k, c_out) in enumerate(zip(kernels, channels)):
        k1, k2, key = jax.random.split(key, 3)
        params.append({
            "w": jax.random.normal(k1, (k,) * 4 + (c_in, c_out), dtype) * 0.1,
            "b": jax.random.normal(k2, (c_out,), dtype) * 0.1,
        })
        c_in = c_out
    return params


@pytest.mark.parametrize("shape,kernels,channels", [
    ((2, 7, 7, 7, 7), (3, 3), (4, 1)),          # IVD-like 2-layer
    ((1, 6, 5, 7, 6), (3, 3, 3), (4, 4, 1)),    # rectangular, 3-layer
    ((1, 9, 9, 9, 9), (5, 5, 5), (4, 4, 1)),    # PF-Pascal k=5 class
])
def test_interpret_parity(shape, kernels, channels):
    """Interpret-mode fused chain == XLA stack (same bf16 inputs, f32
    comparison): locks the A-build order, the (r,s) lane-offset epilogue,
    the halo masks, and the thin-channel zero padding."""
    key = jax.random.key(0)
    # bf16 end-to-end: the kernel computes in bf16 (f32 dot accumulation),
    # so the XLA reference must see the same operands or the comparison
    # measures bf16 rounding, not the kernel
    params = make_params(key, kernels, channels, dtype=jnp.bfloat16)
    x = (jax.random.normal(jax.random.key(7), shape + (1,)) * 0.5
         ).astype(jnp.bfloat16)

    ref = np.asarray(xla_stack(params, x), np.float32)
    got = np.asarray(
        nc_stack_fused_lane(params, x, interpret=True), np.float32
    )
    scale = max(1e-6, float(np.max(np.abs(ref))))
    np.testing.assert_allclose(got / scale, ref / scale, atol=3e-2)


def test_feasibility_gate():
    """Shape-class gate: PF-Pascal passes; InLoc-scale VMEM blowups, mixed
    kernel sizes, and even kernels are all rejected."""
    assert fused_lane_feasible(25, 25, 25, 25, (5, 5, 5), (16, 16, 1))
    assert fused_lane_feasible(13, 13, 13, 13, (3, 3), (16, 1))
    # InLoc fine grid: the fused kl dim alone is ~30k lanes
    assert not fused_lane_feasible(100, 75, 150, 200, (3, 3), (16, 1))
    assert not fused_lane_feasible(25, 25, 25, 25, (5, 3, 5), (16, 16, 1))
    assert not fused_lane_feasible(25, 25, 25, 25, (4, 4, 4), (16, 16, 1))
    # the chain returns the scalar volume: wider final layers are not the
    # NC-stack shape class
    assert not fused_lane_feasible(25, 25, 25, 25, (5, 5), (16, 16))


def test_cpu_routing_falls_back_to_xla():
    """On the CPU backend the chooser must not route to Mosaic: the
    neigh_consensus output equals the XLA stack bit-for-bit."""
    from ncnet_tpu.models.ncnet import neigh_consensus

    key = jax.random.key(1)
    params = make_params(key, (3, 3), (4, 1), dtype=jnp.bfloat16)
    corr = (jax.random.normal(jax.random.key(2), (2, 7, 7, 7, 7)) * 0.5
            ).astype(jnp.bfloat16)
    out = neigh_consensus(params, corr, symmetric=True)
    # reference: the explicit XLA-only path
    ref = neigh_consensus(params, corr, symmetric=True, allow_pallas=False)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_custom_vjp_matches_xla_grads():
    """jax.grad through nc_stack_fused must equal grads of the XLA stack
    (the VJP replays the XLA formulations; the forward here runs interpret
    via monkeypatching is unnecessary — on CPU the fused forward is only
    reachable in interpret mode, so compare the VJP rule directly)."""
    key = jax.random.key(3)
    params = make_params(key, (3,), (2,))
    x = jax.random.normal(jax.random.key(4), (1, 5, 5, 5, 5, 1)) * 0.5

    def loss_fused(p, x):
        # forward value comes from the fused path's own primal; its VJP is
        # defined as the XLA stack's — evaluate via jax.vjp directly
        _, vjp = jax.vjp(lambda pp, xx: nc_stack_fused(pp, xx), p, x)
        return vjp

    # build cotangent from the XLA forward (shapes match)
    out_ref, vjp_ref = jax.vjp(lambda pp, xx: xla_stack(pp, xx), params, x)
    g = jnp.ones_like(out_ref)

    # the fused op's bwd rule is exactly the XLA stack's VJP
    from ncnet_tpu.ops.nc_fused_lane import _fused_bwd

    d_fused = _fused_bwd((params, x), g)
    d_ref = vjp_ref(g)
    for a, b in zip(jax.tree.leaves(d_fused), jax.tree.leaves(d_ref)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-4, atol=1e-5,
        )
