"""Flagship-configuration integration test (VERDICT r1 "What's weak" #3).

Runs the REAL configuration — ResNet-101 trunk at 240², NC kernels (5,5,5),
channels (16,16,1) — through forward, weak loss, one train step, and the
batched PCK plumbing, on tiny synthetic data.  Slow on CPU; every
other test uses the tiny trunk, so this is the one place an integration break
in the production config is caught without the bench.
"""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.config import ModelConfig, TrainConfig
from ncnet_tpu import models, training
from ncnet_tpu.evaluation.pck import pck_metric
from ncnet_tpu.ops import corr_to_matches

pytestmark = pytest.mark.slow


FLAGSHIP = dict(
    backbone="resnet101",
    ncons_kernel_sizes=(5, 5, 5),
    ncons_channels=(16, 16, 1),
)


def test_flagship_forward_loss_trainstep_and_pck():
    cfg = ModelConfig(**FLAGSHIP)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # random-trunk warning is expected here
        tcfg = TrainConfig(model=cfg, batch_size=2, data_parallel=False)
        state, optimizer, mcfg, _ = training.create_train_state(tcfg)

    rng = np.random.default_rng(0)
    src = jnp.asarray(rng.uniform(-1, 1, (2, 240, 240, 3)).astype(np.float32))
    tgt = jnp.asarray(rng.uniform(-1, 1, (2, 240, 240, 3)).astype(np.float32))

    # forward: 240² → 15⁴ volume (the real trunk and NC config; 400² is
    # exercised by bench.py on the accelerator — 25⁴ on the CPU CI mesh is
    # too slow for the suite)
    out = jax.jit(
        lambda p, s, t: models.ncnet_forward(mcfg, p, s, t).corr
    )(state.params, src, tgt)
    assert out.shape == (2, 15, 15, 15, 15)
    assert bool(jnp.all(jnp.isfinite(out)))

    # one full train step at the flagship config
    step = training.make_train_step(mcfg, optimizer, donate=False,
                                    stop_backbone_grad=True)
    batch = {"source_image": src, "target_image": tgt}
    new_state, loss = step(state, batch)
    assert np.isfinite(float(loss))
    assert int(new_state.step) == 1
    # NC weights moved, trunk did not
    assert not np.allclose(np.asarray(new_state.params["nc"][0]["w"]),
                           np.asarray(state.params["nc"][0]["w"]))
    np.testing.assert_array_equal(
        np.asarray(new_state.params["backbone"]["conv1"]["w"]),
        np.asarray(state.params["backbone"]["conv1"]["w"]),
    )

    # batched PCK plumbing on the flagship volume
    matches = corr_to_matches(out, do_softmax=True)
    pts = rng.uniform(30, 210, (2, 2, 20)).astype(np.float32)
    eval_batch = {
        "source_points": jnp.asarray(pts),
        "target_points": jnp.asarray(pts),
        "source_im_size": jnp.full((2, 3), 240.0),
        "target_im_size": jnp.full((2, 3), 240.0),
        "L_pck": jnp.full((2, 1), 240.0),
    }
    per_pair = pck_metric(eval_batch, matches, alpha=0.1)
    assert per_pair.shape == (2,)
    assert bool(jnp.all((per_pair >= 0) & (per_pair <= 1)))
