"""Tier-1 tests for the round-9 telemetry consumers.

Three layers, each proven the way PR 5 proved the write side — by executing
the failure mode, not describing it:

  * span tracing: nesting/parenting round-trips through the event log,
    threads keep separate parent stacks, a subprocess SIGKILLed mid-span
    still yields a torn trace that ``tools/trace_export.py`` renders as
    valid Chrome trace JSON (the unclosed spans ARE the postmortem);
  * perf store + sentinel: ``tools/perf_regress.py`` flags an injected 2×
    step-wall regression against seeded history, stays green on noise, and
    runs clean against the repo's committed BENCH_r01–r05 seed at
    ``perf/history.jsonl`` (the CI gate);
  * tier autotune cache: a cache hit skips the compile probe (spy-counted),
    a demotion persists across a REAL process restart, and invalidation
    (device kind, schema, failed feasibility re-gate) degrades to probing.

The acceptance scenario closes the loop end to end: two instrumented
``fit`` runs produce an event log that exports to a valid trace, a span
breakdown in ``run_report --spans``, a perf store the sentinel gates, and a
heartbeat the stall watchdog judges.
"""

import importlib
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from ncnet_tpu.config import ModelConfig, TrainConfig
from ncnet_tpu.data.synthetic import write_pair_dataset
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.observability.device import Heartbeat
from ncnet_tpu.observability.events import EventLog, replay_events
from ncnet_tpu.observability.perfstore import (
    PerfStore,
    check_regressions,
    ingest_bench_artifact,
    metric_direction,
    resolve_store_path,
)
from ncnet_tpu.observability.tracing import current_span_id, span, traced
from ncnet_tpu.ops import tier_cache
import ncnet_tpu.ops.nc_fused_lane as lane
import ncnet_tpu.ops.nc_fused_lane_vjp as lane_vjp
from ncnet_tpu import training

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import perf_regress  # noqa: E402
import run_report  # noqa: E402
import stall_watchdog  # noqa: E402
import trace_export  # noqa: E402

TINY = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                   ncons_channels=(1,))


@pytest.fixture(autouse=True)
def _clean_slate():
    """No leaked global sink, runtime demotions, emitted-choice dedup state
    or in-process tier-cache mirror across tests (conftest already points
    the cache/store env knobs at 'off', so no on-disk state leaks either)."""
    obs_events.set_global_sink(None)
    lane._runtime_demoted.clear()
    lane._emitted_choices.clear()
    tier_cache._reset_state()
    yield
    obs_events.set_global_sink(None)
    lane._runtime_demoted.clear()
    lane._emitted_choices.clear()
    tier_cache._reset_state()


# ---------------------------------------------------------------------------
# span tracing: API contract
# ---------------------------------------------------------------------------


def test_span_is_inert_without_sink():
    with span("outer") as s:
        assert s._id is None          # nothing allocated
        assert current_span_id() is None  # no stack traffic either
    # and the no-op exit did not raise


def test_span_nesting_roundtrips_through_event_log(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(path)):
        with span("step", step=7) as outer:
            assert current_span_id() == outer._id
            with span("dispatch") as inner:
                assert current_span_id() == inner._id
            with span("loss_sync"):
                time.sleep(0.01)
        assert current_span_id() is None
    _, events = replay_events(path)
    sp = [e for e in events if e["event"] == "span"]
    begins = {e["span"]: e for e in sp if e["ph"] == "B"}
    ends = {e["span"]: e for e in sp if e["ph"] == "E"}
    assert set(begins) == set(ends) and len(begins) == 3
    by_name = {e["name"]: e for e in begins.values()}
    step_id = by_name["step"]["span"]
    assert by_name["step"]["parent"] is None
    assert by_name["step"]["step"] == 7            # fields ride on the B
    assert by_name["step"]["tid"] == threading.get_ident()
    assert by_name["dispatch"]["parent"] == step_id
    assert by_name["loss_sync"]["parent"] == step_id
    assert ends[by_name["loss_sync"]["span"]]["dur_s"] >= 0.01
    # entry order: step opens before its children, E of children precede
    # E of the parent in the log (append order == emit order)
    kinds = [(e["ph"], e["name"]) for e in sp]
    assert kinds[0] == ("B", "step") and kinds[-1] == ("E", "step")


def test_span_parents_are_per_thread(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(path)):
        with span("outer"):
            seen = {}

            def worker():
                with span("in_thread") as s:
                    seen["parent"] = s._parent
                    seen["tid_current"] = current_span_id() == s._id

            t = threading.Thread(target=worker)
            t.start()
            t.join()
    # the worker's span must NOT adopt the main thread's open span
    assert seen["parent"] is None and seen["tid_current"]
    _, events = replay_events(path)
    b = {e["name"]: e for e in events
         if e["event"] == "span" and e["ph"] == "B"}
    assert b["in_thread"]["parent"] is None
    assert b["in_thread"]["tid"] != b["outer"]["tid"]


def test_traced_decorator_and_error_annotation(tmp_path):
    path = str(tmp_path / "events.jsonl")

    @traced()
    def quick():
        return 42

    @traced("boom", phase="test")
    def explode():
        raise ValueError("no")

    with obs_events.bound(EventLog(path)):
        assert quick() == 42
        with pytest.raises(ValueError):
            explode()
    _, events = replay_events(path)
    sp = [e for e in events if e["event"] == "span"]
    names = {e["name"] for e in sp}
    assert names == {"quick", "boom"}   # default name = __name__
    (boom_e,) = [e for e in sp if e["ph"] == "E" and e["name"] == "boom"]
    assert boom_e["error"] == "ValueError"  # the E records how it died
    (boom_b,) = [e for e in sp if e["ph"] == "B" and e["name"] == "boom"]
    assert boom_b["phase"] == "test"


def test_span_out_of_order_exit_never_raises(tmp_path):
    """Telemetry must never raise into the run: closing spans out of order
    (a buggy caller holding both context managers manually) degrades to
    identity removal, and the stack still ends empty."""
    path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(path)):
        a, b = span("a"), span("b")
        a.__enter__()
        b.__enter__()
        a.__exit__(None, None, None)   # out of order
        b.__exit__(None, None, None)
        assert current_span_id() is None
    _, events = replay_events(path)
    assert sum(1 for e in events
               if e["event"] == "span" and e["ph"] == "E") == 2


# ---------------------------------------------------------------------------
# trace_export: Chrome trace rendering, torn traces
# ---------------------------------------------------------------------------


def test_trace_export_complete_spans_and_instant_markers(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(path)) as log:
        log.emit("run_start")
        with span("step", step=1):
            with span("dispatch"):
                pass
        log.emit("checkpoint_commit", step=1)
    trace = trace_export.build_trace([path])
    # valid JSON end to end
    doc = json.loads(json.dumps(trace))
    evs = doc["traceEvents"]
    slices = [e for e in evs if e["ph"] == "X"]
    assert {e["name"] for e in slices} == {"step", "dispatch"}
    for e in slices:
        assert e["dur"] >= 0 and e["ts"] > 0 and e["pid"] >= 1
    (step_slice,) = [e for e in slices if e["name"] == "step"]
    assert step_slice["args"]["step"] == 1   # B fields become args
    instants = {e["name"] for e in evs if e["ph"] == "i"}
    assert {"run_start", "checkpoint_commit"} <= instants
    # metadata names the run's process
    assert any(e["ph"] == "M" and e["name"] == "process_name" for e in evs)


def test_sigkill_mid_span_still_renders_torn_trace(tmp_path):
    """THE crash-visibility claim: a process SIGKILLed with two spans open
    leaves their fsynced B events on disk, and the exporter renders them as
    unclosed slices — even with a torn trailing line on the log."""
    path = str(tmp_path / "events.jsonl")
    worker = tmp_path / "worker.py"
    worker.write_text(f"""
import os, signal, sys
sys.path.insert(0, {_REPO!r})
from ncnet_tpu.observability.events import EventLog, set_global_sink
from ncnet_tpu.observability.tracing import span

set_global_sink(EventLog({path!r}))
with span("epoch", epoch=0):
    with span("step", step=3):
        os.kill(os.getpid(), signal.SIGKILL)
""")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, str(worker)], env=env,
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True, timeout=300)
    assert proc.returncode == -9, proc.stdout[-2000:]
    with open(path, "a") as f:
        f.write('{"t": 1.0, "run": "x", "seq": 99, "event": "to')  # torn
    trace = trace_export.build_trace([path])
    doc = json.loads(json.dumps(trace))
    unclosed = [e for e in doc["traceEvents"]
                if e["ph"] == "B" and e.get("args", {}).get("unclosed")]
    assert {e["name"] for e in unclosed} == {"epoch", "step"}
    assert all(e["ts"] > 0 for e in unclosed)
    # the CLI path writes a loadable file and exits 0 on the same torn log
    out = str(tmp_path / "trace.json")
    assert trace_export.main([path, "-o", out]) == 0
    with open(out) as f:
        assert json.load(f)["traceEvents"]


# ---------------------------------------------------------------------------
# run_report --spans: critical-path accounting
# ---------------------------------------------------------------------------


def _emit_span(log, ph, name, sid, parent=None, dur=None, t=None):
    fields = {"ph": ph, "name": name, "span": sid}
    if ph == "B":
        fields.update(parent=parent, tid=1)
    if dur is not None:
        fields["dur_s"] = dur
    log.emit("span", **fields)


def test_span_breakdown_self_vs_child_time(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with EventLog(path) as log:
        _emit_span(log, "B", "train_step", 1)
        _emit_span(log, "B", "dispatch", 2, parent=1)
        _emit_span(log, "E", "dispatch", 2, dur=0.3)
        _emit_span(log, "B", "loss_sync", 3, parent=1)
        _emit_span(log, "E", "loss_sync", 3, dur=0.2)
        _emit_span(log, "E", "train_step", 1, dur=1.0)
        _emit_span(log, "B", "fetch", 9)   # unclosed: in flight at death
    _, events = replay_events(path)
    sp = run_report.build_span_breakdown(events)
    groups = {(g["parent"], g["name"]): g for g in sp["groups"]}
    # self time = total minus time inside children, the critical-path rank
    assert groups[("-", "train_step")]["self_s"] == pytest.approx(0.5)
    assert groups[("-", "train_step")]["total_s"] == pytest.approx(1.0)
    assert groups[("train_step", "dispatch")]["total_s"] == pytest.approx(0.3)
    assert groups[("train_step", "loss_sync")]["mean_s"] == pytest.approx(0.2)
    assert sp["closed"] == 3 and sp["unclosed"] == 1
    # the report wires it in, and the text render names parent > child
    report = run_report.build_report([path])
    assert report["spans"]["unclosed"] == 1
    text = run_report.render_spans(report)
    assert "train_step > dispatch" in text and "1 unclosed" in text


# ---------------------------------------------------------------------------
# perf store: records, direction inference, the sentinel
# ---------------------------------------------------------------------------


def test_perfstore_roundtrip_tolerates_torn_and_foreign_lines(tmp_path):
    store = PerfStore(str(tmp_path / "h.jsonl"))
    store.append("train_step_ms", 100.0, device_kind="cpu", git_rev="abc")
    store.append("train_step_ms", 102.0, device_kind="cpu")
    with open(store.path, "a") as f:
        f.write("not json at all\n")
        f.write(json.dumps({"kind": "perf", "schema": 999,
                            "metric": "x", "value": 1}) + "\n")  # newer
        f.write('{"kind": "perf", "metric": "torn')              # torn tail
    recs = store.records()
    assert [r["value"] for r in recs] == [100.0, 102.0]
    assert recs[0]["git_rev"] == "abc"
    assert [r["value"] for r in store.history("train_step_ms", "cpu")] \
        == [100.0, 102.0]
    assert store.history("train_step_ms", "tpu") == []
    # append_many drops NaN and non-numeric values silently
    n = store.append_many({"a_ms": 1.0, "nan_ms": float("nan"),
                           "flag": True, "note": "x"}, device_kind="cpu")
    assert n == 1


def test_metric_direction_follows_naming_conventions():
    assert metric_direction("train_step_ms") == "lower"
    assert metric_direction("pf_pascal_eval_s_fetch") == "lower"
    assert metric_direction("pf_pascal_pck") == "higher"
    assert metric_direction("train_pairs_per_sec") == "higher"
    # derived ratios and constants are report-only: gating them teaches
    # operators to ignore the sentinel
    assert metric_direction("forward_bf16_mfu_executed_pct") is None
    assert metric_direction("vs_baseline") is None
    assert metric_direction("roofline_filter_ms") is None
    assert metric_direction("forward_bf16_tflops") is None


def test_sentinel_flags_2x_regression_and_stays_green_on_noise():
    def recs(values):
        return [{"kind": "perf", "metric": "train_step_ms", "value": v,
                 "device_kind": "cpu"} for v in values]

    baseline = [100.0, 103.0, 98.0, 101.0, 99.0]
    # 2x the median is far outside MAD + the relative floor
    (f,) = check_regressions(recs(baseline + [200.0]))
    assert f["status"] == "regression" and f["direction"] == "lower"
    assert f["baseline_median"] == pytest.approx(100.0)
    # ordinary noise stays green
    (f,) = check_regressions(recs(baseline + [104.0]))
    assert f["status"] == "ok"
    # improvement is never a regression
    (f,) = check_regressions(recs(baseline + [55.0]))
    assert f["status"] == "ok"
    # a gate that guesses is worse than no gate: thin history is skipped
    (f,) = check_regressions(recs([100.0, 200.0]))
    assert f["status"] == "skipped"
    # higher-is-better metrics flip the comparison
    pck = [{"kind": "perf", "metric": "pf_pascal_pck", "value": v,
            "device_kind": "cpu"} for v in (0.8, 0.81, 0.79, 0.4)]
    (f,) = check_regressions(pck)
    assert f["status"] == "regression" and f["direction"] == "higher"
    # report-only metrics are not judged unless explicitly listed
    mfu = [{"kind": "perf", "metric": "train_mfu_pct", "value": v,
            "device_kind": "cpu"} for v in (40.0, 41.0, 20.0)]
    assert check_regressions(mfu) == []
    # force-gating infers higher-is-better for the derived ratios: the MFU
    # halving is the regression, an improvement is never one
    (f,) = check_regressions(mfu, metrics=["train_mfu_pct"])
    assert f["status"] == "regression" and f["direction"] == "higher"
    mfu_up = mfu[:-1] + [dict(mfu[-1], value=55.0)]
    (f,) = check_regressions(mfu_up, metrics=["train_mfu_pct"])
    assert f["status"] == "ok"
    # force-gating a metric whose direction nothing can infer refuses to
    # guess: skipped with a reason, not judged lower-is-better
    odd = [{"kind": "perf", "metric": "mystery_quantity", "value": v,
            "device_kind": "cpu"} for v in (1.0, 1.1, 9.0)]
    (f,) = check_regressions(odd, metrics=["mystery_quantity"])
    assert f["status"] == "skipped" and "direction" in f["reason"]


def test_resolve_store_path_env_knob(monkeypatch):
    monkeypatch.setenv("NCNET_TPU_PERF_STORE", "off")
    assert resolve_store_path() is None          # ingestion disabled
    assert resolve_store_path("/x/y.jsonl") == "/x/y.jsonl"  # explicit wins
    monkeypatch.setenv("NCNET_TPU_PERF_STORE", "/env/h.jsonl")
    assert resolve_store_path() == "/env/h.jsonl"


def test_perf_regress_cli_gates_injected_regression(tmp_path, capsys):
    store_path = str(tmp_path / "h.jsonl")
    # seed from bench-shaped artifacts (the bare stdout-line format)
    arts = []
    for i, wall in enumerate([950.0, 1010.0, 980.0]):
        p = tmp_path / f"BENCH_x{i}.json"
        p.write_text(json.dumps({
            "metric": "pf_pascal_forward_ms_per_pair", "value": 11.7 + i / 10,
            "extra": {"train_step_ms": wall, "device_kind": "TPU v5 lite"},
        }))
        arts.append(str(p))
    rc = perf_regress.main(["--seed", *arts, "--store", store_path])
    assert rc == 0
    capsys.readouterr()
    # fresh value inside the noise band: green
    store = PerfStore(store_path)
    store.append("train_step_ms", 990.0, device_kind="TPU v5 lite")
    assert perf_regress.main(["--check", "--store", store_path]) == 0
    out = capsys.readouterr().out
    assert "0 regression(s)" in out
    # injected 2x step-wall regression: exit 1, named in the findings
    store.append("train_step_ms", 1980.0, device_kind="TPU v5 lite")
    assert perf_regress.main(["--check", "--store", store_path,
                              "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    (bad,) = [f for f in doc["findings"] if f["status"] == "regression"]
    assert bad["metric"] == "train_step_ms"


def test_perf_regress_check_is_clean_on_committed_seed_history(capsys):
    """The CI gate: the committed perf/history.jsonl — seeded from
    BENCH_r01–r05 — must gate green, or every job fails out of the box."""
    committed = os.path.join(_REPO, "perf", "history.jsonl")
    assert os.path.exists(committed), "committed seed history is missing"
    assert perf_regress.main(["--check", "--store", committed]) == 0
    out = capsys.readouterr().out
    assert "0 regression(s)" in out
    # and it is a REAL gate over that file, not a vacuous pass
    assert " ok," in out and "[ok]" in out


def test_seeding_from_committed_bench_artifacts(tmp_path):
    """Rebuilding a store from the repo's BENCH_r*.json reproduces the
    committed history: both artifact shapes (harness wrapper with parsed
    payload, wrapper with only a tail) ingest; the failed round contributes
    nothing."""
    store = PerfStore(str(tmp_path / "h.jsonl"))
    counts = {}
    for r in range(1, 6):
        p = os.path.join(_REPO, f"BENCH_r0{r}.json")
        counts[r] = ingest_bench_artifact(store, p)
    assert counts[2] == 0            # the failed round has no metrics
    assert sum(counts.values()) == len(store.records()) > 0
    committed = PerfStore(os.path.join(_REPO, "perf", "history.jsonl"))
    assert len(committed.records()) == len(store.records())


# ---------------------------------------------------------------------------
# tier autotune cache
# ---------------------------------------------------------------------------

ARGS = (25, 25, 25, 25, (5, 5, 5), (16, 16, 1))


def _arm_forward_probes(monkeypatch, results=None):
    """Green feasibility everywhere; compile probes spy-counted (the thing
    a cache hit must skip)."""
    results = results or {}
    conv4d_mod = importlib.import_module("ncnet_tpu.ops.conv4d")
    monkeypatch.setattr(conv4d_mod, "_pallas_available", lambda: True)
    # the arithmetic fft tier legitimately clears its gate at ARGS (k=5);
    # these tests are about the PALLAS ladder's cache discipline, so keep
    # it out of the way (its own routing lives in test_conv4d_tiers.py)
    fft_mod = importlib.import_module("ncnet_tpu.ops.conv4d_fft")
    monkeypatch.setattr(fft_mod, "fft_feasible", lambda *a: False)
    counts = {"resident": 0, "perlayer": 0}
    monkeypatch.setattr(lane, "fused_resident_feasible", lambda *a: True)
    monkeypatch.setattr(lane, "fused_lane_feasible", lambda *a: True)

    def resident_probe(*a):
        counts["resident"] += 1
        return results.get("resident", True)

    def perlayer_probe(*a):
        counts["perlayer"] += 1
        return results.get("perlayer", True)

    monkeypatch.setattr(lane, "fused_resident_compiles", resident_probe)
    monkeypatch.setattr(lane, "fused_lane_compiles", perlayer_probe)
    return counts


@pytest.fixture
def tier_cache_file(tmp_path, monkeypatch):
    path = str(tmp_path / "tier_cache.json")
    monkeypatch.setenv(tier_cache.CACHE_ENV, path)
    tier_cache._reset_state()
    return path


def test_tier_cache_hit_skips_compile_probe(tier_cache_file, monkeypatch,
                                            tmp_path):
    counts = _arm_forward_probes(monkeypatch)
    events_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(events_path)):
        assert lane.choose_fused_stack(*ARGS) == "resident"
        assert counts["resident"] == 1      # cold: the probe ran
        # "fresh process": forget the in-process mirror and dedup state,
        # keep the file — _reset_state is the designed process-restart analog
        tier_cache._reset_state()
        lane._emitted_choices.clear()
        counts["resident"] = counts["perlayer"] = 0
        assert lane.choose_fused_stack(*ARGS) == "resident"
        assert counts == {"resident": 0, "perlayer": 0}  # zero probes
    _, events = replay_events(events_path)
    selected = [e for e in events if e["event"] == "tier_selected"]
    assert [e["cached"] for e in selected] == [False, True]
    assert len({e["tier"] for e in selected}) == 1   # identical decision
    # the store event recorded the cold decision
    assert any(e["event"] == "tier_cache" and e["op"] == "store"
               for e in events)


def test_tier_cache_hit_skips_vjp_compile_probe(tier_cache_file, monkeypatch):
    monkeypatch.delenv("NCNET_FUSED_VJP_FORCE", raising=False)
    conv4d_mod = importlib.import_module("ncnet_tpu.ops.conv4d")
    monkeypatch.setattr(conv4d_mod, "_pallas_available", lambda: True)
    monkeypatch.setattr(lane_vjp, "fused_vjp_feasible", lambda *a: True)
    counts = {"vjp": 0}

    def vjp_probe(*a):
        counts["vjp"] += 1
        return True

    monkeypatch.setattr(lane_vjp, "fused_vjp_compiles", vjp_probe)
    assert lane_vjp.choose_fused_vjp(*ARGS) == "resident_vjp"
    assert counts["vjp"] == 1
    tier_cache._reset_state()
    lane._emitted_choices.clear()
    counts["vjp"] = 0
    assert lane_vjp.choose_fused_vjp(*ARGS) == "resident_vjp"
    assert counts["vjp"] == 0


def test_xla_outcome_is_not_cached(tier_cache_file, monkeypatch):
    """A failed compile probe may be transient (device busy, tunnel
    hiccup): the resulting XLA decision must not persist, or the shape
    would be locked out of its fast tier across every future process."""
    counts = _arm_forward_probes(monkeypatch, results={"resident": False,
                                                      "perlayer": False})
    assert lane.choose_fused_stack(*ARGS) is None
    assert counts["resident"] == 1
    assert tier_cache.lookup("forward", ARGS) is None   # nothing persisted
    # "next process": the probe recovers and the fast tier comes back
    tier_cache._reset_state()
    lane._emitted_choices.clear()
    counts2 = _arm_forward_probes(monkeypatch)
    assert lane.choose_fused_stack(*ARGS) == "resident"
    assert counts2["resident"] == 1    # re-probed, not replayed


def test_tier_downstream_of_failed_probe_is_not_cached(
        tier_cache_file, monkeypatch):
    """'perlayer' reached only because resident's probe failed is just as
    poisoned as an XLA outcome: caching it would pin the shape below its
    fast tier.  A clean-probe 'perlayer' (resident not a candidate) DOES
    cache."""
    counts = _arm_forward_probes(monkeypatch, results={"resident": False})
    assert lane.choose_fused_stack(*ARGS) == "perlayer"
    assert tier_cache.lookup("forward", ARGS) is None   # not persisted
    # next process: resident recovers and wins again
    tier_cache._reset_state()
    lane._emitted_choices.clear()
    counts = _arm_forward_probes(monkeypatch)
    assert lane.choose_fused_stack(*ARGS) == "resident"
    assert counts["resident"] == 1
    # clean perlayer (resident infeasible, its probe never ran) is cached
    tier_cache._reset_state()
    lane._emitted_choices.clear()
    os.remove(tier_cache_file)
    counts = _arm_forward_probes(monkeypatch)
    monkeypatch.setattr(lane, "fused_resident_feasible", lambda *a: False)
    assert lane.choose_fused_stack(*ARGS) == "perlayer"
    assert counts == {"resident": 0, "perlayer": 1}
    assert tier_cache.lookup("forward", ARGS) == ("perlayer",)


def test_vjp_force_knob_bypasses_the_cache(tier_cache_file, monkeypatch):
    """A forced decision is not a probe result: it must neither read nor
    poison the cache."""
    monkeypatch.setenv("NCNET_FUSED_VJP_FORCE", "interpret")
    monkeypatch.setattr(lane_vjp, "fused_vjp_feasible", lambda *a: True)
    assert lane_vjp.choose_fused_vjp(*ARGS) == "interpret"
    assert tier_cache.lookup("backward", ARGS) is None   # nothing written


def test_tier_cache_demotion_survives_in_process_restart(
        tier_cache_file, monkeypatch):
    counts = _arm_forward_probes(monkeypatch)
    assert lane.choose_fused_stack(*ARGS) == "resident"
    assert lane.demote_fused_tier() == "resident"
    # fresh-process analog: runtime registry and mirror both gone
    lane._runtime_demoted.clear()
    lane._emitted_choices.clear()
    tier_cache._reset_state()
    counts["resident"] = counts["perlayer"] = 0
    assert tier_cache.persistent_demotions() == {"resident"}
    # the crashed tier stays demoted: the chooser lands on the next tier
    # WITHOUT re-probing resident (its positive entry was dropped too)
    assert lane.choose_fused_stack(*ARGS) == "perlayer"
    assert counts["resident"] == 0 and counts["perlayer"] == 1
    # a deliberate re-probe re-arms everything, including the cache file
    lane.reset_fused_tier_demotions()
    assert not os.path.exists(tier_cache_file)
    assert tier_cache.persistent_demotions() == frozenset()
    counts["resident"] = 0
    assert lane.choose_fused_stack(*ARGS) == "resident"
    assert counts["resident"] == 1


_TIER_WORKER = """
import json, os, sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import importlib
import ncnet_tpu.ops.nc_fused_lane as lane

conv4d_mod = importlib.import_module("ncnet_tpu.ops.conv4d")
conv4d_mod._pallas_available = lambda: True
fft_mod = importlib.import_module("ncnet_tpu.ops.conv4d_fft")
fft_mod.fft_feasible = lambda *a: False   # Pallas-ladder test, not fft's
lane.fused_resident_feasible = lambda *a: True
lane.fused_lane_feasible = lambda *a: True
counts = {{"resident": 0, "perlayer": 0}}

def _resident(*a):
    counts["resident"] += 1
    return True

def _perlayer(*a):
    counts["perlayer"] += 1
    return True

lane.fused_resident_compiles = _resident
lane.fused_lane_compiles = _perlayer

args = (25, 25, 25, 25, (5, 5, 5), (16, 16, 1))
tier = lane.choose_fused_stack(*args)
if os.environ.get("TIER_WORKER_DEMOTE"):
    lane.demote_fused_tier()
print(json.dumps({{"tier": tier, "counts": counts}}))
"""


def test_tier_demotion_persists_across_real_processes(tmp_path):
    """The restart claim, proven with actual processes: process 1 chooses
    'resident' and crashes it (demotes); process 2, warm off the cache file
    alone, lands on 'perlayer' without ever probing resident."""
    cache = str(tmp_path / "tier_cache.json")
    worker = tmp_path / "worker.py"
    worker.write_text(_TIER_WORKER.format(repo=_REPO))
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", NCNET_TPU_TIER_CACHE=cache,
               TIER_WORKER_DEMOTE="1")
    p1 = subprocess.run([sys.executable, str(worker)], env=env, text=True,
                        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                        timeout=300)
    assert p1.returncode == 0, p1.stderr[-2000:]
    r1 = json.loads(p1.stdout)
    assert r1["tier"] == "resident" and r1["counts"]["resident"] == 1

    env.pop("TIER_WORKER_DEMOTE")
    p2 = subprocess.run([sys.executable, str(worker)], env=env, text=True,
                        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                        timeout=300)
    assert p2.returncode == 0, p2.stderr[-2000:]
    r2 = json.loads(p2.stdout)
    assert r2["tier"] == "perlayer"
    assert r2["counts"]["resident"] == 0   # never re-probed the dead tier


def test_tier_cache_misses_across_device_kinds(tier_cache_file, monkeypatch):
    monkeypatch.setattr(tier_cache, "device_kind", lambda: "TPU v5 lite")
    tier_cache.record("forward", ARGS, "resident")
    assert tier_cache.lookup("forward", ARGS) == ("resident",)
    # a different accelerator simply misses: nothing to invalidate
    monkeypatch.setattr(tier_cache, "device_kind", lambda: "TPU v6")
    assert tier_cache.lookup("forward", ARGS) is None


def test_tier_cache_ignores_foreign_and_newer_schema(tier_cache_file):
    tier_cache.record("forward", ARGS, "resident")
    with open(tier_cache_file) as f:
        doc = json.load(f)
    doc["schema"] = tier_cache.SCHEMA_VERSION + 1
    with open(tier_cache_file, "w") as f:
        json.dump(doc, f)
    tier_cache._reset_state()
    assert tier_cache.lookup("forward", ARGS) is None  # unreadable = miss
    # the next record overwrites the foreign file wholesale
    tier_cache.record("forward", ARGS, "perlayer")
    tier_cache._reset_state()
    assert tier_cache.lookup("forward", ARGS) == ("perlayer",)


def test_cached_tier_failing_feasibility_regate_reprobes(
        tier_cache_file, monkeypatch):
    """A cached decision written under different VMEM budget constants must
    degrade to a re-probe, not a doomed dispatch: the cheap feasibility
    gates still run on every hit."""
    counts = _arm_forward_probes(monkeypatch)
    assert lane.choose_fused_stack(*ARGS) == "resident"
    tier_cache._reset_state()
    lane._emitted_choices.clear()
    counts["resident"] = counts["perlayer"] = 0
    # the budget changed: resident no longer feasible
    monkeypatch.setattr(lane, "fused_resident_feasible", lambda *a: False)
    assert lane.choose_fused_stack(*ARGS) == "perlayer"
    assert counts["perlayer"] == 1          # re-probed on the live ladder


def test_tier_cache_disabled_is_inert(monkeypatch):
    monkeypatch.setenv(tier_cache.CACHE_ENV, "off")
    tier_cache._reset_state()
    assert tier_cache.cache_path() is None
    tier_cache.record("forward", ARGS, "resident")     # all no-ops
    assert tier_cache.lookup("forward", ARGS) is None
    tier_cache.record_demotion("resident")
    assert tier_cache.persistent_demotions() == frozenset()


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------


def test_stall_watchdog_verdicts(tmp_path, capsys):
    hb_path = str(tmp_path / "heartbeat.json")
    events_path = str(tmp_path / "events.jsonl")

    # no heartbeat: exit 2, distinct from stalled
    assert stall_watchdog.main([hb_path]) == 2
    capsys.readouterr()

    Heartbeat(hb_path, run_id="r1").beat(step=5)
    with EventLog(events_path) as log:
        for i, wall in enumerate([0.05, 0.04, 0.06, 0.05], start=1):
            log.emit("step", mode="train", step=i, wall_s=wall)

    # fresh beat: alive (threshold = max(min_age, 10 x median 0.05))
    verdict = stall_watchdog.judge(hb_path, factor=10.0, min_age=0.1)
    assert verdict["status"] == "alive"
    assert verdict["median_step_wall_s"] == pytest.approx(0.05)
    assert verdict["threshold_s"] == pytest.approx(0.5)
    assert verdict["last_beat"]["step"] == 5
    assert stall_watchdog.main([hb_path, "--min-age", "60"]) == 0
    capsys.readouterr()

    # age the heartbeat past the cadence-derived threshold: stalled
    old = time.time() - 30.0
    os.utime(hb_path, (old, old))
    verdict = stall_watchdog.judge(hb_path, factor=10.0, min_age=0.1)
    assert verdict["status"] == "stalled" and verdict["age_s"] > 29
    rc = stall_watchdog.main([hb_path, "--factor", "10", "--min-age", "0.1",
                              "--json"])
    assert rc == 3
    doc = json.loads(capsys.readouterr().out)
    assert doc["status"] == "stalled"

    # without a readable step cadence the floor is the whole threshold
    verdict = stall_watchdog.judge(hb_path, events_path=str(tmp_path / "no"),
                                   min_age=3600.0)
    assert verdict["status"] == "alive"
    assert verdict["median_step_wall_s"] is None
    assert verdict["threshold_s"] == 3600.0


# ---------------------------------------------------------------------------
# acceptance: the whole loop on a real instrumented fit
# ---------------------------------------------------------------------------


def test_acceptance_fit_trace_store_gate(tmp_path, monkeypatch):
    """End-to-end: two instrumented fit runs -> the event log renders to
    valid Chrome trace JSON with the step phases as spans; run_report
    --spans ranks them; both runs' summaries ingest into the perf store;
    the sentinel is green on the real pair and gates an injected 2x
    step-wall regression; the stall watchdog judges the artifact."""
    store_path = str(tmp_path / "history.jsonl")
    monkeypatch.setenv("NCNET_TPU_PERF_STORE", store_path)
    root = str(tmp_path / "data")
    write_pair_dataset(root, n_pairs=4, image_hw=(48, 48),
                       shift=(16, 16), seed=1)

    def run(out):
        cfg = TrainConfig(
            model=TINY, image_size=48,
            dataset_image_path=root,
            dataset_csv_path=root + "/image_pairs",
            num_epochs=1, batch_size=2, lr=1e-3,
            result_model_dir=str(tmp_path / out), log_interval=10,
            data_parallel=False,
        )
        return training.fit(cfg, progress=False)

    r1, r2 = run("out1"), run("out2")
    events_path = os.path.join(r2["checkpoint"], "telemetry",
                               "events.jsonl")

    # 1. the train-step phases are spans in the log
    _, events = replay_events(events_path)
    names = {e["name"] for e in events
             if e["event"] == "span" and e["ph"] == "B"}
    assert {"train_step", "dispatch", "stage", "loss_sync",
            "checkpoint_commit"} <= names

    # 2. trace export: valid Chrome trace JSON, phases nested under steps
    out = str(tmp_path / "trace.json")
    assert trace_export.main([events_path, "-o", out]) == 0
    with open(out) as f:
        doc = json.load(f)
    slices = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {"train_step", "dispatch", "loss_sync"} <= \
        {e["name"] for e in slices}
    steps = [e for e in slices if e["name"] == "train_step"]
    assert len(steps) == 2 and all(e["dur"] > 0 for e in steps)

    # 3. run_report --spans: the critical-path breakdown nests correctly
    report = run_report.build_report([events_path])
    labels = {(g["parent"], g["name"]) for g in report["spans"]["groups"]}
    assert ("train_step", "dispatch") in labels
    assert ("train_step", "loss_sync") in labels
    text = run_report.render_spans(report)
    assert "train_step > dispatch" in text

    # 4. both runs ingested into the perf store
    store = PerfStore(store_path)
    hist = store.history("train_step_wall_s")
    assert len(hist) == 2 and all(r["source"] == "fit" for r in hist)
    assert {r["run_id"] for r in hist} and hist[0]["device_kind"]

    # 5. the sentinel: green on the real pair, exit 1 after an injected
    # regression.  The two baseline points are REAL fit walls (cold vs warm
    # process: legitimately far apart), so the injection must clear the
    # MAD slack they imply for any spread: 10x the worst observed wall is
    # > median + max(mad_k*1.4826*mad, min_rel*median) whatever the pair
    # (the controlled-values 2x case is test_perf_regress_cli_gates_*)
    check = ["--check", "--store", store_path, "--metrics",
             "train_step_wall_s", "--min-history", "1", "--min-rel", "0.5"]
    assert perf_regress.main(check) == 0
    store.append("train_step_wall_s",
                 10.0 * max(r["value"] for r in hist),
                 device_kind=hist[-1]["device_kind"])
    assert perf_regress.main(check) == 1

    # 6. the watchdog judges the run's own artifact off its own cadence
    hb = os.path.join(r2["checkpoint"], "telemetry", "heartbeat.json")
    verdict = stall_watchdog.judge(hb, events_path=events_path,
                                   min_age=3600.0)
    assert verdict["status"] == "alive"
    assert verdict["median_step_wall_s"] > 0
    old = time.time() - 7200.0
    os.utime(hb, (old, old))
    assert stall_watchdog.judge(
        hb, events_path=events_path,
        min_age=1.0)["status"] == "stalled"
