"""Cross-framework parity for the InLoc match-extraction chain.

The InLoc headline depends on the POST-filter chain as much as the filter:
maxpool4d relocalization → ``corr_to_matches(scale='positive', delta4d,
k_size)`` in both directions → score-sort → coordinate dedup → cell-center
recentering (/root/reference/eval_inloc.py:134-190, lib/model.py:177-191,
lib/point_tnf.py:12-80).  This re-states that chain in torch/numpy verbatim
and runs the same filtered volume through our pieces
(``maxpool4d_with_argmax`` → ``corr_to_matches`` → ``recenter`` →
``sort_and_dedup``), comparing the final match tables.  The InLoc analog of
tests/test_torch_parity.py::test_pck_metric_matches_torch_twin.
"""

import numpy as np
import torch
import torch.nn.functional as F

import jax.numpy as jnp

from ncnet_tpu.evaluation.inloc import extract_match_table, sort_and_dedup
from ncnet_tpu.models.ncnet import NCNetOutput
from ncnet_tpu.ops.pooling import maxpool4d_with_argmax


def torch_maxpool4d(corr4d_hres, k_size):
    """lib/model.py:177-191 verbatim (integer div → //)."""
    slices = []
    for i in range(k_size):
        for j in range(k_size):
            for k in range(k_size):
                for m in range(k_size):
                    slices.append(
                        corr4d_hres[:, 0, i::k_size, j::k_size, k::k_size,
                                    m::k_size].unsqueeze(0))
    slices = torch.cat(tuple(slices), dim=1)
    corr4d, max_idx = torch.max(slices, dim=1, keepdim=True)
    max_l = torch.fmod(max_idx, k_size)
    max_k = torch.fmod((max_idx - max_l) // k_size, k_size)
    max_j = torch.fmod(((max_idx - max_l) // k_size - max_k) // k_size, k_size)
    max_i = (((max_idx - max_l) // k_size - max_k) // k_size - max_j) // k_size
    return corr4d, max_i, max_j, max_k, max_l


def torch_corr_to_matches(corr4d, delta4d=None, k_size=1, do_softmax=False,
                          scale="positive", invert_matching_direction=False):
    """lib/point_tnf.py:12-80 verbatim (CPU)."""
    batch_size, _, fs1, fs2, fs3, fs4 = corr4d.size()
    if scale == "centered":
        XA, YA = np.meshgrid(np.linspace(-1, 1, fs2 * k_size),
                             np.linspace(-1, 1, fs1 * k_size))
        XB, YB = np.meshgrid(np.linspace(-1, 1, fs4 * k_size),
                             np.linspace(-1, 1, fs3 * k_size))
    else:
        XA, YA = np.meshgrid(np.linspace(0, 1, fs2 * k_size),
                             np.linspace(0, 1, fs1 * k_size))
        XB, YB = np.meshgrid(np.linspace(0, 1, fs4 * k_size),
                             np.linspace(0, 1, fs3 * k_size))
    JA, IA = np.meshgrid(range(fs2), range(fs1))
    JB, IB = np.meshgrid(range(fs4), range(fs3))
    XA, YA = torch.FloatTensor(XA), torch.FloatTensor(YA)
    XB, YB = torch.FloatTensor(XB), torch.FloatTensor(YB)
    JA, IA = (torch.LongTensor(JA).view(1, -1), torch.LongTensor(IA).view(1, -1))
    JB, IB = (torch.LongTensor(JB).view(1, -1), torch.LongTensor(IB).view(1, -1))

    if invert_matching_direction:
        nc_A_Bvec = corr4d.view(batch_size, fs1, fs2, fs3 * fs4)
        if do_softmax:
            nc_A_Bvec = F.softmax(nc_A_Bvec, dim=3)
        match_A_vals, idx_A_Bvec = torch.max(nc_A_Bvec, dim=3)
        score = match_A_vals.view(batch_size, -1)
        iB = IB.view(-1)[idx_A_Bvec.view(-1)].view(batch_size, -1)
        jB = JB.view(-1)[idx_A_Bvec.view(-1)].view(batch_size, -1)
        iA = IA.expand_as(iB)
        jA = JA.expand_as(jB)
    else:
        nc_B_Avec = corr4d.view(batch_size, fs1 * fs2, fs3, fs4)
        if do_softmax:
            nc_B_Avec = F.softmax(nc_B_Avec, dim=1)
        match_B_vals, idx_B_Avec = torch.max(nc_B_Avec, dim=1)
        score = match_B_vals.view(batch_size, -1)
        iA = IA.view(-1)[idx_B_Avec.view(-1)].view(batch_size, -1)
        jA = JA.view(-1)[idx_B_Avec.view(-1)].view(batch_size, -1)
        iB = IB.expand_as(iA)
        jB = JB.expand_as(jA)

    if delta4d is not None:  # relocalization, point_tnf.py:60-71
        delta_iA, delta_jA, delta_iB, delta_jB = delta4d
        diA = delta_iA.squeeze(0).squeeze(0)[
            iA.view(-1), jA.view(-1), iB.view(-1), jB.view(-1)]
        djA = delta_jA.squeeze(0).squeeze(0)[
            iA.view(-1), jA.view(-1), iB.view(-1), jB.view(-1)]
        diB = delta_iB.squeeze(0).squeeze(0)[
            iA.view(-1), jA.view(-1), iB.view(-1), jB.view(-1)]
        djB = delta_jB.squeeze(0).squeeze(0)[
            iA.view(-1), jA.view(-1), iB.view(-1), jB.view(-1)]
        iA = iA * k_size + diA.expand_as(iA)
        jA = jA * k_size + djA.expand_as(jA)
        iB = iB * k_size + diB.expand_as(iB)
        jB = jB * k_size + djB.expand_as(jB)

    xA = XA[iA.view(-1), jA.view(-1)].view(batch_size, -1)
    yA = YA[iA.view(-1), jA.view(-1)].view(batch_size, -1)
    xB = XB[iB.view(-1), jB.view(-1)].view(batch_size, -1)
    yB = YB[iB.view(-1), jB.view(-1)].view(batch_size, -1)
    return xA, yA, xB, yB, score


def torch_inloc_matches(corr_fine, k_size, do_softmax=True):
    """eval_inloc.py:134-190: maxpool4d → both-direction matches → sort →
    dedup → recenter, returning the final (5, N) table."""
    c = torch.from_numpy(corr_fine)[:, None]  # (1, 1, hA, wA, hB, wB)
    corr4d, mi, mj, mk, ml = torch_maxpool4d(c, k_size)
    delta4d = (mi, mj, mk, ml)
    _, _, fs1, fs2, fs3, fs4 = corr4d.size()

    a = torch_corr_to_matches(corr4d, delta4d=delta4d, k_size=k_size,
                              do_softmax=do_softmax)
    b = torch_corr_to_matches(corr4d, delta4d=delta4d, k_size=k_size,
                              do_softmax=do_softmax,
                              invert_matching_direction=True)
    xA_, yA_, xB_, yB_, score_ = (
        torch.cat((u, v), 1) for u, v in zip(a, b))
    sorted_index = torch.sort(-score_)[1].squeeze()
    xA_, yA_, xB_, yB_, score_ = (
        v.squeeze()[sorted_index].unsqueeze(0)
        for v in (xA_, yA_, xB_, yB_, score_))
    concat_coords = np.concatenate(
        (xA_.numpy(), yA_.numpy(), xB_.numpy(), yB_.numpy()), 0)
    _, unique_index = np.unique(concat_coords, axis=1, return_index=True)
    ui = torch.LongTensor(unique_index)
    xA_, yA_, xB_, yB_, score_ = (
        v.squeeze()[ui].unsqueeze(0) for v in (xA_, yA_, xB_, yB_, score_))
    # recenter (eval_inloc.py:179-189)
    yA_ = yA_ * (fs1 * k_size - 1) / (fs1 * k_size) + 0.5 / (fs1 * k_size)
    xA_ = xA_ * (fs2 * k_size - 1) / (fs2 * k_size) + 0.5 / (fs2 * k_size)
    yB_ = yB_ * (fs3 * k_size - 1) / (fs3 * k_size) + 0.5 / (fs3 * k_size)
    xB_ = xB_ * (fs4 * k_size - 1) / (fs4 * k_size) + 0.5 / (fs4 * k_size)
    return np.stack([v.view(-1).numpy() for v in (xA_, yA_, xB_, yB_, score_)])


def ours_inloc_matches(corr_fine, k_size, do_softmax=True):
    """The PRODUCTION post-forward chain: pool → ``extract_match_table``
    (the same function the pair matcher jits) → host sort/dedup."""
    corr, delta4d = maxpool4d_with_argmax(jnp.asarray(corr_fine), k_size)
    table = extract_match_table(
        NCNetOutput(corr, delta4d), k_size=k_size, do_softmax=do_softmax,
        both_directions=True,
    )
    return np.stack(sort_and_dedup(*np.asarray(table)))


def _fine_volume(rng, ha, wa, hb, wb, c=64):
    fa = rng.standard_normal((1, ha, wa, c)).astype(np.float32)
    fb = rng.standard_normal((1, hb, wb, c)).astype(np.float32)
    fa /= np.linalg.norm(fa, axis=-1, keepdims=True)
    fb /= np.linalg.norm(fb, axis=-1, keepdims=True)
    return np.einsum("bijc,bklc->bijkl", fa, fb)


def test_inloc_match_chain_matches_torch_twin(rng):
    """Rectangular fine volume, k=2 relocalization, both directions: the
    final deduped match tables agree row for row."""
    corr = _fine_volume(rng, 24, 20, 16, 12)
    ours = ours_inloc_matches(corr, k_size=2)
    want = torch_inloc_matches(corr, k_size=2)
    assert ours.shape == want.shape
    np.testing.assert_allclose(ours[:4], want[:4], atol=1e-6)
    np.testing.assert_allclose(ours[4], want[4], rtol=1e-5, atol=1e-7)


def test_inloc_match_chain_matches_torch_twin_no_softmax(rng):
    corr = _fine_volume(rng, 12, 16, 20, 12)
    ours = ours_inloc_matches(corr, k_size=2, do_softmax=False)
    want = torch_inloc_matches(corr, k_size=2, do_softmax=False)
    np.testing.assert_allclose(ours[:4], want[:4], atol=1e-6)
    np.testing.assert_allclose(ours[4], want[4], rtol=1e-5, atol=1e-7)
