"""Resilient-inference tests: every eval-path failure mode the round-7 layer
claims to survive — per-query decode failures, injected runtime device
errors (tier demotion), hung fetches (watchdog), savemat failures, SIGKILL
mid-run — is executed deterministically through the ncnet_tpu/utils/faults.py
harness, whose hooks live inside the production code paths themselves.

The acceptance bars (ISSUE 3):
  (a) a quarantined query never aborts an eval run and appears in the
      manifest,
  (b) SIGKILL at an arbitrary step of PF-Pascal eval resumes to a
      bitwise-identical PCK result,
  (c) an injected mid-run Pallas/device runtime failure demotes the tier
      and the run completes with parity-correct outputs.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest
import jax

from ncnet_tpu import ops
from ncnet_tpu.config import (
    EvalInLocConfig,
    EvalPFPascalConfig,
    LocalizationConfig,
    ModelConfig,
)
from ncnet_tpu.data.synthetic import write_inloc_like, write_pf_pascal_like
from ncnet_tpu.evaluation import run_eval, run_inloc_eval
from ncnet_tpu.evaluation.inloc import match_capacity, validate_matches_mat
from ncnet_tpu.evaluation.pipeline import (
    FetchTimeoutError,
    PipelineDepthController,
    call_with_watchdog,
)
from ncnet_tpu.evaluation.resilience import (
    EvalJournal,
    FaultPolicy,
    RunManifest,
    classify_failure,
    run_isolated,
)
from ncnet_tpu.models.ncnet import init_ncnet
from ncnet_tpu.utils import faults
from ncnet_tpu.utils.faults import FaultPlan

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,), ncons_channels=(1,))
TINY_INLOC = TINY.replace(half_precision=True, relocalization_k_size=2)

# retry fast in tests: no real backoff sleeps
FAST = dict(query_retries=1, retry_backoff_s=0.0)


@pytest.fixture(autouse=True)
def _clean_state():
    """Every test starts and ends with no armed faults and no demoted
    tiers — the demotion registry is process-global by design."""
    faults.clear()
    ops.reset_fused_tier_demotions()
    yield
    faults.clear()
    ops.reset_fused_tier_demotions()


# ---------------------------------------------------------------------------
# unit: classification, policy loop, manifest, journal, watchdog, controller
# ---------------------------------------------------------------------------


def test_classify_failure_kinds():
    from ncnet_tpu.data.datasets import SampleDecodeError

    assert classify_failure(FetchTimeoutError("x")) == "timeout"
    assert classify_failure(faults.InjectedDeviceError("x")) == "device"
    assert classify_failure(
        SampleDecodeError("x.jpg", OSError("bad header"))) == "decode"
    assert classify_failure(faults.InjectedFault("decode failure")) == "decode"
    assert classify_failure(FileNotFoundError("no such file")) == "io"
    assert classify_failure(ValueError("boom")) == "other"


def test_run_manifest_transitions_and_reload(tmp_path):
    path = str(tmp_path / "manifest.json")
    m = RunManifest(path, meta={"experiment": "e1"})
    m.begin("q1")
    assert "q1" in m.data["in_flight"]
    m.complete("q1", skipped=False)
    m.begin("q2")
    m.quarantine("q2", "decode", "bad pano", attempts=3)
    assert m.is_completed("q1") and not m.is_completed("q2")

    # reload from disk: a fresh process sees the same state
    m2 = RunManifest(path)
    assert m2.is_completed("q1")
    assert m2.data["quarantined"]["q2"]["kind"] == "decode"
    assert m2.data["in_flight"] == []
    # a re-run to completion leaves quarantine
    m2.complete("q2")
    assert not RunManifest(path).data["quarantined"]

    # a manifest whose meta fingerprints a DIFFERENT configuration is not
    # adopted (same guard as the journal header)
    m_other = RunManifest(path, meta={"experiment": "e2"})
    assert m_other.data["completed"] == {}
    # ...while the matching configuration still resumes it
    assert RunManifest(path, meta={"experiment": "e1"}).is_completed("q1")

    # an unreadable manifest starts fresh instead of crashing the run
    with open(path, "w") as f:
        f.write("{ torn json")
    m3 = RunManifest(path, meta={"experiment": "e1"})
    assert m3.data["completed"] == {}


def test_run_isolated_retries_then_quarantines(tmp_path):
    m = RunManifest(str(tmp_path / "m.json"))
    calls = []

    def flaky_then_ok():
        calls.append(1)
        if len(calls) < 2:
            raise OSError("transient")
        return 42

    ok, out = run_isolated("u1", flaky_then_ok,
                           policy=FaultPolicy(1, 0.0, True), manifest=m)
    assert (ok, out) == (True, 42) and m.is_completed("u1")

    def always_bad():
        raise OSError("permanent")

    ok, out = run_isolated("u2", always_bad,
                           policy=FaultPolicy(1, 0.0, True), manifest=m)
    assert (ok, out) == (False, None)
    assert m.data["quarantined"]["u2"]["kind"] == "io"
    assert m.data["quarantined"]["u2"]["attempts"] == 2  # 1 + 1 retry

    # quarantine=False restores fail-fast
    with pytest.raises(OSError, match="permanent"):
        run_isolated("u3", always_bad, policy=FaultPolicy(0, 0.0, False))


def test_run_isolated_free_retry_on_recovery():
    """An on_failure recovery (tier demotion) grants an off-budget retry:
    with retries=0, one recovered failure must still reach success."""
    calls = []
    recoveries = []

    def work():
        calls.append(1)
        if len(calls) < 2:
            raise faults.InjectedDeviceError("oom")
        return "done"

    def on_failure(exc, kind):
        recoveries.append(kind)
        return "resident" if len(recoveries) == 1 else None

    ok, out = run_isolated("u", work, policy=FaultPolicy(0, 0.0, True),
                           on_failure=on_failure)
    assert (ok, out) == (True, "done")
    assert recoveries == ["device"]


def test_eval_journal_roundtrip_torn_tail_and_header_mismatch(tmp_path):
    path = str(tmp_path / "j.jsonl")
    header = {"batch_size": 2, "alpha": 0.1}
    j = EvalJournal(path, header)
    a0 = np.asarray([0.25, 0.5], dtype=np.float32)
    a1 = np.asarray([1.0 / 3.0], dtype=np.float32)  # not exactly representable
    j.append(0, a0)
    j.append(1, a1)
    j.close()

    # torn tail: a partial trailing line must be dropped, earlier entries kept
    with open(path, "a") as f:
        f.write('{"batch": 2, "pck"')
    j2 = EvalJournal(path, header)
    assert sorted(j2.entries) == [0, 1]
    np.testing.assert_array_equal(j2.entries[0], a0)
    np.testing.assert_array_equal(j2.entries[1], a1)  # bitwise, not approx
    # the torn bytes were truncated, so a post-resume append starts on a
    # fresh line — a SECOND kill/resume cycle must still see every record
    # (append-onto-partial-line would corrupt the file mid-way)
    a2 = np.asarray([0.75], dtype=np.float32)
    j2.append(2, a2)
    j2.close()
    j2b = EvalJournal(path, header)
    assert sorted(j2b.entries) == [0, 1, 2]
    np.testing.assert_array_equal(j2b.entries[2], a2)
    j2b.close()

    # a PARSEABLE but newline-less final record (write torn exactly at the
    # '\n' boundary) is dropped too: accepting it would let the next append
    # fuse onto it, corrupting the record for every later resume
    with open(path, "rb") as f:
        intact = f.read()
    assert intact.endswith(b"\n")
    with open(path, "wb") as f:
        f.write(intact[:-1])
    j2c = EvalJournal(path, header)
    assert sorted(j2c.entries) == [0, 1]  # record 2 recomputes
    j2c.append(2, a2)
    j2c.close()
    assert sorted(EvalJournal(path, header).entries) == [0, 1, 2]

    # header mismatch (different settings): fresh start, but the displaced
    # run's journal is SET ASIDE (.stale), never destroyed at construction
    j3 = EvalJournal(path, {"batch_size": 4, "alpha": 0.1})
    assert j3.entries == {}
    j3.close()
    stale = EvalJournal(path + ".stale", header)
    assert sorted(stale.entries) == [0, 1, 2]  # the old run survived intact
    stale.close()


def test_resilient_jit_retrace_actually_retraces():
    """retrace() must produce a NEW trace (re-consulting the tier chooser),
    not replay jax's identity-keyed cached jaxpr — re-jitting the same
    function object silently no-ops (jax 0.4.37), which would make the
    whole tier-degradation recovery a dead path on a real TPU."""
    import jax.numpy as jnp

    from ncnet_tpu.models.ncnet import ResilientJit

    traces = [0]

    def f(x, *, flag=False):
        traces[0] += 1  # counts Python traces, not executions
        return x + (1 if flag else 2)

    rj = ResilientJit(f, hook=False, static_argnames=("flag",))
    np.testing.assert_array_equal(np.asarray(rj(jnp.zeros(2), flag=True)),
                                  [1.0, 1.0])
    assert traces[0] == 1
    rj(jnp.zeros(2), flag=True)  # cached: no new trace
    assert traces[0] == 1
    rj.retrace()
    np.testing.assert_array_equal(np.asarray(rj(jnp.zeros(2), flag=True)),
                                  [1.0, 1.0])
    assert traces[0] == 2  # the retrace really re-traced
    rj(jnp.zeros(2), flag=False)  # static_argnames still resolves
    assert traces[0] == 3


def test_quarantine_breaker_trips_on_streak():
    from ncnet_tpu.evaluation.resilience import (
        QuarantineBreaker,
        SystemicEvalError,
    )

    b = QuarantineBreaker(3)
    b.note(True)
    b.note(True)
    b.note(False)  # a completed unit resets the streak
    b.note(True)
    b.note(True)
    with pytest.raises(SystemicEvalError, match="systemic"):
        b.note(True)
    disabled = QuarantineBreaker(0)
    for _ in range(20):
        disabled.note(True)  # limit <= 0: never trips


def test_eval_journal_torn_write_sealed_before_next_append(tmp_path):
    """A write that failed part-way (ENOSPC) leaves a torn prefix; the next
    append must seal it with a newline so the retried record — and every
    later one — survives the next resume (only the torn line is skipped)."""
    path = str(tmp_path / "j.jsonl")
    header = {"v": 1}
    j = EvalJournal(path, header)
    a0 = np.asarray([0.5], dtype=np.float32)
    j.append(0, a0)
    # simulate the failed-write crash window: torn bytes on disk, dirty flag
    # set (as _write_raw leaves it when write/flush raises mid-way)
    j._f.write('{"batch": 1, "pck')
    j._f.flush()
    j._dirty = True
    a1 = np.asarray([0.25], dtype=np.float32)
    j.append(1, a1)  # the retry after the failed write
    j.close()
    j2 = EvalJournal(path, header)
    assert sorted(j2.entries) == [0, 1]
    np.testing.assert_array_equal(j2.entries[1], a1)
    j2.close()


def test_inloc_systemic_failure_aborts_not_mass_quarantine(tmp_path):
    """When EVERY query fails (dead link, wrong dataset root), the run must
    abort after the consecutive-quarantine limit instead of quarantining an
    hours-long run one query at a time and exiting 'successfully'."""
    from ncnet_tpu.evaluation.resilience import SystemicEvalError

    root, params, kw = _inloc_setup(tmp_path, n_queries=6)
    config = EvalInLocConfig(output_root=os.path.join(root, "m"),
                             **FAST, **kw)
    with faults.injected(FaultPlan(decode_fail_substring="query/iphone7")):
        with pytest.raises(SystemicEvalError, match="consecutive"):
            run_inloc_eval(config, model_config=TINY_INLOC, params=params,
                           progress=False)


def test_call_with_watchdog_paths():
    assert call_with_watchdog(lambda x: x + 1, (1,), timeout=0.0) == 2
    assert call_with_watchdog(lambda: "ok", timeout=5.0) == "ok"
    with pytest.raises(ValueError, match="inner"):
        call_with_watchdog(lambda: (_ for _ in ()).throw(ValueError("inner")),
                           timeout=5.0)
    with pytest.raises(FetchTimeoutError, match="watchdog"):
        call_with_watchdog(time.sleep, (5.0,), timeout=0.1, label="hung")


def test_controller_note_failure_clears_anchor_and_window(monkeypatch):
    """After an aborted drain, the next drain must re-anchor instead of
    recording a refill-spanning wall that could trigger a spurious deepen
    (the ADVICE r4 bug class, now on the retry path)."""
    import ncnet_tpu.evaluation.pipeline as pipeline_mod

    now = [0.0]
    monkeypatch.setattr(pipeline_mod.time, "perf_counter", lambda: now[0])
    ctl = PipelineDepthController(0, high=0.7, low=0.45)
    ctl.note_drain()
    for _ in range(3):
        now[0] += 0.3
        ctl.note_drain()
    assert ctl._ewma == pytest.approx(0.3)

    ctl.note_failure()  # aborted drain: retry + backoff follow
    assert ctl._t_last is None and ctl._ewma is None
    assert ctl.best == pytest.approx(0.3)  # device-compute estimate survives
    now[0] += 100.0  # the retry's refill gap
    ctl.note_drain()  # re-anchors; must NOT record 100 s
    assert ctl._ewma is None
    for _ in range(4):
        now[0] += 0.3
        ctl.note_drain()
    assert ctl.depth == 2  # no spurious deepen from the failure


def test_demotion_registry_and_choose_fused_stack(monkeypatch):
    """demote_fused_tier walks resident → perlayer → None, and
    choose_fused_stack skips demoted tiers even where the compile probes
    stay green."""
    import importlib

    import ncnet_tpu.ops.nc_fused_lane as lane

    # the package re-exports a FUNCTION named conv4d, shadowing the module
    # attribute — resolve the module through importlib
    conv4d_mod = importlib.import_module("ncnet_tpu.ops.conv4d")
    monkeypatch.setattr(conv4d_mod, "_pallas_available", lambda: True)
    # the fft tier clears its gate at this k=5 shape; this test is about
    # the Pallas demotion walk, so keep it out (test_conv4d_tiers.py owns
    # the arithmetic tiers' demotion coverage)
    fft_mod = importlib.import_module("ncnet_tpu.ops.conv4d_fft")
    monkeypatch.setattr(fft_mod, "fft_feasible", lambda *a: False)
    for name in ("fused_resident_feasible", "fused_resident_compiles",
                 "fused_lane_feasible", "fused_lane_compiles"):
        monkeypatch.setattr(lane, name, lambda *a, **k: True)

    args = (25, 25, 25, 25, (5, 5, 5), (16, 16, 1))
    assert lane.choose_fused_stack(*args) == "resident"
    assert lane.demote_fused_tier() == "resident"
    assert lane.choose_fused_stack(*args) == "perlayer"
    assert lane.demote_fused_tier() == "perlayer"
    assert lane.choose_fused_stack(*args) is None
    assert lane.demote_fused_tier() is None  # nothing left: real error
    assert lane.demoted_fused_tiers() == {"resident", "perlayer"}
    lane.reset_fused_tier_demotions()
    assert lane.choose_fused_stack(*args) == "resident"


# ---------------------------------------------------------------------------
# InLoc eval: per-query isolation end to end
# ---------------------------------------------------------------------------


def _inloc_setup(tmp_path, n_queries=3, n_panos=1):
    root = str(tmp_path)
    shortlist = write_inloc_like(root, n_queries=n_queries, n_panos=n_panos,
                                 image_hw=(96, 128))
    params = init_ncnet(TINY_INLOC, jax.random.key(0))
    kw = dict(
        inloc_shortlist=shortlist, k_size=2, image_size=128,
        n_queries=n_queries, n_panos=n_panos,
        pano_path=os.path.join(root, "pano"),
        query_path=os.path.join(root, "query", "iphone7"),
    )
    return root, params, kw


def _load_all_matches(out_dir):
    from scipy.io import loadmat

    out = {}
    for name in sorted(os.listdir(out_dir)):
        if name.endswith(".mat"):
            out[name] = loadmat(os.path.join(out_dir, name))["matches"]
    return out


def test_inloc_permanent_decode_failure_quarantines_not_aborts(tmp_path):
    """Acceptance (a): a query whose image never decodes is retried, then
    quarantined into the manifest; the OTHER queries' .mat files are
    written and the run returns normally."""
    root, params, kw = _inloc_setup(tmp_path)
    config = EvalInLocConfig(
        output_root=os.path.join(root, "m"), **FAST, **kw)
    with faults.injected(FaultPlan(decode_fail_substring="query_1.jpg")):
        out_dir = run_inloc_eval(config, model_config=TINY_INLOC,
                                 params=params, progress=False)
    names = sorted(n for n in os.listdir(out_dir) if n.endswith(".mat"))
    assert names == ["1.mat", "3.mat"]  # query 2 (file 2.mat) given up on
    manifest = json.load(open(os.path.join(out_dir, "manifest.json")))
    assert manifest["quarantined"]["query_2"]["kind"] == "decode"
    assert manifest["quarantined"]["query_2"]["attempts"] == 2  # 1 + 1 retry
    assert set(manifest["completed"]) == {"query_1", "query_3"}
    assert manifest["in_flight"] == []


def test_inloc_transient_decode_failure_absorbed_by_retry(tmp_path):
    """A decode fault that clears on the second attempt costs one retry and
    nothing else — every query completes identically to a clean run."""
    root, params, kw = _inloc_setup(tmp_path, n_queries=2)
    clean = run_inloc_eval(
        EvalInLocConfig(output_root=os.path.join(root, "clean"), **FAST, **kw),
        model_config=TINY_INLOC, params=params, progress=False)
    with faults.injected(FaultPlan(decode_fail_substring="query_0.jpg",
                                   decode_fail_times=1)):
        faulty = run_inloc_eval(
            EvalInLocConfig(output_root=os.path.join(root, "f"), **FAST, **kw),
            model_config=TINY_INLOC, params=params, progress=False)
    a, b = _load_all_matches(clean), _load_all_matches(faulty)
    assert sorted(a) == sorted(b) == ["1.mat", "2.mat"]
    for name in a:
        np.testing.assert_array_equal(a[name], b[name])
    manifest = json.load(open(os.path.join(faulty, "manifest.json")))
    assert not manifest["quarantined"]


def test_inloc_device_error_demotes_tier_and_completes_parity(tmp_path):
    """Acceptance (c): an injected runtime device failure on the first pair
    dispatch demotes the fused tier, re-traces, and the run completes with
    outputs identical to a clean run (on CPU both runs execute the XLA
    stack; the demotion is registry-visible)."""
    root, params, kw = _inloc_setup(tmp_path, n_queries=2)
    clean = run_inloc_eval(
        EvalInLocConfig(output_root=os.path.join(root, "clean"), **FAST, **kw),
        model_config=TINY_INLOC, params=params, progress=False)
    assert ops.demoted_fused_tiers() == frozenset()
    with faults.injected(FaultPlan(device_fail_calls=(1,))):
        faulty = run_inloc_eval(
            EvalInLocConfig(output_root=os.path.join(root, "f"), **FAST, **kw),
            model_config=TINY_INLOC, params=params, progress=False)
    assert ops.demoted_fused_tiers() == {"resident"}
    a, b = _load_all_matches(clean), _load_all_matches(faulty)
    for name in a:
        np.testing.assert_array_equal(a[name], b[name])
    manifest = json.load(open(os.path.join(faulty, "manifest.json")))
    assert set(manifest["completed"]) == {"query_1", "query_2"}
    assert not manifest["quarantined"]


def test_inloc_hung_fetch_becomes_retryable_timeout(tmp_path):
    """A hung fetch (injected sleep > watchdog budget) surfaces as a
    FetchTimeoutError, the query retries, and the run completes with
    parity-correct outputs."""
    root, params, kw = _inloc_setup(tmp_path, n_queries=1)
    clean = run_inloc_eval(
        EvalInLocConfig(output_root=os.path.join(root, "clean"), **FAST, **kw),
        model_config=TINY_INLOC, params=params, progress=False)
    with faults.injected(FaultPlan(hang_fetch_calls=(1,),
                                   hang_fetch_seconds=10.0)):
        faulty = run_inloc_eval(
            EvalInLocConfig(output_root=os.path.join(root, "f"),
                            fetch_timeout_s=0.5, **FAST, **kw),
            model_config=TINY_INLOC, params=params, progress=False)
    a, b = _load_all_matches(clean), _load_all_matches(faulty)
    np.testing.assert_array_equal(a["1.mat"], b["1.mat"])
    manifest = json.load(open(os.path.join(faulty, "manifest.json")))
    assert set(manifest["completed"]) == {"query_1"}


def test_inloc_transient_savemat_failure_retried(tmp_path):
    """An artifact write that fails once (flaky NFS) is absorbed by the
    per-query retry; the artifact appears and validates."""
    root, params, kw = _inloc_setup(tmp_path, n_queries=1)
    config = EvalInLocConfig(output_root=os.path.join(root, "m"), **FAST, **kw)
    with faults.injected(FaultPlan(savemat_fail_substring="1.mat",
                                   savemat_fail_times=1)):
        out_dir = run_inloc_eval(config, model_config=TINY_INLOC,
                                 params=params, progress=False)
    n_cap = match_capacity(128, 2, both_directions=True)
    assert validate_matches_mat(os.path.join(out_dir, "1.mat"), 1, n_cap)
    manifest = json.load(open(os.path.join(out_dir, "manifest.json")))
    assert manifest["completed"]["query_1"] == {}
    assert not manifest["quarantined"]


def test_inloc_skip_existing_validates_artifact(tmp_path):
    """A foreign/truncated .mat under skip_existing is recomputed instead of
    silently poisoning the downstream PnP stage; a VALID artifact is still
    skipped untouched.  'Foreign' means the run manifest cannot vouch for
    it — manifest-vouched artifacts skip the per-resume loadmat validation
    entirely (our writer commits atomically, so they cannot be torn)."""
    root, params, kw = _inloc_setup(tmp_path, n_queries=2)
    config = EvalInLocConfig(output_root=os.path.join(root, "m"), **FAST, **kw)
    out_dir = run_inloc_eval(config, model_config=TINY_INLOC, params=params,
                             progress=False)
    good = _load_all_matches(out_dir)
    p1, p2 = (os.path.join(out_dir, n) for n in ("1.mat", "2.mat"))
    # foreign provenance: artifacts present but no manifest vouches for them
    # (e.g. hand-copied into a fresh experiment directory)
    os.remove(os.path.join(out_dir, "manifest.json"))
    with open(p1, "wb") as f:
        f.write(b"MATLAB 5.0 -- truncated garbage")
    mtime2 = os.path.getmtime(p2)
    n_cap = match_capacity(128, 2, both_directions=True)
    assert not validate_matches_mat(p1, 2, n_cap)

    out_dir2 = run_inloc_eval(config, model_config=TINY_INLOC, params=params,
                              progress=False)
    assert out_dir2 == out_dir
    recomputed = _load_all_matches(out_dir)
    np.testing.assert_array_equal(recomputed["1.mat"], good["1.mat"])
    assert os.path.getmtime(p2) == mtime2  # valid artifact untouched


def test_inloc_quarantine_false_restores_fail_fast(tmp_path):
    root, params, kw = _inloc_setup(tmp_path, n_queries=1)
    config = EvalInLocConfig(output_root=os.path.join(root, "m"),
                             quarantine=False, query_retries=0,
                             retry_backoff_s=0.0, **kw)
    with faults.injected(FaultPlan(decode_fail_substring="query_0.jpg")):
        with pytest.raises(faults.InjectedFault):
            run_inloc_eval(config, model_config=TINY_INLOC, params=params,
                           progress=False)


def test_inloc_kill_mid_savemat_then_resume_is_bitwise_identical(tmp_path):
    """SIGKILL between a per-query artifact's temp write and its commit
    rename: the rerun must skip the intact query-1 artifact untouched,
    recompute the torn query, and end with a .mat set bitwise-identical to
    an uninterrupted run."""
    root, params, kw = _inloc_setup(tmp_path, n_queries=3)

    worker = tmp_path / "worker.py"
    worker.write_text(f"""
import sys
sys.path.insert(0, {_REPO!r})
import jax
jax.config.update("jax_platforms", "cpu")
from ncnet_tpu.config import EvalInLocConfig, ModelConfig
from ncnet_tpu.evaluation import run_inloc_eval
from ncnet_tpu.models.ncnet import init_ncnet

model_config = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                           ncons_channels=(1,), half_precision=True,
                           relocalization_k_size=2)
params = init_ncnet(model_config, jax.random.key(0))
config = EvalInLocConfig(
    inloc_shortlist={kw['inloc_shortlist']!r},
    k_size=2, image_size=128, n_queries=3, n_panos=1,
    pano_path={kw['pano_path']!r},
    query_path={kw['query_path']!r},
    output_root={os.path.join(root, 'm')!r},
    query_retries=1, retry_backoff_s=0.0,
)
run_inloc_eval(config, model_config=model_config, params=params,
               progress=False)
""")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # identical device topology to the in-process runs (conftest's 8 virtual
    # CPU devices): the bitwise bar tolerates no reassociation
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["NCNET_TPU_FAULTS"] = json.dumps(
        {"kill_in_savemat_substring": os.sep + "2.mat"})
    proc = subprocess.run(
        [sys.executable, str(worker)], env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=600,
    )
    assert proc.returncode == -9, f"expected SIGKILL, got:\n{proc.stdout[-3000:]}"

    out_dir = os.path.join(root, "m",
                           next(os.walk(os.path.join(root, "m")))[1][0])
    names = sorted(n for n in os.listdir(out_dir) if n.endswith(".mat"))
    assert names == ["1.mat"]  # 2.mat torn mid-commit, 3.mat never reached
    assert os.path.exists(os.path.join(out_dir, "2.mat.tmp"))
    mtime1 = os.path.getmtime(os.path.join(out_dir, "1.mat"))

    # the rerun (same output root) resumes: skips 1, recomputes 2 and 3
    config = EvalInLocConfig(output_root=os.path.join(root, "m"),
                             **FAST, **kw)
    resumed_dir = run_inloc_eval(config, model_config=TINY_INLOC,
                                 params=params, progress=False)
    assert resumed_dir == out_dir
    assert os.path.getmtime(os.path.join(out_dir, "1.mat")) == mtime1

    # the uninterrupted twin
    full_dir = run_inloc_eval(
        EvalInLocConfig(output_root=os.path.join(root, "full"), **FAST, **kw),
        model_config=TINY_INLOC, params=params, progress=False)
    a, b = _load_all_matches(resumed_dir), _load_all_matches(full_dir)
    assert sorted(a) == sorted(b) == ["1.mat", "2.mat", "3.mat"]
    for name in a:
        np.testing.assert_array_equal(a[name], b[name])


# ---------------------------------------------------------------------------
# PF-Pascal eval: journaled resume + per-batch isolation
# ---------------------------------------------------------------------------


def _pf_setup(tmp_path, n_pairs=5, seed=7):
    root = str(tmp_path / "data")
    write_pf_pascal_like(root, n_pairs=n_pairs, image_hw=(96, 96),
                         shift=(16, 16), seed=seed)
    return root


def _pf_run(root, journal_dir="", net=None, fetch_timeout_s=0.0, **kw):
    from ncnet_tpu import models

    config = EvalPFPascalConfig(image_size=96, eval_dataset_path=root,
                                journal_dir=journal_dir, query_retries=1,
                                retry_backoff_s=0.0,
                                fetch_timeout_s=fetch_timeout_s)
    if net is None:
        net = models.NCNet(TINY, seed=0)
    return run_eval(config, net=net, batch_size=1, num_workers=0,
                    progress=False, **kw)


def test_pf_pascal_quarantined_batch_never_aborts(tmp_path):
    """Acceptance (a), PF-Pascal shape: a batch whose dispatch keeps
    failing after every recovery (both tiers demoted, retries exhausted) is
    quarantined — its pairs score invalid — and the rest of the run
    completes."""
    root = _pf_setup(tmp_path)
    journal_dir = str(tmp_path / "j")
    # calls 1-4: batch 0's dispatch + its retries (two demotion free
    # retries, then the counted budget); call 5+ (later batches) succeed
    with faults.injected(FaultPlan(device_fail_calls=(1, 2, 3, 4))):
        stats = _pf_run(root, journal_dir=journal_dir, pipeline_depth=1)
    assert stats["quarantined_batches"] == [0]
    assert stats["total"] == 5 and stats["valid"] == 4
    assert np.isnan(stats["per_pair"][0])
    assert np.isfinite(stats["per_pair"][1:]).all()
    manifest = json.load(open(os.path.join(journal_dir, "manifest.json")))
    assert manifest["quarantined"]["batch_0"]["kind"] == "device"
    assert ops.demoted_fused_tiers() == {"resident", "perlayer"}


def test_pf_pascal_device_error_demotes_and_completes_parity(tmp_path):
    """Acceptance (c), PF-Pascal shape: one injected device failure →
    demote + re-trace + free retry; the per-pair PCK matches a clean run
    exactly."""
    root = _pf_setup(tmp_path)
    clean = _pf_run(root)
    with faults.injected(FaultPlan(device_fail_calls=(1,))):
        faulty = _pf_run(root, pipeline_depth=1)
    np.testing.assert_array_equal(clean["per_pair"], faulty["per_pair"])
    assert faulty["quarantined_batches"] == []
    assert ops.demoted_fused_tiers() == {"resident"}


def test_pf_pascal_hung_fetch_retried_with_parity(tmp_path):
    root = _pf_setup(tmp_path)
    clean = _pf_run(root)
    with faults.injected(FaultPlan(hang_fetch_calls=(1,),
                                   hang_fetch_seconds=10.0)):
        faulty = _pf_run(root, pipeline_depth=1, fetch_timeout_s=0.5)
    np.testing.assert_array_equal(clean["per_pair"], faulty["per_pair"])
    assert faulty["quarantined_batches"] == []


def test_pf_pascal_journal_rerun_reuses_results_bitwise(tmp_path):
    """A completed journaled run re-invoked with the same settings replays
    every batch from the journal (nothing re-dispatched) and returns the
    identical result; a different-settings journal is discarded."""
    root = _pf_setup(tmp_path)
    journal_dir = str(tmp_path / "j")
    first = _pf_run(root, journal_dir=journal_dir)
    journal_path = os.path.join(journal_dir, "pck_journal.jsonl")
    n_lines = len(open(journal_path).read().splitlines())
    assert n_lines == 1 + 5  # header + one record per batch

    second = _pf_run(root, journal_dir=journal_dir)
    np.testing.assert_array_equal(first["per_pair"], second["per_pair"])
    # nothing re-dispatched → nothing re-journaled
    assert len(open(journal_path).read().splitlines()) == n_lines

    # a batch_size change invalidates the journal (header mismatch)
    config = EvalPFPascalConfig(image_size=96, eval_dataset_path=root,
                                journal_dir=journal_dir)
    from ncnet_tpu import models

    stats = run_eval(config, net=models.NCNet(TINY, seed=0), batch_size=5,
                     num_workers=0, progress=False)
    assert stats["total"] == 5


def test_pf_pascal_kill_mid_eval_resumes_bitwise(tmp_path):
    """Acceptance (b): SIGKILL mid-journal-append at an arbitrary step of
    PF-Pascal eval (a torn trailing record on disk); the rerun resumes from
    the journal and the final per-pair PCK — and its mean — is
    bitwise-identical to an uninterrupted run."""
    root = _pf_setup(tmp_path)
    journal_dir = str(tmp_path / "j")

    worker = tmp_path / "worker.py"
    worker.write_text(f"""
import sys
sys.path.insert(0, {_REPO!r})
import jax
jax.config.update("jax_platforms", "cpu")
from ncnet_tpu import models
from ncnet_tpu.config import EvalPFPascalConfig, ModelConfig
from ncnet_tpu.evaluation import run_eval

TINY = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                   ncons_channels=(1,))
config = EvalPFPascalConfig(image_size=96, eval_dataset_path={root!r},
                            journal_dir={journal_dir!r})
run_eval(config, net=models.NCNet(TINY, seed=0), batch_size=1,
         num_workers=0, progress=False)
""")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["NCNET_TPU_FAULTS"] = json.dumps({"kill_at_journal_append": 3})
    proc = subprocess.run(
        [sys.executable, str(worker)], env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=600,
    )
    assert proc.returncode == -9, f"expected SIGKILL, got:\n{proc.stdout[-3000:]}"

    journal_path = os.path.join(journal_dir, "pck_journal.jsonl")
    lines = open(journal_path).read().splitlines()
    assert len(lines) == 1 + 3  # header + 2 complete records + 1 TORN record
    with pytest.raises(ValueError):
        json.loads(lines[-1])  # the torn mid-append prefix

    resumed = _pf_run(root, journal_dir=journal_dir)
    full = _pf_run(root)
    np.testing.assert_array_equal(resumed["per_pair"], full["per_pair"])
    assert resumed["pck"] == full["pck"]
    assert resumed["valid"] == full["valid"] == 5


def test_pf_pascal_corrupt_image_scores_invalid_not_double_counted(tmp_path):
    """A corrupt eval image must not abort the run — the loader substitutes
    the next healthy sample so the pipeline keeps flowing — but the metric
    must not count the substitute twice: the corrupt PAIR scores
    NaN=invalid, and the reported PCK equals the clean pairs' mean."""
    root = _pf_setup(tmp_path)
    bad = os.path.join(root, "images", "test_0_a.jpg")
    with open(bad, "wb") as f:
        f.write(b"\xff\xd8garbage")
    stats = _pf_run(root)
    assert stats["decode_quarantined"] == [bad]
    assert stats["total"] == 5 and stats["valid"] == 4
    assert np.isnan(stats["per_pair"][0])
    assert np.isfinite(stats["per_pair"][1:]).all()
    assert stats["pck"] == pytest.approx(float(np.mean(stats["per_pair"][1:])))


# ---------------------------------------------------------------------------
# localization driver: classified per-query PnP failure handling
# ---------------------------------------------------------------------------


def test_pnp_stage_quarantines_query_with_broken_matches(tmp_path):
    """A query whose matches .mat is missing is classified ('io'),
    quarantined into the stage manifest, and excluded from the ImgList —
    the stage completes instead of aborting at the first worker exception.
    A degraded run must NOT write the stage-level resume .mat (the
    exists-guard would pin the partial ImgList forever); the rerun retries
    the quarantined query instead of reloading the degraded artifact."""
    from ncnet_tpu.localization.driver import (
        _pnp_dirname,
        _pnp_matname,
        run_pnp_stage,
    )

    root = str(tmp_path)
    shortlist = write_inloc_like(root, n_queries=1, n_panos=1,
                                 image_hw=(96, 128))
    config = LocalizationConfig(
        matches_dir=os.path.join(root, "missing_matches"),
        shortlist=shortlist,
        query_path=os.path.join(root, "query", "iphone7"),
        output_dir=os.path.join(root, "out"),
        query_retries=1, retry_backoff_s=0.0, progress=False,
    )
    imglist = run_pnp_stage(config)
    assert imglist == []
    manifest_path = os.path.join(
        root, "out", _pnp_dirname(config), "manifest.json")
    manifest = json.load(open(manifest_path))
    assert manifest["quarantined"]["query_0.jpg"]["kind"] == "io"
    assert manifest["quarantined"]["query_0.jpg"]["attempts"] == 2
    # no stage resume artifact was pinned; the rerun retries the query
    assert not os.path.exists(os.path.join(root, "out", _pnp_matname(config)))
    assert run_pnp_stage(config) == []
    manifest = json.load(open(manifest_path))
    assert manifest["quarantined"]["query_0.jpg"]["attempts"] == 2  # retried
