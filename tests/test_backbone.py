"""Backbone parity vs. a torch functional oracle.

The reference trunk is torchvision resnet101[:layer3] / vgg16[:pool4] in eval
mode (/root/reference/lib/model.py:24-44).  torchvision is not installed here,
so the oracle is a functional re-statement of those architectures driven by a
synthetic torchvision-style state_dict — the same dict is imported through
``import_torch_backbone``, so this tests both the converter and the forward.
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax
import jax.numpy as jnp

from ncnet_tpu.models import backbone as bb

RNG = np.random.default_rng(0)

# the reference-default trunk cuts: conv1..layer3 / features..pool4
STAGES_L3 = {k: v for k, v in bb.RESNET101_STAGES.items() if k != "layer4"}
VGG_PLAN_P4 = bb.VGG16_PLAN[:14]  # through the 4th maxpool


def _conv_w(cout, cin, k):
    std = 0.3 / np.sqrt(cin * k * k)
    return RNG.normal(0, std, (cout, cin, k, k)).astype(np.float32)


def _bn_sd(sd, prefix, c):
    sd[prefix + ".weight"] = RNG.uniform(0.5, 1.5, c).astype(np.float32)
    sd[prefix + ".bias"] = RNG.normal(0, 0.1, c).astype(np.float32)
    sd[prefix + ".running_mean"] = RNG.normal(0, 0.1, c).astype(np.float32)
    sd[prefix + ".running_var"] = RNG.uniform(0.5, 1.5, c).astype(np.float32)


def make_resnet101_state_dict():
    sd = {}
    sd["conv1.weight"] = _conv_w(64, 3, 7)
    _bn_sd(sd, "bn1", 64)
    inplanes = 64
    for stage, n in STAGES_L3.items():
        planes = bb.RESNET101_PLANES[stage]
        for i in range(n):
            p = f"{stage}.{i}"
            sd[p + ".conv1.weight"] = _conv_w(planes, inplanes, 1)
            _bn_sd(sd, p + ".bn1", planes)
            sd[p + ".conv2.weight"] = _conv_w(planes, planes, 3)
            _bn_sd(sd, p + ".bn2", planes)
            sd[p + ".conv3.weight"] = _conv_w(planes * 4, planes, 1)
            _bn_sd(sd, p + ".bn3", planes * 4)
            if i == 0:
                sd[p + ".downsample.0.weight"] = _conv_w(planes * 4, inplanes, 1)
                _bn_sd(sd, p + ".downsample.1", planes * 4)
                inplanes = planes * 4
    return sd


def torch_resnet101_features(sd, x):
    t = {k: torch.from_numpy(v) for k, v in sd.items()}

    def bn(y, p):
        return F.batch_norm(
            y, t[p + ".running_mean"], t[p + ".running_var"],
            t[p + ".weight"], t[p + ".bias"], training=False, eps=1e-5,
        )

    x = F.relu(bn(F.conv2d(x, t["conv1.weight"], stride=2, padding=3), "bn1"))
    x = F.max_pool2d(x, 3, 2, 1)
    for stage, n in STAGES_L3.items():
        for i in range(n):
            p = f"{stage}.{i}"
            stride = 2 if (i == 0 and stage != "layer1") else 1
            out = F.relu(bn(F.conv2d(x, t[p + ".conv1.weight"]), p + ".bn1"))
            out = F.relu(bn(F.conv2d(out, t[p + ".conv2.weight"], stride=stride, padding=1), p + ".bn2"))
            out = bn(F.conv2d(out, t[p + ".conv3.weight"]), p + ".bn3")
            if p + ".downsample.0.weight" in sd:
                x = bn(F.conv2d(x, t[p + ".downsample.0.weight"], stride=stride), p + ".downsample.1")
            x = F.relu(out + x)
    return x


def make_vgg16_state_dict():
    sd = {}
    cin, idx = 3, 0
    for cout in VGG_PLAN_P4:
        if cout == -1:
            idx += 1
            continue
        sd[f"{idx}.weight"] = _conv_w(cout, cin, 3)
        sd[f"{idx}.bias"] = RNG.normal(0, 0.05, cout).astype(np.float32)
        cin = cout
        idx += 2
    return sd


def torch_vgg16_features(sd, x):
    t = {k: torch.from_numpy(v) for k, v in sd.items()}
    idx = 0
    for cout in VGG_PLAN_P4:
        if cout == -1:
            x = F.max_pool2d(x, 2, 2)
            idx += 1
        else:
            x = F.relu(F.conv2d(x, t[f"{idx}.weight"], t[f"{idx}.bias"], padding=1))
            idx += 2
    return x


@pytest.mark.parametrize("hw", [(64, 64), (64, 48)])
def test_resnet101_matches_torch(hw):
    sd = make_resnet101_state_dict()
    x = RNG.normal(0, 1, (1, 3, *hw)).astype(np.float32)
    want = torch_resnet101_features(sd, torch.from_numpy(x)).numpy()

    params = bb.import_torch_backbone(sd, "resnet101")
    got = bb.resnet101_features(params, jnp.asarray(np.transpose(x, (0, 2, 3, 1))))
    got = np.transpose(np.asarray(got), (0, 3, 1, 2))

    assert got.shape == want.shape == (1, 1024, hw[0] // 16, hw[1] // 16)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_vgg16_matches_torch():
    sd = make_vgg16_state_dict()
    x = RNG.normal(0, 1, (2, 3, 48, 64)).astype(np.float32)
    want = torch_vgg16_features(sd, torch.from_numpy(x)).numpy()

    params = bb.import_torch_backbone(sd, "vgg")
    got = bb.vgg16_features(params, jnp.asarray(np.transpose(x, (0, 2, 3, 1))))
    got = np.transpose(np.asarray(got), (0, 3, 1, 2))

    assert got.shape == want.shape == (2, 512, 3, 4)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_tiny_backbone_shape_and_stride():
    params = bb.backbone_init("tiny", jax.random.key(0))
    out = bb.backbone_apply("tiny", params, jnp.zeros((2, 64, 48, 3)))
    assert out.shape == (2, 4, 3, 32)


def test_random_init_shapes_match_import_shapes():
    sd = make_resnet101_state_dict()
    imported = bb.import_torch_backbone(sd, "resnet101")
    initialized = bb.init_resnet101(jax.random.key(0))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a.shape, b.shape),
                 imported, initialized)


def test_last_layer_cut():
    """backbone_last_layer (reference feature_extraction_last_layer) changes
    the cut point; unknown names fail fast."""
    p2 = bb.backbone_init("resnet101", jax.random.key(0), last_layer="layer2")
    assert "layer3" not in p2
    out = bb.backbone_apply("resnet101", p2, jnp.zeros((1, 64, 64, 3)), last_layer="layer2")
    assert out.shape == (1, 8, 8, 512)  # stride 8, 512 ch at layer2

    pv = bb.backbone_init("vgg", jax.random.key(0), last_layer="pool3")
    assert len(pv["convs"]) == 7
    out = bb.backbone_apply("vgg", pv, jnp.zeros((1, 64, 64, 3)), last_layer="pool3")
    assert out.shape == (1, 8, 8, 256)

    with pytest.raises(ValueError):
        bb.backbone_init("resnet101", jax.random.key(0), last_layer="layer9")
    with pytest.raises(ValueError):
        bb.finetune_labels("resnet", {}, 1)


def test_vgg_conv_cut_excludes_trailing_relu():
    """A cut at 'convN_M' ends on the raw conv output (reference Sequential
    slice semantics, model.py:26-35); 'reluN_M' includes the activation."""
    pv = bb.backbone_init("vgg", jax.random.key(3), last_layer="conv2_1")
    assert len(pv["convs"]) == 3
    x = jnp.asarray(RNG.normal(0, 1, (1, 32, 32, 3)).astype(np.float32))
    raw = bb.backbone_apply("vgg", pv, x, last_layer="conv2_1")
    relu = bb.backbone_apply("vgg", pv, x, last_layer="relu2_1")
    assert float(jnp.min(raw)) < 0  # negatives preserved at conv cut
    np.testing.assert_allclose(np.asarray(jnp.maximum(raw, 0)), np.asarray(relu), rtol=1e-6)


def test_finetune_labels_partition():
    params = bb.init_vgg16(jax.random.key(0))
    labels = bb.finetune_labels("vgg", params, 2)
    flat = jax.tree.leaves(labels)
    assert "trainable" in flat and "frozen" in flat
    # exactly the last 2 conv layers (w+b each) are trainable
    assert sum(1 for l in flat if l == "trainable") == 4


def test_finetune_labels_keep_bn_stats_frozen():
    """Reference finetuning unfreezes .parameters() only (train.py:60-63);
    BN running stats are buffers and must never train."""
    params = bb.init_resnet101(jax.random.key(0))
    labels = bb.finetune_labels("resnet101", params, 2)
    last = labels["layer3"][-1]
    assert last["conv1"]["w"] == "trainable"
    assert last["bn1"]["scale"] == "trainable"
    assert last["bn1"]["mean"] == "frozen"
    assert last["bn1"]["var"] == "frozen"
    # untouched blocks fully frozen
    assert set(jax.tree.leaves(labels["layer1"])) == {"frozen"}


def test_deep_cuts_layer4_and_pool5():
    """The reference FeatureExtraction accepts cuts beyond the defaults
    (resnet layer4, vgg pool5); they must be constructible and shape-correct."""
    p4 = bb.backbone_init("resnet101", jax.random.key(1), last_layer="layer4")
    out = bb.backbone_apply("resnet101", p4, jnp.zeros((1, 64, 64, 3)), last_layer="layer4")
    assert out.shape == (1, 2, 2, 2048)  # stride 32

    pv = bb.backbone_init("vgg", jax.random.key(1), last_layer="pool5")
    assert len(pv["convs"]) == 13
    out = bb.backbone_apply("vgg", pv, jnp.zeros((1, 64, 64, 3)), last_layer="pool5")
    assert out.shape == (1, 2, 2, 512)  # stride 32


def test_backbone_weights_config_loads_torch_state_dict(tmp_path):
    """ModelConfig.backbone_weights → init_ncnet builds the trunk from a
    torchvision .pth instead of random init (and does not warn)."""
    import warnings
    import torch
    import jax

    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu.models.ncnet import init_ncnet

    sd = make_resnet101_state_dict()
    path = tmp_path / "resnet101.pth"
    torch.save({k: torch.from_numpy(np.asarray(v)) for k, v in sd.items()}, path)

    cfg = ModelConfig(backbone="resnet101", ncons_kernel_sizes=(3,),
                      ncons_channels=(1,), backbone_weights=str(path))
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # the random-trunk warning must NOT fire
        params = init_ncnet(cfg, jax.random.key(0))
    np.testing.assert_allclose(
        np.asarray(params["backbone"]["conv1"]["w"]).transpose(3, 2, 0, 1),
        sd["conv1.weight"], rtol=1e-6)


def test_random_pretrained_trunk_warns():
    import warnings
    import jax

    from ncnet_tpu.config import ModelConfig
    from ncnet_tpu.models.ncnet import init_ncnet

    cfg = ModelConfig(backbone="resnet101", ncons_kernel_sizes=(3,),
                      ncons_channels=(1,))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        init_ncnet(cfg, jax.random.key(0))
    assert any("RANDOM weights" in str(x.message) for x in w)


def make_densenet201_state_dict():
    sd = {}
    sd["conv0.weight"] = _conv_w(64, 3, 7)
    _bn_sd(sd, "norm0", 64)
    c = 64
    for bi, (bname, n) in enumerate(bb.DENSENET201_BLOCKS.items(), start=1):
        for i in range(1, n + 1):
            p = f"{bname}.denselayer{i}"
            mid = bb.DENSENET_BN_SIZE * bb.DENSENET_GROWTH
            _bn_sd(sd, p + ".norm1", c)
            sd[p + ".conv1.weight"] = _conv_w(mid, c, 1)
            _bn_sd(sd, p + ".norm2", mid)
            sd[p + ".conv2.weight"] = _conv_w(bb.DENSENET_GROWTH, mid, 3)
            c += bb.DENSENET_GROWTH
        _bn_sd(sd, f"transition{bi}.norm", c)
        sd[f"transition{bi}.conv.weight"] = _conv_w(c // 2, c, 1)
        c //= 2
    return sd


def torch_densenet201_features(sd, x):
    t = {k: torch.from_numpy(v) for k, v in sd.items()}

    def bn(y, p):
        return F.batch_norm(
            y, t[p + ".running_mean"], t[p + ".running_var"],
            t[p + ".weight"], t[p + ".bias"], training=False, eps=1e-5,
        )

    x = F.relu(bn(F.conv2d(x, t["conv0.weight"], stride=2, padding=3), "norm0"))
    x = F.max_pool2d(x, 3, 2, 1)
    for bi, (bname, n) in enumerate(bb.DENSENET201_BLOCKS.items(), start=1):
        for i in range(1, n + 1):
            p = f"{bname}.denselayer{i}"
            y = F.conv2d(F.relu(bn(x, p + ".norm1")), t[p + ".conv1.weight"])
            y = F.conv2d(F.relu(bn(y, p + ".norm2")), t[p + ".conv2.weight"], padding=1)
            x = torch.cat([x, y], dim=1)
        x = F.conv2d(F.relu(bn(x, f"transition{bi}.norm")),
                     t[f"transition{bi}.conv.weight"])
        x = F.avg_pool2d(x, 2, 2)
    return x


def test_densenet201_matches_torch():
    """Reference cut = features[:-4] ⇒ conv0..transition2 inclusive, stride 16,
    256 channels (/root/reference/lib/model.py:69-74)."""
    sd = make_densenet201_state_dict()
    x = RNG.normal(0, 1, (1, 3, 64, 48)).astype(np.float32)
    want = torch_densenet201_features(sd, torch.from_numpy(x)).numpy()

    params = bb.import_torch_backbone(sd, "densenet201")
    got = bb.densenet201_features(params, jnp.asarray(np.transpose(x, (0, 2, 3, 1))))
    got = np.transpose(np.asarray(got), (0, 3, 1, 2))

    assert got.shape == want.shape == (1, 256, 4, 3)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_densenet201_random_init_matches_import_shapes():
    sd = make_densenet201_state_dict()
    imported = bb.import_torch_backbone(sd, "densenet201")
    random = bb.backbone_init("densenet201", jax.random.key(0))
    assert jax.tree.map(lambda a: a.shape, imported) == jax.tree.map(
        lambda a: a.shape, random
    )


def test_densenet201_finetune_labels():
    params = bb.backbone_init("densenet201", jax.random.key(0))
    labels = bb.finetune_labels("densenet201", params, 2)
    flat = labels["transition2"]
    assert all(v == "trainable" for k, v in flat["conv"].items())
    assert labels["transition2"]["norm"]["mean"] == "frozen"
    assert labels["denseblock2"][-1]["conv1"]["w"] == "trainable"
    assert labels["denseblock2"][0]["conv1"]["w"] == "frozen"
    assert labels["conv0"]["w"] == "frozen"
