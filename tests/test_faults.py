"""Fault-tolerance tests: each injected failure mode (decode errors, NaN
losses, kill-mid-save, SIGTERM preemption) must be survived by the mechanism
built for it — proven end-to-end on the synthetic dataset via the
ncnet_tpu/utils/faults.py injection harness, whose hooks live inside the
production code paths themselves."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from ncnet_tpu.config import ModelConfig, TrainConfig
from ncnet_tpu.data import DataLoader, ImagePairDataset, SampleDecodeError
from ncnet_tpu.data.synthetic import write_pair_dataset
from ncnet_tpu.models import checkpoint as ckpt_io
from ncnet_tpu import training
from ncnet_tpu.utils import faults
from ncnet_tpu.utils.faults import FaultPlan


TINY = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,), ncons_channels=(1,))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _dataset(tmp_path, n_pairs=4, seed=1):
    root = str(tmp_path / "data")
    write_pair_dataset(root, n_pairs=n_pairs, image_hw=(48, 48),
                       shift=(16, 16), seed=seed)
    return root


def _cfg(root, out_dir, **kw):
    base = dict(
        model=TINY, image_size=48,
        dataset_image_path=root, dataset_csv_path=root + "/image_pairs",
        num_epochs=1, batch_size=2, lr=1e-3,
        result_model_dir=str(out_dir), log_interval=10, data_parallel=False,
    )
    base.update(kw)
    return TrainConfig(**base)


def _assert_states_equal(a, b):
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        a.params, b.params,
    )
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        a.opt_state, b.opt_state,
    )
    assert int(a.step) == int(b.step)


# ---------------------------------------------------------------------------
# atomic versioned checkpoints
# ---------------------------------------------------------------------------


def test_version_resolver_skips_tmp_and_picks_newest(tmp_path):
    root = tmp_path / "root"
    for name, complete in [("step_00000002", True), ("step_00000010", True),
                           ("step_00000012.tmp", False)]:
        d = root / name
        d.mkdir(parents=True)
        if complete:
            (d / "config.json").write_text("{}")
    (root / "step_00000011").mkdir()  # committed name but empty: incomplete
    (root / "notes.txt").write_text("junk")

    assert [n for n, _ in ckpt_io.list_checkpoint_versions(str(root))] == [2, 10]
    assert ckpt_io.resolve_checkpoint_dir(str(root)).endswith("step_00000010")
    # a non-versioned directory resolves to itself
    flat = tmp_path / "flat"
    flat.mkdir()
    (flat / "config.json").write_text("{}")
    assert ckpt_io.resolve_checkpoint_dir(str(flat)) == str(flat)
    # ownership: root and versions map back to the root, foreigners to None
    assert ckpt_io.owning_checkpoint_root(str(root)) == str(root)
    assert ckpt_io.owning_checkpoint_root(
        str(root / "step_00000002")) == str(root)
    assert ckpt_io.owning_checkpoint_root(str(flat)) is None


def test_resolver_rejects_root_with_only_tmp_carcasses(tmp_path):
    root = tmp_path / "root"
    (root / "step_00000003.tmp").mkdir(parents=True)
    with pytest.raises(FileNotFoundError, match="incomplete"):
        ckpt_io.resolve_checkpoint_dir(str(root))


def test_with_io_retries_bounded():
    calls = []

    def flaky(fail_n):
        def fn():
            calls.append(1)
            if len(calls) <= fail_n:
                raise OSError("transient")
            return 7
        return fn

    assert ckpt_io.with_io_retries(flaky(2), attempts=3, backoff=0.0) == 7
    assert len(calls) == 3
    calls.clear()
    with pytest.raises(OSError, match="transient"):
        ckpt_io.with_io_retries(flaky(5), attempts=2, backoff=0.0)
    assert len(calls) == 2


def test_retention_window_and_positions(tmp_path):
    """checkpoint_steps saves carry exact resume cursors; retention keeps
    only the newest ``keep_checkpoints`` versions; best_ copy survives."""
    root = _dataset(tmp_path, n_pairs=8)  # 4 train batches at bs=2
    cfg = _cfg(root, tmp_path / "ckpts", checkpoint_steps=1,
               keep_checkpoints=2)
    result = training.fit(cfg, progress=False)
    ckpt_root = result["checkpoint"]
    versions = ckpt_io.list_checkpoint_versions(ckpt_root)
    assert [n for n, _ in versions] == [3, 4]  # 1, 2 pruned
    with open(os.path.join(versions[0][1], "config.json")) as f:
        meta3 = json.load(f)
    assert meta3["_position"] == {"epoch": 1, "next_batch": 3}
    assert meta3["_epoch"] == 0  # saved mid-epoch-1
    with open(os.path.join(versions[1][1], "config.json")) as f:
        meta4 = json.load(f)  # epoch-end save overwrote the periodic one
    assert meta4["_position"] == {"epoch": 2, "next_batch": 0}
    assert meta4["_epoch"] == 1
    assert any(d.startswith("best_")
               for d in os.listdir(tmp_path / "ckpts"))


def test_rollback_resume_prunes_stale_newer_versions(tmp_path, capsys):
    """Resuming from a NON-newest version is a rollback: versions newer
    than the resume point must be pruned, or a crash before the new lineage
    surpasses them would silently resume the rolled-back-from checkpoint."""
    root = _dataset(tmp_path, n_pairs=8)  # 4 train batches at bs=2
    r1 = training.fit(
        _cfg(root, tmp_path / "ckpts", checkpoint_steps=1,
             keep_checkpoints=10),
        progress=False,
    )
    ckpt_root = r1["checkpoint"]
    assert [n for n, _ in ckpt_io.list_checkpoint_versions(ckpt_root)] \
        == [1, 2, 3, 4]
    cfg2 = _cfg(root, tmp_path / "ckpts", checkpoint_steps=1,
                keep_checkpoints=10,
                model=TINY.replace(
                    checkpoint=os.path.join(ckpt_root, "step_00000002")))
    r2 = training.fit(cfg2, progress=False)
    assert "pruned stale version" in capsys.readouterr().out
    # the rolled-back lineage regenerates 3 and 4 deterministically
    assert [n for n, _ in ckpt_io.list_checkpoint_versions(ckpt_root)] \
        == [1, 2, 3, 4]
    _assert_states_equal(r2["state"], r1["state"])


def test_same_step_resave_crash_window_recovers(tmp_path):
    """A same-step re-save commits via rename(final→.old), rename(tmp→final);
    a crash between the two renames must not strand the run: readers accept
    the displaced .old as version N, and the next save restores it."""
    cfg = TrainConfig(model=TINY, data_parallel=False)
    state, _, mc, _ = training.create_train_state(cfg)
    root = str(tmp_path / "root")
    z = np.zeros(1)
    v = training.save_train_checkpoint(
        root, cfg, mc, state, 1, z, z, False,
        step=2, position={"epoch": 2, "next_batch": 0},
    )
    # simulate the crash window: original displaced, replacement uncommitted
    os.rename(v, v + ".old")
    os.makedirs(v + ".tmp")
    assert ckpt_io.list_checkpoint_versions(root) == [(2, v + ".old")]
    assert ckpt_io.resolve_checkpoint_dir(root) == v + ".old"
    assert ckpt_io.owning_checkpoint_root(v + ".old") == root
    # the next save's reclaim pass restores the displaced version and
    # drops the uncommitted tmp
    training.save_train_checkpoint(
        root, cfg, mc, state, 1, z, z, False,
        step=3, position={"epoch": 2, "next_batch": 1},
    )
    assert sorted(os.listdir(root)) == ["step_00000002", "step_00000003"]


# ---------------------------------------------------------------------------
# NaN/Inf loss guard
# ---------------------------------------------------------------------------


def test_nan_guard_step_skips_update(rng):
    """A non-finite loss must leave params AND Adam state bitwise unchanged
    (the step counter still counts the consumed batch); the next good batch
    updates normally."""
    state, optimizer, mc, _ = training.create_train_state(
        TrainConfig(model=TINY, batch_size=2, data_parallel=False)
    )
    step = training.make_train_step(mc, optimizer, donate=False,
                                    nan_guard=True)
    good = {
        "source_image": jnp.asarray(
            rng.uniform(0, 1, (2, 48, 48, 3)).astype(np.float32)),
        "target_image": jnp.asarray(
            rng.uniform(0, 1, (2, 48, 48, 3)).astype(np.float32)),
    }
    bad = dict(good, source_image=jnp.full((2, 48, 48, 3), np.nan))

    s1, l1 = step(state, good)
    assert np.isfinite(float(l1))
    s2, l2 = step(s1, bad)
    assert not np.isfinite(float(l2))
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        s2.params, s1.params,
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        s2.opt_state, s1.opt_state,
    )
    assert int(s2.step) == 2  # batches consumed, not updates applied
    s3, l3 = step(s2, good)
    assert np.isfinite(float(l3))
    assert not np.array_equal(np.asarray(s3.params["nc"][0]["w"]),
                              np.asarray(s2.params["nc"][0]["w"]))


def test_fit_nan_injection_skips_and_completes(tmp_path, capsys):
    root = _dataset(tmp_path)
    cfg = _cfg(root, tmp_path / "ckpts")
    with faults.injected(FaultPlan(nan_loss_steps=(1,))):
        result = training.fit(cfg, progress=False)
    assert result["nan_steps_skipped"] == 1
    assert np.isfinite(result["train_loss"]).all()  # mean excludes the NaN
    out = capsys.readouterr().out
    assert "non-finite loss at step 1" in out


def test_fit_nan_streak_aborts_with_clear_error(tmp_path):
    root = _dataset(tmp_path)
    cfg = _cfg(root, tmp_path / "ckpts", max_bad_steps=2)
    with faults.injected(FaultPlan(nan_loss_steps=(1, 2))):
        with pytest.raises(training.TrainDivergedError,
                           match="2 consecutive non-finite"):
            training.fit(cfg, progress=False)


# ---------------------------------------------------------------------------
# data-path resilience: decode retry + quarantine
# ---------------------------------------------------------------------------


def test_decode_retry_absorbs_transient_fault(tmp_path):
    root = _dataset(tmp_path)
    ds = ImagePairDataset(root + "/image_pairs", "train_pairs.csv", root,
                          output_size=(48, 48), decode_retries=1)
    with faults.injected(FaultPlan(decode_fail_substring="train_1_b",
                                   decode_fail_times=1)):
        sample = ds[1]  # first attempt fails, the retry succeeds
    assert sample["source_image"].shape == (48, 48, 3)
    ds0 = ImagePairDataset(root + "/image_pairs", "train_pairs.csv", root,
                           output_size=(48, 48), decode_retries=0)
    with faults.injected(FaultPlan(decode_fail_substring="train_1_b")):
        with pytest.raises(SampleDecodeError, match="train_1_b"):
            ds0[1]


def test_loader_raise_policy_propagates(tmp_path):
    root = _dataset(tmp_path)
    bad = os.path.join(root, "images", "train_0_a.jpg")
    with open(bad, "wb") as f:
        f.write(b"not a jpeg at all")
    ds = ImagePairDataset(root + "/image_pairs", "train_pairs.csv", root,
                          output_size=(48, 48), decode_retries=0)
    loader = DataLoader(ds, batch_size=2)  # default: raise
    with pytest.raises(SampleDecodeError, match="train_0_a"):
        list(loader)


def test_loader_quarantine_substitutes_and_reports(tmp_path):
    root = _dataset(tmp_path)
    bad = os.path.join(root, "images", "train_0_a.jpg")
    with open(bad, "wb") as f:
        f.write(b"not a jpeg at all")
    ds = ImagePairDataset(root + "/image_pairs", "train_pairs.csv", root,
                          output_size=(48, 48), decode_retries=0)
    loader = DataLoader(ds, batch_size=2, on_decode_error="quarantine")
    batches = list(loader)
    assert len(batches) == len(loader)  # full epoch, every batch full
    for b in batches:
        assert b["source_image"].shape == (2, 48, 48, 3)
    assert loader.quarantined == {bad}
    # the replacement for sample 0 is the next healthy sample (index 1)
    np.testing.assert_array_equal(
        batches[0]["target_image"][0], batches[0]["target_image"][1]
    )


def test_systemic_decode_failure_fails_fast(tmp_path):
    """When EVERY decode fails (wrong image root, unmounted disk), the
    quarantine substitution must declare the failure systemic after a
    bounded number of fresh failures — not scan the whole dataset."""
    root = _dataset(tmp_path, n_pairs=8)
    ds = ImagePairDataset(root + "/image_pairs", "train_pairs.csv", root,
                          output_size=(48, 48), decode_retries=0)
    loader = DataLoader(ds, batch_size=2, on_decode_error="quarantine")
    with faults.injected(FaultPlan(decode_fail_substring="images/")):
        with pytest.raises(SampleDecodeError, match="consecutive"):
            list(loader)
    assert len(loader.quarantined) <= DataLoader._MAX_FRESH_FAILURES


def test_fit_quarantines_corrupt_image_and_completes(tmp_path, capsys):
    """Acceptance: one corrupt image costs the epoch at most that sample;
    the run completes and the quarantined path is reported."""
    root = _dataset(tmp_path)
    bad = os.path.join(root, "images", "train_1_a.jpg")
    with open(bad, "wb") as f:
        f.write(b"\xff\xd8garbage")
    result = training.fit(_cfg(root, tmp_path / "ckpts"), progress=False)
    assert result["quarantined"] == [bad]
    assert np.isfinite(result["train_loss"]).all()
    assert "quarantined undecodable sample" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# SIGTERM preemption
# ---------------------------------------------------------------------------


def test_sigterm_checkpoints_at_boundary_then_resumes(tmp_path):
    root = _dataset(tmp_path)
    cfg = _cfg(root, tmp_path / "ckpts", num_epochs=3)
    with faults.injected(FaultPlan(sigterm_at_step=2)):
        r1 = training.fit(cfg, progress=False)
    assert r1["preempted"]
    ckpt_root = r1["checkpoint"]
    versions = ckpt_io.list_checkpoint_versions(ckpt_root)
    assert [n for n, _ in versions] == [2]  # the boundary checkpoint
    with open(os.path.join(versions[0][1], "config.json")) as f:
        assert json.load(f)["_position"] == {"epoch": 1, "next_batch": 2}

    # resume finishes the remaining epochs (epoch 1 was fully consumed:
    # only its val pass and the epoch-end bookkeeping remain)
    cfg2 = _cfg(root, tmp_path / "ckpts", num_epochs=3,
                model=TINY.replace(checkpoint=ckpt_root))
    r2 = training.fit(cfg2, progress=False)
    assert not r2["preempted"]
    assert int(r2["state"].step) == 6  # 3 epochs x 2 batches
    assert r2["checkpoint"] == ckpt_root  # continued in place
    assert np.isfinite(r2["train_loss"][1:]).all()


# ---------------------------------------------------------------------------
# kill-mid-save → resume (the acceptance bitwise-equivalence test)
# ---------------------------------------------------------------------------


def test_kill_mid_save_then_resume_is_bitwise_identical(tmp_path):
    """SIGKILL a training subprocess between the params and opt writes of a
    checkpoint version: the .tmp carcass must be ignored, resume must pick
    the last COMPLETE version, and the finished run must match an
    uninterrupted run bitwise (params, opt_state, step)."""
    root = _dataset(tmp_path, n_pairs=8)  # 4 train batches at bs=2

    worker = tmp_path / "worker.py"
    worker.write_text(f"""
import sys
sys.path.insert(0, {_REPO!r})
import jax
jax.config.update("jax_platforms", "cpu")
from ncnet_tpu.config import ModelConfig, TrainConfig
from ncnet_tpu import training

cfg = TrainConfig(
    model=ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                      ncons_channels=(1,)),
    image_size=48,
    dataset_image_path={root!r},
    dataset_csv_path={root + "/image_pairs"!r},
    num_epochs=1, batch_size=2, lr=1e-3,
    result_model_dir={str(tmp_path / "killed")!r},
    log_interval=10, data_parallel=False,
    checkpoint_steps=1, keep_checkpoints=10,
)
training.fit(cfg, progress=False)
""")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # identical device topology to the in-process runs (conftest's 8 virtual
    # CPU devices): XLA CPU partitions reductions per device count, and the
    # bitwise-equality bar below tolerates no reassociation
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["NCNET_TPU_FAULTS"] = json.dumps({"kill_at_version": 3})
    proc = subprocess.run(
        [sys.executable, str(worker)], env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=600,
    )
    assert proc.returncode == -9, f"expected SIGKILL, got:\n{proc.stdout[-3000:]}"

    (ckpt_root,) = [
        os.path.join(tmp_path / "killed", d)
        for d in os.listdir(tmp_path / "killed")
    ]
    names = sorted(os.listdir(ckpt_root))
    assert "step_00000003.tmp" in names  # the mid-save carcass
    assert "step_00000003" not in names  # never committed
    assert [n for n, _ in ckpt_io.list_checkpoint_versions(ckpt_root)] == [1, 2]

    # resume from the same directory: continues from step_2 (epoch 1,
    # batch 2) and reclaims the carcass
    cfg_resume = _cfg(root, tmp_path / "killed",
                      model=TINY.replace(checkpoint=ckpt_root),
                      checkpoint_steps=1, keep_checkpoints=10)
    r_resumed = training.fit(cfg_resume, progress=True)
    assert r_resumed["checkpoint"] == ckpt_root
    assert not any(d.endswith(".tmp") for d in os.listdir(ckpt_root))
    assert [n for n, _ in ckpt_io.list_checkpoint_versions(ckpt_root)] \
        == [1, 2, 3, 4]

    # the uninterrupted twin
    r_full = training.fit(
        _cfg(root, tmp_path / "full", checkpoint_steps=1,
             keep_checkpoints=10),
        progress=False,
    )
    _assert_states_equal(r_resumed["state"], r_full["state"])
    assert int(r_resumed["state"].step) == 4
