"""Tier-1 suite for the live serving telemetry plane (ISSUE 11).

Three layers under test, end to end on CPU fake-engine pools:

  * **Exposition** — ``observability/export.py`` renders registry
    snapshots as Prometheus text and ``serving/introspect.py`` serves
    ``/metrics`` + ``/healthz`` + ``/statusz`` from a live service.  The
    minimal exposition parser in ``export.parse_prometheus`` (plus raw-text
    assertions, so renderer and parser cannot co-sign each other's bugs)
    validates every scrape: label escaping, counter monotonicity across
    two scrapes under load, histogram bucket cumulativity and
    ``_sum``/``_count`` consistency against the in-process ``Histogram``.
  * **Per-request trace timelines** — every terminal outcome emits a
    ``request_timeline`` whose queue/device/fetch segments sum to its
    end-to-end wall, and ``trace_export`` renders each as balanced
    Perfetto async ("b"/"e") slices keyed by request id.
  * **SLO accounting** — the sliding-window error-budget tracker, its
    ``slo`` events, and the scrape-vs-replay consistency bar:
    ``run_report --slo`` recomputed from the event log matches the final
    ``/metrics`` counters exactly.

THE acceptance chain (test_acceptance_chain_live_plane): a 4-replica CPU
service under a synthetic stream serves concurrent ``/healthz`` +
``/metrics`` scrapes that parse cleanly; an injected replica death is
visible in the next ``/healthz`` scrape before resurrection; every
terminated request's timeline renders as async slices with attribution
summing to its latency; and the replayed SLO counters equal the final
scrape's.
"""

import json
import math
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from ncnet_tpu import ops
from ncnet_tpu.observability import EventLog, MetricsRegistry
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.observability.export import (
    Family,
    histogram_percentile,
    parse_prometheus,
    registry_families,
    render,
    sanitize_metric_name,
)
from ncnet_tpu.observability.metrics import Histogram
from ncnet_tpu.serving import (
    HEALTH_DOC_SCHEMA,
    BatchMatchEngine,
    MatchService,
    ServingConfig,
    SLOTracker,
)
from ncnet_tpu.utils import faults
from ncnet_tpu.utils.faults import FaultPlan

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import run_report  # noqa: E402
import serve_top  # noqa: E402
import stall_watchdog  # noqa: E402
import trace_export  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)
    yield
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)


def u8(side=32, seed=0):
    return np.random.default_rng(seed).integers(
        0, 255, (side, side, 3), dtype=np.uint8)


class FakeEngine:
    """Device stand-in (tests/test_serving_pool.py protocol): real
    Replica/MatchService code paths, no jit compiles."""

    split = staticmethod(BatchMatchEngine.split)
    half_precision = False

    def __init__(self, latency_s: float = 0.01):
        self.latency_s = latency_s

    def dispatch(self, src, tgt):
        faults.device_error_hook("fake_serve")
        return (src.shape[0], time.monotonic())

    def fetch(self, handle):
        b, t0 = handle
        while time.monotonic() - t0 < self.latency_s:
            time.sleep(0.005)
        table = np.zeros((b, 6, 16), np.float32)
        table[:, 4, :] = 1.0
        table[:, 5, :5] = [0.5, 0.1, 0.4, 0.9, 0.8]
        return table

    def retrace(self):
        pass


def plane_service(n=2, latency_s=0.01, **over):
    cfg = dict(bucket_multiple=32, max_image_side=64, max_batch=2,
               max_queue=128, max_in_flight_per_client=128,
               introspect_port=0)
    cfg.update(over)
    engines = [FakeEngine(latency_s=latency_s) for _ in range(n)]
    return MatchService(engine=engines,
                        serving=ServingConfig(**cfg)), engines


def wait_until(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def get(url, timeout=10.0):
    """(status, body) — 503 is a valid healthz answer, not an error."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def series(fams, family, suffix="", **labels):
    """The one sample value matching (family+suffix, labels)."""
    hits = [v for name, lb, v in fams[family]["samples"]
            if name == family + suffix
            and all(lb.get(k) == v2 for k, v2 in labels.items())]
    assert len(hits) == 1, (family, suffix, labels, hits)
    return hits[0]


# ---------------------------------------------------------------------------
# exposition units: renderer, parser, escaping, histogram semantics
# ---------------------------------------------------------------------------


def test_prometheus_label_escaping_and_name_sanitizing():
    fam = Family("m_x", "gauge", help='has "quotes" and \\slashes\\')
    fam.add(1.5, path='a"b\\c\nd', plain="ok")
    text = render([fam])
    # raw-text asserts FIRST: the parser must not co-sign renderer bugs
    assert 'path="a\\"b\\\\c\\nd"' in text
    assert "# HELP m_x has \"quotes\" and \\\\slashes\\\\" in text
    assert "# TYPE m_x gauge" in text
    fams = parse_prometheus(text)
    (_, labels, value), = fams["m_x"]["samples"]
    assert labels == {"path": 'a"b\\c\nd', "plain": "ok"}
    assert value == 1.5
    # illegal registry keys become legal metric names
    assert sanitize_metric_name("serve_wall_ms_64x64-96x64") == \
        "serve_wall_ms_64x64_96x64"
    assert sanitize_metric_name("9lives") == "_9lives"


def test_histogram_family_is_cumulative_and_consistent():
    h = Histogram(0.0, 10.0, bins=5)
    h.add([0.5, 1.5, 1.7, 9.9, 25.0])  # 25.0 clamps into the last bin
    fam = Family("lat", "histogram").add_histogram(h, bucket="b")
    text = render([fam])
    fams = parse_prometheus(text)
    buckets = [(lb["le"], v) for name, lb, v in fams["lat"]["samples"]
               if name == "lat_bucket"]
    # cumulative, ordered, +Inf == _count == in-process count
    values = [v for _, v in buckets]
    assert values == sorted(values)
    assert buckets[-1][0] == "+Inf" and buckets[-1][1] == h.count == 5
    assert series(fams, "lat", "_count", bucket="b") == h.count
    assert series(fams, "lat", "_sum", bucket="b") == pytest.approx(h.sum)
    # bucket counts reproduce the digest's bins exactly
    cum = 0
    for (le, v), n in zip(buckets[:-1], h.counts):
        cum += n
        assert v == cum
    # the read-side percentile approximates the digest's own
    bsamples = [s for s in fams["lat"]["samples"]
                if s[0].endswith("_bucket")]
    assert histogram_percentile(bsamples, 50) == pytest.approx(
        h.percentile(50), abs=2.0 * (10.0 / 5))


def test_registry_families_generic_dump():
    reg = MetricsRegistry(scope="t")
    reg.counter("hits").inc(3)
    reg.gauge("depth").set(7)
    t = reg.timer("wall")
    for s in (0.1, 0.2, 0.3):
        t.observe(s)
    reg.histogram("q_score", 0.0, 1.0, 4).add([0.1, 0.6, 0.9])
    fams = parse_prometheus(render(registry_families(reg, prefix="p")))
    assert series(fams, "p_hits_total") == 3
    assert fams["p_hits_total"]["type"] == "counter"
    assert series(fams, "p_depth") == 7
    assert series(fams, "p_wall_seconds", "_count") == 3
    assert series(fams, "p_wall_seconds", "_sum") == pytest.approx(0.6)
    assert series(fams, "p_wall_seconds", quantile="0.5") == \
        pytest.approx(0.2)
    assert series(fams, "p_q_score", "_count") == 3


def test_parser_rejects_malformed_lines():
    with pytest.raises(ValueError):
        parse_prometheus("not a metric line at all!\n")
    with pytest.raises(ValueError):
        parse_prometheus('m{unterminated="} 1\n')


# ---------------------------------------------------------------------------
# the live endpoints
# ---------------------------------------------------------------------------


def test_metrics_scrape_parses_and_counters_monotonic_under_load():
    svc, _ = plane_service(n=2)
    svc.start()
    try:
        url = svc.introspect_url
        assert url is not None
        img = u8()
        futs = [svc.submit(img, img) for _ in range(10)]
        # scrape MID-load, then after more work: both parse, counters rise
        code1, text1 = get(url + "/metrics")
        assert code1 == 200
        f1 = parse_prometheus(text1)
        for f in futs:
            f.result(timeout=60)
        for f in [svc.submit(img, img) for _ in range(6)]:
            f.result(timeout=60)
        assert wait_until(
            lambda: svc.health()["counters"]["results"] == 16)
        code2, text2 = get(url + "/metrics")
        assert code2 == 200
        f2 = parse_prometheus(text2)
        # counter monotonicity per (series, labels) across the two scrapes
        for fam_name, fam in f1.items():
            if fam["type"] != "counter":
                continue
            later = {(n, tuple(sorted(lb.items()))): v
                     for n, lb, v in f2[fam_name]["samples"]}
            for n, lb, v in fam["samples"]:
                key = (n, tuple(sorted(lb.items())))
                assert later.get(key, v) >= v, (key, v, later.get(key))
        assert series(f2, "ncnet_serve_requests_total",
                      outcome="results") == 16
        assert series(f2, "ncnet_serve_scrapes_total") == 2
        # histogram consistency vs the in-process digest
        bucket = "32x32-32x32"
        h = svc._registry.histogram(f"serve_wall_ms_{bucket}", 0.0,
                                    svc.cfg.latency_hist_ms)
        bsamples = [s for s in f2["ncnet_serve_latency_ms"]["samples"]
                    if s[0].endswith("_bucket")
                    and s[1].get("bucket") == bucket]
        values = [v for _, _, v in bsamples]
        assert values == sorted(values)  # cumulative
        assert series(f2, "ncnet_serve_latency_ms", "_count",
                      bucket=bucket) == h.count == 16
        assert series(f2, "ncnet_serve_latency_ms", "_sum",
                      bucket=bucket) == pytest.approx(h.sum)
        inf_v = [v for _, lb, v in bsamples if lb["le"] == "+Inf"]
        assert inf_v == [h.count]
        # quality digests rode along as labeled histogram series
        assert series(f2, "ncnet_serve_quality", "_count",
                      signal="score") == 16
    finally:
        svc.stop()


def test_healthz_document_and_status_codes():
    svc, _ = plane_service(n=2, slo_ms=500.0)
    svc.start()
    try:
        url = svc.introspect_url
        img = u8()
        for f in [svc.submit(img, img) for _ in range(4)]:
            f.result(timeout=60)
        code, body = get(url + "/healthz")
        assert code == 200
        doc = json.loads(body)
        assert doc["schema"] == HEALTH_DOC_SCHEMA
        assert doc["state"] in ("STARTING", "READY")
        assert doc["pool"]["ready"] == doc["pool"]["total"] == 2
        assert {r["id"] for r in doc["pool"]["replicas"]} == \
            {"rep0", "rep1"}
        assert doc["queue"]["buckets"] == ["32x32-32x32"]
        assert doc["counters"]["results"] == 4
        assert doc["slo"]["objectives"]["default_ms"] == 500.0
        assert doc["service"]["history"][0]["state"] == "STARTING"
        assert isinstance(doc["activity"]["age_s"], float)
        # the same dict the in-process probe returns (the unification bar)
        in_proc = svc.health()
        assert doc["pool"]["total"] == in_proc["pool"]["total"]
        assert set(doc) == set(in_proc)
        # draining flips the readiness code to 503, body still the doc —
        # slow fetches keep work in flight so DRAINING lingers long
        # enough to scrape (an idle drain completes instantly and takes
        # the endpoint down with the worker)
        faults.install(FaultPlan(slow_replica_ids=("rep0", "rep1"),
                                 slow_replica_seconds=1.5))
        svc.submit(img, img)
        svc.request_drain("test")
        code, body = get(url + "/healthz")
        assert code == 503
        assert json.loads(body)["state"] == "DRAINING"
    finally:
        faults.clear()
        svc.stop()


def test_statusz_and_root_and_404():
    svc, _ = plane_service(n=2, slo_ms=500.0)
    svc.start()
    try:
        url = svc.introspect_url
        img = u8()
        for f in [svc.submit(img, img) for _ in range(4)]:
            f.result(timeout=60)
        code, body = get(url + "/statusz")
        assert code == 200
        assert "replicas (2/2 ready)" in body
        assert "rep0" in body and "rep1" in body
        assert "bucket ladder: 32x32-32x32" in body
        assert "recent health timeline:" in body
        assert get(url + "/")[0] == 200
        assert get(url + "/nope")[0] == 404
    finally:
        svc.stop()


def test_endpoint_death_leaves_serving_untouched():
    """Kill-mid-scrape: the introspection thread dies while scrapes are in
    flight and the stream keeps serving — the plane is strictly optional.
    A renderer bug answers 500 without touching serving either."""
    svc, _ = plane_service(n=2)
    svc.start()
    try:
        url = svc.introspect_url
        img = u8()
        stop_scraping = threading.Event()
        scrape_errors = []

        def hammer():
            while not stop_scraping.is_set():
                try:
                    get(url + "/metrics", timeout=2.0)
                except Exception as e:  # noqa: BLE001 — expected once dead
                    scrape_errors.append(type(e).__name__)
                time.sleep(0.002)

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        futs = [svc.submit(img, img) for _ in range(8)]
        # kill the endpoint mid-stream, mid-scrape
        svc._introspect.stop()
        for f in [svc.submit(img, img) for _ in range(8)]:
            futs.append(f)
        for f in futs:
            assert f.result(timeout=60).request_id
        stop_scraping.set()
        t.join(5.0)
        assert svc.health()["counters"]["results"] == 16
        assert svc.state in ("READY", "STARTING")
    finally:
        svc.stop()


def test_handler_renderer_bug_answers_500_not_crash(monkeypatch):
    svc, _ = plane_service(n=1)
    svc.start()
    try:
        url = svc.introspect_url
        monkeypatch.setattr(
            svc._introspect, "metrics_text",
            lambda: (_ for _ in ()).throw(RuntimeError("boom")))
        code, body = get(url + "/metrics")
        assert code == 500 and "boom" in body
        img = u8()
        assert svc.submit(img, img).result(timeout=60).request_id
    finally:
        svc.stop()


def test_bind_failure_is_fail_open():
    """A port that cannot bind costs the plane, never the service."""
    svc1, _ = plane_service(n=1)
    svc1.start()
    try:
        port = svc1._introspect.port
        svc2, _ = plane_service(n=1, introspect_port=port)
        svc2.start()  # same port: bind fails, serving continues
        try:
            assert svc2.introspect_url is None
            img = u8()
            assert svc2.submit(img, img).result(timeout=60).request_id
        finally:
            svc2.stop()
    finally:
        svc1.stop()


# ---------------------------------------------------------------------------
# SLO accounting
# ---------------------------------------------------------------------------


def test_slo_tracker_units():
    t = SLOTracker(default_ms=100.0, by_bucket=(("b1", 10.0),),
                   budget_pct=10.0, window=4, emit_every=2)
    assert t.objective_ms("b1") == 10.0
    assert t.objective_ms("other") == 100.0
    assert t.objective_ms(None) == 100.0
    # result within objective: good; over: latency miss
    assert t.observe("result", bucket="other", wall_ms=50.0) is False
    assert t.observe("result", bucket="b1", wall_ms=50.0) is True  # emit due
    assert t.bad["latency"] == 1 and t.ok == 1
    t.observe("deadline", bucket="b1")
    t.observe("quarantined", bucket="b1")
    t.observe("shed", bucket="b1")
    assert t.admitted == 5 and t.bad_total() == 4
    # burn: 4/5 bad over a 10% budget = 800%
    assert t.budget_burn_pct() == pytest.approx(800.0)
    # window holds only the last 4 (all bad) = 1000%
    assert t.window_burn_pct() == pytest.approx(1000.0)
    snap = t.snapshot()
    assert snap["bad"] == {"deadline": 1, "quarantined": 1, "shed": 1,
                           "latency": 1}
    assert snap["window"] == {"n": 4, "bad": 4, "burn_pct": 1000.0}
    with pytest.raises(ValueError):
        t.observe("no_such_outcome")
    with pytest.raises(ValueError):
        SLOTracker(budget_pct=0.0)


def test_slo_events_and_replay_consistency(tmp_path):
    """Deadline blows + latency misses land in slo events, /metrics, and
    run_report --slo identically."""
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, _ = plane_service(
            n=2, latency_s=0.05, slo_ms=500.0,
            slo_ms_by_bucket=(("32x32-32x32", 0.001),),
            slo_budget_pct=5.0, slo_emit_every=3)
        svc.start()
        img = u8()
        futs = [svc.submit(img, img) for _ in range(7)]
        # one admitted request that deadline-blows at dequeue
        dl = svc.submit(img, img, deadline_s=0.001)
        for f in futs:
            f.result(timeout=60)
        with pytest.raises(Exception):
            dl.result(timeout=60)
        assert wait_until(lambda: svc._slo.admitted == 8)
        code, text = get(svc.introspect_url + "/metrics")
        fams = parse_prometheus(text)
        svc.stop()
    _, events = obs_events.replay_events(log_path)
    sec = run_report.build_slo_section(events)
    # replay == final slo event == the live scrape taken at quiescence
    assert sec["matches_final_event"] is True
    assert sec["admitted"] == 8
    assert sec["bad"]["latency"] == 7  # every result over the 1 µs bucket SLO
    assert sec["bad"]["deadline"] == 1
    assert series(fams, "ncnet_serve_slo_requests_total",
                  slo_class="latency") == sec["bad"]["latency"]
    assert series(fams, "ncnet_serve_slo_requests_total",
                  slo_class="deadline") == sec["bad"]["deadline"]
    assert series(fams, "ncnet_serve_slo_admitted_total") == sec["admitted"]
    assert series(fams, "ncnet_serve_slo_budget_burn_pct") == \
        pytest.approx(sec["budget_burn_pct"])
    assert series(fams, "ncnet_serve_slo_objective_ms",
                  bucket="32x32-32x32") == pytest.approx(0.001)
    # periodic slo events actually streamed (emit_every=3, 8 outcomes,
    # plus the final one from _finish)
    slo_events = [e for e in events if e.get("event") == "slo"]
    assert len(slo_events) >= 3
    assert slo_events[-1].get("final") is True
    # CLI surface
    assert run_report.main([log_path, "--slo", "--serving"]) == 0


# ---------------------------------------------------------------------------
# per-request trace timelines
# ---------------------------------------------------------------------------


def test_request_timelines_attribute_and_export(tmp_path):
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, _ = plane_service(n=2, latency_s=0.02)
        svc.start()
        img = u8()
        futs = [svc.submit(img, img) for _ in range(6)]
        dl = svc.submit(img, img, deadline_s=0.001)  # dequeue eviction
        for f in futs:
            f.result(timeout=60)
        with pytest.raises(Exception):
            dl.result(timeout=60)
        assert wait_until(
            lambda: svc.health()["counters"]["results"] == 6)
        svc.stop()
    _, events = obs_events.replay_events(log_path)
    tls = {e["request"]: e for e in events
           if e.get("event") == "request_timeline"}
    results = [e for e in events if e.get("event") == "serve_result"]
    assert len(tls) == 7  # 6 results + 1 deadline: every terminal outcome
    for e in tls.values():
        segs = [e[k] for k in ("queue_ms", "device_ms", "fetch_ms")
                if k in e]
        assert math.isclose(sum(segs), e["total_ms"], abs_tol=1e-6)
    # a served request has all three phases; its timeline total brackets
    # the serve_result wall (both measured submit→settle, stamped apart)
    for r in results:
        tl = tls[r["request"]]
        assert {"queue_ms", "device_ms", "fetch_ms"} <= set(tl)
        assert tl["outcome"] == "result"
        assert tl["replica"] == r["replica"]
        assert tl["total_ms"] == pytest.approx(r["wall_ms"], abs=50.0)
    # the deadline eviction never dispatched: queue time only
    dl_tl = [e for e in tls.values() if e["outcome"] == "deadline"]
    assert len(dl_tl) == 1 and "device_ms" not in dl_tl[0]
    # Perfetto export: balanced async b/e pairs per request id, nested
    # segments tiling the enclosing slice
    trace = trace_export.build_trace([log_path])
    asyncs = [t for t in trace["traceEvents"]
              if t.get("cat") == "serve_request"]
    assert asyncs, "no async slices exported"
    by_id = {}
    for t in asyncs:
        by_id.setdefault(t["id"], []).append(t)
    assert len(by_id) == 7
    for tid, evs in by_id.items():
        assert sum(1 for t in evs if t["ph"] == "b") == \
            sum(1 for t in evs if t["ph"] == "e")
        outer = [t for t in evs if t["ph"] == "b"
                 and t["name"].startswith("req ")]
        assert len(outer) == 1
        # nested segment slices tile the outer one end to end
        outer_b = outer[0]["ts"]
        outer_e = [t for t in evs if t["ph"] == "e"
                   and t["name"] == outer[0]["name"]][0]["ts"]
        seg_b = [t for t in evs if t["ph"] == "b" and t is not outer[0]]
        seg_e = [t for t in evs if t["ph"] == "e"
                 and not t["name"].startswith("req ")]
        assert min(t["ts"] for t in seg_b) == pytest.approx(outer_b, abs=1)
        assert max(t["ts"] for t in seg_e) == pytest.approx(outer_e, abs=1)


# ---------------------------------------------------------------------------
# operator tools: serve_top + stall_watchdog --url
# ---------------------------------------------------------------------------


def test_serve_top_once_against_live_service(capsys):
    svc, _ = plane_service(n=2, slo_ms=500.0)
    svc.start()
    try:
        img = u8()
        for f in [svc.submit(img, img) for _ in range(6)]:
            f.result(timeout=60)
        assert serve_top.main([svc.introspect_url, "--once"]) == 0
        out = capsys.readouterr().out
        assert "state: READY" in out
        assert "rep0" in out and "rep1" in out
        assert "32x32-32x32" in out and "p99_ms" in out
        assert "SLO burn" in out
        # --json mode emits one parseable document
        assert serve_top.main([svc.introspect_url, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["healthz"]["schema"] == HEALTH_DOC_SCHEMA
        assert "ncnet_serve_requests_total" in doc["metrics"]
        # draining service: frame still renders, exit code flips to 3
        # (slow fetches keep DRAINING alive long enough to poll)
        faults.install(FaultPlan(slow_replica_ids=("rep0", "rep1"),
                                 slow_replica_seconds=1.5))
        svc.submit(img, img)
        svc.request_drain("test")
        assert serve_top.main([svc.introspect_url, "--once"]) == 3
        capsys.readouterr()
    finally:
        faults.clear()
        svc.stop()
    # unreachable after stop
    assert serve_top.main([svc.introspect_url or
                           "http://127.0.0.1:9", "--once"]) == 2


def test_stall_watchdog_url_mode(tmp_path):
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, _ = plane_service(n=2)
        svc.start()
        try:
            url = svc.introspect_url
            img = u8()
            for f in [svc.submit(img, img) for _ in range(6)]:
                f.result(timeout=60)
            # alive: fresh activity, cadence threshold from the event log
            v = stall_watchdog.judge_url(url, events_path=log_path,
                                         factor=10.0, min_age=5.0)
            assert v["status"] == "alive" and v["mode"] == "url"
            assert v["median_step_wall_s"] is not None
            assert set(v.get("replicas", {})) == {"rep0", "rep1"}
            # a wedged pool: hang one replica's fetch with work queued so
            # activity stops advancing, and shrink the floor — stalled
            faults.install(FaultPlan(slow_replica_ids=("rep0", "rep1"),
                                     slow_replica_seconds=10.0))
            svc.submit(img, img)
            assert wait_until(lambda: stall_watchdog.judge_url(
                url, factor=1.0, min_age=0.3)["status"] == "stalled",
                timeout=10.0)
            # ...but the event-log replica backstop keeps its PR 10
            # semantics: a stale primary signal is overridden when the
            # log shows a lane still draining.  Fabricate a sidecar log
            # with FRESH replica-tagged batches (the shape a healthy lane
            # writes) and judge the wedged service against it.
            side = str(tmp_path / "fresh.jsonl")
            with obs_events.bound(EventLog(side)):
                for _ in range(4):
                    obs_events.emit("serve_batch", replica="rep0",
                                    wall_s=0.02, size=1)
            v = stall_watchdog.judge_url(url, events_path=side,
                                         factor=1.0, min_age=0.3)
            assert v["status"] == "alive"
            assert v["alive_via"] == "replica_cadence:rep0"
            assert v["replicas"]["rep0"]["recent"] is True
        finally:
            faults.clear()
            svc.stop(drain=False, timeout=5.0)
    # stopped service: unreachable endpoint = missing (exit 2 semantics).
    # The endpoint goes down at the END of _finish, which is bounded by
    # the hung fetcher's join — poll rather than race it.
    assert wait_until(
        lambda: stall_watchdog.judge_url(url)["status"] == "missing",
        timeout=30.0, interval=0.25)
    # CLI argument contract: exactly one of heartbeat / --url
    with pytest.raises(SystemExit):
        stall_watchdog.main([])


# ---------------------------------------------------------------------------
# THE acceptance chain
# ---------------------------------------------------------------------------


def test_acceptance_chain_live_plane(tmp_path):
    """ISSUE 11 acceptance: 4-replica CPU service under a synthetic stream
    with CONCURRENT /healthz + /metrics scrapes parsing cleanly; an
    injected replica death visible in the next /healthz before
    resurrection; every terminated request's timeline exported as async
    slices whose attribution sums to its latency; and run_report --slo
    replayed from the event log matching the final /metrics error-budget
    counters exactly."""
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc, _ = plane_service(
            n=4, latency_s=0.02, slo_ms=2000.0, slo_emit_every=8,
            replica_max_failures=1, resurrect_after_s=0.2)
        svc.start()
        url = svc.introspect_url
        img = u8()
        scrape_failures = []
        stop_scraping = threading.Event()

        def scraper():
            while not stop_scraping.is_set():
                try:
                    code, text = get(url + "/metrics", timeout=5.0)
                    assert code == 200
                    parse_prometheus(text)  # raises on a malformed scrape
                    code, body = get(url + "/healthz", timeout=5.0)
                    json.loads(body)
                except Exception as e:  # noqa: BLE001 — collected, the
                    scrape_failures.append(repr(e))  # test asserts empty
                time.sleep(0.005)

        t = threading.Thread(target=scraper, daemon=True)
        t.start()
        # phase 1: healthy stream under concurrent scrapes
        futs = [svc.submit(img, img) for _ in range(12)]
        for f in futs:
            f.result(timeout=60)
        # phase 2: rep2 dies mid-batch; zero lost; the NEXT /healthz
        # scrape shows it DEAD before resurrection can run (probes keep
        # failing while the fault is armed)
        faults.install(FaultPlan(dead_replica_ids=("rep2",)))
        futs = [svc.submit(img, img) for _ in range(12)]
        for f in futs:
            f.result(timeout=60)
        assert wait_until(lambda: svc.health()["pool"]["ready"] == 3)
        code, body = get(url + "/healthz")
        doc = json.loads(body)
        assert code == 200  # DEGRADED still admits
        assert doc["state"] == "DEGRADED"
        states = {r["id"]: r["state"] for r in doc["pool"]["replicas"]}
        assert states["rep2"] == "DEAD"
        assert doc["pool"]["ready"] == 3
        # phase 3: heal → the probe resurrects rep2, visible on /healthz
        faults.clear()
        assert wait_until(lambda: svc.health()["pool"]["ready"] == 4)
        doc = json.loads(get(url + "/healthz")[1])
        assert doc["state"] == "READY" and doc["pool"]["ready"] == 4
        # phase 4: quiesce, take THE final scrape, then stop
        total = 24
        assert wait_until(lambda: svc._slo.admitted == total)
        fams = parse_prometheus(get(url + "/metrics")[1])
        stop_scraping.set()
        t.join(5.0)
        svc.stop()
    assert scrape_failures == []

    _, events = obs_events.replay_events(log_path)
    # outcome-total + zero lost across the chaos
    sec = run_report.build_serving_section(events)
    assert sec["outcomes"]["unresolved"] == 0
    assert sec["outcomes"]["results"] == total
    assert sec["final_health_doc"]["state"] == "STOPPED"
    assert sec["final_health_doc"]["schema"] == HEALTH_DOC_SCHEMA

    # every terminated request carries a timeline whose segments sum to
    # its end-to-end latency, and each renders as balanced async slices
    tls = [e for e in events if e.get("event") == "request_timeline"]
    assert len(tls) == total
    for e in tls:
        segs = [e[k] for k in ("queue_ms", "device_ms", "fetch_ms")
                if k in e]
        assert math.isclose(sum(segs), e["total_ms"], abs_tol=1e-6)
    trace = trace_export.build_trace([log_path])
    asyncs = [x for x in trace["traceEvents"]
              if x.get("cat") == "serve_request"]
    ids = {x["id"] for x in asyncs}
    assert len(ids) == total
    for rid in ids:
        evs = [x for x in asyncs if x["id"] == rid]
        assert sum(1 for x in evs if x["ph"] == "b") == \
            sum(1 for x in evs if x["ph"] == "e")

    # scrape-vs-replay: run_report --slo == the final /metrics counters
    slo = run_report.build_slo_section(events)
    assert slo["matches_final_event"] is True
    assert series(fams, "ncnet_serve_slo_admitted_total") == \
        slo["admitted"] == total
    assert series(fams, "ncnet_serve_slo_requests_total",
                  slo_class="ok") == slo["ok"]
    for cls in ("latency", "deadline", "quarantined", "shed"):
        assert series(fams, "ncnet_serve_slo_requests_total",
                      slo_class=cls) == slo["bad"][cls]
    assert series(fams, "ncnet_serve_slo_budget_burn_pct") == \
        pytest.approx(slo["budget_burn_pct"])
    # and the death was in the log for the postmortem too
    assert any(e.get("event") == "serve_health"
               and e.get("replica") == "rep2"
               and e.get("state") == "DEAD" for e in events)
