"""Test configuration: force CPU with 8 virtual devices.

Multi-chip hardware isn't available in CI; the sharding/parallelism tests run
on a virtual 8-device CPU mesh instead (the same substitution SURVEY.md §4
prescribes).  Note: this environment pre-imports jax at interpreter startup
(axon sitecustomize), so env vars alone are too late — we override the
platform through jax.config before the backend is first initialized.
"""

import os

# Tests must not mutate the repo's committed perf history or the user-level
# tier cache: both default on (that is the product behavior), so the suite
# turns them off globally — a hard override, not setdefault, so a developer
# with either knob exported in their shell cannot have the suite write into
# (or clear) their real store/cache.  Tests that exercise these point the
# env vars at tmp paths explicitly via monkeypatch.
os.environ["NCNET_TPU_PERF_STORE"] = "off"
os.environ["NCNET_TPU_TIER_CACHE"] = "off"
os.environ["NCNET_TPU_MEMORY_LEDGER"] = "off"

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: slow integration tests (flagship config)")
