"""Test configuration: force CPU with 8 virtual devices.

Multi-chip hardware isn't available in CI; the sharding/parallelism tests run
on a virtual 8-device CPU mesh instead (the same substitution SURVEY.md §4
prescribes).  Note: this environment pre-imports jax at interpreter startup
(axon sitecustomize), so env vars alone are too late — we override the
platform through jax.config before the backend is first initialized.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: slow integration tests (flagship config)")
