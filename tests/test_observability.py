"""Tier-1 tests for the observability layer (PR 5).

The event log makes the same crash-safety claims as the PR 3 EvalJournal
(fsynced atomic appends, torn-tail-tolerant replay), so it carries the same
proof obligations: every claim is executed by deterministic fault injection
(``utils/faults.py``), not merely written.  Beyond the unit contracts, the
acceptance scenario runs end-to-end: a training subprocess SIGKILLed
mid-epoch, resumed in-process, must leave ONE event log that
``tools/run_report.py`` replays without error and whose step / checkpoint /
resume counters are consistent with what actually ran.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from ncnet_tpu.config import ModelConfig, TrainConfig
from ncnet_tpu.data.synthetic import write_pair_dataset
from ncnet_tpu.models import checkpoint as ckpt_io
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.observability.device import Heartbeat
from ncnet_tpu.observability.events import (
    SCHEMA_VERSION,
    EventLog,
    replay_events,
)
from ncnet_tpu.observability.logging import get_logger
from ncnet_tpu.observability.metrics import (
    MetricsRegistry,
    filter_flops,
    train_step_flops,
)
from ncnet_tpu import training
from ncnet_tpu.utils import faults
from ncnet_tpu.utils.faults import FaultPlan

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import check_no_bare_print  # noqa: E402
import run_report  # noqa: E402

TINY = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                   ncons_channels=(1,))


@pytest.fixture(autouse=True)
def _unbound_sink():
    """Every test starts and ends with no global event sink (a leaked sink
    would silently cross-couple tests)."""
    obs_events.set_global_sink(None)
    yield
    obs_events.set_global_sink(None)


# ---------------------------------------------------------------------------
# event log: schema, replay, resume lineage, crash safety
# ---------------------------------------------------------------------------


def test_event_log_schema_roundtrip(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_meta={"note": "t"}) as log:
        log.emit("step", step=1, loss=0.5, shape=(2, 3))
        log.emit("step", step=2, loss=float("nan"),
                 arr=np.float32(1.5), vec=np.arange(2))
    header, events = replay_events(path)
    h = header["header"]
    assert h["schema"] == SCHEMA_VERSION
    assert h["run_id"] == log.run_id
    assert h["meta"] == {"note": "t"}
    assert [e["event"] for e in events] == ["step", "step"]
    assert [e["seq"] for e in events] == [0, 1]
    assert all(e["run"] == log.run_id for e in events)
    assert events[0]["shape"] == [2, 3]          # tuple → list
    assert events[1]["loss"] == "nan"            # strict-JSON safe
    assert events[1]["arr"] == 1.5               # numpy scalar → float
    assert events[1]["vec"] == [0, 1]            # ndarray → list


def test_event_log_reopen_appends_under_new_run_id(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with EventLog(path) as log1:
        log1.emit("run_start")
        run1 = log1.run_id
    with EventLog(path) as log2:
        log2.emit("resume", step=3)
        run2 = log2.run_id
    assert run1 != run2
    header, events = replay_events(path)
    assert header["header"]["run_id"] == run1  # the original header survives
    assert [e["run"] for e in events] == [run1, run2]


def test_event_log_sets_foreign_file_aside(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with open(path, "w") as f:
        f.write("this is not an event log\n")
    with EventLog(path) as log:
        log.emit("run_start")
    assert os.path.exists(path + ".stale")
    with open(path + ".stale") as f:
        assert "not an event log" in f.read()
    _, events = replay_events(path)
    assert len(events) == 1


def test_replay_tolerates_torn_tail_and_reopen_truncates(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with EventLog(path) as log:
        log.emit("a", i=1)
        log.emit("b", i=2)
    with open(path, "a") as f:
        f.write('{"t": 1, "run": "x", "seq": 2, "event": "torn')  # no \n
    _, events = replay_events(path)
    assert [e["event"] for e in events] == ["a", "b"]
    # re-opening truncates the torn tail so the next record starts clean
    with EventLog(path) as log2:
        log2.emit("c", i=3)
    _, events = replay_events(path)
    assert [e["event"] for e in events] == ["a", "b", "c"]


def test_replay_rejects_foreign_and_newer_schema(tmp_path):
    missing = str(tmp_path / "nope.jsonl")
    with pytest.raises(FileNotFoundError):
        replay_events(missing)
    foreign = str(tmp_path / "foreign.jsonl")
    with open(foreign, "w") as f:
        f.write('{"kind": "something_else"}\n')
    with pytest.raises(ValueError):
        replay_events(foreign)
    newer = str(tmp_path / "newer.jsonl")
    with open(newer, "w") as f:
        f.write(json.dumps({"kind": "ncnet_tpu_events",
                            "header": {"schema": SCHEMA_VERSION + 1},
                            "schema": SCHEMA_VERSION + 1}) + "\n")
    with pytest.raises(ValueError):
        replay_events(newer)


def test_sigkill_mid_event_append_replays_and_resumes(tmp_path):
    """The EvalJournal proof obligation, ported: SIGKILL mid-append of the
    3rd record (torn prefix flushed first) must cost at most that one
    record — replay sees records 1-2, and a re-opened log appends cleanly
    after truncating the torn tail."""
    path = str(tmp_path / "events.jsonl")
    worker = tmp_path / "worker.py"
    worker.write_text(f"""
import sys
sys.path.insert(0, {_REPO!r})
from ncnet_tpu.observability.events import EventLog

log = EventLog({path!r})
for i in range(5):
    log.emit("tick", i=i)
""")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["NCNET_TPU_FAULTS"] = json.dumps({"kill_at_event_append": 3})
    proc = subprocess.run(
        [sys.executable, str(worker)], env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=120,
    )
    assert proc.returncode == -9, f"expected SIGKILL, got:\n{proc.stdout}"
    with open(path, "rb") as f:
        raw = f.read()
    assert not raw.endswith(b"\n")  # the torn prefix really is on disk
    header, events = replay_events(path)
    assert [e["i"] for e in events] == [0, 1]
    with EventLog(path) as log2:
        log2.emit("resumed")
    _, events = replay_events(path)
    assert [e["event"] for e in events] == ["tick", "tick", "resumed"]


# ---------------------------------------------------------------------------
# global sink + leveled logger
# ---------------------------------------------------------------------------


def test_emit_is_noop_without_sink():
    obs_events.emit("anything", x=1)  # must not raise


def test_logger_console_rendering_and_structured_tee(tmp_path, capsys):
    path = str(tmp_path / "events.jsonl")
    log = get_logger("test_channel")
    with EventLog(path) as sink, obs_events.bound(sink):
        log.info("plain line")
        log.warning("recoverable thing", kind="decode")
        log.error("bad thing")
    out = capsys.readouterr().out
    assert "plain line\n" in out
    assert "warning: recoverable thing\n" in out  # prefixed exactly once
    assert "error: bad thing\n" in out
    _, events = replay_events(path)
    assert [e["event"] for e in events] == ["log"] * 3
    assert events[0]["level"] == "info" and events[0]["msg"] == "plain line"
    assert events[1]["kind"] == "decode"
    assert events[1]["logger"] == "test_channel"
    assert "kind" not in events[0]


def test_logger_level_filter(tmp_path, capsys, monkeypatch):
    monkeypatch.setenv("NCNET_TPU_LOG_LEVEL", "error")
    path = str(tmp_path / "events.jsonl")
    log = get_logger("test_filter")
    with EventLog(path) as sink, obs_events.bound(sink):
        log.info("suppressed")
        log.warning("also suppressed")
        log.error("kept")
    out = capsys.readouterr().out
    assert "suppressed" not in out and "error: kept" in out
    _, events = replay_events(path)
    assert [e["msg"] for e in events] == ["kept"]


def test_failing_sink_disables_telemetry_not_the_run(tmp_path, capsys):
    path = str(tmp_path / "events.jsonl")
    sink = EventLog(path)
    with obs_events.bound(sink):
        # closed file: the append raises; emit must absorb it and unbind
        # (telemetry never kills the run it observes)
        sink.close()
        obs_events.emit("tick")
        assert obs_events.get_global_sink() is None
        obs_events.emit("tick")  # and stay a no-op afterwards


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def test_metrics_registry_counters_gauges_timers(tmp_path):
    reg = MetricsRegistry(scope="t")
    assert reg.counter("n").inc() == 1
    assert reg.counter("n").inc(2) == 3
    reg.gauge("loss").set(0.25)
    reg.timer("wall").observe(0.1)
    reg.timer("wall").observe(0.3)
    with reg.timer("wall"):
        pass
    snap = reg.snapshot()
    assert snap["n"] == 3 and snap["loss"] == 0.25
    assert snap["wall"]["count"] == 3
    assert snap["wall"]["min_s"] <= snap["wall"]["max_s"] == 0.3
    assert abs(snap["wall"]["total_s"]
               - (0.4 + snap["wall"]["last_s"])) < 1e-9
    with pytest.raises(TypeError):
        reg.gauge("n")  # already a counter
    path = str(tmp_path / "events.jsonl")
    with EventLog(path) as sink:
        out = reg.flush(sink=sink, epoch=2)
    assert out == snap
    _, events = replay_events(path)
    assert events[0]["event"] == "metrics"
    assert events[0]["scope"] == "t" and events[0]["epoch"] == 2
    assert events[0]["metrics"]["n"] == 3


def test_flops_bases_match_readme_constants():
    # ~281.2 GFLOP symmetric filter at the PF-Pascal bench arch; the train
    # step is exactly 6x that (pos+neg forwards + ~2x-forward backwards)
    f = filter_flops(25, (5, 5, 5), (16, 16, 1))
    assert abs(f / 1e9 - 281.2) < 1.0
    assert train_step_flops(25, (5, 5, 5), (16, 16, 1)) == 6.0 * f


# ---------------------------------------------------------------------------
# heartbeat + device snapshots
# ---------------------------------------------------------------------------


def test_heartbeat_mtime_progression_and_payload(tmp_path):
    path = str(tmp_path / "hb" / "heartbeat.json")
    hb = Heartbeat(path, run_id="r1")
    assert Heartbeat.age_s(path) is None  # no beat yet
    hb.beat(step=1)
    m1 = os.stat(path).st_mtime_ns
    age1 = Heartbeat.age_s(path)
    assert age1 is not None and age1 < 60
    time.sleep(0.02)
    hb.beat(step=2, extra="x")
    m2 = os.stat(path).st_mtime_ns
    assert m2 > m1  # the watchdog's one signal: mtime strictly advances
    doc = Heartbeat.read(path)
    assert doc["step"] == 2 and doc["run"] == "r1" and doc["extra"] == "x"
    assert doc["pid"] == os.getpid()
    assert not os.path.exists(path + ".tmp")  # atomic: no droppings


def test_device_monitor_rate_limit(tmp_path):
    from ncnet_tpu.observability.device import DeviceMonitor

    path = str(tmp_path / "events.jsonl")
    with EventLog(path) as sink, obs_events.bound(sink):
        mon = DeviceMonitor(every_s=3600.0)
        assert mon.maybe_emit(step=1) is True   # first call always emits
        assert mon.maybe_emit(step=2) is False  # rate-limited
    _, events = replay_events(path)
    snaps = [e for e in events if e["event"] == "device_snapshot"]
    assert len(snaps) == 1 and snaps[0]["step"] == 1
    assert isinstance(snaps[0]["devices"], list)  # CPU: ids/kinds at least


# ---------------------------------------------------------------------------
# deep-layer events: tier demotion, retry/quarantine isolation
# ---------------------------------------------------------------------------


def test_tier_demotion_emits_event(tmp_path):
    from ncnet_tpu.ops import demote_fused_tier
    from ncnet_tpu.ops.nc_fused_lane import reset_fused_tier_demotions

    path = str(tmp_path / "events.jsonl")
    try:
        with EventLog(path) as sink, obs_events.bound(sink):
            assert demote_fused_tier("resident_vjp") == "resident_vjp"
            assert demote_fused_tier("resident_vjp") is None  # idempotent
        _, events = replay_events(path)
        demos = [e for e in events if e["event"] == "tier_demoted"]
        assert len(demos) == 1
        assert demos[0]["tier"] == "resident_vjp"
        assert demos[0]["demoted"] == ["resident_vjp"]
    finally:
        reset_fused_tier_demotions()


def test_run_isolated_emits_retry_and_quarantine_events(tmp_path):
    from ncnet_tpu.evaluation.resilience import FaultPolicy, run_isolated

    path = str(tmp_path / "events.jsonl")
    calls = {"n": 0}

    def work():
        calls["n"] += 1
        raise OSError("disk on fire")

    with EventLog(path) as sink, obs_events.bound(sink):
        ok, result = run_isolated(
            "unit_1", work,
            policy=FaultPolicy(retries=1, backoff_s=0.0, quarantine=True),
        )
    assert not ok and result is None and calls["n"] == 2
    _, events = replay_events(path)
    retries = [e for e in events if e["event"] == "retry"]
    quars = [e for e in events if e["event"] == "quarantine"]
    assert len(retries) == 1 and retries[0]["kind"] == "io"
    assert retries[0]["on_budget"] is True
    assert len(quars) == 1 and quars[0]["unit"] == "unit_1"
    assert quars[0]["attempts"] == 2


# ---------------------------------------------------------------------------
# profiling window knob
# ---------------------------------------------------------------------------


def test_profile_step_window_parsing(monkeypatch):
    from ncnet_tpu.utils.profiling import profile_step_window

    monkeypatch.delenv("NCNET_TPU_PROFILE_STEPS", raising=False)
    assert profile_step_window() is None
    monkeypatch.setenv("NCNET_TPU_PROFILE_STEPS", "3:7")
    assert profile_step_window() == (3, 7)
    for bad in ("junk", "7:3", "0:4", "1:1", "1:2:3"):
        monkeypatch.setenv("NCNET_TPU_PROFILE_STEPS", bad)
        with pytest.raises(ValueError):
            profile_step_window()


def test_step_window_tracer_start_stop(monkeypatch, tmp_path):
    import jax

    from ncnet_tpu.utils.profiling import StepWindowTracer

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop",)))

    # no log dir → inert even with a window
    t = StepWindowTracer(log_dir=None, window=(2, 4))
    assert not t.enabled
    t.at_step(2)
    assert calls == []

    d = str(tmp_path / "prof")
    t = StepWindowTracer(log_dir=d, window=(2, 4))
    assert t.enabled
    t.at_step(1)
    assert calls == []          # before the window
    t.at_step(2)
    assert calls == [("start", d)]
    t.at_step(3)
    assert calls == [("start", d)]  # still inside [2, 4)
    t.at_step(4)
    assert calls[-1] == ("stop",)   # window edge stops the capture
    assert not t.enabled            # one window per run
    t.close()
    assert calls.count(("stop",)) == 1

    # early exit: close() stops a capture left open mid-window
    calls.clear()
    t2 = StepWindowTracer(log_dir=d, window=(1, 10))
    t2.at_step(1)
    t2.close()
    assert calls == [("start", d), ("stop",)]


# ---------------------------------------------------------------------------
# training integration: instrumented fit, counters, heartbeat
# ---------------------------------------------------------------------------


def _dataset(tmp_path, n_pairs=4, seed=1):
    root = str(tmp_path / "data")
    write_pair_dataset(root, n_pairs=n_pairs, image_hw=(48, 48),
                       shift=(16, 16), seed=seed)
    return root


def _cfg(root, out_dir, **kw):
    base = dict(
        model=TINY, image_size=48,
        dataset_image_path=root, dataset_csv_path=root + "/image_pairs",
        num_epochs=1, batch_size=2, lr=1e-3,
        result_model_dir=str(out_dir), log_interval=10, data_parallel=False,
    )
    base.update(kw)
    return TrainConfig(**base)


def _read_events(ckpt_root):
    return replay_events(os.path.join(ckpt_root, "telemetry",
                                      "events.jsonl"))


def test_fit_writes_consistent_event_log_and_heartbeat(tmp_path):
    root = _dataset(tmp_path, n_pairs=4)  # 2 train batches at bs=2
    r = training.fit(_cfg(root, tmp_path / "out"), progress=False)
    ckpt_root = r["checkpoint"]
    header, events = _read_events(ckpt_root)
    kinds = [e["event"] for e in events]
    assert kinds.count("run_start") == 1
    steps = [e for e in events if e["event"] == "step"]
    assert [e["step"] for e in steps] == [1, 2]
    assert all(e["mode"] == "train" and e["wall_s"] > 0 for e in steps)
    assert all(isinstance(e.get("grad_norm"), float) for e in steps)
    assert kinds.count("epoch_start") == 1 and kinds.count("epoch_end") == 1
    assert kinds.count("checkpoint_commit") == 1  # epoch-end save
    assert kinds.count("run_end") == 1
    assert kinds.index("run_end") == len(kinds) - 1
    # per-epoch metrics flush carries the step timer + checkpoint counter
    metrics = [e for e in events if e["event"] == "metrics"]
    assert metrics and metrics[0]["metrics"]["step_wall"]["count"] == 2
    assert metrics[0]["metrics"]["checkpoint_commits"] == 1
    # heartbeat: last beat is the last step, atomically committed
    hb = Heartbeat.read(os.path.join(ckpt_root, "telemetry",
                                     "heartbeat.json"))
    assert hb["step"] == 2
    # the global sink is restored after fit
    assert obs_events.get_global_sink() is None


def test_fit_no_telemetry_writes_nothing(tmp_path):
    root = _dataset(tmp_path, n_pairs=4)
    r = training.fit(_cfg(root, tmp_path / "out", telemetry=False),
                     progress=False)
    assert not os.path.exists(os.path.join(r["checkpoint"], "telemetry"))


def test_fit_nan_injection_counts_skips_in_telemetry(tmp_path):
    root = _dataset(tmp_path, n_pairs=4)
    cfg = _cfg(root, tmp_path / "out", max_bad_steps=3)
    with faults.injected(FaultPlan(nan_loss_steps=(1,))):
        r = training.fit(cfg, progress=False)
    assert r["nan_steps_skipped"] == 1
    _, events = _read_events(r["checkpoint"])
    skips = [e for e in events if e["event"] == "nan_skip"]
    assert len(skips) == 1 and skips[0]["step"] == 1
    metrics = [e for e in events if e["event"] == "metrics"]
    assert metrics[-1]["metrics"]["nan_skips"] == 1
    report = run_report.build_report(
        [os.path.join(r["checkpoint"], "telemetry", "events.jsonl")])
    assert report["counts"]["nan_skips"] == 1


def test_fit_divergence_emits_postmortem_trail(tmp_path):
    root = _dataset(tmp_path, n_pairs=4)
    cfg = _cfg(root, tmp_path / "out", max_bad_steps=2)
    with faults.injected(FaultPlan(nan_loss_steps=(1, 2))):
        with pytest.raises(training.TrainDivergedError):
            training.fit(cfg, progress=False)
    ckpt_root = os.path.join(
        tmp_path / "out", os.listdir(tmp_path / "out")[0])
    _, events = _read_events(ckpt_root)
    kinds = [e["event"] for e in events]
    assert "diverged" in kinds
    assert kinds.count("run_end") == 1  # the scope closes on the error path
    report = run_report.build_report(
        [os.path.join(ckpt_root, "telemetry", "events.jsonl")])
    pm = report["divergence_postmortem"]
    assert pm["died_at_step"] == 2 and pm["streak"] == 2
    assert [e["step"] for e in pm["last_steps"]] == [1, 2]


def test_sigkill_mid_epoch_resume_yields_replayable_consistent_log(tmp_path):
    """THE acceptance scenario: a training run SIGKILLed mid-epoch (during
    the save of version 3) and resumed must leave one event log holding
    both runs' lineage, which run_report replays without error and whose
    counters are consistent: the re-executed step appears once per run that
    executed it, checkpoint commits match the versions on disk, and the
    resume is recorded with its position."""
    root = _dataset(tmp_path, n_pairs=8)  # 4 train batches at bs=2

    worker = tmp_path / "worker.py"
    worker.write_text(f"""
import sys
sys.path.insert(0, {_REPO!r})
import jax
jax.config.update("jax_platforms", "cpu")
from ncnet_tpu.config import ModelConfig, TrainConfig
from ncnet_tpu import training

cfg = TrainConfig(
    model=ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                      ncons_channels=(1,)),
    image_size=48,
    dataset_image_path={root!r},
    dataset_csv_path={root + "/image_pairs"!r},
    num_epochs=1, batch_size=2, lr=1e-3,
    result_model_dir={str(tmp_path / "killed")!r},
    log_interval=10, data_parallel=False,
    checkpoint_steps=1, keep_checkpoints=10,
)
training.fit(cfg, progress=False)
""")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["NCNET_TPU_FAULTS"] = json.dumps({"kill_at_version": 3})
    proc = subprocess.run(
        [sys.executable, str(worker)], env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=600,
    )
    assert proc.returncode == -9, f"expected SIGKILL, got:\n{proc.stdout[-3000:]}"

    (ckpt_root,) = [
        os.path.join(tmp_path / "killed", d)
        for d in os.listdir(tmp_path / "killed")
    ]
    events_path = os.path.join(ckpt_root, "telemetry", "events.jsonl")
    # the killed run's log replays on its own (torn tail tolerated)
    _, killed_events = replay_events(events_path)
    killed_steps = [e["step"] for e in killed_events
                    if e["event"] == "step"]
    assert killed_steps == [1, 2, 3]  # step 3 ran; its save was killed

    # resume in-process into the same root → the log must APPEND
    cfg_resume = _cfg(root, tmp_path / "killed",
                      model=TINY.replace(checkpoint=ckpt_root),
                      checkpoint_steps=1, keep_checkpoints=10)
    r = training.fit(cfg_resume, progress=False)
    assert r["checkpoint"] == ckpt_root

    report = run_report.build_report([events_path])  # replays without error
    c = report["counts"]
    assert len(report["lineage"]) == 2      # killed run + resumed run
    assert c["resumes"] == 1
    assert c["run_ends"] == 1               # only the resumed run ended
    # step events: killed run emitted 1,2,3; the resume re-executes 3
    # (version 3 never committed) and finishes 4
    _, events = replay_events(events_path)
    step_counts = {}
    for e in events:
        if e["event"] == "step":
            step_counts[e["step"]] = step_counts.get(e["step"], 0) + 1
    assert step_counts == {1: 1, 2: 1, 3: 2, 4: 1}
    # checkpoint commits in the log cover exactly the versions on disk
    committed = {e["step"] for e in events
                 if e["event"] == "checkpoint_commit"}
    on_disk = {n for n, _ in ckpt_io.list_checkpoint_versions(ckpt_root)}
    assert committed == on_disk == {1, 2, 3, 4}
    # the resume event records where the run picked up
    (resume_ev,) = [e for e in events if e["event"] == "resume"]
    assert resume_ev["step"] == 2 and resume_ev["batch"] == 2
    # render paths both work on the real artifact
    assert "run lineage" in run_report.render_text(report)
    # heartbeat reflects the final step
    hb = Heartbeat.read(os.path.join(ckpt_root, "telemetry",
                                     "heartbeat.json"))
    assert hb["step"] == 4


# ---------------------------------------------------------------------------
# run_report on a synthetic log
# ---------------------------------------------------------------------------


def test_run_report_synthetic_log(tmp_path, capsys):
    path = str(tmp_path / "events.jsonl")
    with EventLog(path) as log:
        log.emit("run_start", envelope={"device_kind": "TPU v5 lite"})
        for i, wall in enumerate([0.1, 0.2, 0.3, 0.4, 0.5], start=1):
            log.emit("step", mode="train", step=i, loss=1.0 / i,
                     wall_s=wall, stage_wall_s=0.01, pairs_per_s=16 / wall,
                     mfu_pct=10.0 * i, grad_norm=1.0)
        log.emit("tier_selected", stage="forward", tier="resident",
                 shape=[25, 25, 25, 25])
        log.emit("tier_demoted", tier="resident", demoted=["resident"])
        log.emit("retry", unit="q1", kind="device", attempt=1,
                 on_budget=True)
        log.emit("quarantine", unit="q1", kind="device", attempts=3)
        log.emit("watchdog_timeout", label="fetch q1", timeout_s=5.0)
        log.emit("checkpoint_commit", step=5, epoch=1, best=True)
        log.emit("run_end", step=5, preempted=False, nan_steps_skipped=0)
    report = run_report.build_report([path])
    assert report["counts"]["steps"] == 5
    assert report["counts"]["quarantines"] == 1
    assert report["counts"]["tier_demotions"] == 1
    assert report["counts"]["watchdog_timeouts"] == 1
    assert abs(report["step_wall_s"]["p50"] - 0.3) < 1e-9
    assert report["step_wall_s"]["n"] == 5
    assert report["retries_by_kind"] == {"device": 1}
    assert report["mfu_trajectory"][-1] == {"step": 5, "mfu_pct": 50.0}
    assert [t["event"] for t in report["tier_timeline"]] \
        == ["tier_selected", "tier_demoted"]
    assert report["divergence_postmortem"] is None

    text = run_report.render_text(report)
    assert "DEMOTED resident" in text
    assert "quarantined units" in text
    assert "device=1" in text

    # the CLI surface: text and --json both exit 0, and the JSON doc parses
    assert run_report.main([path]) == 0
    capsys.readouterr()
    assert run_report.main([path, "--json"]) == 0
    json.loads(capsys.readouterr().out)


# ---------------------------------------------------------------------------
# no-bare-print enforcement (the logger migration, locked in)
# ---------------------------------------------------------------------------


def test_library_modules_have_no_bare_print(tmp_path):
    hits = check_no_bare_print.find_bare_prints(
        os.path.join(_REPO, "ncnet_tpu"))
    assert hits == [], f"bare print() in library modules: {hits}"

    # round-10/11 additions pinned explicitly (the quality layer, the
    # serving subsystem, and their tools write structured events /
    # sys.stdout — a bare print() would reopen the side channel): the
    # whole-package walk covers the ncnet_tpu/ paths, but the TOOLS are
    # outside it and only this pin keeps them honest
    # (the ncnet_tpu/serving directory walk recursively covers every
    # serving module, incl. the PR 10 replica.py — no per-file entries)
    # (the ISSUE 11 live-plane modules are pinned explicitly even where
    # the directory walks already cover them: serving/introspect.py and
    # observability/export.py RENDER the scrape payloads and serve_top is
    # a stdout-document tool — a bare print in any of them would corrupt
    # an exposition document or the tool's parseable output)
    # (the ISSUE 12 multi-host modules are pinned explicitly for the same
    # reason: wire.py FRAMES the data-plane payloads and router.py runs
    # inside the routing hot path — a bare print in either corrupts a wire
    # exchange or reopens the side channel.  tools/serve_backend.py is NOT
    # pinned: like the other tools' CLIs its stdout IS its interface — the
    # one startup JSON line spawners block on)
    # (the ISSUE 13 memory plane is pinned for the same reason: memory.py
    # emits ledger/postmortem events from inside dispatch hot paths — a
    # bare print there would reopen the side channel mid-serving)
    # (the ISSUE 14 feature store is pinned for the same reason: the store
    # runs inside the eval/serving dispatch hot paths and its tool's
    # stdout is ONE parseable summary JSON line — a bare print in either
    # corrupts the tool's output or reopens the side channel mid-query)
    # (the ISSUE 17 arithmetic conv4d tiers are pinned for the same
    # reason: the cp/fft ops and the ALS solver run inside the filter's
    # dispatch hot path, and both tools emit parseable probe/conversion
    # reports on stdout)
    # (the PR 18 rollout plane is pinned for the same reason: the
    # controller runs against a LIVE service — a bare print there reopens
    # the side channel mid-serving — and tools/rollout.py's stdout is its
    # machine-scriptable phase timeline)
    # (the ISSUE 20 pod-tracing plane is pinned for the same reason:
    # tracing.py stamps contexts inside every wire hot path, the
    # retrieval wire/coordinator/shard modules carry the trace through
    # scatter-gather dispatch, and tools/trace_export.py writes ONE
    # parseable Perfetto document — a bare print in any of them corrupts
    # an artifact or reopens the side channel mid-request.
    # tools/stall_watchdog.py and tools/run_report.py stay UNPINNED like
    # serve_backend: their stdout verdict/report text IS the interface)
    for target in ("ncnet_tpu/observability/tracing.py",
                   "ncnet_tpu/observability/events.py",
                   "ncnet_tpu/retrieval/wire.py",
                   "ncnet_tpu/retrieval/coordinator.py",
                   "ncnet_tpu/retrieval/shard.py",
                   "tools/trace_export.py",
                   "ncnet_tpu/observability/quality.py",
                   "ncnet_tpu/serving/rollout.py",
                   "tools/rollout.py",
                   "ncnet_tpu/ops/conv4d_cp.py",
                   "ncnet_tpu/ops/conv4d_fft.py",
                   "ncnet_tpu/ops/cp_als.py",
                   "tools/cp_decompose.py",
                   "tools/cp_fft_probe.py",
                   "ncnet_tpu/observability/export.py",
                   "ncnet_tpu/observability/memory.py",
                   "ncnet_tpu/serving",
                   "ncnet_tpu/serving/introspect.py",
                   "ncnet_tpu/serving/router.py",
                   "ncnet_tpu/serving/wire.py",
                   "ncnet_tpu/store",
                   "tools/build_feature_store.py",
                   "tools/quality_drift.py",
                   "tools/serve_probe.py",
                   "tools/serve_top.py"):
        hits = check_no_bare_print.find_bare_prints(
            os.path.join(_REPO, target))
        assert hits == [], f"bare print() in {target}: {hits}"

    # the checker itself must actually detect violations (no vacuous pass):
    bad = tmp_path / "pkg"
    (bad / "sub").mkdir(parents=True)
    (bad / "mod.py").write_text(
        '"""print() in a docstring does not count."""\n'
        "# print() in a comment does not count\n"
        "def f():\n"
        "    print('caught')\n"
    )
    (bad / "cli").mkdir()
    (bad / "cli" / "main.py").write_text("print('exempt')\n")
    (bad / "sub" / "ok.py").write_text("x = 1\n")
    hits = check_no_bare_print.find_bare_prints(str(bad))
    assert [(os.path.basename(p), ln) for p, ln in hits] == [("mod.py", 4)]
    assert check_no_bare_print.main([str(bad)]) == 1
    assert check_no_bare_print.main([str(bad / "sub")]) == 0
