"""Temporal candidate selection + the tracked match pipeline (ISSUE 19).

The ops/model/engine layers of the streaming tentpole, CPU-verifiable:

  (a) ``temporal_candidates`` rows obey the EXACT static-shape
      coverage-padding contract ``topk_candidates`` established (in-grid,
      clamped duplicates at edges, prior cell always contained);
  (b) ``prior_from_table`` inverts a served match table into a
      coverage-total prior pair (identity round trip, max-score wins);
  (c) at FULL COVERAGE (radius spans the coarse grid) the tracked filter's
      output is BITWISE the coarse-to-fine tier's — the acceptance-bar
      equality that makes the steady-state fast path trustworthy;
  (d) the engine's tracked dispatch pays ZERO coarse passes
      (``coarse_passes`` spy flat), resolves reference features once per
      stream (digest memo), and the same-structure weight-swap fast path
      keeps its executables.

Service-level streaming (sessions, cut fallback, chaos) lives in
tests/test_stream_serving.py.
"""

import warnings

import numpy as np
import pytest
import jax

from ncnet_tpu import models, ops
from ncnet_tpu.config import ModelConfig
from ncnet_tpu.models.ncnet import (
    coarse2fine_filter,
    coarse2fine_tracked_filter,
    extract_features,
)
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.ops.image import normalize_imagenet
from ncnet_tpu.ops.sparse_corr import choose_tracked_pipeline
from ncnet_tpu.ops.temporal import (
    identity_prior,
    prior_from_table,
    temporal_candidates,
    tracking_recall_proxy,
    window_size,
)
from ncnet_tpu.serving import BatchMatchEngine
from ncnet_tpu.utils import faults

# tracked-capable tiny config: 96 px → 6x6 fine grid, factor 2 → 3x3 coarse
TRACK = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                    ncons_channels=(1,), sparse_topk=4, sparse_factor=2)


@pytest.fixture(autouse=True)
def _clean_state():
    """No armed faults, no demoted tiers, no leaked event sink."""
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)
    yield
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)


@pytest.fixture(scope="module")
def track_params():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return models.init_ncnet(TRACK, jax.random.key(0))


def u8(side=96, seed=0):
    return np.random.default_rng(seed).integers(
        0, 255, (side, side, 3), dtype=np.uint8)


def feats(params, img):
    x = normalize_imagenet(np.asarray(img[None], np.float32))
    return extract_features(TRACK, params, x)


# ---------------------------------------------------------------------------
# ops/temporal.py units
# ---------------------------------------------------------------------------


def test_window_size_is_static_and_validates():
    assert window_size(0) == 1
    assert window_size(1) == 9
    assert window_size(2) == 25
    with pytest.raises(ValueError):
        window_size(-1)


def test_temporal_candidates_coverage_contract():
    """Static (B, N, (2r+1)²) shape, every index in-grid, every row
    containing its prior cell, and edge windows clamped into duplicates —
    the exact ``topk_candidates`` padding rule."""
    hc = wc = 4
    prior = identity_prior(hc * wc, wc, hc, wc)[None]  # (1, 16)
    out = np.asarray(temporal_candidates(prior, hc, wc, radius=1))
    assert out.shape == (1, 16, 9)
    assert out.dtype == np.int32
    assert out.min() >= 0 and out.max() < hc * wc
    for n in range(16):
        assert prior[0, n] in out[0, n]
    # interior cell: the full 3x3 block, no duplicates
    assert len(set(out[0, 5].tolist())) == 9
    # corner cell 0: the window shifts inward → only the 2x2 block survives
    assert set(out[0, 0].tolist()) == {0, 1, 4, 5}
    # a radius spanning the grid = full coverage from ANY prior
    full = np.asarray(temporal_candidates(prior, hc, wc, radius=3))
    for n in range(16):
        assert set(full[0, n].tolist()) == set(range(16))


def test_temporal_candidates_clips_stale_prior():
    """An out-of-grid prior (stale session, padded row) can never index
    out of bounds — it clips, it does not crash or wrap."""
    prior = np.array([[999, -7]], np.int32)
    out = np.asarray(temporal_candidates(prior, 3, 3, radius=1))
    assert out.min() >= 0 and out.max() < 9


def test_prior_from_table_identity_roundtrip():
    """A table whose every fine target cell matches its own source cell
    inverts to the zero-motion prior on both families."""
    h = w = 6
    factor = 2
    n = h * w
    jj, ii = np.meshgrid(np.arange(w), np.arange(h))
    x = -1.0 + 2.0 * jj.reshape(-1) / (w - 1)
    y = -1.0 + 2.0 * ii.reshape(-1) / (h - 1)
    table = np.stack([x, y, x, y, np.ones(n)]).astype(np.float32)
    pab, pba = prior_from_table(table, (h, w), (h, w), factor)
    ident = identity_prior((h // factor) * (w // factor), w // factor,
                           h // factor, w // factor)
    assert np.array_equal(pab, ident)
    assert np.array_equal(pba, ident)
    assert pab.dtype == np.int32
    # recall proxy: the seeding prior contains every served match → 1.0
    assert tracking_recall_proxy(pab, table, (h, w), (h, w), factor,
                                 radius=0) == 1.0


def test_prior_from_table_max_score_wins_and_validates():
    """Two fine entries claiming one coarse source cell: the higher-score
    entry's target cell is the prior (the vectorized last-write argmax)."""
    h = w = 4
    factor = 2
    n = h * w
    jj, ii = np.meshgrid(np.arange(w), np.arange(h))
    x = -1.0 + 2.0 * jj.reshape(-1) / (w - 1)
    y = -1.0 + 2.0 * ii.reshape(-1) / (h - 1)
    # every entry names SOURCE cell (0,0); entry 0 (low score) points at
    # target fine cell 0 (coarse 0), entry n-1 (high score) at the last
    # fine cell (coarse 3)
    score = np.linspace(0.1, 1.0, n)
    table = np.stack([np.full(n, -1.0), np.full(n, -1.0),
                      x, y, score]).astype(np.float32)
    pab, _ = prior_from_table(table, (h, w), (h, w), factor)
    assert pab[0] == 3  # the max-score claimant's coarse target cell
    # unclaimed source cells fall back to the zero-motion identity
    ident = identity_prior(4, 2, 2, 2)
    assert np.array_equal(pab[1:], ident[1:])
    with pytest.raises(ValueError):
        prior_from_table(table[:4], (h, w), (h, w), factor)  # not (5|6, N)
    with pytest.raises(ValueError):
        prior_from_table(table, (h, w), (8, 8), factor)  # N mismatch


def test_tracking_recall_proxy_detects_displacement():
    """Matches one coarse cell outside the radius-0 window collapse the
    containment proxy to 0; within-radius matches keep it at 1."""
    h = w = 4
    factor = 2
    n = h * w
    jj, ii = np.meshgrid(np.arange(w), np.arange(h))
    x = -1.0 + 2.0 * jj.reshape(-1) / (w - 1)
    y = -1.0 + 2.0 * ii.reshape(-1) / (h - 1)
    # every match displaced by one full coarse cell horizontally: flip x
    table = np.stack([x, y, -x, y, np.ones(n)]).astype(np.float32)
    ident = identity_prior(4, 2, 2, 2)
    r0 = tracking_recall_proxy(ident, table, (h, w), (h, w), factor,
                               radius=0)
    r1 = tracking_recall_proxy(ident, table, (h, w), (h, w), factor,
                               radius=1)
    assert r0 < 1.0
    assert r1 == 1.0  # the dilated window still contains the flip


def test_choose_tracked_pipeline_geometry_and_demotion():
    kw = dict(factor=2, halo=2, radius=0)
    assert choose_tracked_pipeline(6, 6, 6, 6, **kw) == "tracked"
    # odd grid: fine dims must pool by the factor
    assert choose_tracked_pipeline(5, 6, 6, 6, **kw) is None
    assert choose_tracked_pipeline(6, 6, 6, 6, factor=2, halo=2,
                                   radius=-1) is None
    # a demotion of the shared sparse refine machinery disables tracking
    ops.demote_fused_tier("coarse2fine")
    assert choose_tracked_pipeline(6, 6, 6, 6, **kw) is None
    ops.reset_fused_tier_demotions()
    assert choose_tracked_pipeline(6, 6, 6, 6, **kw) == "tracked"


# ---------------------------------------------------------------------------
# model: full-coverage bitwise equality (acceptance bar c)
# ---------------------------------------------------------------------------


def test_full_coverage_tracked_equals_coarse2fine_bitwise(track_params):
    """On the 3x3 coarse grid, radius 2 dilates ANY prior to all 9 cells
    and sparse_topk=9 selects all 9 — identical candidate sets through the
    shared ``_sparse_dual_refine``, so the filtered volumes must be
    BITWISE equal.  This is what makes the steady-state coarse-pass skip
    an optimization rather than an approximation."""
    cfg = TRACK.replace(sparse_topk=9, track_radius=2)
    fa = feats(track_params, u8(96, 1))
    fb = feats(track_params, u8(96, 2))
    ident = identity_prior(9, 3, 3, 3)[None]
    ref = coarse2fine_filter(cfg, track_params, fa, fb)
    trk = coarse2fine_tracked_filter(cfg, track_params, fa, fb,
                                     ident, ident)
    assert np.array_equal(np.asarray(ref.corr), np.asarray(trk.corr))
    # and an ARBITRARY prior reaches the same full coverage (the prior
    # only positions the window; at full span position is irrelevant)
    perm = np.roll(ident, 4, axis=1)
    trk2 = coarse2fine_tracked_filter(cfg, track_params, fa, fb,
                                      perm, perm)
    assert np.array_equal(np.asarray(ref.corr), np.asarray(trk2.corr))


# ---------------------------------------------------------------------------
# engine: zero coarse passes, feature memo, swap fast path
# ---------------------------------------------------------------------------


def test_engine_tracked_dispatch_skips_coarse_pass(track_params):
    """The streaming acceptance spy: a tracked dispatch leaves
    ``coarse_passes`` FLAT, and the reference features are extracted once
    per stream — the digest memo serves every later frame."""
    eng = BatchMatchEngine(TRACK, track_params)
    assert eng.tracking_feasible((96, 96), (96, 96))
    # 48 px → 3x3 feature grid, not poolable by factor 2 → infeasible
    assert not eng.tracking_feasible((48, 48), (48, 48))

    src, tgt = u8(96, 1), u8(96, 2)
    table = eng.fetch(eng.dispatch(src[None], tgt[None]))
    assert eng.coarse_passes == 1
    pab, pba = prior_from_table(table[0], (6, 6), (6, 6), 2)

    cp, fe = eng.coarse_passes, eng.feature_extractions
    t1 = eng.fetch(eng.dispatch_tracked(src[None], u8(96, 3)[None],
                                        pab[None], pba[None]))
    assert eng.coarse_passes == cp          # ZERO coarse passes
    assert eng.tracked_dispatches == 1
    assert eng.feature_extractions == fe + 1  # reference features, once
    assert t1.shape == table.shape
    assert np.isfinite(t1).all()
    # frame 3: same reference object → the digest memo hits, no re-extract
    eng.fetch(eng.dispatch_tracked(src[None], u8(96, 4)[None],
                                   pab[None], pba[None]))
    assert eng.feature_extractions == fe + 1
    assert eng.coarse_passes == cp
    assert eng.tracked_dispatches == 2


def test_engine_tracked_fallback_is_bitwise_cold(track_params):
    """A cut fallback re-runs the frame through ``dispatch`` — the SAME
    executable a cold query uses, so its table is bitwise a cold query's."""
    eng = BatchMatchEngine(TRACK, track_params)
    src, tgt = u8(96, 5), u8(96, 6)
    cold = eng.fetch(eng.dispatch(src[None], tgt[None]))
    again = eng.fetch(eng.dispatch(src[None], tgt[None]))
    assert np.array_equal(cold, again)


def test_engine_swap_fastpath_keeps_tracked_executables(track_params):
    """A same-structure weight swap takes the fast path (no retrace): the
    tracked program keeps serving, and only a structurally different tree
    drops the compiled executables."""
    eng = BatchMatchEngine(TRACK, track_params)
    src, tgt = u8(96, 1), u8(96, 2)
    table = eng.fetch(eng.dispatch(src[None], tgt[None]))
    pab, pba = prior_from_table(table[0], (6, 6), (6, 6), 2)
    eng.fetch(eng.dispatch_tracked(src[None], tgt[None],
                                   pab[None], pba[None]))

    new_params = jax.tree.map(lambda x: x * 1.0, track_params)
    eng.swap_params(new_params)
    assert eng.swap_fastpath_hits == 1
    # the swapped engine still serves tracked frames, coarse passes flat
    cp = eng.coarse_passes
    eng.fetch(eng.dispatch_tracked(src[None], tgt[None],
                                   pab[None], pba[None]))
    assert eng.coarse_passes == cp

    # structurally different tree (extra leaf) → full retrace path
    bigger = dict(new_params)
    bigger["extra"] = np.zeros(3, np.float32)
    eng.swap_params(bigger)
    assert eng.swap_fastpath_hits == 1
