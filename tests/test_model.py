"""Model assembly tests: composition order, symmetric consensus, checkpoint
import round-trips.  The individual ops are oracle-tested in test_ops_*; here
the subject is the ImMatchNet-equivalent pipeline
(/root/reference/lib/model.py:193-282)."""

import argparse

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from ncnet_tpu.config import ModelConfig
from ncnet_tpu import models
from ncnet_tpu.models import backbone as bb

from test_backbone import make_resnet101_state_dict


def _np_conv4d(x, w, b):
    """Brute-force 'same' 4D conv, channels-last (tiny shapes only)."""
    B, ha, wa, hb, wb, ci = x.shape
    ka, kwa, kb, kwb, _, co = w.shape
    out = np.zeros((B, ha, wa, hb, wb, co), np.float32)
    pads = [k // 2 for k in (ka, kwa, kb, kwb)]
    xp = np.pad(x, [(0, 0)] + [(p, p) for p in pads] + [(0, 0)])
    for i in range(ha):
        for j in range(wa):
            for k in range(hb):
                for l in range(wb):
                    patch = xp[:, i:i + ka, j:j + kwa, k:k + kb, l:l + kwb, :]
                    out[:, i, j, k, l, :] = np.einsum("bpqrsc,pqrsco->bo", patch, w) + b
    return out


def _np_mutual(c):
    eps = 1e-5
    return c * (c / (c.max(axis=(3, 4), keepdims=True) + eps)) * (
        c / (c.max(axis=(1, 2), keepdims=True) + eps)
    )


def _np_filter_pipeline(corr, nc_params, symmetric=True):
    """numpy oracle of MutualMatching → NeighConsensus → MutualMatching."""

    def stack(x):
        for layer in nc_params:
            x = np.maximum(_np_conv4d(x, np.asarray(layer["w"]), np.asarray(layer["b"])), 0.0)
        return x

    x = _np_mutual(corr)[..., None]
    if symmetric:
        xt = np.transpose(x, (0, 3, 4, 1, 2, 5))
        x = stack(x) + np.transpose(stack(xt), (0, 3, 4, 1, 2, 5))
    else:
        x = stack(x)
    return _np_mutual(x[..., 0])


@pytest.fixture
def tiny_cfg():
    return ModelConfig(
        backbone="tiny", ncons_kernel_sizes=(3, 3), ncons_channels=(4, 1)
    )


def test_filter_pipeline_matches_numpy_oracle(tiny_cfg, rng):
    params = models.init_ncnet(tiny_cfg, jax.random.key(0))
    corr = rng.standard_normal((2, 3, 4, 3, 4)).astype(np.float32)
    out = models.ncnet_filter(tiny_cfg, params, jnp.asarray(corr))
    assert out.delta4d is None
    want = _np_filter_pipeline(corr, params["nc"], symmetric=True)
    np.testing.assert_allclose(np.asarray(out.corr), want, rtol=1e-4, atol=1e-5)


def test_filter_pipeline_small_cout_matches_numpy_oracle(rng):
    """A c_in>4 layer feeding a 1-channel layer (the reference's last-NC-layer
    shape class) against the independent numpy oracle, both for the square
    (batch-folded symmetric) and rectangular volume shapes."""
    cfg = ModelConfig(
        backbone="tiny", ncons_kernel_sizes=(3, 3), ncons_channels=(8, 1)
    )
    params = models.init_ncnet(cfg, jax.random.key(2))
    for shape in [(2, 3, 4, 3, 4), (1, 3, 3, 2, 4)]:
        corr = rng.standard_normal(shape).astype(np.float32)
        out = models.ncnet_filter(cfg, params, jnp.asarray(corr))
        want = _np_filter_pipeline(corr, params["nc"], symmetric=True)
        np.testing.assert_allclose(
            np.asarray(out.corr), want, rtol=1e-4, atol=1e-5
        )


def test_conv4d_explicit_toeplitz_matches_plain_path(rng):
    """toeplitz_b is no longer auto-selected but stays a public explicit
    formulation (and a structurally-independent oracle) — keep it
    numerically locked to the plain path on a two-layer chain."""
    from ncnet_tpu import ops

    x = jnp.asarray(rng.standard_normal((2, 3, 4, 3, 4, 5)).astype(np.float32))
    w1 = jnp.asarray(
        rng.standard_normal((3, 3, 3, 3, 5, 6)).astype(np.float32) * 0.2)
    w2 = jnp.asarray(
        rng.standard_normal((3, 3, 3, 3, 6, 1)).astype(np.float32) * 0.2)
    mid = ops.conv4d(x, w1, variant="coutfold")
    got = ops.conv4d(mid, w2, variant="toeplitz_b")
    plain = ops.conv4d(
        ops.conv4d(x, w1, variant="unroll"), w2, variant="unroll"
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(plain), rtol=2e-4, atol=2e-4
    )


def test_filter_pipeline_asymmetric(tiny_cfg, rng):
    cfg = tiny_cfg.replace(symmetric_mode=False)
    params = models.init_ncnet(cfg, jax.random.key(1))
    corr = rng.standard_normal((1, 3, 3, 3, 3)).astype(np.float32)
    out = models.ncnet_filter(cfg, params, jnp.asarray(corr))
    want = _np_filter_pipeline(corr, params["nc"], symmetric=False)
    np.testing.assert_allclose(np.asarray(out.corr), want, rtol=1e-4, atol=1e-5)


def test_symmetric_output_transposes_consistently(tiny_cfg, rng):
    """Stack-level symmetry ⇒ filter(corrᵀ) == filter(corr)ᵀ
    (property implied by reference model.py:144-150)."""
    params = models.init_ncnet(tiny_cfg, jax.random.key(2))
    corr = jnp.asarray(rng.standard_normal((1, 3, 3, 3, 3)).astype(np.float32))
    out = models.neigh_consensus(params["nc"], corr)
    out_t = models.neigh_consensus(params["nc"], jnp.transpose(corr, (0, 3, 4, 1, 2)))
    np.testing.assert_allclose(
        np.asarray(out_t), np.asarray(jnp.transpose(out, (0, 3, 4, 1, 2))),
        rtol=1e-4, atol=1e-6,
    )


def test_forward_shapes_and_relocalization(tiny_cfg, rng):
    src = jnp.asarray(rng.uniform(0, 1, (2, 64, 64, 3)).astype(np.float32))
    tgt = jnp.asarray(rng.uniform(0, 1, (2, 64, 64, 3)).astype(np.float32))
    params = models.init_ncnet(tiny_cfg, jax.random.key(3))
    out = models.ncnet_forward(tiny_cfg, params, src, tgt)
    assert out.corr.shape == (2, 4, 4, 4, 4) and out.delta4d is None

    cfg_r = tiny_cfg.replace(relocalization_k_size=2)
    out_r = models.ncnet_forward(cfg_r, params, src, tgt)
    assert out_r.corr.shape == (2, 2, 2, 2, 2)
    assert len(out_r.delta4d) == 4 and out_r.delta4d[0].shape == (2, 2, 2, 2, 2)


def test_half_precision_runs_bf16(tiny_cfg, rng):
    cfg = tiny_cfg.replace(half_precision=True)
    params = models.init_ncnet(cfg, jax.random.key(4))
    src = jnp.asarray(rng.uniform(0, 1, (1, 32, 32, 3)).astype(np.float32))
    out = models.ncnet_forward(cfg, params, src, src)
    assert out.corr.dtype == jnp.bfloat16


def test_ncnet_wrapper_jit(tiny_cfg, rng):
    net = models.NCNet(tiny_cfg, seed=0)
    src = jnp.asarray(rng.uniform(0, 1, (1, 32, 32, 3)).astype(np.float32))
    out = net(src, src)
    assert out.corr.shape == (1, 2, 2, 2, 2)


def test_point_matcher_matches_direct_forward(tiny_cfg, rng):
    """The warm demo/bs1 path (make_point_matcher: uint8 upload, device
    normalize, on-device match extraction) produces the same matches as the
    direct forward + corr_to_matches composition on the equivalently
    normalized float input."""
    from ncnet_tpu.ops import corr_to_matches
    from ncnet_tpu.ops.image import normalize_imagenet

    params = models.init_ncnet(tiny_cfg, jax.random.key(0))
    src_u8 = rng.integers(0, 255, (1, 64, 64, 3), dtype=np.uint8)
    tgt_u8 = rng.integers(0, 255, (1, 64, 64, 3), dtype=np.uint8)

    matcher = models.make_point_matcher(tiny_cfg, params, do_softmax=True)
    got = matcher(src_u8, tgt_u8)

    src = normalize_imagenet(jnp.asarray(src_u8).astype(jnp.float32))
    tgt = normalize_imagenet(jnp.asarray(tgt_u8).astype(jnp.float32))
    out = jax.jit(
        lambda p, s, t: models.ncnet_forward(tiny_cfg, p, s, t).corr
    )(params, src, tgt)
    want = corr_to_matches(out, do_softmax=True)
    for g, w in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w, np.float32), rtol=1e-5, atol=1e-5)


def test_point_matcher_applies_relocalization_deltas(rng):
    """A relocalization config (k>1) must return FINE-grid matches from the
    warm matcher — delta4d applied exactly as the direct composition does,
    not silently dropped."""
    from ncnet_tpu.ops import corr_to_matches
    from ncnet_tpu.ops.image import normalize_imagenet

    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                      ncons_channels=(1,), relocalization_k_size=2)
    params = models.init_ncnet(cfg, jax.random.key(1))
    src_u8 = rng.integers(0, 255, (1, 64, 64, 3), dtype=np.uint8)
    tgt_u8 = rng.integers(0, 255, (1, 64, 64, 3), dtype=np.uint8)

    matcher = models.make_point_matcher(cfg, params, do_softmax=True)
    got = matcher(src_u8, tgt_u8)

    src = normalize_imagenet(jnp.asarray(src_u8).astype(jnp.float32))
    tgt = normalize_imagenet(jnp.asarray(tgt_u8).astype(jnp.float32))
    out = jax.jit(
        lambda p, s, t: models.ncnet_forward(cfg, p, s, t)
    )(params, src, tgt)
    assert out.delta4d is not None
    want = corr_to_matches(out.corr, delta4d=out.delta4d, k_size=2,
                           do_softmax=True)
    for g, w in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w, np.float32), rtol=1e-5, atol=1e-5)


def test_import_torch_checkpoint(rng):
    """Synthetic reference-format .pth.tar dict → our pytree, including the
    Sequential-index remap and the pre-permuted Conv4d weight layout."""
    # the reference stores the trunk as nn.Sequential → numeric child indices
    # (0=conv1 1=bn1 4=layer1 5=layer2 6=layer3, lib/model.py:38-44)
    name_to_idx = {"conv1": "0", "bn1": "1", "layer1": "4", "layer2": "5", "layer3": "6"}
    base_sd = make_resnet101_state_dict()
    sd = {}
    for k, v in base_sd.items():
        name, _, tail = k.partition(".")
        sd[f"FeatureExtraction.model.{name_to_idx[name]}.{tail}"] = v
    # our layout (kA,kWA,kB,kWB,Cin,Cout) → stored torch layout (kA,Cout,Cin,kWA,kB,kWB)
    nc_ours = [
        (rng.standard_normal((5, 5, 5, 5, 1, 16)).astype(np.float32),
         rng.standard_normal(16).astype(np.float32)),
        (rng.standard_normal((5, 5, 5, 5, 16, 1)).astype(np.float32),
         rng.standard_normal(1).astype(np.float32)),
    ]
    for j, (w, b) in enumerate(nc_ours):
        sd[f"NeighConsensus.conv.{2 * j}.weight"] = np.transpose(w, (0, 5, 4, 1, 2, 3))
        sd[f"NeighConsensus.conv.{2 * j}.bias"] = b
    ckpt = {
        "state_dict": sd,
        "args": argparse.Namespace(
            ncons_kernel_sizes=[5, 5], ncons_channels=[16, 1],
            feature_extraction_cnn="resnet101",
        ),
    }
    config, params = models.import_torch_checkpoint(ckpt)
    assert config.ncons_kernel_sizes == (5, 5)
    assert config.ncons_channels == (16, 1)
    for j, (w, b) in enumerate(nc_ours):
        np.testing.assert_array_equal(np.asarray(params["nc"][j]["w"]), w)
        np.testing.assert_array_equal(np.asarray(params["nc"][j]["b"]), b)
    # trunk went through the same converter as direct import
    direct = bb.import_torch_backbone(base_sd, "resnet101")
    np.testing.assert_array_equal(
        np.asarray(params["backbone"]["layer3"][22]["conv3"]["w"]),
        np.asarray(direct["layer3"][22]["conv3"]["w"]),
    )


def test_orbax_roundtrip(tiny_cfg, tmp_path):
    params = models.init_ncnet(tiny_cfg, jax.random.key(5))
    models.save_params(str(tmp_path / "ckpt"), tiny_cfg, params)
    config, restored = models.load_params(str(tmp_path / "ckpt"))
    assert config == tiny_cfg
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, restored,
    )


def test_orbax_load_keeps_runtime_flags(tiny_cfg, tmp_path):
    """Arch comes from the checkpoint; runtime flags (relocalization,
    half_precision) stay with the caller — same policy as the torch path."""
    params = models.init_ncnet(tiny_cfg, jax.random.key(6))
    models.save_params(str(tmp_path / "ckpt"), tiny_cfg, params)
    base = tiny_cfg.replace(
        relocalization_k_size=2, half_precision=True,
        ncons_channels=(99, 99),  # arch lie: must be overridden by checkpoint
    )
    config, _ = models.load_params(str(tmp_path / "ckpt"), base)
    assert config.ncons_channels == tiny_cfg.ncons_channels
    assert config.relocalization_k_size == 2
    assert config.half_precision is True


def test_init_ncnet_rejects_mismatched_config():
    bad = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3, 3, 3), ncons_channels=(10, 1))
    with pytest.raises(ValueError, match="equal length"):
        models.init_ncnet(bad, jax.random.key(0))


def test_symmetric_tap_swap_equals_transpose_form(rng):
    """The rectangular symmetric fast path (tap-swapped kernels + fused
    1-channel first layer; models/ncnet.py neigh_consensus) must equal the
    transpose form ``stack(x) + stack(xT)^T`` it replaces — the algebraic
    identity NC(xT)^T == NC_tap-swapped(x) for cubic kernels."""
    from ncnet_tpu.models.ncnet import neigh_consensus, tap_swap_fusable
    from ncnet_tpu import ops

    nc_params = []
    for ci, co, k in ((1, 6, 5), (6, 1, 3)):
        nc_params.append({
            "w": jnp.asarray(rng.standard_normal((k, k, k, k, ci, co))
                             .astype(np.float32) * 0.2),
            "b": jnp.asarray(rng.standard_normal(co).astype(np.float32) * 0.1),
        })
    assert tap_swap_fusable(nc_params)
    # rectangular volume => the batch-fold branch cannot take it
    corr = jnp.asarray(rng.standard_normal((2, 5, 7, 6, 4)).astype(np.float32))

    got = neigh_consensus(nc_params, corr, symmetric=True)

    def stack(x):
        for layer in nc_params:
            x = jax.nn.relu(ops.conv4d(x, layer["w"], layer["b"]))
        return x

    x = corr[..., None]
    xt = jnp.transpose(x, (0, 3, 4, 1, 2, 5))
    want = (stack(x) + jnp.transpose(stack(xt), (0, 3, 4, 1, 2, 5)))[..., 0]
    # identical math, different tap-summation order: float32 reassociation
    # shows up at the ~1e-6 level (measured 3/1680 elements at 6.7e-6 abs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)
