"""Data pipeline tests: CSV schemas, preprocessing order, loader semantics."""

import os

import numpy as np
import pytest

from ncnet_tpu.data import (
    DataLoader,
    ImagePairDataset,
    PFPascalDataset,
    default_collate,
)
from ncnet_tpu.data.synthetic import write_pair_dataset, write_pf_pascal_like
from ncnet_tpu.ops.image import IMAGENET_MEAN, IMAGENET_STD


@pytest.fixture(scope="module")
def pair_root(tmp_path_factory):
    return write_pair_dataset(str(tmp_path_factory.mktemp("pairs")), n_pairs=5)


@pytest.fixture(scope="module")
def pf_csv(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("pf"))
    return write_pf_pascal_like(root, n_pairs=3), root


def test_image_pair_dataset_sample(pair_root):
    ds = ImagePairDataset(
        pair_root + "/image_pairs", "train_pairs.csv", pair_root,
        output_size=(64, 80),
    )
    assert len(ds) == 5
    s = ds[0]
    assert s["source_image"].shape == (64, 80, 3)
    assert s["target_image"].shape == (64, 80, 3)
    # im_size records the PRE-resize shape (im_pair_dataset.py:81)
    np.testing.assert_array_equal(s["source_im_size"], [96, 128, 3])
    # ImageNet normalization applied
    assert s["source_image"].dtype == np.float32
    assert -3 < s["source_image"].mean() < 3


def test_image_pair_dataset_flip_applies_to_both(pair_root, tmp_path):
    import pandas as pd

    csv = pair_root + "/image_pairs/train_pairs.csv"
    df = pd.read_csv(csv)
    df["flip"] = 1
    flipped_csv_dir = str(tmp_path)
    df.to_csv(flipped_csv_dir + "/train_pairs.csv", index=False)

    ds0 = ImagePairDataset(pair_root + "/image_pairs", "train_pairs.csv", pair_root,
                           output_size=(96, 128), normalize=False)
    ds1 = ImagePairDataset(flipped_csv_dir, "train_pairs.csv", pair_root,
                           output_size=(96, 128), normalize=False)
    a0, a1 = ds0[0]["source_image"], ds1[0]["source_image"]
    b0, b1 = ds0[0]["target_image"], ds1[0]["target_image"]
    np.testing.assert_allclose(a1, a0[:, ::-1], atol=1e-4)
    np.testing.assert_allclose(b1, b0[:, ::-1], atol=1e-4)


def test_pf_pascal_dataset_pf_procedure(pf_csv):
    csv, root = pf_csv
    ds = PFPascalDataset(csv, root, output_size=(64, 80), pck_procedure="pf")
    s = ds[0]
    pts = s["source_points"]
    assert pts.shape == (2, 20)
    n_valid = int((pts[0] != -1).sum())
    assert n_valid == 6
    assert (pts[:, n_valid:] == -1).all()
    valid = pts[:, :n_valid]
    expected_l = np.max(valid.max(axis=1) - valid.min(axis=1))
    np.testing.assert_allclose(s["L_pck"], [expected_l])
    # GT shift: B = A + (dx, dy) with default shift (16, 16)
    tgt = s["target_points"][:, :n_valid]
    np.testing.assert_allclose(valid + 16, tgt)


def test_pf_pascal_dataset_scnet_procedure(pf_csv):
    csv, root = pf_csv
    raw = PFPascalDataset(csv, root, pck_procedure="pf")[1]
    s = PFPascalDataset(csv, root, pck_procedure="scnet")[1]
    np.testing.assert_allclose(s["L_pck"], [224.0])
    np.testing.assert_array_equal(s["source_im_size"], [224, 224, 3])
    n = int((s["source_points"][0] != -1).sum())
    # scnet points = raw points rescaled by 224/original size (pf_dataset.py:64-75)
    np.testing.assert_allclose(
        s["source_points"][0, :n], raw["source_points"][0, :n] * 224.0 / 128.0,
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        s["source_points"][1, :n], raw["source_points"][1, :n] * 224.0 / 96.0,
        rtol=1e-5,
    )
    assert (s["source_points"][:, n:] == -1).all()


def test_loader_batching_and_collate(pair_root):
    ds = ImagePairDataset(pair_root + "/image_pairs", "train_pairs.csv", pair_root,
                          output_size=(32, 32))
    loader = DataLoader(ds, batch_size=2)
    batches = list(loader)
    assert len(loader) == len(batches) == 3
    assert batches[0]["source_image"].shape == (2, 32, 32, 3)
    assert batches[-1]["source_image"].shape == (1, 32, 32, 3)
    loader_dl = DataLoader(ds, batch_size=2, drop_last=True)
    assert len(list(loader_dl)) == len(loader_dl) == 2


def test_loader_shuffle_deterministic_and_epoch_keyed(pair_root):
    ds = ImagePairDataset(pair_root + "/image_pairs", "train_pairs.csv", pair_root,
                          output_size=(16, 16))
    l1 = DataLoader(ds, batch_size=5, shuffle=True, seed=7)
    l2 = DataLoader(ds, batch_size=5, shuffle=True, seed=7)
    b1, b2 = next(iter(l1)), next(iter(l2))
    np.testing.assert_array_equal(b1["source_image"], b2["source_image"])
    l2.set_epoch(1)
    b3 = next(iter(l2))
    assert not np.array_equal(b1["source_image"], b3["source_image"])


def test_loader_sharding_disjoint(pair_root):
    ds = ImagePairDataset(pair_root + "/image_pairs", "train_pairs.csv", pair_root,
                          output_size=(16, 16))
    idx0 = DataLoader(ds, batch_size=2, num_shards=2, shard_index=0, shuffle=True,
                      seed=3)._epoch_indices()
    idx1 = DataLoader(ds, batch_size=2, num_shards=2, shard_index=1, shuffle=True,
                      seed=3)._epoch_indices()
    assert len(idx0) == len(idx1) == 2
    assert set(idx0.tolist()).isdisjoint(idx1.tolist())


def test_loader_prefetch_matches_sync(pair_root):
    ds = ImagePairDataset(pair_root + "/image_pairs", "train_pairs.csv", pair_root,
                          output_size=(24, 24))
    sync = list(DataLoader(ds, batch_size=2, num_workers=0))
    pre = list(DataLoader(ds, batch_size=2, num_workers=2))
    assert len(sync) == len(pre)
    for a, b in zip(sync, pre):
        np.testing.assert_array_equal(a["source_image"], b["source_image"])


def test_loader_propagates_worker_errors():
    class Boom:
        def __len__(self):
            return 4

        def __getitem__(self, i):
            raise RuntimeError("decode failed")

    with pytest.raises(RuntimeError, match="decode failed"):
        list(DataLoader(Boom(), batch_size=2, num_workers=2))


def test_collate_mixed_types():
    batch = default_collate(
        [{"a": np.zeros((2, 2)), "s": "x", "n": 1}, {"a": np.ones((2, 2)), "s": "y", "n": 2}]
    )
    assert batch["a"].shape == (2, 2, 2)
    assert batch["s"] == ["x", "y"]
    np.testing.assert_array_equal(batch["n"], [1, 2])


def test_loader_early_break_no_deadlock(pair_root):
    """Abandoning a prefetching iterator must stop the producer thread."""
    import threading

    ds = ImagePairDataset(pair_root + "/image_pairs", "train_pairs.csv", pair_root,
                          output_size=(16, 16))
    before = threading.active_count()
    for _ in range(3):
        for batch in DataLoader(ds, batch_size=1, num_workers=2, prefetch_batches=1):
            break  # abandon mid-epoch
    assert threading.active_count() <= before + 1


def test_random_crop_deterministic_across_workers(pair_root):
    """Per-(seed, epoch, idx) RNG: crops must not depend on thread timing."""
    def batches(workers):
        ds = ImagePairDataset(pair_root + "/image_pairs", "train_pairs.csv",
                              pair_root, output_size=(32, 32), random_crop=True,
                              seed=5)
        return list(DataLoader(ds, batch_size=2, num_workers=workers))

    for a, b in zip(batches(0), batches(3)):
        np.testing.assert_array_equal(a["source_image"], b["source_image"])

    # and epoch changes the draws
    ds = ImagePairDataset(pair_root + "/image_pairs", "train_pairs.csv",
                          pair_root, output_size=(32, 32), random_crop=True, seed=5)
    l = DataLoader(ds, batch_size=2, shuffle=False, num_workers=0)
    e0 = next(iter(l))
    l.set_epoch(1)
    e1 = next(iter(l))
    assert not np.array_equal(e0["source_image"], e1["source_image"])


# ---------------------------------------------------------------------------
# Vendored manifests: the reference commits its curated pair lists and IVD
# url/dir manifests (reference datasets/); this repo vendors the same files so
# the data layer constructs offline.  Row counts per SURVEY §2.3.

REPO_DATASETS = os.path.join(os.path.dirname(__file__), "..", "datasets")


@pytest.mark.parametrize(
    "sub,csv,rows",
    [
        ("pf-pascal", "train_pairs.csv", 2940),
        ("pf-pascal", "val_pairs.csv", 308),
        ("ivd", "train_pairs.csv", 6932),
        ("ivd", "val_pairs.csv", 758),
    ],
)
def test_vendored_pair_csvs_construct(sub, csv, rows):
    ds = ImagePairDataset(
        os.path.join(REPO_DATASETS, sub, "image_pairs"), csv,
        os.path.join(REPO_DATASETS, sub),
    )
    assert len(ds) == rows
    assert set(np.unique(ds.flip)) <= {0, 1}
    assert all(name.endswith((".jpg", ".png")) for name in ds.img_a_names[:50])


def test_vendored_pf_test_csv_keypoints():
    from ncnet_tpu.data.datasets import _parse_points

    ds = PFPascalDataset(
        os.path.join(REPO_DATASETS, "pf-pascal", "image_pairs", "test_pairs.csv"),
        os.path.join(REPO_DATASETS, "pf-pascal"),
    )
    assert len(ds) == 299
    # every row's keypoint strings parse to matched, −1-padded (2,20) tables
    for i in range(0, 299, 37):
        pa = _parse_points(ds.point_a.iloc[i, 0], ds.point_a.iloc[i, 1])
        pb = _parse_points(ds.point_b.iloc[i, 0], ds.point_b.iloc[i, 1])
        assert pa.shape == pb.shape == (2, 20)
        na = int(np.sum(pa[0] != -1))
        assert 1 <= na <= 20
        assert na == int(np.sum(pb[0] != -1))  # A/B keypoints correspond


def test_vendored_ivd_manifests():
    base = os.path.join(REPO_DATASETS, "ivd")
    with open(os.path.join(base, "dirs.txt")) as f:
        dirs = [ln.split()[0] for ln in f if ln.strip()]
    assert len(dirs) == 89  # 89 venues (SURVEY §2.3)
    with open(os.path.join(base, "urls.txt")) as f:
        rows = [ln.split() for ln in f if ln.strip()]
    assert all(len(r) == 2 and r[1].startswith("http") for r in rows)
    # every image path sits under a listed venue directory
    venues = set(dirs)
    assert all(os.path.dirname(r[0]) in venues for r in rows)
