"""Training tests: weak-loss oracle, feature-roll equivalence, convergence on
synthetic data, full-state checkpoint resume, CLI smoke."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from ncnet_tpu.config import ModelConfig, TrainConfig
from ncnet_tpu.data.synthetic import write_pair_dataset
from ncnet_tpu import models, training
from ncnet_tpu.models.ncnet import ncnet_forward


TINY = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,), ncons_channels=(1,))


def _np_match_score(corr, normalization="softmax"):
    """Oracle for match_score per reference train.py:125-134."""
    b, ha, wa, hb, wb = corr.shape

    def norm(x, axis):
        if normalization == "softmax":
            e = np.exp(x - x.max(axis=axis, keepdims=True))
            return e / e.sum(axis=axis, keepdims=True)
        if normalization == "l1":
            return x / (x.sum(axis=axis, keepdims=True) + 1e-4)
        return x

    nc_b = norm(corr.reshape(b, ha * wa, hb, wb), 1)
    nc_a = norm(corr.reshape(b, ha, wa, hb * wb), 3)
    return (nc_a.max(axis=3) + nc_b.max(axis=1)).mean() / 2.0


@pytest.mark.parametrize("normalization", ["softmax", "l1", "none"])
def test_match_score_oracle(rng, normalization):
    corr = rng.standard_normal((2, 3, 3, 3, 3)).astype(np.float32)
    if normalization == "l1":
        corr = np.abs(corr)  # reference l1 path assumes non-negative volumes
    got = float(training.match_score(jnp.asarray(corr), normalization))
    want = _np_match_score(corr, normalization)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_weak_loss_feature_roll_equals_image_roll(rng):
    """Our negative (roll features) must equal the reference's negative
    (roll source images then re-extract): feature extraction is per-image."""
    params = models.init_ncnet(TINY, jax.random.key(0))
    src = jnp.asarray(rng.uniform(0, 1, (3, 48, 48, 3)).astype(np.float32))
    tgt = jnp.asarray(rng.uniform(0, 1, (3, 48, 48, 3)).astype(np.float32))

    loss = training.weak_loss(TINY, params, {"source_image": src, "target_image": tgt})

    # reference-style: full forward on the rolled image batch
    rolled = jnp.roll(src, -1, axis=0)
    pos = ncnet_forward(TINY, params, src, tgt).corr
    neg = ncnet_forward(TINY, params, rolled, tgt).corr
    want = training.match_score(neg) - training.match_score(pos)
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-5, atol=1e-6)


def test_weak_loss_remat_layers_is_semantics_preserving(rng):
    """remat_nc_layers is a memory knob: loss AND gradients must be
    unchanged (jax.checkpoint only changes what the backward stores)."""
    params = models.init_ncnet(TINY, jax.random.key(0))
    batch = {
        "source_image": jnp.asarray(
            rng.uniform(0, 1, (2, 48, 48, 3)).astype(np.float32)),
        "target_image": jnp.asarray(
            rng.uniform(0, 1, (2, 48, 48, 3)).astype(np.float32)),
    }

    def loss_and_grad(remat):
        return jax.value_and_grad(
            lambda p: training.weak_loss(TINY, p, batch,
                                         remat_nc_layers=remat)
        )(params)

    l0, g0 = loss_and_grad(False)
    l1, g1 = loss_and_grad(True)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("half,remat,custom",
                         [(False, False, False), (True, True, False),
                          (False, False, True)])
def test_train_step_reduces_loss_on_fixed_batch(rng, half, remat, custom):
    """A few Adam steps on one batch must reduce the weak loss (the negative
    is a different pair, so the model can discriminate).  The (True, True, _)
    case backs the documented single-chip bs16 recipe (bf16 volume +
    per-layer remat); the custom case backs the conv4d-custom-VJP memory
    knob — both must still learn."""
    cfg = TrainConfig(model=TINY.replace(half_precision=half), lr=1e-3,
                      batch_size=4)
    state, optimizer, mc, _ = training.create_train_state(cfg)
    step = training.make_train_step(mc, optimizer, donate=False,
                                    remat_nc_layers=remat,
                                    nc_custom_grad=custom)
    batch = {
        "source_image": jnp.asarray(rng.uniform(0, 1, (4, 48, 48, 3)).astype(np.float32)),
        "target_image": jnp.asarray(rng.uniform(0, 1, (4, 48, 48, 3)).astype(np.float32)),
    }
    losses = []
    for _ in range(12):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert int(state.step) == 12


def test_frozen_backbone_unchanged_nc_changes(rng):
    cfg = TrainConfig(model=TINY, lr=1e-3)
    state, optimizer, mc, _ = training.create_train_state(cfg)
    step = training.make_train_step(mc, optimizer, donate=False)
    batch = {
        "source_image": jnp.asarray(rng.uniform(0, 1, (2, 48, 48, 3)).astype(np.float32)),
        "target_image": jnp.asarray(rng.uniform(0, 1, (2, 48, 48, 3)).astype(np.float32)),
    }
    bb_before = jax.tree.map(lambda x: np.asarray(x), state.params["backbone"])
    nc_before = np.asarray(state.params["nc"][0]["w"])
    state, _ = step(state, batch)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a, np.asarray(b)),
        bb_before, state.params["backbone"],
    )
    assert not np.array_equal(nc_before, np.asarray(state.params["nc"][0]["w"]))


def test_finetune_updates_last_backbone_block(rng):
    cfg = TrainConfig(model=TINY, lr=1e-3, fe_finetune_params=1)
    state, optimizer, mc, _ = training.create_train_state(cfg)
    step = training.make_train_step(mc, optimizer, donate=False)
    batch = {
        "source_image": jnp.asarray(rng.uniform(0, 1, (2, 48, 48, 3)).astype(np.float32)),
        "target_image": jnp.asarray(rng.uniform(0, 1, (2, 48, 48, 3)).astype(np.float32)),
    }
    before = np.asarray(state.params["backbone"]["conv2"]["w"])
    state, _ = step(state, batch)
    assert not np.array_equal(before, np.asarray(state.params["backbone"]["conv2"]["w"]))


def test_fit_and_resume(tmp_path, capsys):
    """fit() runs the reference flow end-to-end on synthetic data; the saved
    checkpoint restores params + optimizer + step exactly."""
    root = str(tmp_path / "data")
    write_pair_dataset(root, n_pairs=4, image_hw=(48, 48), shift=(16, 16), seed=1)
    cfg = TrainConfig(
        model=TINY,
        image_size=48,
        dataset_image_path=root,
        dataset_csv_path=root + "/image_pairs",
        num_epochs=2,
        batch_size=2,
        lr=1e-3,
        result_model_dir=str(tmp_path / "ckpts"),
        log_interval=10,
    )
    result = training.fit(cfg)
    assert result["train_loss"].shape == (2,)
    assert np.isfinite(result["train_loss"]).all()

    # resume: fresh state restored from disk equals in-memory final state
    state2, optimizer, mc, _ = training.create_train_state(cfg)
    restored, epoch, tr, te, position = training.load_train_checkpoint(
        result["checkpoint"], state2
    )
    assert epoch == 2
    assert position == {"epoch": 3, "next_batch": 0}  # epoch-end cursor
    np.testing.assert_allclose(tr, result["train_loss"])
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        restored.params, result["state"].params,
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        restored.opt_state, result["state"].opt_state,
    )
    assert int(restored.step) == int(result["state"].step)
    # best_ copy exists (epoch-2 val loss improved or not; dir must exist
    # after at least the first epoch which always improves from +inf)
    import os

    assert any(d.startswith("best_") for d in os.listdir(tmp_path / "ckpts"))


def test_train_cli_smoke(tmp_path, capsys):
    from ncnet_tpu.cli.train import main

    root = str(tmp_path / "data")
    write_pair_dataset(root, n_pairs=2, image_hw=(48, 48), shift=(16, 16), seed=2)
    rc = main([
        "--dataset_image_path", root,
        "--dataset_csv_path", root + "/image_pairs",
        "--image_size", "48", "--num_epochs", "1", "--batch_size", "2",
        "--backbone", "tiny", "--ncons_kernel_sizes", "3",
        "--ncons_channels", "1",
        "--result-model-dir", str(tmp_path / "ckpts"),
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Train set: Average loss" in out and "Done!" in out


def test_data_parallel_matches_single_device(tmp_path):
    """fit() on the 8-virtual-device CPU mesh (data-parallel path) must match
    the single-device run batch for batch.

    History (round 10): this failed on the clean seed in this container —
    the FIRST step's loss already differed by ~2e-3 (far beyond f32
    reassociation noise), i.e. the sharded program computed wrong VALUES.
    Root cause: this jaxlib's CPU GSPMD partitioner miscompiles
    ``weak_loss_and_grads``'s chunked scan when the scanned operands are a
    ``reshape(chunks, c, ...)`` of the sharded-concatenated feature batch
    and the body runs the symmetric batch-fold
    (``conv4d(concat([x, xT])) → y[:b] + y[b:]``): the folded halves
    resolve to wrong slices (reproduced standalone at exactly 4× the true
    sum with the conv replaced by identity; the two-pass form and the
    no-scan form are both correct).  Fixed at the root in
    ``training/loss.py``: the scan walks chunk INDICES and
    ``dynamic_slice``s the operands inside the body — bitwise-identical on
    one device, correct under sharding."""
    root = str(tmp_path / "data")
    write_pair_dataset(root, n_pairs=8, image_hw=(48, 48), shift=(16, 16), seed=3)

    def run(dp, out):
        cfg = TrainConfig(
            model=TINY, image_size=48,
            dataset_image_path=root, dataset_csv_path=root + "/image_pairs",
            num_epochs=1, batch_size=8, lr=1e-3,
            result_model_dir=str(tmp_path / out), log_interval=10,
            data_parallel=dp,
        )
        return training.fit(cfg, progress=False)

    r_dp = run(True, "dp")
    r_sd = run(False, "sd")
    np.testing.assert_allclose(r_dp["train_loss"], r_sd["train_loss"], rtol=1e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        r_dp["state"].params["nc"], r_sd["state"].params["nc"],
    )


def test_train_checkpoint_loadable_by_eval(tmp_path):
    """The reference workflow train -> eval --checkpoint must work: a fit()
    checkpoint is readable by models.load_params (arch from checkpoint,
    runtime flags from caller)."""
    root = str(tmp_path / "data")
    write_pair_dataset(root, n_pairs=2, image_hw=(48, 48), shift=(16, 16), seed=4)
    cfg = TrainConfig(
        model=TINY, image_size=48,
        dataset_image_path=root, dataset_csv_path=root + "/image_pairs",
        num_epochs=1, batch_size=2, lr=1e-3,
        result_model_dir=str(tmp_path / "ckpts"), log_interval=10,
    )
    result = training.fit(cfg, progress=False)
    mc, params = models.load_params(result["checkpoint"])
    assert mc.backbone == "tiny" and mc.ncons_kernel_sizes == (3,)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, result["state"].params,
    )
    # and NCNet(checkpoint=...) boots straight from it
    net = models.NCNet(mc.replace(checkpoint=result["checkpoint"]))
    out = net(jnp.zeros((1, 48, 48, 3)), jnp.zeros((1, 48, 48, 3)))
    assert out.corr.shape == (1, 3, 3, 3, 3)


def test_fit_resume_continues_from_saved_epoch(tmp_path, capsys):
    """fit() on its own checkpoint restores optimizer+epoch and continues."""
    root = str(tmp_path / "data")
    write_pair_dataset(root, n_pairs=2, image_hw=(48, 48), shift=(16, 16), seed=5)
    base = dict(
        image_size=48, dataset_image_path=root,
        dataset_csv_path=root + "/image_pairs", batch_size=2, lr=1e-3,
        result_model_dir=str(tmp_path / "ckpts"), log_interval=10,
    )
    r1 = training.fit(TrainConfig(model=TINY, num_epochs=1, **base), progress=False)

    cfg2 = TrainConfig(
        model=TINY.replace(checkpoint=r1["checkpoint"]), num_epochs=2, **base
    )
    r2 = training.fit(cfg2, progress=True)
    out = capsys.readouterr().out
    assert "Resumed full train state" in out
    assert "Epoch: 1 [" not in out.split("Resumed")[1]  # epoch 1 not re-run
    np.testing.assert_allclose(r2["train_loss"][0], r1["train_loss"][0])
    assert int(r2["state"].step) == 2  # 1 batch/epoch: one old + one new step


def test_stop_backbone_grad_preserves_nc_updates(tmp_path):
    """With a frozen trunk, detaching features (the memory-saving path fit()
    uses when fe_finetune_params == 0) must not change the NC update at all."""
    mc = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,), ncons_channels=(1,))
    cfg = TrainConfig(model=mc, batch_size=2, lr=1e-3)
    rng = np.random.default_rng(0)
    batch = {
        "source_image": jnp.asarray(rng.uniform(-1, 1, (2, 32, 32, 3)).astype(np.float32)),
        "target_image": jnp.asarray(rng.uniform(-1, 1, (2, 32, 32, 3)).astype(np.float32)),
    }
    outs = {}
    for flag in (False, True):
        state, optimizer, mcfg, _ = training.create_train_state(cfg)
        step = training.make_train_step(mcfg, optimizer, donate=False,
                                        stop_backbone_grad=flag)
        new_state, loss = step(state, batch)
        outs[flag] = (np.asarray(new_state.params["nc"][0]["w"]), float(loss))
    np.testing.assert_allclose(outs[True][0], outs[False][0], rtol=1e-6, atol=1e-7)
    assert outs[True][1] == pytest.approx(outs[False][1], rel=1e-6)


@pytest.mark.slow
def test_two_process_distributed_fit(tmp_path):
    """Real multi-process coverage for fit()'s distributed branch: two CPU
    processes under jax.distributed (local TCP coordinator), one device each,
    training on synthetic pairs.  Virtual-device tests cannot catch wiring
    mistakes in per-process batch assembly
    (make_array_from_process_local_data), is_best agreement, or the
    process-0-only checkpoint write — this does."""
    import os
    import socket
    import subprocess
    import sys

    root = str(tmp_path / "data")
    write_pair_dataset(root, n_pairs=4, image_hw=(48, 48), shift=(16, 16), seed=5)

    with socket.socket() as s:  # free TCP port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "worker.py"
    worker.write_text(f"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {str(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))!r})
from ncnet_tpu.config import ModelConfig, TrainConfig
from ncnet_tpu import training
from ncnet_tpu.parallel import initialize_distributed

pid = int(sys.argv[1])
initialize_distributed("127.0.0.1:{port}", num_processes=2, process_id=pid)
assert jax.process_count() == 2 and jax.device_count() == 2

cfg = TrainConfig(
    model=ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                      ncons_channels=(1,)),
    image_size=48,
    dataset_image_path={root!r},
    dataset_csv_path={root + "/image_pairs"!r},
    num_epochs=2, batch_size=2, lr=1e-3,
    result_model_dir={str(tmp_path / "ckpts")!r},
    log_interval=10,
    data_parallel=True, distributed=True,
)
res = training.fit(cfg, progress=pid == 0)
leaves = [np.asarray(x) for x in jax.tree.leaves(res["state"].params)]
np.savez({str(tmp_path)!r} + f"/params_{{pid}}.npz", *leaves)
with open({str(tmp_path)!r} + f"/ckptname_{{pid}}.txt", "w") as f:
    f.write(res["checkpoint"])
""")

    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i)],
            env=env, cwd=str(tmp_path),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"

    # both processes must end with bit-identical parameters
    p0 = np.load(tmp_path / "params_0.npz")
    p1 = np.load(tmp_path / "params_1.npz")
    assert list(p0.files) == list(p1.files) and len(p0.files) > 0
    for k in p0.files:
        np.testing.assert_array_equal(p0[k], p1[k])

    # only process 0 wrote the checkpoint (same name computed on both)
    names = {(tmp_path / f"ckptname_{i}.txt").read_text() for i in range(2)}
    assert len(names) == 1
    ckpt = names.pop()
    from ncnet_tpu.models.checkpoint import resolve_checkpoint_dir

    latest = resolve_checkpoint_dir(ckpt)  # newest complete step_<N> version
    assert os.path.isdir(latest) and os.path.isdir(os.path.join(latest, "params"))


def test_auto_accum_chunks():
    """Chunk-4 target, device-divisibility, odd-batch fallbacks."""
    f = training.auto_accum_chunks
    assert f(8) == 4        # 2B=16, chunk 4
    assert f(16) == 8       # 2B=32, chunk 4
    assert f(2) == 1        # 2B=4 -> one chunk of 4
    assert f(3) == 2        # 2B=6: nearest feasible chunk is 3
    assert f(8, n_dev=8) == 2    # chunk must be a multiple of 8
    assert f(16, n_dev=8) == 4
    assert f(1) == 1


@pytest.mark.parametrize("chunks", [1, 2, 4, -1])
def test_weak_loss_and_grads_matches_plain_backward(rng, chunks):
    """The volume-chunked accumulation path (training/loss.py
    weak_loss_and_grads) must reproduce value_and_grad(weak_loss) exactly:
    same loss, same NC grads, zero trunk grads."""
    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3, 3),
                      ncons_channels=(4, 1))
    params = models.init_ncnet(cfg, jax.random.key(0))
    src = jnp.asarray(rng.uniform(0, 1, (4, 48, 48, 3)).astype(np.float32))
    tgt = jnp.asarray(rng.uniform(0, 1, (4, 48, 48, 3)).astype(np.float32))
    batch = {"source_image": src, "target_image": tgt}

    want_l, want_g = jax.value_and_grad(
        lambda p: training.weak_loss(cfg, p, batch, stop_backbone_grad=True,
                                     remat_filter=False)
    )(params)
    got_l, got_g = training.weak_loss_and_grads(
        cfg, params, batch, accum_chunks=chunks
    )
    np.testing.assert_allclose(float(got_l), float(want_l), rtol=1e-5,
                               atol=1e-7)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        ),
        got_g["nc"], want_g["nc"],
    )
    assert all(
        float(jnp.max(jnp.abs(x))) == 0.0
        for x in jax.tree.leaves(got_g["backbone"])
    )


def test_train_step_accum_chunks_reduces_loss(rng):
    """The accum path drives the same optimization as the plain step."""
    state, optimizer, mc2, _ = training.create_train_state(
        TrainConfig(model=TINY, batch_size=4, data_parallel=False)
    )
    step = training.make_train_step(
        mc2, optimizer, donate=False, stop_backbone_grad=True, accum_chunks=-1
    )
    src = jnp.asarray(rng.uniform(0, 1, (4, 48, 48, 3)).astype(np.float32))
    tgt = jnp.asarray(rng.uniform(0, 1, (4, 48, 48, 3)).astype(np.float32))
    batch = {"source_image": src, "target_image": tgt}
    state, first = step(state, batch)
    for _ in range(5):
        state, loss = step(state, batch)
    assert float(loss) < float(first)


def test_training_improves_pck_on_structured_shift_pairs():
    """Train→metric convergence (VERDICT r3 item 7): weak-loss training must
    IMPROVE PCK, not just the loss.  Dense random textures fail here (their
    correlation has no consistent structure to amplify — a measured r3
    negative), so the fixture is structured blob scenes with shifted-copy
    targets: the positive volume carries a spatially-consistent peak
    structure the NC filter can learn to amplify, and the circular shift
    gives exact GT correspondences for PCK."""
    from ncnet_tpu.evaluation.pck import pck_metric
    from ncnet_tpu.ops import corr_to_matches

    r = np.random.default_rng(3)

    def blob_image(hw, n_blobs):
        img = np.zeros(hw + (3,), np.float32)
        yy, xx = np.mgrid[0:hw[0], 0:hw[1]]
        for _ in range(n_blobs):
            cy, cx = r.uniform(6, hw[0] - 6), r.uniform(6, hw[1] - 6)
            col = r.uniform(0.3, 1.0, 3)
            g = np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2)
                       / (2 * r.uniform(2.0, 4.0) ** 2))
            img += g[..., None] * col
        return np.clip(img, 0, 1)

    bsz, s, shift = 8, 96, 32
    src = np.stack([blob_image((s, s), 25) for _ in range(bsz)])
    tgt = np.roll(src, (shift, shift), axis=(1, 2))
    batch = {"source_image": jnp.asarray(src), "target_image": jnp.asarray(tgt)}

    n_kp = 16
    ky = r.uniform(4, s - 4, (bsz, n_kp))
    kx = r.uniform(4, s - 4, (bsz, n_kp))
    pts_tgt = np.full((bsz, 2, 20), -1.0, np.float32)
    pts_src = np.full((bsz, 2, 20), -1.0, np.float32)
    pts_tgt[:, 0, :n_kp], pts_tgt[:, 1, :n_kp] = kx, ky
    pts_src[:, 0, :n_kp] = (kx - shift) % s
    pts_src[:, 1, :n_kp] = (ky - shift) % s
    im = np.tile(np.array([[float(s), float(s), 3.0]], np.float32), (bsz, 1))
    eval_batch = {
        "source_points": jnp.asarray(pts_src),
        "target_points": jnp.asarray(pts_tgt),
        "source_im_size": jnp.asarray(im),
        "target_im_size": jnp.asarray(im),
        "L_pck": jnp.asarray(np.full((bsz, 1), float(s), np.float32)),
    }

    def mean_pck(params):
        out = ncnet_forward(TINY, params,
                            batch["source_image"], batch["target_image"])
        m = corr_to_matches(out.corr, do_softmax=True)
        # alpha·L = 19 px ≥ the 16 px feature-cell pitch: the metric scores
        # cell-level matching, not sub-cell interpolation luck
        return float(jnp.mean(pck_metric(eval_batch, m, alpha=0.2)))

    state, optimizer, mc, _ = training.create_train_state(
        TrainConfig(model=TINY, batch_size=bsz, lr=3e-3, data_parallel=False)
    )
    step = training.make_train_step(
        mc, optimizer, donate=False, stop_backbone_grad=True, accum_chunks=-1
    )
    pck_before = mean_pck(state.params)
    for _ in range(40):
        state, loss = step(state, batch)
    pck_after = mean_pck(state.params)
    # measured on this fixture/seed: 0.42 -> 0.52; the bar leaves slack for
    # cross-platform float drift while still requiring a real improvement
    assert pck_after > pck_before + 0.04, (pck_before, pck_after)
    assert float(loss) < 0.0


def test_explicit_accum_chunks_with_finetune_raises(tmp_path):
    """An explicit chunk count contradicts finetuning (the chunked path
    detaches the trunk); fit must refuse loudly rather than silently
    dropping the knob (r4 review finding), while the auto default quietly
    falls back to the whole-batch backward."""
    root = str(tmp_path / "data")
    write_pair_dataset(root, n_pairs=4, image_hw=(48, 48), shift=(16, 16),
                       seed=9)
    kw = dict(
        model=TINY, image_size=48, dataset_image_path=root,
        dataset_csv_path=root + "/image_pairs", num_epochs=1, batch_size=2,
        result_model_dir=str(tmp_path / "m"), data_parallel=False,
        fe_finetune_params=1,
    )
    with pytest.raises(ValueError, match="accum_chunks"):
        training.fit(TrainConfig(**kw, accum_chunks=4), progress=False)
    # auto (-1) with finetuning: falls back, trains fine
    r = training.fit(TrainConfig(**kw, accum_chunks=-1), progress=False)
    assert np.isfinite(r["train_loss"]).all()

    with pytest.raises(ValueError, match="frozen trunk"):
        training.make_train_step(
            TINY, training.make_optimizer(
                training.trainable_labels(
                    TINY, models.init_ncnet(TINY, jax.random.key(0)), 1)
            )(1e-3),
            stop_backbone_grad=False, accum_chunks=4,
        )


@pytest.mark.parametrize("bad", [-2, 3])
def test_invalid_explicit_accum_chunks_rejected_early(tmp_path, bad):
    """Bad explicit chunk counts (below -1, or not dividing 2*batch) must be
    a clear config error before compile, not a trace-time reshape failure."""
    root = str(tmp_path / "data")
    write_pair_dataset(root, n_pairs=4, image_hw=(48, 48), shift=(16, 16),
                       seed=10)
    cfg = TrainConfig(
        model=TINY, image_size=48, dataset_image_path=root,
        dataset_csv_path=root + "/image_pairs", num_epochs=1, batch_size=2,
        result_model_dir=str(tmp_path / "m"), data_parallel=False,
        accum_chunks=bad,
    )
    with pytest.raises(ValueError, match="accum_chunks"):
        training.fit(cfg, progress=False)


def test_default_config_resolves_to_chunked_backward():
    """The production default (frozen trunk, accum auto) must resolve to a
    real chunk count — pinning that the measured fast path IS the default."""
    from ncnet_tpu.training.train import _resolve_accum_chunks

    assert _resolve_accum_chunks(TrainConfig(), n_dev=1) == 8  # bs16, chunk 4
    assert _resolve_accum_chunks(TrainConfig(), n_dev=8) == 4  # chunk 8
    assert _resolve_accum_chunks(
        TrainConfig(accum_chunks=0), n_dev=1) == 0  # explicit off respected


def test_explicit_accum_chunks_must_divide_over_devices():
    """An explicit chunk count whose chunk size does not divide over the
    data mesh would force GSPMD resharding every scan iteration — rejected
    loudly instead (ADVICE r4)."""
    from ncnet_tpu.training.train import _resolve_accum_chunks

    # bs8, accum 8 → chunk 2: fine on 1-2 devices, rejected on 8
    cfg = TrainConfig(batch_size=8, accum_chunks=8)
    assert _resolve_accum_chunks(cfg, n_dev=1) == 8
    assert _resolve_accum_chunks(cfg, n_dev=2) == 8
    with pytest.raises(ValueError, match="does not divide over 8"):
        _resolve_accum_chunks(cfg, n_dev=8)
    # a coherent explicit count still passes on the same mesh
    assert _resolve_accum_chunks(
        TrainConfig(batch_size=8, accum_chunks=2), n_dev=8) == 2
