"""Resize semantics: must match torch align-corners bilinear (the reference's
identity-affine grid_sample / F.upsample path).  torch (CPU) is used purely as
an independent oracle."""

import numpy as np
import jax.numpy as jnp
import pytest

from ncnet_tpu import ops

torch = pytest.importorskip("torch")


@pytest.mark.parametrize("shape,out", [((13, 17), (7, 5)), ((5, 6), (10, 12)),
                                       ((8, 8), (8, 8))])
def test_resize_matches_torch_align_corners(rng, shape, out):
    img = rng.standard_normal((*shape, 3)).astype(np.float32)
    ours = np.asarray(ops.resize_bilinear_align_corners(jnp.asarray(img), *out))
    ours_np = ops.resize_bilinear_align_corners_np(img, *out)
    t = torch.nn.functional.interpolate(
        torch.from_numpy(img.transpose(2, 0, 1))[None], size=out,
        mode="bilinear", align_corners=True,
    )[0].numpy().transpose(1, 2, 0)
    np.testing.assert_allclose(ours, t, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ours_np, t, rtol=1e-4, atol=1e-5)


def test_normalize_imagenet():
    img = np.full((4, 4, 3), 255.0, dtype=np.float32)
    out = ops.normalize_imagenet(img)
    expected = (1.0 - ops.IMAGENET_MEAN) / ops.IMAGENET_STD
    np.testing.assert_allclose(out[0, 0], expected, rtol=1e-5)
