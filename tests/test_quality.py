"""Match-quality observability (ISSUE 7): signal correctness on synthetic
volumes, digest accuracy, the drift sentinel, resume-merged digests, and THE
acceptance path — a synthetic PF-Pascal eval emitting tier-tagged per-pair
quality events whose rank correlation against PCK is positive and whose
distributions gate against the committed reference
(``perf/quality_ref.jsonl``) via ``tools/quality_drift.py``.
"""

import json
import os
import subprocess
import sys

import numpy as np
import jax.numpy as jnp
import pytest

from ncnet_tpu.observability.metrics import Histogram, MetricsRegistry
from ncnet_tpu.observability.quality import (
    DIGEST_BINS,
    QUALITY_SIGNALS,
    SIGNAL_RANGE,
    check_drift,
    digests_from_events,
    load_reference,
    psi,
    quality_signals,
    quality_table,
    signal_pck_correlation,
    spearman,
    write_reference,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import quality_drift  # noqa: E402  (tools/quality_drift.py)


# ---------------------------------------------------------------------------
# signal correctness on synthetic volumes
# ---------------------------------------------------------------------------


def _identity_volume(side=5, peak=30.0):
    corr = np.zeros((1, side, side, side, side), np.float32)
    for i in range(side):
        for j in range(side):
            corr[0, i, j, i, j] = peak
    return jnp.asarray(corr)


def test_delta_peaked_volume_scores_confident():
    """A delta-peaked (identity) volume is maximally confident: ~1.0
    margin/agreement/score, ~0 entropy, perfectly coherent flow."""
    s = {k: float(v[0]) for k, v in quality_signals(_identity_volume()).items()}
    assert s["margin"] > 0.95
    assert s["mnn_agreement"] == 1.0
    assert s["score"] > 0.95
    assert s["entropy"] < 0.05
    assert s["coherence"] == 1.0


def test_uniform_volume_scores_max_entropy():
    """A constant (uninformative) volume scores maximum normalized entropy
    and zero margin — softmax over A cells is exactly uniform."""
    s = {k: float(v[0])
         for k, v in quality_signals(jnp.zeros((1, 5, 5, 5, 5))).items()}
    assert s["entropy"] == pytest.approx(1.0, abs=1e-5)
    assert s["margin"] == pytest.approx(0.0, abs=1e-6)
    assert s["score"] == pytest.approx(1.0 / 25.0, abs=1e-6)
    # the collapsed constant-argmax field must NOT read as a perfect flow:
    # the coherence band sits strictly below one grid step by design
    assert s["coherence"] == pytest.approx(0.0, abs=1e-6)


def test_shifted_volume_is_coherent_random_is_not():
    """A rigid one-cell shift keeps a smooth displacement field (only the
    clamped border row breaks the step pattern); spatially-incoherent
    argmax noise does not."""
    side = 6
    shifted = np.zeros((1, side, side, side, side), np.float32)
    for i in range(side):
        for j in range(side):
            shifted[0, min(i + 1, side - 1), j, i, j] = 20.0
    s = quality_signals(jnp.asarray(shifted))
    # 60 adjacent pairs, 6 broken by the border clamp (the last row's
    # plateau counts incoherent under the strict sub-one-step band)
    assert float(s["coherence"][0]) == pytest.approx(54 / 60, abs=1e-6)

    rng = np.random.default_rng(3)
    noise = rng.normal(0, 5, (1, side, side, side, side)).astype(np.float32)
    r = quality_signals(jnp.asarray(noise))
    assert float(r["coherence"][0]) < 0.5


def test_quality_table_order_and_batch_independence():
    """The stacked table lays columns out in QUALITY_SIGNALS order, and a
    pair's signals do not depend on its batch neighbours."""
    rng = np.random.default_rng(0)
    v1 = rng.normal(0, 3, (1, 4, 4, 4, 4)).astype(np.float32)
    v2 = rng.normal(0, 3, (1, 4, 4, 4, 4)).astype(np.float32)
    both = quality_table(jnp.asarray(np.concatenate([v1, v2])))
    one = quality_table(jnp.asarray(v1))
    sigs = quality_signals(jnp.asarray(v1))
    np.testing.assert_allclose(np.asarray(both)[0], np.asarray(one)[0],
                               rtol=1e-6)
    for i, name in enumerate(QUALITY_SIGNALS):
        assert float(one[0, i]) == pytest.approx(float(sigs[name][0]),
                                                 abs=1e-6)


# ---------------------------------------------------------------------------
# digest accuracy + merge
# ---------------------------------------------------------------------------


def test_histogram_digest_tracks_exact_percentiles():
    rng = np.random.default_rng(1)
    vals = np.clip(rng.normal(0.55, 0.15, 5000), 0, 1)
    h = Histogram(0.0, 1.0, DIGEST_BINS)
    h.add(vals)
    bin_w = 1.0 / DIGEST_BINS
    assert h.count == 5000
    assert h.mean() == pytest.approx(float(np.mean(vals)), abs=1e-6)
    for q in (50, 90):
        assert h.percentile(q) == pytest.approx(
            float(np.percentile(vals, q)), abs=bin_w)
    # NaN is dropped, not binned
    h2 = Histogram(0.0, 1.0, DIGEST_BINS)
    h2.add([0.5, float("nan"), 0.5])
    assert h2.count == 2


def test_histogram_merge_equals_single_pass_and_roundtrips():
    rng = np.random.default_rng(2)
    vals = np.clip(rng.normal(0.4, 0.2, 1000), 0, 1)
    whole = Histogram(0.0, 1.0, DIGEST_BINS)
    whole.add(vals)
    a, b = Histogram(0.0, 1.0, DIGEST_BINS), Histogram(0.0, 1.0, DIGEST_BINS)
    a.add(vals[:300])
    b.add(vals[300:])
    a.merge(b)
    assert a.counts == whole.counts and a.count == whole.count
    assert a.sum == pytest.approx(whole.sum)
    # snapshot → from_snapshot preserves the distribution (PSI exactly 0)
    back = Histogram.from_snapshot(whole.snapshot())
    assert psi(whole, back) == 0.0
    with pytest.raises(ValueError):
        a.merge(Histogram(0.0, 1.0, DIGEST_BINS + 1))


def test_registry_histogram_binning_is_pinned():
    reg = MetricsRegistry(scope="t")
    h = reg.histogram("q_margin", 0.0, 1.0, DIGEST_BINS)
    assert reg.histogram("q_margin", 0.0, 1.0, DIGEST_BINS) is h
    with pytest.raises(ValueError):
        reg.histogram("q_margin", 0.0, 2.0, DIGEST_BINS)
    h.add([0.5])
    assert reg.snapshot()["q_margin"]["count"] == 1


# ---------------------------------------------------------------------------
# drift sentinel: flags an injected shift, stays green on noise
# ---------------------------------------------------------------------------


def _digest_of(rng, mu, n=400, sigma=0.08):
    h = Histogram(0.0, 1.0, DIGEST_BINS)
    h.add(np.clip(rng.normal(mu, sigma, n), 0, 1))
    return h


def test_drift_sentinel_flags_shift_stays_green_on_noise(tmp_path):
    rng = np.random.default_rng(5)
    ref = {("resident", "score"): _digest_of(rng, 0.62)}
    ref_path = str(tmp_path / "ref.jsonl")
    write_reference(ref_path, ref, device_kind="TPU v5 lite")
    reference = load_reference(ref_path)
    assert ("TPU v5 lite", "resident", "score") in reference

    # same distribution, fresh sampling noise → green
    noisy = {("resident", "score"): _digest_of(rng, 0.62)}
    findings = check_drift(reference, noisy, device_kind="TPU v5 lite")
    assert [f["status"] for f in findings] == ["ok"]

    # a bf16-style score shift (distribution moved down) → flagged
    shifted = {("resident", "score"): _digest_of(rng, 0.45)}
    findings = check_drift(reference, shifted, device_kind="TPU v5 lite")
    assert [f["status"] for f in findings] == ["drift"]
    assert findings[0]["psi"] > findings[0]["threshold"]

    # series the reference cannot vouch for are skipped, never guessed:
    # unknown signal, and a matching signal on a DIFFERENT device kind.
    # Symmetrically, a reference series the run failed to produce at all
    # (broken emitter / tier never executed) must SURFACE as skipped, not
    # silently vanish from the findings
    extra = {("resident", "margin"): _digest_of(rng, 0.5)}
    findings = check_drift(reference, extra, device_kind="TPU v5 lite")
    assert sorted(f["signal"] for f in findings) == ["margin", "score"]
    assert all(f["status"] == "skipped" for f in findings)
    missing = next(f for f in findings if f["signal"] == "score")
    assert "absent from this run" in missing["reason"]
    findings = check_drift(reference, noisy, device_kind="cpu")
    assert [f["status"] for f in findings] == ["skipped"]


def test_drift_tool_refuses_to_judge_zero_evidence(tmp_path):
    """An accuracy gate must never report green on zero evidence: a log
    with NO quality events is an input error (exit 2), not a clean run."""
    from ncnet_tpu.observability.events import EventLog

    p = str(tmp_path / "events.jsonl")
    log = EventLog(p)
    log.emit("run_start")
    log.close()
    committed = os.path.join(_REPO, "perf", "quality_ref.jsonl")
    assert os.path.exists(committed)
    assert quality_drift.main(["--check", p]) == 2


def test_render_quality_survives_all_nan_series():
    """A (tier, signal) series whose every sample was NaN (all pairs
    quarantined under that tier) renders as n/a, not a TypeError."""
    import run_report

    events = [{"event": "quality", "tier": "resident",
               "signals": {"score": [float("nan")]}}]
    section = run_report.build_quality_section(events, "cpu")
    assert section["table"][0]["n"] == 0
    report = {"quality": section}
    text = run_report.render_quality(report)
    assert "n/a" in text


def test_perf_store_direction_inference_for_quality_metrics():
    """Satellite: quality_* series gate with the stated directions."""
    from ncnet_tpu.observability.perfstore import metric_direction

    assert metric_direction("pf_pascal_pck") == "higher"
    assert metric_direction("pf_pascal_quality_margin") == "higher"
    assert metric_direction("pf_pascal_quality_mnn_agreement") == "higher"
    assert metric_direction("pf_pascal_quality_coherence") == "higher"
    assert metric_direction("pf_pascal_quality_score") == "higher"
    assert metric_direction("pf_pascal_quality_entropy") == "lower"
    assert metric_direction("train_quality_score_gap") == "higher"


def test_spearman_rank_correlation():
    assert spearman([1, 2, 3, 4], [10, 20, 30, 40]) == pytest.approx(1.0)
    assert spearman([1, 2, 3, 4], [4, 3, 2, 1]) == pytest.approx(-1.0)
    assert np.isnan(spearman([1, 1, 1], [1, 2, 3]))   # constant side
    assert np.isnan(spearman([1, 2], [2, 1]))         # too few pairs
    # NaN pairs are dropped, ties get average ranks
    r = spearman([1, 2, 2, 3, np.nan], [1, 2, 2, 3, 99])
    assert r == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# THE acceptance path: synthetic eval → tier-tagged events → run_report
# correlation → drift gate green vs committed ref, red on perturbation
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def clean_run(tmp_path_factory):
    work = str(tmp_path_factory.mktemp("quality_clean"))
    stats, events_path = quality_drift.synthetic_reference_run(work)
    return stats, events_path


def test_eval_emits_tier_tagged_per_pair_quality_events(clean_run):
    """Every PF-Pascal eval batch emits one `quality` event carrying
    per-pair signals AND per-pair PCK, tagged with the active fused tier —
    with zero per-pair Python postprocessing on the hot path (the signals
    arrive in the same fetched table as the PCK column)."""
    from ncnet_tpu.observability.events import replay_events

    stats, events_path = clean_run
    _, events = replay_events(events_path)
    qevents = [e for e in events if e.get("event") == "quality"]
    n_batches = quality_drift.SYNTH_PAIRS // quality_drift.SYNTH_BATCH
    assert len(qevents) == n_batches
    for e in qevents:
        assert e["scope"] == "pf_pascal_eval"
        assert e["tier"] == "xla"  # CPU backend: no Pallas chooser ran
        assert set(e["signals"]) == set(QUALITY_SIGNALS)
        for vals in e["signals"].values():
            assert len(vals) == quality_drift.SYNTH_BATCH
        assert len(e["pck"]) == quality_drift.SYNTH_BATCH
    # the eval summary carries the per-signal digests (metrics registry)
    summaries = [e for e in events if e.get("event") == "eval_summary"
                 and isinstance(e.get("metrics"), dict)]
    assert summaries
    snap = summaries[-1]["metrics"]
    for name in QUALITY_SIGNALS:
        assert snap[f"q_{name}"]["count"] == quality_drift.SYNTH_PAIRS
    # and the stats dict exposes the same aggregation
    assert stats["quality_tier"] == "xla"
    for name in QUALITY_SIGNALS:
        assert stats["quality_digests"][name]["count"] == \
            quality_drift.SYNTH_PAIRS
        assert len(stats["quality"][name]) == quality_drift.SYNTH_PAIRS


def test_signals_rank_correlate_with_pck(clean_run):
    """The confident/scrambled pair mix must produce a POSITIVE Spearman
    rho between each confidence signal and PCK (entropy: negative) — the
    signals are validated as label-free PCK proxies, both in the eval's own
    stats and through run_report --quality."""
    stats, events_path = clean_run
    rho = stats["quality_pck_spearman"]
    for name in ("score", "margin", "mnn_agreement", "coherence"):
        assert rho[name] > 0.3, f"{name}: rho={rho[name]}"
    assert rho["entropy"] < -0.3

    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import run_report

    report = run_report.build_report([events_path])
    q = report["quality"]
    assert q["pck_spearman"]["margin"] > 0.3
    assert q["pck_spearman"]["entropy"] < -0.3
    rows = {(r["tier"], r["signal"]): r for r in q["table"]}
    assert rows[("xla", "margin")]["n"] == quality_drift.SYNTH_PAIRS
    text = run_report.render_quality(report)
    assert "signal-vs-PCK rank correlation" in text

    # event-level correlation helper agrees with the stats-level one
    from ncnet_tpu.observability.events import replay_events

    _, events = replay_events(events_path)
    rho_ev = signal_pck_correlation(events)
    assert rho_ev["margin"] == pytest.approx(rho["margin"], abs=1e-6)


def test_drift_gate_green_on_committed_ref_red_on_perturbation(
        clean_run, tmp_path):
    """quality_drift --check exits 0 against the COMMITTED reference for a
    clean run of the pinned fixture, and nonzero when the volume is
    perturbed to simulate a low-precision tier regression."""
    _, events_path = clean_run
    committed = os.path.join(_REPO, "perf", "quality_ref.jsonl")
    assert os.path.exists(committed), "committed quality_ref.jsonl missing"
    assert quality_drift.main(["--check", events_path]) == 0

    work = str(tmp_path / "perturbed")
    os.makedirs(work)
    _, bad_events = quality_drift.synthetic_reference_run(work, perturb=True)
    assert quality_drift.main(["--check", bad_events]) == 1

    # run_report --quality shows the same verdicts inline
    import run_report

    report = run_report.build_report([bad_events], quality_ref=committed)
    drift = {(f["tier"], f["signal"]): f["status"]
             for f in report["quality"]["drift"]}
    assert "drift" in drift.values()


def test_quality_counters_in_trace_export(clean_run, tmp_path):
    """quality + metrics events render as Perfetto counter ('C') tracks on
    the same timeline as the spans."""
    import trace_export

    _, events_path = clean_run
    trace = trace_export.build_trace([events_path])
    counters = [e for e in trace["traceEvents"] if e["ph"] == "C"]
    names = {e["name"] for e in counters}
    assert any(n.startswith("quality/pf_pascal_eval/xla") for n in names)
    assert any(n.startswith("metrics/") for n in names)
    qc = next(e for e in counters
              if e["name"].startswith("quality/pf_pascal_eval"))
    assert set(QUALITY_SIGNALS) <= set(qc["args"])
    assert all(isinstance(v, float) for v in qc["args"].values())
    # a quality/metrics event never also renders as an instant marker
    instants = {e["name"] for e in trace["traceEvents"] if e["ph"] == "i"}
    assert "quality" not in instants and "metrics" not in instants
    # still a loadable Chrome trace document
    json.dumps(trace)


# ---------------------------------------------------------------------------
# SIGKILL-mid-eval resume: merged digests match an uninterrupted run
# ---------------------------------------------------------------------------


def test_sigkill_resume_merged_digests_match_uninterrupted(tmp_path):
    """SIGKILL mid-journal-append; the resumed run replays journaled
    batches into the quality digests (no re-dispatch), and the merged
    digests — replayed + fresh — are identical to an uninterrupted run's."""
    from ncnet_tpu.data.synthetic import write_pf_pascal_like
    from ncnet_tpu import models
    from ncnet_tpu.config import EvalPFPascalConfig, ModelConfig
    from ncnet_tpu.evaluation import run_eval

    root = str(tmp_path / "data")
    write_pf_pascal_like(root, n_pairs=3, image_hw=(96, 96), shift=(16, 16),
                         seed=7)
    journal_dir = str(tmp_path / "j")

    worker = tmp_path / "worker.py"
    worker.write_text(f"""
import sys
sys.path.insert(0, {_REPO!r})
import jax
jax.config.update("jax_platforms", "cpu")
from ncnet_tpu import models
from ncnet_tpu.config import EvalPFPascalConfig, ModelConfig
from ncnet_tpu.evaluation import run_eval

TINY = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                   ncons_channels=(1,))
config = EvalPFPascalConfig(image_size=96, eval_dataset_path={root!r},
                            journal_dir={journal_dir!r})
run_eval(config, net=models.NCNet(TINY, seed=0), batch_size=1,
         num_workers=0, progress=False)
""")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["NCNET_TPU_FAULTS"] = json.dumps({"kill_at_journal_append": 2})
    proc = subprocess.run(
        [sys.executable, str(worker)], env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=600,
    )
    assert proc.returncode == -9, f"expected SIGKILL:\n{proc.stdout[-3000:]}"

    tiny = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                       ncons_channels=(1,))

    def run(journal=""):
        cfg = EvalPFPascalConfig(image_size=96, eval_dataset_path=root,
                                 journal_dir=journal)
        return run_eval(cfg, net=models.NCNet(tiny, seed=0), batch_size=1,
                        num_workers=0, progress=False)

    resumed = run(journal=journal_dir)
    full = run()
    np.testing.assert_array_equal(resumed["per_pair"], full["per_pair"])
    for name in QUALITY_SIGNALS:
        np.testing.assert_array_equal(resumed["quality"][name],
                                      full["quality"][name])
        assert resumed["quality_digests"][name]["counts"] == \
            full["quality_digests"][name]["counts"]
        assert resumed["quality_digests"][name]["count"] == 3


# ---------------------------------------------------------------------------
# digests_from_events binning follows the reference
# ---------------------------------------------------------------------------


def test_digests_from_events_respects_reference_binning():
    events = [
        {"event": "quality", "tier": "resident",
         "signals": {"score": [0.2, 0.4], "margin": [0.1]}},
        {"event": "quality", "tier": "resident",
         "signals": {"score": [0.6, float("nan")]}},
        {"event": "other"},
    ]
    digs = digests_from_events(events)
    assert digs[("resident", "score")].count == 3  # NaN dropped
    assert digs[("resident", "margin")].count == 1
    # reference-provided binning overrides the default
    digs = digests_from_events(
        events, bins_like={"score": {"lo": 0.0, "hi": 2.0,
                                     "counts": [0] * 8}})
    h = digs[("resident", "score")]
    assert (h.lo, h.hi, h.bins) == (0.0, 2.0, 8)
    # default binning comes from SIGNAL_RANGE
    lo, hi = SIGNAL_RANGE["margin"]
    hm = digs[("resident", "margin")]
    assert (hm.lo, hm.hi, hm.bins) == (lo, hi, DIGEST_BINS)
