"""Memory observability (ISSUE 13): the compiled-program ledger, the live
HBM plane, the leak sentinel, OOM postmortems, and their consumers.

The acceptance bars executed here:

  * ledger round-trip with a fake ``memory_analysis`` — record, persist,
    warm-process cache replay (no second analysis compile);
  * CPU-backend graceful degradation — no ``memory_stats`` ⇒ the plane
    stays silent, never errors;
  * the leak sentinel flags an injected buffer-retaining loop and stays
    green on steady-state serving;
  * an injected ``RESOURCE_EXHAUSTED`` produces exactly ONE
    ``memory_postmortem`` whose ledger rows name the failed program;
  * ``run_report --memory`` replays it all from the event log alone;
  * a warmed REAL engine ladder exposes ``ncnet_serve_hbm_*`` (the
    predicted-footprint gauge) on ``/metrics``;
  * ``perf_regress --check`` stays green on a seeded memory series and
    flags an injected 2x ``temp_bytes`` regression.
"""

import json
import os
import sys
import time
import warnings

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from ncnet_tpu import models, ops
from ncnet_tpu.config import ModelConfig
from ncnet_tpu.observability import EventLog, events as obs_events
from ncnet_tpu.observability import memory as mem
from ncnet_tpu.observability.events import replay_events
from ncnet_tpu.serving import BatchMatchEngine, MatchService, ServingConfig
from ncnet_tpu.utils import faults

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import run_report  # noqa: E402
import perf_regress  # noqa: E402
import stall_watchdog  # noqa: E402

TINY = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                   ncons_channels=(1,))

FAKE_ANALYSIS = {"argument_bytes": 1000, "output_bytes": 200,
                 "temp_bytes": 4096, "generated_code_bytes": 64,
                 "alias_bytes": 0}


@pytest.fixture(autouse=True)
def _clean_state():
    """No armed faults, no demoted tiers, no leaked sink, fresh ledger
    state (the in-process analog of a new process)."""
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)
    mem._reset_state()
    yield
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)
    mem._reset_state()


@pytest.fixture(scope="module")
def tiny_params():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return models.init_ncnet(TINY, jax.random.key(0))


def u8(side=32, seed=0):
    return np.random.default_rng(seed).integers(
        0, 255, (side, side, 3), dtype=np.uint8)


def _events_to(tmp_path, name="events.jsonl"):
    return EventLog(str(tmp_path / name))


# ---------------------------------------------------------------------------
# ledger: record, persist, warm-process replay
# ---------------------------------------------------------------------------


def test_ledger_round_trip_with_fake_analysis(tmp_path, monkeypatch):
    monkeypatch.setenv(mem.LEDGER_ENV, str(tmp_path / "ledger.json"))
    mem._reset_state()
    log = _events_to(tmp_path)
    with obs_events.bound(log):
        row = mem.record_program("probe_prog", "25x25x25x25|k=5,5,5",
                                 analysis=FAKE_ANALYSIS, tier="resident",
                                 device_kind="TPU v5 lite")
    log.close()
    assert row["temp_bytes"] == 4096
    assert row["total_bytes"] == 1000 + 200 + 4096  # args + out + temp
    assert row["tier"] == "resident"

    # the event carries the full row, schema-versioned
    _, evs = replay_events(log.path)
    led = [e for e in evs if e["event"] == "memory_ledger"]
    assert len(led) == 1
    assert led[0]["program"] == "probe_prog"
    assert led[0]["schema"] == mem.SCHEMA_VERSION
    assert led[0]["temp_bytes"] == 4096

    # persisted beside the tier cache, keyed by (program, shape, tier, kind)
    doc = json.loads((tmp_path / "ledger.json").read_text())
    key = mem.ledger_key("probe_prog", "25x25x25x25|k=5,5,5",
                         "resident", "TPU v5 lite")
    assert doc["rows"][key]["temp_bytes"] == 4096

    # warm process: forget the in-process state, ensure() replays the
    # persisted row WITHOUT calling analyze — and still emits the event
    mem._reset_state()
    calls = []
    log2 = _events_to(tmp_path, "events2.jsonl")
    with obs_events.bound(log2):
        row2 = mem.ensure_program(
            "probe_prog", "25x25x25x25|k=5,5,5",
            analyze=lambda: calls.append(1) or FAKE_ANALYSIS,
            tier="resident", device_kind="TPU v5 lite")
    log2.close()
    assert calls == []  # no second analysis compile
    assert row2["temp_bytes"] == 4096
    _, evs2 = replay_events(log2.path)
    cached = [e for e in evs2 if e["event"] == "memory_ledger"]
    assert len(cached) == 1 and cached[0]["source"] == "cache"

    # a genuine miss (different tier) DOES analyze
    with obs_events.bound(None):
        mem.ensure_program("probe_prog", "25x25x25x25|k=5,5,5",
                           analyze=lambda: calls.append(1) or FAKE_ANALYSIS,
                           tier="xla", device_kind="TPU v5 lite")
    assert calls == [1]


def test_ledger_analysis_dict_from_compiled():
    # the real jax AOT object (CPU backend exposes the same accounting)
    compiled = jax.jit(lambda x: x @ x.T).lower(
        jnp.ones((8, 8), jnp.float32)).compile()
    d = mem.analysis_dict(compiled)
    assert d is not None and d["argument_bytes"] == 256
    assert "total_bytes" in d
    # garbage degrades to None, never raises
    assert mem.analysis_dict(None) is None
    assert mem.analysis_dict(object()) is None


def test_predicted_footprint_sums_temp_plus_output():
    mem.record_program("serve_batch", "a", analysis=FAKE_ANALYSIS,
                       device_kind="cpu")
    mem.record_program("serve_batch", "b", analysis=FAKE_ANALYSIS,
                       device_kind="cpu")
    mem.record_program("other", "a", analysis=FAKE_ANALYSIS,
                       device_kind="cpu")
    assert mem.predicted_footprint_bytes(program="serve_batch") \
        == 2 * (4096 + 200)
    # re-recording the same key replaces, never double-counts
    mem.record_program("serve_batch", "a", analysis=FAKE_ANALYSIS,
                       device_kind="cpu")
    assert mem.predicted_footprint_bytes(program="serve_batch") \
        == 2 * (4096 + 200)
    # nothing warmed: None, not 0 (a gauge that guesses is worse than none)
    mem._reset_state()
    assert mem.predicted_footprint_bytes(program="serve_batch") is None


def test_predicted_footprint_evicts_superseded_tier():
    # a demote-retrace re-records the same (program, shape) under the new
    # tier: the old tier's row must leave the warmed set, or the predicted
    # gauge double-counts every bucket right after the recovery
    mem.record_program("serve_batch", "a", analysis=FAKE_ANALYSIS,
                       tier="fused_lane", device_kind="cpu")
    mem.record_program("serve_batch", "b", analysis=FAKE_ANALYSIS,
                       tier="fused_lane", device_kind="cpu")
    assert mem.predicted_footprint_bytes(program="serve_batch") \
        == 2 * (4096 + 200)
    mem.record_program("serve_batch", "a", analysis=FAKE_ANALYSIS,
                       tier="xla", device_kind="cpu")
    # still 2 shapes — one row each, not 3
    rows = mem.ledger_rows(program="serve_batch")
    assert len(rows) == 2
    assert {(r["shape_class"], r["tier"]) for r in rows} \
        == {("a", "xla"), ("b", "fused_lane")}
    assert mem.predicted_footprint_bytes(program="serve_batch") \
        == 2 * (4096 + 200)


def test_ensure_program_async_dedupes_in_flight_keys():
    import threading

    started = threading.Event()
    release = threading.Event()
    calls = []

    def slow_analyze():
        calls.append(1)
        started.set()
        release.wait(timeout=30.0)
        return FAKE_ANALYSIS

    assert mem.ensure_program_async(
        "p", "s", analyze=slow_analyze, device_kind="cpu") is None
    assert started.wait(timeout=10.0)
    # a second miss on the SAME key while the first is in flight must not
    # spawn a duplicate analysis compile (the multi-replica warmup shape)
    assert mem.ensure_program_async(
        "p", "s", analyze=slow_analyze, device_kind="cpu") is None
    release.set()
    mem.flush_pending(timeout=30.0)
    assert calls == [1]
    assert len(mem.ledger_rows(program="p")) == 1


def test_shape_class_is_compact_and_deterministic(tiny_params):
    a = mem.shape_class((tiny_params, jnp.zeros((2, 32, 32, 3))))
    b = mem.shape_class((tiny_params, jnp.zeros((2, 32, 32, 3))))
    assert a == b and len(a) < 200
    assert a != mem.shape_class((tiny_params, jnp.zeros((4, 32, 32, 3))))
    assert mem.shape_class(()) == "scalar"


# ---------------------------------------------------------------------------
# CPU-backend graceful degradation
# ---------------------------------------------------------------------------


def test_cpu_backend_hbm_plane_stays_silent():
    # the CPU backend exposes no memory_stats: the plane is None/absent,
    # never an error
    assert mem.hbm_stats() is None
    from ncnet_tpu.observability.device import device_snapshot

    snap = device_snapshot()
    assert snap and all("bytes_in_use" not in d for d in snap)
    # the census still works (live_arrays is backend-independent)
    census = mem.live_array_census()
    assert census is not None and census["n"] >= 0


# ---------------------------------------------------------------------------
# leak sentinel
# ---------------------------------------------------------------------------


def test_leak_sentinel_flags_retaining_loop_and_stays_green(tmp_path):
    log = _events_to(tmp_path)
    retained = []
    with obs_events.bound(log):
        s = mem.LeakSentinel(window=3, scope="test")
        fired = None
        for i in range(8):
            # the injected leak: one more live (97,) array per boundary
            retained.append(jnp.zeros((97,), jnp.float32) + i)
            fired = fired or s.observe(step=i)
        assert fired is not None
        assert any(sus["shape_class"] == "float32[97]"
                   for sus in fired["suspects"])

        # steady state: allocate-and-drop churn of the same class — counts
        # do not grow monotonically, the sentinel stays green
        s2 = mem.LeakSentinel(window=3, scope="steady")
        for i in range(8):
            _ = jnp.zeros((55,), jnp.float32) + i  # dropped immediately
            assert s2.observe(step=i) is None
    log.close()
    _, evs = replay_events(log.path)
    leaks = [e for e in evs if e["event"] == "memory_leak_suspect"]
    assert leaks and leaks[0]["scope"] == "test"
    assert all(e["scope"] != "steady" for e in leaks)


def test_leak_sentinel_rearms_after_firing():
    retained = []
    s = mem.LeakSentinel(window=2, scope="t")
    fires = 0
    for i in range(12):
        retained.append(jnp.zeros((41,), jnp.float32) + i)
        if s.observe(step=i):
            fires += 1
    # window resets after each event: one fire per full window, not per step
    assert 1 <= fires <= 4


# ---------------------------------------------------------------------------
# OOM postmortem
# ---------------------------------------------------------------------------


def test_report_oom_classifies_and_dedupes(tmp_path):
    log = _events_to(tmp_path)
    mem.record_program("serve_batch", "32x32-32x32xb1",
                       analysis=FAKE_ANALYSIS, device_kind="cpu")
    with obs_events.bound(log):
        exc = faults.InjectedDeviceError(
            "RESOURCE_EXHAUSTED: out of memory allocating 56000000 bytes")
        assert mem.report_oom(exc, program="serve_batch", scope="serving")
        # the demote-retrace seam sees the SAME exception: no second event
        assert not mem.report_oom(exc, scope="demote_retrace")
        # a non-OOM device error is not a memory failure
        assert not mem.report_oom(
            faults.InjectedDeviceError("tunnel reset"), scope="serving")
        # bare "oom" is word-bounded: an IO error naming reading_room_3.mat
        # must not render as an OOM postmortem
        assert not mem.is_oom(
            OSError("no such file: /data/reading_room_3.mat"))
        assert mem.is_oom(faults.InjectedDeviceError("HBM OOM on core 0"))
    log.close()
    _, evs = replay_events(log.path)
    pm = [e for e in evs if e["event"] == "memory_postmortem"]
    assert len(pm) == 1
    assert pm[0]["program"] == "serve_batch"
    assert pm[0]["kind"] == "oom"
    assert "RESOURCE_EXHAUSTED" in pm[0]["error"]
    # the bundle: ledger rows naming the failed program + the census
    assert pm[0]["ledger"] and \
        pm[0]["ledger"][0]["program"] == "serve_batch"
    assert pm[0]["census"]["n"] >= 0


class _OOMEngine:
    """FakeEngine whose FIRST dispatch dies with a RESOURCE_EXHAUSTED-
    shaped runtime device error; subsequent dispatches serve normally."""

    split = staticmethod(BatchMatchEngine.split)
    half_precision = False

    def __init__(self):
        self.dispatches = 0
        self.retraces = 0

    def dispatch(self, src, tgt):
        self.dispatches += 1
        if self.dispatches == 1:
            raise faults.InjectedDeviceError(
                "RESOURCE_EXHAUSTED: out of memory while allocating the "
                "correlation volume")
        return src.shape[0]

    def fetch(self, handle):
        table = np.zeros((handle, 6, 16), np.float32)
        table[:, 4, :] = 1.0
        return table

    def retrace(self):
        self.retraces += 1


def test_serving_oom_emits_exactly_one_postmortem(tmp_path):
    mem.record_program("serve_batch", "32x32-32x32xb1",
                       analysis=FAKE_ANALYSIS, device_kind="cpu")
    log = _events_to(tmp_path)
    with obs_events.bound(log):
        engine = _OOMEngine()
        svc = MatchService(engine=engine, serving=ServingConfig(
            bucket_multiple=32, max_image_side=64, max_batch=2))
        with svc:
            r = svc.submit(u8(), u8(seed=1)).result(timeout=30.0)
            assert r.table.shape[0] == 5  # served after the free retry
    log.close()
    _, evs = replay_events(log.path)
    pm = [e for e in evs if e["event"] == "memory_postmortem"]
    # the failure crossed BOTH seams (the serving failure handler and the
    # demote-retrace recovery) — still exactly one postmortem
    assert len(pm) == 1
    assert pm[0]["program"] == "serve_batch"
    assert pm[0]["scope"] == "serving"
    assert pm[0]["replica"] == "rep0"
    assert any(r["program"] == "serve_batch" for r in pm[0]["ledger"])
    # the non-memory accounting is untouched: the request still resolved
    results = [e for e in evs if e["event"] == "serve_result"]
    assert len(results) == 1


# ---------------------------------------------------------------------------
# serving plane: warmed REAL ladder -> ledger events + /metrics gauges
# ---------------------------------------------------------------------------


def test_warmed_ladder_ledger_and_metrics_scrape(tmp_path, monkeypatch,
                                                 tiny_params):
    import urllib.request

    monkeypatch.setenv(mem.LEDGER_ENV, str(tmp_path / "ledger.json"))
    mem._reset_state()
    log = _events_to(tmp_path)
    with obs_events.bound(log):
        svc = MatchService(TINY, tiny_params, ServingConfig(
            bucket_multiple=32, max_image_side=64, max_batch=2,
            warm_buckets=((32, 32),), introspect_port=0))
        svc.start()
        t0 = time.monotonic()
        while svc.state == "STARTING" and time.monotonic() - t0 < 180:
            time.sleep(0.05)
        assert svc.state == "READY"
        url = svc.introspect_url
        txt = urllib.request.urlopen(url + "/metrics",
                                     timeout=30).read().decode()
        doc = svc.health()
        statusz = urllib.request.urlopen(url + "/statusz",
                                         timeout=30).read().decode()
        svc.stop()
    log.close()

    # every warmed bucket program (bucket x each ladder batch size) has a
    # memory_ledger event
    _, evs = replay_events(log.path)
    led = [e for e in evs if e["event"] == "memory_ledger"
           and e["program"] == "serve_batch"]
    assert {e["shape_class"] for e in led} == {
        "32x32-32x32xb1", "32x32-32x32xb2"}

    # /metrics exposes the predicted-footprint gauge (CPU: no hbm_bytes
    # series, but the ledger-driven gauge still renders)
    assert "ncnet_serve_hbm_predicted_ladder_bytes" in txt
    predicted = mem.predicted_footprint_bytes(program="serve_batch")
    assert predicted is not None and predicted > 0
    line = next(l for l in txt.splitlines()
                if l.startswith("ncnet_serve_hbm_predicted_ladder_bytes"))
    assert int(line.split()[-1]) == predicted

    # the health document carries the same memory section
    assert doc["memory"]["predicted_ladder_bytes"] == predicted
    assert doc["memory"]["ledger_programs"] == 2
    assert "memory: predicted ladder" in statusz

    # device_snapshot now flows from the serving worker tick too
    assert any(e["event"] == "device_snapshot" for e in evs)

    # run_report --memory replays all of it from the event log alone
    report = run_report.build_report([log.path])
    assert len(report["memory"]["ledger"]) == 2
    text = run_report.render_memory(report)
    assert "compiled-program ledger" in text
    assert "serve_batch" in text
    assert run_report.main([log.path, "--memory"]) == 0


def test_hbm_gauges_render_when_stats_exist(tmp_path):
    # the TPU-shaped path, driven with injected stats (CPU exposes none):
    # per-replica hbm gauges + fill % + headroom vs the predicted ladder
    from ncnet_tpu.serving.introspect import metrics_families, render_statusz

    mem.record_program("serve_batch", "x", analysis=FAKE_ANALYSIS,
                       device_kind="cpu")
    svc = MatchService(engine=_OOMEngine(), serving=ServingConfig(
        bucket_multiple=32, max_image_side=64))
    svc._hbm["rep0"] = {"device": 0, "bytes_in_use": 6 << 20,
                        "peak_bytes_in_use": 8 << 20,
                        "bytes_limit": 16 << 20,
                        "bytes_reserved": 1 << 20,
                        "largest_free_block_bytes": 4 << 20,
                        "fill_pct": 37.5}
    fams = {f.name: f for f in metrics_families(svc)}
    assert fams["ncnet_serve_hbm_bytes"].samples
    labels = {(s[1].get("replica"), s[1].get("stat"))
              for s in fams["ncnet_serve_hbm_bytes"].samples}
    assert ("rep0", "bytes_in_use") in labels
    assert ("rep0", "largest_free_block_bytes") in labels
    fill = fams["ncnet_serve_hbm_fill_pct"].samples[0]
    assert fill[2] == 37.5
    predicted = 4096 + 200
    head = fams["ncnet_serve_hbm_headroom_bytes"].samples[0][2]
    assert head == (16 << 20) - predicted
    sz = render_statusz(svc)
    assert "37.5" in sz and "headroom" in sz


def test_stall_watchdog_hbm_warning_is_not_a_stall():
    verdict = {"status": "alive"}
    doc = {"memory": {"hbm": {
        "rep0": {"fill_pct": 95.0, "bytes_in_use": 15, "bytes_limit": 16},
        "rep1": {"fill_pct": 20.0},
    }}}
    stall_watchdog._apply_hbm_warning(verdict, doc, 90.0)
    assert verdict["status"] == "alive"  # pressure is never a stall
    assert list(verdict["hbm_warning"]["replicas"]) == ["rep0"]
    # below threshold / no memory section: no warning key at all
    v2 = {"status": "alive"}
    stall_watchdog._apply_hbm_warning(v2, {}, 90.0)
    assert "hbm_warning" not in v2


# ---------------------------------------------------------------------------
# run_report --memory on a synthetic log (leaks + postmortems + trajectory)
# ---------------------------------------------------------------------------


def test_run_report_memory_full_rendering(tmp_path, capsys):
    path = str(tmp_path / "events.jsonl")
    with EventLog(path) as log, obs_events.bound(log):
        mem.record_program("train_step", "sig", analysis=FAKE_ANALYSIS,
                           device_kind="TPU v5 lite", tier="resident_vjp")
        obs_events.emit("device_snapshot", devices=[
            {"id": 0, "kind": "TPU v5 lite", "platform": "tpu",
             "bytes_in_use": 100 << 20, "peak_bytes_in_use": 200 << 20,
             "bytes_limit": 16 << 30, "bytes_reserved": 0,
             "largest_free_block_bytes": 8 << 30}])
        obs_events.emit("memory_leak_suspect", scope="serving", window=4,
                        suspects=[{"shape_class": "float32[97]",
                                   "n_first": 1, "n_last": 5,
                                   "bytes_first": 388, "bytes_last": 1940,
                                   "growth_bytes": 1552}],
                        live_n=10, live_bytes=4096)
        exc = faults.InjectedDeviceError("RESOURCE_EXHAUSTED: oom")
        mem.report_oom(exc, program="train_step", scope="demote_retrace")

    report = run_report.build_report([path])
    m = report["memory"]
    assert m["ledger"][0]["program"] == "train_step"
    assert m["hbm_trajectory"][0]["bytes_in_use"] == 100 << 20
    assert m["leak_suspects"][0]["suspects"][0]["shape_class"] \
        == "float32[97]"
    assert m["postmortems"][0]["program"] == "train_step"

    text = run_report.render_memory(report)
    assert "LEAK SUSPECTS" in text
    assert "OOM POSTMORTEMS" in text
    assert "float32[97]" in text
    assert "HBM trajectory" in text

    assert run_report.main([path, "--memory"]) == 0
    out = capsys.readouterr().out
    assert "OOM POSTMORTEMS" in out
    # and --json carries the section as data
    assert run_report.main([path, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["memory"]["postmortems"]


# ---------------------------------------------------------------------------
# perf store: memory series gate lower-is-better
# ---------------------------------------------------------------------------


def test_memory_metrics_gate_like_walls(tmp_path, monkeypatch):
    from ncnet_tpu.observability.perfstore import (
        PerfStore, check_regressions, metric_direction)

    for name in ("mem_forward_temp_bytes", "mem_filter_temp_bytes",
                 "mem_peak_hbm_bytes"):
        assert metric_direction(name) == "lower"

    store_path = str(tmp_path / "history.jsonl")
    store = PerfStore(store_path)
    for v in (1000.0, 1010.0, 990.0, 1005.0):
        store.append("mem_forward_temp_bytes", v, device_kind="TPU v5 lite")
    findings = check_regressions(store.records())
    f = next(x for x in findings if x["metric"] == "mem_forward_temp_bytes")
    assert f["status"] == "ok"  # the seeded series is green

    # injected 2x temp_bytes regression: perf_regress --check exits 1
    store.append("mem_forward_temp_bytes", 2000.0,
                 device_kind="TPU v5 lite")
    assert perf_regress.main(["--check", "--store", store_path]) == 1
    findings = check_regressions(store.records())
    f = next(x for x in findings if x["metric"] == "mem_forward_temp_bytes")
    assert f["status"] == "regression"


# ---------------------------------------------------------------------------
# ResilientJit ledger seam (the train_step / point_matcher path)
# ---------------------------------------------------------------------------


def test_resilient_jit_records_one_row_per_shape(tmp_path, monkeypatch):
    from ncnet_tpu.models.ncnet import ResilientJit

    monkeypatch.setenv(mem.LEDGER_ENV, str(tmp_path / "ledger.json"))
    mem._reset_state()
    log = _events_to(tmp_path)
    with obs_events.bound(log):
        jitted = ResilientJit(lambda x: x * 2, label="t",
                              ledger_program="unit_prog")
        jitted(jnp.ones((4, 4)))
        jitted(jnp.ones((4, 4)))      # same shape: no second row
        jitted(jnp.ones((8, 4)))      # new shape class: second row
        # the analysis compile runs OFF the dispatch thread: join it
        # before asserting on the emitted events
        mem.flush_pending(timeout=60.0)
    log.close()
    _, evs = replay_events(log.path)
    led = [e for e in evs if e["event"] == "memory_ledger"]
    assert len(led) == 2
    assert {e["shape_class"] for e in led} == {
        "float32[4x4]", "float32[8x4]"}
    assert all(e["program"] == "unit_prog" for e in led)

    # the off switch skips the analysis compile entirely
    monkeypatch.setenv(mem.LEDGER_ENV, "off")
    mem._reset_state()
    log2 = _events_to(tmp_path, "events2.jsonl")
    with obs_events.bound(log2):
        j2 = ResilientJit(lambda x: x + 1, label="t2",
                          ledger_program="unit_prog2")
        j2(jnp.ones((3,)))
        mem.flush_pending(timeout=60.0)
    log2.close()
    _, evs2 = replay_events(log2.path)
    assert not [e for e in evs2 if e["event"] == "memory_ledger"]
