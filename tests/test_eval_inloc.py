"""InLoc evaluation path: quantized resize, dedup, .mat writer, e2e loop.

Oracle: the reference recipe (/root/reference/eval_inloc.py) re-derived in
plain numpy on tiny synthetic data — see each test's docstring.
"""

import math
import os

import jax.numpy as jnp
import numpy as np
import pytest
from scipy.io import loadmat

from ncnet_tpu.config import EvalInLocConfig, ModelConfig
from ncnet_tpu.data.synthetic import write_inloc_like
from ncnet_tpu.evaluation.inloc import (
    _as_str,
    load_shortlist,
    match_capacity,
    output_folder_name,
    quantized_resize_shape,
    recenter,
    run_inloc_eval,
    sort_and_dedup,
)
from ncnet_tpu.models.ncnet import init_ncnet

import jax


@pytest.mark.parametrize(
    "h,w,image_size,k",
    [(3024, 4032, 3200, 2), (4032, 3024, 3200, 2), (480, 640, 3200, 1),
     (96, 128, 128, 2), (1000, 1500, 1600, 2)],
)
def test_quantized_resize_shape_matches_reference_formula(h, w, image_size, k):
    """Reference formula (eval_inloc.py:83-89): scale the longest side to
    image_size, then (k>1) floor each dim to a multiple of 16·k."""
    scale = np.max([h, w]) / image_size
    if k == 1:
        expected = (int(h / scale), int(w / scale))
    else:
        sf = 0.0625
        expected = (
            int(np.floor(h / scale * sf / k) / sf * k),
            int(np.floor(w / scale * sf / k) / sf * k),
        )
    got = quantized_resize_shape(h, w, image_size, k)
    assert got == expected
    if k > 1:
        assert got[0] % (16 * k) == 0 and got[1] % (16 * k) == 0


def test_match_capacity_reference_values():
    """eval_inloc.py:116-118 at the published settings: 3200px, k=2, both
    directions → 2 · 100 · 75 = 15000 rows."""
    assert match_capacity(3200, 2, both_directions=True) == 15000
    assert match_capacity(3200, 2, both_directions=False) == 7500
    assert match_capacity(3200, 1, both_directions=True) == 2 * 200 * 150


def test_recenter_maps_endpoints_to_cell_centers():
    """x·(n−1)/n + 0.5/n sends 0 → half-cell and 1 → 1 − half-cell
    (eval_inloc.py:179-189)."""
    import jax.numpy as jnp

    n = 8
    ends = recenter(jnp.asarray([0.0, 1.0]), n)
    np.testing.assert_allclose(np.asarray(ends), [0.5 / n, 1 - 0.5 / n], atol=1e-6)


def test_sort_and_dedup_keeps_max_score_instance():
    """Duplicates of a coordinate row must collapse to the highest-scoring
    copy; output follows np.unique's lexicographic column order
    (eval_inloc.py:159-173)."""
    xa = np.array([0.1, 0.5, 0.1, 0.9], dtype=np.float32)
    ya = np.array([0.2, 0.5, 0.2, 0.9], dtype=np.float32)
    xb = np.array([0.3, 0.5, 0.3, 0.9], dtype=np.float32)
    yb = np.array([0.4, 0.5, 0.4, 0.9], dtype=np.float32)
    score = np.array([0.7, 0.2, 0.9, 0.5], dtype=np.float32)
    oxa, oya, oxb, oyb, oscore = sort_and_dedup(xa, ya, xb, yb, score)
    assert len(oxa) == 3
    # the duplicated (0.1,0.2,0.3,0.4) row keeps score 0.9 (not 0.7)
    i = int(np.argmin(np.abs(oxa - 0.1)))
    assert oscore[i] == pytest.approx(0.9)
    # no duplicate coordinate rows remain
    coords = np.stack([oxa, oya, oxb, oyb])
    assert np.unique(coords, axis=1).shape[1] == coords.shape[1]


def test_shortlist_roundtrip(tmp_path):
    shortlist = write_inloc_like(str(tmp_path), n_queries=2, n_panos=3)
    query_fns, pano_fns = load_shortlist(shortlist)
    assert query_fns == ["query_0.jpg", "query_1.jpg"]
    assert [len(p) for p in pano_fns] == [3, 3]
    assert _as_str(pano_fns[0][0]) == "DUC1/DUC_cutout_000_0_0.jpg"
    assert _as_str(pano_fns[1][2]) == "DUC1/DUC_cutout_001_60_0.jpg"


def test_output_folder_name_encodes_settings():
    cfg = EvalInLocConfig(inloc_shortlist="x/shortlist.mat", image_size=3200,
                          k_size=2)
    name = output_folder_name(cfg)
    assert name == "shortlist_SZ_NEW_3200_K_2_BOTHDIRS_SOFTMAX"
    cfg2 = EvalInLocConfig(inloc_shortlist="shortlist.mat", softmax=False,
                           matching_both_directions=False,
                           flip_matching_direction=True,
                           image_size=1600, k_size=1, checkpoint="m/best.pth.tar")
    assert output_folder_name(cfg2) == "shortlist_SZ_NEW_1600_K_1_AtoB_CHECKPOINT_best"


def _identity_nc_params(model_config, key):
    """Params whose single NC layer is an identity-peaked 3⁴ kernel, so the
    filtered volume preserves the raw correlation's argmax structure."""
    params = init_ncnet(model_config, key)
    w = np.zeros_like(np.asarray(params["nc"][0]["w"]))
    w[1, 1, 1, 1, 0, 0] = 1.0
    params["nc"][0]["w"] = w
    params["nc"][0]["b"] = np.zeros_like(np.asarray(params["nc"][0]["b"]))
    return params


def test_run_inloc_eval_end_to_end(tmp_path):
    """Full loop on a synthetic shortlist: per-query .mat files appear with
    the reference's fixed-capacity layout; the self-match pano (pano 0 is the
    query image itself) yields near-identity correspondences."""
    root = str(tmp_path)
    shortlist = write_inloc_like(root, n_queries=2, n_panos=2, image_hw=(96, 128))
    model_config = ModelConfig(
        backbone="tiny",
        ncons_kernel_sizes=(3,),
        ncons_channels=(1,),
        half_precision=True,
        relocalization_k_size=2,
    )
    params = _identity_nc_params(model_config, jax.random.key(0))
    config = EvalInLocConfig(
        inloc_shortlist=shortlist,
        k_size=2,
        image_size=128,
        n_queries=2,
        n_panos=2,
        pano_path=os.path.join(root, "pano"),
        query_path=os.path.join(root, "query", "iphone7"),
        output_root=os.path.join(root, "matches"),
    )
    out_dir = run_inloc_eval(config, model_config=model_config, params=params,
                             progress=False)

    n_cap = match_capacity(128, 2, both_directions=True)
    for q in (1, 2):
        path = os.path.join(out_dir, f"{q}.mat")
        assert os.path.exists(path)
        mat = loadmat(path)
        assert mat["matches"].shape == (1, 2, n_cap, 5)
        m = mat["matches"][0, 0]  # self-match pano
        valid = m[m[:, 4] > 0]
        assert len(valid) > 0
        # coords are recentered into (0, 1)
        assert np.all(valid[:, :4] > 0) and np.all(valid[:, :4] < 1)
        # self-match: best-scoring rows map each cell ~onto itself.  96×128 →
        # fine grid 6×8, pooled 3×4; one fine cell pitch is 1/8 ≤ axis.
        top = valid[np.argsort(-valid[:, 4])][: len(valid) // 2]
        assert np.all(np.abs(top[:, 0] - top[:, 2]) <= 1 / 8 + 1e-6)
        assert np.all(np.abs(top[:, 1] - top[:, 3]) <= 1 / 6 + 1e-6)
        assert _as_str(mat["query_fn"]) == f"query_{q - 1}.jpg"


def test_run_inloc_eval_host_striping(tmp_path):
    """Multi-host query striping: two 'hosts' over the same output dir write
    disjoint per-query files whose union is the full set, matching a
    single-host run byte-for-byte."""
    root = str(tmp_path)
    shortlist = write_inloc_like(root, n_queries=3, n_panos=1, image_hw=(96, 128))
    model_config = ModelConfig(
        backbone="tiny", ncons_kernel_sizes=(3,), ncons_channels=(1,),
        half_precision=True, relocalization_k_size=2,
    )
    params = _identity_nc_params(model_config, jax.random.key(0))
    kw = dict(
        inloc_shortlist=shortlist, k_size=2, image_size=128,
        n_queries=3, n_panos=1,
        pano_path=os.path.join(root, "pano"),
        query_path=os.path.join(root, "query", "iphone7"),
    )
    single = run_inloc_eval(
        EvalInLocConfig(output_root=os.path.join(root, "single"), **kw),
        model_config=model_config, params=params, progress=False)
    for host in (0, 1):
        striped = run_inloc_eval(
            EvalInLocConfig(output_root=os.path.join(root, "striped"),
                            host_index=host, host_count=2, **kw),
            model_config=model_config, params=params, progress=False)
    def mats(d):
        # the run manifests (manifest*.json, per host stripe) live beside
        # the artifacts; only the .mat set must match
        return sorted(n for n in os.listdir(d) if n.endswith(".mat"))

    names = mats(striped)
    assert names == ["1.mat", "2.mat", "3.mat"] == mats(single)
    for n in names:
        a = loadmat(os.path.join(single, n))["matches"]
        b = loadmat(os.path.join(striped, n))["matches"]
        np.testing.assert_array_equal(a, b)


def test_host_striping_validation(tmp_path):
    """Incoherent stripes (index without count, index ≥ count) must fail loudly
    instead of silently dropping or duplicating queries."""
    root = str(tmp_path)
    shortlist = write_inloc_like(root, n_queries=1, n_panos=1, image_hw=(96, 128))
    model_config = ModelConfig(
        backbone="tiny", ncons_kernel_sizes=(3,), ncons_channels=(1,),
        half_precision=True, relocalization_k_size=2,
    )
    params = _identity_nc_params(model_config, jax.random.key(0))
    kw = dict(
        inloc_shortlist=shortlist, k_size=2, image_size=128,
        n_queries=1, n_panos=1,
        pano_path=os.path.join(root, "pano"),
        query_path=os.path.join(root, "query", "iphone7"),
        output_root=os.path.join(root, "m"),
    )
    for bad in (dict(host_index=1), dict(host_index=3, host_count=2)):
        with pytest.raises(ValueError):
            run_inloc_eval(EvalInLocConfig(**kw, **bad),
                           model_config=model_config, params=params,
                           progress=False)


def test_skip_existing_resumes(tmp_path):
    """Resume-by-artifact: a second run leaves existing per-query .mat files
    untouched (their mtime does not change)."""
    root = str(tmp_path)
    shortlist = write_inloc_like(root, n_queries=1, n_panos=1, image_hw=(96, 128))
    model_config = ModelConfig(
        backbone="tiny", ncons_kernel_sizes=(3,), ncons_channels=(1,),
        half_precision=True, relocalization_k_size=2,
    )
    params = _identity_nc_params(model_config, jax.random.key(0))
    config = EvalInLocConfig(
        inloc_shortlist=shortlist, k_size=2, image_size=128,
        n_queries=1, n_panos=1,
        pano_path=os.path.join(root, "pano"),
        query_path=os.path.join(root, "query", "iphone7"),
        output_root=os.path.join(root, "m"),
    )
    out_dir = run_inloc_eval(config, model_config=model_config, params=params,
                             progress=False)
    path = os.path.join(out_dir, "1.mat")
    mtime = os.path.getmtime(path)
    run_inloc_eval(config, model_config=model_config, params=params,
                   progress=False)
    assert os.path.getmtime(path) == mtime


def test_run_inloc_eval_zero_panos_writes_empty_table(tmp_path):
    """n_panos=0 (or an empty shortlist row) must still write the query's
    all-zeros table instead of crashing the run."""
    root = str(tmp_path)
    shortlist = write_inloc_like(root, n_queries=1, n_panos=2, image_hw=(96, 128))
    model_config = ModelConfig(
        backbone="tiny", ncons_kernel_sizes=(3,), ncons_channels=(1,),
        half_precision=True, relocalization_k_size=2,
    )
    params = _identity_nc_params(model_config, jax.random.key(0))
    config = EvalInLocConfig(
        inloc_shortlist=shortlist, k_size=2, image_size=128,
        n_queries=1, n_panos=0,
        pano_path=os.path.join(root, "pano"),
        query_path=os.path.join(root, "query", "iphone7"),
        output_root=os.path.join(root, "matches"),
    )
    out_dir = run_inloc_eval(config, model_config=model_config, params=params,
                             progress=False)
    mat = loadmat(os.path.join(out_dir, "1.mat"))
    assert mat["matches"].shape[1] == 0 or np.all(mat["matches"] == 0)


def test_run_inloc_eval_single_direction(tmp_path):
    """flip/single-direction modes produce half-capacity tables."""
    root = str(tmp_path)
    shortlist = write_inloc_like(root, n_queries=1, n_panos=1, image_hw=(96, 128))
    model_config = ModelConfig(
        backbone="tiny", ncons_kernel_sizes=(3,), ncons_channels=(1,),
        relocalization_k_size=2,
    )
    params = init_ncnet(model_config, jax.random.key(0))
    config = EvalInLocConfig(
        inloc_shortlist=shortlist, k_size=2, image_size=128,
        n_queries=1, n_panos=1,
        matching_both_directions=False, flip_matching_direction=True,
        pano_path=os.path.join(root, "pano"),
        query_path=os.path.join(root, "query", "iphone7"),
        output_root=os.path.join(root, "matches"),
    )
    out_dir = run_inloc_eval(config, model_config=model_config, params=params,
                             progress=False)
    mat = loadmat(os.path.join(out_dir, "1.mat"))
    assert mat["matches"].shape == (1, 1, match_capacity(128, 2, False), 5)


def test_run_inloc_eval_spatial_shards_parity(tmp_path):
    """spatial_shards=2 must write byte-identical match tables to the
    single-device run (the sharded forward is numerics-parity-tested in
    test_spatial.py; this checks the end-to-end wiring + fallback logic)."""
    root = str(tmp_path)
    # 128x128 → fine grid 8x8 (divisible by n_shards*k = 4) → sharded path;
    shortlist = write_inloc_like(root, n_queries=1, n_panos=2, image_hw=(128, 128))
    model_config = ModelConfig(
        backbone="tiny", ncons_kernel_sizes=(3,), ncons_channels=(1,),
        relocalization_k_size=2,
    )
    params = init_ncnet(model_config, jax.random.key(0))
    kw = dict(
        inloc_shortlist=shortlist, k_size=2, image_size=128,
        n_queries=1, n_panos=2,
        pano_path=os.path.join(root, "pano"),
        query_path=os.path.join(root, "query", "iphone7"),
    )
    out_plain = run_inloc_eval(
        EvalInLocConfig(output_root=os.path.join(root, "m1"), **kw),
        model_config=model_config, params=params, progress=False)
    out_sharded = run_inloc_eval(
        EvalInLocConfig(output_root=os.path.join(root, "m2"), spatial_shards=2, **kw),
        model_config=model_config, params=params, progress=False)
    m1 = loadmat(os.path.join(out_plain, "1.mat"))["matches"]
    m2 = loadmat(os.path.join(out_sharded, "1.mat"))["matches"]
    np.testing.assert_allclose(m2, m1, rtol=1e-5, atol=1e-6)


def test_device_preprocess_matches_host_path(tmp_path):
    """The jitted uint8→normalize→resize path must reproduce the host-side
    load_and_preprocess (same normalize-then-resize order, same align-corners
    resize) — it replaces it in the eval loop to cut host→device traffic."""
    from PIL import Image

    from ncnet_tpu.evaluation.inloc import (
        device_preprocess,
        load_and_preprocess,
        load_raw,
    )

    rng = np.random.default_rng(0)
    img = rng.integers(0, 256, (60, 80, 3), dtype=np.uint8)
    path = os.path.join(str(tmp_path), "img.png")  # lossless: exact parity
    Image.fromarray(img).save(path)

    host = load_and_preprocess(path, image_size=64, k_size=2)
    dev = np.asarray(device_preprocess(
        jnp.asarray(load_raw(path)), image_size=64, k_size=2))
    assert host.shape == dev.shape
    # the two paths round differently (independent compilations; numpy scalar
    # promotion in the host normalize): ~3e-5 skew through the 1/std scaling
    # is expected, while a formula or resize-order error would be orders of
    # magnitude larger
    np.testing.assert_allclose(dev, host, rtol=1e-4, atol=1e-4)


def test_prepared_query_features_path_bit_identical():
    """matcher.preprocess returns a PreparedQuery whose cached-trunk fast
    path must produce BIT-identical match tables to the image path (the
    query features are the same extract_features output either way)."""
    from ncnet_tpu.evaluation.inloc import PreparedQuery, make_pair_matcher
    from ncnet_tpu.models.ncnet import init_ncnet

    cfg = ModelConfig(
        backbone="tiny", ncons_kernel_sizes=(3,), ncons_channels=(1,),
        half_precision=True, relocalization_k_size=2,
    )
    params = init_ncnet(cfg, jax.random.key(0))
    matcher = make_pair_matcher(
        cfg, params, do_softmax=True, both_directions=True,
        flip_direction=False, preprocess_image_size=128,
    )
    rng = np.random.default_rng(3)
    q = rng.integers(0, 255, (1, 96, 128, 3), dtype=np.uint8)
    db = rng.integers(0, 255, (1, 128, 96, 3), dtype=np.uint8)

    prepared = matcher.preprocess(q)
    assert isinstance(prepared, PreparedQuery)
    fast = matcher(prepared, db)
    # image path: hand the preprocessed image (trunk recomputed in-program)
    slow = matcher(np.asarray(prepared.image), db)
    for a, b in zip(fast, slow):
        np.testing.assert_array_equal(a, b)


def test_pipeline_depth_controller_adapts(monkeypatch):
    """Latency-regime adaptation: deepen past 2 only when the per-pair wall
    EWMA shows dispatch latency dominating AND the deepen measurably helps;
    return to 2 when the tunnel recovers; gaps excluded; never adapt when
    pinned."""
    import ncnet_tpu.evaluation.inloc as inloc_mod
    import ncnet_tpu.evaluation.pipeline as pipeline_mod

    now = [0.0]
    monkeypatch.setattr(pipeline_mod.time, "perf_counter", lambda: now[0])

    ctl = inloc_mod._PipelineDepthController(0, high=0.7, low=0.45)
    assert ctl.depth == 2

    def drain_every(dt, n):
        for _ in range(n):
            now[0] += dt
            ctl.note_drain()

    ctl.note_drain()            # first drain: no interval yet
    drain_every(1.0, 4)         # high-latency regime: probe-deepen at the 4th
    assert ctl.depth == 3
    drain_every(0.55, 5)        # anchor + 4 samples; the deepen improved the
    assert ctl.depth == 3       # wall >15%, so the probe is confirmed
    drain_every(0.25, 1)        # tunnel recovered: EWMA crosses low
    assert ctl.depth == 2

    # a depth change resets the interval anchor (ADVICE r4): the first
    # post-change drain re-anchors instead of recording a refill-spanning
    # interval, so a fresh deepen needs 1 anchor + 4 samples again
    assert ctl._t_last is None

    # gap exclusion must hold with a live EWMA: re-anchor, record real
    # samples, then verify a 100 s inter-query gap does not enter the EWMA
    drain_every(0.3, 3)
    assert ctl._ewma == pytest.approx(0.3)
    ctl.note_gap()
    now[0] += 100.0
    ctl.note_drain()
    assert ctl._ewma == pytest.approx(0.3)

    pinned = inloc_mod._PipelineDepthController(3)
    for _ in range(20):
        now[0] += 5.0
        pinned.note_drain()
    assert pinned.depth == 3


def test_pipeline_depth_controller_derived_thresholds(monkeypatch):
    """With no explicit high/low, the thresholds derive from the windowed
    minimum wall (a measured device-compute estimate): 0.35 s steady-state
    walls set best=0.35, so 1.0 s walls (2.9x best) probe-deepen, an
    improved wall confirms the probe, and recovery to ~best shrinks back."""
    import ncnet_tpu.evaluation.inloc as inloc_mod
    import ncnet_tpu.evaluation.pipeline as pipeline_mod

    now = [0.0]
    monkeypatch.setattr(pipeline_mod.time, "perf_counter", lambda: now[0])

    ctl = inloc_mod._PipelineDepthController(0)
    assert ctl.depth == 2

    def drain_every(dt, n):
        for _ in range(n):
            now[0] += dt
            ctl.note_drain()

    ctl.note_drain()
    drain_every(0.35, 8)        # steady state: establishes best == 0.35
    assert ctl.depth == 2       # 0.35 < 1.3*0.35 — no spurious deepen
    assert ctl.best == pytest.approx(0.35)
    drain_every(1.0, 2)         # latency spike: EWMA crosses 2x best
    assert ctl.depth == 3
    drain_every(0.5, 5)         # deepen helped (1.0 -> 0.5): probe confirmed
    assert ctl.depth == 3
    drain_every(0.3, 2)         # recovery to ~best shrinks back
    assert ctl.depth == 2

    with pytest.raises(ValueError):
        inloc_mod._PipelineDepthController(-1)


def test_pipeline_depth_controller_cold_start_and_outlier(monkeypatch):
    """The two failure modes of pure min-ratio thresholds are bounded:
    (a) a run that COLD-STARTS in a high-latency regime still deepens (the
    fixed 0.7 s cap triggers even though every wall inflates the minimum);
    (b) one anomalously short wall causes at most one speculative probe —
    it cannot pin depth 4 for the whole run."""
    import ncnet_tpu.evaluation.inloc as inloc_mod
    import ncnet_tpu.evaluation.pipeline as pipeline_mod

    now = [0.0]
    monkeypatch.setattr(pipeline_mod.time, "perf_counter", lambda: now[0])

    # (a) cold start at 0.99 s/pair (the r3 high-latency day): best == 0.99
    # so 2*best never triggers, but the 0.7 cap does
    ctl = inloc_mod._PipelineDepthController(0)
    ctl.note_drain()
    for _ in range(5):
        now[0] += 0.99
        ctl.note_drain()
    assert ctl.depth >= 3

    # (b) steady 0.35 walls, then a single 0.05 outlier: the collapsed
    # thresholds trigger a probe-deepen, the unchanged wall refutes it, and
    # the controller reverts and blocks further deepens in this regime
    ctl = inloc_mod._PipelineDepthController(0)
    ctl.note_drain()
    for _ in range(6):
        now[0] += 0.35
        ctl.note_drain()
    assert ctl.depth == 2
    now[0] += 0.05
    ctl.note_drain()            # the outlier
    seen = set()
    for _ in range(24):
        now[0] += 0.35
        ctl.note_drain()
        seen.add(ctl.depth)
    assert ctl.depth == 2       # reverted: the probe did not help
    assert max(seen) == 3       # exactly one speculative step, never 4
    assert ctl.best == pytest.approx(0.35)


def test_pipeline_depth_controller_compute_bound_probe(monkeypatch):
    """A rig whose genuine device compute exceeds the 0.7 s cap is NOT
    pinned at depth 4: the speculative deepen measures no improvement,
    reverts, and blocks until the EWMA leaves that regime — at which point
    a genuinely worse (latency) regime may probe again."""
    import ncnet_tpu.evaluation.inloc as inloc_mod
    import ncnet_tpu.evaluation.pipeline as pipeline_mod

    now = [0.0]
    monkeypatch.setattr(pipeline_mod.time, "perf_counter", lambda: now[0])

    ctl = inloc_mod._PipelineDepthController(0)
    ctl.note_drain()
    seen = set()
    for _ in range(40):         # compute-bound: 0.9 s walls at ANY depth
        now[0] += 0.9
        ctl.note_drain()
        seen.add(ctl.depth)
    assert ctl.depth == 2       # settled back at the memory-cheap depth
    assert max(seen) == 3       # one probe, then blocked — never reached 4

    seen2 = set()
    for _ in range(10):         # regime worsens well past the failed probe:
        now[0] += 2.0           # the block lifts and probing resumes
        ctl.note_drain()
        seen2.add(ctl.depth)
    assert 3 in seen2           # a fresh probe fired in the new regime
    # (the simulated clock gives the probe no improvement, so it honestly
    # reverts again — in a real latency regime the wall would drop and the
    # probe would be confirmed, as test_..._adapts exercises)


def test_pipeline_depth_controller_block_lifts_on_recovery(monkeypatch):
    """A failed probe from depth 2 must not disable deepening forever: once
    the EWMA recovers below ``low`` the block lifts, so a LATER genuine
    latency regime (above high but below 1.3x the old failed-probe wall)
    can probe again."""
    import ncnet_tpu.evaluation.inloc as inloc_mod
    import ncnet_tpu.evaluation.pipeline as pipeline_mod

    now = [0.0]
    monkeypatch.setattr(pipeline_mod.time, "perf_counter", lambda: now[0])

    ctl = inloc_mod._PipelineDepthController(0)
    ctl.note_drain()

    def drain_every(dt, n):
        for _ in range(n):
            now[0] += dt
            ctl.note_drain()

    drain_every(0.9, 14)        # compute-bound phase: probe fails, block=0.9
    assert ctl.depth == 2
    assert ctl._block is not None
    drain_every(0.3, 10)        # genuine recovery: EWMA < low lifts the block
    assert ctl._block is None
    seen = set()
    for _ in range(8):          # latency regime in the 0.7..1.17 dead band
        now[0] += 1.0
        ctl.note_drain()
        seen.add(ctl.depth)
    assert 3 in seen            # ...now probes again instead of staying pinned
