"""Arithmetic conv4d tiers (round 17): CP-decomposed and FFT stacks.

Three claims are locked here.  EXACTNESS: a rank-full CP factorization and
the spectral conv both equal dense conv4d to pinned fp32 tolerance on every
shape class the NC filter serves (square, rectangular, k=1, k=5).
CONVERSION: the HOSVD+ALS solver's error is monotone non-increasing in
rank, and recovers an exactly-low-rank kernel to float precision.
ROUTING: ``choose_fused_stack`` selects the tiers only where their
arithmetic gates predict a FLOP win (spy-counted compile probes), the
decisions persist in the tier cache keyed by CP rank, demotion walks
cp → fft → XLA, the forced path (``ModelConfig.nc_tier``) bypasses the
gates on both the dense and the folded-tile sparse pipelines, and quality
events carry the tier names.
"""

import importlib
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ncnet_tpu.ops.nc_fused_lane as lane
from ncnet_tpu.config import ModelConfig
from ncnet_tpu.models.ncnet import ncnet_filter, neigh_consensus
from ncnet_tpu.ops import tier_cache
from ncnet_tpu.ops.conv4d import conv4d
from ncnet_tpu.ops.cp_als import decompose_kernel, decompose_stack, \
    nested_decompose
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.observability.events import EventLog, replay_events

cp_mod = importlib.import_module("ncnet_tpu.ops.conv4d_cp")
fft_mod = importlib.import_module("ncnet_tpu.ops.conv4d_fft")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))


def xla_stack(params, x):
    for layer in params:
        x = jax.nn.relu(conv4d(x, layer["w"], layer["b"]))
    return x


def make_params(key, kernels, channels, dtype=jnp.float32):
    params, c_in = [], 1
    for k, c_out in zip(kernels, channels):
        k1, k2, key = jax.random.split(key, 3)
        params.append({
            "w": jax.random.normal(k1, (k,) * 4 + (c_in, c_out), dtype) * 0.1,
            "b": jax.random.normal(k2, (c_out,), dtype) * 0.1,
        })
        c_in = c_out
    return params


def rank1_params(key, kernels, channels):
    """NC params whose kernels are EXACT rank-1 CP tensors (built from the
    factors, so the attached "cp" entries reconstruct them to float
    precision) — the fixture for natural CP routing and sparse parity."""
    params, c_in = [], 1
    for k, c_out in zip(kernels, channels):
        keys = jax.random.split(key, 8)
        key = keys[7]
        cp = {
            "ka": jax.random.normal(keys[0], (k, 1)),
            "kwa": jax.random.normal(keys[1], (k, 1)),
            "kb": jax.random.normal(keys[2], (k, 1)),
            "kwb": jax.random.normal(keys[3], (k, 1)),
            "cin": jax.random.normal(keys[4], (c_in, 1)),
            "cout": jax.random.normal(keys[5], (1, c_out)) * 0.5,
        }
        params.append({
            "w": cp_mod.cp_reconstruct(cp),
            "b": jax.random.normal(keys[6], (c_out,)) * 0.1,
            "cp": cp,
        })
        c_in = c_out
    return params


# the four shape classes of the parity claim: square, rectangular, k=1, k=5
PARITY_SHAPES = [
    ((2, 6, 6, 6, 6), (3, 3), (3, 1)),
    ((1, 5, 6, 4, 7), (3,), (2,)),
    ((1, 5, 5, 5, 5), (1, 1), (3, 1)),
    ((1, 6, 6, 6, 6), (5,), (2,)),
]


def _normed_close(got, ref, atol):
    got = np.asarray(got, np.float32)
    ref = np.asarray(ref, np.float32)
    scale = max(1e-6, float(np.max(np.abs(ref))))
    np.testing.assert_allclose(got / scale, ref / scale, atol=atol)


# ---------------------------------------------------------------------------
# exactness: rank-full CP and FFT == dense conv4d (pinned fp32 tolerance)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("shape,kernels,channels", PARITY_SHAPES)
def test_rank_full_cp_stack_matches_dense(shape, kernels, channels):
    params = make_params(jax.random.key(0), kernels, channels)
    for layer in params:
        layer["cp"] = cp_mod.exact_cp_factors(layer["w"])
    x = jax.random.normal(jax.random.key(7), shape + (1,)) * 0.5
    _normed_close(cp_mod.nc_stack_cp(params, x), xla_stack(params, x),
                  atol=1e-5)


@pytest.mark.parametrize("shape,kernels,channels", PARITY_SHAPES)
def test_fft_stack_matches_dense(shape, kernels, channels):
    params = make_params(jax.random.key(1), kernels, channels)
    x = jax.random.normal(jax.random.key(8), shape + (1,)) * 0.5
    _normed_close(fft_mod.nc_stack_fft(params, x), xla_stack(params, x),
                  atol=1e-4)


def test_fft_single_layer_matches_conv4d_rectangular():
    """conv4d_fft alone (no ReLU chain) on a rectangular multi-channel
    volume: the crop arithmetic must hold per dim independently."""
    w = jax.random.normal(jax.random.key(2), (5, 3, 3, 5, 2, 3)) * 0.2
    b = jax.random.normal(jax.random.key(3), (3,)) * 0.1
    x = jax.random.normal(jax.random.key(4), (1, 7, 6, 5, 8, 2))
    _normed_close(fft_mod.conv4d_fft(x, w, b), conv4d(x, w, b), atol=1e-5)


def test_fft_rejects_even_kernels():
    w = jnp.zeros((2, 2, 2, 2, 1, 1))
    x = jnp.zeros((1, 4, 4, 4, 4, 1))
    with pytest.raises(AssertionError, match="odd-tap"):
        fft_mod.conv4d_fft(x, w)


def test_cp_reconstruct_inverts_exact_factors():
    w = jax.random.normal(jax.random.key(5), (3, 3, 3, 3, 2, 4))
    cp = cp_mod.exact_cp_factors(w)
    np.testing.assert_allclose(np.asarray(cp_mod.cp_reconstruct(cp)),
                               np.asarray(w), atol=1e-6)


# ---------------------------------------------------------------------------
# conversion: HOSVD+ALS error monotone in rank; exact recovery
# ---------------------------------------------------------------------------


def test_cp_als_error_monotone_in_rank():
    w = np.asarray(jax.random.normal(jax.random.key(6), (3, 3, 3, 3, 2, 2)))
    ranks = (1, 2, 4, 8)
    errs = [err for _, err in nested_decompose(w, ranks, iters=20)]
    assert all(b <= a + 1e-9 for a, b in zip(errs, errs[1:])), errs
    assert errs[-1] < errs[0]


def test_cp_als_recovers_low_rank_kernel_exactly():
    cp = {k: np.asarray(jax.random.normal(jax.random.key(i), (3, 2)))
          for i, k in enumerate(("ka", "kwa", "kb", "kwb"))}
    cp["cin"] = np.asarray(jax.random.normal(jax.random.key(4), (2, 2)))
    cp["cout"] = np.asarray(jax.random.normal(jax.random.key(5), (2, 2)))
    w = np.asarray(cp_mod.cp_reconstruct(
        {k: jnp.asarray(v) for k, v in cp.items()}))
    _, err = decompose_kernel(w, rank=2, iters=60)
    assert err < 1e-5, err


def test_decompose_stack_attaches_factors_and_preserves_dense():
    params = make_params(jax.random.key(9), (3, 3), (2, 1))
    dense_w = [np.asarray(layer["w"]) for layer in params]
    out, errs = decompose_stack(params, rank=4, iters=10)
    assert cp_mod.cp_stack_ranks(out) == (4, 4)
    assert len(errs) == 2 and all(0 <= e < 1.0 for e in errs)
    for layer, w0 in zip(out, dense_w):
        # the dense kernel stays beside the factors (checkpoint-compatible)
        np.testing.assert_array_equal(np.asarray(layer["w"]), w0)
        assert layer["cp"]["cout"].dtype == jnp.float32
    # a stack with partial factor coverage is NOT CP-routable
    partial = [out[0], {k: v for k, v in out[1].items() if k != "cp"}]
    assert cp_mod.cp_stack_ranks(partial) is None


# ---------------------------------------------------------------------------
# the arithmetic gates: pass exactly where the FLOP model predicts a win
# ---------------------------------------------------------------------------


def test_cp_gate_directions():
    # rank 16 at the PF-Pascal arch: a predicted ~42x FLOP cut — passes
    assert cp_mod.cp_feasible(25, 25, 25, 25, (5, 5, 5), (16, 16, 1),
                              (16, 16, 16))
    # rank-full parity factors lose the arithmetic — the gate keeps dense
    assert not cp_mod.cp_feasible(6, 6, 6, 6, (3,), (1,), (81,))
    # low rank at k=3 still clears (28 vs 0.75*162 FLOPs/cell)
    assert cp_mod.cp_feasible(7, 7, 7, 7, (3,), (1,), (2,))
    assert not cp_mod.cp_feasible(7, 7, 7, 7, (3,), (1,), (8,))
    # even kernels and rank/kernel arity mismatches are out of class
    assert not cp_mod.cp_feasible(8, 8, 8, 8, (4,), (1,), (2,))
    assert not cp_mod.cp_feasible(8, 8, 8, 8, (3, 3), (4, 1), (2,))


def test_fft_gate_directions(monkeypatch):
    # k=5 arch: spectral beats direct k^4 even under the VPU penalty
    assert fft_mod.fft_feasible(25, 25, 25, 25, (5, 5, 5), (16, 16, 1))
    assert fft_mod.fft_feasible(8, 8, 8, 8, (5, 5, 5), (16, 16, 1))
    # k=3 arches keep the dense tiers (the paper's crossover direction)
    assert not fft_mod.fft_feasible(13, 13, 13, 13, (3, 3), (16, 1))
    assert not fft_mod.fft_feasible(6, 6, 6, 6, (3,), (1,))
    assert not fft_mod.fft_feasible(8, 8, 8, 8, (4,), (1,))  # even taps
    # the weight-spectrum budget rejects volume-scale blowups
    monkeypatch.setattr(fft_mod, "_FFT_TEMP_BUDGET", 1024)
    assert not fft_mod.fft_feasible(25, 25, 25, 25, (5, 5, 5), (16, 16, 1))


# ---------------------------------------------------------------------------
# chooser routing: spy-counted probes, demotion, tier-cache persistence
# ---------------------------------------------------------------------------

K5_ARGS = (25, 25, 25, 25, (5, 5, 5), (16, 16, 1))
K5_RANKS = (16, 16, 16)


@pytest.fixture
def fresh_chooser():
    lane.reset_fused_tier_demotions()
    lane._emitted_choices.clear()
    lane._last_selected.clear()
    yield
    lane.reset_fused_tier_demotions()
    lane._emitted_choices.clear()
    lane._last_selected.clear()


def _arm_arith_probes(monkeypatch, results=None):
    """Spy-counted compile probes for both arithmetic tiers: the gate's job
    is proven by which probes RUN, not just by the returned tier."""
    results = results or {}
    counts = {"cp": 0, "fft": 0}

    def cp_probe(*a):
        counts["cp"] += 1
        return results.get("cp", True)

    def fft_probe(*a):
        counts["fft"] += 1
        return results.get("fft", True)

    monkeypatch.setattr(cp_mod, "cp_compiles", cp_probe)
    monkeypatch.setattr(fft_mod, "fft_compiles", fft_probe)
    return counts


def test_chooser_selects_arith_tiers_only_where_gates_pass(
        fresh_chooser, monkeypatch):
    counts = _arm_arith_probes(monkeypatch)
    # with factors attached the CP tier wins (and fft is never probed)
    assert lane.choose_fused_stack(*K5_ARGS, cp_ranks=K5_RANKS) == "cp"
    assert counts == {"cp": 1, "fft": 0}
    # without factors the spectral tier takes the k=5 arch
    assert lane.choose_fused_stack(*K5_ARGS) == "fft"
    assert counts == {"cp": 1, "fft": 1}
    # a k=3 arch fails both gates: no probe runs, XLA keeps the shape
    assert lane.choose_fused_stack(13, 13, 13, 13, (3, 3), (16, 1)) is None
    assert lane.choose_fused_stack(
        7, 7, 7, 7, (3,), (1,), cp_ranks=(8,)) is None
    assert counts == {"cp": 1, "fft": 1}
    assert lane.last_selected_tier("forward") == "xla"


def test_arith_tier_outranks_pallas_ladder(fresh_chooser, monkeypatch):
    conv4d_base = importlib.import_module("ncnet_tpu.ops.conv4d")
    monkeypatch.setattr(conv4d_base, "_pallas_available", lambda: True)
    monkeypatch.setattr(lane, "fused_resident_feasible", lambda *a: True)
    resident = {"n": 0}

    def resident_probe(*a):
        resident["n"] += 1
        return True

    monkeypatch.setattr(lane, "fused_resident_compiles", resident_probe)
    counts = _arm_arith_probes(monkeypatch)
    assert lane.choose_fused_stack(*K5_ARGS, cp_ranks=K5_RANKS) == "cp"
    assert counts["cp"] == 1 and resident["n"] == 0
    # ... but a failed arithmetic probe falls through to the Pallas ladder
    lane._emitted_choices.clear()
    counts = _arm_arith_probes(monkeypatch, results={"cp": False,
                                                     "fft": False})
    assert lane.choose_fused_stack(*K5_ARGS, cp_ranks=K5_RANKS) == "resident"
    assert counts == {"cp": 1, "fft": 1} and resident["n"] == 1


def test_demotion_walks_cp_then_fft(fresh_chooser, monkeypatch):
    _arm_arith_probes(monkeypatch)
    assert lane.choose_fused_stack(*K5_ARGS, cp_ranks=K5_RANKS) == "cp"
    assert lane.demote_fused_tier() == "cp"
    assert lane.choose_fused_stack(*K5_ARGS, cp_ranks=K5_RANKS) == "fft"
    assert lane.demote_fused_tier() == "fft"
    # both arithmetic tiers dead, no Pallas backend on CPU: XLA
    assert lane.choose_fused_stack(*K5_ARGS, cp_ranks=K5_RANKS) is None
    assert lane.demoted_fused_tiers() == {"cp", "fft"}
    lane.reset_fused_tier_demotions()
    assert lane.choose_fused_stack(*K5_ARGS, cp_ranks=K5_RANKS) == "cp"


def test_inactive_arith_tiers_are_skipped_by_the_demotion_walk(
        fresh_chooser, monkeypatch):
    """A process whose chooser never routed cp/fft must not burn its
    demotion cycle on them: the walk lands on the Pallas ladder."""
    assert lane.demote_fused_tier() == "resident"


def test_tier_cache_persists_cp_decision_keyed_by_rank(
        fresh_chooser, monkeypatch, tmp_path):
    path = str(tmp_path / "tier_cache.json")
    monkeypatch.setenv(tier_cache.CACHE_ENV, path)
    tier_cache._reset_state()
    counts = _arm_arith_probes(monkeypatch)
    assert lane.choose_fused_stack(*K5_ARGS, cp_ranks=K5_RANKS) == "cp"
    assert counts["cp"] == 1
    sig_ext = K5_ARGS + (K5_RANKS,)
    assert tier_cache.lookup("forward", sig_ext) == ("cp",)
    assert "|r=" in tier_cache.signature_key("forward", sig_ext)
    # "fresh process": the cached decision replays without a probe
    tier_cache._reset_state()
    lane._emitted_choices.clear()
    counts["cp"] = counts["fft"] = 0
    assert lane.choose_fused_stack(*K5_ARGS, cp_ranks=K5_RANKS) == "cp"
    assert counts == {"cp": 0, "fft": 0}
    # a DIFFERENT rank is a different decision: cache miss, fresh probe
    assert lane.choose_fused_stack(*K5_ARGS, cp_ranks=(8, 8, 8)) == "cp"
    assert counts["cp"] == 1
    tier_cache._reset_state()


# ---------------------------------------------------------------------------
# model routing: natural selection, forced tiers, sparse folded tiles
# ---------------------------------------------------------------------------


def test_neigh_consensus_selects_cp_naturally(fresh_chooser):
    """Factors attached + gate green: the fp32 CPU volume routes through
    the CP chain with no force, and matches the dense stack (the rank-1
    kernels are exactly their factors)."""
    params = rank1_params(jax.random.key(10), (3,), (1,))
    corr = jax.random.normal(jax.random.key(11), (1, 7, 7, 7, 7)) * 0.5
    out = neigh_consensus(params, corr, symmetric=False)
    assert lane.last_selected_tier("forward") == "cp"
    ref = neigh_consensus(
        [{"w": p["w"], "b": p["b"]} for p in params], corr, symmetric=False)
    _normed_close(out, ref, atol=1e-5)


def test_neigh_consensus_selects_fft_naturally(fresh_chooser):
    """The k=5 16-channel arch clears the spectral gate on the fp32 CPU
    path: the chooser (real compile probe) routes fft, and the output
    matches the XLA stack."""
    params = make_params(jax.random.key(12), (5, 5, 5), (16, 16, 1))
    corr = jax.random.normal(jax.random.key(13), (1, 8, 8, 8, 8)) * 0.5
    out = neigh_consensus(params, corr, symmetric=False)
    assert lane.last_selected_tier("forward") == "fft"
    ref = neigh_consensus(params, corr, symmetric=False, allow_pallas=False)
    _normed_close(out, ref, atol=1e-4)


def test_force_tier_fft_overrides_gate(fresh_chooser):
    """k=3 fails the spectral gate, but the forced path must run it anyway
    (exactness fixture / ModelConfig.nc_tier seam) and tag the decision."""
    params = make_params(jax.random.key(14), (3, 3), (4, 1))
    corr = jax.random.normal(jax.random.key(15), (2, 6, 6, 6, 6)) * 0.5
    out = neigh_consensus(params, corr, symmetric=True, force_tier="fft")
    assert lane.last_selected_tier("forward") == "fft"
    ref = neigh_consensus(params, corr, symmetric=True, allow_pallas=False)
    _normed_close(out, ref, atol=1e-4)


def test_force_tier_cp_requires_factors(fresh_chooser):
    params = make_params(jax.random.key(16), (3,), (1,))
    corr = jnp.zeros((1, 6, 6, 6, 6))
    with pytest.raises(ValueError, match="CP factors"):
        neigh_consensus(params, corr, force_tier="cp")
    with pytest.raises(ValueError, match="force_tier"):
        neigh_consensus(params, corr, force_tier="resident")
    # with rank-full factors attached the forced CP run is exact
    for layer in params:
        layer["cp"] = cp_mod.exact_cp_factors(layer["w"])
    corr = jax.random.normal(jax.random.key(17), (1, 6, 6, 6, 6)) * 0.5
    out = neigh_consensus(params, corr, symmetric=True, force_tier="cp")
    assert lane.last_selected_tier("forward") == "cp"
    ref = neigh_consensus(params, corr, symmetric=True, allow_pallas=False)
    _normed_close(out, ref, atol=1e-5)


@pytest.mark.parametrize("tier", ["cp", "fft"])
def test_sparse_folded_tiles_match_dense_through_each_tier(tier,
                                                          fresh_chooser):
    """The PR 15 coarse-to-fine pipeline's folded-tile stacks route through
    the same forced tier as the dense volume, and at full top-k coverage
    the sparse output still equals the dense filter — through CP factors
    and through the spectral conv alike."""
    from ncnet_tpu.models.ncnet import ncnet_match_volume
    from ncnet_tpu.ops import correlation_4d

    rng = np.random.default_rng(18)
    fa = jnp.asarray(rng.standard_normal((1, 8, 8, 12)).astype(np.float32))
    fb = jnp.asarray(rng.standard_normal((1, 8, 8, 12)).astype(np.float32))
    params = {"nc": rank1_params(jax.random.key(19), (3, 3), (4, 1))}
    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3, 3),
                      ncons_channels=(4, 1), nc_tier=tier)
    dense = ncnet_filter(cfg, params, correlation_4d(fa, fb)).corr
    assert lane.last_selected_tier("forward") == tier
    sp = ncnet_match_volume(
        cfg.replace(sparse_topk=16, sparse_factor=2, sparse_halo=2),
        params, fa, fb)
    np.testing.assert_allclose(np.asarray(sp.corr), np.asarray(dense),
                               atol=1e-4, rtol=1e-3)
    # (tier-vs-unforced-dense exactness is owned by the parity and
    # natural-selection tests above — not re-run here.)


# ---------------------------------------------------------------------------
# observability: quality tags, tier_selected events
# ---------------------------------------------------------------------------


def test_active_tier_reports_arithmetic_tiers(fresh_chooser):
    from ncnet_tpu.observability.quality import active_tier

    lane._last_selected["forward"] = "cp"
    # precision-agnostic: the label holds whether or not bf16 was eligible
    assert active_tier(False) == "cp"
    assert active_tier(True) == "cp"
    lane._last_selected["forward"] = "fft"
    assert active_tier(False) == "fft"
    lane._last_selected["forward"] = "xla"
    assert active_tier(False) == "xla"


def test_tier_selected_events_for_chosen_and_forced(fresh_chooser,
                                                    monkeypatch, tmp_path):
    _arm_arith_probes(monkeypatch)
    events_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(events_path)):
        assert lane.choose_fused_stack(*K5_ARGS, cp_ranks=K5_RANKS) == "cp"
        lane.note_forced_tier(6, 6, 6, 6, (3,), (1,), "fft")
    _, events = replay_events(events_path)
    selected = [e for e in events if e["event"] == "tier_selected"]
    assert [e["tier"] for e in selected] == ["cp", "fft"]
    # sig[6] (ranks / forced tag) keys the decision but is not a wire field
    assert all("shape" in e and len(e["shape"]) == 4 for e in selected)


# ---------------------------------------------------------------------------
# training entry + probe tool smoke
# ---------------------------------------------------------------------------


def test_finetune_cp_rank_decomposes_and_forces_cp():
    import warnings

    from ncnet_tpu import training
    from ncnet_tpu.config import TrainConfig

    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                      ncons_channels=(1,))
    tcfg = TrainConfig(model=cfg, batch_size=2, data_parallel=False,
                       finetune_cp_rank=2)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # random-trunk warning expected
        state, _, mcfg, _ = training.create_train_state(tcfg)
    assert mcfg.nc_tier == "cp"
    assert cp_mod.cp_stack_ranks(state.params["nc"]) == (2,)
    # the two fine-tune-the-adapter modes are mutually exclusive
    import dataclasses

    with pytest.raises(ValueError, match="fe_finetune_params"):
        training.create_train_state(
            dataclasses.replace(tcfg, fe_finetune_params=1))


def test_cp_fft_probe_tiny_smoke(capsys):
    import cp_fft_probe

    assert cp_fft_probe.main(["--tiny"]) == 0
    outp = capsys.readouterr().out
    assert "tiny smoke: OK" in outp
