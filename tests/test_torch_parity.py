"""Full-forward cross-framework parity: ncnet_forward vs a torch twin.

The strongest quality evidence available offline (no released weights, no
torchvision): a functional PyTorch re-statement of the reference's ENTIRE
forward semantics — resnet101[:layer3] trunk, featureL2Norm (eps inside the
sqrt, model.py:14-17), bmm 4D correlation (model.py:106-115), MutualMatching
with eps=1e-5 and the reference parenthesization (model.py:155-175),
stack-level symmetric NeighConsensus with the conv4d-as-loop-over-conv3d
kernel (conv4d.py:39-48), final MutualMatching — driven by the SAME weights
as our jitted forward.  Agreement here means the whole composition (not just
each op against numpy) matches torch float semantics end to end.

Complements tests/test_backbone.py (trunk-only oracle) and the op-level
brute-force oracles; see tools/parity_kit.py for the real-weights version of
this check.
"""

import numpy as np
import torch
import torch.nn.functional as F

import jax.numpy as jnp

from ncnet_tpu.config import ModelConfig
from ncnet_tpu.models import backbone as bb
from ncnet_tpu.models.ncnet import ncnet_forward

from test_backbone import make_resnet101_state_dict, torch_resnet101_features
from test_inloc_match_parity import torch_corr_to_matches

RNG = np.random.default_rng(7)


def make_nc_layers(chans, k):
    """Random NC stack in BOTH layouts: torch Conv4d (C_out, C_in, kA, kWA,
    kB, kWB) and ours (kA, kWA, kB, kWB, C_in, C_out) — the one place the
    cross-framework weight transpose is written."""
    nc_torch, nc_ours = [], []
    for cin, cout in chans:
        w = RNG.normal(0, 0.3 / np.sqrt(cin * k**4),
                       (k, k, k, k, cin, cout)).astype(np.float32)
        bias = RNG.normal(0, 0.02, cout).astype(np.float32)
        nc_torch.append((torch.from_numpy(np.transpose(w, (5, 4, 0, 1, 2, 3))),
                         torch.from_numpy(bias)))
        nc_ours.append({"w": jnp.asarray(w), "b": jnp.asarray(bias)})
    return nc_torch, nc_ours


def torch_l2norm(f):
    return f / torch.sqrt(torch.sum(f * f, dim=1, keepdim=True) + 1e-6)


def torch_mutual(c):
    # reference model.py:155-175 (eps and parenthesization preserved)
    b, _, ha, wa, hb, wb = c.shape
    c3_b = c.view(b, ha * wa, hb, wb)
    c3_a = c.view(b, ha, wa, hb * wb)
    max_a, _ = torch.max(c3_b, dim=1, keepdim=True)        # over A for each B
    max_b, _ = torch.max(c3_a, dim=3, keepdim=True)        # over B for each A
    eps = 1e-5
    c_a = c3_a / (max_b + eps)
    c_b = c3_b / (max_a + eps)
    c = c * (c_a.view_as(c) * c_b.view_as(c))
    return c


def torch_conv4d_loop(x, w, bias):
    # the reference's conv4d: python loop over hA, conv3d per kA tap
    # (conv4d.py:39-48), "same" zero padding on every spatial dim
    bsz, cin, ha, wa, hb, wb = x.shape
    cout, _, ka, kwa, kb, kwb = w.shape
    pad = ka // 2
    xp = F.pad(x, (0, 0, 0, 0, 0, 0, pad, pad))  # pad hA only; conv3d pads rest
    out = torch.zeros(bsz, cout, ha, wa, hb, wb)
    for i in range(ha):
        acc = None
        for p in range(ka):
            o = F.conv3d(xp[:, :, i + p], w[:, :, p], bias=None,
                         padding=kwa // 2)
            acc = o if acc is None else acc + o
        out[:, :, i] = acc + bias.view(1, -1, 1, 1, 1)
    return out


def torch_nc_symmetric(x, layers):
    # stack-level symmetry: conv(x) + conv(x^T)^T (model.py:144-150)
    def stack(v):
        for w, b in layers:
            v = F.relu(torch_conv4d_loop(v, w, b))
        return v

    xt = x.permute(0, 1, 4, 5, 2, 3)
    return stack(x) + stack(xt).permute(0, 1, 4, 5, 2, 3)


def torch_full_forward(sd, nc_layers, src, tgt):
    fa = torch_l2norm(torch_resnet101_features(sd, src))
    fb = torch_l2norm(torch_resnet101_features(sd, tgt))
    b, c, ha, wa = fa.shape
    hb, wb = fb.shape[2:]
    corr = torch.bmm(
        fa.view(b, c, ha * wa).transpose(1, 2), fb.view(b, c, hb * wb)
    ).view(b, 1, ha, wa, hb, wb)
    corr = torch_mutual(corr)
    corr = torch_nc_symmetric(corr, nc_layers)
    corr = torch_mutual(corr)
    return corr


def torch_weak_loss(sd, nc_layers, src_batch, tgt_batch):
    """The reference's training objective (train.py:110-156): full forward
    for the positive pairs and for negatives built by rolling the SOURCES by
    −1 within the batch (train.py:137); score = mean over cells and both
    directions of the max softmax-normalized match value; loss =
    score(neg) − score(pos)."""

    def score(src, tgt):
        c = torch_full_forward(sd, nc_layers, src, tgt)
        b, _, ha, wa, hb, wb = c.shape
        nc_b = torch.softmax(c.view(b, ha * wa, hb, wb), dim=1)
        nc_a = torch.softmax(c.view(b, ha, wa, hb * wb), dim=3)
        s_b, _ = torch.max(nc_b, dim=1)
        s_a, _ = torch.max(nc_a, dim=3)
        return (torch.mean(s_a) + torch.mean(s_b)) / 2.0

    pos = score(src_batch, tgt_batch)
    neg = score(torch.roll(src_batch, -1, dims=0), tgt_batch)
    return neg - pos


def test_weak_loss_matches_torch_twin():
    """The training objective agrees cross-framework end to end (forward ×2
    + roll negatives + softmax scoring) — and so does its sign structure:
    the same-weights loss value is what training optimizes, so this is the
    offline evidence that the TPU training target IS the reference's."""
    from ncnet_tpu.training.loss import weak_loss

    sd = make_resnet101_state_dict()
    k = 3
    nc_torch, nc_ours = make_nc_layers([(1, 1)], k)
    params = {
        "backbone": bb.import_torch_backbone(sd, "resnet101"),
        "nc": nc_ours,
    }
    x = RNG.normal(0, 1, (3, 3, 48, 48)).astype(np.float32)
    y = RNG.normal(0, 1, (3, 3, 48, 48)).astype(np.float32)
    with torch.no_grad():
        want = float(torch_weak_loss(
            sd, nc_torch, torch.from_numpy(x), torch.from_numpy(y)
        ))
    cfg = ModelConfig(backbone="resnet101", ncons_kernel_sizes=(k,), ncons_channels=(1,))
    got = float(weak_loss(
        cfg, params,
        {
            "source_image": jnp.asarray(np.transpose(x, (0, 2, 3, 1))),
            "target_image": jnp.asarray(np.transpose(y, (0, 2, 3, 1))),
        },
    ))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_full_forward_matches_torch_twin():
    sd = make_resnet101_state_dict()
    k, chans = 3, [(1, 8), (8, 1)]
    nc_torch, nc_ours = make_nc_layers(chans, k)

    x = RNG.normal(0, 1, (1, 3, 64, 64)).astype(np.float32)
    y = RNG.normal(0, 1, (1, 3, 64, 48)).astype(np.float32)
    with torch.no_grad():
        want = torch_full_forward(
            sd, nc_torch, torch.from_numpy(x), torch.from_numpy(y)
        ).numpy()

    cfg = ModelConfig(backbone="resnet101", ncons_kernel_sizes=(k, k),
                      ncons_channels=tuple(c for _, c in chans))
    params = {
        "backbone": bb.import_torch_backbone(sd, "resnet101"),
        "nc": nc_ours,
    }
    got = ncnet_forward(
        cfg, params,
        jnp.asarray(np.transpose(x, (0, 2, 3, 1))),
        jnp.asarray(np.transpose(y, (0, 2, 3, 1))),
    ).corr  # (B, hA, wA, hB, wB)

    assert np.asarray(got).shape == tuple(want.shape[i] for i in (0, 2, 3, 4, 5))
    np.testing.assert_allclose(
        np.asarray(got), want[:, 0], rtol=2e-4, atol=2e-4
    )


# ---------------------------------------------------------------------------
# PCK-metric-level parity (VERDICT r3 item 4): the full eval pipeline
# dataset → corr_to_matches(do_softmax) → bilinearInterpPointTnf → pck
# re-stated in torch per eval_pf_pascal.py:69-81 + lib/point_tnf.py +
# lib/eval_util.py:12-50, against our jitted chain on the same volume.
# ---------------------------------------------------------------------------


def torch_normalize_axis(x, L):
    return (x - 1 - (L - 1) / 2) * 2 / (L - 1)  # point_tnf.py:6-7


def torch_unnormalize_axis(x, L):
    return x * (L - 1) / 2 + 1 + (L - 1) / 2  # point_tnf.py:9-10


def torch_bilinear_interp_point_tnf(matches, target_points_norm):
    """point_tnf.py:96-148 verbatim (note: its flat indexing reads batch 0's
    grids — correct only at batch size 1, which is how the reference eval
    runs; the parity loop below therefore compares per single-pair batch)."""
    xA, yA, xB, yB = matches
    feature_size = int(np.sqrt(xB.shape[-1]))
    b, _, N = target_points_norm.size()
    X_, Y_ = xB.view(-1), yB.view(-1)
    grid = torch.FloatTensor(
        np.linspace(-1, 1, feature_size)).unsqueeze(0).unsqueeze(2)
    x_minus = torch.sum(
        ((target_points_norm[:, 0, :] - grid) > 0).long(), dim=1,
        keepdim=True) - 1
    x_minus[x_minus < 0] = 0
    x_plus = x_minus + 1
    y_minus = torch.sum(
        ((target_points_norm[:, 1, :] - grid) > 0).long(), dim=1,
        keepdim=True) - 1
    y_minus[y_minus < 0] = 0
    y_plus = y_minus + 1
    toidx = lambda x, y, L: y * L + x  # noqa: E731
    m_m_idx = toidx(x_minus, y_minus, feature_size)
    p_p_idx = toidx(x_plus, y_plus, feature_size)
    p_m_idx = toidx(x_plus, y_minus, feature_size)
    m_p_idx = toidx(x_minus, y_plus, feature_size)
    topoint = lambda idx, X, Y: torch.cat(  # noqa: E731
        (X[idx.view(-1)].view(b, 1, N).contiguous(),
         Y[idx.view(-1)].view(b, 1, N).contiguous()), dim=1)
    P_m_m = topoint(m_m_idx, X_, Y_)
    P_p_p = topoint(p_p_idx, X_, Y_)
    P_p_m = topoint(p_m_idx, X_, Y_)
    P_m_p = topoint(m_p_idx, X_, Y_)
    multrows = lambda x: x[:, 0, :] * x[:, 1, :]  # noqa: E731
    f_p_p = multrows(torch.abs(target_points_norm - P_m_m))
    f_m_m = multrows(torch.abs(target_points_norm - P_p_p))
    f_m_p = multrows(torch.abs(target_points_norm - P_p_m))
    f_p_m = multrows(torch.abs(target_points_norm - P_m_p))
    Q_m_m = topoint(m_m_idx, xA.reshape(-1), yA.reshape(-1))
    Q_p_p = topoint(p_p_idx, xA.reshape(-1), yA.reshape(-1))
    Q_p_m = topoint(p_m_idx, xA.reshape(-1), yA.reshape(-1))
    Q_m_p = topoint(m_p_idx, xA.reshape(-1), yA.reshape(-1))
    return (Q_m_m * f_m_m + Q_p_p * f_p_p + Q_m_p * f_m_p + Q_p_m * f_p_m) / (
        f_p_p + f_m_m + f_m_p + f_p_m)


def torch_points_to_unit(P, im_size):
    h, w = im_size[:, 0], im_size[:, 1]
    out = P.clone()
    out[:, 0, :] = torch_normalize_axis(P[:, 0, :], w.unsqueeze(1).expand_as(P[:, 0, :]))
    out[:, 1, :] = torch_normalize_axis(P[:, 1, :], h.unsqueeze(1).expand_as(P[:, 1, :]))
    return out


def torch_points_to_pixel(P, im_size):
    h, w = im_size[:, 0], im_size[:, 1]
    out = P.clone()
    out[:, 0, :] = torch_unnormalize_axis(P[:, 0, :], w.unsqueeze(1).expand_as(P[:, 0, :]))
    out[:, 1, :] = torch_unnormalize_axis(P[:, 1, :], h.unsqueeze(1).expand_as(P[:, 1, :]))
    return out


def torch_pck(source_points, warped_points, L_pck, alpha=0.1):
    """eval_util.py:12-25 verbatim (per-sample valid-prefix slice)."""
    batch_size = source_points.size(0)
    out = torch.zeros(batch_size)
    for i in range(batch_size):
        p_src = source_points[i, :]
        p_wrp = warped_points[i, :]
        N_pts = int(torch.sum(
            torch.ne(p_src[0, :], -1) * torch.ne(p_src[1, :], -1)))
        d = torch.pow(torch.sum(
            torch.pow(p_src[:, :N_pts] - p_wrp[:, :N_pts], 2), 0), 0.5)
        correct = torch.le(d, L_pck[i].expand_as(d) * alpha)
        out[i] = torch.mean(correct.float())
    return out


def test_inloc_configuration_matches_torch_twin():
    """The full InLoc eval configuration (VERDICT r5 #5): k=2 relocalization
    — maxpool4d → mutual → symmetric IVD NC stack (3⁴ kernels, 16→1) →
    mutual (the PRODUCTION ``ncnet_filter`` composition) → both-direction
    ``corr_to_matches`` WITH delta4d application → sort → dedup → recenter —
    against the reference semantics re-stated in torch
    (model.py:177-191/261-282 + point_tnf.py:12-80 + eval_inloc.py:134-190),
    on a RECTANGULAR fine volume.  Asserts the final match tables row for
    row, including the relocalization deltas (the coordinates land on the
    2× finer grid only through correct delta application).  On this shape
    class our filter takes the tap-swapped symmetric path, so the parity
    also pins ``NC(xᵀ)ᵀ ≡ NC_tap-swapped(x)`` against torch's plain
    two-pass symmetry."""
    from ncnet_tpu.evaluation.inloc import extract_match_table, sort_and_dedup
    from test_inloc_match_parity import torch_maxpool4d

    k = 3  # the IVD/InLoc NC architecture: 3⁴ kernels, 16 → 1
    k_size = 2
    rng = np.random.default_rng(42)  # order-independent draws: the match-
    # index comparison below is discrete, so the twin runs on a SHARED fine
    # volume (the trunk has its own twin, test_backbone/test_full_forward —
    # composing it here would stack ~1e-4 of cross-framework conv jitter
    # under an argmax and make near-tied cells flip)
    nc_torch, nc_ours = [], []
    for cin, cout in [(1, 16), (16, 1)]:
        w = rng.normal(0, 0.3 / np.sqrt(cin * k ** 4),
                       (k, k, k, k, cin, cout)).astype(np.float32)
        bias = rng.normal(0, 0.02, cout).astype(np.float32)
        nc_torch.append((torch.from_numpy(np.transpose(w, (5, 4, 0, 1, 2, 3))),
                         torch.from_numpy(bias)))
        nc_ours.append({"w": jnp.asarray(w), "b": jnp.asarray(bias)})

    # rectangular fine volume (4, 6, 6, 4) from shared normalized features
    # → pooled (2, 3, 3, 2); both frameworks consume the SAME array
    fa = rng.standard_normal((1, 4, 6, 64)).astype(np.float32)
    fb = rng.standard_normal((1, 6, 4, 64)).astype(np.float32)
    fa /= np.linalg.norm(fa, axis=-1, keepdims=True)
    fb /= np.linalg.norm(fb, axis=-1, keepdims=True)
    corr_fine = np.einsum("bijc,bklc->bijkl", fa, fb)

    with torch.no_grad():
        corr, mi, mj, mk, ml = torch_maxpool4d(
            torch.from_numpy(corr_fine)[:, None], k_size)
        delta4d_t = (mi, mj, mk, ml)
        corr = torch_mutual(corr)
        corr = torch_nc_symmetric(corr, nc_torch)
        corr = torch_mutual(corr)
        fs1, fs2, fs3, fs4 = corr.shape[2:]
        a = torch_corr_to_matches(corr, delta4d=delta4d_t, k_size=k_size,
                                  do_softmax=True, scale="positive")
        bwd = torch_corr_to_matches(corr, delta4d=delta4d_t, k_size=k_size,
                                    do_softmax=True, scale="positive",
                                    invert_matching_direction=True)
        # the reference's host tail, restated in torch/numpy
        # (eval_inloc.py:159-189): score sort → coordinate dedup → recenter
        xA_, yA_, xB_, yB_, score_ = (
            torch.cat((u, v), 1) for u, v in zip(a, bwd))
        sorted_index = torch.sort(-score_)[1].squeeze()
        xA_, yA_, xB_, yB_, score_ = (
            v.squeeze()[sorted_index].unsqueeze(0)
            for v in (xA_, yA_, xB_, yB_, score_))
        concat_coords = np.concatenate(
            (xA_.numpy(), yA_.numpy(), xB_.numpy(), yB_.numpy()), 0)
        _, unique_index = np.unique(concat_coords, axis=1, return_index=True)
        ui = torch.LongTensor(unique_index)
        xA_, yA_, xB_, yB_, score_ = (
            v.squeeze()[ui] for v in (xA_, yA_, xB_, yB_, score_))
        yA_ = yA_ * (fs1 * k_size - 1) / (fs1 * k_size) + 0.5 / (fs1 * k_size)
        xA_ = xA_ * (fs2 * k_size - 1) / (fs2 * k_size) + 0.5 / (fs2 * k_size)
        yB_ = yB_ * (fs3 * k_size - 1) / (fs3 * k_size) + 0.5 / (fs3 * k_size)
        xB_ = xB_ * (fs4 * k_size - 1) / (fs4 * k_size) + 0.5 / (fs4 * k_size)
        want = np.stack([v.numpy().ravel()
                         for v in (xA_, yA_, xB_, yB_, score_)])

    from ncnet_tpu.models.ncnet import ncnet_filter

    cfg = ModelConfig(backbone="resnet101", ncons_kernel_sizes=(k, k),
                      ncons_channels=(16, 1), relocalization_k_size=k_size)
    out = ncnet_filter(cfg, {"nc": nc_ours}, jnp.asarray(corr_fine))
    assert out.delta4d is not None
    table = extract_match_table(
        out, k_size=k_size, do_softmax=True, both_directions=True,
        flip_direction=False,
    )
    got = np.stack(sort_and_dedup(*np.asarray(table, np.float32)))

    assert got.shape == want.shape
    np.testing.assert_allclose(got[:4], want[:4], atol=1e-5)
    np.testing.assert_allclose(got[4], want[4], rtol=1e-4, atol=1e-6)


def test_pck_metric_matches_torch_twin():
    """The strongest offline proxy for the unverifiable headline ~78.9%:
    with identical weights, OUR dataset→matches→warp→PCK chain and the
    reference's (re-stated in torch) produce the same per-pair PCK to 1e-4
    on synthetic annotated pairs, across varying keypoint counts."""
    from ncnet_tpu.evaluation.pck import pck_metric
    from ncnet_tpu.ops import corr_to_matches

    sd = make_resnet101_state_dict()
    k, chans = 3, [(1, 4), (4, 1)]
    nc_torch, nc_ours = make_nc_layers(chans, k)
    cfg = ModelConfig(backbone="resnet101", ncons_kernel_sizes=(k, k),
                      ncons_channels=(4, 1))
    params = {"backbone": bb.import_torch_backbone(sd, "resnet101"),
              "nc": nc_ours}

    n_pairs, n_kp = 3, 20
    for i in range(n_pairs):  # reference eval runs batch_size 1 (see twin)
        x = RNG.normal(0, 1, (1, 3, 64, 64)).astype(np.float32)
        y = RNG.normal(0, 1, (1, 3, 64, 64)).astype(np.float32)
        n_valid = [5, 11, 20][i]
        pts_src = np.full((1, 2, n_kp), -1.0, np.float32)
        pts_tgt = np.full((1, 2, n_kp), -1.0, np.float32)
        pts_src[0, :, :n_valid] = RNG.uniform(2, 62, (2, n_valid))
        pts_tgt[0, :, :n_valid] = RNG.uniform(2, 62, (2, n_valid))
        im_src = np.array([[64.0, 64.0, 3.0]], np.float32)
        im_tgt = np.array([[64.0, 64.0, 3.0]], np.float32)
        l_pck = RNG.uniform(20, 50, (1, 1)).astype(np.float32)

        with torch.no_grad():
            corr_t = torch_full_forward(
                sd, nc_torch, torch.from_numpy(x), torch.from_numpy(y))
            m_t = torch_corr_to_matches(corr_t, do_softmax=True,
                                        scale="centered")
            tgt_norm = torch_points_to_unit(
                torch.from_numpy(pts_tgt), torch.from_numpy(im_tgt))
            warped_norm = torch_bilinear_interp_point_tnf(m_t[:4], tgt_norm)
            warped = torch_points_to_pixel(warped_norm, torch.from_numpy(im_src))
            want = torch_pck(torch.from_numpy(pts_src), warped,
                             torch.from_numpy(l_pck))

        out = ncnet_forward(
            cfg, params,
            jnp.asarray(np.transpose(x, (0, 2, 3, 1))),
            jnp.asarray(np.transpose(y, (0, 2, 3, 1))),
        )
        matches = corr_to_matches(out.corr, do_softmax=True)
        got = pck_metric(
            {
                "source_points": jnp.asarray(pts_src),
                "target_points": jnp.asarray(pts_tgt),
                "source_im_size": jnp.asarray(im_src),
                "target_im_size": jnp.asarray(im_tgt),
                "L_pck": jnp.asarray(l_pck),
            },
            matches,
        )
        np.testing.assert_allclose(
            np.asarray(got), want.numpy(), rtol=0, atol=1e-4,
            err_msg=f"pair {i} (n_valid={n_valid})",
        )
