"""Full-forward cross-framework parity: ncnet_forward vs a torch twin.

The strongest quality evidence available offline (no released weights, no
torchvision): a functional PyTorch re-statement of the reference's ENTIRE
forward semantics — resnet101[:layer3] trunk, featureL2Norm (eps inside the
sqrt, model.py:14-17), bmm 4D correlation (model.py:106-115), MutualMatching
with eps=1e-5 and the reference parenthesization (model.py:155-175),
stack-level symmetric NeighConsensus with the conv4d-as-loop-over-conv3d
kernel (conv4d.py:39-48), final MutualMatching — driven by the SAME weights
as our jitted forward.  Agreement here means the whole composition (not just
each op against numpy) matches torch float semantics end to end.

Complements tests/test_backbone.py (trunk-only oracle) and the op-level
brute-force oracles; see tools/parity_kit.py for the real-weights version of
this check.
"""

import numpy as np
import torch
import torch.nn.functional as F

import jax.numpy as jnp

from ncnet_tpu.config import ModelConfig
from ncnet_tpu.models import backbone as bb
from ncnet_tpu.models.ncnet import ncnet_forward

from test_backbone import make_resnet101_state_dict, torch_resnet101_features

RNG = np.random.default_rng(7)


def torch_l2norm(f):
    return f / torch.sqrt(torch.sum(f * f, dim=1, keepdim=True) + 1e-6)


def torch_mutual(c):
    # reference model.py:155-175 (eps and parenthesization preserved)
    b, _, ha, wa, hb, wb = c.shape
    c3_b = c.view(b, ha * wa, hb, wb)
    c3_a = c.view(b, ha, wa, hb * wb)
    max_a, _ = torch.max(c3_b, dim=1, keepdim=True)        # over A for each B
    max_b, _ = torch.max(c3_a, dim=3, keepdim=True)        # over B for each A
    eps = 1e-5
    c_a = c3_a / (max_b + eps)
    c_b = c3_b / (max_a + eps)
    c = c * (c_a.view_as(c) * c_b.view_as(c))
    return c


def torch_conv4d_loop(x, w, bias):
    # the reference's conv4d: python loop over hA, conv3d per kA tap
    # (conv4d.py:39-48), "same" zero padding on every spatial dim
    bsz, cin, ha, wa, hb, wb = x.shape
    cout, _, ka, kwa, kb, kwb = w.shape
    pad = ka // 2
    xp = F.pad(x, (0, 0, 0, 0, 0, 0, pad, pad))  # pad hA only; conv3d pads rest
    out = torch.zeros(bsz, cout, ha, wa, hb, wb)
    for i in range(ha):
        acc = None
        for p in range(ka):
            o = F.conv3d(xp[:, :, i + p], w[:, :, p], bias=None,
                         padding=kwa // 2)
            acc = o if acc is None else acc + o
        out[:, :, i] = acc + bias.view(1, -1, 1, 1, 1)
    return out


def torch_nc_symmetric(x, layers):
    # stack-level symmetry: conv(x) + conv(x^T)^T (model.py:144-150)
    def stack(v):
        for w, b in layers:
            v = F.relu(torch_conv4d_loop(v, w, b))
        return v

    xt = x.permute(0, 1, 4, 5, 2, 3)
    return stack(x) + stack(xt).permute(0, 1, 4, 5, 2, 3)


def torch_full_forward(sd, nc_layers, src, tgt):
    fa = torch_l2norm(torch_resnet101_features(sd, src))
    fb = torch_l2norm(torch_resnet101_features(sd, tgt))
    b, c, ha, wa = fa.shape
    hb, wb = fb.shape[2:]
    corr = torch.bmm(
        fa.view(b, c, ha * wa).transpose(1, 2), fb.view(b, c, hb * wb)
    ).view(b, 1, ha, wa, hb, wb)
    corr = torch_mutual(corr)
    corr = torch_nc_symmetric(corr, nc_layers)
    corr = torch_mutual(corr)
    return corr


def torch_weak_loss(sd, nc_layers, src_batch, tgt_batch):
    """The reference's training objective (train.py:110-156): full forward
    for the positive pairs and for negatives built by rolling the SOURCES by
    −1 within the batch (train.py:137); score = mean over cells and both
    directions of the max softmax-normalized match value; loss =
    score(neg) − score(pos)."""

    def score(src, tgt):
        c = torch_full_forward(sd, nc_layers, src, tgt)
        b, _, ha, wa, hb, wb = c.shape
        nc_b = torch.softmax(c.view(b, ha * wa, hb, wb), dim=1)
        nc_a = torch.softmax(c.view(b, ha, wa, hb * wb), dim=3)
        s_b, _ = torch.max(nc_b, dim=1)
        s_a, _ = torch.max(nc_a, dim=3)
        return (torch.mean(s_a) + torch.mean(s_b)) / 2.0

    pos = score(src_batch, tgt_batch)
    neg = score(torch.roll(src_batch, -1, dims=0), tgt_batch)
    return neg - pos


def test_weak_loss_matches_torch_twin():
    """The training objective agrees cross-framework end to end (forward ×2
    + roll negatives + softmax scoring) — and so does its sign structure:
    the same-weights loss value is what training optimizes, so this is the
    offline evidence that the TPU training target IS the reference's."""
    from ncnet_tpu.training.loss import weak_loss

    sd = make_resnet101_state_dict()
    k = 3
    w = RNG.normal(0, 0.3 / np.sqrt(k**4), (k, k, k, k, 1, 1)).astype(np.float32)
    bias = RNG.normal(0, 0.02, 1).astype(np.float32)
    nc_torch = [(torch.from_numpy(np.transpose(w, (5, 4, 0, 1, 2, 3))),
                 torch.from_numpy(bias))]
    params = {
        "backbone": bb.import_torch_backbone(sd, "resnet101"),
        "nc": [{"w": jnp.asarray(w), "b": jnp.asarray(bias)}],
    }
    x = RNG.normal(0, 1, (3, 3, 48, 48)).astype(np.float32)
    y = RNG.normal(0, 1, (3, 3, 48, 48)).astype(np.float32)
    with torch.no_grad():
        want = float(torch_weak_loss(
            sd, nc_torch, torch.from_numpy(x), torch.from_numpy(y)
        ))
    cfg = ModelConfig(backbone="resnet101", ncons_kernel_sizes=(k,), ncons_channels=(1,))
    got = float(weak_loss(
        cfg, params,
        {
            "source_image": jnp.asarray(np.transpose(x, (0, 2, 3, 1))),
            "target_image": jnp.asarray(np.transpose(y, (0, 2, 3, 1))),
        },
    ))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_full_forward_matches_torch_twin():
    sd = make_resnet101_state_dict()
    k, chans = 3, [(1, 8), (8, 1)]
    nc_torch, nc_ours = [], []
    for cin, cout in chans:
        w = RNG.normal(0, 0.3 / np.sqrt(cin * k**4),
                       (k, k, k, k, cin, cout)).astype(np.float32)
        bias = RNG.normal(0, 0.02, cout).astype(np.float32)
        # torch Conv4d layout (C_out, C_in, kA, kWA, kB, kWB)
        nc_torch.append((torch.from_numpy(np.transpose(w, (5, 4, 0, 1, 2, 3))),
                         torch.from_numpy(bias)))
        nc_ours.append({"w": jnp.asarray(w), "b": jnp.asarray(bias)})

    x = RNG.normal(0, 1, (1, 3, 64, 64)).astype(np.float32)
    y = RNG.normal(0, 1, (1, 3, 64, 48)).astype(np.float32)
    with torch.no_grad():
        want = torch_full_forward(
            sd, nc_torch, torch.from_numpy(x), torch.from_numpy(y)
        ).numpy()

    cfg = ModelConfig(backbone="resnet101", ncons_kernel_sizes=(k, k),
                      ncons_channels=tuple(c for _, c in chans))
    params = {
        "backbone": bb.import_torch_backbone(sd, "resnet101"),
        "nc": nc_ours,
    }
    got = ncnet_forward(
        cfg, params,
        jnp.asarray(np.transpose(x, (0, 2, 3, 1))),
        jnp.asarray(np.transpose(y, (0, 2, 3, 1))),
    ).corr  # (B, hA, wA, hB, wB)

    assert np.asarray(got).shape == tuple(want.shape[i] for i in (0, 2, 3, 4, 5))
    np.testing.assert_allclose(
        np.asarray(got), want[:, 0], rtol=2e-4, atol=2e-4
    )
