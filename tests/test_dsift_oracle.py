"""Independent pure-numpy oracle for the dense-SIFT used in pose
verification (VERDICT r3 item 8).

``localization/dsift.py`` computes PHOW-geometry descriptors (4×4 spatial
bins of ``bin_size`` px, 8 orientations, ``step``-px grid — the vl_phow
call in /root/reference/lib_matlab/parfor_nc4d_PV.m) with a fused
scatter+separable-convolution XLA program.  This oracle re-derives each
descriptor FROM THE DEFINITION — a per-descriptor, per-bin, per-pixel
accumulation loop with triangular spatial weighting and soft orientation
binning — sharing no code path with the implementation beyond np.gradient.
"""

import numpy as np
import pytest

from ncnet_tpu.localization.dsift import (
    N_BINS,
    N_ORIENT,
    dense_sift,
    descriptor_grid,
    rootsift,
)


def dsift_oracle(img: np.ndarray, bin_size: int, step: int) -> np.ndarray:
    """Brute-force dense SIFT by definition."""
    img = np.asarray(img, np.float64)
    h, w = img.shape
    gy, gx = np.gradient(img, axis=0), np.gradient(img, axis=1)
    mag = np.sqrt(gx * gx + gy * gy)
    ang = np.arctan2(gy, gx)
    o = (ang / (2 * np.pi) * N_ORIENT) % N_ORIENT
    lo = np.floor(o).astype(int) % N_ORIENT
    frac = o - np.floor(o)
    hi = (lo + 1) % N_ORIENT

    ys, xs = descriptor_grid(h, w, bin_size, step)
    offs = (bin_size * (np.arange(N_BINS) - (N_BINS - 1) / 2.0)).astype(int)

    def tri(d):  # triangular spatial window, support |d| < bin_size
        return max(0.0, 1.0 - abs(d) / bin_size)

    out = np.zeros((len(ys), len(xs), N_BINS, N_BINS, N_ORIENT))
    for iy, cy in enumerate(ys):
        for ix, cx in enumerate(xs):
            for by, oy in enumerate(offs):
                for bx, ox in enumerate(offs):
                    my, mx = cy + oy, cx + ox  # this bin's center pixel
                    for py in range(max(0, my - bin_size + 1),
                                    min(h, my + bin_size)):
                        wy = tri(py - my)
                        for px in range(max(0, mx - bin_size + 1),
                                        min(w, mx + bin_size)):
                            wgt = wy * tri(px - mx) * mag[py, px]
                            out[iy, ix, by, bx, lo[py, px]] += (
                                wgt * (1 - frac[py, px]))
                            out[iy, ix, by, bx, hi[py, px]] += (
                                wgt * frac[py, px])
    d = out.reshape(len(ys), len(xs), -1)
    n = np.linalg.norm(d, axis=-1, keepdims=True)
    d = d / np.maximum(n, 1e-9)
    d = np.minimum(d, 0.2)
    n = np.linalg.norm(d, axis=-1, keepdims=True)
    return d / np.maximum(n, 1e-9)


@pytest.mark.parametrize("bin_size,step,hw", [
    (8, 4, (48, 52)),   # the PHOW geometry the PV stage uses
    (4, 3, (30, 26)),   # a second geometry so constants can't be baked in
])
def test_dense_sift_matches_bruteforce_oracle(rng, bin_size, step, hw):
    img = rng.uniform(0, 255, hw).astype(np.float32)
    got = dense_sift(img, bin_size=bin_size, step=step)
    want = dsift_oracle(img, bin_size, step)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_dense_sift_oracle_structured_image(rng):
    """A structured (step-edge + gradient) image rather than noise: exercises
    strongly-oriented gradients and the 0.2 clipping branch."""
    yy, xx = np.mgrid[0:48, 0:48].astype(np.float64)
    img = 40.0 * (xx > 24) + yy + rng.uniform(0, 1, (48, 48))
    got = dense_sift(img, bin_size=8, step=4)
    want = dsift_oracle(img, 8, 4)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_rootsift_hellinger_property(rng):
    """RootSIFT: ‖r(a)−r(b)‖² = 2 − 2·Bhattacharyya(a,b) for L1-normalized
    non-negative descriptors (the property the PV score relies on)."""
    a = np.abs(rng.standard_normal(128))
    b = np.abs(rng.standard_normal(128))
    ra, rb = rootsift(a), rootsift(b)
    an, bn = a / a.sum(), b / b.sum()
    bc = np.sum(np.sqrt(an * bn))
    np.testing.assert_allclose(np.sum((ra - rb) ** 2), 2 - 2 * bc, rtol=1e-6)
