"""Streaming video matching through the serving plane (ISSUE 19).

Service-level acceptance of the tracked (coarse-pass-skipping) mode:

  (a) a steady tracked stream dispatches ZERO coarse-pass programs
      (engine spy), resolves its reference features once, and reports
      itself on /healthz, /metrics, and /statusz;
  (b) a scene cut detected mid-stream falls back to the full pipeline and
      the fallback frame's table is BITWISE a cold coarse-to-fine query's
      (same executable), after which tracking re-seeds;
  (c) chaos: a replica SIGKILLed mid-stream loses ZERO frames, and the
      per-stream seq ordering + frame-outcome identity are recomputed
      from the event log alone (run_report discipline);
  (d) stream sessions are bounded (``stream_cap`` shedding), idle-evicted,
      drained with the service, and their reference-digest memo hashes
      once per (array, bucket);
  (e) the wire's additive ``stream`` tag routes through the per-stream
      session when the host has one and degrades to plain serving when it
      does not;
  (f) a same-structure rollout swap takes the engine fast path (the
      ladder warmup replays cached executables) and says so on the
      ``rollout_swap`` event;
  (g) tools/stream_probe.py --tiny smokes end to end on CPU with the
      steady-frame wall strictly below the per-frame coarse-to-fine wall.

Ops/model/engine layers live in tests/test_temporal.py.
"""

import os
import sys
import warnings

import numpy as np
import pytest
import jax

from ncnet_tpu import models, ops
from ncnet_tpu.config import ModelConfig
from ncnet_tpu.observability import EventLog
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.observability.export import parse_prometheus, render
from ncnet_tpu.serving import (
    BatchMatchEngine,
    MatchService,
    Overloaded,
    ServingConfig,
    StreamSession,
    StreamTable,
    run_stream_load,
)
from ncnet_tpu.serving.introspect import metrics_families, render_statusz
from ncnet_tpu.serving.wire import (
    decode_response,
    encode_request,
    serve_match,
)
from ncnet_tpu.utils import faults
from ncnet_tpu.utils.faults import FaultPlan

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import stream_probe  # noqa: E402

# tracked-capable tiny config: 96 px → 6x6 fine grid, factor 2 → 3x3 coarse
TRACK = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3,),
                    ncons_channels=(1,), sparse_topk=4, sparse_factor=2)


@pytest.fixture(autouse=True)
def _clean_state():
    """No armed faults, no demoted tiers, no leaked event sink."""
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)
    yield
    faults.clear()
    ops.reset_fused_tier_demotions()
    obs_events.set_global_sink(None)


@pytest.fixture(scope="module")
def track_params():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return models.init_ncnet(TRACK, jax.random.key(0))


def u8(side=96, seed=0):
    return np.random.default_rng(seed).integers(
        0, 255, (side, side, 3), dtype=np.uint8)


def jittered(ref, seed):
    """A steady frame: the reference plus small sensor noise."""
    rng = np.random.default_rng(seed)
    return np.clip(ref.astype(np.int16)
                   + rng.integers(-3, 4, ref.shape), 0, 255).astype(np.uint8)


def track_service(params, **over):
    cfg = dict(bucket_multiple=32, max_image_side=96, max_batch=2)
    cfg.update(over)
    return MatchService(TRACK, params, ServingConfig(**cfg))


# ---------------------------------------------------------------------------
# (a) steady stream: zero coarse passes + observability surfaces
# ---------------------------------------------------------------------------


def test_steady_stream_skips_coarse_pass_and_reports(track_params):
    svc = track_service(track_params).start()
    try:
        eng = svc._pool.replicas[0].engine
        src = u8(96, 1)
        fr0 = svc.stream_submit("cam0", src, jittered(src, 2))
        assert fr0.seq == 0 and not fr0.tracked and not fr0.fallback
        cp, fe = eng.coarse_passes, eng.feature_extractions
        frames = [svc.stream_submit("cam0", src, jittered(src, 10 + i))
                  for i in range(4)]
        # the acceptance spy: the steady segment dispatched ZERO programs
        # that pay a coarse pass, and the reference features resolved once
        assert eng.coarse_passes == cp
        assert eng.feature_extractions == fe + 1
        assert eng.tracked_dispatches == 4
        assert [f.seq for f in frames] == [1, 2, 3, 4]
        assert all(f.tracked and not f.fallback for f in frames)
        assert all(f.recall is not None
                   and f.recall >= svc.cfg.stream_cut_recall
                   for f in frames)
        assert all(np.isfinite(f.table).all() for f in frames)

        sm = svc.health()["streams"]
        assert sm["active"] == 1
        assert sm["frames"] == 5
        assert sm["tracked_frames"] == 4
        assert sm["cold_frames"] == 1
        assert sm["fallback_frames"] == 0
        assert sm["sessions"][0]["stream"] == "cam0"
        assert sm["sessions"][0]["seeded"] is True

        fams = parse_prometheus(render(metrics_families(svc)))
        samples = {lab.get("kind"): v for _n, lab, v in
                   fams["ncnet_serve_stream_frames_total"]["samples"]}
        assert samples["tracked"] == 4
        assert samples["cold"] == 1
        tier = fams["ncnet_serve_stream_pipeline"]["samples"][0]
        assert tier[1]["tier"] == "tracked" and tier[2] == 1
        sz = render_statusz(svc)
        assert "streams: active=1" in sz and "tracked=4" in sz
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# (b) scene cut: exact fallback, bitwise a cold query, then re-seed
# ---------------------------------------------------------------------------


def test_cut_fallback_is_bitwise_cold_and_reseeds(track_params, tmp_path):
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc = track_service(track_params,
                            stream_cut_quality_frac=0.8).start()
        try:
            src = u8(96, 1)
            svc.stream_submit("cam0", src, jittered(src, 2))
            for i in range(2):
                fr = svc.stream_submit("cam0", src, jittered(src, 20 + i))
                assert fr.tracked
            # the cut: an unrelated scene — the tracker's prior stops
            # describing the frame and the detector must fall back
            cut_tgt = u8(96, 99)
            fr_cut = svc.stream_submit("cam0", src, cut_tgt)
            assert fr_cut.fallback and not fr_cut.tracked
            # bitwise identity with a COLD query of the same pair: the
            # fallback re-ran the frame through the identical executable
            ref = svc.submit(src, cut_tgt).result(timeout=600)
            assert np.array_equal(fr_cut.result.table, ref.table)
            # the fallback's table re-seeded the tracker on the new scene
            fr_next = svc.stream_submit("cam0", src, jittered(cut_tgt, 7))
            assert fr_next.tracked and not fr_next.fallback
            assert svc.health()["streams"]["fallback_frames"] == 1
        finally:
            svc.stop()

    _, events = obs_events.replay_events(log_path)
    cuts = [e for e in events if e.get("event") == "stream_cut"]
    assert len(cuts) == 1 and cuts[0]["stream"] == "cam0" \
        and cuts[0]["seq"] == 3
    kinds = [e["kind"] for e in events
             if e.get("event") == "stream_frame"]
    assert kinds == ["cold", "tracked", "tracked", "fallback", "tracked"]
    # drain evicted the session and said so
    ev = [e for e in events if e.get("event") == "stream_evict"]
    assert len(ev) == 1 and ev[0]["reason"] == "drain" \
        and ev[0]["frames"] == 5


# ---------------------------------------------------------------------------
# (c) chaos: replica death mid-stream — ordering + zero lost from the log
# ---------------------------------------------------------------------------


class FakeEngine:
    """Device stand-in (tests/test_serving.py protocol): no tracked
    capability, so every stream frame takes the cold path — the chaos bar
    here is ordering + zero lost through REAL replica failover."""

    split = staticmethod(BatchMatchEngine.split)
    half_precision = False

    def dispatch(self, src, tgt):
        faults.device_error_hook("fake_serve")
        return src.shape[0]

    def fetch(self, handle):
        table = np.zeros((handle, 6, 16), np.float32)
        table[:, 4, :] = 1.0
        table[:, 5, :5] = [0.5, 0.1, 0.4, 0.9, 0.8]
        return table

    def retrace(self):
        pass


def test_chaos_replica_death_mid_stream_zero_lost(tmp_path):
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc = MatchService(
            engine=[FakeEngine() for _ in range(4)],
            serving=ServingConfig(
                bucket_multiple=32, max_image_side=64, max_batch=2,
                replica_max_failures=1, resurrect_after_s=0.2,
                max_queue=128, max_in_flight_per_client=128)).start()
        try:
            frame = lambda si, fi: (u8(32, si), u8(32, 100 + fi))  # noqa
            recs = run_stream_load(svc, frame, streams=3, frames=3,
                                   rate_hz=200.0, seed=5)
            # SIGKILL-style death of one replica MID-STREAM: the sessions
            # continue (seq keeps rising) and failover serves every frame
            faults.install(FaultPlan(dead_replica_ids=("rep1",)))
            recs += run_stream_load(svc, frame, streams=3, frames=4,
                                    rate_hz=200.0, seed=6)
            assert all(r["outcome"] == "result" for r in recs)
            sm = svc.health()["streams"]
            assert sm["frames"] == 21 and sm["active"] == 3
        finally:
            faults.clear()
            svc.stop()

    # the replayed log alone proves ordering + the outcome identity
    _, events = obs_events.replay_events(log_path)
    frames_ev = [e for e in events if e.get("event") == "stream_frame"]
    per = {}
    for e in frames_ev:
        per.setdefault(e["stream"], []).append(e["seq"])
    assert set(per) == {"cam0", "cam1", "cam2"}
    for seqs in per.values():
        assert seqs == list(range(7))  # contiguous, in-order, none lost
    kinds = [e["kind"] for e in frames_ev]
    assert len(frames_ev) == 21 == len(recs)
    assert (kinds.count("tracked") + kinds.count("fallback")
            + kinds.count("cold")) == len(frames_ev)
    assert [e for e in events if e.get("event") == "stream_evict"
            and e["reason"] == "drain"]


# ---------------------------------------------------------------------------
# (d) session bounds, idle eviction, digest memo
# ---------------------------------------------------------------------------


def test_stream_table_cap_lru_and_idle_eviction():
    tbl = StreamTable(max_sessions=2, idle_evict_s=5.0)
    s1, s2 = tbl.acquire("a"), tbl.acquire("b")
    with s1.lock, s2.lock:
        # both ACTIVE (locks held): a third stream sheds, classified
        with pytest.raises(Overloaded) as e:
            tbl.acquire("c")
        assert e.value.reason == "stream_cap"
    # idle LRU makes room: the stalest unlocked session is evicted
    s1.last_activity -= 100.0
    tbl.acquire("c")
    d = tbl.doc()
    assert d["active"] == 2 and d["evicted"] == 1
    assert {r["stream"] for r in d["sessions"]} == {"b", "c"}
    # idle eviction skips a session whose FIFO lock is held (in flight)
    s2.last_activity -= 100.0
    s3 = tbl.acquire("c")
    s3.last_activity -= 100.0
    with s3.lock:
        assert [s.id for s in tbl.evict_idle()] == ["b"]
    # aggregate counters are monotone across evictions
    tbl.note_frame("tracked")
    tbl.note_frame("cold")
    d = tbl.doc()
    assert d["frames"] == 2 and d["tracked_frames"] == 1 \
        and d["cold_frames"] == 1 and d["evicted"] == 2


def test_stream_session_digest_memo_hashes_once():
    sess = StreamSession("x")
    bucket = ((32, 32), (32, 32))
    src, hashes = u8(32, 1), []

    def padded():
        hashes.append(1)
        return src

    d1 = sess.src_digest(src, bucket, padded)
    d2 = sess.src_digest(src, bucket, padded)
    assert d1 == d2 and len(hashes) == 1  # same (array, bucket): memoized
    # a different reference object re-hashes (and a changed bucket would)
    other = u8(32, 2)
    d3 = sess.src_digest(other, bucket, lambda: other)
    assert d3 != d1
    # the memo is one-deep by design (a stream has ONE reference): going
    # back to the first array re-hashes, to the same digest
    assert sess.src_digest(src, bucket, padded) == d1 and len(hashes) == 2


# ---------------------------------------------------------------------------
# (e) wire: the additive stream tag
# ---------------------------------------------------------------------------


def test_wire_stream_tag_routes_through_session():
    svc = MatchService(engine=FakeEngine(),
                       serving=ServingConfig(bucket_multiple=32,
                                             max_image_side=64)).start()
    try:
        body = encode_request(u8(32, 1), u8(32, 2), client="edge",
                              stream="camW")
        status, _ctype, payload = serve_match(
            svc.submit, body, stream_submit=svc.stream_submit)
        assert status == 200
        res = decode_response(payload)
        assert res.table.size > 0
        assert svc.health()["streams"]["frames"] == 1
        # a host WITHOUT a streaming plane (router) serves the same bytes
        # as an ordinary request: correct, just never session-routed
        status2, _, payload2 = serve_match(svc.submit, body)
        assert status2 == 200
        assert decode_response(payload2).table.shape == res.table.shape
        assert svc.health()["streams"]["frames"] == 1
        # an untagged request never touches the stream table
        status3, _, _ = serve_match(svc.submit,
                                    encode_request(u8(32, 1), u8(32, 2)),
                                    stream_submit=svc.stream_submit)
        assert status3 == 200
        assert svc.health()["streams"]["frames"] == 1
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# (f) rollout: same-structure swap rides the warm-executable fast path
# ---------------------------------------------------------------------------


def test_rollout_swap_fastpath_keeps_executables(track_params, tmp_path):
    log_path = str(tmp_path / "events.jsonl")
    with obs_events.bound(EventLog(log_path)):
        svc = track_service(track_params, replicas=2).start()
        try:
            svc.submit(u8(96, 1), u8(96, 2)).result(timeout=600)
            rep = svc.rollout_pick_canary()
            assert svc.rollout_drain(rep, 30.0)
            new = jax.tree.map(lambda x: x * 1.0, track_params)
            svc.rollout_swap(rep, new, "v1")
            assert rep.engine.swap_fastpath_hits == 1
            assert rep.model_version == "v1"
        finally:
            svc.stop()
    _, events = obs_events.replay_events(log_path)
    sw = [e for e in events if e.get("event") == "rollout_swap"]
    assert sw and sw[-1]["ok"] is True
    assert sw[-1]["fastpath"] is True  # ladder warmup replayed cache hits


# ---------------------------------------------------------------------------
# (g) stream_probe --tiny: the end-to-end CPU smoke
# ---------------------------------------------------------------------------


def test_stream_probe_tiny_smoke(tmp_path):
    doc = stream_probe.probe(
        tiny=True, streams=2, frames=8, rate_hz=30.0,
        events_path=str(tmp_path / "events.jsonl"))
    assert doc["tracking_feasible"]
    # zero coarse passes on the steady segment, to the dispatch
    assert doc["coarse_passes_steady_delta"] == doc["expected_coarse_passes"]
    assert doc["coarse_skip_pct"] > 50.0
    # the perf headline, at tiny scale: tracked steady frames beat the
    # per-frame coarse-to-fine wall at the same shape
    assert doc["steady_below_c2f"]
    # replayability from the log alone
    assert doc["replay_ordering_ok"]
    assert doc["replay_outcome_identity_ok"]
    assert doc["streams_doc"]["frames"] == 2 * 8
