"""Chaos suite for shard-replicated coarse-volume retrieval (ISSUE 16).

Layers under test:

  * **Assignment** (``retrieval/assignment.py``): rendezvous placement is
    deterministic, balanced, R-replicated, and minimal-movement under
    shard removal — the property that makes failover a re-dispatch, not a
    reshuffle.
  * **Scoring + index** (``retrieval/scoring.py`` / ``index.py``): the
    raw extractor discriminates structured panos, top-k is deterministic
    under ties, and ``local_shortlist`` rides the store's verified-read /
    quarantine / recompute ladder (a bit-flipped entry recomputes to an
    IDENTICAL shortlist).
  * **Wire** (``retrieval/wire.py`` + ``POST /retrieve``): framed round
    trips, checksum-sealed answers (corrupt scores are refused, never
    served), classified terminal errors.
  * **Coordinator** (``retrieval/coordinator.py``): replication turns
    shard death into lost capacity at full coverage; R=1 loss is reported
    DEGRADED with honest coverage, never silent; stragglers are hedged;
    probes resurrect a restarted shard.
  * **Tools**: ``run_report --retrieval`` (the outcome-total identity
    replayed from the log), ``stall_watchdog --url`` on a coordinator
    document, ``serve_probe``'s fixture/spawn helpers.

THE acceptance chain (test_acceptance_sigkill_full_coverage): a 4-shard
R=2 CPU pod of REAL ``serve_shard.py`` processes under a query stream
survives SIGKILL of one shard with every query still terminating
classified at coverage 1.0, marks it DEAD, re-admits a restarted process
at the same address, and the event log replays the identity with zero
lost queries.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from ncnet_tpu.observability import EventLog
from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.observability.events import replay_events
from ncnet_tpu.observability.export import parse_prometheus
from ncnet_tpu.observability.perfstore import metric_direction
from ncnet_tpu.retrieval import (
    RetrievalConfig,
    RetrievalCoordinator,
    RetrieveClient,
    ShardService,
    assignment_table,
    coarse_volume_from_features,
    decode_retrieve_request,
    decode_retrieve_response,
    encode_retrieve_request,
    encode_retrieve_response,
    load_index_manifests,
    local_shortlist,
    pooled_descriptor,
    raw_coarse_volume,
    replica_shards,
    score_coarse_volume,
    top_k,
    write_index_manifest,
)
from ncnet_tpu.serving import DeadlineExceeded
from ncnet_tpu.serving.wire import WireError
from ncnet_tpu.store import FeatureStore, coarse_fingerprint
from ncnet_tpu.store.feature_store import _weights_segment
from ncnet_tpu.utils import faults

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import run_report  # noqa: E402
import serve_probe  # noqa: E402
import stall_watchdog  # noqa: E402

FACTOR = 4
GRID = 16
FP = coarse_fingerprint(f"raw-s{GRID}-k0-f32", FACTOR)


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    obs_events.set_global_sink(None)
    yield
    faults.clear()
    obs_events.set_global_sink(None)


def wait_until(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def make_img(i, hw=(96, 128)):
    """STRUCTURED test pano (distinct hue levels + stripe cadence).
    Random noise is useless here: the raw statistics extractor scores
    noise panos all ~identical (cosine ~0.9999), so a noise fixture could
    never prove the shortlist ranks correctly."""
    img = np.zeros((*hw, 3), np.uint8)
    img[..., 0] = (37 * i) % 256
    img[..., 1] = (91 * i + 13) % 256
    img[:: (i % 5) + 2, :, 2] = 255
    return img


def descriptor(img):
    return pooled_descriptor(raw_coarse_volume(img, FACTOR, grid=GRID))


def build_fixture(root, n_panos=12):
    """Coarse store + index under ``root`` via the serve_probe helper (the
    probe's fixture IS this suite's fixture — one builder, no drift)."""
    return serve_probe.build_coarse_fixture(str(root), n_panos,
                                            factor=FACTOR, grid=GRID)


def start_inproc_pod(root, n_shards, replication, n_panos=12):
    """In-process shard pod: N ``ShardService``s over one store + index,
    each behind its own introspection plane.  Returns
    ``(services, {sid: url}, index)``."""
    index_path, images = build_fixture(root, n_panos)
    index = load_index_manifests(index_path)
    shard_ids = [f"s{i}" for i in range(n_shards)]
    services, urls = [], {}
    for sid in shard_ids:
        store = FeatureStore(str(root), index["fingerprint"],
                             scope=f"test_{sid}")
        svc = ShardService(sid, shard_ids, index, store,
                           replication=replication, introspect_port=0)
        svc.start()
        assert svc.introspect_url is not None
        services.append(svc)
        urls[sid] = svc.introspect_url
    return services, urls, index, images


# ---------------------------------------------------------------------------
# assignment: deterministic, balanced, replicated, minimal movement
# ---------------------------------------------------------------------------


def test_rendezvous_assignment_properties():
    shards = [f"s{i}" for i in range(4)]
    panos = [f"p{i:03d}" for i in range(200)]
    t1 = assignment_table(panos, shards, 2)
    t2 = assignment_table(panos, shards, 2)
    assert t1 == t2  # pure function of (pano, shard) ids
    # R-way replication: every pano on exactly R distinct shards
    owners = {p: [s for s in shards if p in set(t1[s])] for p in panos}
    assert all(len(o) == 2 for o in owners.values())
    assert all(set(o) == set(replica_shards(p, shards, 2))
               for p, o in owners.items())
    # balance: expected 100 panos/shard; rendezvous keeps it in a band
    counts = [len(t1[s]) for s in shards]
    assert sum(counts) == 400
    assert min(counts) > 50 and max(counts) < 150
    # minimal movement: removing s3 only re-homes panos that LIVED on s3
    survivors = shards[:-1]
    for p in panos:
        old = replica_shards(p, shards, 2)
        new = replica_shards(p, survivors, 2)
        if "s3" not in old:
            assert new == old  # untouched panos do not move
        else:
            assert set(old) & set(new)  # the surviving replica stays
    with pytest.raises(ValueError):
        replica_shards("p0", shards, 0)


# ---------------------------------------------------------------------------
# scoring / fingerprints / perf-gate directions
# ---------------------------------------------------------------------------


def test_raw_extractor_discriminates_and_topk_deterministic():
    vols = {f"p{i}": raw_coarse_volume(make_img(i), FACTOR, grid=GRID)
            for i in range(6)}
    desc = descriptor(make_img(3))
    scores = {n: score_coarse_volume(desc, v) for n, v in vols.items()}
    ranked = top_k(scores, 3)
    assert ranked[0][0] == "p3"  # the query's own pano wins
    assert ranked == top_k(scores, 3)
    # tie-break is the pano id, not dict/iteration order
    assert top_k([("b", 1.0), ("a", 1.0), ("c", 0.5)], 2) == \
        (("a", 1.0), ("b", 1.0))
    # channel mismatch is a refusal, never a silently-wrong ranking
    with pytest.raises(ValueError):
        score_coarse_volume(np.ones(5, np.float32), vols["p0"])
    # both extractors produce the shared formats
    feat = np.random.default_rng(0).normal(size=(1, 16, 16, 8))
    vol = coarse_volume_from_features(feat, FACTOR)
    assert vol.shape == (4, 4, 8)
    assert np.allclose(np.linalg.norm(vol, axis=-1), 1.0, atol=1e-5)


def test_coarse_fingerprint_is_own_generation_same_weights_segment():
    base = "abc123-s3200-k2-bf16"
    fp = coarse_fingerprint(base, 4)
    assert fp == "abc123-s3200-k2-bf16-c4"
    assert fp != coarse_fingerprint(base, 2)  # factor rides the generation
    # same weights segment: checkpoint-scoped GC covers coarse entries too
    assert _weights_segment(fp) == _weights_segment(base)


def test_retrieval_metrics_gate_directions():
    assert metric_direction("retrieve_coverage_pct") == "higher"
    assert metric_direction("retrieve_hedge_pct") == "lower"
    assert metric_direction("retrieve_p95_ms") == "lower"


# ---------------------------------------------------------------------------
# wire: framed round trips, checksum seal, classified errors
# ---------------------------------------------------------------------------


def test_wire_roundtrip_and_checksum_refusal():
    desc = descriptor(make_img(0))
    data = encode_retrieve_request(desc, panos=["a", "b"], topk=3,
                                   client="t", budget_s=1.5,
                                   request_id="q1")
    got, meta = decode_retrieve_request(data)
    np.testing.assert_allclose(got, desc, rtol=1e-6)
    assert meta["panos"] == ["a", "b"] and meta["topk"] == 3
    assert meta["budget_s"] == 1.5 and meta["request"] == "q1"

    answer = {"shard": "s0", "scores": [["p1", 0.9]], "coverage": 1.0}
    status, payload = encode_retrieve_response(answer)
    assert status == 200
    assert decode_retrieve_response(payload) == answer
    # one flipped payload byte breaks the sha256 seal: corrupt scores are
    # a WireError (shard failure -> replica re-route), never served
    corrupt = bytearray(payload)
    corrupt[-2] ^= 0x01
    with pytest.raises(WireError):
        decode_retrieve_response(bytes(corrupt))


def test_fault_plan_shard_hooks():
    url = "http://127.0.0.1:45678"
    # unarmed: no-ops
    faults.shard_fault_hook(url, "send")
    assert faults.shard_payload_hook(url, b"abc") == b"abc"
    faults.install(faults.FaultPlan(dead_shard_urls=("127.0.0.1:45678",),
                                    shard_bitflip_urls=("127.0.0.1:9",)))
    with pytest.raises(ConnectionError):
        faults.shard_fault_hook(url, "send")
    faults.shard_fault_hook("http://127.0.0.1:1", "send")  # others pass
    assert faults.shard_payload_hook(url, b"abc") == b"abc"  # not armed
    assert faults.shard_payload_hook("http://127.0.0.1:9", b"abc") != b"abc"
    faults.clear()
    faults.shard_fault_hook(url, "send")  # disarmed again


# ---------------------------------------------------------------------------
# local shortlist: the store ladder under a bit flip
# ---------------------------------------------------------------------------


def test_local_shortlist_bitflip_quarantines_recomputes_identical(tmp_path):
    """A bit-flipped coarse entry is caught by the store checksum,
    quarantined, recomputed — and the shortlist comes out IDENTICAL to
    the uncorrupted pass (the headline: corruption costs latency, never
    ranking)."""
    index_path, images = build_fixture(tmp_path, n_panos=6)
    index = load_index_manifests(index_path)

    def compute(name):
        return raw_coarse_volume(images[name], FACTOR, grid=GRID)

    store = FeatureStore(str(tmp_path), index["fingerprint"], scope="t")
    try:
        desc = descriptor(images[sorted(images)[2]])
        baseline = local_shortlist(store, index, desc, topk=4,
                                   compute=compute)
        assert baseline["coverage"] == 1.0
        assert baseline["scores"][0][0] == sorted(images)[2]

        # corrupt one committed entry post-commit, then re-sweep
        victim = sorted(images)[2]
        digest = index["panos"][victim]
        arr = compute(victim)
        with faults.injected(faults.FaultPlan(
                store_bitflip_paths=(digest,))):
            store.put(digest, arr)  # committed, then bit-flipped
        again = local_shortlist(store, index, desc, topk=4,
                                compute=compute)
        assert store.counters["corrupt"] == 1  # caught, not served
        assert again["scores"] == baseline["scores"]  # identical shortlist
        assert again["coverage"] == 1.0
        # without compute, an unreadable entry lowers coverage instead
        with faults.injected(faults.FaultPlan(
                store_bitflip_paths=(digest,))):
            store.put(digest, arr)
        partial = local_shortlist(store, index, desc, topk=4)
        assert partial["coverage"] < 1.0
        assert victim in partial["unavailable"]
    finally:
        store.close()


# ---------------------------------------------------------------------------
# in-process pod: R=1 honesty, hedging, wire bitflip failover
# ---------------------------------------------------------------------------


def test_r1_dead_shard_reports_degraded_coverage_never_silent(tmp_path):
    """At R=1 a dead shard's panos are simply GONE from the sweep: the
    answer must say so — coverage < 1.0 and DEGRADED — rather than
    silently serving a truncated shortlist as if it were total."""
    services, urls, index, images = start_inproc_pod(tmp_path, 2, 1)
    coord = None
    try:
        cfg = RetrievalConfig(replication=1, topk=5, max_failures=2,
                              probe_period_s=5.0)
        coord = RetrievalCoordinator(urls, list(index["panos"]), cfg)
        coord.start()
        dead = urls["s1"].replace("http://", "")
        faults.install(faults.FaultPlan(dead_shard_urls=(dead,)))
        ans = coord.retrieve(descriptor(make_img(1)), budget_s=10.0,
                             request_id="r1-q0")
        assert ans["degraded"] is True
        assert 0.0 < ans["coverage"] < 1.0
        assert ans["consulted"] < ans["total"]
        # the living half still ranks correctly within its coverage
        assert all(p in index["panos"] for p, _ in ans["scores"])
    finally:
        faults.clear()
        if coord is not None:
            coord.stop()
        for s in services:
            s.stop()


def test_hedging_beats_slow_straggler(tmp_path):
    """A shard that is merely SLOW is hedged, not killed: its panos
    re-dispatch to replicas after ``hedge_after_s`` and the query answers
    at full coverage well under the straggler's wall."""
    services, urls, index, images = start_inproc_pod(tmp_path, 4, 2)
    coord = None
    try:
        cfg = RetrievalConfig(replication=2, topk=5, hedge_after_s=0.12,
                              probe_period_s=5.0)
        coord = RetrievalCoordinator(urls, list(index["panos"]), cfg)
        coord.start()
        slow = urls["s2"].replace("http://", "")
        faults.install(faults.FaultPlan(slow_shard_urls=(slow,),
                                        slow_shard_seconds=1.5))
        t0 = time.perf_counter()
        ans = coord.retrieve(descriptor(make_img(2)), budget_s=10.0,
                             request_id="hedge-q0")
        wall = time.perf_counter() - t0
        assert ans["coverage"] == 1.0  # replicas covered the straggler
        assert ans["hedges"] >= 1
        assert wall < 1.2  # beat the 1.5 s straggler
        assert ans["scores"][0][0] == sorted(images)[2]
        b = coord._backends["s2"]
        assert b.state == "READY"  # slow is hedged, never punished dead
    finally:
        faults.clear()
        if coord is not None:
            coord.stop()
        for s in services:
            s.stop()


def test_wire_bitflip_refused_replica_covers(tmp_path):
    """A shard answering with corrupt bytes fails its checksum seal: the
    coordinator refuses the scores, re-routes to replicas (coverage stays
    1.0, identical top-1), and the repeat offender goes DEAD."""
    services, urls, index, images = start_inproc_pod(tmp_path, 4, 2)
    coord = None
    try:
        cfg = RetrievalConfig(replication=2, topk=5, max_failures=2,
                              probe_period_s=5.0)
        coord = RetrievalCoordinator(urls, list(index["panos"]), cfg)
        coord.start()
        clean = coord.retrieve(descriptor(make_img(4)), budget_s=10.0,
                               request_id="bf-base")
        assert clean["coverage"] == 1.0
        flip = urls["s0"].replace("http://", "")
        faults.install(faults.FaultPlan(shard_bitflip_urls=(flip,)))
        for i in range(3):
            ans = coord.retrieve(descriptor(make_img(4)), budget_s=10.0,
                                 request_id=f"bf-q{i}")
            assert ans["coverage"] == 1.0
            assert ans["scores"][0][0] == clean["scores"][0][0]
        assert coord._backends["s0"].state == "DEAD"  # streak caught it
    finally:
        faults.clear()
        if coord is not None:
            coord.stop()
        for s in services:
            s.stop()


def test_zero_budget_classifies_deadline(tmp_path):
    services, urls, index, _ = start_inproc_pod(tmp_path, 2, 2, n_panos=4)
    coord = None
    try:
        coord = RetrievalCoordinator(urls, list(index["panos"]),
                                     RetrievalConfig(probe_period_s=5.0))
        coord.start()
        with pytest.raises(DeadlineExceeded):
            coord.retrieve(descriptor(make_img(0)), budget_s=0.0,
                           request_id="dl-q0")
    finally:
        if coord is not None:
            coord.stop()
        for s in services:
            s.stop()


def test_shard_wire_plane_and_metrics(tmp_path):
    """``POST /retrieve`` on the shard's introspection server answers a
    framed client; ``POST /match`` there is a 404 (this host serves the
    retrieval plane); ``/metrics`` exports the ncnet_retrieve_* family."""
    import urllib.error
    import urllib.request

    services, urls, index, images = start_inproc_pod(tmp_path, 2, 2,
                                                     n_panos=6)
    try:
        url = urls["s0"]
        client = RetrieveClient(url)
        ans = client.retrieve(descriptor(make_img(1)), budget_s=5.0,
                              request_id="wire-q0")
        client.close()
        assert ans["shard"] == "s0"
        assert ans["consulted"]  # it scored its assigned panos
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                urllib.request.Request(f"{url}/match", data=b"x",
                                       method="POST"), timeout=5)
        assert ei.value.code == 404
        body = urllib.request.urlopen(f"{url}/metrics",
                                      timeout=5).read().decode()
        families = parse_prometheus(body)
        up = [v for _n, _l, v in
              families["ncnet_retrieve_shard_up"]["samples"]]
        assert up == [1.0]
        assert "ncnet_retrieve_shard_requests_total" in families
    finally:
        for s in services:
            s.stop()


# ---------------------------------------------------------------------------
# THE acceptance chain: real processes, SIGKILL at R=2, restart-in-place
# ---------------------------------------------------------------------------


def _spawn_shard(sid, shard_ids, store_root, index_path, port=0):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               NCNET_TPU_PERF_STORE="off", NCNET_TPU_TIER_CACHE="off")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_REPO, "tools", "serve_shard.py"),
         "--shard-id", sid, "--shards", ",".join(shard_ids),
         "--store", str(store_root), "--index", str(index_path),
         "--replication", "2", "--port", str(port)],
        stdout=subprocess.PIPE, text=True, env=env)
    doc = json.loads(proc.stdout.readline())
    assert "url" in doc, f"shard failed to start: {doc}"
    return proc, doc["url"]


def test_acceptance_sigkill_full_coverage(tmp_path):
    """ISSUE 16 acceptance: 4 real shard processes at R=2 — SIGKILL one
    mid-stream and every query still terminates classified at coverage
    1.0 with the correct top-1; the victim goes DEAD, the pod DEGRADED
    (capacity, not coverage); a restarted process at the SAME address is
    re-admitted by the wire probe; the event log replays the outcome
    identity with zero lost queries; stall_watchdog reads the coordinator
    document with the per-shard breakdown."""
    index_path, images = build_fixture(tmp_path, n_panos=12)
    index = load_index_manifests(index_path)
    names = sorted(images)
    shard_ids = [f"s{i}" for i in range(4)]
    log_path = str(tmp_path / "retrieval_events.jsonl")
    procs = {}
    with obs_events.bound(EventLog(log_path)):
        for sid in shard_ids:
            procs[sid] = _spawn_shard(sid, shard_ids, tmp_path, index_path)
        coord = RetrievalCoordinator(
            {sid: url for sid, (_, url) in procs.items()},
            list(index["panos"]),
            RetrievalConfig(replication=2, topk=5, probe_period_s=0.2,
                            resurrect_after_s=0.3, max_failures=2,
                            introspect_port=0))
        coord.start()
        try:
            def query(i, tag):
                return coord.retrieve(descriptor(images[names[i]]),
                                      budget_s=15.0,
                                      request_id=f"{tag}-{i}")

            # phase 1: healthy stream — full coverage, correct top-1
            for i in range(len(names)):
                ans = query(i, "steady")
                assert ans["coverage"] == 1.0
                assert ans["degraded"] is False
                assert ans["scores"][0][0] == names[i]

            # phase 2: SIGKILL s1 — capacity lost, coverage kept
            p1, url1 = procs["s1"]
            p1.kill()  # SIGKILL: no drain, no goodbye
            for i in range(len(names)):
                ans = query(i, "killed")
                assert ans["coverage"] == 1.0  # replication's headline
                assert ans["scores"][0][0] == names[i]
            victim = coord._backends["s1"]
            assert wait_until(lambda: victim.state == "DEAD", 15)
            assert coord.state == "DEGRADED"  # shards:3/4
            assert victim.deaths >= 1

            # phase 3: restart-in-place at the same port; the healthz +
            # wire probe re-admits it and capacity recovers
            port = int(url1.rsplit(":", 1)[1])
            p1.wait(timeout=10)
            procs["s1"] = _spawn_shard("s1", shard_ids, tmp_path,
                                       index_path, port=port)
            assert wait_until(lambda: victim.state == "READY", 15)
            assert wait_until(lambda: coord.state == "READY", 5)
            ans = query(0, "revived")
            assert ans["coverage"] == 1.0

            # stall_watchdog reads the coordinator document directly
            v = stall_watchdog.judge_url(coord.introspect_url, factor=5,
                                         min_age=30.0)
            assert v["status"] == "alive" and v["role"] == "retrieval"
            assert v["retrieval"]["shards_total"] == 4
            assert set(v["backends"]) == set(shard_ids)
        finally:
            coord.stop()  # emits the final retrieve_health_doc
            for p, _ in procs.values():
                if p.poll() is None:
                    p.terminate()
            for p, _ in procs.values():
                try:
                    p.wait(timeout=20)
                except Exception:  # noqa: BLE001 — wedged child
                    p.kill()

    # the event log replays the whole story: outcome-total identity,
    # zero lost queries, the death + resurrection on s1
    report = run_report.build_report([log_path])
    r = report["retrieval"]
    o = r["outcomes"]
    assert o["admitted"] == 25  # 12 + 12 + 1
    assert o["results"] == o["admitted"]
    assert o["deadline_exceeded"] == 0 and o["shed"] == 0
    assert o["unresolved"] == 0 and not r["lost_requests"]
    assert r["coverage"]["min"] == 1.0 and r["coverage"]["below_full"] == 0
    assert r["shards"]["s1"]["deaths"] >= 1
    assert r["shards"]["s1"]["resurrections"] >= 1
    assert r["final_health_doc"] is not None
    assert run_report.main([log_path, "--retrieval"]) == 0

    _, events = replay_events(log_path)
    deaths = [e for e in events if e.get("event") == "retrieve_backend"
              and e.get("state") == "DEAD"]
    assert any(e.get("shard") == "s1" for e in deaths)


def test_run_report_retrieval_identity_flags_lost(tmp_path):
    """The replayed identity must actually bite: an admit with no
    terminal outcome reads as unresolved/lost, and a degraded result is
    split out of the full-coverage count."""
    log_path = str(tmp_path / "ev.jsonl")
    sink = EventLog(log_path)
    with obs_events.bound(sink):
        obs_events.emit("retrieve_admit", request="q1", client="t",
                        panos=4, budget_s=1.0)
        obs_events.emit("retrieve_result", request="q1", client="t",
                        coverage=0.5, degraded=True, hedges=0,
                        attempts=2, consulted=2, total=4, wall_ms=3.0)
        obs_events.emit("retrieve_admit", request="q2", client="t",
                        panos=4, budget_s=1.0)  # ... and then silence
    r = run_report.build_report([log_path])["retrieval"]
    assert r["outcomes"]["admitted"] == 2
    assert r["outcomes"]["results"] == 1
    assert r["outcomes"]["results_degraded"] == 1
    assert r["outcomes"]["unresolved"] == 1
    assert len(r["lost_requests"]) == 1
    assert r["coverage"]["below_full"] == 1
    out = run_report.render_retrieval({"retrieval": r})
    assert "VIOLATED" in out


def test_stall_watchdog_retrieval_advisory_unit():
    doc = {"role": "retrieval", "state": "DEGRADED",
           "activity": {"age_s": 0.1},
           "retrieval": {"coverage_p50": 0.9, "coverage_min": 0.5,
                         "min_coverage": 1.0, "replication": 2},
           "pod": {"ready": 3, "total": 4, "backends": []}}
    verdict = {"status": "alive"}
    stall_watchdog._apply_retrieval_advisory(verdict, doc)
    rt = verdict["retrieval"]
    assert rt["shards_ready"] == 3 and rt["shards_total"] == 4
    assert rt["coverage_min"] == 0.5
    # non-retrieval documents are untouched
    verdict2 = {"status": "alive"}
    stall_watchdog._apply_retrieval_advisory(verdict2, {"role": "router"})
    assert "retrieval" not in verdict2


# ---------------------------------------------------------------------------
# index manifests: merge refusal + builder contract
# ---------------------------------------------------------------------------


def test_index_manifests_refuse_mixed_generations(tmp_path):
    a = str(tmp_path / "a.json")
    b = str(tmp_path / "b.json")
    write_index_manifest(a, fingerprint=FP, factor=4, extractor="raw",
                         panos={"p0": "d0"})
    write_index_manifest(b, fingerprint=FP, factor=4, extractor="raw",
                         panos={"p1": "d1"})
    merged = load_index_manifests([a, b])
    assert set(merged["panos"]) == {"p0", "p1"}
    write_index_manifest(b, fingerprint=FP, factor=2, extractor="raw",
                         panos={"p1": "d1"})
    with pytest.raises(ValueError):
        load_index_manifests([a, b])  # factor disagreement
    with pytest.raises(ValueError):
        load_index_manifests(str(tmp_path / "nothing*.json"))
