"""Frozen-weight activation goldens: cross-round numerical drift detection.

The released reference checkpoints are unreachable (zero egress), so these
are *self-goldens* recorded by tools/make_goldens.py: deterministic weights +
fixed inputs → stored outputs.  A failure here means the numerics of the
backbone / correlation / mutual-matching / conv4d / match-extraction stack
changed since the golden was recorded — either fix the regression or, if the
change is intentional, regenerate via ``python tools/make_goldens.py`` and
say so in the commit message (SURVEY §4 "Golden").
"""

import os
import warnings

import numpy as np
import pytest

import jax.numpy as jnp

from ncnet_tpu.config import ModelConfig
from ncnet_tpu.models.ncnet import extract_features, ncnet_forward
from ncnet_tpu.ops import corr_to_matches

GOLDEN = os.path.join(os.path.dirname(__file__), "goldens", "activations.npz")

# the golden generator doubles as the source of shared comparison helpers
sys_path_tools = os.path.join(os.path.dirname(__file__), "..", "tools")
import sys  # noqa: E402

if sys_path_tools not in sys.path:
    sys.path.insert(0, sys_path_tools)


@pytest.fixture(scope="module")
def golden():
    if not os.path.exists(GOLDEN):
        pytest.skip("goldens not generated (run tools/make_goldens.py)")
    g = np.load(GOLDEN)
    # assert_allclose treats NaN==NaN as equal; a NaN golden would wave
    # everything through, so reject it outright
    bad = [k for k in g.files if not np.isfinite(g[k]).all()]
    assert not bad, f"golden arrays contain non-finite values: {bad}"
    return g


def _params(cfg):
    from make_goldens import deterministic_params

    return deterministic_params(cfg)


def test_tiny_forward_matches_golden(golden):
    cfg = ModelConfig(backbone="tiny", ncons_kernel_sizes=(3, 3),
                      ncons_channels=(8, 1), relocalization_k_size=2)
    params = _params(cfg)
    out = ncnet_forward(cfg, params, jnp.asarray(golden["tiny_src"]),
                        jnp.asarray(golden["tiny_tgt"]))
    np.testing.assert_allclose(np.asarray(out.corr), golden["tiny_corr"],
                               rtol=1e-5, atol=1e-6)
    for i, d in enumerate(out.delta4d):
        np.testing.assert_array_equal(np.asarray(d), golden[f"tiny_delta{i}"])
    m = corr_to_matches(out.corr, delta4d=out.delta4d, k_size=2,
                        do_softmax=True, scale="positive")
    got = np.stack([np.asarray(v) for v in (m.xA, m.yA, m.xB, m.yB, m.score)])
    np.testing.assert_allclose(got, golden["tiny_matches"], rtol=1e-5, atol=1e-6)


def test_dsift_matches_golden(golden):
    from ncnet_tpu.localization.dsift import dense_sift, rootsift

    desc = rootsift(dense_sift(golden["dsift_img"]))
    np.testing.assert_allclose(desc[::3, ::3, :16],
                               golden["dsift_desc_sample"],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(desc.mean(axis=-1), golden["dsift_desc_mean"],
                               rtol=1e-4, atol=1e-5)


def test_p3p_matches_golden(golden):
    from ncnet_tpu.localization.p3p import p3p_solve
    from make_goldens import canonical_p3p_order

    sols = p3p_solve(golden["p3p_rays"], golden["p3p_pts"])
    # NaN slots masked with -1e9 (NaN would make assert_allclose vacuous) and
    # slots canonically ordered — eigvals slot order varies across LAPACKs
    np.testing.assert_allclose(canonical_p3p_order(sols),
                               golden["p3p_solutions"], rtol=1e-6, atol=1e-8)


def test_resnet_features_match_golden(golden):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # deterministic random trunk
        cfg = ModelConfig(backbone="resnet101", ncons_kernel_sizes=(3,),
                          ncons_channels=(1,))
        params = _params(cfg)
    feats = np.asarray(
        extract_features(cfg, params, jnp.asarray(golden["resnet_img"]))
    )
    np.testing.assert_allclose(feats.mean(axis=-1), golden["resnet_feat_mean"],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(feats[0, :, :, :8], golden["resnet_feat_slice"],
                               rtol=1e-4, atol=1e-5)
